"""Host shared versioned buffer — the SASE+ compact match DAG.

A dict-backed reimplementation of the reference's
``nfa/buffer/impl/KVSharedVersionedBuffer.java``: every partially-matched
event is stored once, keyed by ``(stage name, stage type, topic, partition,
offset)`` (``StackEventKey.java:28-54``), with a list of Dewey-versioned
predecessor pointers and a refcount (``TimedKeyValue.java:27-45``).

Semantics preserved exactly:

* ``put`` with a predecessor requires the predecessor entry to exist
  (hard error, ``KVSharedVersionedBuffer.java:86-89``);
* a first-stage ``put`` registers a null-predecessor pointer recording the
  run version (``KVSharedVersionedBuffer.java:117-128``);
* ``branch`` walks a path incrementing refcounts so shared prefixes survive
  sibling-run removal (``KVSharedVersionedBuffer.java:99-110``);
* ``peek`` walks predecessors selecting at each hop the first pointer whose
  version is compatible, decrementing refcounts (floored at zero,
  ``TimedKeyValue.java:59-61``), deleting entries when refs reach zero with at
  most one predecessor, and pruning traversed pointers
  (``KVSharedVersionedBuffer.java:147-171``).

This buffer backs the host oracle engine; the array engine uses the slab
equivalent in ``ops/slab.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from kafkastreams_cep_tpu.compiler.stages import Stage
from kafkastreams_cep_tpu.nfa.dewey import DeweyVersion
from kafkastreams_cep_tpu.utils.events import Event, Sequence

# (stage name, stage type value, topic, partition, offset)
StackKey = Tuple[str, str, str, int, int]


@dataclasses.dataclass(eq=False)
class Pointer:
    """A versioned predecessor pointer; a ``None`` key marks the run origin."""

    version: DeweyVersion
    key: Optional[StackKey]


class _Entry:
    __slots__ = ("key", "value", "timestamp", "refs", "preds")

    def __init__(self, key: Any, value: Any, timestamp: int):
        self.key = key
        self.value = value
        self.timestamp = timestamp
        self.refs = 1
        self.preds: List[Pointer] = []

    def decrement(self) -> int:
        # Floors at zero (TimedKeyValue.java:59-61).
        if self.refs > 0:
            self.refs -= 1
        return self.refs

    def pointer_by_version(self, version: DeweyVersion) -> Optional[Pointer]:
        # First compatible pointer in insertion order (TimedKeyValue.java:83-92).
        for pointer in self.preds:
            if version.is_compatible(pointer.version):
                return pointer
        return None


def _stack_key(stage: Stage, event: Event) -> StackKey:
    return (stage.name, stage.type.value, event.topic, event.partition, event.offset)


class SharedVersionedBuffer:
    """Host shared versioned buffer over a plain dict."""

    def __init__(self) -> None:
        self.store: Dict[StackKey, _Entry] = {}

    def __len__(self) -> int:
        return len(self.store)

    def put_first(self, stage: Stage, event: Event, version: DeweyVersion) -> None:
        """First-stage put: records the run version via a null predecessor."""
        entry = _Entry(event.key, event.value, event.timestamp)
        entry.preds.append(Pointer(version, None))
        self.store[_stack_key(stage, event)] = entry

    def put(
        self,
        curr_stage: Stage,
        curr_event: Event,
        prev_stage: Stage,
        prev_event: Event,
        version: DeweyVersion,
    ) -> None:
        prev_key = _stack_key(prev_stage, prev_event)
        curr_key = _stack_key(curr_stage, curr_event)
        if prev_key not in self.store:
            raise RuntimeError(f"cannot find predecessor event for {prev_key}")
        entry = self.store.get(curr_key)
        if entry is None:
            entry = _Entry(curr_event.key, curr_event.value, curr_event.timestamp)
            self.store[curr_key] = entry
        entry.preds.append(Pointer(version, prev_key))

    def branch(self, stage: Stage, event: Event, version: DeweyVersion) -> None:
        pointer: Optional[Pointer] = Pointer(version, _stack_key(stage, event))
        while pointer is not None and pointer.key is not None:
            entry = self.store.get(pointer.key)
            if entry is None:
                # The reference NPEs here (KVSharedVersionedBuffer.java:
                # 102-108 dereferences store.get unchecked); reachable when
                # sibling runs sharing a path die in one event (e.g. window
                # pruning).  A crash is not a semantics — the walk stops,
                # matching the array engine's counted-miss behavior.
                break
            entry.refs += 1
            pointer = entry.pointer_by_version(pointer.version)

    def get(self, stage: Stage, event: Event, version: DeweyVersion) -> Sequence:
        return self._peek(stage, event, version, remove=False)

    def remove(self, stage: Stage, event: Event, version: DeweyVersion) -> Sequence:
        return self._peek(stage, event, version, remove=True)

    def _peek(self, stage: Stage, event: Event, version: DeweyVersion, remove: bool) -> Sequence:
        pointer: Optional[Pointer] = Pointer(version, _stack_key(stage, event))
        sequence = Sequence()
        while pointer is not None and pointer.key is not None:
            key = pointer.key
            entry = self.store.get(key)
            if entry is None:
                break  # reference-NPE state; see branch() above
            refs_left = entry.decrement()
            if remove and refs_left == 0 and len(entry.preds) <= 1:
                del self.store[key]
            stage_name, _, topic, partition, offset = key
            sequence.add(
                stage_name,
                Event(entry.key, entry.value, entry.timestamp, topic, partition, offset),
            )
            nxt = entry.pointer_by_version(pointer.version)
            if remove and nxt is not None and refs_left == 0:
                entry.preds.remove(nxt)
            pointer = nxt
        return sequence
