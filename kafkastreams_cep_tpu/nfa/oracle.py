"""Host oracle NFA engine — the conformance reference.

A faithful reimplementation of the reference evaluator (``nfa/NFA.java``) used
as (a) the behavioral oracle the JAX array engine is differentially tested
against, and (b) a host fallback path.  Per-event semantics preserved:

* one pass over a snapshot of the run queue per event; runs created during the
  event are not evaluated until the next event (``NFA.java:94-109``);
* window pruning before evaluation, skipped for BEGIN-typed runs
  (``NFA.java:143-144``, ``ComputationStage.java:98-100``);
* the begin state is re-added on every event so new runs can start, with the
  version bumped only when the event also progressed a match
  (``NFA.java:148-157``);
* edge dispatch: PROCEED recurses into the target stage appending a stage
  digit when crossing into a new stage off a non-branching run
  (``NFA.java:182-190``); TAKE re-adds a self-loop epsilon run and buffers the
  event (``NFA.java:191-209``); BEGIN buffers the event and advances
  (``NFA.java:210-222``); IGNORE re-adds the run unchanged
  (``NFA.java:223-227``);
* nondeterministic branching when the matched-op set contains {PROCEED,TAKE},
  {IGNORE,TAKE}, {IGNORE,BEGIN} or {IGNORE,PROCEED} (``NFA.java:280-289``):
  the branch run gets ``version.add_run()`` and a fresh run id, fold state is
  copied to the new run, and refcounts along the old path are incremented
  (``NFA.java:231-246``);
* folds evaluate only when the event was consumed, after edge evaluation
  (``NFA.java:248,260-265``);
* dead runs remove their buffer path; completed matches are extracted via
  ``buffer.remove`` per final state (``NFA.java:102-123``).

Preserved quirk: a run whose stage *type* is BEGIN takes the **current**
event's timestamp as the window start (``NFA.java:347-349``), so for patterns
whose first stage has cardinality ONE the window effectively starts at the
second event.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from kafkastreams_cep_tpu.compiler.stages import (
    Edge,
    EdgeOperation,
    Stage,
    StageType,
    compile_pattern,
)
from kafkastreams_cep_tpu.nfa.buffer import SharedVersionedBuffer
from kafkastreams_cep_tpu.nfa.dewey import DeweyVersion
from kafkastreams_cep_tpu.pattern.pattern import Pattern
from kafkastreams_cep_tpu.utils.events import Event, Sequence


@dataclasses.dataclass
class Run:
    """One live run of the NFA (``nfa/ComputationStage.java:31-53``)."""

    stage: Stage
    version: DeweyVersion
    event: Optional[Event] = None
    start_ts: int = -1
    seq: int = 1
    branching: bool = False

    def with_version(self, version: DeweyVersion) -> "Run":
        # setVersion clears the branching flag (ComputationStage.java:76-84).
        return Run(self.stage, version, self.event, self.start_ts, self.seq)

    def is_begin(self) -> bool:
        return self.stage.is_begin()

    def is_out_of_window(self, ts: int) -> bool:
        return self.stage.window_ms != -1 and (ts - self.start_ts) > self.stage.window_ms

    def is_forwarding(self) -> bool:
        return self.stage.is_epsilon()

    def is_forwarding_to_final(self) -> bool:
        return self.is_forwarding() and self.stage.edges[0].target.is_final()


class StatesView:
    """Read-only fold-state view handed to predicates
    (``pattern/States.java:46-68``)."""

    __slots__ = ("_nfa", "_seq")

    def __init__(self, nfa: "OracleNFA", seq: int):
        self._nfa = nfa
        self._seq = seq

    def get(self, name: str):
        return self._nfa._get_state(name, self._seq)

    def get_or_else(self, name: str, default):
        value = self._nfa._get_state(name, self._seq)
        return default if value is None else value

    def __getitem__(self, name: str):
        return self.get(name)


@dataclasses.dataclass(frozen=True)
class _Ctx:
    key: Any
    value: Any
    ts: int
    event: Event
    run: Run

    def first_ts(self) -> int:
        # NFA.java:347-349 — BEGIN-typed runs reset the window start.
        return self.ts if self.run.stage.type is StageType.BEGIN else self.run.start_ts

    def with_run(self, run: Run) -> "_Ctx":
        return _Ctx(self.key, self.value, self.ts, self.event, run)


_BRANCH_OP_SETS = (
    {EdgeOperation.PROCEED, EdgeOperation.TAKE},
    {EdgeOperation.IGNORE, EdgeOperation.TAKE},
    {EdgeOperation.IGNORE, EdgeOperation.BEGIN},
    {EdgeOperation.IGNORE, EdgeOperation.PROCEED},
)


class OracleNFA:
    """Single-partition host NFA over compiled stages."""

    def __init__(
        self,
        stages: List[Stage],
        buffer: Optional[SharedVersionedBuffer] = None,
        enforce_windows: bool = False,
    ):
        # ``enforce_windows`` mirrors ``EngineConfig.enforce_windows``: the
        # documented deviation that prunes runs by the *evaluation* stage's
        # window (the epsilon wrapper's PROCEED target), where the faithful
        # default reproduces the reference's quirk that epsilon wrappers
        # drop ``windowMs`` (``Stage.java:41-46``) so ``within()`` never
        # actually prunes.
        self.enforce_windows = bool(enforce_windows)
        self.stages = stages
        self.buffer = buffer if buffer is not None else SharedVersionedBuffer()
        self.runs: Deque[Run] = deque(
            Run(stage=s, version=DeweyVersion(1), seq=1) for s in stages if s.is_begin()
        )
        self._run_counter = 1
        self._offset_counter = 0
        # Per-run fold state: (state name, run id) -> value.
        self._agg_state: Dict[Tuple[str, int], Any] = {}
        # Declared init per state name (see pattern/aggregator.py deviation note).
        self._state_inits: Dict[str, Any] = {}
        # Typed fold state (the Aggregator<K,V,T> analog): the oracle
        # mirrors the array engine's storage casts exactly — int32 states
        # truncate toward zero and wrap, float32 states round to IEEE
        # single — so engine/oracle parity holds for every fold result.
        self._state_dtypes: Dict[str, str] = {}
        for stage in stages:
            for agg in stage.aggregates:
                self._state_inits.setdefault(agg.name, agg.init)
                self._state_dtypes.setdefault(agg.name, agg.resolved_dtype)

    @classmethod
    def from_pattern(
        cls, pattern: Pattern, enforce_windows: bool = False
    ) -> "OracleNFA":
        return cls(compile_pattern(pattern), enforce_windows=enforce_windows)

    # ------------------------------------------------------------------
    # fold state
    # ------------------------------------------------------------------
    def _get_state(self, name: str, seq: int):
        return self._agg_state.get((name, seq), self._state_inits.get(name))

    def _set_state(self, name: str, seq: int, value) -> None:
        if self._state_dtypes.get(name) == "float32":
            value = float(np.float32(value))
        else:
            v = int(value)  # truncate toward zero, like jnp int32 cast
            value = ((v + 2**31) % 2**32) - 2**31
        self._agg_state[(name, seq)] = value

    def _branch_state(self, name: str, seq: int, new_seq: int) -> None:
        # Copy-on-branch (ValueStore.java:92-97): only copies a present value.
        if (name, seq) in self._agg_state:
            self._agg_state[(name, new_seq)] = self._agg_state[(name, seq)]

    def _next_run_id(self) -> int:
        self._run_counter += 1
        return self._run_counter

    # ------------------------------------------------------------------
    # per-event stepping
    # ------------------------------------------------------------------
    def match(
        self,
        key: Any,
        value: Any,
        timestamp: int,
        topic: str = "test",
        partition: int = 0,
        offset: Optional[int] = None,
    ) -> List[Sequence]:
        """Process one event; returns completed matches (``NFA.java:94-109``).

        ``offset`` is the event identity within ``(topic, partition)``
        (``Event.java:56-69``); when omitted, a monotonic per-NFA counter is
        used so successive calls never collide.
        """
        if offset is None:
            offset = self._offset_counter
        self._offset_counter = max(self._offset_counter, offset + 1)
        event = Event(key, value, timestamp, topic, partition, offset)
        ctx_base = dict(key=key, value=value, ts=timestamp, event=event)

        finals: List[Run] = []
        for _ in range(len(self.runs)):
            run = self.runs.popleft()
            successors = self._match_one(_Ctx(run=run, **ctx_base))
            if not successors:
                self._remove_pattern(run)
            else:
                finals.extend(r for r in successors if r.is_forwarding_to_final())
            self.runs.extend(r for r in successors if not r.is_forwarding_to_final())
        matches = [self.buffer.remove(r.stage, r.event, r.version) for r in finals]
        # Fold state is keyed (name, run id); drop entries for dead runs so
        # state does not grow for the NFA's lifetime (the reference has the
        # same leak, but its stores are RocksDB-backed).
        live = {r.seq for r in self.runs}
        for key_seq in [k for k in self._agg_state if k[1] not in live]:
            del self._agg_state[key_seq]
        return matches

    def _remove_pattern(self, run: Run) -> None:
        if run.event is not None:
            self.buffer.remove(run.stage, run.event, run.version)

    def _enforced_out_of_window(self, run: Run, ts: int) -> bool:
        """The engine's ``enforce_windows`` rule (engine/matcher.py): prune
        by the evaluation stage's window; BEGIN-typed runs are exempt (their
        window start resets to the current event, ``NFA.java:347-349``)."""
        if run.is_begin():
            return False
        eval_stage = (
            run.stage.edges[0].target if run.stage.is_epsilon() else run.stage
        )
        w = eval_stage.window_ms
        return w != -1 and (ts - run.start_ts) > w

    def _match_one(self, ctx: _Ctx) -> List[Run]:
        run = ctx.run
        if not run.is_begin() and run.is_out_of_window(ctx.ts):
            return []
        if self.enforce_windows and self._enforced_out_of_window(run, ctx.ts):
            return []
        successors = self._evaluate(ctx, run.stage, None)
        if run.is_begin() and not run.is_forwarding():
            # Re-seed so a new run can start on every event (NFA.java:148-157).
            version = run.version if not successors else run.version.add_run()
            successors.append(Run(stage=run.stage, version=version, seq=self._next_run_id()))
        return successors

    def _matched_edges(self, ctx: _Ctx, stage: Stage, seq: int) -> List[Edge]:
        states = StatesView(self, seq)
        return [
            e for e in stage.edges if bool(e.matches(ctx.key, ctx.value, ctx.ts, states))
        ]

    @staticmethod
    def _is_branching(edges: List[Edge]) -> bool:
        ops = {e.op for e in edges}
        return any(s <= ops for s in _BRANCH_OP_SETS)

    def _evaluate(
        self, ctx: _Ctx, current: Stage, previous: Optional[Stage]
    ) -> List[Run]:
        """The hot loop (``NFA.java:162-250``)."""
        run = ctx.run
        seq_id = run.seq
        prev_event = run.event
        version = run.version

        matched = self._matched_edges(ctx, current, seq_id)
        if previous is None:
            # Begin-stage IGNORE edges are subsumed by the begin re-seed
            # (NFA.java:148-157): honoring them duplicates the begin run and
            # a begin-stage branch dereferences a null previous stage in the
            # reference (NFA.java:236).  Documented deviation: drop them.
            matched = [e for e in matched if e.op is not EdgeOperation.IGNORE]
        branching = self._is_branching(matched)
        cur_event = ctx.event
        start = ctx.first_ts()

        successors: List[Run] = []
        consumed = False
        ignored = False

        for edge in matched:
            if edge.op is EdgeOperation.PROCEED:
                next_ctx = ctx
                # Append a stage digit when crossing into a new stage off a
                # non-branching run (NFA.java:185-188).
                if edge.target != current and not run.branching:
                    next_ctx = ctx.with_run(run.with_version(version.add_stage()))
                successors.extend(self._evaluate(next_ctx, edge.target, current))
            elif edge.op is EdgeOperation.TAKE:
                if not branching:
                    successors.append(
                        Run(
                            stage=Stage.epsilon(current, current),
                            version=version,
                            event=cur_event,
                            start_ts=start,
                            seq=seq_id,
                        )
                    )
                    self._put(current, previous, prev_event, cur_event, version)
                else:
                    # On a branch the take is recorded under the bumped
                    # version; the surviving run comes from the branch block.
                    self._put(current, previous, prev_event, cur_event, version.add_run())
                consumed = True
            elif edge.op is EdgeOperation.BEGIN:
                self._put(current, previous, prev_event, cur_event, version)
                successors.append(
                    Run(
                        stage=Stage.epsilon(current, edge.target),
                        version=version,
                        event=cur_event,
                        start_ts=start,
                        seq=seq_id,
                    )
                )
                consumed = True
            elif edge.op is EdgeOperation.IGNORE:
                if not branching:
                    successors.append(run)
                ignored = True

        if branching:
            new_seq = self._next_run_id()
            latest_event = prev_event if ignored else cur_event
            successors.append(
                Run(
                    stage=Stage.epsilon(previous, current),
                    version=version.add_run(),
                    event=latest_event,
                    start_ts=start,
                    seq=new_seq,
                    branching=True,
                )
            )
            for agg in current.aggregates:
                self._branch_state(agg.name, seq_id, new_seq)
            self.buffer.branch(previous, prev_event, version)

        if consumed:
            for agg in current.aggregates:
                cur = self._get_state(agg.name, seq_id)
                self._set_state(agg.name, seq_id, agg.fn(ctx.key, ctx.value, cur))

        return successors

    def _put(
        self,
        current: Stage,
        previous: Optional[Stage],
        prev_event: Optional[Event],
        cur_event: Event,
        version: DeweyVersion,
    ) -> None:
        # NFA.putToSharedBuffer (NFA.java:252-257).
        if previous is not None:
            self.buffer.put(current, cur_event, previous, prev_event, version)
        else:
            self.buffer.put_first(current, cur_event, version)
