"""Dewey version numbers — the SASE+ run-versioning algebra.

Semantics follow ``nfa/DeweyVersion.java``:

* ``add_run``   increments the last component (``DeweyVersion.java:51-56``),
* ``add_stage`` appends a ``0`` component (``DeweyVersion.java:84-86``),
* ``is_compatible(that)`` is true when ``that`` is a proper prefix of
  ``self``, or both have equal length with an equal prefix and
  ``last(self) >= last(that)`` (``DeweyVersion.java:62-82``).

This host class backs the oracle engine; the array engine uses the
fixed-width equivalent in ``ops/dewey_ops.py``.
"""

from __future__ import annotations

from typing import Tuple, Union


class DeweyVersion:
    __slots__ = ("components",)

    def __init__(self, init: Union[int, str, Tuple[int, ...]] = 1):
        if isinstance(init, int):
            self.components: Tuple[int, ...] = (init,)
        elif isinstance(init, str):
            self.components = tuple(int(part) for part in init.split("."))
        else:
            self.components = tuple(init)

    def add_run(self) -> "DeweyVersion":
        return DeweyVersion(self.components[:-1] + (self.components[-1] + 1,))

    def add_stage(self) -> "DeweyVersion":
        return DeweyVersion(self.components + (0,))

    def __len__(self) -> int:
        return len(self.components)

    def is_compatible(self, that: "DeweyVersion") -> bool:
        mine, theirs = self.components, that.components
        if len(mine) > len(theirs):
            return mine[: len(theirs)] == theirs
        if len(mine) == len(theirs):
            return mine[:-1] == theirs[:-1] and mine[-1] >= theirs[-1]
        return False

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, DeweyVersion):
            return NotImplemented
        return self.components == other.components

    def __hash__(self) -> int:
        return hash(self.components)

    def __str__(self) -> str:
        return ".".join(str(c) for c in self.components)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"DeweyVersion({self})"
