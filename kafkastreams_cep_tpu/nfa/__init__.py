from kafkastreams_cep_tpu.nfa.dewey import DeweyVersion
from kafkastreams_cep_tpu.nfa.buffer import SharedVersionedBuffer
from kafkastreams_cep_tpu.nfa.oracle import OracleNFA

__all__ = ["DeweyVersion", "SharedVersionedBuffer", "OracleNFA"]
