"""Dewey version renormalization — bounded-width versions on unbounded streams.

The reference's versions grow without bound: every event that a run spends
straddling a stage boundary appends a ``.0`` component (``NFA.java:185-188``
via ``DeweyVersion.java:84-86``), so version length is O(events in the run's
lifetime) — see the oracle reproducing ``1.0.0.0...`` growth on the stock
pattern.  The reference can afford that (heap tuples); the array engine's
fixed ``[D]`` width cannot, and at overflow the digit is dropped and counted
(``ops/dewey_ops.py:add_stage``).

This module removes the growth instead of widening ``D``: between scan
steps, interior positions provably equal to ``0`` in *every* version that
crosses them are deleted from all versions in the lane at once.  Deleting
position ``k`` is **semantics-preserving** — every ``is_compatible(q, p)``
outcome, for all current versions and all versions derivable from current
run versions by future ``add_stage``/``add_run`` chains, is unchanged —
when all of:

1. every live pointer version ``p`` has ``len(p) <= k``, or
   ``p[k] == 0 and len(p) >= k + 2``;
2. every alive non-seed run version crosses with slack:
   ``len(v) >= k + 2 and v[k] == 0`` (a run *ending* at ``k`` or short of it
   could later grow fresh digits across ``k`` and misalign against the
   shifted pointers — seen in the worked counterexamples in the proof note
   below);
3. alive seed runs (``id_pos < 0`` — fresh counter version, nothing
   consumed, no buffer footprint) are exempt from (2) but no crossing
   version may share their first digit (their descendants are then
   digit-0-incompatible with every shifted version, before and after).

Proof sketch (pairwise, per position; simultaneous deletion composes by
induction on descending ``k``): pairs both crossing ``k`` shift together —
digit comparisons below ``k`` unchanged, the deleted digits are equal
(``0 == 0``), digits above shift equally, and neither last digit moves
relative to its version (``len >= k+2`` keeps the last digit off ``k``);
pairs where only the longer version crosses preserve strict length
inequality because ``len >= k+2`` keeps the shrunken length ``>= k+1 >
k >= len(short)``; the ``len == k+1`` exclusion is what forbids a shrink
onto *equal* length, where the last-digit ``>=`` rule could flip a verdict
(e.g. ``q=1.0.3`` deleting ``k=1`` against a sibling pointer ``p=1.5``).

The deletable positions are exactly where unbounded growth happens (the
appended zero runs), so a sweep cadence that outpaces per-batch growth
keeps ``D`` bounded for arbitrarily long streams — with ``ver_overflows``
still counting any trace that outruns it.
"""

from __future__ import annotations

import jax.numpy as jnp


def safe_positions(
    run_ver, run_vlen, run_alive, run_seed, pver, pvlen, ptr_live
):
    """The ``[D]`` bool mask of deletable positions for one lane.

    ``run_ver [R, D]``, ``run_vlen [R]``, ``run_alive [R]``, ``run_seed
    [R]`` (alive & never-consumed), ``pver [N, D]``, ``pvlen [N]``,
    ``ptr_live [N]`` (entry live & slot < npreds).
    """
    i32 = jnp.int32
    D = run_ver.shape[1]
    idx = jnp.arange(D, dtype=i32)  # position axis

    nonseed = run_alive & ~run_seed

    def cross_ok(ver, vlen, mask):
        # For versions in ``mask`` crossing k: digit 0 at k and len >= k+2.
        crossing = mask[:, None] & (vlen[:, None] > idx[None, :])
        ok = (ver == 0) & (vlen[:, None] >= idx[None, :] + 2)
        return ~jnp.any(crossing & ~ok, axis=0)  # [D]

    # (2): non-seed runs must ALL cross with slack (a short non-seed run
    # blocks every k at or beyond its length).
    run_short = nonseed[:, None] & (run_vlen[:, None] <= idx[None, :])
    run_ok = cross_ok(run_ver, run_vlen, nonseed) & ~jnp.any(run_short, axis=0)

    # (1): pointers either don't reach k or cross with slack.
    ptr_ok = cross_ok(pver, pvlen, ptr_live)

    # (3): no crossing version shares a seed's first digit.
    cross_run = run_alive[:, None] & (run_vlen[:, None] > idx[None, :])
    cross_ptr = ptr_live[:, None] & (pvlen[:, None] > idx[None, :])
    seed_d0 = run_ver[:, 0]  # [R]
    clash_run = jnp.any(
        run_seed[:, None, None]
        & cross_run[None, :, :]
        & (seed_d0[:, None, None] == run_ver[None, :, 0:1]),
        axis=(0, 1),
    )
    clash_ptr = jnp.any(
        run_seed[:, None, None]
        & cross_ptr[None, :, :]
        & (seed_d0[:, None, None] == pver[None, :, 0:1]),
        axis=(0, 1),
    )
    return run_ok & ptr_ok & ~clash_run & ~clash_ptr  # [D]


def delete_positions(ver, vlen, safe):
    """Stable-compact ``safe`` positions out of ``ver [..., D]``.

    Positions ``k`` with ``safe[k] and k < vlen`` are removed; later digits
    shift down, the tail zero-fills, ``vlen`` shrinks by the removed count.
    """
    i32 = jnp.int32
    D = ver.shape[-1]
    idx = jnp.arange(D, dtype=i32)
    shape1 = (1,) * (ver.ndim - 1)
    inside = idx.reshape(shape1 + (D,)) < vlen[..., None]
    drop = safe.reshape(shape1 + (D,)) & inside
    keep = ~drop
    tgt = jnp.cumsum(keep.astype(i32), axis=-1) - 1
    perm = keep[..., None] & (idx.reshape(shape1 + (1, D)) == tgt[..., None])
    new_ver = jnp.sum(
        jnp.where(perm, ver[..., None], 0), axis=-2
    ).astype(ver.dtype)
    new_vlen = (vlen - jnp.sum(drop, axis=-1)).astype(vlen.dtype)
    return new_ver, new_vlen


def renorm_lane(run_ver, run_vlen, alive, id_pos, slab):
    """Renormalize one lane's run + pointer versions; returns
    ``(run_ver, run_vlen, slab, n_deleted)``."""
    E, MP, D = slab.pver.shape
    seed = alive & (id_pos < 0)
    live_entry = slab.stage >= 0
    slot_live = live_entry[:, None] & (
        jnp.arange(MP, dtype=jnp.int32)[None, :] < slab.npreds[:, None]
    )
    pv = slab.pver.reshape(E * MP, D)
    pl = slab.pvlen.reshape(E * MP)
    safe = safe_positions(
        run_ver, run_vlen, alive, seed, pv, pl, slot_live.reshape(E * MP)
    )
    new_rv, new_rl = delete_positions(run_ver, run_vlen, safe)
    new_pv, new_pl = delete_positions(
        slab.pver, slab.pvlen, safe
    )
    # Only live rows move; dead/garbage rows stay byte-identical so
    # differential tests against the un-renormalized path stay sharp.
    run_m = alive
    rv = jnp.where(run_m[:, None], new_rv, run_ver)
    rl = jnp.where(run_m, new_rl, run_vlen)
    pvo = jnp.where(slot_live[:, :, None], new_pv, slab.pver)
    plo = jnp.where(slot_live, new_pl, slab.pvlen)
    slab = slab._replace(pver=pvo, pvlen=plo)
    return rv, rl, slab, jnp.sum(safe.astype(jnp.int32))
