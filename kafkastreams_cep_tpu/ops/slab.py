"""Device shared versioned buffer — the SASE match DAG as a fixed slab.

Array equivalent of the host dict buffer (``nfa/buffer.py``) and the
reference ``nfa/buffer/impl/KVSharedVersionedBuffer.java``.  One slab holds
the buffer for ONE key/partition; the engine ``vmap``s these functions over
the key axis.

Representation (``E`` entries × ``MP`` predecessor pointers × depth ``D``):

* an *entry* is keyed by ``(stage, off)`` — the stage's canonical identity
  position (``compiler/tables.py``) and the event offset, the array form of
  ``StackEventKey`` (``StackEventKey.java:28-54``); ``stage == -1`` marks a
  free slot;
* each entry carries a refcount and an ordered list of Dewey-versioned
  predecessor pointers (``TimedKeyValue.java:27-45``); a pointer with
  ``pstage == -1`` is the null-predecessor run origin
  (``KVSharedVersionedBuffer.java:117-128``).

Semantics preserved exactly (differentially tested against the host buffer):

* ``put`` requires the predecessor entry to exist — the reference throws
  (``KVSharedVersionedBuffer.java:86-89``); under ``jit`` we count it in
  ``missing`` and drop the write;
* ``put_first`` overwrites unconditionally (``:117-128``);
* walks select, at each hop, the **first** pointer (insertion order) whose
  version is compatible with the walk version, then adopt that pointer's
  version (``TimedKeyValue.java:83-92``);
* refcount decrements floor at zero (``TimedKeyValue.java:59-61``); an entry
  is deleted only when ``remove`` and ``refs == 0`` and it has at most one
  predecessor; the traversed pointer is pruned when ``refs == 0``
  (``KVSharedVersionedBuffer.java:147-171``);
* capacity limits (slab full, pointer list full, walk bound) have no
  reference analog; overflows are counted, never raised.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from kafkastreams_cep_tpu.ops import dewey_ops


class SlabState(NamedTuple):
    stage: jnp.ndarray  # [E] int32 — identity stage position; -1 free
    off: jnp.ndarray  # [E] int32 — event offset
    refs: jnp.ndarray  # [E] int32
    npreds: jnp.ndarray  # [E] int32
    pstage: jnp.ndarray  # [E, MP] int32 — -1 = null pointer (run origin)
    poff: jnp.ndarray  # [E, MP] int32
    pver: jnp.ndarray  # [E, MP, D] int32
    pvlen: jnp.ndarray  # [E, MP] int32
    full_drops: jnp.ndarray  # scalar int32 — entry allocation failures
    pred_drops: jnp.ndarray  # scalar int32 — pointer-list overflow drops
    missing: jnp.ndarray  # scalar int32 — lookups the reference would NPE on
    trunc: jnp.ndarray  # scalar int32 — walks cut short by the walk bound


def make(num_entries: int, max_preds: int, depth: int) -> SlabState:
    E, MP, D = num_entries, max_preds, depth
    i32 = jnp.int32
    return SlabState(
        stage=jnp.full((E,), -1, dtype=i32),
        off=jnp.full((E,), -1, dtype=i32),
        refs=jnp.zeros((E,), dtype=i32),
        npreds=jnp.zeros((E,), dtype=i32),
        pstage=jnp.full((E, MP), -1, dtype=i32),
        poff=jnp.full((E, MP), -1, dtype=i32),
        pver=jnp.zeros((E, MP, D), dtype=i32),
        pvlen=jnp.zeros((E, MP), dtype=i32),
        full_drops=jnp.zeros((), dtype=i32),
        pred_drops=jnp.zeros((), dtype=i32),
        missing=jnp.zeros((), dtype=i32),
        trunc=jnp.zeros((), dtype=i32),
    )


def find(slab: SlabState, stage, off) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Entry index for ``(stage, off)`` and whether it exists."""
    hit = (slab.stage == stage) & (slab.off == off)
    return jnp.argmax(hit), jnp.any(hit)


def _alloc(slab: SlabState):
    free = slab.stage < 0
    return jnp.argmax(free), jnp.any(free)


def _select_pointer(slab: SlabState, e, qver, qlen):
    """First version-compatible predecessor pointer of entry ``e``
    (``TimedKeyValue.java:83-92``)."""
    mp = slab.pstage.shape[1]
    valid = jnp.arange(mp, dtype=jnp.int32) < slab.npreds[e]
    compat = jax.vmap(dewey_ops.is_compatible, in_axes=(None, None, 0, 0))(
        qver, qlen, slab.pver[e], slab.pvlen[e]
    )
    hit = compat & valid
    return jnp.argmax(hit), jnp.any(hit)


def _append_pointer(slab: SlabState, e, pstage, poff, ver, vlen, enable):
    """Append a pointer to entry ``e``'s list; drops (counted) when full."""
    mp = slab.pstage.shape[1]
    n = slab.npreds[e]
    full = n >= mp
    do = enable & ~full
    slot = jnp.minimum(n, mp - 1)

    def upd(field, value):
        return field.at[e, slot].set(jnp.where(do, value, field[e, slot]))

    return slab._replace(
        pstage=upd(slab.pstage, pstage),
        poff=upd(slab.poff, poff),
        pver=slab.pver.at[e, slot].set(jnp.where(do, ver, slab.pver[e, slot])),
        pvlen=upd(slab.pvlen, vlen),
        npreds=slab.npreds.at[e].add(jnp.where(do, 1, 0)),
        pred_drops=slab.pred_drops + jnp.where(enable & full, 1, 0),
    )


def _prune_pointer(slab: SlabState, e, j, enable):
    """Remove pointer ``j`` of entry ``e``, shifting later pointers left to
    keep insertion order (``TimedKeyValue.removePredecessor``)."""
    mp = slab.pstage.shape[1]
    idx = jnp.arange(mp, dtype=jnp.int32)
    src = jnp.where(idx >= j, jnp.minimum(idx + 1, mp - 1), idx)

    def shift(field):
        return jnp.where(enable, jnp.take(field, src, axis=0), field)

    pstage_e = shift(slab.pstage[e])
    poff_e = shift(slab.poff[e])
    pvlen_e = shift(slab.pvlen[e])
    pver_e = shift(slab.pver[e])
    return slab._replace(
        pstage=slab.pstage.at[e].set(pstage_e),
        poff=slab.poff.at[e].set(poff_e),
        pvlen=slab.pvlen.at[e].set(pvlen_e),
        pver=slab.pver.at[e].set(pver_e),
        npreds=slab.npreds.at[e].add(jnp.where(enable, -1, 0)),
    )


def put_first(slab: SlabState, stage, off, ver, vlen, enable=True) -> SlabState:
    """First-stage put: fresh entry whose single null-predecessor pointer
    records the run version; overwrites any existing entry
    (``KVSharedVersionedBuffer.java:117-128``)."""
    enable = jnp.asarray(enable)
    existing, found = find(slab, stage, off)
    free, has_free = _alloc(slab)
    e = jnp.where(found, existing, free)
    ok = enable & (found | has_free)

    def set1(field, value):
        return field.at[e].set(jnp.where(ok, value, field[e]))

    slab = slab._replace(
        stage=set1(slab.stage, stage),
        off=set1(slab.off, off),
        refs=set1(slab.refs, 1),
        npreds=set1(slab.npreds, 0),
        full_drops=slab.full_drops + jnp.where(enable & ~found & ~has_free, 1, 0),
    )
    return _append_pointer(slab, e, jnp.int32(-1), jnp.int32(-1), ver, vlen, ok)


def put(slab: SlabState, cur_stage, cur_off, prev_stage, prev_off, ver, vlen, enable=True) -> SlabState:
    """Append a versioned predecessor pointer to ``(cur_stage, cur_off)``.

    The predecessor entry must exist (``KVSharedVersionedBuffer.java:86-89``);
    a miss is counted and the write dropped.
    """
    enable = jnp.asarray(enable)
    _, prev_found = find(slab, prev_stage, prev_off)
    slab = slab._replace(missing=slab.missing + jnp.where(enable & ~prev_found, 1, 0))
    enable = enable & prev_found

    existing, found = find(slab, cur_stage, cur_off)
    free, has_free = _alloc(slab)
    e = jnp.where(found, existing, free)
    create = enable & ~found & has_free
    ok = enable & (found | has_free)

    def init1(field, value):
        return field.at[e].set(jnp.where(create, value, field[e]))

    slab = slab._replace(
        stage=init1(slab.stage, cur_stage),
        off=init1(slab.off, cur_off),
        refs=init1(slab.refs, 1),
        npreds=init1(slab.npreds, 0),
        full_drops=slab.full_drops + jnp.where(enable & ~found & ~has_free, 1, 0),
    )
    return _append_pointer(slab, e, prev_stage, prev_off, ver, vlen, ok)


def branch(slab: SlabState, stage, off, ver, vlen, max_walk: int, enable=True) -> SlabState:
    """Refcount-increment walk so shared prefixes survive sibling removal
    (``KVSharedVersionedBuffer.java:99-110``)."""

    def body(_, carry):
        slab, stage, off, qver, qlen, active = carry
        e, found = find(slab, stage, off)
        slab = slab._replace(missing=slab.missing + jnp.where(active & ~found, 1, 0))
        active = active & found
        slab = slab._replace(refs=slab.refs.at[e].add(jnp.where(active, 1, 0)))
        j, sel = _select_pointer(slab, e, qver, qlen)
        active = active & sel & (slab.pstage[e, j] >= 0)
        stage = jnp.where(active, slab.pstage[e, j], stage)
        off = jnp.where(active, slab.poff[e, j], off)
        qver = jnp.where(active, slab.pver[e, j], qver)
        qlen = jnp.where(active, slab.pvlen[e, j], qlen)
        return slab, stage, off, qver, qlen, active

    init = (
        slab,
        jnp.asarray(stage, jnp.int32),
        jnp.asarray(off, jnp.int32),
        jnp.asarray(ver, jnp.int32),
        jnp.asarray(vlen, jnp.int32),
        jnp.asarray(enable),
    )
    out = jax.lax.fori_loop(0, max_walk, body, init)
    slab, still_active = out[0], out[5]
    # A walk still active after max_walk hops was truncated: refcounts along
    # the untraversed tail were not incremented (no reference analog).
    return slab._replace(trunc=slab.trunc + jnp.where(still_active, 1, 0))


def peek(
    slab: SlabState,
    stage,
    off,
    ver,
    vlen,
    max_walk: int,
    remove: bool,
    enable=True,
):
    """Backward pointer walk assembling a match, final stage first.

    Returns ``(slab, out_stage[max_walk], out_off[max_walk], count)``; hops
    beyond the walk bound are dropped (no reference analog — counted via the
    returned ``count`` saturating at ``max_walk``).  With ``remove`` this is
    ``SharedVersionedBuffer.remove`` (refcount GC + pointer pruning);
    without, ``get`` — which still decrements refcounts, a preserved quirk of
    ``KVSharedVersionedBuffer.peek`` (``:156``).
    """
    L = max_walk
    out_stage = jnp.full((L,), -1, dtype=jnp.int32)
    out_off = jnp.full((L,), -1, dtype=jnp.int32)

    def body(i, carry):
        slab, stage, off, qver, qlen, active, out_stage, out_off, count = carry
        e, found = find(slab, stage, off)
        slab = slab._replace(missing=slab.missing + jnp.where(active & ~found, 1, 0))
        active = active & found

        refs_left = jnp.maximum(slab.refs[e] - 1, 0)  # floors at zero
        slab = slab._replace(
            refs=slab.refs.at[e].set(jnp.where(active, refs_left, slab.refs[e]))
        )
        delete = active & remove & (refs_left == 0) & (slab.npreds[e] <= 1)
        slab = slab._replace(
            stage=slab.stage.at[e].set(jnp.where(delete, -1, slab.stage[e])),
            off=slab.off.at[e].set(jnp.where(delete, -1, slab.off[e])),
        )

        out_stage = out_stage.at[i].set(jnp.where(active, stage, out_stage[i]))
        out_off = out_off.at[i].set(jnp.where(active, off, out_off[i]))
        count = count + jnp.where(active, 1, 0)

        j, sel = _select_pointer(slab, e, qver, qlen)
        sel = sel & active
        prune = sel & remove & (refs_left == 0)
        nxt_stage = slab.pstage[e, j]
        nxt_off = slab.poff[e, j]
        nxt_ver = slab.pver[e, j]
        nxt_len = slab.pvlen[e, j]
        slab = _prune_pointer(slab, e, j, prune)

        active = sel & (nxt_stage >= 0)
        stage = jnp.where(active, nxt_stage, stage)
        off = jnp.where(active, nxt_off, off)
        qver = jnp.where(active, nxt_ver, qver)
        qlen = jnp.where(active, nxt_len, qlen)
        return slab, stage, off, qver, qlen, active, out_stage, out_off, count

    init = (
        slab,
        jnp.asarray(stage, jnp.int32),
        jnp.asarray(off, jnp.int32),
        jnp.asarray(ver, jnp.int32),
        jnp.asarray(vlen, jnp.int32),
        jnp.asarray(enable),
        out_stage,
        out_off,
        jnp.zeros((), dtype=jnp.int32),
    )
    slab, _, _, _, _, still_active, out_stage, out_off, count = jax.lax.fori_loop(
        0, L, body, init
    )
    # Truncated extraction: the untraversed tail keeps its refcounts (a leak
    # the caller can see via this counter) and the returned hops are partial.
    slab = slab._replace(trunc=slab.trunc + jnp.where(still_active, 1, 0))
    return slab, out_stage, out_off, count


def live_entries(slab: SlabState) -> jnp.ndarray:
    """Number of occupied slots (host/diagnostic helper)."""
    return jnp.sum(slab.stage >= 0)


# Eager per-op dispatch is orders of magnitude slower than compiled code on
# this host; the public entry points are jitted (the engine additionally
# inlines them under its own jit, where these wrappers are free).
put_first = jax.jit(put_first)
put = jax.jit(put)
branch = jax.jit(branch, static_argnames=("max_walk",))
peek = jax.jit(peek, static_argnames=("max_walk", "remove"))
