"""Device shared versioned buffer — the SASE match DAG as a fixed slab.

Array equivalent of the host dict buffer (``nfa/buffer.py``) and the
reference ``nfa/buffer/impl/KVSharedVersionedBuffer.java``.  One slab holds
the buffer for ONE key/partition; the engine ``vmap``s these functions over
the key axis.

Representation (``E`` entries × ``MP`` predecessor pointers × depth ``D``):

* an *entry* is keyed by ``(stage, off)`` — the stage's canonical identity
  position (``compiler/tables.py``) and the event offset, the array form of
  ``StackEventKey`` (``StackEventKey.java:28-54``); ``stage == -1`` marks a
  free slot;
* each entry carries a refcount and an ordered list of Dewey-versioned
  predecessor pointers (``TimedKeyValue.java:27-45``); a pointer with
  ``pstage == -1`` is the null-predecessor run origin
  (``KVSharedVersionedBuffer.java:117-128``).

Semantics preserved exactly (differentially tested against the host buffer):

* ``put`` requires the predecessor entry to exist — the reference throws
  (``KVSharedVersionedBuffer.java:86-89``); under ``jit`` we count it in
  ``missing`` and drop the write;
* ``put_first`` overwrites unconditionally (``:117-128``);
* walks select, at each hop, the **first** pointer (insertion order) whose
  version is compatible with the walk version, then adopt that pointer's
  version (``TimedKeyValue.java:83-92``);
* refcount decrements floor at zero (``TimedKeyValue.java:59-61``); an entry
  is deleted only when ``remove`` and ``refs == 0`` and it has at most one
  predecessor; the traversed pointer is pruned when ``refs == 0``
  (``KVSharedVersionedBuffer.java:147-171``);
* capacity limits (slab full, pointer list full, walk bound) have no
  reference analog; overflows are counted, never raised.

Implementation note: no traced-index scatters/gathers/dynamic-slices.
Every indexed read/write goes through one-hot masked selects (``_oh`` /
``_get_e`` / ``_get_ej``), which XLA fuses into the surrounding
elementwise work.  On TPU, batched-index scatter/gather ops do not fuse —
each becomes a standalone kernel whose launch overhead, times the
thousands of tiny slab ops per step, dominated the engine's early runtime
by ~50x (and scaled linearly with the vmapped lane count).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from kafkastreams_cep_tpu.ops import dewey_ops
from kafkastreams_cep_tpu.ops.onehot import (
    get_at as _get_e,
    get_at2 as _get_ej,
    oh as _oh,
)


class SlabState(NamedTuple):
    stage: jnp.ndarray  # [E] int32 — identity stage position; -1 free
    off: jnp.ndarray  # [E] int32 — event offset
    refs: jnp.ndarray  # [E] int32
    npreds: jnp.ndarray  # [E] int32
    pstage: jnp.ndarray  # [E, MP] int32 — -1 = null pointer (run origin)
    poff: jnp.ndarray  # [E, MP] int32
    pver: jnp.ndarray  # [E, MP, D] int32
    pvlen: jnp.ndarray  # [E, MP] int32
    full_drops: jnp.ndarray  # scalar int32 — entry allocation failures
    pred_drops: jnp.ndarray  # scalar int32 — pointer-list overflow drops
    missing: jnp.ndarray  # scalar int32 — lookups the reference would NPE on
    trunc: jnp.ndarray  # scalar int32 — walks cut short by the walk bound
    collisions: jnp.ndarray  # scalar int32 — same-entry same-hop meetings of
    #   two lockstep remove-walkers: the exact trigger for prune/delete
    #   attribution deviating from the reference's sequential order.  Always
    #   0 on the default paths (walker_budget=1 runs walkers alone; the
    #   Pallas kernel is sequential by construction); nonzero means a
    #   walker_budget>1 run may have diverged (see EngineConfig).
    # --- two-tier telemetry (zero when hot_entries == 0; see module note
    #     "Two-tier layout" below).  Not capacity counters: they never
    #     indicate loss, only where walk hops resolved.
    hot_hits: jnp.ndarray  # scalar int32 — walk hops resolved in the hot tier
    hot_misses: jnp.ndarray  # scalar int32 — walk hops not resolved hot
    overflow_walks: jnp.ndarray  # scalar int32 — walk hops resolved overflow
    demotions: jnp.ndarray  # scalar int32 — hot -> overflow entry moves
    # --- walk-cost telemetry (never loss indicators): every active hop of
    #     every walker is classified exactly once by walker class, so the
    #     reduce-width perf model (PROFILE_r05/r06: per-hop masked reduces x
    #     lockstep trip counts) is measurable on CPU CI without a chip.
    walk_hops: jnp.ndarray  # scalar int32 — branch/dead-removal walker hops
    extract_hops: jnp.ndarray  # scalar int32 — eager in-step extraction hops
    drain_hops: jnp.ndarray  # scalar int32 — deferred drain-pass hops (lazy)
    # --- per-stage walk-cost attribution (EngineConfig.stage_attribution):
    #     hop tallies keyed by the walker's CURRENT stage at each hop, the
    #     per-stage half of the continuous-profiling layer.  Shape [S]
    #     (S = the pattern's stage count) when attribution is on, [0] when
    #     off — a zero-size array adds no device work and no kernel
    #     plumbing (both Pallas kernels skip it at trace time).  Never a
    #     loss indicator.
    stage_hops: jnp.ndarray  # [S] int32 — walk hops by current stage


def make(
    num_entries: int, max_preds: int, depth: int, num_stages: int = 0
) -> SlabState:
    E, MP, D = num_entries, max_preds, depth
    i32 = jnp.int32
    return SlabState(
        stage=jnp.full((E,), -1, dtype=i32),
        off=jnp.full((E,), -1, dtype=i32),
        refs=jnp.zeros((E,), dtype=i32),
        npreds=jnp.zeros((E,), dtype=i32),
        pstage=jnp.full((E, MP), -1, dtype=i32),
        poff=jnp.full((E, MP), -1, dtype=i32),
        pver=jnp.zeros((E, MP, D), dtype=i32),
        pvlen=jnp.zeros((E, MP), dtype=i32),
        full_drops=jnp.zeros((), dtype=i32),
        pred_drops=jnp.zeros((), dtype=i32),
        missing=jnp.zeros((), dtype=i32),
        trunc=jnp.zeros((), dtype=i32),
        collisions=jnp.zeros((), dtype=i32),
        hot_hits=jnp.zeros((), dtype=i32),
        hot_misses=jnp.zeros((), dtype=i32),
        overflow_walks=jnp.zeros((), dtype=i32),
        demotions=jnp.zeros((), dtype=i32),
        walk_hops=jnp.zeros((), dtype=i32),
        extract_hops=jnp.zeros((), dtype=i32),
        drain_hops=jnp.zeros((), dtype=i32),
        stage_hops=jnp.zeros((num_stages,), dtype=i32),
    )


def find(slab: SlabState, stage, off) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Entry index for ``(stage, off)`` and whether it exists."""
    hit = (slab.stage == stage) & (slab.off == off)
    return jnp.argmax(hit), jnp.any(hit)


def _alloc(slab: SlabState):
    free = slab.stage < 0
    return jnp.argmax(free), jnp.any(free)


# ---------------------------------------------------------------------------
# Two-tier layout (``hot_entries`` static knob, 0 = legacy single tier)
#
# Slots ``[0, hot_entries)`` are the *hot tier*, the rest the *overflow
# tier*.  New entries always land in the hot tier: a free hot slot if one
# exists, else the least-recent hot entry (minimum event offset — offsets
# are monotone per lane, so the offset IS the recency; ties break to the
# lowest index) is *demoted* into a free overflow slot and its hot slot
# reused.  An allocation fails only when the WHOLE slab is full — exactly
# the single-tier drop condition — so ``full_drops`` and every other
# overflow counter stay bit-identical to the single-tier engine; only the
# slot an entry occupies (its tier placement) may differ.
#
# Lookups key on ``(stage, off)``, which is unique across the whole slab,
# so results are placement-independent; this jnp path therefore keeps its
# full-slab masked lookups (under XLA both tiers would be computed anyway)
# and only *accounts* tier residency via the hot_hits / hot_misses /
# overflow_walks counters.  The Pallas kernels (``ops/walk_kernel.py``,
# ``ops/scan_kernel.py``) exploit the same layout structurally: the per-hop
# reduce runs over the hot rows only and the overflow rows are touched
# under a block-level ``pl.when`` that skips entirely when every lane of
# the block resolved hot — the E-linear hop cost drops to E_hot-linear on
# the common path (PROFILE_r05.md finding 2, redesign candidate 1).
# ---------------------------------------------------------------------------


def _alloc_slot(slab: SlabState, hot_entries: int, want):
    """Allocation slot for one new entry, two-tier aware.

    Returns ``(slab, e, ok)``.  ``want`` gates the (slab-mutating)
    demotion: pass ``enable & ~found`` so lookups that reuse an existing
    entry never demote.  With ``hot_entries == 0`` this is :func:`_alloc`.
    """
    free = slab.stage < 0
    if not hot_entries:
        return slab, jnp.argmax(free), jnp.any(free)
    E = slab.stage.shape[0]
    EH = hot_entries
    i32 = jnp.int32
    idx = jnp.arange(E, dtype=i32)
    is_hot = idx < EH
    free_hot = free & is_hot
    free_ov = free & ~is_hot
    any_fh = jnp.any(free_hot)
    any_fo = jnp.any(free_ov)
    e_hot = jnp.argmax(free_hot).astype(i32)
    e_ov = jnp.argmax(free_ov).astype(i32)
    # Demotion victim: least-recent (min event offset) occupied hot entry,
    # first index on ties — deterministic, matched by both Pallas kernels.
    occ_hot = ~free & is_hot
    okey = jnp.where(occ_hot, slab.off, i32(1 << 30))
    victim = jnp.argmin(okey).astype(i32)
    demote = jnp.asarray(want) & ~any_fh & any_fo

    vm = _oh(victim, E) & demote
    om = _oh(e_ov, E) & demote

    def mv(field):
        m_v = vm.reshape((E,) + (1,) * (field.ndim - 1))
        m_o = om.reshape((E,) + (1,) * (field.ndim - 1))
        row = jnp.sum(jnp.where(m_v, field, 0), axis=0)
        return jnp.where(m_o, row[None].astype(field.dtype), field)

    slab = slab._replace(
        stage=jnp.where(vm, -1, mv(slab.stage)),
        off=jnp.where(vm, -1, mv(slab.off)),
        refs=mv(slab.refs),
        npreds=mv(slab.npreds),
        pstage=mv(slab.pstage),
        poff=mv(slab.poff),
        pver=mv(slab.pver),
        pvlen=mv(slab.pvlen),
        demotions=slab.demotions + jnp.where(demote, 1, 0),
    )
    e = jnp.where(any_fh, e_hot, victim)
    return slab, e, any_fh | any_fo


def _tier_counts(slab: SlabState, active, found_hot, found):
    """Walk-hop tier accounting: ``active`` walkers whose entry resolved in
    the hot tier / did not / resolved in the overflow tier.  Works on any
    matching bool shapes (scalar per-walker or ``[P]`` lockstep)."""
    i32 = jnp.int32
    return slab._replace(
        hot_hits=slab.hot_hits
        + jnp.sum((active & found_hot).astype(i32)),
        hot_misses=slab.hot_misses
        + jnp.sum((active & ~found_hot).astype(i32)),
        overflow_walks=slab.overflow_walks
        + jnp.sum((active & ~found_hot & found).astype(i32)),
    )


def _hop_counts(
    slab: SlabState, active, want_out=None, kind: str = "walk", stage=None
):
    """Classify one hop's active walkers into the walk-cost counters.

    ``want_out`` (when given) splits the pool: emitting walkers count to
    the ``kind`` class ("extract" eager in-step, "drain" deferred pass),
    non-emitting walkers to ``walk_hops``.  Without it, every active
    walker counts to ``kind``.  Static ``kind`` keeps the counter choice
    trace-time, mirroring the Pallas kernels' static routing.

    ``stage`` (the walkers' current stage, scalar or ``[P]``) additionally
    attributes every active hop to its ``stage_hops[stage]`` row when the
    slab carries stage attribution (``stage_hops.shape[-1] > 0``); with
    attribution off the tally is skipped at trace time.
    """
    i32 = jnp.int32
    if want_out is None:
        n_emit = jnp.sum(jnp.asarray(active).astype(i32))
        n_walk = jnp.zeros((), i32)
    else:
        n_emit = jnp.sum((active & want_out).astype(i32))
        n_walk = jnp.sum((active & ~want_out).astype(i32))
    upd = {"walk_hops": slab.walk_hops + n_walk}
    if kind == "walk":
        upd["walk_hops"] = upd["walk_hops"] + n_emit
    elif kind == "extract":
        upd["extract_hops"] = slab.extract_hops + n_emit
    elif kind == "drain":
        upd["drain_hops"] = slab.drain_hops + n_emit
    else:  # pragma: no cover - trace-time misuse
        raise ValueError(f"unknown hop kind {kind!r}")
    S = int(slab.stage_hops.shape[-1])
    if S and stage is not None:
        oh = (
            jnp.asarray(stage, i32)[..., None]
            == jnp.arange(S, dtype=i32)
        ) & jnp.asarray(active)[..., None]
        upd["stage_hops"] = slab.stage_hops + jnp.sum(
            oh.astype(i32).reshape(-1, S), axis=0
        )
    return slab._replace(**upd)


def _select_pointer(slab: SlabState, e, qver, qlen):
    """First version-compatible predecessor pointer of entry ``e``
    (``TimedKeyValue.java:83-92``)."""
    mp = slab.pstage.shape[1]
    valid = jnp.arange(mp, dtype=jnp.int32) < _get_e(slab.npreds, e)
    compat = jax.vmap(dewey_ops.is_compatible, in_axes=(None, None, 0, 0))(
        qver, qlen, _get_e(slab.pver, e), _get_e(slab.pvlen, e)
    )
    hit = compat & valid
    return jnp.argmax(hit), jnp.any(hit)


def _append_pointer(slab: SlabState, e, pstage, poff, ver, vlen, enable):
    """Append a pointer to entry ``e``'s list; drops (counted) when full."""
    E, mp = slab.pstage.shape
    n = _get_e(slab.npreds, e)
    full = n >= mp
    do = enable & ~full
    slot = jnp.minimum(n, mp - 1)
    m2 = (_oh(e, E)[:, None] & _oh(slot, mp)[None, :]) & do

    return slab._replace(
        pstage=jnp.where(m2, pstage, slab.pstage),
        poff=jnp.where(m2, poff, slab.poff),
        pver=jnp.where(m2[:, :, None], ver[None, None, :], slab.pver),
        pvlen=jnp.where(m2, vlen, slab.pvlen),
        npreds=slab.npreds + jnp.where(_oh(e, E) & do, 1, 0),
        pred_drops=slab.pred_drops + jnp.where(enable & full, 1, 0),
    )


def _prune_pointer(slab: SlabState, e, j, enable):
    """Remove pointer ``j`` of entry ``e``, shifting later pointers left to
    keep insertion order (``TimedKeyValue.removePredecessor``)."""
    E, mp = slab.pstage.shape
    idx = jnp.arange(mp, dtype=jnp.int32)
    # Shift-by-one as a static roll + mask: slot i >= j takes slot i+1's
    # value (the last slot keeps its own — matching min(i+1, mp-1)).
    m2 = (_oh(e, E)[:, None] & (idx[None, :] >= j)) & enable

    def shift(field, m):
        nxt = jnp.concatenate([field[:, 1:], field[:, -1:]], axis=1)
        return jnp.where(m, nxt, field)

    return slab._replace(
        pstage=shift(slab.pstage, m2),
        poff=shift(slab.poff, m2),
        pvlen=shift(slab.pvlen, m2),
        pver=shift(slab.pver, m2[:, :, None]),
        npreds=slab.npreds - jnp.where(_oh(e, E) & enable, 1, 0),
    )


def put_first(
    slab: SlabState, stage, off, ver, vlen, enable=True, hot_entries: int = 0
) -> SlabState:
    """First-stage put: fresh entry whose single null-predecessor pointer
    records the run version; overwrites any existing entry
    (``KVSharedVersionedBuffer.java:117-128``)."""
    enable = jnp.asarray(enable)
    existing, found = find(slab, stage, off)
    slab, free, has_free = _alloc_slot(slab, hot_entries, enable & ~found)
    e = jnp.where(found, existing, free)
    ok = enable & (found | has_free)
    m1 = _oh(e, slab.stage.shape[0]) & ok

    slab = slab._replace(
        stage=jnp.where(m1, stage, slab.stage),
        off=jnp.where(m1, off, slab.off),
        refs=jnp.where(m1, 1, slab.refs),
        npreds=jnp.where(m1, 0, slab.npreds),
        full_drops=slab.full_drops + jnp.where(enable & ~found & ~has_free, 1, 0),
    )
    return _append_pointer(slab, e, jnp.int32(-1), jnp.int32(-1), ver, vlen, ok)


def put(slab: SlabState, cur_stage, cur_off, prev_stage, prev_off, ver, vlen, enable=True, hot_entries: int = 0) -> SlabState:
    """Append a versioned predecessor pointer to ``(cur_stage, cur_off)``.

    The predecessor entry must exist (``KVSharedVersionedBuffer.java:86-89``);
    a miss is counted and the write dropped.
    """
    enable = jnp.asarray(enable)
    _, prev_found = find(slab, prev_stage, prev_off)
    slab = slab._replace(missing=slab.missing + jnp.where(enable & ~prev_found, 1, 0))
    enable = enable & prev_found

    existing, found = find(slab, cur_stage, cur_off)
    slab, free, has_free = _alloc_slot(slab, hot_entries, enable & ~found)
    e = jnp.where(found, existing, free)
    create = enable & ~found & has_free
    ok = enable & (found | has_free)
    m1 = _oh(e, slab.stage.shape[0]) & create

    slab = slab._replace(
        stage=jnp.where(m1, cur_stage, slab.stage),
        off=jnp.where(m1, cur_off, slab.off),
        refs=jnp.where(m1, 1, slab.refs),
        npreds=jnp.where(m1, 0, slab.npreds),
        full_drops=slab.full_drops + jnp.where(enable & ~found & ~has_free, 1, 0),
    )
    return _append_pointer(slab, e, prev_stage, prev_off, ver, vlen, ok)


def branch(slab: SlabState, stage, off, ver, vlen, max_walk: int, enable=True, hot_entries: int = 0) -> SlabState:
    """Refcount-increment walk so shared prefixes survive sibling removal
    (``KVSharedVersionedBuffer.java:99-110``)."""

    def body(_, carry):
        slab, stage, off, qver, qlen, active = carry
        e, found = find(slab, stage, off)
        if hot_entries:
            slab = _tier_counts(
                slab, active, found & (e < hot_entries), found
            )
        slab = _hop_counts(slab, active, stage=stage)
        slab = slab._replace(missing=slab.missing + jnp.where(active & ~found, 1, 0))
        active = active & found
        slab = slab._replace(
            refs=slab.refs + jnp.where(_oh(e, slab.refs.shape[0]) & active, 1, 0)
        )
        j, sel = _select_pointer(slab, e, qver, qlen)
        nxt_stage = _get_ej(slab.pstage, e, j)
        active = active & sel & (nxt_stage >= 0)
        stage = jnp.where(active, nxt_stage, stage)
        off = jnp.where(active, _get_ej(slab.poff, e, j), off)
        qver = jnp.where(active, _get_ej(slab.pver, e, j), qver)
        qlen = jnp.where(active, _get_ej(slab.pvlen, e, j), qlen)
        return slab, stage, off, qver, qlen, active

    init = (
        slab,
        jnp.asarray(stage, jnp.int32),
        jnp.asarray(off, jnp.int32),
        jnp.asarray(ver, jnp.int32),
        jnp.asarray(vlen, jnp.int32),
        jnp.asarray(enable),
    )
    out = jax.lax.fori_loop(0, max_walk, body, init)
    slab, still_active = out[0], out[5]
    # A walk still active after max_walk hops was truncated: refcounts along
    # the untraversed tail were not incremented (no reference analog).
    return slab._replace(trunc=slab.trunc + jnp.where(still_active, 1, 0))


def peek(
    slab: SlabState,
    stage,
    off,
    ver,
    vlen,
    max_walk: int,
    remove: bool,
    enable=True,
    hot_entries: int = 0,
    hop_kind: str = "extract",
):
    """Backward pointer walk assembling a match, final stage first.

    Returns ``(slab, out_stage[max_walk], out_off[max_walk], count)``; hops
    beyond the walk bound are dropped (no reference analog — counted via the
    returned ``count`` saturating at ``max_walk``).  With ``remove`` this is
    ``SharedVersionedBuffer.remove`` (refcount GC + pointer pruning);
    without, ``get`` — which still decrements refcounts, a preserved quirk of
    ``KVSharedVersionedBuffer.peek`` (``:156``).
    """
    L = max_walk
    out_stage = jnp.full((L,), -1, dtype=jnp.int32)
    out_off = jnp.full((L,), -1, dtype=jnp.int32)

    def body(i, carry):
        slab, stage, off, qver, qlen, active, out_stage, out_off, count = carry
        E = slab.stage.shape[0]
        e, found = find(slab, stage, off)
        if hot_entries:
            slab = _tier_counts(
                slab, active, found & (e < hot_entries), found
            )
        slab = _hop_counts(slab, active, kind=hop_kind, stage=stage)
        slab = slab._replace(missing=slab.missing + jnp.where(active & ~found, 1, 0))
        active = active & found
        m1 = _oh(e, E) & active

        refs_left = jnp.maximum(_get_e(slab.refs, e) - 1, 0)  # floors at zero
        slab = slab._replace(refs=jnp.where(m1, refs_left, slab.refs))
        delete = (
            active & remove & (refs_left == 0) & (_get_e(slab.npreds, e) <= 1)
        )
        md = _oh(e, E) & delete
        slab = slab._replace(
            stage=jnp.where(md, -1, slab.stage),
            off=jnp.where(md, -1, slab.off),
        )

        mi = _oh(i, out_stage.shape[0]) & active
        out_stage = jnp.where(mi, stage, out_stage)
        out_off = jnp.where(mi, off, out_off)
        count = count + jnp.where(active, 1, 0)

        j, sel = _select_pointer(slab, e, qver, qlen)
        sel = sel & active
        prune = sel & remove & (refs_left == 0)
        nxt_stage = _get_ej(slab.pstage, e, j)
        nxt_off = _get_ej(slab.poff, e, j)
        nxt_ver = _get_ej(slab.pver, e, j)
        nxt_len = _get_ej(slab.pvlen, e, j)
        slab = _prune_pointer(slab, e, j, prune)

        active = sel & (nxt_stage >= 0)
        stage = jnp.where(active, nxt_stage, stage)
        off = jnp.where(active, nxt_off, off)
        qver = jnp.where(active, nxt_ver, qver)
        qlen = jnp.where(active, nxt_len, qlen)
        return slab, stage, off, qver, qlen, active, out_stage, out_off, count

    init = (
        slab,
        jnp.asarray(stage, jnp.int32),
        jnp.asarray(off, jnp.int32),
        jnp.asarray(ver, jnp.int32),
        jnp.asarray(vlen, jnp.int32),
        jnp.asarray(enable),
        out_stage,
        out_off,
        jnp.zeros((), dtype=jnp.int32),
    )
    slab, _, _, _, _, still_active, out_stage, out_off, count = jax.lax.fori_loop(
        0, L, body, init
    )
    # Truncated extraction: the untraversed tail keeps its refcounts (a leak
    # the caller can see via this counter) and the returned hops are partial.
    slab = slab._replace(trunc=slab.trunc + jnp.where(still_active, 1, 0))
    return slab, out_stage, out_off, count


def live_entries(slab: SlabState) -> jnp.ndarray:
    """Number of occupied slots (host/diagnostic helper)."""
    return jnp.sum(slab.stage >= 0)


def mark_sweep(slab: SlabState, run_stage, run_off, depth: int) -> SlabState:
    """Free every entry unreachable from live run state — the deferred
    compaction scan of SURVEY §7 step 4.

    The reference never needs this: its refcount GC
    (``KVSharedVersionedBuffer.java:147-171``) runs over unbounded walks.
    This engine's walks are bounded by ``max_walk``, so a truncated removal
    walk strands its untraversed tail with elevated refcounts (counted in
    ``trunc``) and the slab fills over long streams.  The sweep is
    *observably equivalent* to the reference's state: every future buffer
    operation starts from live run state — consuming puts reference a run's
    pointer event, branch/removal/extraction walks start at a run's pointer
    event or the current event — and walks take at most ``max_walk`` hops,
    so an entry not reachable within ``depth >= max_walk`` pointer hops of
    any live run can never be read or written again.  Freeing it changes no
    future output and no counter.

    ``run_off`` is the ``[N]`` array of the live runs' pointer-event
    offsets (``off < 0`` rows ignored); ``run_stage`` is accepted for
    signature symmetry but roots are keyed by offset alone — buffer
    operations may start at any *stage* carrying a run's pointer offset
    (e.g. a branch walk starts at the branching frame's predecessor stage,
    a chained put references the same offset under the put frame's stage).
    Marking follows ALL pointers (not version-filtered) — conservative
    over every possible future walk version.  Vmappable over a leading
    lane axis.
    """
    del run_stage  # roots are offset-keyed; see docstring
    E, MP = slab.pstage.shape
    run_off = jnp.asarray(run_off, jnp.int32)

    # Roots: every entry at any live run's pointer-event offset.
    root_hit = (slab.off[:, None] == run_off[None, :]) & (
        run_off[None, :] >= 0
    )  # [E, N]
    marked = jnp.any(root_hit, axis=1) & (slab.stage >= 0)

    # Adjacency: entry e reaches e' when any live pointer of e keys
    # (stage, off)[e'].  Reduced over MP up front — marking ignores which
    # pointer hit, and [E, E] is MP-times smaller than the [E, MP, E]
    # grid a naive formulation would hold live across the loop.
    valid_ptr = (
        jnp.arange(MP, dtype=jnp.int32)[None, :] < slab.npreds[:, None]
    ) & (slab.pstage >= 0)  # [E, MP]
    adj = jnp.any(
        (slab.pstage[:, :, None] == slab.stage[None, None, :])
        & (slab.poff[:, :, None] == slab.off[None, None, :])
        & valid_ptr[:, :, None],
        axis=1,
    )  # [E, E']

    def body(_, m):
        reach = jnp.any(adj & m[:, None], axis=0)  # [E']
        return m | (reach & (slab.stage >= 0))

    marked = jax.lax.fori_loop(0, depth, body, marked)

    free = ~marked
    return slab._replace(
        stage=jnp.where(free, -1, slab.stage),
        off=jnp.where(free, -1, slab.off),
        refs=jnp.where(free, 0, slab.refs),
        npreds=jnp.where(free, 0, slab.npreds),
    )


def walks_batched(
    slab: SlabState,
    en,
    stage,
    off,
    ver,
    vlen,
    is_remove,
    want_out,
    max_walk: int,
    collect: bool = True,
    hot_entries: int = 0,
    drain: bool = False,
):
    """ALL of one step's buffer walks — branch refcount walks, dead-run
    removals, and final-match extractions — in a single lockstep pass.

    ``is_remove[p]`` selects decrement/prune/delete semantics (dead/final
    walkers) vs. increment semantics (branch walkers); ``want_out[p]``
    walkers additionally emit their hops.  Merging the three phases is
    sound by the same refcount invariant as :func:`peek_batched`:
    per-entry refcount deltas commute (summed per hop), and only the last
    remaining traverser of a node can observe ``refs == 0``, so
    delete/prune attribution to the last same-hop remove-walker
    reproduces the sequential outcome regardless of phase interleaving.
    The engine-level A/B test (``sequential_slab``) and the oracle fuzz
    suite validate the merged order end to end.

    Returns ``(slab, out_stage [P, W], out_off [P, W], count [P])`` —
    rows meaningful only where ``want_out``.
    """
    E, MP = slab.pstage.shape
    D = slab.pver.shape[-1]
    P = jnp.asarray(stage).shape[0]
    W = max_walk
    i32 = jnp.int32
    f32 = jnp.float32
    mp_idx = jnp.arange(MP, dtype=i32)
    pidx = jnp.arange(P, dtype=i32)
    later = pidx[None, :] > pidx[:, None]

    # Safety of merging increments with removals: the only way a removal
    # could collect a node an in-flight branch walk still needs is a
    # refs==1 path shared by a branch walker and a dead walker of the SAME
    # run (cross-run shared nodes always carry one ref per lineage, i.e.
    # >= 2).  That cannot happen: a run that branches has a successor by
    # definition (matcher.py: has_succ = survivor | any branch), so it is
    # never dead in the same step.
    is_remove = jnp.asarray(is_remove)
    want_out = jnp.asarray(want_out)
    ptrs = _pack_ptrs(slab)  # read-only: prunes are tombstoned, not shifted
    valid0 = mp_idx[None, :] < slab.npreds[:, None]  # [E, MP] at phase start

    def cond(carry):
        active = carry[6]
        hops = carry[11]
        return jnp.any(active) & (hops < W)

    def body(carry):
        (slab, dead, stage, off, qver, qlen, active, out_stage, out_off,
         count, trunc, hops) = carry
        hit = (slab.stage[None, :] == stage[:, None]) & (
            slab.off[None, :] == off[:, None]
        )
        found = jnp.any(hit, axis=1)
        if hot_entries:
            slab = _tier_counts(
                slab, active, jnp.any(hit[:, :hot_entries], axis=1), found
            )
        slab = _hop_counts(
            slab, active, want_out, kind="drain" if drain else "extract",
            stage=stage,
        )
        slab = slab._replace(
            missing=slab.missing + jnp.sum((active & ~found).astype(i32))
        )
        active = active & found
        ham = hit & active[:, None]  # [P, E]

        m1 = jnp.any(ham, axis=0)
        inc = jnp.sum((ham & ~is_remove[:, None]).astype(i32), axis=0)
        dec = jnp.sum((ham & is_remove[:, None]).astype(i32), axis=0)
        refs_after_e = jnp.maximum(slab.refs + inc - dec, 0)
        refs_after = jnp.sum(jnp.where(ham, refs_after_e[None, :], 0), axis=1)
        slab = slab._replace(refs=jnp.where(m1, refs_after_e, slab.refs))

        # Queue-last remove-walker at each entry — the only one that may
        # collect (prune/delete) when refs reaches zero.
        arm = active & is_remove
        e = jnp.argmax(hit, axis=1)
        last = arm & ~jnp.any(
            (e[None, :] == e[:, None]) & later & arm[None, :], axis=1
        )
        # Two remove-walkers at one entry in one hop is the exact condition
        # under which last-walker attribution can deviate from sequential
        # order — count every extra walker so the deviation is observable
        # (EngineConfig.walker_budget; 0 by construction at budget=1).
        n_rm = jnp.sum((ham & is_remove[:, None]).astype(i32), axis=0)
        slab = slab._replace(
            collisions=slab.collisions + jnp.sum(jnp.maximum(n_rm - 1, 0))
        )

        # Row extraction stays a one-hot matmul over the full packed slab:
        # a batched gather (``jnp.take(ptrs, e, axis=0)``) was measured 4x
        # SLOWER end-to-end (41s vs 9.5s headline scan) — TPU dynamic
        # gathers in a while-loop body neither fuse nor vectorize.  The
        # einsum's full-slab re-read per hop is the remaining HBM cost the
        # Pallas walk kernel eliminates (state resident in VMEM).
        rows = _rows(ptrs, ham)
        pv, ps, po, pl = (
            rows[..., :D],
            rows[..., D],
            rows[..., D + 1],
            rows[..., D + 2],
        )
        live = valid0 & ~dead  # [E, MP]
        live_p = jnp.einsum(
            "pe,em->pm", ham.astype(f32), live.astype(f32),
            preferred_element_type=f32,
        ) > 0.5
        np_live = jnp.sum(live_p.astype(i32), axis=1)
        delete = last & collect & (refs_after == 0) & (np_live <= 1)
        md = jnp.any(hit & delete[:, None], axis=0)
        slab = slab._replace(
            stage=jnp.where(md, -1, slab.stage),
            off=jnp.where(md, -1, slab.off),
        )

        # Emit the hop for extraction walkers.
        emit = active & want_out
        mw = (jnp.arange(W, dtype=i32)[None, :] == count[:, None]) & emit[:, None]
        out_stage = jnp.where(mw, stage[:, None], out_stage)
        out_off = jnp.where(mw, off[:, None], out_off)
        count = count + jnp.where(emit, 1, 0)

        ok = _compat_rows(qver, qlen, pv, pl) & live_p
        j = jnp.argmax(ok, axis=1)
        sel = jnp.any(ok, axis=1) & active
        prune = sel & last & collect & (refs_after == 0)

        ohj = mp_idx[None, :] == j[:, None]
        tomb = jnp.einsum(
            "pe,pm->em", (hit & prune[:, None]).astype(f32), ohj.astype(f32),
            preferred_element_type=f32,
        ) > 0.5
        dead = dead | tomb
        slab = slab._replace(
            npreds=slab.npreds - jnp.sum(tomb.astype(i32), axis=1)
        )

        # Selected pointer row, all channels in one masked reduction.
        sel_row = jnp.sum(jnp.where(ohj[:, :, None], rows, 0), axis=1)  # [P, C]
        ns = sel_row[:, D]
        nactive = sel & (ns >= 0)
        stage = jnp.where(nactive, ns.astype(i32), stage)
        off = jnp.where(nactive, sel_row[:, D + 1].astype(i32), off)
        qver = jnp.where(nactive[:, None], sel_row[:, :D], qver)
        qlen = jnp.where(nactive, sel_row[:, D + 2].astype(i32), qlen)

        # Extraction walkers get W emitting hops; others walk at most W
        # hops total (the while bound) — both truncations are counted.
        budget_out = emit & (count >= W)
        trunc = trunc + jnp.sum((budget_out & nactive).astype(i32))
        active = nactive & ~budget_out
        return (slab, dead, stage, off, qver, qlen, active, out_stage,
                out_off, count, trunc, hops + 1)

    init = (
        slab,
        jnp.zeros((E, MP), bool),
        jnp.asarray(stage, i32),
        jnp.asarray(off, i32),
        jnp.asarray(ver, f32),
        jnp.asarray(vlen, i32),
        jnp.asarray(en),
        jnp.full((P, W), -1, i32),
        jnp.full((P, W), -1, i32),
        jnp.zeros((P,), i32),
        jnp.zeros((), i32),
        jnp.zeros((), i32),
    )
    (slab, dead, _, _, _, _, active, out_stage, out_off, count, trunc, _) = (
        jax.lax.while_loop(cond, body, init)
    )

    # Apply tombstones: stable-compact surviving pointers to the front.
    any_dead = jnp.any(dead, axis=1)
    live = valid0 & ~dead
    tgt = jnp.cumsum(live.astype(i32), axis=1) - 1
    perm = live[:, :, None] & (mp_idx[None, None, :] == tgt[:, :, None])

    def comp2(field):
        v = jnp.sum(jnp.where(perm, field[:, :, None], 0), axis=1)
        return jnp.where(any_dead[:, None], v.astype(field.dtype), field)

    def comp3(field):
        v = jnp.sum(jnp.where(perm[..., None], field[:, :, None, :], 0), axis=1)
        return jnp.where(any_dead[:, None, None], v.astype(field.dtype), field)

    slab = slab._replace(
        pstage=comp2(slab.pstage),
        poff=comp2(slab.poff),
        pvlen=comp2(slab.pvlen),
        pver=comp3(slab.pver),
        trunc=slab.trunc + trunc + jnp.sum(active.astype(i32)),
    )
    return slab, out_stage, out_off, count


# ---------------------------------------------------------------------------
# Batched per-step kernels
#
# The sequential entry points above apply ONE op per call; chained under the
# engine's per-run loop that costs a full pass over the pointer arrays per op
# (HBM-bound) or a serial kernel chain (launch-bound).  The batched kernels
# apply ALL of one event-step's ops in a constant number of wide passes:
#
# * ``puts_batched``   — the step's consuming puts, in queue/frame order,
#   grouped by target entry (every consuming put of one step targets the
#   *current* event, so groups are keyed by stage);
# * ``branch_batched`` — all branch refcount walks in lockstep.  Increments
#   commute and pointer selection never reads refcounts, so lockstep is
#   *exactly* sequential order;
# * ``peek_batched``   — all removal walks in lockstep with a same-entry
#   stall protocol: when two walkers meet at one entry in the same hop, the
#   later (higher run-slot) walker waits, so per-entry mutation order equals
#   the reference's queue order.  Walks are backward over strictly older
#   events, so no walker revisits an entry and stalls always clear.
#
# Walk-phase row extraction runs as one f32 matmul per hop on the packed
# pointer tensor (ver ∘ pstage ∘ poff ∘ pvlen) — MXU work; all packed values
# are small ints (< 2^24), exact in f32.
# ---------------------------------------------------------------------------


class PutOps(NamedTuple):
    """One step's consuming puts, flattened in reference order (queue order,
    then frame order within a run)."""

    en: jnp.ndarray  # [P] bool
    first: jnp.ndarray  # [P] bool — put_first (null-predecessor origin)
    cur_stage: jnp.ndarray  # [P] int32 — target stage (identity position)
    prev_stage: jnp.ndarray  # [P] int32 — -1 for first puts
    prev_off: jnp.ndarray  # [P] int32
    ver: jnp.ndarray  # [P, D] int32
    vlen: jnp.ndarray  # [P] int32


def puts_batched(
    slab: SlabState, ops: PutOps, off, hot_entries: int = 0
) -> SlabState:
    """Apply all of one step's consuming puts in one pass.

    Replicates the sequential semantics op by op: chained puts require an
    existing predecessor (else counted ``missing``); the *last* ``put_first``
    of a target group resets the entry and erases the group's earlier
    appends (``KVSharedVersionedBuffer.java:117-128`` overwrite quirk);
    surviving appends take consecutive pointer slots in op order.  All
    targets share the current event offset ``off``, so groups are keyed by
    ``cur_stage`` alone; predecessors always reference older events, so no
    op's predecessor lookup can observe another op of the same step.

    Two-tier slabs (``hot_entries > 0``) take the ranked sequential loop
    instead: the closed-form creator-to-free-slot ranking above assumes any
    free slot is usable, while two-tier allocation interleaves demotions
    between creations.  The jnp two-tier path exists for differential
    parity, not throughput (the Pallas kernels are the perf path), so the
    loop's extra passes are acceptable.
    """
    if hot_entries:
        return _puts_sequential(slab, ops, off, hot_entries)
    i32 = jnp.int32
    E, MP = slab.pstage.shape
    P = ops.en.shape[0]
    pidx = jnp.arange(P, dtype=i32)
    earlier = pidx[None, :] < pidx[:, None]  # [p, p']: p' before p
    later = pidx[None, :] > pidx[:, None]

    # Chained puts need an existing predecessor entry.
    prev_hit = (slab.stage[None, :] == ops.prev_stage[:, None]) & (
        slab.off[None, :] == ops.prev_off[:, None]
    )
    prev_found = jnp.any(prev_hit, axis=1)
    miss = ops.en & ~ops.first & ~prev_found
    en = ops.en & (ops.first | prev_found)

    # Target grouping by stage (same group == same target entry).
    same = ops.cur_stage[None, :] == ops.cur_stage[:, None]  # [P, P]
    cur_hit = (slab.stage[None, :] == ops.cur_stage[:, None]) & (
        slab.off[None, :] == off
    )
    exist0 = jnp.any(cur_hit, axis=1)
    e0 = jnp.argmax(cur_hit, axis=1)

    # Entry allocation: the first enabled op of a group whose entry does not
    # exist claims the next free slot (creators ranked in op order).
    first_of_group = en & ~jnp.any(same & earlier & en[None, :], axis=1)
    creator = first_of_group & ~exist0
    crank = jnp.cumsum(creator.astype(i32)) - 1
    free = slab.stage < 0
    nfree = jnp.sum(free.astype(i32))
    free_rank = jnp.cumsum(free.astype(i32)) - 1  # [E]
    alloc_hit = (
        free[None, :] & (free_rank[None, :] == crank[:, None]) & creator[:, None]
    )
    has_free = creator & (crank < nfree)
    grp_creator = same & creator[None, :]  # [P, P]
    alloc_e_all = jnp.argmax(alloc_hit, axis=1)
    e_created = jnp.sum(jnp.where(grp_creator, alloc_e_all[None, :], 0), axis=1)
    grp_has_free = jnp.any(grp_creator & has_free[None, :], axis=1)
    e = jnp.where(exist0, e0, e_created).astype(i32)
    entry_ok = en & (exist0 | grp_has_free)
    # Sequential parity: every op that finds neither an existing entry nor a
    # free slot counts one full drop.
    full = en & ~exist0 & ~grp_has_free

    # put_first reset: a first-put that lands (entry_ok) resets its entry's
    # pointer list; the group's ops therefore run in *segments* delimited by
    # resets.  Every segment's appends really happened sequentially (and can
    # drop on overflow — counted), but only the final segment's writes
    # survive the last reset.
    isfirst_ok = entry_ok & ops.first
    reset_at_or_before = same & ~later & isfirst_ok[None, :]
    has_reset = jnp.any(reset_at_or_before, axis=1)
    seg_head = jnp.max(jnp.where(reset_at_or_before, pidx[None, :], -1), axis=1)
    seg_eq = same & (seg_head[None, :] == seg_head[:, None])

    npreds0_e = jnp.sum(jnp.where(cur_hit, slab.npreds[None, :], 0), axis=1)
    base0 = jnp.where(exist0, npreds0_e, 0)
    base = jnp.where(has_reset, 0, base0)

    # npreds as each op saw it: base of its segment plus earlier successful
    # appends in the segment (appends saturate at MP — a dropped append
    # leaves npreds unchanged for its successors).
    prior = jnp.sum((seg_eq & earlier & entry_ok[None, :]).astype(i32), axis=1)
    slot = jnp.minimum(base + prior, MP)
    pred_drop = entry_ok & (slot >= MP)

    # Only final-segment ops persist (no reset after them in the group).
    last_seg = ~jnp.any(same & later & isfirst_ok[None, :], axis=1)
    surv = entry_ok & last_seg
    fit = surv & (slot < MP)
    grp_has_first = jnp.any(same & isfirst_ok[None, :], axis=1)
    base_n = jnp.where(grp_has_first | ~exist0, 0, npreds0_e)

    entry_oh = (jnp.arange(E, dtype=i32)[None, :] == e[:, None]) & fit[:, None]
    slot_oh = jnp.arange(MP, dtype=i32)[None, :] == slot[:, None]
    m3 = entry_oh[:, :, None] & slot_oh[:, None, :]  # [P, E, MP]
    hit3 = jnp.any(m3, axis=0)

    pstage_val = jnp.where(ops.first, -1, ops.prev_stage)
    poff_val = jnp.where(ops.first, -1, ops.prev_off)

    def write(field, val):
        upd = jnp.sum(jnp.where(m3, val[:, None, None], 0), axis=0)
        return jnp.where(hit3, upd.astype(field.dtype), field)

    new_pstage = write(slab.pstage, pstage_val)
    new_poff = write(slab.poff, poff_val)
    new_pvlen = write(slab.pvlen, ops.vlen)
    upd_v = jnp.sum(
        jnp.where(m3[..., None], ops.ver[:, None, None, :], 0), axis=0
    )
    new_pver = jnp.where(hit3[..., None], upd_v.astype(slab.pver.dtype), slab.pver)

    # Entry metadata, group-consistent (cnt is the group's fit count).
    cnt = jnp.sum((same & fit[None, :]).astype(i32), axis=1)
    npreds_val = jnp.minimum(base_n + cnt, MP)
    reset_refs = grp_has_first | ~exist0
    ge = (jnp.arange(E, dtype=i32)[None, :] == e[:, None]) & entry_ok[:, None]
    anyop = jnp.any(ge, axis=0)
    npreds_e = jnp.max(jnp.where(ge, npreds_val[:, None], 0), axis=0)
    setref_e = jnp.any(ge & reset_refs[:, None], axis=0)
    stage_e = jnp.max(jnp.where(ge, ops.cur_stage[:, None], -1), axis=0)

    return slab._replace(
        stage=jnp.where(anyop, stage_e.astype(i32), slab.stage),
        off=jnp.where(anyop, off, slab.off),
        refs=jnp.where(anyop & setref_e, 1, slab.refs),
        npreds=jnp.where(anyop, npreds_e.astype(i32), slab.npreds),
        pstage=new_pstage,
        poff=new_poff,
        pvlen=new_pvlen,
        pver=new_pver,
        missing=slab.missing + jnp.sum(miss.astype(i32)),
        full_drops=slab.full_drops + jnp.sum(full.astype(i32)),
        pred_drops=slab.pred_drops + jnp.sum(pred_drop.astype(i32)),
    )


def _puts_sequential(
    slab: SlabState, ops: PutOps, off, hot_entries: int
) -> SlabState:
    """One step's consuming puts applied one op at a time in queue order —
    the two-tier variant of :func:`puts_batched` (see its docstring)."""
    from kafkastreams_cep_tpu.ops.onehot import get_at

    P = int(ops.en.shape[0])

    def body(p, slab):
        en = get_at(ops.en, p)
        first = get_at(ops.first, p)
        cur = get_at(ops.cur_stage, p)
        slab = put_first(
            slab, cur, off, get_at(ops.ver, p), get_at(ops.vlen, p),
            enable=en & first, hot_entries=hot_entries,
        )
        return put(
            slab, cur, off, get_at(ops.prev_stage, p),
            get_at(ops.prev_off, p), get_at(ops.ver, p), get_at(ops.vlen, p),
            enable=en & ~first, hot_entries=hot_entries,
        )

    return jax.lax.fori_loop(0, P, body, slab)


def _pack_ptrs(slab: SlabState) -> jnp.ndarray:
    """Pointer arrays packed as one f32 tensor ``[E, MP, D+3]`` so walk-hop
    row extraction is a single MXU matmul.  Layout: ver, pstage, poff, pvlen.
    All values are small ints — exact in f32 (offsets are bounded by the
    engine's documented 2^24-events-per-lane limit)."""
    return jnp.concatenate(
        [
            slab.pver.astype(jnp.float32),
            slab.pstage[..., None].astype(jnp.float32),
            slab.poff[..., None].astype(jnp.float32),
            slab.pvlen[..., None].astype(jnp.float32),
        ],
        axis=-1,
    )


def _rows(ptrs: jnp.ndarray, hit: jnp.ndarray):
    """Extract each walker's entry row from the packed pointer tensor:
    ``[P, E] one-hot x [E, MP*(D+3)] -> [P, MP, D+3]`` — one f32 matmul."""
    E, MP, C = ptrs.shape
    rows = jnp.einsum(
        "pe,ec->pc",
        hit.astype(jnp.float32),
        ptrs.reshape(E, MP * C),
        preferred_element_type=jnp.float32,
    )
    return rows.reshape(-1, MP, C)


def _compat_rows(qver, qlen, pv, pl):
    """``dewey_ops.is_compatible`` vectorized over walkers x pointers:
    ``qver [P, D]`` (f32), ``qlen [P]``, ``pv [P, MP, D]`` (f32),
    ``pl [P, MP]``.  One source of truth for the compatibility rule — the
    masked elementwise math works identically on f32-encoded components."""
    per_walker = jax.vmap(dewey_ops.is_compatible, in_axes=(None, None, 0, 0))
    return jax.vmap(per_walker)(qver, qlen, pv, pl)


def branch_batched(
    slab: SlabState, en, stage, off, ver, vlen, max_walk: int,
    hot_entries: int = 0,
) -> SlabState:
    """All branch refcount walks of one step, in lockstep
    (``KVSharedVersionedBuffer.java:99-110``).

    Per-hop refcount increments are summed across walkers — increments
    commute and pointer selection never reads refcounts, so the result is
    identical to any sequential interleaving.  The hop loop is a
    ``while_loop`` that exits as soon as no walker is active — the common
    case (no branching this event) costs one condition check.
    """
    E, MP = slab.pstage.shape
    D = slab.pver.shape[-1]
    i32 = jnp.int32
    mp_idx = jnp.arange(MP, dtype=i32)
    ptrs = _pack_ptrs(slab)  # read-only in this phase

    def cond(carry):
        slab, stage, off, qver, qlen, active, hops = carry
        return jnp.any(active) & (hops < max_walk)

    def body(carry):
        slab, stage, off, qver, qlen, active, hops = carry
        hit = (slab.stage[None, :] == stage[:, None]) & (
            slab.off[None, :] == off[:, None]
        )
        found = jnp.any(hit, axis=1)
        if hot_entries:
            slab = _tier_counts(
                slab, active, jnp.any(hit[:, :hot_entries], axis=1), found
            )
        slab = _hop_counts(slab, active, stage=stage)
        slab = slab._replace(
            missing=slab.missing + jnp.sum((active & ~found).astype(i32))
        )
        active = active & found
        inc = jnp.sum((hit & active[:, None]).astype(i32), axis=0)
        slab = slab._replace(refs=slab.refs + inc)

        rows = _rows(ptrs, hit & active[:, None])  # [P, MP, D+3]
        pv, ps, po, pl = (
            rows[..., :D],
            rows[..., D],
            rows[..., D + 1],
            rows[..., D + 2],
        )
        np_ = jnp.sum(jnp.where(hit, slab.npreds[None, :], 0), axis=1)
        ok = _compat_rows(qver, qlen, pv, pl) & (mp_idx[None, :] < np_[:, None])
        j = jnp.argmax(ok, axis=1)
        sel = jnp.any(ok, axis=1)
        ohj = mp_idx[None, :] == j[:, None]
        ns = jnp.sum(jnp.where(ohj, ps, 0), axis=1)
        active = active & sel & (ns >= 0)
        stage = jnp.where(active, ns.astype(i32), stage)
        off = jnp.where(
            active, jnp.sum(jnp.where(ohj, po, 0), axis=1).astype(i32), off
        )
        qver = jnp.where(
            active[:, None], jnp.sum(jnp.where(ohj[..., None], pv, 0), axis=1), qver
        )
        qlen = jnp.where(
            active, jnp.sum(jnp.where(ohj, pl, 0), axis=1).astype(i32), qlen
        )
        return (slab, stage, off, qver, qlen, active, hops + 1)

    init = (
        slab,
        jnp.asarray(stage, i32),
        jnp.asarray(off, i32),
        jnp.asarray(ver, jnp.float32),
        jnp.asarray(vlen, i32),
        jnp.asarray(en),
        jnp.zeros((), i32),
    )
    slab, _, _, _, _, active, _ = jax.lax.while_loop(cond, body, init)
    return slab._replace(
        trunc=slab.trunc + jnp.sum(active.astype(i32))
    )


def walks_compacted(
    slab: SlabState,
    en,
    stage,
    off,
    ver,
    vlen,
    is_remove,
    want_out,
    max_walk: int,
    budget: int,
    out_base: int,
    out_rows: int,
    hot_entries: int = 0,
    drain: bool = False,
):
    """The step's walk pass over a *small* compacted walker pool.

    The engine presents P candidate walkers per step (every branch frame,
    every dead run, every potential final extraction) but typically only a
    handful are enabled.  Carrying all P slots through every walk hop made
    the walk pass ~90% of the headline step (PROFILE_r04.md): per-hop HBM
    traffic is proportional to the pool width.  This wrapper compacts the
    *enabled* walkers, in queue-order rank, into ``budget`` slots and runs
    :func:`walks_batched` over batches of that width until all are served.

    Ordering: batches are processed in ascending rank order; each batch's
    deletes/prunes and pointer compaction complete before the next batch
    starts.  With ``budget=1`` (the engine default) every walker runs alone
    — exactly the reference's sequential per-walker order.  With wider
    budgets, walkers *within* a batch run under :func:`walks_batched`'s
    lockstep protocol, which deviates from sequential when two removal
    walkers meet at one entry in the same hop (prune/delete attribution
    goes to the queue-last walker only; a refs==0 entry can survive with a
    stale pointer) — see ``EngineConfig.walker_budget``.

    Only rows ``[out_base, out_base + out_rows)`` of the candidate list can
    request output (the engine's final-extraction segment); their hops are
    scattered back to ``out_rows``-indexed rows so the engine never
    materializes a [P, W] output.

    Returns ``(slab, out_stage [out_rows, W], out_off [out_rows, W],
    count [out_rows])``.
    """
    i32 = jnp.int32
    W = max_walk
    P = jnp.asarray(stage).shape[0]
    B = budget
    en = jnp.asarray(en)
    stage = jnp.asarray(stage, i32)
    off = jnp.asarray(off, i32)
    ver = jnp.asarray(ver, i32)
    vlen = jnp.asarray(vlen, i32)
    is_remove = jnp.asarray(is_remove)
    want_out = jnp.asarray(want_out)

    rank = jnp.cumsum(en.astype(i32)) - 1  # queue-order rank of enabled
    n = jnp.sum(en.astype(i32))
    bidx = jnp.arange(B, dtype=i32)

    def cond(carry):
        return carry[1] < n

    def body(carry):
        slab, start, out_stage, out_off, count = carry
        ohc = (en & (rank >= start) & (rank < start + B))[:, None] & (
            (rank - start)[:, None] == bidx[None, :]
        )  # [P, B] — at most one True per row and per column

        def gather(field, fill=0):
            m = ohc.reshape((P, B) + (1,) * (field.ndim - 1))
            v = jnp.sum(jnp.where(m, field[:, None], 0), axis=0)
            if field.dtype == jnp.bool_:
                return jnp.any(m & field.reshape((P, 1) + field.shape[1:]), axis=0)
            got = jnp.any(ohc, axis=0).reshape((B,) + (1,) * (field.ndim - 1))
            return jnp.where(got, v.astype(field.dtype), fill)

        b_en = jnp.any(ohc, axis=0)
        slab, b_out_stage, b_out_off, b_count = walks_batched(
            slab,
            b_en,
            gather(stage),
            gather(off),
            gather(ver),
            gather(vlen),
            gather(is_remove),
            gather(want_out),
            W,
            hot_entries=hot_entries,
            drain=drain,
        )
        # Scatter served output walkers back to their final-segment rows.
        oho = ohc[out_base:out_base + out_rows]  # [out_rows, B]
        got = jnp.any(oho, axis=1)
        upd_st = jnp.sum(jnp.where(oho[:, :, None], b_out_stage[None], 0), axis=1)
        upd_of = jnp.sum(jnp.where(oho[:, :, None], b_out_off[None], 0), axis=1)
        upd_ct = jnp.sum(jnp.where(oho, b_count[None], 0), axis=1)
        out_stage = jnp.where(got[:, None], upd_st.astype(i32), out_stage)
        out_off = jnp.where(got[:, None], upd_of.astype(i32), out_off)
        count = jnp.where(got, upd_ct.astype(i32), count)
        return slab, start + B, out_stage, out_off, count

    init = (
        slab,
        jnp.zeros((), i32),
        jnp.full((out_rows, W), -1, i32),
        jnp.full((out_rows, W), -1, i32),
        jnp.zeros((out_rows,), i32),
    )
    slab, _, out_stage, out_off, count = jax.lax.while_loop(cond, body, init)
    return slab, out_stage, out_off, count


def peek_batched(
    slab: SlabState,
    en,
    stage,
    off,
    ver,
    vlen,
    max_walk: int,
    remove: bool,
    hot_entries: int = 0,
    drain: bool = False,
):
    """Lockstep removal walks — a thin wrapper over :func:`walks_batched`
    with every walker removing and emitting (``remove=False`` keeps the
    reference's get-still-decrements quirk but skips delete/prune).

    Returns ``(slab, out_stage [P, W], out_off [P, W], count [P])``.
    """
    P = jnp.asarray(stage).shape[0]
    ones = jnp.ones((P,), bool)
    return walks_batched(
        slab, en, stage, off, ver, vlen,
        is_remove=ones, want_out=ones, max_walk=max_walk, collect=remove,
        hot_entries=hot_entries, drain=drain,
    )


# Eager per-op dispatch is orders of magnitude slower than compiled code on
# this host; the public sequential entry points are jitted (the engine's
# sequential mode additionally inlines them under its own jit, where these
# wrappers are free).  The batched kernels are always called under the
# engine's jit and need no wrappers.
put_first = jax.jit(put_first, static_argnames=("hot_entries",))
put = jax.jit(put, static_argnames=("hot_entries",))
branch = jax.jit(branch, static_argnames=("max_walk", "hot_entries"))
peek = jax.jit(
    peek, static_argnames=("max_walk", "remove", "hot_entries", "hop_kind")
)
