"""Device-side array kernels (currently: fixed-width Dewey versions)."""
