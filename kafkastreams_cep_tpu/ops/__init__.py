"""Device-side array kernels: fixed-width Dewey versions (``dewey_ops``) and
the slab shared versioned buffer (``slab``)."""
