"""Fixed-width Dewey version kernels for the array engine.

The host class (`nfa/dewey.py`) stores a variable-length tuple; the device
representation is a fixed ``[D]`` int32 vector plus a scalar length, so every
operation is a masked, jit-compatible array op.  Semantics match the
reference's ``nfa/DeweyVersion.java``:

* ``add_run``   increments the last live component (``DeweyVersion.java:51-56``);
* ``add_stage`` appends a ``0`` component (``DeweyVersion.java:84-86``) —
  unlike the host, the device width is bounded, so ``add_stage`` additionally
  returns an ``overflow`` flag that is true when the version is already full
  (the component is then dropped; callers surface the flag as an engine
  counter).  Depth growth is unbounded in the reference: an inner-frame
  IGNORE re-add appends a stage digit without advancing the run
  (``NFA.java:186,223-227``), so any fixed width can overflow on adversarial
  traces;
* ``is_compatible(q, p)`` is true when ``p`` is a proper prefix of ``q``, or
  both have equal length with an equal prefix and ``last(q) >= last(p)``
  (``DeweyVersion.java:62-82``).

All functions take and return plain ``jnp`` values and vmap cleanly.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

Version = jnp.ndarray  # [D] int32
Length = jnp.ndarray  # scalar int32


def make(components, depth: int):
    """Host helper: a ``(version, length)`` pair from an int tuple.

    Returns numpy values (cheap on host; JAX converts at trace boundaries).
    """
    components = tuple(int(c) for c in components)
    if len(components) > depth:
        raise ValueError(f"version {components} deeper than D={depth}")
    vec = np.zeros((depth,), dtype=np.int32)
    vec[: len(components)] = components
    return vec, np.int32(len(components))


def to_tuple(ver, vlen):
    """Host helper: back to the tuple form used by ``nfa.dewey.DeweyVersion``."""
    return tuple(int(c) for c in ver[: int(vlen)])


def add_run(ver: Version, vlen: Length) -> Version:
    """Increment the last live component (length is unchanged)."""
    idx = jnp.arange(ver.shape[0], dtype=jnp.int32)
    return ver + jnp.where(idx == vlen - 1, 1, 0).astype(ver.dtype)


def add_stage(ver: Version, vlen: Length):
    """Append a ``0`` component; returns ``(ver, vlen, overflow)``.

    On overflow (``vlen == D``) the version is returned unchanged and the
    flag is set; the engine counts these and the run keeps its version — a
    documented deviation from the reference's unbounded growth.
    """
    depth = ver.shape[0]
    overflow = vlen >= depth
    new_len = jnp.where(overflow, vlen, vlen + 1)
    # Slots at index >= vlen are already zero by construction, so appending a
    # zero needs no write; only the length moves.
    return ver, new_len.astype(vlen.dtype), overflow


def is_compatible(qver: Version, qlen: Length, pver: Version, plen: Length):
    """``DeweyVersion.isCompatible`` over fixed-width vectors.

    ``q`` is the query (receiver) version, ``p`` the pointer version — the
    same argument order as ``qv.isCompatible(pv)`` in the reference
    (``TimedKeyValue.java:91``).
    """
    idx = jnp.arange(qver.shape[0], dtype=jnp.int32)
    eq = qver == pver
    # all(q[:n] == p[:n]) for a dynamic n, via masking.
    prefix_full = jnp.all(jnp.where(idx < plen, eq, True))
    prefix_butlast = jnp.all(jnp.where(idx < plen - 1, eq, True))
    last_q = jnp.sum(jnp.where(idx == plen - 1, qver, 0))
    last_p = jnp.sum(jnp.where(idx == plen - 1, pver, 0))
    longer = (qlen > plen) & prefix_full
    equal = (qlen == plen) & prefix_butlast & (last_q >= last_p)
    return longer | equal
