"""Device-side match compaction — shrink the decode transfer.

The processor's decode pulls the scan's match outputs to the host.  Raw
``StepOutput`` arrays are ``[K, T, R, W]`` — at the headline shape that is
gigabytes per batch, nearly all of it zeros (match density is a fraction
of a slot per lane-step), and the host pull dominates the processor's
critical path (SURVEY §2.2 PP row; the reference's per-record loop never
materializes a grid, ``CEPProcessor.java:154-163``).

``compact_matches`` reduces the transfer on-device: per lane, the hit rows
(``count > 0``) move to the front of a fixed ``budget`` of rows via a
stable key sort (hits keep (t, r) scan order), plus the (t, r, count)
metadata the host decode needs for arrival-order emission.  A one-shot
batched gather is fine on TPU — the 4x-slower-gather finding in
PROFILE_r04 applies to gathers inside while-loop bodies, not to a single
post-scan op.  Lanes with more hits than ``budget`` are flagged; the
processor falls back to the full pull for that batch (correctness never
depends on the budget).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("budget",))
def compact_matches(out, budget: int):
    """``StepOutput [K, T, R, ...]`` -> globally compacted match rows.

    Returns ``(stage [G, W], off [G, W], count [G], k [G], t [G], r [G],
    n_hits [], overflow [] bool)`` with the hit rows first in (k, t, r)
    order and ``count == 0`` rows past the total hit count.  Compaction
    is global across lanes (one stable sort over the flattened grid): the
    host pull is then proportional to the match *budget*, not ``lanes x
    budget`` — on a tunneled device the transfer is the decode wall, and
    a per-lane layout was measured pulling ~200 MB/batch for ~18K actual
    matches.  ``n_hits`` lets the caller slice the rows to the actual
    match count before pulling (two-phase pull: one scalar, then
    ``rows[:n]``).
    """
    K, T, R = out.count.shape
    W = out.stage.shape[-1]
    N = K * T * R
    G = min(budget, N)
    i32 = jnp.int32

    count = out.count.reshape(N)
    hit = count > 0
    n_hits = jnp.sum(jnp.where(hit, 1, 0))
    overflow = n_hits > G

    # Rank-scatter, not sort: a full argsort over the N-row grid was
    # measured at seconds per batch on TPU; an exclusive prefix sum plus
    # one masked scatter is linear and keeps (k, t, r) order (ranks are
    # monotone).  Non-hits scatter to index G, dropped by mode="drop".
    rank = jnp.cumsum(jnp.where(hit, 1, 0)) - 1
    dst = jnp.where(hit, rank, G).astype(i32)

    def scat(flat, width=None):
        if width is None:
            z = jnp.zeros((G,), flat.dtype)
            return z.at[dst].set(flat, mode="drop")
        z = jnp.zeros((G, width), flat.dtype)
        return z.at[dst].set(flat, mode="drop")

    n = jnp.arange(N, dtype=i32)
    return (
        scat(out.stage.reshape(N, W), W),
        scat(out.off.reshape(N, W), W),
        scat(count),
        scat(n // (T * R)),
        scat((n // R) % T),
        scat(n % R),
        n_hits,
        overflow,
    )


@functools.partial(jax.jit, static_argnames=("budget",))
def compact_drained(dout, budget: int):
    """``DrainOutput [K, HB, ...]`` -> globally compacted match rows.

    The lazy-extraction analog of :func:`compact_matches`: the drain
    pass's raw outputs are ``[K, HB, W]`` — ~100 MB per drain at
    production lane counts, nearly all empty ring slots — so the hit
    rows compact on-device into ``budget`` rows in (lane, ring) order
    before the host pull.  Returns ``(stage [G, W], off [G, W],
    count [G], seq [G], row [G], k [G], n_hits [], overflow [] bool)``;
    same two-phase-pull contract as :func:`compact_matches` (overflow ⇒
    the caller falls back to the full pull — correctness never depends
    on the budget).
    """
    K, HB = dout.count.shape
    W = dout.stage.shape[-1]
    N = K * HB
    G = min(budget, N)
    i32 = jnp.int32

    count = dout.count.reshape(N)
    hit = count > 0
    n_hits = jnp.sum(jnp.where(hit, 1, 0))
    overflow = n_hits > G

    rank = jnp.cumsum(jnp.where(hit, 1, 0)) - 1
    dst = jnp.where(hit, rank, G).astype(i32)

    def scat(flat, width=None):
        if width is None:
            z = jnp.zeros((G,), flat.dtype)
            return z.at[dst].set(flat, mode="drop")
        z = jnp.zeros((G, width), flat.dtype)
        return z.at[dst].set(flat, mode="drop")

    n = jnp.arange(N, dtype=i32)
    return (
        scat(dout.stage.reshape(N, W), W),
        scat(dout.off.reshape(N, W), W),
        scat(count),
        scat(dout.seq.reshape(N)),
        scat(dout.row.reshape(N)),
        scat(n // HB),
        n_hits,
        overflow,
    )
