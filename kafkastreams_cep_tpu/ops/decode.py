"""Device-side match compaction — shrink the decode transfer.

The processor's decode pulls the scan's match outputs to the host.  Raw
``StepOutput`` arrays are ``[K, T, R, W]`` — at the headline shape that is
gigabytes per batch, nearly all of it zeros (match density is a fraction
of a slot per lane-step), and the host pull dominates the processor's
critical path (SURVEY §2.2 PP row; the reference's per-record loop never
materializes a grid, ``CEPProcessor.java:154-163``).

``compact_matches`` reduces the transfer on-device: per lane, the hit rows
(``count > 0``) move to the front of a fixed ``budget`` of rows via a
stable key sort (hits keep (t, r) scan order), plus the (t, r, count)
metadata the host decode needs for arrival-order emission.  A one-shot
batched gather is fine on TPU — the 4x-slower-gather finding in
PROFILE_r04 applies to gathers inside while-loop bodies, not to a single
post-scan op.  Lanes with more hits than ``budget`` are flagged; the
processor falls back to the full pull for that batch (correctness never
depends on the budget).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("budget",))
def compact_matches(out, budget: int):
    """``StepOutput [K, T, R, ...]`` -> compacted per-lane match rows.

    Returns ``(stage [K, M, W], off [K, M, W], count [K, M], t [K, M],
    r [K, M], overflow [] bool)`` with hit rows first in (t, r) scan
    order; rows past a lane's hit count carry ``count == 0``.
    """
    K, T, R = out.count.shape
    W = out.stage.shape[-1]
    M = min(budget, T * R)
    i32 = jnp.int32

    count = out.count.reshape(K, T * R)
    hit = count > 0
    n_hits = jnp.sum(hit.astype(i32), axis=1)  # [K]
    overflow = jnp.any(n_hits > M)

    # Stable sort on the miss flag floats hits to the front in scan order.
    order = jnp.argsort(
        jnp.where(hit, 0, 1).astype(i32), axis=1, stable=True
    )[:, :M]  # [K, M]

    def rows(field):  # [K, T, R, W] -> [K, M, W]
        return jnp.take_along_axis(
            field.reshape(K, T * R, W), order[:, :, None], axis=1
        )

    def scalars(field):  # [K, N] -> [K, M]
        return jnp.take_along_axis(field, order, axis=1)

    n = jnp.arange(T * R, dtype=i32)
    t_of = jnp.broadcast_to((n // R)[None, :], (K, T * R))
    r_of = jnp.broadcast_to((n % R)[None, :], (K, T * R))
    return (
        rows(out.stage),
        rows(out.off),
        scalars(count),
        scalars(t_of),
        scalars(r_of),
        overflow,
    )
