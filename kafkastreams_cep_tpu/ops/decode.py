"""Device-side match compaction — shrink the decode transfer.

The processor's decode pulls the scan's match outputs to the host.  Raw
``StepOutput`` arrays are ``[K, T, R, W]`` — at the headline shape that is
gigabytes per batch, nearly all of it zeros (match density is a fraction
of a slot per lane-step), and the host pull dominates the processor's
critical path (SURVEY §2.2 PP row; the reference's per-record loop never
materializes a grid, ``CEPProcessor.java:154-163``).

``compact_matches`` reduces the transfer on-device: per lane, the hit rows
(``count > 0``) move to the front of a fixed ``budget`` of rows via a
stable key sort (hits keep (t, r) scan order), plus the (t, r, count)
metadata the host decode needs for arrival-order emission.  A one-shot
batched gather is fine on TPU — the 4x-slower-gather finding in
PROFILE_r04 applies to gathers inside while-loop bodies, not to a single
post-scan op.  Lanes with more hits than ``budget`` are flagged; the
processor falls back to the full pull for that batch (correctness never
depends on the budget).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("budget",))
def compact_matches(out, budget: int):
    """``StepOutput [K, T, R, ...]`` -> globally compacted match rows.

    Returns ``(stage [G, W], off [G, W], count [G], k [G], t [G], r [G],
    overflow [] bool)`` with the hit rows first in (k, t, r) order and
    ``count == 0`` rows past the total hit count.  Compaction is global
    across lanes (one stable sort over the flattened grid): the host pull
    is then proportional to the match *budget*, not ``lanes x budget`` —
    on a tunneled device the transfer is the decode wall, and a per-lane
    layout was measured pulling ~200 MB/batch for ~18K actual matches.
    """
    K, T, R = out.count.shape
    W = out.stage.shape[-1]
    N = K * T * R
    G = min(budget, N)
    i32 = jnp.int32

    count = out.count.reshape(N)
    hit = count > 0
    n_hits = jnp.sum(jnp.where(hit, 1, 0))
    overflow = n_hits > G

    # Stable sort on the miss flag floats hits to the front, preserving
    # (k, t, r) order among them.
    order = jnp.argsort(
        jnp.where(hit, 0, 1).astype(i32), stable=True
    )[:G]  # [G]

    return (
        out.stage.reshape(N, W)[order],
        out.off.reshape(N, W)[order],
        count[order],
        (order // (T * R)).astype(i32),
        ((order // R) % T).astype(i32),
        (order % R).astype(i32),
        overflow,
    )
