"""Whole-scan fused Pallas kernel — the entire event loop in VMEM.

Round 4's walk kernel (``ops/walk_kernel.py``) fused the buffer phases of
ONE step; the remaining ~2 ms/step of jnp (predicates, the unrolled
evaluation chain, op building, queue compaction) plus the per-step kernel
launch and per-step slab HBM round-trip set the round-4 ceiling at ~630K
ev/s (PROFILE_r04.md postscript item 5).  This kernel fuses the WHOLE
scan: grid ``(K/128, T)`` with the time axis as the sequential minor
dimension, so each 128-lane block's run state and slab live in VMEM
output blocks revisited across all ``T`` steps (the standard TPU
reduction/accumulator pattern) — state and slab cross HBM once per scan,
not once per step — while each step's events stream in and each step's
match emissions stream out through ``t``-indexed blocks.

Inside one grid step the phases are the engine's, in the engine's order
(``engine/matcher.py _build_step``): predicate evaluation over the run
axis, the unrolled ``NFA.evaluate`` chain (``NFA.java:94-289``) including
typed fold application, consuming puts and the merged walk pass (ported
from ``ops/walk_kernel.py`` — one walker per lane per batch in queue-order
rank, sequential-exact by construction), and scatter-free queue
compaction.  User predicates and fold functions are traced INTO the
kernel as ``[R, L]`` vector programs — they are already required to be
pure elementwise array code, so the same lambdas lower to Mosaic; a
pattern whose predicates do not lower falls back to the per-step path
(``build_scan`` raises at trace time, callers catch).

Single-query only (``Q == 1``); stacked banks keep the per-step kernel.
Differentially tested against the jnp engine in
``tests/test_scan_kernel.py`` (interpret mode on CPU) and through the
engine A/B fuzz suites.
"""

from __future__ import annotations

import functools
from typing import Any, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kafkastreams_cep_tpu.compiler.tables import OP_BEGIN, OP_TAKE, TYPE_BEGIN
from kafkastreams_cep_tpu.engine.matcher import (
    ArrayStates,
    EngineConfig,
    EngineState,
    EventBatch,
    StepOutput,
)
from kafkastreams_cep_tpu.ops.slab import SlabState
from kafkastreams_cep_tpu.ops.walk_kernel import _coalesced_demote
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("ops.scan_kernel")

LANE_BLOCK = 128

# jax renamed TPUCompilerParams -> CompilerParams across the versions this
# engine runs on (laptop CI pins an older jaxlib than the TPU hosts).
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


def _cumsum0(x):
    """Inclusive prefix sum along axis 0 via log-shift adds — Mosaic has
    no cumsum lowering; log2(N) shifted adds of the [N, L] plane do."""
    n = x.shape[0]
    k = 1
    while k < n:
        pad = jnp.zeros((k,) + x.shape[1:], x.dtype)
        x = x + jnp.concatenate([pad, x[:-k]], axis=0)
        k *= 2
    return x


def _sel_table(table: np.ndarray, idx):
    """``table[idx]`` for a tiny static table and a traced [..] index —
    compile-time-unrolled one-hot (S is the stage count, single digits)."""
    out = jnp.zeros_like(idx)
    for s, v in enumerate(np.asarray(table).tolist()):
        out = jnp.where(idx == s, jnp.int32(v), out)
    return out


def build_scan(tables, config: EngineConfig, promotion=None):
    """A jitted ``scan(state, events) -> (state, outs)`` over the fused
    whole-scan kernel, or raise if the pattern cannot lower.

    Contract matches ``BatchMatcher.scan``: ``state`` is a ``[K]``-batched
    :class:`EngineState`, ``events`` a ``[K, T]`` :class:`EventBatch`,
    outputs ``[K, T, R, W]``.  ``K`` must be a multiple of 128.

    ``promotion`` (the tiering plan's prefix length ``p``) compiles the
    *tiered* variant: ``scan(state, events, promo) -> (state, outs,
    promoted)`` where ``promo`` is the stencil tier's
    :class:`~kafkastreams_cep_tpu.engine.stencil.PromoOutput` feed.  The
    promotion step (``engine/tiered.py: build_promote`` — the prefix
    chain's slab writes plus the suffix run-queue append) runs as a fused
    phase after the engine phases of each step, and the whole engine step
    is gated per step on device: a step with no live suffix run and no
    prefix completion touches nothing but the step counter — the
    in-kernel analog of the chunked path's ``lax.cond`` skip
    (``parallel/tiered.py``).
    """
    cfg = config
    PROMO = int(promotion) if promotion else 0
    R, E, MP, D, W = (
        cfg.max_runs, cfg.slab_entries, cfg.slab_preds, cfg.dewey_depth,
        cfg.max_walk,
    )
    # Two-tier slab layout (ops/slab.py "Two-tier layout" note): rows
    # [0, EHk) hot, [EHk, E) overflow.  slab_hot_entries == 0 instantiates
    # the legacy single tier as EHk = E / EO = 0 — the overflow-side blocks
    # below then vanish at trace time and the hot-side code is the original
    # full-slab code.
    EH = cfg.slab_hot_entries
    EHk = EH if EH else E
    EO = E - EHk
    # Lazy extraction (EngineConfig.lazy_extraction): completed matches
    # append to the in-state handle ring (phase 6) instead of running
    # extraction walkers in phase 4; the drain pass runs OUTSIDE this
    # kernel (engine/matcher.py build_drain) at scan cadence.
    LAZY = cfg.lazy_extraction
    HB = cfg.handle_ring
    # Per-stage attribution width (EngineConfig.stage_attribution): when
    # 0 the two attribution arrays are absent from the kernel I/O and all
    # tally code vanishes at trace time — zero new device work.
    SA = tables.num_stages if cfg.stage_attribution else 0
    # kernel output refs (run state + slab + counters + ring + emits
    # [+ the two stage-attribution arrays when SA > 0][+ the promotion
    # count accumulator when PROMO])
    N_OUT = 43 + (2 if SA else 0) + (1 if PROMO else 0)
    H = tables.max_hops
    NS = max(tables.num_states, 1)
    S_CAND = 1 + H + 1
    RS = R * S_CAND
    RH = R * H
    PW = RH + 2 * R  # walker queue: branches, dead removals, finals
    S = tables.num_stages
    L = LANE_BLOCK
    i32 = jnp.int32

    ident = np.asarray(tables.ident)
    types = np.asarray(tables.types)
    consume_op = np.asarray(tables.consume_op)
    consume_pred = np.asarray(tables.consume_pred)
    consume_target = np.asarray(tables.consume_target)
    ignore_pred = np.asarray(tables.ignore_pred)
    proceed_pred = np.asarray(tables.proceed_pred)
    proceed_target = np.asarray(tables.proceed_target)
    window_ms = np.asarray(tables.window_ms.astype(np.int64))
    final_pos = int(tables.final_pos)
    begin_pos = int(tables.begin_pos)
    # Same predicate-dedup pass as the jnp path (_build_step): distinct
    # predicates evaluate once per event, shared across every edge that
    # references them; provably state-independent ones get an empty
    # states env so their kernel code carries no agg dependence.
    from kafkastreams_cep_tpu.compiler.multitenant import (
        plan_step_predicates,
    )

    pred_plan = plan_step_predicates([tables])
    pred_entries = list(pred_plan.event_entries) + list(
        pred_plan.run_entries
    )
    _remap = pred_plan.remaps[0]
    if len(_remap):
        def _remap_ids(a):
            return np.where(a >= 0, _remap[np.maximum(a, 0)], a)

        consume_pred = _remap_ids(consume_pred)
        ignore_pred = _remap_ids(ignore_pred)
        proceed_pred = _remap_ids(proceed_pred)
    is_float = [d == "float32" for d in tables.state_dtypes] + [False] * (
        NS - tables.num_states
    )
    inits_np = np.asarray(
        [
            int(np.float32(x).view(np.int32)) if f else int(np.int32(x))
            for x, f in zip(
                list(tables.state_inits) + [0] * (NS - tables.num_states),
                is_float,
            )
        ]
        or [0],
        dtype=np.int32,
    )

    if PROMO:
        # Promotion statics (engine/tiered.py build_promote): the prefix
        # stage identities, the appended run's eval position, and the
        # chain's per-put predecessor links are all trace-time constants.
        if not 0 < PROMO <= D:
            raise ValueError(
                f"promotion={PROMO} must be in 1..dewey_depth={D}"
            )
        promo_idents = [int(ident[j]) for j in range(PROMO)]
        promo_eval = int(consume_target[PROMO - 1])

    def dec(v, flt):
        return jax.lax.bitcast_convert_type(v, jnp.float32) if flt else v

    def enc(v, flt):
        if flt:
            return jax.lax.bitcast_convert_type(
                jnp.asarray(v, jnp.float32), jnp.int32
            )
        return jnp.asarray(v, i32)

    # Aggregator slots: (stage position, state slot, fn).
    agg_slots = [(a.stage, a.state, a.fn) for a in tables.aggs]

    def kernel(
        # inputs: run state (lane-last)
        alive, id_pos, eval_pos, vlen, event_off, start_ts, branching, agg,
        ver,
        # slab
        sstage, soff, srefs, snpreds, spstage, spoff, spvlen, spver,
        # counters
        run_drops, ver_ovf, fulld, predd, missing, trunc, hh, hm, ow, dm,
        wh, eh, dh,
        # lazy-extraction handle ring + step counter
        hr_stage, hr_off, hr_vlen_i, hr_ts, hr_seq, hr_row, hr_ver,
        hr_count, seq0, hovf,
        # tail: [stc_in, shp_in when SA] then per-t event slices, outputs,
        # scratch — unpacked by index so SA == 0 adds nothing.
        *rest,
    ):
        ri = 0
        if SA:
            stc_in, shp_in = rest[0], rest[1]
            ri = 2
        ev_key, ev_ts, ev_off, ev_valid = rest[ri:ri + 4]
        ri += 4
        n_leaves = len(value_dtypes)
        ev_leaves = rest[ri:ri + n_leaves]
        ri += n_leaves
        if PROMO:
            # Per-step promotion feed (stencil tier): fire flag, the p
            # prefix-event offsets, the window anchor, the seed version.
            pr_fire, pr_offs, pr_anchor, pr_sver = rest[ri:ri + 4]
            ri += 4
        outs_flat = rest[ri:ri + N_OUT]
        (o_alive, o_id, o_eval, o_vlen, o_event, o_start, o_branch, o_agg,
         o_ver, o_sstage, o_soff, o_srefs, o_snpreds, o_spstage, o_spoff,
         o_spvlen, o_spver, o_rd, o_vo, o_fd, o_pd, o_ms, o_tr,
         o_hh, o_hm, o_ow, o_dm, o_wh, o_eh, o_dh,
         o_hrstage, o_hroff, o_hrvlen, o_hrts, o_hrseq, o_hrrow, o_hrver,
         o_hrcount, o_seq, o_hovf) = outs_flat[:40]
        oi = 40
        if SA:
            o_stc, o_shp = outs_flat[40], outs_flat[41]
            oi = 42
        if PROMO:
            o_promoted = outs_flat[oi]
            oi += 1
        o_ostage, o_ooff, o_ocount = outs_flat[oi:oi + 3]
        if EO:
            (sc_found, sc_refs, sc_np, sc_ps, sc_po, sc_pl, sc_pv) = rest[
                ri + N_OUT:
            ]

        t = pl.program_id(1)

        @pl.when(t == 0)
        def _():
            o_alive[:] = alive[:]
            o_id[:] = id_pos[:]
            o_eval[:] = eval_pos[:]
            o_vlen[:] = vlen[:]
            o_event[:] = event_off[:]
            o_start[:] = start_ts[:]
            o_branch[:] = branching[:]
            o_agg[:] = agg[:]
            o_ver[:] = ver[:]
            o_sstage[:] = sstage[:]
            o_soff[:] = soff[:]
            o_srefs[:] = srefs[:]
            o_snpreds[:] = snpreds[:]
            o_spstage[:] = spstage[:]
            o_spoff[:] = spoff[:]
            o_spvlen[:] = spvlen[:]
            o_spver[:] = spver[:]
            o_rd[:] = run_drops[:]
            o_vo[:] = ver_ovf[:]
            o_fd[:] = fulld[:]
            o_pd[:] = predd[:]
            o_ms[:] = missing[:]
            o_tr[:] = trunc[:]
            o_hh[:] = hh[:]
            o_hm[:] = hm[:]
            o_ow[:] = ow[:]
            o_dm[:] = dm[:]
            o_wh[:] = wh[:]
            o_eh[:] = eh[:]
            o_dh[:] = dh[:]
            o_hrstage[:] = hr_stage[:]
            o_hroff[:] = hr_off[:]
            o_hrvlen[:] = hr_vlen_i[:]
            o_hrts[:] = hr_ts[:]
            o_hrseq[:] = hr_seq[:]
            o_hrrow[:] = hr_row[:]
            o_hrver[:] = hr_ver[:]
            o_hrcount[:] = hr_count[:]
            o_hovf[:] = hovf[:]
            if SA:
                o_stc[:] = stc_in[:]
                o_shp[:] = shp_in[:]
            if PROMO:
                o_promoted[:] = jnp.zeros((1, L), i32)

        # The per-lane step counter ticks every step (padding included) —
        # it is the emission t-index, not match state.  seq_now is this
        # step's stamp; the output carries the post-scan value.
        seq_now = seq0[:] + t
        o_seq[:] = seq_now + 1

        # Event blocks arrive [1, 1, L] ([T, 1, K] arrays — the middle 1
        # keeps the trailing dims tileable); squeeze the t axis.
        valid = ev_valid[:][0] != 0  # [1, L]
        key = ev_key[:][0]
        ts = ev_ts[:][0]
        off = ev_off[:][0]

        # Emission blocks are fresh garbage at every t: write the
        # no-emission frame up front so a gated-off step still outputs a
        # well-formed (empty) slice.  Steps that do run overwrite these
        # in phase 4 and re-mask them in phase 5.
        o_ostage[:] = jnp.full((1, R, W, L), -1, i32)
        o_ooff[:] = jnp.full((1, R, W, L), -1, i32)
        o_ocount[:] = jnp.zeros((1, R, L), i32)
        if PROMO:
            fire_row = pr_fire[:][0] != 0  # [1, L]

        # The engine step proper.  Under PROMO the whole step runs
        # under a per-step device gate — the in-kernel analog of the
        # chunked path's lax.cond skip (parallel/tiered.py): with no
        # live suffix run and no prefix completion, every phase
        # effect below is masked to zero and the state/emission
        # writes are no-ops (the empty emission frame was already
        # written above), so skipping the step is exact.
        def _engine_step():
            # ---- phase 1: predicates over the run axis ([R, L] operands) ----
            st_alive = o_alive[:] != 0  # [R, L]
            st_branch = o_branch[:] != 0
            agg_now = o_agg[:]  # [NS, R, L]
            states = ArrayStates(
                {
                    n: dec(agg_now[i], is_float[i])
                    for i, n in enumerate(tables.state_names)
                }
            )
            value = jax.tree_util.tree_unflatten(
                value_treedef, [l[:][0] for l in ev_leaves]
            )
            empty_states = ArrayStates({})
            preds = [
                jnp.broadcast_to(
                    jnp.asarray(
                        e.pred(
                            key, value, ts,
                            states if e.stateful else empty_states,
                        ),
                        jnp.bool_,
                    ),
                    (R, L),
                )
                for e in pred_entries
            ]

            def pv(pid):
                """Predicate value by (traced) id; -1 = absent edge = False.
                Boolean algebra, not where() — Mosaic cannot select i1
                vectors (same note as ops/walk_kernel.py)."""
                out = jnp.zeros((R, L), jnp.bool_)
                for p, v in enumerate(preds):
                    out = out | ((pid == p) & v)
                return out

            # ---- phase 2: the unrolled evaluation chain (NFA.java:94-289),
            # the direct vector port of matcher.chain_one with [R, L] frames --
            iota_d = jax.lax.broadcasted_iota(i32, (D, R, L), 0)

            def add_run(vv, vl):
                return vv + jnp.where(iota_d == vl[None] - 1, 1, 0)

            seed = o_id[:] < 0
            idc = jnp.maximum(o_id[:], 0)
            id_type_begin = seed | (_sel_table(types, idc) == TYPE_BEGIN)
            start = jnp.where(id_type_begin, ts, o_start[:])

            if cfg.enforce_windows:
                w = _sel_table(window_ms.astype(np.int32), o_eval[:])
                out_w = (
                    (~id_type_begin) & (w != -1) & (ts - o_start[:] > w)
                )
            else:
                out_w = jnp.zeros((R, L), jnp.bool_)
            active = st_alive & ~out_w & valid

            cross0 = _sel_table(ident, o_eval[:]) != idc
            do_add0 = active & ~seed & cross0 & ~st_branch
            ovf0 = o_vlen[:] >= D
            vl = jnp.where(do_add0 & ~ovf0, o_vlen[:] + 1, o_vlen[:])
            vv = o_ver[:]
            ovf_ct = jnp.sum(
                jnp.where(do_add0 & ovf0, 1, 0), axis=0, keepdims=True
            )

            cur = o_eval[:]
            prev = jnp.where(seed, i32(-1), o_id[:])

            surv_alive = jnp.zeros((R, L), jnp.bool_)
            surv_final = jnp.zeros((R, L), jnp.bool_)
            surv_id = jnp.zeros((R, L), i32)
            surv_eval = jnp.zeros((R, L), i32)
            surv_ver = jnp.zeros((D, R, L), i32)
            surv_vlen = jnp.zeros((R, L), i32)
            surv_event = jnp.zeros((R, L), i32)
            surv_start = jnp.zeros((R, L), i32)
            surv_branching = jnp.zeros((R, L), jnp.bool_)

            put_en, put_cur, put_prev, put_ver, put_vlen = [], [], [], [], []
            br_en, br_prev, br_ver, br_vlen = [], [], [], []
            br_run_ver, br_id, br_eval, br_event, br_start = [], [], [], [], []
            consumed_h, frame_pos = [], []
            if SA:
                iota_sar = jax.lax.broadcasted_iota(i32, (SA, R, L), 0)
                tly = [jnp.zeros((SA, L), i32) for _ in range(4)]

            for _h in range(H):
                cs = jnp.maximum(cur, 0)
                cop = _sel_table(consume_op, cs)
                cp = pv(_sel_table(consume_pred, cs))
                take_m = active & (cop == OP_TAKE) & cp
                begin_m = active & (cop == OP_BEGIN) & cp
                ig_m = active & pv(_sel_table(ignore_pred, cs))
                pr_m = active & pv(_sel_table(proceed_pred, cs))
                branch_m = (
                    (pr_m & take_m) | (ig_m & take_m) | (ig_m & begin_m)
                    | (ig_m & pr_m)
                ) & (prev >= 0)
                consumed = take_m | begin_m
                if SA:
                    # Per-stage selectivity tallies (matcher.chain_one):
                    # evaluated / accepted / ignored / rejected frames by
                    # stage, reduced over the run axis.
                    rejected = active & ~consumed & ~ig_m & ~pr_m
                    hit_s = iota_sar == cs[None]
                    for c, m in enumerate((active, consumed, ig_m, rejected)):
                        tly[c] = tly[c] + jnp.sum(
                            jnp.where(hit_s & m[None], 1, 0), axis=1
                        )

                st = take_m & ~branch_m
                sb = begin_m
                si = ig_m & ~branch_m
                fire = st | sb | si
                tgt = _sel_table(consume_target, cs)
                surv_id = jnp.where(
                    fire, jnp.where(si, o_id[:], _sel_table(ident, cs)), surv_id
                )
                surv_eval = jnp.where(
                    fire, jnp.where(st, cs, jnp.where(sb, tgt, o_eval[:])),
                    surv_eval,
                )
                surv_ver = jnp.where(fire[None], vv, surv_ver)
                surv_vlen = jnp.where(fire, vl, surv_vlen)
                surv_event = jnp.where(
                    fire, jnp.where(si, o_event[:], off), surv_event
                )
                surv_start = jnp.where(
                    fire, jnp.where(si, o_start[:], start), surv_start
                )
                # Boolean algebra (no i1 selects in Mosaic).
                surv_branching = (fire & si & st_branch) | (
                    ~fire & surv_branching
                )
                surv_final = (fire & sb & (tgt == final_pos)) | (
                    ~fire & surv_final
                )
                surv_alive = surv_alive | fire

                put_en.append(consumed)
                put_cur.append(_sel_table(ident, cs))
                put_prev.append(
                    jnp.where(
                        prev >= 0, _sel_table(ident, jnp.maximum(prev, 0)),
                        i32(-1),
                    )
                )
                put_ver.append(
                    jnp.where((take_m & branch_m)[None], add_run(vv, vl), vv)
                )
                put_vlen.append(vl)

                br_en.append(branch_m)
                br_prev.append(_sel_table(ident, jnp.maximum(prev, 0)))
                br_ver.append(vv)
                br_vlen.append(vl)
                br_run_ver.append(add_run(vv, vl))
                br_id.append(_sel_table(ident, jnp.maximum(prev, 0)))
                br_eval.append(cs)
                br_event.append(jnp.where(ig_m, o_event[:], off))
                br_start.append(start)
                consumed_h.append(consumed)
                frame_pos.append(cs)

                ptgt = _sel_table(proceed_target, cs)
                ptc = jnp.maximum(ptgt, 0)
                do_add = (
                    pr_m
                    & (_sel_table(ident, ptc) != _sel_table(ident, cs))
                    & ~st_branch
                )
                ovf_b = vl >= D
                ovf_ct = ovf_ct + jnp.sum(
                    jnp.where(do_add & ovf_b, 1, 0), axis=0, keepdims=True
                )
                vl = jnp.where(do_add & ~ovf_b, vl + 1, vl)
                prev = jnp.where(pr_m, cs, prev)
                cur = jnp.where(pr_m, ptc, cur)
                active = pr_m

            # Folds (deepest frame last to first, NFA.java:243 before :248),
            # with branch copies restricted to the branching stage's states.
            # (Init values build from scalar literals — Pallas kernels cannot
            # capture array constants.)
            # The agg planes stay a Python list of [R, L] arrays — indexed
            # updates on a stacked array would lower to scatter, which Mosaic
            # has no rule for.
            s_list = [agg_now[ns] for ns in range(NS)]
            init_list = [
                jnp.full((R, L), int(v), i32) for v in inits_np.tolist()
            ]
            br_agg: List[Any] = [None] * H
            for h in range(H - 1, -1, -1):
                copy_rows = []
                for ns in range(NS):
                    m = jnp.zeros((R, L), jnp.bool_)
                    for stage_pos, state_slot, _fn in agg_slots:
                        if state_slot == ns:
                            m = m | (frame_pos[h] == stage_pos)
                    copy_rows.append(m)
                br_agg[h] = jnp.stack(
                    [
                        jnp.where(copy_rows[ns], s_list[ns], init_list[ns])
                        for ns in range(NS)
                    ]
                )
                for stage_pos, state_slot, fn in agg_slots:
                    cond = consumed_h[h] & (frame_pos[h] == stage_pos)
                    flt = is_float[state_slot]
                    val = enc(fn(key, value, dec(s_list[state_slot], flt)), flt)
                    s_list[state_slot] = jnp.where(
                        cond, val, s_list[state_slot]
                    )
            final_agg = jnp.stack(s_list)
            inits_rl = jnp.stack(init_list)

            any_br = (
                functools.reduce(jnp.logical_or, br_en)
                if H else jnp.zeros((R, L), jnp.bool_)
            )
            has_succ = surv_alive | any_br
            dead = st_alive & ~seed & ~has_succ & valid
            final_en = surv_alive & surv_final & valid
            if SA:
                o_stc[:] = o_stc[:] + jnp.stack(tly)

            # ---- phase 3: consuming puts, in queue order (one per lane per
            # batch — the sequential semantics; port of walk_kernel put phase
            # against the resident slab refs) ----
            def stack_rh(frames):  # H x [R, L] -> [RH, L], run-major
                return jnp.stack(frames, axis=1).reshape(RH, L)

            def stack_rh_d(frames):  # H x [D, R, L] -> [D, RH, L]
                return jnp.stack(frames, axis=2).reshape(D, RH, L)

            # Masks stack/reshape in i32 — Mosaic cannot relayout i1
            # vectors through stack/reshape (bitcast_vreg failure).
            p_en_i = stack_rh([jnp.where(m, 1, 0) for m in put_en])
            p_en = p_en_i != 0
            p_cur = stack_rh(put_cur)
            p_prev = stack_rh(put_prev)
            p_pver = stack_rh_d(put_ver)
            p_pvlen = stack_rh(put_vlen)
            p_first_i = jnp.where(p_en & (p_prev < 0), 1, 0)
            prev_off_rep = jnp.broadcast_to(
                o_event[:][:, None, :], (R, H, L)
            ).reshape(RH, L)

            p_rank = jnp.where(p_en, _cumsum0(p_en_i) - 1, -1)
            max_pn = jnp.max(jnp.sum(p_en_i, axis=0))
            if EO:
                # Coalesced demotion pre-pass (ops/walk_kernel.py): one move
                # pass per step instead of one pl.when per put.
                creator_c, crank_c, claim_c, kcap_c = _coalesced_demote(
                    (o_sstage, o_soff, o_srefs, o_snpreds, o_spstage, o_spoff,
                     o_spvlen, o_spver, o_dm),
                    p_en, p_first_i != 0, p_cur, p_prev, prev_off_rep, off,
                    EHk=EHk, EO=EO, MP=MP, D=D,
                )

            iota_e = jax.lax.broadcasted_iota(i32, (E, L), 0)
            iota_mp = jax.lax.broadcasted_iota(i32, (MP, L), 0)
            iota_mp3 = jax.lax.broadcasted_iota(i32, (E, MP, L), 1)
            iota_d3 = jax.lax.broadcasted_iota(i32, (D, MP, L), 0)
            iota_eh = jax.lax.broadcasted_iota(i32, (EHk, L), 0)
            iota_mp3h = jax.lax.broadcasted_iota(i32, (EHk, MP, L), 1)
            if EO:
                iota_mp3o = jax.lax.broadcasted_iota(i32, (EO, MP, L), 1)

            def put_body(b):
                pselm = p_rank == b  # [RH, L]
                en0 = jnp.any(pselm, axis=0, keepdims=True)

                def ppick(f):
                    return jnp.sum(jnp.where(pselm, f, 0), axis=0, keepdims=True)

                first = jnp.any(
                    pselm & (p_first_i != 0), axis=0, keepdims=True
                )
                cur_s = ppick(p_cur)
                pst = ppick(p_prev)
                pof = ppick(prev_off_rep)
                pvl = ppick(p_pvlen)
                pvr = jnp.sum(jnp.where(pselm[None], p_pver, 0), axis=1)  # [D, L]
                off_l = off  # [1, L]

                prev_hit = (o_sstage[:] == pst) & (o_soff[:] == pof)
                prev_found = jnp.any(prev_hit, axis=0, keepdims=True)
                o_ms[:] = o_ms[:] + jnp.where(en0 & ~first & ~prev_found, 1, 0)
                en_ok = en0 & (first | prev_found)

                cur_hit = (o_sstage[:] == cur_s) & (o_soff[:] == off_l)
                exist = jnp.any(cur_hit, axis=0, keepdims=True)
                # Two-tier allocation: demotions already ran in the coalesced
                # pre-pass (ops/walk_kernel.py _coalesced_demote); allocation
                # is a rank lookup into the claim map.  EO == 0 keeps the
                # legacy first-free-slot scan verbatim.
                if EO:
                    is_cr = jnp.any(
                        pselm & creator_c, axis=0, keepdims=True
                    )
                    crk = ppick(crank_c)
                    alloc_h = (claim_c == crk) & is_cr
                    alloc = jnp.min(
                        jnp.where(alloc_h, iota_eh, E), axis=0, keepdims=True
                    )
                    has_free = is_cr & (crk < kcap_c) & (alloc < E)
                else:
                    free_h = o_sstage[:] < 0
                    ffs_h = jnp.min(
                        jnp.where(free_h, iota_eh, EHk), axis=0, keepdims=True
                    )
                    alloc = ffs_h
                    has_free = ffs_h < EHk
                tgt = (exist & cur_hit) | (~exist & (iota_e == alloc))
                ok = en_ok & (exist | has_free)
                o_fd[:] = o_fd[:] + jnp.where(en_ok & ~exist & ~has_free, 1, 0)
                m1 = tgt & ok
                reset = ok & (first | ~exist)
                o_sstage[:] = jnp.where(m1, cur_s, o_sstage[:])
                o_soff[:] = jnp.where(m1, off_l, o_soff[:])
                o_srefs[:] = jnp.where(m1 & reset, 1, o_srefs[:])
                np_e = jnp.sum(
                    jnp.where(m1, o_snpreds[:], 0), axis=0, keepdims=True
                )
                n_eff = jnp.where(reset, 0, np_e)
                pfull = ok & (n_eff >= MP)
                o_pd[:] = o_pd[:] + jnp.where(pfull, 1, 0)
                do = ok & ~pfull
                slot = jnp.minimum(n_eff, MP - 1)
                m2 = (
                    m1[:, None, :]
                    & (iota_mp3 == slot[:, None, :])
                    & do[:, None, :]
                )
                o_spstage[:] = jnp.where(
                    m2, jnp.where(first, -1, pst)[:, None, :], o_spstage[:]
                )
                o_spoff[:] = jnp.where(
                    m2, jnp.where(first, -1, pof)[:, None, :], o_spoff[:]
                )
                o_spvlen[:] = jnp.where(m2, pvl[:, None, :], o_spvlen[:])
                o_spver[:] = jnp.where(
                    m2[None], pvr[:, None, None, :], o_spver[:]
                )
                o_snpreds[:] = jnp.where(
                    m1, n_eff + jnp.where(do, 1, 0), o_snpreds[:]
                )
                return b + 1

            jax.lax.while_loop(lambda b: b < max_pn, put_body, jnp.zeros((), i32))

            # ---- phase 4: the merged walk pass (branch refcount walks
            # deepest-first, dead-run removals, final extractions) — port of
            # walk_kernel batch loop against the resident refs ----
            def rev_rh(frames):  # deepest-first: reverse the frame axis
                return jnp.stack(frames[::-1], axis=1).reshape(RH, L)

            def rev_rh_d(frames):
                return jnp.stack(frames[::-1], axis=2).reshape(D, RH, L)

            dead_en = dead & (o_event[:] >= 0)
            # Lazy extraction: the final segment keeps its rows (static
            # layout) but never enables — matches become ring handles in
            # phase 6 instead of W-hop extraction walkers here.
            final_w = (
                jnp.zeros((R, L), i32) if LAZY else jnp.where(final_en, 1, 0)
            )
            w_en_i = jnp.concatenate([
                rev_rh([jnp.where(m, 1, 0) for m in br_en]),
                jnp.where(dead_en, 1, 0),
                final_w,
            ])
            w_en = w_en_i != 0
            w_rem_i = jnp.concatenate(
                [jnp.zeros((RH, L), i32), jnp.ones((2 * R, L), i32)]
            )
            w_out_i = jnp.concatenate(
                [jnp.zeros((RH + R, L), i32), jnp.ones((R, L), i32)]
            )
            w_stage = jnp.concatenate(
                [rev_rh(br_prev), jnp.maximum(o_id[:], 0), surv_id]
            )
            w_off = jnp.concatenate(
                [prev_off_rep, o_event[:], jnp.broadcast_to(off, (R, L))]
            )
            w_ver = jnp.concatenate([rev_rh_d(br_ver), o_ver[:], surv_ver], axis=1)
            w_vlen = jnp.concatenate([rev_rh(br_vlen), o_vlen[:], surv_vlen])
            w_rank = jnp.where(w_en, _cumsum0(w_en_i) - 1, -1)
            max_n = jnp.max(jnp.sum(w_en_i, axis=0))
            iota_pw = jax.lax.broadcasted_iota(i32, (PW, L), 0)
            if SA:
                iota_sa2 = jax.lax.broadcasted_iota(i32, (SA, L), 0)
            # Emission blocks carry the t axis as a leading 1 (out_t_spec).
            iota_or3 = jax.lax.broadcasted_iota(i32, (1, R, W, L), 1)
            iota_w2 = jax.lax.broadcasted_iota(i32, (W, L), 0)
            iota_or2 = jax.lax.broadcasted_iota(i32, (1, R, L), 1)

            def batch_body(carry):
                b = carry
                selm = w_rank == b
                act0 = jnp.any(selm, axis=0, keepdims=True)

                def pick(f):
                    return jnp.sum(jnp.where(selm, f, 0), axis=0, keepdims=True)

                ws = pick(w_stage)
                wo = pick(w_off)
                wvl = pick(w_vlen)
                wrm_i = jnp.where(
                    jnp.any(selm & (w_rem_i != 0), axis=0, keepdims=True), 1, 0
                )
                wot_i = jnp.where(
                    jnp.any(selm & (w_out_i != 0), axis=0, keepdims=True), 1, 0
                )
                srow = pick(iota_pw - (RH + R))
                qv0 = jnp.sum(jnp.where(selm[None], w_ver, 0), axis=1)  # [D, L]

                st_stage = jnp.full((W, L), -1, i32)
                st_off = jnp.full((W, L), -1, i32)

                def hop_cond(c):
                    h, active_i = c[0], c[1]
                    return (h < W) & jnp.any(active_i != 0)

                def hop_body(c):
                    h, active_i, cs, co, qv, ql, cnt, st_stage, st_off = c
                    hactive = active_i != 0
                    # Walk-cost accounting (ops/slab.py _hop_counts); the
                    # drain pass never runs in-kernel, so the emit class is
                    # always the eager extraction counter.
                    o_wh[:] = o_wh[:] + jnp.where(
                        hactive & (wot_i == 0), 1, 0
                    )
                    o_eh[:] = o_eh[:] + jnp.where(
                        hactive & (wot_i != 0), 1, 0
                    )
                    if SA:
                        # Per-stage hop attribution at the walker's current
                        # stage (ops/slab.py _hop_counts; walk_kernel parity).
                        o_shp[:] = o_shp[:] + jnp.where(
                            (iota_sa2 == cs) & hactive, 1, 0
                        )
                    # Hot-tier lookup first (ops/walk_kernel.py hop): the
                    # overflow rows are touched only when some lane of the
                    # block missed hot.
                    hit_h = (o_sstage[0:EHk] == cs) & (o_soff[0:EHk] == co)
                    found_h = jnp.any(hit_h, axis=0, keepdims=True)
                    if EO:
                        miss = hactive & ~found_h
                        sc_found[:] = jnp.zeros((1, L), i32)
                        sc_refs[:] = jnp.zeros((1, L), i32)
                        sc_np[:] = jnp.zeros((1, L), i32)
                        sc_ps[:] = jnp.zeros((MP, L), i32)
                        sc_po[:] = jnp.zeros((MP, L), i32)
                        sc_pl[:] = jnp.zeros((MP, L), i32)
                        sc_pv[:] = jnp.zeros((D, MP, L), i32)

                        @pl.when(jnp.any(miss))
                        def _():
                            hit_o = (o_sstage[EHk:] == cs) & (
                                o_soff[EHk:] == co
                            )
                            hamo = hit_o & miss  # [EO, L]
                            sc_found[:] = jnp.where(
                                jnp.any(hamo, axis=0, keepdims=True), 1, 0
                            )
                            sc_refs[:] = jnp.sum(
                                jnp.where(hamo, o_srefs[EHk:], 0),
                                axis=0, keepdims=True,
                            )
                            sc_np[:] = jnp.sum(
                                jnp.where(hamo, o_snpreds[EHk:], 0),
                                axis=0, keepdims=True,
                            )
                            hamo3 = hamo[:, None, :]
                            sc_ps[:] = jnp.sum(
                                jnp.where(hamo3, o_spstage[EHk:], 0), axis=0
                            )
                            sc_po[:] = jnp.sum(
                                jnp.where(hamo3, o_spoff[EHk:], 0), axis=0
                            )
                            sc_pl[:] = jnp.sum(
                                jnp.where(hamo3, o_spvlen[EHk:], 0), axis=0
                            )
                            sc_pv[:] = jnp.sum(
                                jnp.where(
                                    hamo[None, :, None, :], o_spver[:, EHk:], 0
                                ),
                                axis=1,
                            )

                        act_o = sc_found[:] != 0
                        found = found_h | act_o
                        o_hh[:] = o_hh[:] + jnp.where(hactive & found_h, 1, 0)
                        o_hm[:] = o_hm[:] + jnp.where(miss, 1, 0)
                        o_ow[:] = o_ow[:] + jnp.where(act_o, 1, 0)
                    else:
                        act_o = jnp.zeros((1, L), jnp.bool_)
                        found = found_h
                    o_ms[:] = o_ms[:] + jnp.where(hactive & ~found, 1, 0)
                    hactive = hactive & found
                    ham_h = hit_h & hactive

                    refs_e = jnp.sum(
                        jnp.where(ham_h, o_srefs[0:EHk], 0),
                        axis=0, keepdims=True,
                    )
                    np_e = jnp.sum(
                        jnp.where(ham_h, o_snpreds[0:EHk], 0),
                        axis=0, keepdims=True,
                    )
                    if EO:
                        refs_e = refs_e + sc_refs[:]
                        np_e = np_e + sc_np[:]
                    newref = jnp.where(
                        wrm_i != 0, jnp.maximum(refs_e - 1, 0), refs_e + 1
                    )
                    o_srefs[0:EHk] = jnp.where(ham_h, newref, o_srefs[0:EHk])
                    dele = hactive & (wrm_i != 0) & (newref == 0) & (np_e <= 1)
                    dmask = ham_h & dele
                    o_sstage[0:EHk] = jnp.where(dmask, -1, o_sstage[0:EHk])
                    o_soff[0:EHk] = jnp.where(dmask, -1, o_soff[0:EHk])

                    emit = hactive & (wot_i != 0)
                    mw = (iota_w2 == cnt) & emit
                    st_stage = jnp.where(mw, cs, st_stage)
                    st_off = jnp.where(mw, co, st_off)
                    cnt = cnt + jnp.where(emit, 1, 0)

                    ham3 = ham_h[:, None, :]
                    ps_ = jnp.sum(jnp.where(ham3, o_spstage[0:EHk], 0), axis=0)
                    po_ = jnp.sum(jnp.where(ham3, o_spoff[0:EHk], 0), axis=0)
                    pl_ = jnp.sum(jnp.where(ham3, o_spvlen[0:EHk], 0), axis=0)
                    pv_ = jnp.sum(
                        jnp.where(ham_h[None, :, None, :], o_spver[:, 0:EHk], 0),
                        axis=1,
                    )  # [D, MP, L]
                    if EO:
                        ps_ = ps_ + sc_ps[:]
                        po_ = po_ + sc_po[:]
                        pl_ = pl_ + sc_pl[:]
                        pv_ = pv_ + sc_pv[:]
                    live = iota_mp < np_e

                    neq = (qv[:, None, :] != pv_).astype(i32)
                    plm = pl_[None, :, :]
                    prefix_full = (
                        jnp.sum(neq * (iota_d3 < plm).astype(i32), axis=0) == 0
                    )
                    prefix_butl = (
                        jnp.sum(neq * (iota_d3 < plm - 1).astype(i32), axis=0)
                        == 0
                    )
                    last_q = jnp.sum(
                        jnp.where(iota_d3 == plm - 1, qv[:, None, :], 0), axis=0
                    )
                    last_p = jnp.sum(
                        jnp.where(iota_d3 == plm - 1, pv_, 0), axis=0
                    )
                    ok = ((ql > pl_) & prefix_full) | (
                        (ql == pl_) & prefix_butl & (last_q >= last_p)
                    )
                    ok = ok & live
                    j = jnp.min(
                        jnp.where(ok, iota_mp, MP), axis=0, keepdims=True
                    )
                    selany = j < MP
                    ohj = iota_mp == j

                    prune = selany & hactive & (wrm_i != 0) & (newref == 0)
                    prune_h = prune & found_h

                    def _shifted(f, m, axis):
                        nxt = jnp.concatenate(
                            [
                                jax.lax.slice_in_dim(f, 1, None, axis=axis),
                                jax.lax.slice_in_dim(f, -1, None, axis=axis),
                            ],
                            axis=axis,
                        )
                        return jnp.where(m, nxt, f)

                    @pl.when(jnp.any(prune_h))
                    def _():
                        pm = ham3 & (iota_mp3h >= j[None]) & prune_h[None]
                        o_spstage[0:EHk] = _shifted(o_spstage[0:EHk], pm, 1)
                        o_spoff[0:EHk] = _shifted(o_spoff[0:EHk], pm, 1)
                        o_spvlen[0:EHk] = _shifted(o_spvlen[0:EHk], pm, 1)
                        o_spver[:, 0:EHk] = _shifted(
                            o_spver[:, 0:EHk], pm[None], 2
                        )
                        o_snpreds[0:EHk] = o_snpreds[0:EHk] - jnp.where(
                            ham_h & prune_h, 1, 0
                        )

                    if EO:
                        # One overflow-side mutation pass: refs decrement,
                        # delete, and prune for walkers resolved overflow —
                        # skipped whenever every lane resolved hot.
                        @pl.when(jnp.any(act_o))
                        def _():
                            hit_o = (o_sstage[EHk:] == cs) & (
                                o_soff[EHk:] == co
                            )
                            hamo = hit_o & act_o
                            o_srefs[EHk:] = jnp.where(
                                hamo, newref, o_srefs[EHk:]
                            )
                            dmo = hamo & dele
                            o_sstage[EHk:] = jnp.where(dmo, -1, o_sstage[EHk:])
                            o_soff[EHk:] = jnp.where(dmo, -1, o_soff[EHk:])
                            prune_o = prune & act_o
                            pmo = (
                                hamo[:, None, :]
                                & (iota_mp3o >= j[None])
                                & prune_o[None]
                            )
                            o_spstage[EHk:] = _shifted(o_spstage[EHk:], pmo, 1)
                            o_spoff[EHk:] = _shifted(o_spoff[EHk:], pmo, 1)
                            o_spvlen[EHk:] = _shifted(o_spvlen[EHk:], pmo, 1)
                            o_spver[:, EHk:] = _shifted(
                                o_spver[:, EHk:], pmo[None], 2
                            )
                            o_snpreds[EHk:] = o_snpreds[EHk:] - jnp.where(
                                hamo & prune_o, 1, 0
                            )

                    nxt_s = jnp.sum(jnp.where(ohj, ps_, 0), axis=0, keepdims=True)
                    nxt_o = jnp.sum(jnp.where(ohj, po_, 0), axis=0, keepdims=True)
                    nxt_l = jnp.sum(jnp.where(ohj, pl_, 0), axis=0, keepdims=True)
                    nxt_v = jnp.sum(jnp.where(ohj[None], pv_, 0), axis=1)

                    nactive = hactive & selany & (nxt_s >= 0)
                    budget_out = emit & (cnt >= W)
                    o_tr[:] = o_tr[:] + jnp.where(budget_out & nactive, 1, 0)
                    hactive = nactive & ~budget_out
                    cs = jnp.where(hactive, nxt_s, cs)
                    co = jnp.where(hactive, nxt_o, co)
                    ql = jnp.where(hactive, nxt_l, ql)
                    qv = jnp.where(hactive, nxt_v, qv)
                    return (h + 1, jnp.where(hactive, 1, 0), cs, co, qv, ql, cnt,
                            st_stage, st_off)

                zero_l = jnp.zeros((1, L), i32)
                (h, active_i, cs, co, qv, ql, cnt, st_stage, st_off) = (
                    jax.lax.while_loop(
                        hop_cond, hop_body,
                        (jnp.zeros((), i32), jnp.where(act0, 1, 0), ws, wo, qv0, wvl,
                         zero_l, st_stage, st_off),
                    )
                )
                o_tr[:] = o_tr[:] + active_i
                mo = (iota_or3 == srow[None, :, None, :]) & (
                    wot_i[None, :, None, :] != 0
                )
                o_ostage[:] = jnp.where(mo, st_stage[None, None], o_ostage[:])
                o_ooff[:] = jnp.where(mo, st_off[None, None], o_ooff[:])
                cm = (iota_or2 == srow[None]) & (wot_i[None] != 0)
                o_ocount[:] = jnp.where(cm, cnt[None], o_ocount[:])
                return b + 1

            jax.lax.while_loop(
                lambda b: b < max_n, batch_body, jnp.zeros((), i32)
            )

            # ---- phase 5: queue compaction (matcher.finish port) ----
            # Candidates stay as separate per-slot [R, L] planes — any
            # [R, S_CAND, L] -> [RS, L] interleave reshape leaves Mosaic
            # relayouting every downstream op (measured ~1.5 s of the scan);
            # pure masked reductions over unrolled slots cost ~a tenth.
            reseed_ver = jnp.where(
                has_succ[None], add_run(o_ver[:], o_vlen[:]), o_ver[:]
            )
            seed_mask = st_alive & seed

            ones_rl = jnp.ones((R, L), i32)
            zeros_rl = jnp.zeros((R, L), i32)
            neg1_rl = jnp.full((R, L), -1, i32)
            # Queue order: per run [survivor, branches deepest-first, re-seed].
            alive_c = (
                [surv_alive & ~surv_final]
                + [br_en[H - 1 - j] for j in range(H)]
                + [seed_mask]
            )
            planes_c = {
                "id": [surv_id] + [br_id[H - 1 - j] for j in range(H)] + [neg1_rl],
                "eval": [surv_eval] + [br_eval[H - 1 - j] for j in range(H)]
                + [jnp.full((R, L), begin_pos, i32)],
                "vlen": [surv_vlen] + [br_vlen[H - 1 - j] for j in range(H)]
                + [o_vlen[:]],
                "event": [surv_event] + [br_event[H - 1 - j] for j in range(H)]
                + [neg1_rl],
                "start": [surv_start] + [br_start[H - 1 - j] for j in range(H)]
                + [neg1_rl],
                "branch": [jnp.where(surv_branching, 1, 0)]
                + [ones_rl] * H + [zeros_rl],
                "got": [ones_rl] * (H + 2),
            }
            for k in range(D):
                planes_c[f"ver{k}"] = (
                    [surv_ver[k]]
                    + [br_run_ver[H - 1 - j][k] for j in range(H)]
                    + [reseed_ver[k]]
                )
            for ns in range(NS):
                planes_c[f"agg{ns}"] = (
                    [final_agg[ns]]
                    + [br_agg[H - 1 - j][ns] for j in range(H)]
                    + [init_list[ns]]
                )

            # Queue-order rank of each candidate: exclusive prefix of per-run
            # totals over the run axis, plus the within-run prefix.
            run_tot = zeros_rl
            for m in alive_c:
                run_tot = run_tot + jnp.where(m, 1, 0)
            run_pre = run_tot
            b = 1
            while b < R:
                run_pre = run_pre + jnp.concatenate(
                    [jnp.zeros((b, L), i32), run_pre[:-b]], axis=0
                )
                b *= 2
            run_pre = run_pre - run_tot  # exclusive
            idx_c, kept_c = [], []
            within = zeros_rl
            for m in alive_c:
                idx = run_pre + within
                idx_c.append(idx)
                kept_c.append(m & (idx < R))
                within = within + jnp.where(m, 1, 0)

            dropped = jnp.zeros((1, L), i32)
            for m, idx in zip(alive_c, idx_c):
                dropped = dropped + jnp.sum(
                    jnp.where(m & (idx >= R), 1, 0), axis=0, keepdims=True
                )
            o_rd[:] = o_rd[:] + jnp.where(valid, dropped, 0)
            o_vo[:] = o_vo[:] + jnp.where(valid, ovf_ct, 0)

            # Destination assembly: for each queue slot j, a masked reduce
            # over all candidates picks the (unique) one with rank j.
            names = list(planes_c)
            rows = {name: [] for name in names}
            for j in range(R):
                sel = [k & (idx == j) for k, idx in zip(kept_c, idx_c)]
                for name in names:
                    v = jnp.zeros((1, L), i32)
                    for s, p in zip(sel, planes_c[name]):
                        v = v + jnp.sum(
                            jnp.where(s, p, 0), axis=0, keepdims=True
                        )
                    rows[name].append(v)

            def assemble(name):
                return jnp.concatenate(rows[name], axis=0)  # [R, L]

            got = assemble("got") != 0
            new_alive = got

            def head(name, fill):
                return jnp.where(got, assemble(name), i32(fill))

            n_id = head("id", -1)
            n_eval = head("eval", 0)
            n_vlen = head("vlen", 0)
            n_event = head("event", -1)
            n_start = head("start", -1)
            n_branch = head("branch", 0)
            n_ver = jnp.stack([head(f"ver{k}", 0) for k in range(D)])
            n_agg = jnp.stack([head(f"agg{ns}", 0) for ns in range(NS)])

            # Padding steps freeze the state (matcher.finish contract).
            o_alive[:] = jnp.where(valid & new_alive, 1,
                                   jnp.where(valid, 0, o_alive[:]))
            o_id[:] = jnp.where(valid, n_id, o_id[:])
            o_eval[:] = jnp.where(valid, n_eval, o_eval[:])
            o_vlen[:] = jnp.where(valid, n_vlen, o_vlen[:])
            o_event[:] = jnp.where(valid, n_event, o_event[:])
            o_start[:] = jnp.where(valid, n_start, o_start[:])
            o_branch[:] = jnp.where(valid, n_branch, o_branch[:])
            o_ver[:] = jnp.where(valid[None], n_ver, o_ver[:])
            o_agg[:] = jnp.where(valid[None], n_agg, o_agg[:])
            # Emission masking for padding steps.
            o_ostage[:] = jnp.where(valid[None, :, None, :], o_ostage[:], -1)
            o_ooff[:] = jnp.where(valid[None, :, None, :], o_ooff[:], -1)
            o_ocount[:] = jnp.where(valid[None], o_ocount[:], 0)

            # ---- phase 6 (lazy only): handle-ring append + root pin — the
            # in-kernel port of matcher.finish's lazy branch.  Completed
            # matches take consecutive ring slots in run-queue order; each
            # appended handle pins its root entry (refs +1) so no later
            # removal walk can delete the chain root before the out-of-kernel
            # drain pass unpins it.  Ring-full matches are dropped and
            # counted (handle_overflows — the loss-free contract's counter).
            if LAZY:
                fin_i = jnp.where(final_en, 1, 0)  # [R, L]
                frank = _cumsum0(fin_i) - 1
                dst = o_hrcount[:] + frank  # [R, L]
                fit = final_en & (dst < HB)
                iota_hb3 = jax.lax.broadcasted_iota(i32, (R, HB, L), 1)
                m3h = fit[:, None, :] & (iota_hb3 == dst[:, None, :])
                got = jnp.any(m3h, axis=0)  # [HB, L]

                def ring2(val_rl):  # [R, L] -> [HB, L] (masked pick)
                    return jnp.sum(jnp.where(m3h, val_rl[:, None, :], 0), axis=0)

                o_hrstage[:] = jnp.where(got, ring2(surv_id), o_hrstage[:])
                o_hroff[:] = jnp.where(got, off, o_hroff[:])
                o_hrvlen[:] = jnp.where(got, ring2(surv_vlen), o_hrvlen[:])
                o_hrts[:] = jnp.where(got, ts, o_hrts[:])
                o_hrseq[:] = jnp.where(got, seq_now, o_hrseq[:])
                iota_r = jax.lax.broadcasted_iota(i32, (R, L), 0)
                o_hrrow[:] = jnp.where(got, ring2(iota_r), o_hrrow[:])
                for k in range(D):
                    o_hrver[k] = jnp.where(
                        got, ring2(surv_ver[k]), o_hrver[k]
                    )
                o_hrcount[:] = o_hrcount[:] + jnp.sum(
                    jnp.where(fit, 1, 0), axis=0, keepdims=True
                )
                o_hovf[:] = o_hovf[:] + jnp.sum(
                    jnp.where(final_en & ~fit, 1, 0), axis=0, keepdims=True
                )
                pin = jnp.sum(
                    jnp.where(
                        (o_sstage[:][None, :, :] == surv_id[:, None, :])
                        & (o_soff[:][None, :, :] == off[None])
                        & fit[:, None, :],
                        1, 0,
                    ),
                    axis=0,
                )  # [E, L]
                o_srefs[:] = o_srefs[:] + pin

            # ---- promotion phase (tiered hybrid only): replay the prefix
            # chain's slab writes and append the suffix run — the in-kernel
            # port of engine/tiered.py build_promote, fused AFTER the engine
            # phases so a prefix completing at t first evaluates at t+1
            # (exactly the untiered run's schedule). ----
            if PROMO:
                p_offs = pr_offs[:][0]  # [PROMO, L]
                anchor = pr_anchor[:][0]  # [1, L]
                sver = pr_sver[:][0]  # [1, L]
                # Live runs are a contiguous prefix (phase 5 compaction just
                # ran), so the append row is the live count.
                pcnt = jnp.sum(
                    jnp.where(o_alive[:] != 0, 1, 0), axis=0, keepdims=True
                )  # [1, L]
                fit = fire_row & (pcnt < R)
                # Promoted Dewey version [v, 0, ..., 0] as [D, L] planes.
                pvr = jnp.concatenate(
                    [sver, jnp.zeros((D - 1, L), i32)], axis=0
                )
                if EO:
                    iota_eo2 = jax.lax.broadcasted_iota(i32, (EO, L), 0)

                # One put per prefix stage, at most one per lane per step —
                # each is the scalar slab op (ops/slab.py put_first / put)
                # as full-plane masked vector code, the same shapes as
                # phase 3's put_body but with a statically known chain.
                for j in range(PROMO):
                    first = j == 0
                    cur_s = i32(promo_idents[j])
                    off_j = p_offs[j:j + 1]  # [1, L]
                    if first:
                        en_ok = fit
                    else:
                        pst = i32(promo_idents[j - 1])
                        pof = p_offs[j - 1:j]
                        prev_hit = (o_sstage[:] == pst) & (o_soff[:] == pof)
                        prev_found = jnp.any(prev_hit, axis=0, keepdims=True)
                        o_ms[:] = o_ms[:] + jnp.where(
                            fit & ~prev_found, 1, 0
                        )
                        en_ok = fit & prev_found

                    cur_hit = (o_sstage[:] == cur_s) & (o_soff[:] == off_j)
                    exist = jnp.any(cur_hit, axis=0, keepdims=True)
                    want = en_ok & ~exist
                    free_h = o_sstage[0:EHk] < 0
                    any_fh = jnp.any(free_h, axis=0, keepdims=True)
                    ffs_h = jnp.min(
                        jnp.where(free_h, iota_eh, EHk), axis=0, keepdims=True
                    )
                    if EO:
                        # Inline two-tier allocation (ops/slab.py
                        # _alloc_slot): free hot slot first, else demote the
                        # min-offset (lowest index on ties) hot entry into
                        # the first free overflow slot and reuse its slot.
                        free_o = o_sstage[EHk:] < 0
                        any_fo = jnp.any(free_o, axis=0, keepdims=True)
                        ffs_o = jnp.min(
                            jnp.where(free_o, iota_eo2, EO), axis=0,
                            keepdims=True,
                        )
                        occ_h = o_sstage[0:EHk] >= 0
                        okey = jnp.where(
                            occ_h, o_soff[0:EHk], i32(1 << 30)
                        )
                        vkey = jnp.min(okey, axis=0, keepdims=True)
                        victim = jnp.min(
                            jnp.where(okey == vkey, iota_eh, EHk), axis=0,
                            keepdims=True,
                        )
                        demote = want & ~any_fh & any_fo
                        o_dm[:] = o_dm[:] + jnp.where(demote, 1, 0)
                        vm = (iota_eh == victim) & demote  # [EHk, L]
                        om = (iota_eo2 == ffs_o) & demote  # [EO, L]
                        vstage = jnp.sum(
                            jnp.where(vm, o_sstage[0:EHk], 0), axis=0,
                            keepdims=True,
                        )
                        voff = jnp.sum(
                            jnp.where(vm, o_soff[0:EHk], 0), axis=0,
                            keepdims=True,
                        )
                        vrefs = jnp.sum(
                            jnp.where(vm, o_srefs[0:EHk], 0), axis=0,
                            keepdims=True,
                        )
                        vnp = jnp.sum(
                            jnp.where(vm, o_snpreds[0:EHk], 0), axis=0,
                            keepdims=True,
                        )
                        vm3 = vm[:, None, :]
                        vps = jnp.sum(
                            jnp.where(vm3, o_spstage[0:EHk], 0), axis=0
                        )  # [MP, L]
                        vpo = jnp.sum(
                            jnp.where(vm3, o_spoff[0:EHk], 0), axis=0
                        )
                        vpl = jnp.sum(
                            jnp.where(vm3, o_spvlen[0:EHk], 0), axis=0
                        )
                        vpv = jnp.sum(
                            jnp.where(
                                vm[None, :, None, :], o_spver[:, 0:EHk], 0
                            ),
                            axis=1,
                        )  # [D, MP, L]
                        om3 = om[:, None, :]
                        o_sstage[EHk:] = jnp.where(om, vstage, o_sstage[EHk:])
                        o_soff[EHk:] = jnp.where(om, voff, o_soff[EHk:])
                        o_srefs[EHk:] = jnp.where(om, vrefs, o_srefs[EHk:])
                        o_snpreds[EHk:] = jnp.where(
                            om, vnp, o_snpreds[EHk:]
                        )
                        o_spstage[EHk:] = jnp.where(
                            om3, vps[None], o_spstage[EHk:]
                        )
                        o_spoff[EHk:] = jnp.where(
                            om3, vpo[None], o_spoff[EHk:]
                        )
                        o_spvlen[EHk:] = jnp.where(
                            om3, vpl[None], o_spvlen[EHk:]
                        )
                        o_spver[:, EHk:] = jnp.where(
                            om[None, :, None, :], vpv[:, None],
                            o_spver[:, EHk:],
                        )
                        o_sstage[0:EHk] = jnp.where(vm, -1, o_sstage[0:EHk])
                        o_soff[0:EHk] = jnp.where(vm, -1, o_soff[0:EHk])
                        alloc = jnp.where(any_fh, ffs_h, victim)
                        has_free = any_fh | any_fo
                    else:
                        alloc = ffs_h
                        has_free = ffs_h < EHk

                    tgt = (exist & cur_hit) | (~exist & (iota_e == alloc))
                    ok = en_ok & (exist | has_free)
                    o_fd[:] = o_fd[:] + jnp.where(
                        en_ok & ~exist & ~has_free, 1, 0
                    )
                    m1 = tgt & ok
                    # put_first overwrites (resets refs/npreds) even on an
                    # existing entry; put resets only on create.
                    reset = ok if first else ok & ~exist
                    np_e = jnp.sum(
                        jnp.where(m1, o_snpreds[:], 0), axis=0, keepdims=True
                    )
                    n_eff = jnp.where(reset, 0, np_e)
                    o_sstage[:] = jnp.where(m1, cur_s, o_sstage[:])
                    o_soff[:] = jnp.where(m1, off_j, o_soff[:])
                    o_srefs[:] = jnp.where(m1 & reset, 1, o_srefs[:])
                    pfull = ok & (n_eff >= MP)
                    o_pd[:] = o_pd[:] + jnp.where(pfull, 1, 0)
                    do = ok & ~pfull
                    slot = jnp.minimum(n_eff, MP - 1)
                    m2 = (
                        m1[:, None, :]
                        & (iota_mp3 == slot[:, None, :])
                        & do[:, None, :]
                    )
                    if first:
                        o_spstage[:] = jnp.where(m2, i32(-1), o_spstage[:])
                        o_spoff[:] = jnp.where(m2, i32(-1), o_spoff[:])
                    else:
                        o_spstage[:] = jnp.where(m2, pst, o_spstage[:])
                        o_spoff[:] = jnp.where(
                            m2, pof[:, None, :], o_spoff[:]
                        )
                    o_spvlen[:] = jnp.where(m2, i32(j + 1), o_spvlen[:])
                    o_spver[:] = jnp.where(
                        m2[None], pvr[:, None, None, :], o_spver[:]
                    )
                    o_snpreds[:] = jnp.where(
                        m1, n_eff + jnp.where(do, 1, 0), o_snpreds[:]
                    )

                # Suffix run append at the first free queue row.
                iota_r2 = jax.lax.broadcasted_iota(i32, (R, L), 0)
                row_m = (iota_r2 == pcnt) & fit  # [R, L]
                o_alive[:] = jnp.where(row_m, 1, o_alive[:])
                o_id[:] = jnp.where(
                    row_m, i32(promo_idents[PROMO - 1]), o_id[:]
                )
                o_eval[:] = jnp.where(row_m, i32(promo_eval), o_eval[:])
                o_vlen[:] = jnp.where(row_m, i32(PROMO), o_vlen[:])
                o_event[:] = jnp.where(
                    row_m, p_offs[PROMO - 1:PROMO], o_event[:]
                )
                o_start[:] = jnp.where(row_m, anchor, o_start[:])
                o_branch[:] = jnp.where(row_m, 0, o_branch[:])
                o_ver[:] = jnp.where(row_m[None], pvr[:, None, :], o_ver[:])
                o_agg[:] = jnp.where(row_m[None], inits_rl, o_agg[:])
                # Queue-full promotion = the run the untiered narrow queue
                # could not hold (engine/tiered.py run_drops semantics).
                o_rd[:] = o_rd[:] + jnp.where(fire_row & ~fit, 1, 0)
                o_promoted[:] = o_promoted[:] + jnp.where(fit, 1, 0)
        if PROMO:

            @pl.when(jnp.any(o_alive[:] != 0) | jnp.any(fire_row))
            def _():
                _engine_step()

        else:
            _engine_step()

    # ------------------------------------------------------------------
    # Host-side wrapper: layouts, specs, and the jitted entry point.
    # ------------------------------------------------------------------
    value_dtypes = None
    value_treedef = None

    def scan(state: EngineState, events: EventBatch, promo=None):
        nonlocal value_dtypes, value_treedef
        K = int(state.alive.shape[0])
        T = int(events.ts.shape[1])
        if K % LANE_BLOCK:
            raise ValueError(f"K={K} not a multiple of {LANE_BLOCK}")

        leaves, treedef = jax.tree_util.tree_flatten(events.value)
        value_treedef = treedef
        value_dtypes = [l.dtype for l in leaves]

        tin = lambda x: jnp.moveaxis(x, 0, -1)  # [K, ...] -> [..., K]
        tout = lambda x: jnp.moveaxis(x, -1, 0)
        row = lambda x: x[None, :]
        # [K, T] -> [T, 1, K]: the middle singleton keeps event blocks'
        # trailing dims (1, L) legal under the TPU (8, 128) tiling rule.
        tev = lambda x: jnp.swapaxes(x, 0, 1)[:, None, :]

        ins = [
            tin(state.alive.astype(jnp.int32)),
            tin(state.id_pos),
            tin(state.eval_pos),
            tin(state.vlen),
            tin(state.event_off),
            tin(state.start_ts),
            tin(state.branching.astype(jnp.int32)),
            jnp.transpose(state.agg, (2, 1, 0)),  # [K, R, NS] -> [NS, R, K]
            jnp.transpose(state.ver, (2, 1, 0)),  # [K, R, D] -> [D, R, K]
            tin(state.slab.stage),
            tin(state.slab.off),
            tin(state.slab.refs),
            tin(state.slab.npreds),
            tin(state.slab.pstage),
            tin(state.slab.poff),
            tin(state.slab.pvlen),
            jnp.transpose(state.slab.pver, (3, 1, 2, 0)),  # [D, E, MP, K]
            row(state.run_drops),
            row(state.ver_overflows),
            row(state.slab.full_drops),
            row(state.slab.pred_drops),
            row(state.slab.missing),
            row(state.slab.trunc),
            row(state.slab.hot_hits),
            row(state.slab.hot_misses),
            row(state.slab.overflow_walks),
            row(state.slab.demotions),
            row(state.slab.walk_hops),
            row(state.slab.extract_hops),
            row(state.slab.drain_hops),
            tin(state.hr_stage),
            tin(state.hr_off),
            tin(state.hr_vlen),
            tin(state.hr_ts),
            tin(state.hr_seq),
            tin(state.hr_row),
            jnp.transpose(state.hr_ver, (2, 1, 0)),  # [D, HB, K]
            row(state.hr_count),
            row(state.step_seq),
            row(state.handle_overflows),
        ]
        if SA:
            ins += [
                # [K, 4, S] -> [4, S, K] and [K, S] -> [S, K].
                jnp.transpose(state.stage_counts, (1, 2, 0)),
                tin(state.slab.stage_hops),
            ]
        ins += [
            tev(jnp.asarray(events.key, jnp.int32)),
            tev(jnp.asarray(events.ts, jnp.int32)),
            tev(jnp.asarray(events.off, jnp.int32)),
            tev(jnp.asarray(events.valid).astype(jnp.int32)),
            *[tev(jnp.asarray(l)) for l in leaves],
        ]
        if PROMO:
            # The stencil tier's promotion feed joins the event stream:
            # per-t blocks like the event slices, with the offs matrix
            # carrying its [p] axis as the block's middle dims.
            ins += [
                tev(jnp.asarray(promo.fire).astype(jnp.int32)),
                jnp.transpose(
                    jnp.asarray(promo.offs, jnp.int32), (1, 2, 0)
                ),  # [K, T, p] -> [T, p, K]
                tev(jnp.asarray(promo.anchor_ts, jnp.int32)),
                tev(jnp.asarray(promo.sver, jnp.int32)),
            ]

        grid = (K // LANE_BLOCK, T)

        def state_spec(shape):
            nd = len(shape)
            return pl.BlockSpec(
                shape[:-1] + (LANE_BLOCK,),
                (lambda i, t, nd=nd: (0,) * (nd - 1) + (i,)),
                memory_space=pltpu.VMEM,
            )

        def ev_spec(shape):
            # [T, ..., K]: block (1, ..., L) stepping the t axis — event
            # slices are [T, 1, K]; the promotion offs feed is [T, p, K].
            nd = len(shape)
            return pl.BlockSpec(
                (1,) + shape[1:-1] + (LANE_BLOCK,),
                (lambda i, t, nd=nd: (t,) + (0,) * (nd - 2) + (i,)),
                memory_space=pltpu.VMEM,
            )

        def out_t_spec(shape):
            nd = len(shape)
            return pl.BlockSpec(
                (1,) + shape[1:-1] + (LANE_BLOCK,),
                (lambda i, t, nd=nd: (t,) + (0,) * (nd - 2) + (i,)),
                memory_space=pltpu.VMEM,
            )

        # Inputs have n_sin state arrays; outputs additionally carry the
        # promotion-count accumulator (state-spec, no input analog).
        n_sin = 40 + (2 if SA else 0)
        n_state = n_sin + (1 if PROMO else 0)
        in_specs = (
            [state_spec(tuple(x.shape)) for x in ins[:n_sin]]
            + [ev_spec(tuple(x.shape)) for x in ins[n_sin:]]
        )

        f32_leaves = [
            np.dtype(d).kind == "f" for d in value_dtypes
        ]
        i32 = jnp.int32
        out_shapes = [
            jax.ShapeDtypeStruct((R, K), i32),  # alive
            jax.ShapeDtypeStruct((R, K), i32),  # id_pos
            jax.ShapeDtypeStruct((R, K), i32),  # eval_pos
            jax.ShapeDtypeStruct((R, K), i32),  # vlen
            jax.ShapeDtypeStruct((R, K), i32),  # event_off
            jax.ShapeDtypeStruct((R, K), i32),  # start_ts
            jax.ShapeDtypeStruct((R, K), i32),  # branching
            jax.ShapeDtypeStruct((NS, R, K), i32),  # agg
            jax.ShapeDtypeStruct((D, R, K), i32),  # ver
            jax.ShapeDtypeStruct((E, K), i32),  # slab stage
            jax.ShapeDtypeStruct((E, K), i32),  # slab off
            jax.ShapeDtypeStruct((E, K), i32),  # refs
            jax.ShapeDtypeStruct((E, K), i32),  # npreds
            jax.ShapeDtypeStruct((E, MP, K), i32),  # pstage
            jax.ShapeDtypeStruct((E, MP, K), i32),  # poff
            jax.ShapeDtypeStruct((E, MP, K), i32),  # pvlen
            jax.ShapeDtypeStruct((D, E, MP, K), i32),  # pver
            jax.ShapeDtypeStruct((1, K), i32),  # run_drops
            jax.ShapeDtypeStruct((1, K), i32),  # ver_overflows
            jax.ShapeDtypeStruct((1, K), i32),  # full_drops
            jax.ShapeDtypeStruct((1, K), i32),  # pred_drops
            jax.ShapeDtypeStruct((1, K), i32),  # missing
            jax.ShapeDtypeStruct((1, K), i32),  # trunc
            jax.ShapeDtypeStruct((1, K), i32),  # hot_hits
            jax.ShapeDtypeStruct((1, K), i32),  # hot_misses
            jax.ShapeDtypeStruct((1, K), i32),  # overflow_walks
            jax.ShapeDtypeStruct((1, K), i32),  # demotions
            jax.ShapeDtypeStruct((1, K), i32),  # walk_hops
            jax.ShapeDtypeStruct((1, K), i32),  # extract_hops
            jax.ShapeDtypeStruct((1, K), i32),  # drain_hops
            jax.ShapeDtypeStruct((HB, K), i32),  # hr_stage
            jax.ShapeDtypeStruct((HB, K), i32),  # hr_off
            jax.ShapeDtypeStruct((HB, K), i32),  # hr_vlen
            jax.ShapeDtypeStruct((HB, K), i32),  # hr_ts
            jax.ShapeDtypeStruct((HB, K), i32),  # hr_seq
            jax.ShapeDtypeStruct((HB, K), i32),  # hr_row
            jax.ShapeDtypeStruct((D, HB, K), i32),  # hr_ver
            jax.ShapeDtypeStruct((1, K), i32),  # hr_count
            jax.ShapeDtypeStruct((1, K), i32),  # step_seq
            jax.ShapeDtypeStruct((1, K), i32),  # handle_overflows
        ]
        if SA:
            out_shapes += [
                jax.ShapeDtypeStruct((4, SA, K), i32),  # stage_counts
                jax.ShapeDtypeStruct((SA, K), i32),  # stage_hops
            ]
        if PROMO:
            out_shapes += [
                jax.ShapeDtypeStruct((1, K), i32),  # promoted count
            ]
        out_shapes += [
            jax.ShapeDtypeStruct((T, R, W, K), i32),  # out stage
            jax.ShapeDtypeStruct((T, R, W, K), i32),  # out off
            jax.ShapeDtypeStruct((T, R, K), i32),  # out count
        ]
        out_specs = (
            [state_spec(tuple(s.shape)) for s in out_shapes[:n_state]]
            + [out_t_spec(tuple(s.shape)) for s in out_shapes[n_state:]]
        )
        scratch_shapes = []
        if EO:
            # Per-hop staging of the overflow tier's contribution (written
            # only under the miss branch, read in the combine).
            scratch_shapes = [
                pltpu.VMEM((1, LANE_BLOCK), jnp.int32),  # sc_found
                pltpu.VMEM((1, LANE_BLOCK), jnp.int32),  # sc_refs
                pltpu.VMEM((1, LANE_BLOCK), jnp.int32),  # sc_np
                pltpu.VMEM((MP, LANE_BLOCK), jnp.int32),  # sc_ps
                pltpu.VMEM((MP, LANE_BLOCK), jnp.int32),  # sc_po
                pltpu.VMEM((MP, LANE_BLOCK), jnp.int32),  # sc_pl
                pltpu.VMEM((D, MP, LANE_BLOCK), jnp.int32),  # sc_pv
            ]

        outs = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            compiler_params=_CompilerParams(
                vmem_limit_bytes=110 * 1024 * 1024,
                dimension_semantics=("parallel", "arbitrary"),
            ),
            scratch_shapes=scratch_shapes,
            interpret=scan.interpret,
        )(*ins)

        (n_alive, n_id, n_eval, n_vlen, n_event, n_start, n_branch, n_agg,
         n_ver, n_sstage, n_soff, n_srefs, n_snpreds, n_spstage, n_spoff,
         n_spvlen, n_spver, n_rd, n_vo, n_fd, n_pd, n_ms, n_tr,
         n_hh, n_hm, n_ow, n_dm, n_wh, n_eh, n_dh,
         n_hrstage, n_hroff, n_hrvlen, n_hrts, n_hrseq, n_hrrow, n_hrver,
         n_hrcount, n_seq, n_hovf) = outs[:40]
        if SA:
            n_stc = jnp.transpose(outs[40], (2, 0, 1))  # [K, 4, S]
            n_shp = jnp.moveaxis(outs[41], -1, 0)  # [K, S]
        else:
            n_stc = state.stage_counts
            n_shp = state.slab.stage_hops
        o_stage, o_off, o_count = outs[n_state:]

        unrow = lambda x: x[0]
        new_state = EngineState(
            alive=tout(n_alive).astype(bool),
            id_pos=tout(n_id),
            eval_pos=tout(n_eval),
            ver=jnp.transpose(n_ver, (2, 1, 0)),
            vlen=tout(n_vlen),
            event_off=tout(n_event),
            start_ts=tout(n_start),
            branching=tout(n_branch).astype(bool),
            agg=jnp.transpose(n_agg, (2, 1, 0)),
            slab=SlabState(
                stage=tout(n_sstage),
                off=tout(n_soff),
                refs=tout(n_srefs),
                npreds=tout(n_snpreds),
                pstage=tout(n_spstage),
                poff=tout(n_spoff),
                pvlen=tout(n_spvlen),
                pver=jnp.transpose(n_spver, (3, 1, 2, 0)),
                full_drops=unrow(n_fd),
                pred_drops=unrow(n_pd),
                missing=unrow(n_ms),
                trunc=unrow(n_tr),
                collisions=state.slab.collisions,  # sequential: none
                hot_hits=unrow(n_hh),
                hot_misses=unrow(n_hm),
                overflow_walks=unrow(n_ow),
                demotions=unrow(n_dm),
                walk_hops=unrow(n_wh),
                extract_hops=unrow(n_eh),
                drain_hops=unrow(n_dh),
                stage_hops=n_shp,
            ),
            run_drops=unrow(n_rd),
            ver_overflows=unrow(n_vo),
            hr_stage=tout(n_hrstage),
            hr_off=tout(n_hroff),
            hr_ver=jnp.transpose(n_hrver, (2, 1, 0)),
            hr_vlen=tout(n_hrvlen),
            hr_ts=tout(n_hrts),
            hr_seq=tout(n_hrseq),
            hr_row=tout(n_hrrow),
            hr_count=unrow(n_hrcount),
            step_seq=unrow(n_seq),
            handle_overflows=unrow(n_hovf),
            stage_counts=n_stc,
        )
        out = StepOutput(
            stage=jnp.transpose(o_stage, (3, 0, 1, 2)),  # [K, T, R, W]
            off=jnp.transpose(o_off, (3, 0, 1, 2)),
            count=jnp.transpose(o_count, (2, 0, 1)),
        )
        if PROMO:
            return new_state, out, unrow(outs[n_sin])  # promoted [K]
        return new_state, out

    scan.interpret = False
    return scan
