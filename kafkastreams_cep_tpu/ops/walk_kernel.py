"""Fused Pallas walk-pass kernel — the step's buffer walks in VMEM.

The walk pass (branch refcount walks ``KVSharedVersionedBuffer.java:99-110``,
dead-run removals ``:147-171``, final-match extraction ``NFA.java:111-115``)
is ~90% of the headline step in the jnp engine (PROFILE_r04.md): every hop of
its while-loop re-reads the packed pointer slab from HBM.  This kernel keeps
each lane-block's slab resident in VMEM across *all* hops of *all* walkers of
the step, reducing per-step slab HBM traffic to one read + one write.

Execution model
---------------
One grid program owns ``L`` lanes (lane axis last, width 128).  Walker
candidates arrive as a ``[PW]``-row queue per lane with a precomputed
queue-order ``rank``; the kernel loops ``b = 0..max(n_enabled)`` batches, and
in each batch every lane serves its rank-``b`` walker — **one walker per lane
at a time**, so per-lane buffer mutation order is *exactly* the reference's
sequential queue order (no lockstep merge argument needed), while the vector
unit parallelizes across the 128 lanes of the block.

Pointer prunes are physical (`TimedKeyValue.removePredecessor` shift-left),
applied immediately — again exactly the sequential semantics, affordable
because the arrays live in VMEM.

Semantics are differentially tested against the jnp pass
(``ops/slab.py: walks_compacted``) and, through it, against the sequential
per-op path and the host oracle (``tests/test_walk_kernel.py``,
``tests/test_engine_fuzz.py``).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kafkastreams_cep_tpu.ops.slab import SlabState

LANE_BLOCK = 128

# jax renamed TPUCompilerParams -> CompilerParams across the versions this
# engine runs on (laptop CI pins an older jaxlib than the TPU hosts).
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


def _cumsum0(x):
    """Inclusive prefix sum along axis 0 via log-shift adds — Mosaic has
    no cumsum lowering; log2(N) shifted adds of the [N, L] plane do."""
    n = x.shape[0]
    k = 1
    while k < n:
        pad = jnp.zeros((k,) + x.shape[1:], x.dtype)
        x = x + jnp.concatenate([pad, x[:-k]], axis=0)
        k *= 2
    return x


def _coalesced_demote(
    refs, p_en, p_first, p_cur, p_pst, p_pof, off_l,
    EHk: int, EO: int, MP: int, D: int,
):
    """One pass serving ALL of a step's hot→overflow demotions, plus the
    per-creation hot-slot claim map the put loop allocates from —
    replacing the per-put ``pl.when`` demotion (PROFILE_r06 "next
    leverage" item 2: hot-tier thrash at E_hot ≪ live entries paid one
    masked move pass per put).

    Sequential-equivalence argument: within one step every put targets
    the current event, so (a) predecessor lookups (strictly older events)
    and target-existence groups are fixed at step start, (b) each target
    group's FIRST enabled op is the only creator, (c) creations consume
    free hot slots in ascending index order (the sequential allocator's
    lowest-index-free rule) and then demote victims in ascending
    (event offset, index) order (its min-off rule — entries created this
    step carry the current, maximal offset, so victims always come from
    the step-start occupancy while E_hot ≥ the pattern's consuming-stage
    count, which the E_hot ≥ 8 floor guarantees for every compiled
    pattern here), with victim ``d`` landing in the ``d``-th free
    overflow slot.  All of that is computable up front, so the moves
    coalesce into one pass and the loop's allocation becomes a rank
    lookup.  Bit-exact parity with the per-op jnp path is pinned by
    ``tests/test_two_tier.py``.

    ``refs`` is ``(stage, off, refs, npreds, pstage, poff, pvlen, pver,
    dm)`` output refs (pver laid out ``[D, E, MP, L]``); ``p_*`` are the
    step's put-op planes ``[PP, L]`` (values, lane-last).  Returns
    ``(creator [PP, L] bool, crank [PP, L], claim [EHk, L], k_cap
    [1, L])``: creation-rank ``c`` allocates the slot with ``claim == c``
    and drops iff ``c >= k_cap``.
    """
    (o_stage, o_off, o_refs, o_npreds, o_pstage, o_poff, o_pvlen, o_pver,
     o_dm) = refs
    i32 = jnp.int32
    PP, L = p_cur.shape
    E = EHk + EO
    st0 = o_stage[:]
    of0 = o_off[:]

    # Per-op enablement and target existence, fixed at step start (puts
    # never delete; predecessors and targets cannot collide in-step).
    prev_found = jnp.any(
        (st0[None] == p_pst[:, None, :]) & (of0[None] == p_pof[:, None, :]),
        axis=1,
    )  # [PP, L]
    en_ok = p_en & (p_first | prev_found)
    exist0 = jnp.any(
        (st0[None] == p_cur[:, None, :]) & (of0[None] == off_l[None]),
        axis=1,
    )

    # Group (same target stage) first-enabled op = the creator.
    iota_p0 = jax.lax.broadcasted_iota(i32, (PP, PP, L), 0)
    iota_p1 = jax.lax.broadcasted_iota(i32, (PP, PP, L), 1)
    same = p_cur[None, :, :] == p_cur[:, None, :]
    earlier_en = same & (iota_p1 < iota_p0) & en_ok[None, :, :]
    creator = en_ok & ~jnp.any(earlier_en, axis=1) & ~exist0
    creator_i = jnp.where(creator, 1, 0)
    crank = _cumsum0(creator_i) - creator_i  # exclusive: creation rank
    n_create = jnp.sum(creator_i, axis=0, keepdims=True)  # [1, L]

    # Free-slot ranks and demotion victims.
    iota_eh = jax.lax.broadcasted_iota(i32, (EHk, L), 0)
    free_h = st0[0:EHk] < 0
    free_h_i = jnp.where(free_h, 1, 0)
    frank = _cumsum0(free_h_i) - free_h_i
    n_free_hot = jnp.sum(free_h_i, axis=0, keepdims=True)
    occ_h = ~free_h
    n_occ = jnp.sum(jnp.where(occ_h, 1, 0), axis=0, keepdims=True)
    iota_eo = jax.lax.broadcasted_iota(i32, (EO, L), 0)
    free_o = st0[EHk:] < 0
    free_o_i = jnp.where(free_o, 1, 0)
    orank = _cumsum0(free_o_i) - free_o_i
    n_free_ov = jnp.sum(free_o_i, axis=0, keepdims=True)
    k_cap = n_free_hot + n_free_ov

    # Victim rank: ascending (offset, index) among step-start occupied.
    of_h = of0[0:EHk]
    iota_a = jax.lax.broadcasted_iota(i32, (EHk, EHk, L), 0)
    iota_b = jax.lax.broadcasted_iota(i32, (EHk, EHk, L), 1)
    less = (of_h[None, :, :] < of_h[:, None, :]) | (
        (of_h[None, :, :] == of_h[:, None, :]) & (iota_b < iota_a)
    )
    vrank = jnp.sum(
        jnp.where(less & occ_h[None, :, :], 1, 0), axis=1
    )  # [EHk, L]

    n_demote = jnp.clip(
        n_create - n_free_hot, 0, jnp.minimum(n_free_ov, n_occ)
    )
    o_dm[:] = o_dm[:] + n_demote
    is_victim = occ_h & (vrank < n_demote)

    @pl.when(jnp.any(is_victim))
    def _():
        # Victim d -> d-th free overflow slot, ALL moves in one pass.
        mv = (
            is_victim[:, None, :]
            & free_o[None, :, :]
            & (vrank[:, None, :] == orank[None, :, :])
        )  # [EHk, EO, L]
        anym = jnp.any(mv, axis=0)  # [EO, L]

        def mv2(ref):
            v = jnp.sum(jnp.where(mv, ref[0:EHk][:, None, :], 0), axis=0)
            ref[EHk:] = jnp.where(anym, v, ref[EHk:])

        mv2(o_refs)
        mv2(o_npreds)

        def mv3(ref):
            v = jnp.sum(
                jnp.where(mv[:, :, None, :], ref[0:EHk][:, None], 0),
                axis=0,
            )  # [EO, MP, L]
            ref[EHk:] = jnp.where(anym[:, None, :], v, ref[EHk:])

        mv3(o_pstage)
        mv3(o_poff)
        mv3(o_pvlen)
        for d in range(D):
            v = jnp.sum(
                jnp.where(mv[:, :, None, :], o_pver[d, 0:EHk][:, None], 0),
                axis=0,
            )
            o_pver[d, EHk:] = jnp.where(anym[:, None, :], v, o_pver[d, EHk:])
        vst = jnp.sum(jnp.where(mv, o_stage[0:EHk][:, None, :], 0), axis=0)
        vof = jnp.sum(jnp.where(mv, o_off[0:EHk][:, None, :], 0), axis=0)
        o_stage[EHk:] = jnp.where(anym, vst, o_stage[EHk:])
        o_off[EHk:] = jnp.where(anym, vof, o_off[EHk:])
        o_stage[0:EHk] = jnp.where(is_victim, -1, o_stage[0:EHk])
        o_off[0:EHk] = jnp.where(is_victim, -1, o_off[0:EHk])

    # Claim map: creation rank c takes the c-th free hot slot (ascending
    # index), then victims in vrank order.
    BIG = jnp.int32(PP + E + 1)
    claim = jnp.where(free_h, frank, BIG)
    claim = jnp.where(is_victim, n_free_hot + vrank, claim)
    return creator, crank, claim, k_cap


def _kernel(
    # inputs (lane-last blocks)
    stage, off, refs, npreds, pstage, poff, pvlen, pver, missing, trunc,
    fulld, predd, hh, hm, ow, dm, wh, eh, dh,
    p_first, p_cur, p_pstage, p_poff, p_vlen, p_ver, p_rank, p_nen, ev_off,
    en, wstage, woff, wvlen, wver, wrem, wout, rank, nen,
    # the tail holds, in order: [shp] (stage-hop input, SA > 0 only), the
    # 22 outputs, [o_shp] (SA > 0 only), the two staging scratch buffers,
    # and the tier scratch (EH > 0 only) — unpacked by index below so the
    # attribution plumbing vanishes entirely when SA == 0.
    *rest,
    W: int, out_base: int, out_rows: int, with_puts: bool, EH: int,
    SA: int, drain: bool,
):
    i = 0
    if SA:
        shp = rest[i]
        i += 1
    (o_stage, o_off, o_refs, o_npreds, o_pstage, o_poff, o_pvlen, o_pver,
     o_missing, o_trunc, o_fulld, o_predd, o_hh, o_hm, o_ow, o_dm,
     o_wh, o_eh, o_dh,
     o_ostage, o_ooff, o_count) = rest[i:i + 22]
    i += 22
    if SA:
        o_shp = rest[i]
        i += 1
    st_stage, st_off = rest[i], rest[i + 1]
    tier_scratch = rest[i + 2:]
    E, MP, L = pstage.shape
    # pver blocks arrive [D, E, MP, L]: the tiled trailing dims are then
    # (MP=8-aligned, L) instead of (D, L) with D padded up to the sublane
    # tile — ~25% less VMEM traffic on the per-hop pointer-row reduce,
    # the kernel's dominant op.
    D = pver.shape[0]
    PW = en.shape[0]
    OR = out_rows
    i32 = jnp.int32
    # Two-tier layout (ops/slab.py "Two-tier layout" note): rows [0, EHk)
    # are the hot tier, [EHk, E) the overflow tier.  EH == 0 instantiates
    # the legacy single tier as EHk = E / EO = 0 — every overflow-side
    # block below is then skipped at trace time and the hot-side code IS
    # the original full-slab code.
    EHk = EH if EH else E
    EO = E - EHk
    if EO:
        (sc_found, sc_refs, sc_np, sc_ps, sc_po, sc_pl, sc_pv) = tier_scratch

    # Working state lives in the output refs (VMEM) for the whole pass.
    o_stage[:] = stage[:]
    o_off[:] = off[:]
    o_refs[:] = refs[:]
    o_npreds[:] = npreds[:]
    o_pstage[:] = pstage[:]
    o_poff[:] = poff[:]
    o_pvlen[:] = pvlen[:]
    o_pver[:] = pver[:]
    o_missing[:] = missing[:]
    o_trunc[:] = trunc[:]
    o_fulld[:] = fulld[:]
    o_predd[:] = predd[:]
    o_hh[:] = hh[:]
    o_hm[:] = hm[:]
    o_ow[:] = ow[:]
    o_dm[:] = dm[:]
    o_wh[:] = wh[:]
    o_eh[:] = eh[:]
    o_dh[:] = dh[:]
    if SA:
        o_shp[:] = shp[:]
        iota_sa = jax.lax.broadcasted_iota(i32, (SA, L), 0)
    o_ostage[:] = jnp.full((OR, W, L), -1, i32)
    o_ooff[:] = jnp.full((OR, W, L), -1, i32)
    o_count[:] = jnp.zeros((OR, L), i32)

    iota_pw = jax.lax.broadcasted_iota(i32, (PW, L), 0)
    iota_mp = jax.lax.broadcasted_iota(i32, (MP, L), 0)
    iota_mp3 = jax.lax.broadcasted_iota(i32, (E, MP, L), 1)
    iota_mp3h = jax.lax.broadcasted_iota(i32, (EHk, MP, L), 1)
    iota_d3 = jax.lax.broadcasted_iota(i32, (D, MP, L), 0)
    iota_or3 = jax.lax.broadcasted_iota(i32, (OR, W, L), 0)
    iota_w2 = jax.lax.broadcasted_iota(i32, (W, L), 0)
    iota_or2 = jax.lax.broadcasted_iota(i32, (OR, L), 0)
    iota_eh = jax.lax.broadcasted_iota(i32, (EHk, L), 0)
    if EO:
        iota_mp3o = jax.lax.broadcasted_iota(i32, (EO, MP, L), 1)

    # ---- consuming-put phase (reference order precedes all walks; one
    # put per lane per batch in queue-order rank = the sequential
    # semantics of slab.put / slab.put_first exactly) ----
    if with_puts:
        iota_e = jax.lax.broadcasted_iota(i32, (E, L), 0)
        max_pn = jnp.max(p_nen[0, :])
        if EO:
            # Coalesced demotion pre-pass: ALL of the step's hot→overflow
            # demotions in one move pass (not one pl.when per put), plus
            # the claim map the loop's allocation reads.
            creator_c, crank_c, claim_c, kcap_c = _coalesced_demote(
                (o_stage, o_off, o_refs, o_npreds, o_pstage, o_poff,
                 o_pvlen, o_pver, o_dm),
                p_rank[:] >= 0, p_first[:] != 0, p_cur[:],
                p_pstage[:], p_poff[:], ev_off[:],
                EHk=EHk, EO=EO, MP=MP, D=D,
            )

        def put_body(b):
            pselm = p_rank[:] == b  # [PP, L] — at most one True per lane
            en0 = jnp.any(pselm, axis=0, keepdims=True)  # [1, L]

            def ppick(f):
                return jnp.sum(jnp.where(pselm, f, 0), axis=0, keepdims=True)

            first = jnp.any(
                pselm & (p_first[:] != 0), axis=0, keepdims=True
            )
            cur = ppick(p_cur[:])
            pst = ppick(p_pstage[:])
            pof = ppick(p_poff[:])
            pvl = ppick(p_vlen[:])
            pvr = jnp.sum(
                jnp.where(pselm[None], p_ver[:], 0), axis=1
            )  # [D, L]
            off_l = ev_off[:]  # [1, L]

            # Chained puts need an existing predecessor entry
            # (KVSharedVersionedBuffer.java:86-89; counted miss here).
            prev_hit = (o_stage[:] == pst) & (o_off[:] == pof)
            prev_found = jnp.any(prev_hit, axis=0, keepdims=True)
            o_missing[:] = o_missing[:] + jnp.where(
                en0 & ~first & ~prev_found, 1, 0
            )
            en_ok = en0 & (first | prev_found)

            cur_hit = (o_stage[:] == cur) & (o_off[:] == off_l)  # [E, L]
            exist = jnp.any(cur_hit, axis=0, keepdims=True)
            # Two-tier allocation: demotions already ran in the coalesced
            # pre-pass, so allocation is a rank lookup into the claim map
            # (creation rank c -> the slot claiming c; c >= k_cap drops —
            # exactly the whole-slab-full condition).  EO == 0 keeps the
            # legacy first-free-slot scan verbatim.
            if EO:
                is_cr = jnp.any(
                    pselm & creator_c, axis=0, keepdims=True
                )  # [1, L] — this batch's op is its group's creator
                crk = ppick(crank_c)
                alloc_h = (claim_c == crk) & is_cr  # [EHk, L], <=1 True
                alloc = jnp.min(
                    jnp.where(alloc_h, iota_eh, E), axis=0, keepdims=True
                )
                # alloc < E guard: a creation past the start-occupied
                # victim pool would claim nothing (unreachable while
                # E_hot >= the pattern's consuming-stage count — the
                # E_hot >= 8 floor); the guard turns it into a counted
                # drop instead of a silent no-op write.
                has_free = is_cr & (crk < kcap_c) & (alloc < E)
            else:
                free_h = o_stage[:] < 0
                ffs_h = jnp.min(
                    jnp.where(free_h, iota_eh, EHk), axis=0, keepdims=True
                )
                alloc = ffs_h
                has_free = ffs_h < EHk
            # Boolean algebra, not where(): Mosaic can't select i1 vectors.
            tgt = (exist & cur_hit) | (~exist & (iota_e == alloc))  # [E, L]
            ok = en_ok & (exist | has_free)
            o_fulld[:] = o_fulld[:] + jnp.where(
                en_ok & ~exist & ~has_free, 1, 0
            )
            m1 = tgt & ok
            # put_first resets the entry (:117-128); creation initializes.
            reset = ok & (first | ~exist)
            o_stage[:] = jnp.where(m1, cur, o_stage[:])
            o_off[:] = jnp.where(m1, off_l, o_off[:])
            o_refs[:] = jnp.where(m1 & reset, 1, o_refs[:])
            np_e = jnp.sum(
                jnp.where(m1, o_npreds[:], 0), axis=0, keepdims=True
            )
            n_eff = jnp.where(reset, 0, np_e)  # [1, L]
            pfull = ok & (n_eff >= MP)
            o_predd[:] = o_predd[:] + jnp.where(pfull, 1, 0)
            do = ok & ~pfull
            slot = jnp.minimum(n_eff, MP - 1)
            m2 = (
                m1[:, None, :]
                & (iota_mp3 == slot[:, None, :])
                & do[:, None, :]
            )  # [E, MP, L]
            o_pstage[:] = jnp.where(
                m2, jnp.where(first, -1, pst)[:, None, :], o_pstage[:]
            )
            o_poff[:] = jnp.where(
                m2, jnp.where(first, -1, pof)[:, None, :], o_poff[:]
            )
            o_pvlen[:] = jnp.where(m2, pvl[:, None, :], o_pvlen[:])
            o_pver[:] = jnp.where(
                m2[None], pvr[:, None, None, :], o_pver[:]
            )
            o_npreds[:] = jnp.where(
                m1, n_eff + jnp.where(do, 1, 0), o_npreds[:]
            )
            return b + 1

        jax.lax.while_loop(
            lambda b: b < max_pn, put_body, jnp.zeros((), i32)
        )

    max_n = jnp.max(nen[0, :])

    def batch_body(b):
        selm = rank[:] == b  # [PW, L] — at most one True per lane
        act0 = jnp.any(selm, axis=0, keepdims=True)  # [1, L]

        def pick(f):  # [PW, L] -> [1, L]
            return jnp.sum(jnp.where(selm, f, 0), axis=0, keepdims=True)

        st_stage[:] = jnp.full((W, L), -1, i32)
        st_off[:] = jnp.full((W, L), -1, i32)
        ws = pick(wstage[:])
        wo = pick(woff[:])
        wvl = pick(wvlen[:])
        wrm = jnp.any(selm & (wrem[:] != 0), axis=0, keepdims=True)
        wot = jnp.any(selm & (wout[:] != 0), axis=0, keepdims=True)
        srow = pick(iota_pw - out_base)
        # wver arrives [D, PW, L] (same tile-exact layout as pver).
        qv0 = jnp.sum(
            jnp.where(selm[None, :, :], wver[:], 0), axis=1
        )  # [D, L]

        def hop_cond(c):
            h, active = c[0], c[1]
            return (h < W) & jnp.any(active != 0)

        def hop_body(c):
            h, active_i, cs, co, qv, ql, cnt = c
            active = active_i != 0
            # Walk-cost accounting (ops/slab.py _hop_counts): every active
            # walker's hop classified once, by walker class; the emit
            # class is static (drain pass vs eager extraction).
            emit_hop = jnp.where(active & wot, 1, 0)
            o_wh[:] = o_wh[:] + jnp.where(active & ~wot, 1, 0)
            if drain:
                o_dh[:] = o_dh[:] + emit_hop
            else:
                o_eh[:] = o_eh[:] + emit_hop
            if SA:
                # Per-stage hop attribution (ops/slab.py _hop_counts):
                # every active hop tallies at the walker's current stage.
                o_shp[:] = o_shp[:] + jnp.where(
                    (iota_sa == cs) & active, 1, 0
                )
            # Hot-tier lookup first: [EHk, L] compares instead of [E, L].
            # The overflow rows are consulted only when some lane of the
            # block missed hot — the common all-hot hop never touches them
            # (the E-linear -> E_hot-linear win of the two-tier layout).
            hit_h = (o_stage[0:EHk] == cs) & (o_off[0:EHk] == co)
            found_h = jnp.any(hit_h, axis=0, keepdims=True)  # [1, L]
            if EO:
                miss = active & ~found_h
                sc_found[:] = jnp.zeros((1, L), i32)
                sc_refs[:] = jnp.zeros((1, L), i32)
                sc_np[:] = jnp.zeros((1, L), i32)
                sc_ps[:] = jnp.zeros((MP, L), i32)
                sc_po[:] = jnp.zeros((MP, L), i32)
                sc_pl[:] = jnp.zeros((MP, L), i32)
                sc_pv[:] = jnp.zeros((D, MP, L), i32)

                @pl.when(jnp.any(miss))
                def _():
                    hit_o = (o_stage[EHk:] == cs) & (o_off[EHk:] == co)
                    hamo = hit_o & miss  # [EO, L]
                    sc_found[:] = jnp.where(
                        jnp.any(hamo, axis=0, keepdims=True), 1, 0
                    )
                    sc_refs[:] = jnp.sum(
                        jnp.where(hamo, o_refs[EHk:], 0),
                        axis=0, keepdims=True,
                    )
                    sc_np[:] = jnp.sum(
                        jnp.where(hamo, o_npreds[EHk:], 0),
                        axis=0, keepdims=True,
                    )
                    hamo3 = hamo[:, None, :]
                    sc_ps[:] = jnp.sum(
                        jnp.where(hamo3, o_pstage[EHk:], 0), axis=0
                    )
                    sc_po[:] = jnp.sum(
                        jnp.where(hamo3, o_poff[EHk:], 0), axis=0
                    )
                    sc_pl[:] = jnp.sum(
                        jnp.where(hamo3, o_pvlen[EHk:], 0), axis=0
                    )
                    sc_pv[:] = jnp.sum(
                        jnp.where(
                            hamo[None, :, None, :], o_pver[:, EHk:], 0
                        ),
                        axis=1,
                    )

                act_o = sc_found[:] != 0  # active walkers resolved overflow
                found = found_h | act_o
                o_hh[:] = o_hh[:] + jnp.where(active & found_h, 1, 0)
                o_hm[:] = o_hm[:] + jnp.where(miss, 1, 0)
                o_ow[:] = o_ow[:] + jnp.where(act_o, 1, 0)
            else:
                act_o = jnp.zeros((1, L), jnp.bool_)
                found = found_h
            o_missing[:] = o_missing[:] + jnp.where(active & ~found, 1, 0)
            active = active & found
            ham_h = hit_h & active  # [EHk, L] — <=1 True/lane (unique keys)

            refs_e = jnp.sum(
                jnp.where(ham_h, o_refs[0:EHk], 0), axis=0, keepdims=True
            )
            np_e = jnp.sum(
                jnp.where(ham_h, o_npreds[0:EHk], 0), axis=0, keepdims=True
            )
            if EO:
                # Per-lane sums pick the single hit entry, so the hot and
                # staged-overflow contributions are disjoint: add them.
                refs_e = refs_e + sc_refs[:]
                np_e = np_e + sc_np[:]
            # Remove-walkers decrement (floored at zero,
            # TimedKeyValue.java:59-61); branch walkers increment.
            newref = jnp.where(wrm, jnp.maximum(refs_e - 1, 0), refs_e + 1)
            o_refs[0:EHk] = jnp.where(ham_h, newref, o_refs[0:EHk])
            dele = active & wrm & (newref == 0) & (np_e <= 1)
            dmask = ham_h & dele
            o_stage[0:EHk] = jnp.where(dmask, -1, o_stage[0:EHk])
            o_off[0:EHk] = jnp.where(dmask, -1, o_off[0:EHk])

            # Emit the hop for extraction walkers into the per-batch [W, L]
            # staging buffer (scattering straight into the [OR, W, L] output
            # every hop costs OR/1 times the traffic).
            emit = active & wot
            mw = (iota_w2 == cnt) & emit
            st_stage[:] = jnp.where(mw, cs, st_stage[:])
            st_off[:] = jnp.where(mw, co, st_off[:])
            cnt = cnt + jnp.where(emit, 1, 0)

            # The hit entry's pointer rows (masked reduce over the hot rows
            # — the slab stays in VMEM, so this is pure vector work; the
            # overflow contribution was staged under the miss branch).
            ham3 = ham_h[:, None, :]
            ps_ = jnp.sum(jnp.where(ham3, o_pstage[0:EHk], 0), axis=0)
            po_ = jnp.sum(jnp.where(ham3, o_poff[0:EHk], 0), axis=0)
            pl_ = jnp.sum(jnp.where(ham3, o_pvlen[0:EHk], 0), axis=0)
            pv_ = jnp.sum(
                jnp.where(ham_h[None, :, None, :], o_pver[:, 0:EHk], 0),
                axis=1,
            )  # [D, MP, L]
            if EO:
                ps_ = ps_ + sc_ps[:]
                po_ = po_ + sc_po[:]
                pl_ = pl_ + sc_pl[:]
                pv_ = pv_ + sc_pv[:]
            live = iota_mp < np_e  # [MP, L]

            # dewey_ops.is_compatible vectorized over the MP pointers
            # (DeweyVersion.java:62-82).  Prefix checks count violations in
            # i32 — Mosaic cannot select on i1 vectors.
            neq = (qv[:, None, :] != pv_).astype(jnp.int32)  # [D, MP, L]
            plm = pl_[None, :, :]
            prefix_full = (
                jnp.sum(neq * (iota_d3 < plm).astype(jnp.int32), axis=0) == 0
            )
            prefix_butl = (
                jnp.sum(neq * (iota_d3 < plm - 1).astype(jnp.int32), axis=0)
                == 0
            )
            last_q = jnp.sum(
                jnp.where(iota_d3 == plm - 1, qv[:, None, :], 0), axis=0
            )
            last_p = jnp.sum(jnp.where(iota_d3 == plm - 1, pv_, 0), axis=0)
            ok = ((ql > pl_) & prefix_full) | (
                (ql == pl_) & prefix_butl & (last_q >= last_p)
            )
            ok = ok & live  # [MP, L]
            # First compatible pointer = masked min over slot index (Mosaic
            # argmax supports only f32; this is the spike-validated idiom).
            j = jnp.min(jnp.where(ok, iota_mp, MP), axis=0, keepdims=True)
            selany = j < MP  # [1, L]
            ohj = iota_mp == j  # [MP, L]

            # Physical prune of the traversed pointer when refs hit zero
            # (KVSharedVersionedBuffer.java:164-168): shift-left at
            # (entry, slots >= j), last slot keeping its own value
            # (TimedKeyValue.removePredecessor).
            prune = selany & active & wrm & (newref == 0)
            prune_h = prune & found_h

            @pl.when(jnp.any(prune_h))
            def _():
                pm = ham3 & (iota_mp3h >= j[None]) & prune_h[None]

                def shift(get, put, m, axis=1):
                    f = get()
                    nxt = jnp.concatenate(
                        [
                            jax.lax.slice_in_dim(f, 1, None, axis=axis),
                            jax.lax.slice_in_dim(f, -1, None, axis=axis),
                        ],
                        axis=axis,
                    )
                    put(jnp.where(m, nxt, f))

                def set_h(ref):
                    def put(v):
                        ref[0:EHk] = v
                    return put

                shift(lambda: o_pstage[0:EHk], set_h(o_pstage), pm)
                shift(lambda: o_poff[0:EHk], set_h(o_poff), pm)
                shift(lambda: o_pvlen[0:EHk], set_h(o_pvlen), pm)

                def put_pver(v):
                    o_pver[:, 0:EHk] = v

                shift(lambda: o_pver[:, 0:EHk], put_pver, pm[None], axis=2)
                o_npreds[0:EHk] = o_npreds[0:EHk] - jnp.where(
                    ham_h & prune_h, 1, 0
                )

            if EO:
                # One overflow-side mutation pass serves refs decrement,
                # delete, and prune for walkers resolved in the overflow
                # tier; recomputing the [EO, L] hit is cheaper than staging
                # [EO, ...] masks, and the pass is skipped whenever every
                # lane of the block resolved hot.
                @pl.when(jnp.any(act_o))
                def _():
                    hit_o = (o_stage[EHk:] == cs) & (o_off[EHk:] == co)
                    hamo = hit_o & act_o  # [EO, L]
                    o_refs[EHk:] = jnp.where(hamo, newref, o_refs[EHk:])
                    dmo = hamo & dele
                    o_stage[EHk:] = jnp.where(dmo, -1, o_stage[EHk:])
                    o_off[EHk:] = jnp.where(dmo, -1, o_off[EHk:])
                    prune_o = prune & act_o
                    pmo = (
                        hamo[:, None, :]
                        & (iota_mp3o >= j[None])
                        & prune_o[None]
                    )

                    def shift_o(get, put, m, axis=1):
                        f = get()
                        nxt = jnp.concatenate(
                            [
                                jax.lax.slice_in_dim(f, 1, None, axis=axis),
                                jax.lax.slice_in_dim(f, -1, None, axis=axis),
                            ],
                            axis=axis,
                        )
                        put(jnp.where(m, nxt, f))

                    def set_o(ref):
                        def put(v):
                            ref[EHk:] = v
                        return put

                    shift_o(lambda: o_pstage[EHk:], set_o(o_pstage), pmo)
                    shift_o(lambda: o_poff[EHk:], set_o(o_poff), pmo)
                    shift_o(lambda: o_pvlen[EHk:], set_o(o_pvlen), pmo)

                    def put_pver_o(v):
                        o_pver[:, EHk:] = v

                    shift_o(
                        lambda: o_pver[:, EHk:], put_pver_o, pmo[None],
                        axis=2,
                    )
                    o_npreds[EHk:] = o_npreds[EHk:] - jnp.where(
                        hamo & prune_o, 1, 0
                    )

            nxt_s = jnp.sum(jnp.where(ohj, ps_, 0), axis=0, keepdims=True)
            nxt_o = jnp.sum(jnp.where(ohj, po_, 0), axis=0, keepdims=True)
            nxt_l = jnp.sum(jnp.where(ohj, pl_, 0), axis=0, keepdims=True)
            nxt_v = jnp.sum(jnp.where(ohj[None], pv_, 0), axis=1)  # [D, L]

            nactive = active & selany & (nxt_s >= 0)
            # Extraction walkers get W emitting hops; cut beyond that is a
            # counted truncation (matches ops/slab.py walks_batched).
            budget_out = emit & (cnt >= W)
            o_trunc[:] = o_trunc[:] + jnp.where(budget_out & nactive, 1, 0)
            active = nactive & ~budget_out
            cs = jnp.where(active, nxt_s, cs)
            co = jnp.where(active, nxt_o, co)
            ql = jnp.where(active, nxt_l, ql)
            qv = jnp.where(active, nxt_v, qv)
            return h + 1, active.astype(jnp.int32), cs, co, qv, ql, cnt

        zero_l = jnp.zeros((1, L), i32)
        # Early exit matters: the average walk ends well before the W-hop
        # bound (a fixed-trip fori_loop measured ~2x slower end-to-end).
        h, active_i, cs, co, qv, ql, cnt = jax.lax.while_loop(
            hop_cond, hop_body,
            (jnp.zeros((), i32), act0.astype(i32), ws, wo, qv0, wvl, zero_l),
        )
        # Walkers still active at the hop bound were truncated.
        o_trunc[:] = o_trunc[:] + active_i
        # Served extraction walkers scatter their staged hops + hop count.
        mo = (iota_or3 == srow[None]) & wot[None]
        o_ostage[:] = jnp.where(mo, st_stage[:][None], o_ostage[:])
        o_ooff[:] = jnp.where(mo, st_off[:][None], o_ooff[:])
        cm = (iota_or2 == srow) & wot
        o_count[:] = jnp.where(cm, cnt, o_count[:])
        return b + 1

    jax.lax.while_loop(
        lambda b: b < max_n, batch_body, jnp.zeros((), i32)
    )


def _to_lane_last(x):
    """[K, ...] -> [..., K]."""
    return jnp.moveaxis(x, 0, -1)


def _from_lane_last(x):
    return jnp.moveaxis(x, -1, 0)


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_walk", "out_base", "out_rows", "interpret", "hot_entries",
        "drain",
    ),
)
def walk_pass_kernel(
    slab: SlabState,
    en,
    stage,
    off,
    ver,
    vlen,
    is_remove,
    want_out,
    max_walk: int,
    out_base: int,
    out_rows: int,
    interpret: bool = False,
    put_ops=None,
    ev_off=None,
    hot_entries: int = 0,
    drain: bool = False,
) -> Tuple[SlabState, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The step's walk pass for a ``[K]``-batched slab via the fused kernel.

    Same contract as ``jax.vmap`` of ``ops/slab.py: walks_compacted`` —
    ``K`` must be a multiple of 128.  Returns
    ``(slab, out_stage [K, out_rows, W], out_off, count [K, out_rows])``.

    With ``put_ops`` (a ``[K]``-batched :class:`ops.slab.PutOps`) and
    ``ev_off`` (``[K]`` current-event offsets), the step's consuming puts
    apply in-kernel BEFORE the walks — same contract as ``jax.vmap`` of
    ``puts_batched`` — so the slab crosses HBM once per step instead of
    twice.

    ``hot_entries`` > 0 enables the two-tier layout (ops/slab.py
    "Two-tier layout"): allocation prefers the hot rows (demoting the
    min-off hot entry when full), each hop's lookup/reduce runs over the
    hot rows, and the overflow rows are touched only under a block-level
    ``pl.when`` that skips when every lane of the block resolved hot —
    the common hop pays an E_hot-sized reduce instead of an E-sized one.
    Bit-exact (including the residency counters) with ``jax.vmap`` of the
    jnp path at the same ``hot_entries``.
    """
    i32 = jnp.int32
    K, E = slab.stage.shape
    MP = slab.pstage.shape[2]
    D = slab.pver.shape[3]
    PW = en.shape[1]
    W = max_walk
    OR = out_rows
    if K % LANE_BLOCK:
        raise ValueError(f"K={K} not a multiple of {LANE_BLOCK}")
    if hot_entries and (hot_entries % 8 or not 0 < hot_entries < E):
        raise ValueError(
            f"hot_entries={hot_entries} must be a multiple of 8 strictly "
            f"below slab_entries={E}"
        )

    en_i = en.astype(i32)
    rank = jnp.where(en, jnp.cumsum(en_i, axis=1) - 1, -1)

    tin = _to_lane_last
    tout = _from_lane_last
    row = lambda x: x[None, :]
    unrow = lambda x: x[0]

    nen = jnp.sum(en_i, axis=1)  # [K]

    with_puts = put_ops is not None
    if with_puts:
        p_en_i = jnp.asarray(put_ops.en).astype(i32)
        p_rank = jnp.where(put_ops.en, jnp.cumsum(p_en_i, axis=1) - 1, -1)
        put_ins = [
            tin(jnp.asarray(put_ops.first).astype(i32)),
            tin(jnp.asarray(put_ops.cur_stage, i32)),
            tin(jnp.asarray(put_ops.prev_stage, i32)),
            tin(jnp.asarray(put_ops.prev_off, i32)),
            tin(jnp.asarray(put_ops.vlen, i32)),
            jnp.transpose(jnp.asarray(put_ops.ver, i32), (2, 1, 0)),
            tin(p_rank),
            row(jnp.sum(p_en_i, axis=1)),
            row(jnp.asarray(ev_off, i32)),
        ]
    else:
        zc = jnp.zeros((1, K), i32)
        put_ins = [zc, zc, zc, zc, zc,
                   jnp.zeros((1, 1, K), i32), zc, zc, zc]

    ins = [
        tin(slab.stage),
        tin(slab.off),
        tin(slab.refs),
        tin(slab.npreds),
        tin(slab.pstage),
        tin(slab.poff),
        tin(slab.pvlen),
        # [K, E, MP, D] -> [D, E, MP, K]: tile-exact (MP, L) trailing dims.
        jnp.transpose(slab.pver, (3, 1, 2, 0)),
        # Per-lane scalar counters arrive as [K]; kernel blocks want [1, L].
        row(slab.missing),
        row(slab.trunc),
        row(slab.full_drops),
        row(slab.pred_drops),
        row(slab.hot_hits),
        row(slab.hot_misses),
        row(slab.overflow_walks),
        row(slab.demotions),
        row(slab.walk_hops),
        row(slab.extract_hops),
        row(slab.drain_hops),
        *put_ins,
        tin(en_i),
        tin(jnp.asarray(stage, i32)),
        tin(jnp.asarray(off, i32)),
        tin(jnp.asarray(vlen, i32)),
        # [K, PW, D] -> [D, PW, K] (tile-exact trailing dims).
        jnp.transpose(jnp.asarray(ver, i32), (2, 1, 0)),
        tin(jnp.asarray(is_remove).astype(i32)),
        tin(jnp.asarray(want_out).astype(i32)),
        tin(rank),
        row(nen),
    ]
    # Per-stage hop attribution rides only when enabled — SA == 0 adds no
    # input, no output, and no kernel ops (zero new device work).
    SA = int(slab.stage_hops.shape[-1])
    if SA:
        ins.append(tin(slab.stage_hops))  # [S, K]

    L = LANE_BLOCK
    grid = (K // L,)

    def bspec(shape):
        nd = len(shape)
        return pl.BlockSpec(
            shape[:-1] + (L,),
            (lambda i, nd=nd: (0,) * (nd - 1) + (i,)),
            memory_space=pltpu.VMEM,
        )

    in_specs = [bspec(tuple(x.shape[:-1]) + (L,)) for x in ins]
    out_shapes = [
        jax.ShapeDtypeStruct((E, K), i32),  # stage
        jax.ShapeDtypeStruct((E, K), i32),  # off
        jax.ShapeDtypeStruct((E, K), i32),  # refs
        jax.ShapeDtypeStruct((E, K), i32),  # npreds
        jax.ShapeDtypeStruct((E, MP, K), i32),  # pstage
        jax.ShapeDtypeStruct((E, MP, K), i32),  # poff
        jax.ShapeDtypeStruct((E, MP, K), i32),  # pvlen
        jax.ShapeDtypeStruct((D, E, MP, K), i32),  # pver
        jax.ShapeDtypeStruct((1, K), i32),  # missing
        jax.ShapeDtypeStruct((1, K), i32),  # trunc
        jax.ShapeDtypeStruct((1, K), i32),  # full_drops
        jax.ShapeDtypeStruct((1, K), i32),  # pred_drops
        jax.ShapeDtypeStruct((1, K), i32),  # hot_hits
        jax.ShapeDtypeStruct((1, K), i32),  # hot_misses
        jax.ShapeDtypeStruct((1, K), i32),  # overflow_walks
        jax.ShapeDtypeStruct((1, K), i32),  # demotions
        jax.ShapeDtypeStruct((1, K), i32),  # walk_hops
        jax.ShapeDtypeStruct((1, K), i32),  # extract_hops
        jax.ShapeDtypeStruct((1, K), i32),  # drain_hops
        jax.ShapeDtypeStruct((OR, W, K), i32),  # out_stage
        jax.ShapeDtypeStruct((OR, W, K), i32),  # out_off
        jax.ShapeDtypeStruct((OR, K), i32),  # count
    ]
    if SA:
        out_shapes.append(jax.ShapeDtypeStruct((SA, K), i32))  # stage_hops
    out_specs = [bspec(tuple(s.shape[:-1]) + (L,)) for s in out_shapes]

    scratch_shapes = [
        pltpu.VMEM((W, LANE_BLOCK), jnp.int32),
        pltpu.VMEM((W, LANE_BLOCK), jnp.int32),
    ]
    if hot_entries:
        # Per-hop staging of the overflow tier's contribution (written only
        # under the miss branch, read unconditionally in the combine).
        scratch_shapes += [
            pltpu.VMEM((1, LANE_BLOCK), jnp.int32),  # sc_found
            pltpu.VMEM((1, LANE_BLOCK), jnp.int32),  # sc_refs
            pltpu.VMEM((1, LANE_BLOCK), jnp.int32),  # sc_np
            pltpu.VMEM((MP, LANE_BLOCK), jnp.int32),  # sc_ps
            pltpu.VMEM((MP, LANE_BLOCK), jnp.int32),  # sc_po
            pltpu.VMEM((MP, LANE_BLOCK), jnp.int32),  # sc_pl
            pltpu.VMEM((D, MP, LANE_BLOCK), jnp.int32),  # sc_pv
        ]

    outs = pl.pallas_call(
        functools.partial(
            _kernel, W=W, out_base=out_base, out_rows=out_rows,
            with_puts=with_puts, EH=hot_entries, SA=SA, drain=drain,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )(*ins)

    (n_stage, n_off, n_refs, n_npreds, n_pstage, n_poff, n_pvlen, n_pver,
     n_missing, n_trunc, n_fulld, n_predd, n_hh, n_hm, n_ow, n_dm,
     n_wh, n_eh, n_dh,
     o_stage, o_off, o_count) = outs[:22]
    new_stage_hops = tout(outs[22]) if SA else slab.stage_hops
    new_slab = slab._replace(
        stage=tout(n_stage),
        off=tout(n_off),
        refs=tout(n_refs),
        npreds=tout(n_npreds),
        pstage=tout(n_pstage),
        poff=tout(n_poff),
        pvlen=tout(n_pvlen),
        pver=jnp.transpose(n_pver, (3, 1, 2, 0)),
        missing=unrow(n_missing),
        trunc=unrow(n_trunc),
        full_drops=unrow(n_fulld),
        pred_drops=unrow(n_predd),
        hot_hits=unrow(n_hh),
        hot_misses=unrow(n_hm),
        overflow_walks=unrow(n_ow),
        demotions=unrow(n_dm),
        walk_hops=unrow(n_wh),
        extract_hops=unrow(n_eh),
        drain_hops=unrow(n_dh),
        stage_hops=new_stage_hops,
    )
    return (
        new_slab,
        tout(o_stage),
        tout(o_off),
        tout(o_count),
    )
