"""Gather/scatter-free indexed access via one-hot masks.

On TPU, XLA lowers batched-traced-index scatters, gathers, and dynamic
slices to standalone kernels that do not fuse with surrounding elementwise
work; inside the engine's per-event step (thousands of tiny indexed ops in
sequential chains) the per-kernel overhead dominated runtime by ~50x and
scaled linearly with the vmapped lane count.  These helpers express the
same reads/writes as one-hot masked selects — pure elementwise ops the
compiler fuses into a handful of kernels per loop body.

All take traced scalar indices and vmap cleanly.  Out-of-range indices
select nothing (reads return 0 / writes drop), matching the engine's
"masked lane" convention.
"""

from __future__ import annotations

import jax.numpy as jnp


def oh(i, n: int) -> jnp.ndarray:
    """One-hot bool mask ``[n]`` for a traced scalar index ``i``."""
    return jnp.arange(n, dtype=jnp.int32) == i


def get_at(field: jnp.ndarray, i) -> jnp.ndarray:
    """``field[i]`` (leading axis) without a gather.

    Masks the leading axis and sums; exactly one row is selected, so values
    — including negatives — pass through, and bools round-trip via the
    final astype.
    """
    m = oh(i, field.shape[0]).reshape((-1,) + (1,) * (field.ndim - 1))
    return jnp.sum(jnp.where(m, field, 0), axis=0).astype(field.dtype)


def get_at2(field: jnp.ndarray, i, j) -> jnp.ndarray:
    """``field[i, j]`` for traced scalars, gather-free."""
    m = oh(i, field.shape[0])[:, None] & oh(j, field.shape[1])[None, :]
    m = m.reshape(m.shape + (1,) * (field.ndim - 2))
    return jnp.sum(jnp.where(m, field, 0), axis=(0, 1)).astype(field.dtype)


def put_at(field: jnp.ndarray, i, value, enable=True) -> jnp.ndarray:
    """``field.at[i].set(value)`` (leading axis) without a scatter."""
    m = oh(i, field.shape[0]) & enable
    m = m.reshape((-1,) + (1,) * (field.ndim - 1))
    return jnp.where(m, value, field)
