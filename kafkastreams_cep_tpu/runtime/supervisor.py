"""Failure detection & recovery — the rebalance/changelog-restore analog.

The reference delegates fault tolerance entirely to Kafka Streams (SURVEY
§5): every store is changelog-backed, so when a task dies the partition is
reassigned and the new owner replays the changelog to rebuild run queue,
buffer, and aggregate state (``CEPProcessor.java:117-134,144-149``).  The
library's own contribution is keeping *all* engine state store-resident so
that recovery is possible at every record boundary.

The TPU analog splits the same contract in two:

* **checkpoint** = the changelog snapshot: the supervisor persists the
  processor's full state (``runtime/checkpoint.py``) every
  ``checkpoint_every`` batches — far cheaper than the reference's
  every-record run-queue serialization (``CEPProcessor.java:158-160``),
  with the gap covered by a record journal;
* **journal + replay** = the changelog tail: records processed since the
  last checkpoint are kept host-side; on failure the supervisor restores
  the checkpoint and replays the journal, which is deterministic (the
  engine is a pure function of state × records), so the recovered
  processor lands in exactly the pre-failure state.

Failure *detection* covers what a lost Kafka Streams task would surface:
any exception out of the device dispatch (device reset, OOM, tunnel loss)
triggers recovery, and :meth:`Supervisor.health` exposes the engine's
overflow counters plus state-validity probes (NaN fold state, negative
refcounts) as a typed report — the counters exist precisely because
fixed-shape capacity overflow is this design's failure mode, with no
reference analog to inherit.

Matches replayed during recovery are suppressed (they were already
emitted), preserving exactly-once *emission* for everything the caller saw
before the failure — one better than the reference, whose at-least-once
replay duplicates and corrupts runs (``README.md:108``).

On a meshed processor (``mesh=`` kwarg) the same machinery covers **shard
failure**: a dead device (``ShardLost`` out of the dispatch, or a
``shard_probe`` report attached to any device error) triggers *evacuation*
— restore the last checkpoint and replay the journal onto the surviving
sub-mesh (``parallel.sharding.surviving_mesh``; lanes re-place through
``runtime.migrate.repartition_state``), pin the new assignment with an
immediate snapshot, and retry the batch degraded but exactly-once.
Straggler watermarks (:meth:`Supervisor.observe_shard_latency`, fed by the
deployment's per-host heartbeat) declare a lagging shard and evacuate it
at the next batch boundary; and at checkpoint boundaries the PR 6 per-key
heavy-hitter counters drive **hot-key rebalancing** — a pure lane
relabeling (``runtime.migrate.move_lanes``) that moves hot lanes off a
saturated shard with zero dropped or duplicated matches
(:class:`ShardPolicy` hysteresis keeps assignments from thrashing).
"""

from __future__ import annotations

import itertools
import os
import pickle
import tempfile
import time
from dataclasses import dataclass, field
from typing import Hashable, List, Optional, Sequence as Seq, Tuple

import numpy as np

from kafkastreams_cep_tpu.engine import sizing
from kafkastreams_cep_tpu.engine.matcher import EngineConfig
from kafkastreams_cep_tpu.engine.sizing import EscalationPolicy
from kafkastreams_cep_tpu.native.journal import Journal
from kafkastreams_cep_tpu.parallel.sharding import ShardLost, surviving_mesh
from kafkastreams_cep_tpu.runtime import checkpoint as ckpt_mod
from kafkastreams_cep_tpu.runtime import migrate as migrate_mod
from kafkastreams_cep_tpu.runtime.overload import (
    MAX_LEVEL as _OVERLOAD_MAX_LEVEL,
    OverloadController,
)
from kafkastreams_cep_tpu.runtime.processor import (
    CEPProcessor,
    InputRejected,
    Record,
)
from kafkastreams_cep_tpu.utils.events import Sequence
from kafkastreams_cep_tpu.utils.failpoints import fire as _failpoint
from kafkastreams_cep_tpu.utils.telemetry import (
    MetricsRegistry,
    maybe_span,
    positive_delta,
    timed_histogram,
)

from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime.supervisor")


@dataclass
class HealthReport:
    """One health probe of a live processor."""

    healthy: bool
    warnings: List[str] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)
    counters: dict = field(default_factory=dict)


def check_health(processor: CEPProcessor) -> HealthReport:
    """Probe a processor's engine state for capacity loss and corruption.

    *Warnings* are capacity-policy events (bounded-shape drops: runs, slab
    entries, pointer lists, Dewey width, walk length) — matching may have
    silently lost branches, which the reference (unbounded heap) never
    does; *errors* are states no healthy execution can reach (NaN fold
    state, negative refcounts) and indicate corruption.
    """
    counters = processor.counters()
    warnings = [
        f"{name}={val} capacity drops" for name, val in counters.items() if val
    ]
    errors = []
    # Fold state is typed-encoded int32 (float32 states as bit patterns,
    # engine/matcher.py); only float-typed columns can hold NaN.
    # Tiered processors wrap the engine state (engine/tiered.py).
    eng = getattr(processor.state, "engine", processor.state)
    agg = np.asarray(eng.agg)
    dtypes = processor.batch.matcher.tables.state_dtypes
    flt = [i for i, d in enumerate(dtypes) if d == "float32"]
    if flt and np.isnan(
        np.ascontiguousarray(agg[..., flt]).view(np.float32)
    ).any():
        errors.append("NaN in fold-aggregate state")
    refs = np.asarray(eng.slab.refs)
    if (refs < 0).any():
        errors.append("negative slab refcount")
    return HealthReport(
        healthy=not errors, warnings=warnings, errors=errors, counters=counters
    )


@dataclass
class ShardPolicy:
    """When a meshed supervisor declares a shard sick and when it moves
    lanes — both sides deliberately hysteretic, because evacuation and
    rebalancing each cost a restore-or-move plus a pinning snapshot and
    must not thrash on noise.

    Straggler side (fed by :meth:`Supervisor.observe_shard_latency`): a
    shard whose step-latency watermark (max over the last
    ``straggler_window`` observations) exceeds ``straggler_factor`` × the
    median of the other shards' watermarks on ``straggler_streak``
    consecutive observations is declared lagging; with
    ``evacuate_stragglers`` it is evacuated at the next batch boundary,
    exactly like a dead shard (the slow host may be dying — and even if
    not, the whole mesh steps at the straggler's pace).

    Skew side (checked at checkpoint boundaries from the per-lane hop
    deltas behind ``CEPProcessor.per_key_cost``): a boundary *trips* when
    the window saw at least ``rebalance_min_hops`` total hops and the
    hottest shard carried more than ``rebalance_skew`` × the mean
    per-shard load.  After ``rebalance_streak`` consecutive tripping
    boundaries (and at least ``rebalance_cooldown`` boundaries since the
    last move), hot lanes are re-spread greedily
    (``runtime.migrate.plan_rebalance``) and moved via
    ``runtime.migrate.move_lanes`` — a pure relabeling, so the stream
    sees no dropped or duplicated matches.
    """

    straggler_factor: float = 3.0
    straggler_window: int = 8
    straggler_streak: int = 3
    evacuate_stragglers: bool = True
    rebalance_skew: float = 2.0
    rebalance_min_hops: int = 64
    rebalance_streak: int = 2
    rebalance_cooldown: int = 1


@dataclass
class AdaptPolicy:
    """When the supervisor re-derives the execution plan from *measured*
    selectivity — adaptive recompilation (ISSUE 16 tentpole part 3), the
    loop that closes profiler → compiler.

    The compiler's lazy-chain conjunct ordering and tier split
    (``compiler/tiering.py``) are derived once, from hints or from
    whatever profile existed at build time.  A stream whose selectivity
    drifts (the cheap gate stops being selective) leaves that plan
    stale — correct, but doing the expensive conjunct's work first.  At
    every checkpoint boundary the supervisor compares the *windowed*
    per-stage (and per-conjunct, when ``stage_attribution`` tallies
    them) accept fraction against the selectivity the live plan was
    derived from; sustained drift triggers
    ``runtime.migrate.replan_processor`` — re-running
    ``apply_lazy_order``/``plan_tiering`` over the measured profile and
    swapping the processor in place.  Conjunct reordering commutes and
    the state transfers verbatim, so matches, emission order, and loss
    counters are invariant to the swap point (chaos-tested in
    tests/test_chaos.py).

    Hysteresis mirrors :class:`ShardPolicy`: a boundary *trips* when any
    tracked selectivity that saw at least ``min_evals`` windowed
    evaluations moved more than ``drift_threshold`` (absolute) from its
    plan-time value; ``replan_streak`` consecutive tripping boundaries
    (with ``cooldown`` boundaries since the last swap) fire the replan.
    A swap that fails (``replan.swap`` fault site) leaves the old
    processor and plan fully intact and counts in ``replan_failures``.
    """

    drift_threshold: float = 0.25
    min_evals: int = 256
    replan_streak: int = 2
    cooldown: int = 1


class Supervisor:
    """Checkpointing, health-probing, auto-recovering processor wrapper.

    ``pattern`` must be re-compilable user code (predicates/folds live in
    code, never in checkpoints — the ``ComputationStageSerDe`` contract);
    the supervisor owns the processor it creates.

    ``process(records)`` behaves like :meth:`CEPProcessor.process`, plus:

    * every ``checkpoint_every`` batches the full state is checkpointed
      (atomic rename, so a crash mid-write keeps the previous snapshot);
    * if the underlying processor raises, the supervisor restores the
      latest checkpoint, replays the journaled records since it
      (suppressing their already-emitted matches), retries the failing
      batch once, and counts the recovery in ``recoveries``;
    * with ``journal_path`` set, every batch is also appended to a durable
      CRC-framed on-disk journal (``native/journal.py``, C++ write path) —
      then :meth:`Supervisor.resume` recovers from a full *process* crash:
      restore the snapshot, replay the journal's intact prefix, continue.
      ``journal_sync=True`` fsyncs per batch (machine-crash durable);
    * with ``auto_escalate`` set (``True`` for the default
      :class:`~kafkastreams_cep_tpu.engine.sizing.EscalationPolicy`, or a
      policy instance), a batch that trips a capacity-loss counter is
      *rolled back* (checkpoint restore + journal replay), the live state
      is migrated onto a strictly-wider config (``runtime/migrate.py`` —
      a pure embedding, so nothing already matched changes), and the
      batch re-processes at the new width — its dropped branches are
      recovered, not warned about.  Escalations are counted in
      ``escalations``; a post-escalation snapshot pins the wide config so
      later recoveries and resumes replay at the new width.
    """

    _instance_ids = itertools.count()

    def __init__(
        self,
        pattern,
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        checkpoint_path: Optional[str] = None,
        checkpoint_every: int = 16,
        max_retries: int = 1,
        journal_path: Optional[str] = None,
        journal_sync: bool = False,
        auto_escalate=False,
        retry_backoff_ms: float = 50.0,
        retry_backoff_cap_ms: float = 5000.0,
        processor: Optional[CEPProcessor] = None,
        shard_policy: Optional[ShardPolicy] = None,
        shard_probe=None,
        adapt_policy=None,
        overload_policy=None,
        _resuming: bool = False,
        **proc_kwargs,
    ):
        if auto_escalate is True:
            self._policy: Optional[EscalationPolicy] = EscalationPolicy()
        elif auto_escalate:
            self._policy = auto_escalate
        else:
            self._policy = None
        self._pattern = pattern
        self._proc_kwargs = dict(proc_kwargs)
        # ``processor`` injection lets resume() hand over an
        # already-restored processor instead of building one to discard.
        self.processor = processor or CEPProcessor(
            pattern, num_lanes, config, **self._proc_kwargs
        )
        # Per-instance default path: two supervisors in one process must
        # never clobber each other's snapshots.
        self.checkpoint_path = checkpoint_path or os.path.join(
            tempfile.gettempdir(),
            f"cep_supervisor_{os.getpid()}_{next(self._instance_ids)}.ckpt",
        )
        self.checkpoint_every = int(checkpoint_every)
        self.max_retries = int(max_retries)
        # Exponential retry backoff with deterministic jitter: a device
        # fault that survives the instant retry is usually environmental
        # (reset storm, tunnel flap), and hammering it back-to-back turns
        # one fault into a fault train.  Jitter derives from (seq,
        # attempt) so a given retry always waits the same time —
        # reproducible chaos runs.  Tests patch ``self._sleep``.
        self.retry_backoff_ms = float(retry_backoff_ms)
        self.retry_backoff_cap_ms = float(retry_backoff_cap_ms)
        self.retry_backoff_ms_total = 0.0
        self._sleep = time.sleep
        self._journal: List[List[Record]] = []  # batches since last ckpt
        self._disk_journal = (
            Journal(journal_path, sync=journal_sync) if journal_path else None
        )
        if not _resuming:
            # A fresh supervisor starting over a previous incarnation's
            # files: that history would otherwise leak into a later
            # resume() — the old checkpoint (with its higher seq) would be
            # restored and the new run's journal frames skipped.  Starting
            # fresh declares the old history abandoned — remove both
            # loudly.  (To continue it, use Supervisor.resume.)
            if (
                self._disk_journal is not None
                and os.path.exists(journal_path)
                and os.path.getsize(journal_path) > 0
            ):
                logger.warning(
                    "journal %s holds frames from a previous run; truncating "
                    "(use Supervisor.resume to continue that history)",
                    journal_path,
                )
                self._disk_journal.truncate()
            if self._disk_journal is not None and os.path.exists(
                journal_path + ".prev"
            ):
                os.remove(journal_path + ".prev")
            if os.path.exists(self.checkpoint_path):
                logger.warning(
                    "checkpoint %s belongs to a previous run; removing "
                    "(use Supervisor.resume to continue that history)",
                    self.checkpoint_path,
                )
                os.remove(self.checkpoint_path)
            if os.path.exists(self.checkpoint_path + ".prev"):
                os.remove(self.checkpoint_path + ".prev")
        self._has_checkpoint = False
        self._batches_since_ckpt = 0
        # Monotone batch sequence number: stamped into journal frames and
        # the checkpoint header so resume() can tell which frames a
        # snapshot already contains (a crash between snapshot and journal
        # truncation must not double-replay them).
        self._seq = 0
        self.recoveries = 0
        self.checkpoints = 0
        self.checkpoint_failures = 0
        self.journal_failures = 0
        self.escalations = 0
        self.ingest_escalations = 0
        # Ingest-loss escalation baseline (guard counters are cumulative,
        # like the engine capacity counters).
        self._ingest_base: Optional[dict] = None
        # Escalation bookkeeping: capacity counters are cumulative, so
        # trips are detected on the per-batch DELTA against this snapshot
        # (refreshed after every batch / recovery / migration).
        self._counter_base: Optional[dict] = None
        self._trip_streak = 0
        # Matches flushed out of a pipelined processor by a checkpoint but
        # not yet returned to the caller (drained at the end of process();
        # survives a checkpoint-save failure so nothing is ever lost).
        self._unclaimed: List[Tuple[Hashable, Sequence]] = []
        # Mesh fault tolerance (module docstring): on by default whenever
        # the processor is meshed — a dead shard with no policy would be a
        # hard crash, which is strictly worse than degraded continuation.
        # Pass ``shard_policy=False`` to opt out explicitly.
        if shard_policy is False:
            self._shard_policy: Optional[ShardPolicy] = None
        elif shard_policy is not None:
            self._shard_policy = shard_policy
        else:
            self._shard_policy = (
                ShardPolicy() if self._mesh() is not None else None
            )
        # Optional deployment hook: zero-arg callable returning the shard
        # indices an external health source (host heartbeat, PCIe error
        # telemetry) currently believes dead.  Consulted when a dispatch
        # fails with a *generic* device error — ShardLost needs no probe.
        self._shard_probe = shard_probe
        self.evacuations = 0
        self.rebalances = 0
        self.rebalance_failures = 0
        self.lanes_moved = 0
        self.stragglers = 0
        # Straggler bookkeeping: recent step latencies per shard index,
        # consecutive over-watermark counts, and shards declared lagging
        # (evacuated at the next batch boundary).  All cleared on
        # evacuation — shard indices are renumbered by the shrink.
        self._shard_lat: dict = {}
        self._lag_streak: dict = {}
        self._lagging: set = set()
        # SLO burn rising-edge latch (see _slo_tick): one flight dump per
        # excursion over burn 1.0, not one per batch while burning.
        self._slo_burning = False
        # Rebalance hysteresis: per-lane hop baseline for the windowed
        # delta, consecutive tripping boundaries, boundaries since the
        # last move.
        self._hops_base: Optional[np.ndarray] = None
        self._rebalance_streak = 0
        self._boundaries_since_move = 10**9  # no cooldown before 1st move
        # Adaptive recompilation (AdaptPolicy): ``True`` takes the
        # defaults, a policy instance tunes the hysteresis, None/False
        # disables.  Only a tiered processor with ``stage_attribution``
        # produces the measured signal — the check is a boundary-time
        # no-op otherwise, so enabling it on any processor is harmless.
        if adapt_policy is True:
            self._adapt_policy: Optional[AdaptPolicy] = AdaptPolicy()
        elif adapt_policy:
            self._adapt_policy = adapt_policy
        else:
            self._adapt_policy = None
        self.replans = 0
        self.replan_failures = 0
        # Selectivity the LIVE plan was derived from ({key: fraction};
        # None until the first boundary with >= min_evals measured), and
        # the cumulative (evals, accepts) snapshot at the previous
        # boundary for the windowed delta.  Both reset on any rollback
        # rebuild (_restore_tail) — restored processors carry the
        # default plan and reverted counters.
        self._plan_sel: Optional[dict] = None
        self._sel_prev: Optional[dict] = None
        self._replan_streak = 0
        self._boundaries_since_replan = 10**9  # no cooldown before 1st
        # After a failed append the on-disk journal is no longer a complete
        # history — appending later batches would leave a seq gap that a
        # resume would replay straight through into a wrong state.  Suspend
        # journaling until the next checkpoint re-establishes a clean base.
        self._journal_suspended = False
        # Telemetry: the supervisor shares the processor's trace sink (pass
        # ``trace_sink=`` like any processor kwarg) and owns the lifecycle
        # latency histograms — checkpoint/recover/escalate cost as
        # p50/p99, not just the bare integers above.
        self.trace = self._proc_kwargs.get("trace_sink")
        self.telemetry = MetricsRegistry()
        for _n in ("checkpoint", "recover", "escalate", "evacuate",
                   "rebalance", "replan"):
            self.telemetry.histogram(f"phase.{_n}")
        # Flight recorder (runtime/flight.py): pass ``flight=`` like any
        # processor kwarg; the supervisor owns the dump triggers — crash
        # (retries exhausted), recovery, escalation — and re-attaches the
        # recorder across restore/migrate (restored processors carry no
        # telemetry wiring, same rule as the trace sink).
        self.flight = self._proc_kwargs.get("flight")
        if self.flight is not None:
            self.processor.flight = self.flight
        # Brownout ladder (runtime/overload.py): ``True`` takes the
        # default OverloadPolicy, a policy instance tunes
        # thresholds/actuators, None/False disables.  The controller is
        # supervisor-owned durable state: its level rides the checkpoint
        # header (``extra["overload"]``) and every transition is pinned
        # with an immediate snapshot, so recovery/resume/migration land
        # in the same level and replay under the same actuators.
        if overload_policy is True:
            self._overload: Optional[OverloadController] = (
                OverloadController()
            )
        elif overload_policy:
            self._overload = OverloadController(overload_policy)
        else:
            self._overload = None
        # Optional caller-owned admission front door (runtime/tenant.py
        # TenantAdmission, or a bare AdmissionLimiter) the L2 actuator
        # squeezes — see attach_admission().
        self._admission = None
        if self._overload is not None:
            self._overload.base_drain = self.processor.drain_interval
            self._overload_wire()

    @classmethod
    def resume(
        cls,
        pattern,
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        checkpoint_path: Optional[str] = None,
        journal_path: Optional[str] = None,
        **kwargs,
    ) -> "Supervisor":
        """Rebuild a supervisor after a process crash.

        Restores ``checkpoint_path`` if the file exists (else starts
        fresh), then replays the on-disk journal chain's intact prefix —
        deterministic, so the processor lands exactly where the crashed
        process left off; replayed matches are suppressed (the old process
        already emitted them).  Journal frames carry the batch sequence
        number, and frames at or below the checkpoint's sequence are
        skipped — so a crash *between* snapshotting and journal rotation
        cannot double-replay the snapshotted batches.

        A snapshot that fails its integrity check (``checkpoint.py``
        sha256 — bit rot, torn write) does not crash the resume: the
        previous-good ``.prev`` snapshot is restored instead (or a fresh
        processor when the corrupt one was the first), and the journal
        chain (``.prev`` frames + live frames, one generation retained
        per snapshot) replays the full gap.
        """
        proc = None
        base_seq = 0
        overload_state = None
        candidates = []
        if checkpoint_path:
            candidates = [
                p for p in (checkpoint_path, checkpoint_path + ".prev")
                if os.path.exists(p)
            ]
        for path in candidates:
            try:
                ckpt = ckpt_mod.load_checkpoint(path)
                proc = ckpt_mod.restore_processor(
                    pattern, path, ckpt=ckpt, mesh=kwargs.get("mesh"),
                )
                extra = ckpt["header"].get("extra", {})
                base_seq = int(extra.get("seq", 0))
                overload_state = extra.get("overload")
                break
            except ckpt_mod.CheckpointCorrupt:
                logger.exception(
                    "checkpoint %s is corrupt; falling back (journal-chain "
                    "replay covers the gap)", path,
                )
        sup = cls(
            pattern, num_lanes, config,
            checkpoint_path=checkpoint_path,
            journal_path=journal_path,
            processor=proc,
            _resuming=True,
            **kwargs,
        )
        sup._has_checkpoint = proc is not None
        sup._seq = base_seq
        # An injected (restored) processor carries no telemetry wiring.
        sup.processor.trace = sup.trace
        sup.processor.flight = sup.flight
        # The clock is wiring too (checkpoints carry no callables): a
        # pinned clock must keep ticking the restored guard and ledger —
        # without this the SLO tracker's burn-rate window (restored from
        # the checkpoint header) would observe wall-clock stamps against
        # pinned-clock history and the controller's input would be junk.
        clock = sup._proc_kwargs.get("clock")
        if clock is not None:
            sup.processor.set_clock(clock)
        # Load the pinned brownout level BEFORE the journal replay: every
        # journaled batch was processed at the pinned level (transitions
        # checkpoint immediately, truncating the journal), so replay must
        # run under the same actuators to shed the same records.
        if sup._overload is not None and overload_state:
            sup._overload.load_state(overload_state)
        sup._overload_wire()
        replayed = skipped = 0
        if sup._disk_journal is not None:
            # The chain: the retired ``.prev`` generation first (frames at
            # or below the LIVE snapshot's seq — needed only when that
            # snapshot was corrupt and the fallback rewound base_seq),
            # then the live journal.
            gap = False
            for jr in (
                Journal(journal_path + ".prev"), sup._disk_journal,
            ):
                for payload in jr.replay():
                    seq, batch = pickle.loads(payload)
                    if seq <= base_seq:
                        skipped += 1  # already inside the snapshot
                        continue
                    if seq != sup._seq + 1:
                        # Defense in depth: a seq gap means the journal is
                        # not a complete history (it should be impossible —
                        # a failed append suspends journaling).  Replaying
                        # past the gap would build a state that never saw
                        # the missing batches; stop at the last contiguous
                        # frame.
                        logger.error(
                            "journal seq gap (%d -> %d); stopping replay at "
                            "the last contiguous frame", sup._seq, seq,
                        )
                        gap = True
                        break
                    sup.processor.process(batch)  # matches already emitted
                    sup._overload_replay_tick()
                    sup._journal.append(batch)
                    sup._batches_since_ckpt += 1
                    sup._seq = seq
                    replayed += len(batch)
                if gap:
                    break
        # Pipelined replay leaves the last batch undecoded: drain it
        # (suppressed — the crashed process already emitted it) so it
        # cannot leak out of the first post-resume process() call.
        sup.processor.flush()
        if sup._policy is not None:
            sup._counter_base = sup._capacity_counters()
            sup._ingest_base = sup._ingest_loss_counters()
        logger.info(
            "resumed from %s + %s: %d journaled records replayed "
            "(%d pre-snapshot frames skipped)",
            checkpoint_path, journal_path, replayed, skipped,
        )
        return sup

    # -- checkpointing ------------------------------------------------------

    def checkpoint(self) -> List[Tuple[Hashable, Sequence]]:
        """Snapshot now (atomic) and truncate the journals.

        A pipelined processor is flushed first — a snapshot cannot carry
        an undecoded device batch (checkpoint.py refuses it), and before
        this flush every periodic snapshot of a ``pipeline=True``
        processor silently failed into ``checkpoint_failures``.  The
        flushed matches are returned (empty for serial processors); if
        the snapshot itself fails they are retained and the next
        :meth:`process` call returns them instead — flushing is
        observable emission and must never be dropped with the snapshot.
        """
        with maybe_span(self.trace, "checkpoint", seq=self._seq), \
                timed_histogram(self.telemetry, "phase.checkpoint"):
            if self.processor.pipeline:
                self._unclaimed.extend(self.processor.flush())
            tmp = self.checkpoint_path + ".tmp"
            extra = {"seq": self._seq}
            if self._overload is not None:
                extra["overload"] = self._overload.to_state()
            ckpt_mod.save_checkpoint(self.processor, tmp, extra=extra)
            # Fault site: the crash window between writing the tmp snapshot
            # and atomically installing it (utils/failpoints.py).
            _failpoint("checkpoint.rename")
            # One-generation retention: the outgoing snapshot survives as
            # ``.prev`` and the outgoing journal as ``.prev`` frames, so
            # a snapshot that later fails its integrity check (bit rot —
            # checkpoint.py sha256) can fall back to the previous-good
            # snapshot with the journal CHAIN covering the full gap.
            if os.path.exists(self.checkpoint_path):
                os.replace(
                    self.checkpoint_path, self.checkpoint_path + ".prev"
                )
            os.replace(tmp, self.checkpoint_path)
            self._has_checkpoint = True
            self._journal.clear()
            if self._disk_journal is not None:
                self._rotate_journal()
                self._journal_suspended = False  # clean base re-established
            self._batches_since_ckpt = 0
            self.checkpoints += 1
        return self._drain_unclaimed()

    def _rotate_journal(self) -> None:
        """Retire the journal's frames into ``.prev`` (all covered by the
        snapshot just installed; kept one generation for the corrupt-
        snapshot fallback) and start the live journal empty."""
        jr = self._disk_journal.path
        if os.path.exists(jr):
            os.replace(jr, jr + ".prev")
        else:
            # Nothing to retire, but a stale .prev from two checkpoints
            # ago must not linger past its snapshot.
            try:
                os.remove(jr + ".prev")
            except FileNotFoundError:
                pass

    def _drain_unclaimed(self) -> List[Tuple[Hashable, Sequence]]:
        out, self._unclaimed = self._unclaimed, []
        return out

    def drain_ingest(self) -> List[Tuple[Hashable, Sequence]]:
        """End-of-stream drain of the ingestion guard's reorder buffer,
        made durable: the drain dispatch is not journaled (it has no
        input batch a replay could reproduce), so the post-drain state is
        pinned with an immediate snapshot — a crash after this call
        resumes with the buffer empty and the drained matches already
        emitted, never double-emitted.  Terminal by convention: call when
        the stream is declared over."""
        matches = self.processor.drain_ingest()
        matches += self.processor.flush()
        try:
            matches = matches + self.checkpoint()
        except Exception:
            self.checkpoint_failures += 1
            logger.exception(
                "post-drain checkpoint failed; a resume will re-drain "
                "(the drained matches were already emitted — re-submit "
                "nothing, the journal still covers the pre-drain state)"
            )
        return matches

    # -- the supervised hot path -------------------------------------------

    def process(
        self, records: Seq[Record]
    ) -> List[Tuple[Hashable, Sequence]]:
        records = list(records)
        # Correlation id: the journal seq this batch WILL get on success.
        # Recovery/escalation spans fired while handling it carry the same
        # id, so a trace walks from a fault to the batch that provoked it.
        corr = f"batch-{self._seq + 1}"
        with maybe_span(
            self.trace, "supervisor.batch", corr=corr, seq=self._seq + 1,
            records=len(records),
        ) as sp:
            matches = self._process_supervised(records, corr)
            sp["matches"] = len(matches)
            return matches

    def _process_supervised(
        self, records: List[Record], corr: str
    ) -> List[Tuple[Hashable, Sequence]]:
        # Shards declared lagging by observe_shard_latency() are evacuated
        # at the batch boundary — before the dispatch, where the restore
        # and replay are cheapest and nothing is in flight.
        if (
            self._lagging
            and self._shard_policy is not None
            and self._shard_policy.evacuate_stragglers
        ):
            mesh = self._mesh()
            if mesh is not None and int(mesh.devices.size) > 1:
                lagging = sorted(self._lagging)
                logger.warning(
                    "evacuating lagging shard(s) %s at the batch boundary",
                    lagging,
                )
                self._evacuate(lagging, corr)
        for attempt in range(self.max_retries + 1):
            try:
                # Captured per attempt (a recovery resets the pipeline):
                # whether the batch before this one is still undecoded —
                # escalation must then recompute it too, since its matches
                # ride the lossy attempt's (discarded) return value.
                had_pending = (
                    getattr(self.processor, "_pending", None) is not None
                )
                matches = self.processor.process(records)
                break
            except InputRejected:
                # Deterministic input rejection (schema, lane overflow,
                # timestamp range): the batch is bad, not the device —
                # restore-and-replay cannot help and state was untouched
                # (processor validation is atomic).  Only the typed
                # exception short-circuits: JAX surfaces some real device
                # faults as bare ValueError, and those must recover.
                raise
            except ShardLost as e:
                # A typed shard loss out of the meshed dispatch: the
                # device is gone, so restore-and-replay onto the SAME mesh
                # (plain recovery) would re-dispatch straight into the
                # dead device.  Evacuate instead: shrink to the surviving
                # sub-mesh and retry there.  Unmeshed or single-device,
                # there is nothing to evacuate onto — crash.
                mesh = self._mesh()
                if (
                    mesh is None
                    or int(mesh.devices.size) < 2
                    or attempt >= self.max_retries
                ):
                    if self.flight is not None:
                        self.flight.dump("crash", corr=corr)
                    raise
                logger.exception(
                    "shard %d lost on a %d-record batch; evacuating onto "
                    "the surviving sub-mesh", e.shard, len(records),
                )
                self._evacuate([e.shard], corr)
                self._backoff(attempt)
            except Exception:
                if attempt >= self.max_retries:
                    # Crash: retries exhausted, the exception propagates
                    # to the caller — ship the last-N-batches context
                    # first so the post-mortem has it.
                    if self.flight is not None:
                        self.flight.dump("crash", corr=corr)
                    raise
                # A generic device error does not say WHICH device (JAX
                # surfaces resets as bare RuntimeError); ask the optional
                # external probe before falling back to same-mesh
                # recovery.
                dead = self._probe_dead_shards()
                if dead:
                    logger.exception(
                        "processor failed and the shard probe reports "
                        "shard(s) %s dead; evacuating", sorted(dead),
                    )
                    self._evacuate(dead, corr)
                else:
                    logger.exception(
                        "processor failed on a %d-record batch; recovering",
                        len(records),
                    )
                    self._recover(corr)
                self._backoff(attempt)
        if self._policy is not None:
            matches = self._maybe_escalate(records, matches, had_pending, corr)
        self._journal.append(records)
        self._seq += 1
        if self._disk_journal is not None:
            # Journal after success, before returning matches.  A process
            # crash in the tiny window before this append loses the batch
            # from recovery (the caller should re-submit unacknowledged
            # batches; replay dedup absorbs them); a crash after it replays
            # the batch with emissions suppressed.  Either way state and
            # the match stream stay consistent — the reference's Kafka
            # commit boundary has the same at-least-once window
            # (README.md:108), without the dedup.
            #
            # An append *failure* (disk full) must not raise here: state
            # already advanced, and a caller retry would double-apply the
            # batch.  Count it and SUSPEND journaling until the next
            # checkpoint — later frames after a missing seq would otherwise
            # replay into a state that never saw this batch.  The in-memory
            # journal still covers device-failure recovery; process-crash
            # durability is degraded until the next snapshot.
            if not self._journal_suspended:
                try:
                    self._disk_journal.append(
                        pickle.dumps((self._seq, records))
                    )
                except Exception:
                    self.journal_failures += 1
                    self._journal_suspended = True
                    logger.exception(
                        "journal append failed; journaling suspended until "
                        "the next checkpoint (batch %d+ not crash-durable)",
                        self._seq,
                    )
        self._batches_since_ckpt += 1
        # Overload/SLO observation BEFORE the cadence snapshot below: a
        # batch's tick must be pinned together with the batch itself, or
        # a crash landing right after the snapshot restores streaks that
        # are one observation behind the crash-free run — and since the
        # batch is inside the checkpoint it is never re-submitted, so the
        # lost tick can never be replayed (the ladder would then exit a
        # brownout level one batch late and shed records an uncrashed
        # run admits).  A transition taken here pins its own snapshot,
        # which also resets the cadence counter.
        self._slo_tick(corr)
        self._overload_tick(corr)
        # A suspended journal means acknowledged batches are NOT in the
        # crash history — don't wait out the cadence, close the window by
        # snapshotting immediately (a successful snapshot contains the
        # un-journaled batch and re-arms journaling).
        force_ckpt = self._journal_suspended
        if force_ckpt or self._batches_since_ckpt >= self.checkpoint_every:
            # Hot-key rebalance check BEFORE the snapshot: a move landing
            # here is immediately pinned by the checkpoint below, so every
            # recovery and resume replays under the new lane assignment.
            if self._shard_policy is not None:
                self._maybe_rebalance()
            # Adaptive replan check, same placement for the same reason:
            # a plan swap landing here is pinned by the snapshot below.
            if self._adapt_policy is not None:
                self._maybe_replan(corr)
            # A failed snapshot (disk full, ...) must not lose the batch's
            # matches: the journal still covers everything since the last
            # good snapshot, so log, count, and retry next batch.
            try:
                matches = matches + self.checkpoint()
            except Exception:
                self.checkpoint_failures += 1
                logger.exception("checkpoint failed; journal retained")
        if self._policy is not None:
            self._maybe_escalate_ingest()
        if self._unclaimed:
            # A failed snapshot above still flushed the pipeline; those
            # matches belong to the caller either way.
            matches = matches + self._drain_unclaimed()
        return matches

    def _backoff(self, attempt: int) -> None:
        """Sleep before re-dispatching a faulted batch: exponential in the
        attempt, capped, with deterministic jitter — ``(seq, attempt)``
        seeds the jitter so a replayed chaos schedule waits identically.
        ``retry_backoff_ms=0`` disables (the historical immediate retry).
        """
        if self.retry_backoff_ms <= 0:
            return
        delay_ms = min(
            self.retry_backoff_cap_ms,
            self.retry_backoff_ms * (2.0 ** attempt),
        )
        rng = np.random.default_rng((self._seq + 1, attempt))
        delay_ms *= 0.5 + 0.5 * float(rng.random())  # jitter in [0.5, 1.0)
        self.retry_backoff_ms_total += delay_ms
        logger.info(
            "retry backoff: %.1f ms before attempt %d", delay_ms, attempt + 2
        )
        self._sleep(delay_ms / 1000.0)

    def _restore_tail(self) -> int:
        """Restore the last checkpoint and replay the journal tail.

        Replay is deterministic, so the processor lands in exactly the
        state it had after the last successful batch; replayed matches are
        dropped (already emitted).  With no checkpoint yet, the journal is
        the full history and replay starts from a fresh processor.
        Shared by failure recovery and escalation rollback.
        """
        if self._has_checkpoint:
            try:
                self.processor = ckpt_mod.restore_processor(
                    self._pattern, self.checkpoint_path,
                    mesh=self._proc_kwargs.get("mesh"),
                )
            except ckpt_mod.CheckpointCorrupt:
                # Same fallback order as resume(): the previous-good
                # snapshot; the in-memory journal of a supervisor that
                # restored from .prev covers everything since it.
                logger.exception(
                    "checkpoint %s is corrupt during recovery; restoring "
                    "the previous-good snapshot", self.checkpoint_path,
                )
                self.processor = ckpt_mod.restore_processor(
                    self._pattern, self.checkpoint_path + ".prev",
                    mesh=self._proc_kwargs.get("mesh"),
                )
            # Checkpoints carry no telemetry wiring: reattach the trace
            # sink so post-recovery batches keep emitting spans.  The
            # clock is wiring too (checkpoints carry no callables) — a
            # pinned test clock must keep ticking the restored ledger.
            self.processor.trace = self.trace
            self.processor.flight = self.flight
            clock = self._proc_kwargs.get("clock")
            if clock is not None:
                self.processor.set_clock(clock)
        else:
            num_lanes = self.processor.num_lanes
            config = self.processor.batch.matcher.config
            self.processor = CEPProcessor(
                self._pattern, num_lanes, config, **self._proc_kwargs
            )
        # Re-wire the brownout actuators BEFORE the replay: every
        # journaled batch ran at the pinned level (transitions snapshot
        # immediately), so replay must shed under the same actuators.
        self._overload_wire()
        replayed = 0
        for batch in self._journal:
            self.processor.process(batch)  # matches already emitted
            replayed += len(batch)
        # Pipelined replay leaves the last batch undecoded; drain it here
        # (suppressed — already emitted) or it would leak into the next
        # real process() call as a duplicate emission.
        self.processor.flush()
        # Every rollback rebuild (recovery, evacuation, escalation) lands
        # on the checkpoint-restored processor, which carries the DEFAULT
        # execution plan and reverted attribution counters — the adaptive
        # replanner's plan baseline and window snapshot are both stale.
        self._plan_sel = None
        self._sel_prev = None
        self._replan_streak = 0
        return replayed

    def _observe_stall(
        self, cause: str, seconds: float, corr: Optional[str]
    ) -> None:
        """Attribute one lifecycle stall (recover/evacuate/replan wall
        time) to the latency ledger, tagged with the ``corr`` id of the
        batch the rollback was handling — a stall exemplar then resolves
        to the same trace span as the recovery span itself.  The live
        (post-rebuild) processor's ledger takes the observation: the
        pre-failure ledger rolled back with the state it described."""
        ledger = getattr(self.processor, "ledger", None)
        if ledger is not None:
            ledger.observe_stall(cause, seconds, corr=corr)

    def _slo_tick(self, corr: str) -> None:
        """Rising-edge SLO-burn annotation: when the ledger's burn rate
        first crosses 1.0 (burning faster than the error budget), note the
        rate in the flight ring and dump it — the post-mortem then carries
        the batches that spent the budget.  Re-arms when burn falls back
        under 1.0."""
        ledger = getattr(self.processor, "ledger", None)
        if ledger is None or ledger.slo is None:
            return
        burn = ledger.slo.burn_rate()
        if burn > 1.0 and not self._slo_burning:
            self._slo_burning = True
            logger.warning(
                "SLO burn rate %.3f exceeds budget (corr=%s)", burn, corr
            )
            if self.flight is not None:
                self.flight.note(slo_burn=round(burn, 3))
                self.flight.dump("slo_burn", corr=corr)
        elif burn <= 1.0 and self._slo_burning:
            self._slo_burning = False

    # -- overload control (runtime/overload.py) ------------------------------

    def attach_admission(self, admission) -> None:
        """Register the caller-owned tenant admission front door
        (runtime/tenant.py ``TenantAdmission``, or a bare
        ``AdmissionLimiter``) so the L2 actuator can squeeze its token
        buckets proportionally to measured tenant cost.  Idempotent —
        re-applies the current pinned pressure immediately, so callers
        re-attach after their own restore."""
        self._admission = admission
        self._overload_wire()

    def _overload_limiter(self):
        adm = self._admission
        if adm is None:
            return None
        return getattr(adm, "limiter", adm)

    def _overload_wire(self) -> None:
        """Re-apply the pinned level's actuators — after any processor
        rebuild or swap (restore, resume, migration, rebalance, replan)
        the new processor carries default actuators and must be re-wired
        before it processes (or replays) anything."""
        if self._overload is not None:
            self._overload_apply()

    def _overload_apply(self) -> None:
        ctl = self._overload
        proc = self.processor
        base = max(int(ctl.base_drain), 1)
        proc.drain_interval = max(1, base * ctl.drain_widen())
        proc.telemetry_defer = ctl.telemetry_defer()
        proc.overload_admit_fraction = ctl.admit_fraction()
        lim = self._overload_limiter()
        if lim is not None:
            scale, shares = ctl.admission_pressure
            lim.set_pressure(scale, shares)

    def _overload_signals(self) -> dict:
        """The pressure inputs, all host-side (no per-batch device
        reads): SLO burn rate, reorder hold depth/age, ingest-queue
        segment p99, and the deferred-drain backlog (the host proxy for
        handle-ring occupancy).  Missing subsystems contribute nothing —
        a processor without a guard or ledger reads pressure 0."""
        sig: dict = {}
        proc = self.processor
        guard = getattr(proc, "_guard", None)
        if guard is not None:
            depth = guard.policy.reorder_depth
            if depth:
                sig["hold_frac"] = guard.held / depth
            grace = guard.policy.grace_ms
            if grace > 0:
                sig["hold_age_frac"] = guard.hold_age_ms() / grace
        ledger = getattr(proc, "ledger", None)
        if ledger is not None:
            if ledger.slo is not None:
                sig["burn_rate"] = ledger.slo.burn_rate()
            hist = ledger._hists.get("queue")
            if hist is not None:
                sig["queue_p99_s"] = hist.percentile(0.99)
            sig["ring_depth"] = len(ledger._deferred)
        return sig

    def _overload_shares(self) -> dict:
        """Per-tenant cost shares from the heavy-hitter attribution
        (per_key_cost top list), mapped through the admission policy's
        key→tenant function — the L2 squeeze is proportional to measured
        cost, not record count.  One device gather, paid only on an L2+
        transition (never per batch)."""
        adm = self._admission
        if adm is None:
            return {}
        policy = getattr(adm, "policy", None)
        key_tenant = getattr(policy, "key_tenant", None) or str
        try:
            top = self.processor.per_key_cost().get("top") or []
        except Exception:
            logger.exception(
                "per-key cost attribution failed; squeezing all tenants "
                "uniformly"
            )
            return {}
        shares: dict = {}
        for row in top:
            tenant = str(key_tenant(row["key"]))
            shares[tenant] = shares.get(tenant, 0.0) + float(row["share"])
        return shares

    def _overload_replay_tick(self) -> None:
        """Advance the controller's observation streaks for one REPLAYED
        batch without taking transitions.  The crashed process ticked
        once per journaled batch after the last pin; a cold resume
        restores the PINNED streaks, so replay must re-run those
        observations or the resumed ladder would trail the crash-free
        trajectory by the journal window (holding a brownout level — and
        shedding — for extra batches an uncrashed run would not).  A
        transition cannot legitimately arise here: a committed
        transition pins a snapshot that truncates the journal, so every
        replayed batch was a no-transition tick in the original run.  A
        proposal (possible only from nondeterministic wall-clock
        signals) is deferred, not dropped — streaks are retained at
        threshold, so the first live batch re-proposes and commits it
        under the full transition protocol."""
        ctl = self._overload
        if ctl is None:
            return
        guard = getattr(self.processor, "_guard", None)
        if guard is not None:
            ctl.shed_total = guard.overload_shed
        ctl.tick(self._overload_signals())

    def _overload_tick(self, corr: str) -> None:
        """One controller observation per batch (after _slo_tick, before
        the unclaimed drain).  A proposal runs the transition protocol;
        no proposal costs a few host float compares."""
        ctl = self._overload
        if ctl is None:
            return
        guard = getattr(self.processor, "_guard", None)
        if guard is not None:
            ctl.shed_total = guard.overload_shed
        proposal = ctl.tick(self._overload_signals())
        if proposal is not None:
            self._overload_transition(proposal[0], proposal[1], corr)

    def _overload_transition(
        self, from_level: int, to_level: int, corr: str
    ) -> None:
        """The supervisor-owned transition protocol: failpoint →
        tentative level → actuators → pin checkpoint → commit.  ANY
        failure (armed failpoint, pin-snapshot failure) reverts level
        and actuators — the previous level stays authoritative, keeping
        the invariant that the in-memory level always equals the
        last-pinned level (so recovery replay never spans a
        transition)."""
        ctl = self._overload
        entering = to_level > from_level
        site = "overload.enter" if entering else "overload.exit"
        try:
            with maybe_span(
                self.trace, "overload.transition", corr=corr,
                from_level=from_level, to_level=to_level,
                pressure=round(ctl.last_pressure, 4),
            ):
                # Fault site: before actuators apply or the level pins —
                # a crash here must leave the previous level live.
                _failpoint(site)
                ctl.begin(to_level)
                scale = ctl.admission_scale(to_level)
                ctl.admission_pressure = (
                    float(scale),
                    dict(self._overload_shares()) if scale < 1.0 else {},
                )
                self._overload_apply()
                if entering and to_level >= _OVERLOAD_MAX_LEVEL:
                    # Emergency entry: flush pinned drains so the pin
                    # snapshot carries them.  Flushed matches are
                    # observable emission — they ride _unclaimed out.
                    self._unclaimed.extend(self.processor.flush())
                # Pin: the transition exists only once snapshotted — a
                # replayed crash must land in the same level.
                self._unclaimed.extend(self.checkpoint())
        except Exception:
            ctl.abort()
            self._overload_apply()
            logger.exception(
                "overload transition L%d -> L%d failed; L%d stays "
                "authoritative", from_level, to_level, from_level,
            )
            return
        ctl.commit()
        if self.flight is not None:
            self.flight.note(
                overload_level=to_level,
                overload_pressure=round(ctl.last_pressure, 4),
            )
            if entering and to_level >= 3:
                # L3+ entry is the incident boundary: ship the last-N
                # batches of context while the ring still holds the
                # flood that forced the shed.
                self.flight.dump("overload", corr=corr)

    def _recover(self, corr: Optional[str] = None) -> None:
        # ``corr`` correlates the recovery span with the batch span whose
        # failure provoked it (None when driven outside process(), e.g.
        # a manual probe); the restore-and-replay cost lands in the
        # ``recover`` latency histogram either way.
        if self.flight is not None:
            # Dump BEFORE the rollback: the ring still holds the faulted
            # batch's context (the restore rebuilds the processor, and
            # replayed batches would overwrite the interesting tail).
            self.flight.dump("recover", corr=corr)
        t0 = time.perf_counter()
        with maybe_span(
            self.trace, "recover", corr=corr, seq=self._seq,
        ) as sp, timed_histogram(self.telemetry, "phase.recover"):
            replayed = self._restore_tail()
            sp["replayed_records"] = replayed
            sp["from_checkpoint"] = self._has_checkpoint
        self._observe_stall("recover", time.perf_counter() - t0, corr)
        self.recoveries += 1
        # Counters reverted with the state; re-snapshot the escalation
        # baseline BEFORE the retry re-runs the failing batch, or its
        # delta would be measured against the pre-failure accumulation.
        if self._policy is not None:
            self._counter_base = self._capacity_counters()
            self._ingest_base = self._ingest_loss_counters()
        logger.info(
            "recovered: checkpoint=%s, %d journaled records replayed",
            self._has_checkpoint, replayed,
        )
        # The rebalance baseline indexes lanes in the *live* processor's
        # order; a rollback may precede the last move, so re-measure.
        self._hops_base = None

    # -- mesh fault tolerance ------------------------------------------------

    def _mesh(self):
        """The mesh the NEXT (re)built processor will land on — the
        ``mesh`` proc kwarg, which evacuation rewrites; falls back to the
        live processor's mesh for an injected (resumed) processor."""
        mesh = self._proc_kwargs.get("mesh")
        if mesh is None:
            mesh = getattr(self.processor, "mesh", None)
        return mesh

    def _probe_dead_shards(self) -> set:
        if self._shard_probe is None or self._shard_policy is None:
            return set()
        mesh = self._mesh()
        if mesh is None or int(mesh.devices.size) < 2:
            return set()
        try:
            return {int(s) for s in (self._shard_probe() or ())}
        except Exception:
            logger.exception("shard probe failed; treating as no report")
            return set()

    def _evacuate(self, dead, corr: Optional[str] = None) -> None:
        """Move the lost shard(s)' lanes onto the surviving sub-mesh.

        Same rollback spine as :meth:`_recover` — restore the last
        checkpoint and replay the journal tail, deterministic and
        emission-suppressed — but the rebuilt processor is placed on
        ``surviving_mesh(mesh, dead)`` (``_proc_kwargs["mesh"]`` is
        rewritten first, so ``_restore_tail`` and every later rebuild
        land there; ``checkpoint.restore_processor`` routes the lane
        re-placement through ``migrate.repartition_state``).  The shrunk
        assignment is pinned with an immediate snapshot: a recovery or
        resume between here and the next periodic snapshot must not
        re-place lanes on the dead device.  Processing continues
        *degraded* — fewer devices, same lanes, exactly-once emission.
        """
        mesh = self._mesh()
        dead = sorted({int(d) for d in dead})
        new_mesh = surviving_mesh(mesh, dead, self.processor.num_lanes)
        if self.flight is not None:
            self.flight.note(
                evacuation=self.evacuations + 1, dead_shards=dead
            )
            self.flight.dump("evacuate", corr=corr)
        t0 = time.perf_counter()
        with maybe_span(
            self.trace, "evacuate", corr=corr, seq=self._seq,
            dead_shards=dead, survivors=int(new_mesh.devices.size),
        ) as sp, timed_histogram(self.telemetry, "phase.evacuate"):
            self._proc_kwargs["mesh"] = new_mesh
            replayed = self._restore_tail()
            sp["replayed_records"] = replayed
            sp["from_checkpoint"] = self._has_checkpoint
            try:
                self._unclaimed.extend(self.checkpoint())
            except Exception:
                self.checkpoint_failures += 1
                logger.exception(
                    "post-evacuation checkpoint failed; a resume before "
                    "the next good snapshot re-places lanes itself "
                    "(restore_processor repartitions on mesh-size change)"
                )
        self._observe_stall("evacuate", time.perf_counter() - t0, corr)
        self.evacuations += 1
        # Shard indices are renumbered by the shrink: every piece of
        # straggler and skew bookkeeping keyed by the old numbering is
        # meaningless now.
        self._shard_lat.clear()
        self._lag_streak.clear()
        self._lagging.clear()
        self._hops_base = None
        if self._policy is not None:
            self._counter_base = self._capacity_counters()
            self._ingest_base = self._ingest_loss_counters()
        logger.warning(
            "shard(s) %s evacuated: %d lanes now on %d device(s), "
            "%d journaled records replayed (degraded but exactly-once)",
            dead, self.processor.num_lanes, int(new_mesh.devices.size),
            replayed,
        )

    def observe_shard_latency(self, shard: int, seconds: float) -> bool:
        """Feed one shard's step-latency watermark (per-host heartbeat in
        a real deployment; the bench and chaos harness call it directly).

        A shard whose watermark — max over the last
        ``ShardPolicy.straggler_window`` observations — exceeds
        ``straggler_factor`` × the median of the other shards' watermarks
        on ``straggler_streak`` consecutive observations is declared
        lagging.  Returns True when ``shard`` is currently declared; with
        ``evacuate_stragglers`` the declaration triggers evacuation at
        the next batch boundary.
        """
        policy = self._shard_policy
        if policy is None:
            return False
        shard = int(shard)
        lat = self._shard_lat.setdefault(shard, [])
        lat.append(float(seconds))
        del lat[: -int(policy.straggler_window)]
        others = [
            max(v) for s, v in self._shard_lat.items() if s != shard and v
        ]
        if not others:
            return shard in self._lagging
        med = float(np.median(others))
        if med > 0.0 and max(lat) > policy.straggler_factor * med:
            self._lag_streak[shard] = self._lag_streak.get(shard, 0) + 1
        else:
            self._lag_streak[shard] = 0
        if (
            self._lag_streak[shard] >= policy.straggler_streak
            and shard not in self._lagging
        ):
            self._lagging.add(shard)
            self.stragglers += 1
            if self.trace is not None:
                self.trace.event(
                    "straggler", shard=shard, watermark_s=max(lat),
                    peer_median_s=med,
                )
            logger.warning(
                "shard %d declared lagging (watermark %.4fs vs peer "
                "median %.4fs); evacuation at the next batch boundary",
                shard, max(lat), med,
            )
        return shard in self._lagging

    def _maybe_rebalance(self) -> None:
        """Move hot lanes off a saturated shard at a checkpoint boundary.

        The signal is the windowed per-lane hop DELTA (walk + extract +
        drain — the counters behind ``CEPProcessor.per_key_cost``) since
        the last boundary: cumulative totals would forever punish a key
        that was hot an hour ago.  Trip + streak + cooldown hysteresis
        per :class:`ShardPolicy`; the move itself is
        ``migrate.move_lanes`` with the greedy ``plan_rebalance``
        permutation — a pure relabeling, pinned by the checkpoint that
        immediately follows in ``_process_supervised``.  A move that
        fails (``rebalance.move`` fault site) leaves the old processor
        and assignment fully intact.
        """
        policy = self._shard_policy
        mesh = self._mesh()
        if policy is None or mesh is None:
            return
        n = int(mesh.devices.size)
        k = self.processor.num_lanes
        if n < 2 or k % n != 0:
            return
        self._boundaries_since_move += 1
        arrays = {
            name: np.asarray(vals, dtype=np.int64).reshape(-1)
            for name, vals in self.processor.batch.per_lane_counters(
                self.processor.state
            ).items()
            if name in ("walk_hops", "extract_hops", "drain_hops")
        }
        if not arrays:
            return
        hops = sum(arrays.values())
        base = self._hops_base
        if base is None or base.shape != hops.shape:
            self._hops_base = hops
            self._rebalance_streak = 0
            return
        window = hops - base
        self._hops_base = hops
        total = int(window.sum())
        shard_loads = window.reshape(n, k // n).sum(axis=1)
        mean = total / n
        tripped = (
            total >= policy.rebalance_min_hops
            and float(shard_loads.max()) > policy.rebalance_skew * mean
        )
        if not tripped:
            self._rebalance_streak = 0
            return
        self._rebalance_streak += 1
        if (
            self._rebalance_streak < policy.rebalance_streak
            or self._boundaries_since_move <= policy.rebalance_cooldown
        ):
            return
        perm = migrate_mod.plan_rebalance(window, n)
        if perm is None:
            self._rebalance_streak = 0
            return
        # The PR 6 heavy-hitter attribution over the same window names
        # the keys being moved — operator-facing (span + log), the
        # decision above is already made from the identical arrays.
        hot = self.processor.per_key_cost(
            top_k=4,
            per_lane_arrays={
                "walk_hops": window,
                "extract_hops": np.zeros_like(window),
                "drain_hops": np.zeros_like(window),
            },
        )
        moved = int(np.sum(perm != np.arange(k)))
        with maybe_span(
            self.trace, "rebalance", seq=self._seq, lanes_moved=moved,
            hot_keys=[h["key"] for h in hot["top"]],
            shard_loads=[int(x) for x in shard_loads],
        ), timed_histogram(self.telemetry, "phase.rebalance"):
            if self.processor.pipeline:
                # An undecoded device batch cannot be permuted host-side;
                # flushing is observable emission, kept for the caller.
                self._unclaimed.extend(self.processor.flush())
            try:
                self.processor = migrate_mod.move_lanes(
                    self._pattern, self.processor, perm, mesh=mesh
                )
            except Exception:
                self.rebalance_failures += 1
                # move_lanes mutates nothing before it succeeds — the old
                # processor and lane assignment are intact; skip this
                # boundary and re-measure (the baseline still indexes the
                # unmoved lane order).
                logger.exception(
                    "lane rebalance failed; keeping the current assignment"
                )
                return
            self.processor.trace = self.trace
            self.processor.flight = self.flight
            self._overload_wire()
            self.rebalances += 1
            self.lanes_moved += moved
            # The baseline must follow its lanes to the new positions.
            self._hops_base = hops[perm]
            self._rebalance_streak = 0
            self._boundaries_since_move = 0
        logger.warning(
            "hot-key rebalance #%d: moved %d lanes (window loads per "
            "shard %s; hottest keys %s)",
            self.rebalances, moved,
            [int(x) for x in shard_loads],
            [h["key"] for h in hot["top"]],
        )

    # -- adaptive recompilation ---------------------------------------------

    @staticmethod
    def _sel_counts(per_stage: dict) -> dict:
        """Flatten a ``stage_counters`` snapshot into cumulative
        ``{key: (evals, accepts)}`` rows — one ``(stage,)`` row per stage
        and one ``(stage, conjunct_key)`` row per measured conjunct (the
        exact selectivities ``apply_lazy_order`` would rank by)."""
        counts: dict = {}
        for name, row in per_stage.items():
            if not isinstance(row, dict):
                continue
            counts[(name,)] = (
                int(row.get("stage_evals", 0) or 0),
                int(row.get("stage_accepts", 0) or 0),
            )
            cj = row.get("conjuncts")
            if isinstance(cj, dict):
                for key, crow in cj.items():
                    if isinstance(crow, dict):
                        counts[(name, key)] = (
                            int(crow.get("evals", 0) or 0),
                            int(crow.get("accepts", 0) or 0),
                        )
        return counts

    def _maybe_replan(self, corr: Optional[str] = None) -> None:
        """Swap the processor onto a re-derived execution plan when the
        measured selectivity has drifted from the plan's assumptions.

        Runs at checkpoint boundaries only (see :class:`AdaptPolicy` for
        the signal and hysteresis).  The swap is
        ``migrate.replan_processor`` — config unchanged, state verbatim,
        matches/emission order/loss counters invariant — and is pinned by
        the checkpoint that immediately follows in
        ``_process_supervised``, so recoveries and resumes replay under a
        *consistent* plan either side of the boundary.  A failed swap
        (``replan.swap`` fault site) keeps the old processor and plan.
        """
        policy = self._adapt_policy
        if policy is None:
            return
        config = self.processor.batch.matcher.config
        if not getattr(config, "tiering", False):
            return  # replan_processor requires the tiered matcher
        per_stage = self.processor.batch.stage_counters(
            self.processor.state
        )
        if not per_stage:
            return  # stage_attribution off: no measured signal
        counts = self._sel_counts(per_stage)
        prev, self._sel_prev = self._sel_prev, counts
        self._boundaries_since_replan += 1
        if self._plan_sel is None:
            # First boundary with measured data: pin the plan baseline
            # (keys below min_evals stay unpinned until they have seen
            # enough evaluations to mean anything).
            self._plan_sel = {
                key: ac / ev
                for key, (ev, ac) in counts.items()
                if ev >= policy.min_evals
            }
            return
        # Late-warming keys join the baseline as they cross min_evals.
        for key, (ev, ac) in counts.items():
            if key not in self._plan_sel and ev >= policy.min_evals:
                self._plan_sel[key] = ac / ev
        if prev is None:
            return  # no window yet (first boundary after a rollback)
        drifted = []
        for key, (ev, ac) in counts.items():
            pev, pac = prev.get(key, (0, 0))
            wev, wac = ev - pev, ac - pac
            base = self._plan_sel.get(key)
            # wev < 0: the cumulative tally restarted under this key (a
            # prior replan resets the conjunct accumulator) — skip until
            # the window is meaningful again.
            if base is None or wev < policy.min_evals:
                continue
            wsel = wac / wev
            if abs(wsel - base) > policy.drift_threshold:
                drifted.append((key, round(base, 4), round(wsel, 4)))
        if not drifted:
            self._replan_streak = 0
            return
        self._replan_streak += 1
        if (
            self._replan_streak < policy.replan_streak
            or self._boundaries_since_replan <= policy.cooldown
        ):
            return
        t0 = time.perf_counter()
        with maybe_span(
            self.trace, "replan", corr=corr, seq=self._seq,
            drifted=[
                {"key": "/".join(k), "plan": b, "window": w}
                for k, b, w in drifted
            ],
        ), timed_histogram(self.telemetry, "phase.replan"):
            if self.processor.pipeline:
                # An undecoded device batch belongs to the OLD plan's
                # dispatch; flushing is observable emission, kept for
                # the caller (same rule as rebalance/checkpoint).
                self._unclaimed.extend(self.processor.flush())
            try:
                self.processor = migrate_mod.replan_processor(
                    self._pattern, self.processor, per_stage
                )
            except Exception:
                self.replan_failures += 1
                # replan_processor mutates nothing before it succeeds —
                # the old processor, plan, and state are fully intact;
                # skip this boundary and re-measure.
                logger.exception(
                    "adaptive replan failed; keeping the current plan"
                )
                self._replan_streak = 0
                return
            self.processor.trace = self.trace
            self.processor.flight = self.flight
            self._overload_wire()
            self.replans += 1
            self._replan_streak = 0
            self._boundaries_since_replan = 0
            # The new plan was derived from exactly this profile: its
            # baseline is the cumulative selectivity at the swap.  The
            # window snapshot resets — the rebuilt matcher restarts the
            # per-conjunct accumulator from zero.
            self._plan_sel = {
                key: ac / ev
                for key, (ev, ac) in counts.items()
                if ev >= policy.min_evals
            }
            self._sel_prev = None
        self._observe_stall("replan", time.perf_counter() - t0, corr)
        logger.warning(
            "adaptive replan #%d: selectivity drift %s (plan -> window); "
            "plan re-derived from the measured profile",
            self.replans,
            [(("/".join(k)), b, w) for k, b, w in drifted],
        )

    # -- elastic capacity escalation ----------------------------------------

    def _capacity_counters(self) -> dict:
        return sizing.capacity_counters(self.processor.counters())

    def _ingest_loss_counters(self) -> dict:
        guard = getattr(self.processor, "_guard", None)
        if guard is None:
            return {}
        return sizing.ingest_capacity_counters(guard.loss_counters())

    def _maybe_escalate(
        self, records, matches, had_pending: bool = False,
        corr: Optional[str] = None,
    ) -> List[Tuple[Hashable, Sequence]]:
        """Detect capacity loss in the batch just processed and recover it.

        Loss counters are cumulative, so a trip is a positive DELTA over
        the post-previous-batch snapshot.  On a trip (after ``hysteresis``
        consecutive tripping batches): roll the processor back to the
        pre-batch state (the drop already cost this batch branches, and
        those branches exist only in the pre-batch world), migrate the
        live state onto the next wider config, snapshot it (so later
        recoveries and resumes replay at the new width), and re-process
        the batch — returning the re-run's matches, which supersede the
        lossy attempt's (never emitted).  Repeats up to
        ``policy.max_rounds`` if the re-run still trips; degrades to the
        historical warn-and-count behavior at the policy ceiling.
        """
        policy = self._policy
        counters = self._capacity_counters()
        base = self._counter_base
        if base is None:
            # First observation (fresh/restored processor): no delta yet.
            base = {k: 0 for k in counters} if self._seq == 0 else counters
        tripped = positive_delta(counters, base)
        if not tripped:
            self._counter_base = counters
            self._trip_streak = 0
            return matches
        self._trip_streak += 1
        if self._trip_streak < policy.hysteresis:
            logger.warning(
                "capacity trip %s tolerated (%d/%d before escalation); "
                "this batch's lost branches are NOT recovered",
                tripped, self._trip_streak, policy.hysteresis,
            )
            self._counter_base = counters
            return matches
        # Serial mode: ``matches`` is the lossy attempt's output, fully
        # superseded by the re-run.  Pipeline mode: the attempt's return
        # can mix the PREVIOUS batch's clean matches with this batch's
        # lossy ones (the gc cadence drains both), so splitting it is not
        # reliable — instead, when the previous batch was still in flight
        # (``had_pending``), it is popped from the journal tail and
        # recomputed from the rollback point alongside the tripping batch;
        # both re-runs are flushed so everything returns synchronously.
        pipeline = self.processor.pipeline
        kept: List[Tuple[Hashable, Sequence]] = []
        rerun = [] if pipeline else matches
        # (had_pending implies the previous batch is the journal tail: a
        # checkpoint or escalation would have flushed the pipeline, and
        # both clear the pending marker — the bool() is belt-and-braces.)
        redo_prev = pipeline and had_pending and bool(self._journal)
        rolled = False
        for _round in range(policy.max_rounds):
            cfg = self.processor.batch.matcher.config
            new_cfg = sizing.escalate(cfg, tripped, policy)
            if new_cfg is None:
                logger.warning(
                    "escalation exhausted at the policy ceiling (counters "
                    "%s); degrading to warn-and-count", tripped,
                )
                self._counter_base = counters
                return (kept + rerun) if rolled else matches
            new_dims = {
                k: getattr(new_cfg, k)
                for k in ("max_runs", "slab_entries", "slab_preds",
                          "dewey_depth", "max_walk")
            }
            with maybe_span(
                self.trace, "escalate", corr=corr, round=_round,
                tripped=dict(tripped), new_config=new_dims,
            ) as esp, timed_histogram(self.telemetry, "phase.escalate"):
                if self.flight is not None:
                    # Context of the batches that led to the trip, before
                    # the rollback discards them.
                    self.flight.note(escalation=self.escalations + 1,
                                     tripped=dict(tripped))
                    self.flight.dump("escalate", corr=corr)
                if redo_prev:
                    prev_batch = self._journal.pop()
                # Roll back to the pre-batch state; a pending pipelined
                # decode belongs to the lossy attempt and dies with the
                # old processor.
                self._restore_tail()
                self.processor = migrate_mod.migrate_processor(
                    self._pattern, self.processor, new_cfg,
                    mesh=self._proc_kwargs.get("mesh"),
                )
                self.processor.trace = self.trace
                self.processor.flight = self.flight
                self._overload_wire()
                self.escalations += 1
                logger.warning(
                    "capacity escalation #%d: %s after counters %s; "
                    "re-processing the %d-record batch at the new width",
                    self.escalations, new_dims, tripped, len(records),
                )
                if redo_prev:
                    # The in-flight previous batch: its matches rode the
                    # discarded lossy return, so emit them from this re-run
                    # (a wider config never drops where the narrow one
                    # didn't, so this re-run is clean by construction).
                    kept = list(self.processor.process(prev_batch))
                    kept += self.processor.flush()
                    self._journal.append(prev_batch)
                    redo_prev = False
                # Pin the wide config on disk before re-processing: a
                # recovery or resume between here and the next periodic
                # snapshot must replay at the new width, not the old one.
                try:
                    self.checkpoint()
                except Exception:
                    self.checkpoint_failures += 1
                    logger.exception(
                        "post-escalation checkpoint failed; a recovery "
                        "before the next good snapshot replays at the "
                        "OLD width"
                    )
                pre = self._capacity_counters()
                rerun = self.processor.process(records)
                if pipeline:
                    rerun = rerun + self.processor.flush()
                rolled = True
                counters = self._capacity_counters()
                tripped = positive_delta(counters, pre)
                esp["still_tripped"] = bool(tripped)
            if not tripped:
                break
        else:
            logger.warning(
                "batch still trips %s after %d escalation rounds; "
                "keeping the widest result", tripped, policy.max_rounds,
            )
        self._counter_base = counters
        self._trip_streak = 0
        return kept + rerun

    def _maybe_escalate_ingest(self) -> None:
        """Grow the ingestion-guard policy when a batch tripped an
        ingest loss counter (``sizing.escalate_ingest`` rows: late drops
        grow the grace, evictions grow the buffer depth).

        Forward-only, unlike engine escalation: the dropped records are
        already dead-lettered (recoverable by the caller from the DLQ),
        and re-processing them would require re-ordering history the
        engine has moved past — widening stops the loss for the rest of
        the stream.  The widened policy is pinned with an immediate
        snapshot so recoveries and resumes replay under it.
        """
        guard = getattr(self.processor, "_guard", None)
        if guard is None:
            return
        counters = self._ingest_loss_counters()
        base = self._ingest_base
        if base is None:
            base = {k: 0 for k in counters}
        tripped = positive_delta(counters, base)
        self._ingest_base = counters
        if not tripped:
            return
        new_policy = sizing.escalate_ingest(
            guard.policy, tripped, growth=self._policy.growth
        )
        if new_policy is None:
            logger.warning(
                "ingest loss %s but the guard policy cannot grow; records "
                "remain in the dead-letter queue", tripped,
            )
            return
        old = guard.policy
        guard.policy = new_policy
        self.ingest_escalations += 1
        logger.warning(
            "ingest escalation #%d: grace_ms %d -> %d, reorder_depth "
            "%d -> %d after loss %s (already-dropped records stay in the "
            "dead-letter queue)",
            self.ingest_escalations, old.grace_ms, new_policy.grace_ms,
            old.reorder_depth, new_policy.reorder_depth, tripped,
        )
        try:
            # checkpoint() returns any pipeline-flush matches; they belong
            # to the caller via the _unclaimed drain in process().
            self._unclaimed.extend(self.checkpoint())
        except Exception:
            self.checkpoint_failures += 1
            logger.exception(
                "post-ingest-escalation checkpoint failed; a recovery "
                "before the next good snapshot replays under the OLD "
                "ingest policy"
            )

    # -- diagnostics --------------------------------------------------------

    def health(self) -> HealthReport:
        return check_health(self.processor)

    def metrics_snapshot(self, per_lane: bool = True) -> dict:
        """The processor snapshot (per-phase latency histograms, per-lane
        and per-pattern counter breakdowns, hot-tier counters, watermark
        and HBM gauges) + supervisor lifecycle telemetry: the bare event
        counts AND their latency histograms (``phases`` gains
        ``checkpoint`` / ``recover`` / ``escalate`` with p50/p99) — when
        they fired and what they cost, not just how many."""
        out = self.processor.metrics_snapshot(per_lane=per_lane)
        out["recoveries"] = self.recoveries
        out["checkpoints"] = self.checkpoints
        out["checkpoint_failures"] = self.checkpoint_failures
        out["journal_failures"] = self.journal_failures
        out["escalations"] = self.escalations
        out["ingest_escalations"] = self.ingest_escalations
        out["evacuations"] = self.evacuations
        out["rebalances"] = self.rebalances
        out["rebalance_failures"] = self.rebalance_failures
        out["replans"] = self.replans
        out["replan_failures"] = self.replan_failures
        out["lanes_moved"] = self.lanes_moved
        out["stragglers"] = self.stragglers
        if self.flight is not None:
            out["flight_dumps"] = self.flight.dumps
        if self._overload is not None:
            # cep_overload_level / _pressure / _transitions /
            # _transition_failures gauges (README metrics reference).
            out.update(self._overload.metrics())
        out["retry_backoff_ms_total"] = round(self.retry_backoff_ms_total, 3)
        phases = dict(out.get("phases") or {})
        phases.update(
            {
                name[len("phase."):]: inst.snapshot()
                for name, inst in self.telemetry.items()
                if name.startswith("phase.")
            }
        )
        out["phases"] = phases
        return out
