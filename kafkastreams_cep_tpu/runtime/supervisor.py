"""Failure detection & recovery — the rebalance/changelog-restore analog.

The reference delegates fault tolerance entirely to Kafka Streams (SURVEY
§5): every store is changelog-backed, so when a task dies the partition is
reassigned and the new owner replays the changelog to rebuild run queue,
buffer, and aggregate state (``CEPProcessor.java:117-134,144-149``).  The
library's own contribution is keeping *all* engine state store-resident so
that recovery is possible at every record boundary.

The TPU analog splits the same contract in two:

* **checkpoint** = the changelog snapshot: the supervisor persists the
  processor's full state (``runtime/checkpoint.py``) every
  ``checkpoint_every`` batches — far cheaper than the reference's
  every-record run-queue serialization (``CEPProcessor.java:158-160``),
  with the gap covered by a record journal;
* **journal + replay** = the changelog tail: records processed since the
  last checkpoint are kept host-side; on failure the supervisor restores
  the checkpoint and replays the journal, which is deterministic (the
  engine is a pure function of state × records), so the recovered
  processor lands in exactly the pre-failure state.

Failure *detection* covers what a lost Kafka Streams task would surface:
any exception out of the device dispatch (device reset, OOM, tunnel loss)
triggers recovery, and :meth:`Supervisor.health` exposes the engine's
overflow counters plus state-validity probes (NaN fold state, negative
refcounts) as a typed report — the counters exist precisely because
fixed-shape capacity overflow is this design's failure mode, with no
reference analog to inherit.

Matches replayed during recovery are suppressed (they were already
emitted), preserving exactly-once *emission* for everything the caller saw
before the failure — one better than the reference, whose at-least-once
replay duplicates and corrupts runs (``README.md:108``).
"""

from __future__ import annotations

import itertools
import os
import pickle
import tempfile
from dataclasses import dataclass, field
from typing import Hashable, List, Optional, Sequence as Seq, Tuple

import numpy as np

from kafkastreams_cep_tpu.engine.matcher import EngineConfig
from kafkastreams_cep_tpu.native.journal import Journal
from kafkastreams_cep_tpu.runtime import checkpoint as ckpt_mod
from kafkastreams_cep_tpu.runtime.processor import CEPProcessor, Record
from kafkastreams_cep_tpu.utils.events import Sequence

from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime.supervisor")


@dataclass
class HealthReport:
    """One health probe of a live processor."""

    healthy: bool
    warnings: List[str] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)
    counters: dict = field(default_factory=dict)


def check_health(processor: CEPProcessor) -> HealthReport:
    """Probe a processor's engine state for capacity loss and corruption.

    *Warnings* are capacity-policy events (bounded-shape drops: runs, slab
    entries, pointer lists, Dewey width, walk length) — matching may have
    silently lost branches, which the reference (unbounded heap) never
    does; *errors* are states no healthy execution can reach (NaN fold
    state, negative refcounts) and indicate corruption.
    """
    counters = processor.counters()
    warnings = [
        f"{name}={val} capacity drops" for name, val in counters.items() if val
    ]
    errors = []
    # Fold state is typed-encoded int32 (float32 states as bit patterns,
    # engine/matcher.py); only float-typed columns can hold NaN.
    agg = np.asarray(processor.state.agg)
    dtypes = processor.batch.matcher.tables.state_dtypes
    flt = [i for i, d in enumerate(dtypes) if d == "float32"]
    if flt and np.isnan(
        np.ascontiguousarray(agg[..., flt]).view(np.float32)
    ).any():
        errors.append("NaN in fold-aggregate state")
    refs = np.asarray(processor.state.slab.refs)
    if (refs < 0).any():
        errors.append("negative slab refcount")
    return HealthReport(
        healthy=not errors, warnings=warnings, errors=errors, counters=counters
    )


class Supervisor:
    """Checkpointing, health-probing, auto-recovering processor wrapper.

    ``pattern`` must be re-compilable user code (predicates/folds live in
    code, never in checkpoints — the ``ComputationStageSerDe`` contract);
    the supervisor owns the processor it creates.

    ``process(records)`` behaves like :meth:`CEPProcessor.process`, plus:

    * every ``checkpoint_every`` batches the full state is checkpointed
      (atomic rename, so a crash mid-write keeps the previous snapshot);
    * if the underlying processor raises, the supervisor restores the
      latest checkpoint, replays the journaled records since it
      (suppressing their already-emitted matches), retries the failing
      batch once, and counts the recovery in ``recoveries``;
    * with ``journal_path`` set, every batch is also appended to a durable
      CRC-framed on-disk journal (``native/journal.py``, C++ write path) —
      then :meth:`Supervisor.resume` recovers from a full *process* crash:
      restore the snapshot, replay the journal's intact prefix, continue.
      ``journal_sync=True`` fsyncs per batch (machine-crash durable).
    """

    _instance_ids = itertools.count()

    def __init__(
        self,
        pattern,
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        checkpoint_path: Optional[str] = None,
        checkpoint_every: int = 16,
        max_retries: int = 1,
        journal_path: Optional[str] = None,
        journal_sync: bool = False,
        processor: Optional[CEPProcessor] = None,
        _resuming: bool = False,
        **proc_kwargs,
    ):
        self._pattern = pattern
        self._proc_kwargs = dict(proc_kwargs)
        # ``processor`` injection lets resume() hand over an
        # already-restored processor instead of building one to discard.
        self.processor = processor or CEPProcessor(
            pattern, num_lanes, config, **self._proc_kwargs
        )
        # Per-instance default path: two supervisors in one process must
        # never clobber each other's snapshots.
        self.checkpoint_path = checkpoint_path or os.path.join(
            tempfile.gettempdir(),
            f"cep_supervisor_{os.getpid()}_{next(self._instance_ids)}.ckpt",
        )
        self.checkpoint_every = int(checkpoint_every)
        self.max_retries = int(max_retries)
        self._journal: List[List[Record]] = []  # batches since last ckpt
        self._disk_journal = (
            Journal(journal_path, sync=journal_sync) if journal_path else None
        )
        if not _resuming:
            # A fresh supervisor starting over a previous incarnation's
            # files: that history would otherwise leak into a later
            # resume() — the old checkpoint (with its higher seq) would be
            # restored and the new run's journal frames skipped.  Starting
            # fresh declares the old history abandoned — remove both
            # loudly.  (To continue it, use Supervisor.resume.)
            if (
                self._disk_journal is not None
                and os.path.exists(journal_path)
                and os.path.getsize(journal_path) > 0
            ):
                logger.warning(
                    "journal %s holds frames from a previous run; truncating "
                    "(use Supervisor.resume to continue that history)",
                    journal_path,
                )
                self._disk_journal.truncate()
            if os.path.exists(self.checkpoint_path):
                logger.warning(
                    "checkpoint %s belongs to a previous run; removing "
                    "(use Supervisor.resume to continue that history)",
                    self.checkpoint_path,
                )
                os.remove(self.checkpoint_path)
        self._has_checkpoint = False
        self._batches_since_ckpt = 0
        # Monotone batch sequence number: stamped into journal frames and
        # the checkpoint header so resume() can tell which frames a
        # snapshot already contains (a crash between snapshot and journal
        # truncation must not double-replay them).
        self._seq = 0
        self.recoveries = 0
        self.checkpoints = 0
        self.checkpoint_failures = 0
        self.journal_failures = 0
        # After a failed append the on-disk journal is no longer a complete
        # history — appending later batches would leave a seq gap that a
        # resume would replay straight through into a wrong state.  Suspend
        # journaling until the next checkpoint re-establishes a clean base.
        self._journal_suspended = False

    @classmethod
    def resume(
        cls,
        pattern,
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        checkpoint_path: Optional[str] = None,
        journal_path: Optional[str] = None,
        **kwargs,
    ) -> "Supervisor":
        """Rebuild a supervisor after a process crash.

        Restores ``checkpoint_path`` if the file exists (else starts
        fresh), then replays the on-disk journal's intact prefix —
        deterministic, so the processor lands exactly where the crashed
        process left off; replayed matches are suppressed (the old process
        already emitted them).  Journal frames carry the batch sequence
        number, and frames at or below the checkpoint's sequence are
        skipped — so a crash *between* snapshotting and journal truncation
        cannot double-replay the snapshotted batches.
        """
        proc = None
        base_seq = 0
        if checkpoint_path and os.path.exists(checkpoint_path):
            ckpt = ckpt_mod.load_checkpoint(checkpoint_path)
            base_seq = int(ckpt["header"].get("extra", {}).get("seq", 0))
            proc = ckpt_mod.restore_processor(
                pattern, checkpoint_path, ckpt=ckpt,
                mesh=kwargs.get("mesh"),
            )
        sup = cls(
            pattern, num_lanes, config,
            checkpoint_path=checkpoint_path,
            journal_path=journal_path,
            processor=proc,
            _resuming=True,
            **kwargs,
        )
        sup._has_checkpoint = proc is not None
        sup._seq = base_seq
        replayed = skipped = 0
        if sup._disk_journal is not None:
            for payload in sup._disk_journal.replay():
                seq, batch = pickle.loads(payload)
                if seq <= base_seq:
                    skipped += 1  # already inside the snapshot
                    continue
                if seq != sup._seq + 1:
                    # Defense in depth: a seq gap means the journal is not
                    # a complete history (it should be impossible — a
                    # failed append suspends journaling).  Replaying past
                    # the gap would build a state that never saw the
                    # missing batches; stop at the last contiguous frame.
                    logger.error(
                        "journal seq gap (%d -> %d); stopping replay at "
                        "the last contiguous frame", sup._seq, seq,
                    )
                    break
                sup.processor.process(batch)  # matches already emitted
                sup._journal.append(batch)
                sup._batches_since_ckpt += 1
                sup._seq = seq
                replayed += len(batch)
        logger.info(
            "resumed from %s + %s: %d journaled records replayed "
            "(%d pre-snapshot frames skipped)",
            checkpoint_path, journal_path, replayed, skipped,
        )
        return sup

    # -- checkpointing ------------------------------------------------------

    def checkpoint(self) -> None:
        """Snapshot now (atomic) and truncate the journals."""
        tmp = self.checkpoint_path + ".tmp"
        ckpt_mod.save_checkpoint(self.processor, tmp, extra={"seq": self._seq})
        os.replace(tmp, self.checkpoint_path)
        self._has_checkpoint = True
        self._journal.clear()
        if self._disk_journal is not None:
            self._disk_journal.truncate()
            self._journal_suspended = False  # clean base re-established
        self._batches_since_ckpt = 0
        self.checkpoints += 1

    # -- the supervised hot path -------------------------------------------

    def process(
        self, records: Seq[Record]
    ) -> List[Tuple[Hashable, Sequence]]:
        records = list(records)
        for attempt in range(self.max_retries + 1):
            try:
                matches = self.processor.process(records)
                break
            except ValueError:
                # Deterministic input rejection (schema, lane overflow,
                # timestamp range): the batch is bad, not the device —
                # restore-and-replay cannot help and state was untouched
                # (processor validation is atomic).
                raise
            except Exception:
                if attempt >= self.max_retries:
                    raise
                logger.exception(
                    "processor failed on a %d-record batch; recovering",
                    len(records),
                )
                self._recover()
        self._journal.append(records)
        self._seq += 1
        if self._disk_journal is not None:
            # Journal after success, before returning matches.  A process
            # crash in the tiny window before this append loses the batch
            # from recovery (the caller should re-submit unacknowledged
            # batches; replay dedup absorbs them); a crash after it replays
            # the batch with emissions suppressed.  Either way state and
            # the match stream stay consistent — the reference's Kafka
            # commit boundary has the same at-least-once window
            # (README.md:108), without the dedup.
            #
            # An append *failure* (disk full) must not raise here: state
            # already advanced, and a caller retry would double-apply the
            # batch.  Count it and SUSPEND journaling until the next
            # checkpoint — later frames after a missing seq would otherwise
            # replay into a state that never saw this batch.  The in-memory
            # journal still covers device-failure recovery; process-crash
            # durability is degraded until the next snapshot.
            if not self._journal_suspended:
                try:
                    self._disk_journal.append(
                        pickle.dumps((self._seq, records))
                    )
                except Exception:
                    self.journal_failures += 1
                    self._journal_suspended = True
                    logger.exception(
                        "journal append failed; journaling suspended until "
                        "the next checkpoint (batch %d+ not crash-durable)",
                        self._seq,
                    )
        self._batches_since_ckpt += 1
        if self._batches_since_ckpt >= self.checkpoint_every:
            # A failed snapshot (disk full, ...) must not lose the batch's
            # matches: the journal still covers everything since the last
            # good snapshot, so log, count, and retry next batch.
            try:
                self.checkpoint()
            except Exception:
                self.checkpoint_failures += 1
                logger.exception("checkpoint failed; journal retained")
        return matches

    def _recover(self) -> None:
        """Restore the last checkpoint and replay the journal tail.

        Replay is deterministic, so the processor lands in exactly the
        state it had after the last successful batch; replayed matches are
        dropped (already emitted).  With no checkpoint yet, the journal is
        the full history and replay starts from a fresh processor.
        """
        if self._has_checkpoint:
            self.processor = ckpt_mod.restore_processor(
                self._pattern, self.checkpoint_path,
                mesh=self._proc_kwargs.get("mesh"),
            )
        else:
            num_lanes = self.processor.num_lanes
            config = self.processor.batch.matcher.config
            self.processor = CEPProcessor(
                self._pattern, num_lanes, config, **self._proc_kwargs
            )
        replayed = 0
        for batch in self._journal:
            self.processor.process(batch)  # matches already emitted
            replayed += len(batch)
        self.recoveries += 1
        logger.info(
            "recovered: checkpoint=%s, %d journaled records replayed",
            self._has_checkpoint, replayed,
        )

    # -- diagnostics --------------------------------------------------------

    def health(self) -> HealthReport:
        return check_health(self.processor)

    def metrics_snapshot(self) -> dict:
        out = self.processor.metrics_snapshot()
        out["recoveries"] = self.recoveries
        out["checkpoints"] = self.checkpoints
        out["checkpoint_failures"] = self.checkpoint_failures
        out["journal_failures"] = self.journal_failures
        return out
