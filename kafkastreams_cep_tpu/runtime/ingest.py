"""Ingestion guard — watermark-driven out-of-order absorption + quarantine.

The engine consumes records in arrival order and reproduces SASE+ run
semantics over that order; real streams are out-of-order in *event time*
and occasionally poisoned per record.  The reference absorbs both at the
Kafka layer (partition logs are arrival-ordered; bad records are a serde
concern); this module is the TPU runtime's front door analog:

* **Reorder buffer.**  Admitted records are held in a bounded min-heap
  keyed by event time and released only once the **watermark** — the max
  event timestamp seen, minus ``grace_ms`` — passes them, in timestamp
  order.  For any arrival shuffle whose timestamp inversions are bounded
  by the grace (``|ts(y) - ts(x)| <= grace_ms`` whenever ``y`` arrives
  before ``x`` with ``ts(y) > ts(x)``), the released stream is the
  globally timestamp-sorted stream — identical to what the in-order
  trace releases — so matches, emission order, and loss counters are
  **bit-identical** to the in-order run (property-tested in
  ``tests/test_ingest.py``).  Records with equal timestamps release in
  arrival order.

* **Quarantine / dead-letter.**  Per-record validation defects (schema,
  lane overflow, timestamp range) and too-late events are diverted to a
  capped dead-letter queue — record + typed reason + batch correlation
  id — instead of rejecting the whole batch; the rest of the batch
  proceeds.  ``on_bad_record="raise"`` preserves the strict batch-level
  :class:`InputRejected` behavior.

* **Loss counters.**  ``late_dropped`` (event time older than the
  watermark at arrival), ``quarantined`` (validation defects),
  ``reorder_evictions`` (buffer-depth overflow force-released a record
  before its watermark), and ``overload_shed`` (admissible records shed
  by the brownout ladder, ``runtime/overload.py``).  All zero ⇒ the
  guard was loss-free and the release stream is exactly the sorted
  admitted stream.

The guard is first-class durable state: :func:`IngestGuard.to_state`
round-trips through the checkpoint header (``runtime/checkpoint.py``),
survives live migration (``runtime/migrate.py``), and replays
deterministically from the supervisor journal — a crash with records
held in the buffer recovers them from the snapshot + journal replay
(chaos-tested with the ``ingest.admit`` / ``ingest.release`` failpoints).
"""

from __future__ import annotations

import dataclasses
import heapq
import time
from typing import Any, Dict, List, NamedTuple, Optional

from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime.ingest")

#: Typed dead-letter reasons (the quarantine policy table, README
#: "Graceful ingestion").  This tuple and :data:`REASON_DOCS` are the
#: SINGLE source of truth for the DLQ reason enum: the Prometheus
#: ``dead_letters_total{reason=...}`` label values (utils/telemetry.py
#: renders the ``dead_letters`` snapshot key), and the README policy
#: table (:func:`policy_table_markdown` — tests assert the README embeds
#: its output verbatim) both derive from here.  Adding a reason means
#: adding it here, once.
REASON_SCHEMA = "schema"
REASON_LANE_OVERFLOW = "lane_overflow"
REASON_TIME_RANGE = "time_range"
REASON_LATE = "late"
REASON_TENANT_QUOTA = "tenant_quota"
REASON_OVERLOAD_SHED = "overload_shed"

REASONS = (
    REASON_SCHEMA,
    REASON_LANE_OVERFLOW,
    REASON_TIME_RANGE,
    REASON_LATE,
    REASON_TENANT_QUOTA,
    REASON_OVERLOAD_SHED,
)

#: reason -> (trigger description, loss counter it lands in).  Drives the
#: README "dead-letter policy" table; keep every member of ``REASONS``
#: present (tests/test_tenant_isolation.py enforces the bijection).
REASON_DOCS: Dict[str, tuple] = {
    REASON_SCHEMA: (
        "value tree shape, or a float in an int field, differs from the "
        "first record",
        "`quarantined`",
    ),
    REASON_LANE_OVERFLOW: (
        "a new key past `num_lanes`",
        "`quarantined`",
    ),
    REASON_TIME_RANGE: (
        "timestamp outside int32 device time from the epoch",
        "`quarantined`",
    ),
    REASON_LATE: (
        "event time behind the watermark (or the release frontier) at "
        "arrival",
        "`late_dropped`",
    ),
    REASON_TENANT_QUOTA: (
        "tenant over its admission token bucket, or traffic for a "
        "quarantined tenant (runtime/tenant.py `AdmissionPolicy`)",
        "`admission_shed` / `admission_quarantined_dropped` (per tenant)",
    ),
    REASON_OVERLOAD_SHED: (
        "brownout ladder at L3+ shedding admissible records at ingest "
        "(runtime/overload.py `OverloadController`); deterministic "
        "within-batch stride, so `offered == admitted + shed + "
        "dead_lettered` reconciles exactly",
        "`overload_shed`",
    ),
}

#: Non-reason rows of the policy table (losses that never produce a dead
#: letter but belong in the same contract).
EXTRA_POLICY_ROWS = (
    (
        "—",
        "depth-cap force-release (the record still reaches the engine, "
        "just early)",
        "`reorder_evictions`",
    ),
)


def policy_table_markdown() -> str:
    """Render the dead-letter policy table (README "Graceful ingestion")
    from :data:`REASON_DOCS` — the one place the reason enum is
    documented.  The README embeds this output verbatim."""
    rows = [("reason", "trigger", "counter"), ("---", "---", "---")]
    for reason in REASONS:
        trigger, counter = REASON_DOCS[reason]
        rows.append((f"`{reason}`", trigger, counter))
    rows.extend(EXTRA_POLICY_ROWS)
    return "\n".join("| " + " | ".join(r) + " |" for r in rows)


class AdmissionLimiter:
    """Per-tenant token buckets for record admission (the front door of
    the `tenant_quota` shed path — ``runtime/tenant.py`` wires it ahead
    of packing/dispatch so a flooding tenant is shed before it costs the
    engine anything).

    ``refill()`` once per batch adds ``rate_per_batch`` tokens to every
    known bucket (capped at ``burst``); ``admit(tenant)`` spends one.
    New tenants start with a full burst.  Pure deterministic host state:
    :meth:`to_state` round-trips through the checkpoint header and
    replays identically from the supervisor journal.

    Under brownout (runtime/overload.py L2+) :meth:`set_pressure`
    tightens every bucket proportionally to the tenant's measured cost
    share: the heaviest tenant's refill rate (and a new tenant's initial
    burst) is multiplied by ``scale``, a zero-share tenant keeps factor
    1.0, and tenants with no measured share get the conservative
    ``scale``.  Pressure is part of :meth:`to_state` so a replayed crash
    admits the same records.
    """

    def __init__(self, rate_per_batch: float, burst: Optional[float] = None):
        if rate_per_batch < 0:
            raise ValueError(
                f"rate_per_batch must be >= 0, got {rate_per_batch}"
            )
        self.rate = float(rate_per_batch)
        self.burst = float(burst) if burst is not None else max(
            1.0, 2.0 * self.rate
        )
        self.tokens: Dict[str, float] = {}
        self.pressure_scale: float = 1.0
        self.pressure_shares: Dict[str, float] = {}

    def set_pressure(
        self, scale: float, shares: Optional[Dict[str, float]] = None
    ) -> None:
        """Apply (or at ``scale=1.0`` clear) overload pressure: the
        supervisor's brownout controller calls this on every transition
        and after every restore/migration, so it must be idempotent."""
        self.pressure_scale = min(1.0, max(0.0, float(scale)))
        self.pressure_shares = {
            str(k): float(v) for k, v in (shares or {}).items()
        }

    def _factor(self, tenant: str) -> float:
        if self.pressure_scale >= 1.0:
            return 1.0
        shares = self.pressure_shares
        if not shares:
            return self.pressure_scale
        share = shares.get(tenant)
        if share is None:
            # Unmeasured tenant: no evidence it is cheap, so it gets the
            # full squeeze rather than a free pass.
            return self.pressure_scale
        max_share = max(shares.values())
        if max_share <= 0:
            return 1.0
        return 1.0 - (1.0 - self.pressure_scale) * (share / max_share)

    def refill(self) -> None:
        for tenant in self.tokens:
            self.tokens[tenant] = min(
                self.burst, self.tokens[tenant] + self.rate * self._factor(
                    tenant
                )
            )

    def admit(self, tenant: str) -> bool:
        bucket = self.tokens.get(tenant)
        if bucket is None:
            bucket = self.burst * self._factor(tenant)
        if bucket < 1.0:
            self.tokens[tenant] = bucket
            return False
        self.tokens[tenant] = bucket - 1.0
        return True

    def to_state(self) -> Dict[str, Any]:
        return {
            "rate": self.rate,
            "burst": self.burst,
            "tokens": dict(self.tokens),
            "pressure_scale": self.pressure_scale,
            "pressure_shares": dict(self.pressure_shares),
        }

    @classmethod
    def from_state(cls, state: Dict[str, Any]) -> "AdmissionLimiter":
        lim = cls(state["rate"], state["burst"])
        lim.tokens = {str(k): float(v) for k, v in state["tokens"].items()}
        # Pre-overload checkpoints carry no pressure keys: default open.
        lim.pressure_scale = float(state.get("pressure_scale", 1.0))
        lim.pressure_shares = {
            str(k): float(v)
            for k, v in state.get("pressure_shares", {}).items()
        }
        return lim


@dataclasses.dataclass(frozen=True)
class IngestPolicy:
    """How the guard absorbs disorder and disposes of bad records.

    ``grace_ms``       — watermark lag: a record is held until the max
                         seen timestamp exceeds its own by this much
                         (0 = release immediately; arrival order must
                         then already be timestamp order).
    ``reorder_depth``  — max records held across all lanes; overflow
                         force-releases the earliest-timestamp record
                         (counted in ``reorder_evictions`` — bounded
                         memory, degraded ordering).
    ``on_bad_record``  — ``"quarantine"`` (default): divert the record
                         to the dead-letter queue and keep going;
                         ``"raise"``: today's strict batch-level
                         :class:`InputRejected`.
    ``dead_letter_cap``— max retained dead letters; beyond it the oldest
                         is dropped (counted, never silent).
    """

    grace_ms: int = 0
    reorder_depth: int = 4096
    on_bad_record: str = "quarantine"
    dead_letter_cap: int = 1024

    def __post_init__(self):
        if self.on_bad_record not in ("quarantine", "raise"):
            raise ValueError(
                f"on_bad_record={self.on_bad_record!r}: expected "
                "'quarantine' or 'raise'"
            )
        if self.grace_ms < 0 or self.reorder_depth < 1:
            raise ValueError(
                f"IngestPolicy needs grace_ms >= 0 and reorder_depth >= 1, "
                f"got grace_ms={self.grace_ms} reorder_depth="
                f"{self.reorder_depth}"
            )


class DeadLetter(NamedTuple):
    """One quarantined record: what, why (typed), and which ingest batch."""

    record: Any
    reason: str
    detail: str
    corr: str


class Defect(NamedTuple):
    """A per-record validation verdict (``None`` = admissible).

    ``silent=True`` marks drops that are policy, not loss (replay
    duplicates) — they are counted by the caller, never dead-lettered.
    """

    reason: str
    detail: str
    silent: bool = False


class IngestGuard:
    """The reorder buffer + dead-letter queue of one processor.

    Pure host state with no device or engine dependencies; the owning
    :class:`CEPProcessor` drives validation (it owns the schema, lane
    map, and epoch) and feeds admitted records through :meth:`push` /
    :meth:`release`.
    """

    def __init__(self, policy: IngestPolicy, clock=None):
        self.policy = policy
        # Injectable wall clock for the latency ledger's admit stamps
        # (tests pin a fake; stamps must survive process restarts, so the
        # default is time.time, not perf_counter).
        self._clock = clock if clock is not None else time.time
        # Min-heap of (timestamp, admission seq, record, admit_stamp):
        # seq is unique, so comparison never reaches the record and
        # equal-timestamp records pop in arrival order.  The admit stamp
        # is the host wall clock at push — it rides the heap entry (and
        # therefore checkpoint state) so reorder-hold latency survives
        # restore without loss.
        self._heap: List[tuple] = []
        self._evicted: List[tuple] = []  # depth-overflow force-releases
        #: Admit stamps of the records the last release()/drain() emitted,
        #: aligned with the returned list (None entries = stamp unknown,
        #: e.g. entries restored from a pre-stamp checkpoint).
        self.last_release_stamps: List[Optional[float]] = []
        self._seq = 0
        # Event-time bookkeeping (absolute ms): max timestamp admitted,
        # and the release frontier — the highest timestamp already handed
        # to the engine (only ever ahead of the watermark after an
        # eviction; admission behind it would disorder the engine stream).
        self.max_seen: Optional[int] = None
        self.frontier: Optional[int] = None
        # Per-lane source-offset high-water marks (at-least-once dedup at
        # admission: the engine sees auto-assigned offsets in release
        # order, so replay dedup must happen here, on the source offsets).
        self.source_hw: Dict[int, int] = {}
        # Loss counters — all zero ⇒ loss-free (README contract).
        self.late_dropped = 0
        self.quarantined = 0
        self.reorder_evictions = 0
        self.overload_shed = 0
        # Non-loss telemetry.
        self.admitted = 0
        self.released = 0
        self.dead_letter_dropped = 0
        self.reason_counts: Dict[str, int] = {}
        self.dead_letters: List[DeadLetter] = []

    # -- admission ----------------------------------------------------------

    @property
    def watermark(self) -> Optional[int]:
        """Max admitted timestamp minus the grace (None before any)."""
        if self.max_seen is None:
            return None
        return self.max_seen - self.policy.grace_ms

    def late_by(self, ts: int) -> Optional[int]:
        """How many ms ``ts`` is behind the release cutoff (None = on
        time).  Strictly behind: a record AT the watermark (or at an
        already-released timestamp) still admits, behind its equals."""
        cutoff = self.watermark
        if self.frontier is not None:
            cutoff = self.frontier if cutoff is None else max(
                cutoff, self.frontier
            )
        if cutoff is None or ts >= cutoff:
            return None
        return cutoff - ts

    def push(self, record) -> None:
        """Admit one validated record into the buffer (may force-release
        the earliest held record when the depth cap is hit)."""
        ts = int(record.timestamp)
        heapq.heappush(self._heap, (ts, self._seq, record, self._clock()))
        self._seq += 1
        self.admitted += 1
        self.max_seen = ts if self.max_seen is None else max(
            self.max_seen, ts
        )
        if len(self._heap) > self.policy.reorder_depth:
            ent = heapq.heappop(self._heap)
            self._evicted.append(ent)
            self.reorder_evictions += 1
            self.frontier = ent[0] if self.frontier is None else max(
                self.frontier, ent[0]
            )

    def observe_time(self, ts: int) -> None:
        """Advance event time without admitting the record (brownout
        sheds): a shed record's timestamp is still *observed*, so the
        watermark keeps moving, held records keep releasing, and the
        backlog clears even while the door is closed (L4 would otherwise
        deadlock — nothing admits, so nothing ever releases)."""
        ts = int(ts)
        self.max_seen = ts if self.max_seen is None else max(
            self.max_seen, ts
        )

    def quarantine(self, record, reason: str, detail: str, corr: str) -> None:
        """Divert one record to the dead-letter queue with a typed reason."""
        if reason == REASON_LATE:
            self.late_dropped += 1
        elif reason == REASON_OVERLOAD_SHED:
            self.overload_shed += 1
        else:
            self.quarantined += 1
        self.reason_counts[reason] = self.reason_counts.get(reason, 0) + 1
        if len(self.dead_letters) >= self.policy.dead_letter_cap:
            self.dead_letters.pop(0)
            self.dead_letter_dropped += 1
        self.dead_letters.append(DeadLetter(record, reason, detail, corr))
        logger.warning(
            "quarantined record (reason=%s, corr=%s): %s", reason, corr,
            detail,
        )

    # -- release ------------------------------------------------------------

    def release(self) -> List:
        """Records whose timestamps the watermark has passed, in
        (timestamp, arrival) order — plus any depth-cap evictions, which
        always precede them (an eviction popped the then-minimum, and
        later admissions behind it are late-dropped at the door)."""
        out = self._evicted
        self._evicted = []
        wm = self.watermark
        if wm is not None:
            while self._heap and self._heap[0][0] <= wm:
                out.append(heapq.heappop(self._heap))
        return self._emit(out)

    def drain(self) -> List:
        """End-of-stream: release everything held, watermark regardless."""
        out = self._evicted
        self._evicted = []
        while self._heap:
            out.append(heapq.heappop(self._heap))
        return self._emit(out)

    def _emit(self, entries: List[tuple]) -> List:
        if entries:
            self.frontier = entries[-1][0] if self.frontier is None else max(
                self.frontier, entries[-1][0]
            )
            self.released += len(entries)
        # len(e) guard: entries restored from a pre-stamp (3-tuple)
        # checkpoint have no admit stamp — their reorder hold reads 0.
        self.last_release_stamps = [
            e[3] if len(e) > 3 else None for e in entries
        ]
        return [e[2] for e in entries]

    # -- telemetry ----------------------------------------------------------

    @property
    def held(self) -> int:
        return len(self._heap) + len(self._evicted)

    def hold_age_ms(self) -> int:
        """Event-time age of the oldest held record (how long the head of
        the buffer has been waiting relative to the newest admission)."""
        if not self._heap or self.max_seen is None:
            return 0
        return max(0, self.max_seen - self._heap[0][0])

    def loss_counters(self) -> Dict[str, int]:
        """The loss contract: all zero ⇒ nothing dropped or disordered."""
        return {
            "late_dropped": self.late_dropped,
            "quarantined": self.quarantined,
            "reorder_evictions": self.reorder_evictions,
            "overload_shed": self.overload_shed,
        }

    def stats(self) -> Dict[str, int]:
        out = dict(self.loss_counters())
        out.update(
            ingest_held=self.held,
            ingest_hold_age_ms=self.hold_age_ms(),
            ingest_admitted=self.admitted,
            ingest_released=self.released,
            dead_letter_depth=len(self.dead_letters),
            dead_letter_dropped=self.dead_letter_dropped,
        )
        if self.watermark is not None:
            out["ingest_watermark"] = self.watermark
        return out

    # -- durability ---------------------------------------------------------

    def to_state(self) -> Dict[str, Any]:
        """Picklable snapshot (checkpoint header payload).  Records and
        dead letters carry user values — the same pickle contract as the
        processor's host event mirror."""
        return {
            "policy": dataclasses.asdict(self.policy),
            "heap": list(self._heap),
            "evicted": list(self._evicted),
            "seq": self._seq,
            "max_seen": self.max_seen,
            "frontier": self.frontier,
            "source_hw": dict(self.source_hw),
            "late_dropped": self.late_dropped,
            "quarantined": self.quarantined,
            "reorder_evictions": self.reorder_evictions,
            "overload_shed": self.overload_shed,
            "admitted": self.admitted,
            "released": self.released,
            "dead_letter_dropped": self.dead_letter_dropped,
            "reason_counts": dict(self.reason_counts),
            "dead_letters": list(self.dead_letters),
        }

    @classmethod
    def from_state(cls, state: Dict[str, Any]) -> "IngestGuard":
        guard = cls(IngestPolicy(**state["policy"]))
        # Pre-stamp (3-tuple) checkpoint entries pad with a None admit
        # stamp: restored holds read 0 rather than fabricating a stamp.
        def _pad(e):
            e = tuple(e)
            return e if len(e) > 3 else e + (None,)

        guard._heap = [_pad(e) for e in state["heap"]]
        heapq.heapify(guard._heap)
        guard._evicted = [_pad(e) for e in state["evicted"]]
        guard._seq = int(state["seq"])
        guard.max_seen = state["max_seen"]
        guard.frontier = state["frontier"]
        guard.source_hw = {int(k): int(v) for k, v in state["source_hw"].items()}
        guard.late_dropped = int(state["late_dropped"])
        guard.quarantined = int(state["quarantined"])
        guard.reorder_evictions = int(state["reorder_evictions"])
        # Pre-overload checkpoints carry no shed counter: default zero.
        guard.overload_shed = int(state.get("overload_shed", 0))
        guard.admitted = int(state["admitted"])
        guard.released = int(state["released"])
        guard.dead_letter_dropped = int(state["dead_letter_dropped"])
        guard.reason_counts = dict(state["reason_counts"])
        guard.dead_letters = [DeadLetter(*d) for d in state["dead_letters"]]
        return guard
