"""Crash flight recorder — the last N batches' context, always on hand.

Production failures are diagnosed from what was happening *just before*:
which batch tripped, what the phase timings looked like, which counters
were moving, how full the slab and handle ring were.  The telemetry
registry (PR 3) answers "what is the lifetime total"; this module keeps a
bounded ring of **per-batch** records — phase-timing deltas, counter
deltas, watermark, occupancy, escalation state — and dumps it as JSONL
whenever something goes wrong (supervisor crash/recovery, capacity
escalation, a quarantine burst) or on demand, so every failure ships its
own last-N-batches context instead of a lifetime aggregate.

Design constraints:

* **Cheap per batch.**  One record is a handful of host counter reads
  plus two small device reductions (slab/ring occupancy); the deltas come
  from :func:`~kafkastreams_cep_tpu.utils.telemetry.positive_delta` over
  the previous record's snapshot.  Disabled (no recorder attached) the
  cost is one ``None`` check per batch.
* **Bounded.**  ``capacity`` batches, FIFO — a deque, never a file,
  until a dump is requested.
* **Dump schema** (one JSON object per line): a ``flight_dump`` header
  ``{type, reason, corr, ts_ms, records, dropped}`` followed by
  ``flight_record`` lines ``{type, corr, seq, ts_ms, records_in,
  matches_out, phase_seconds, counters, watermark, slab_live,
  ring_pending, lanes, ...}`` — newest last, exactly the ring order.
  ``corr`` is the processor's batch correlation id
  (``<name>-<batch_seq>``, the same id the ingestion guard stamps on
  dead letters), so a dump row joins against trace spans and DLQ
  entries.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from kafkastreams_cep_tpu.utils.telemetry import positive_delta
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime.flight")

#: Cumulative per-batch-delta'd runtime counters (utils/metrics.py names).
_RUNTIME_KEYS = (
    "records_in",
    "matches_out",
    "duplicates_dropped",
    "decode_fallbacks",
)
_SECONDS_KEYS = (
    "pack_seconds",
    "dispatch_seconds",
    "drain_seconds",
    "device_seconds",
    "decode_seconds",
    "gc_seconds",
)


class FlightRecorder:
    """Bounded ring of per-batch flight records with JSONL dump-on-event.

    ``capacity`` bounds the ring (oldest records drop, counted).
    ``path`` is the dump destination *prefix*: each dump writes
    ``<path>-<reason>-<n>.jsonl`` (``n`` monotone per recorder); without
    a path, :meth:`dump` returns the records and writes nothing.
    ``quarantine_burst`` is the per-batch dead-letter count at or above
    which the processor triggers an automatic dump.
    """

    def __init__(
        self,
        capacity: int = 64,
        path: Optional[str] = None,
        quarantine_burst: int = 32,
    ):
        self.capacity = max(int(capacity), 1)
        self.path = path
        self.quarantine_burst = max(int(quarantine_burst), 1)
        self.records: deque = deque(maxlen=self.capacity)
        self.dropped = 0  # records aged out of the ring
        self.dumps = 0
        self.dump_paths: List[str] = []
        self._base: Dict[str, Any] = {}
        self._lock = threading.Lock()

    # -- recording (one call per processed batch) ---------------------------

    def observe(self, processor, corr: Optional[str] = None) -> Dict[str, Any]:
        """Append one per-batch record built from ``processor``'s live
        state.  Called by :class:`~kafkastreams_cep_tpu.runtime.processor.
        CEPProcessor` at the end of every batch when a recorder is
        attached; safe to call manually (e.g. between supervisor steps).
        """
        import jax
        import jax.numpy as jnp

        reg = processor.metrics.registry
        flat: Dict[str, Any] = {
            k: reg.counter(k).value for k in _RUNTIME_KEYS + _SECONDS_KEYS
        }
        flat.update(processor.counters())
        flat.update(processor.hot_counters())
        flat.update(processor.walk_counters())
        guard = getattr(processor, "_guard", None)
        if guard is not None:
            flat.update(guard.loss_counters())
        # Tiered processors wrap the engine state (engine/tiered.py).
        state = getattr(processor.state, "engine", processor.state)
        # Two tiny device reductions; jax.device_get syncs them together.
        slab_live, ring_pending = (
            int(v)
            for v in jax.device_get(
                (
                    jnp.sum(state.slab.stage >= 0),
                    jnp.sum(state.hr_count),
                )
            )
        )
        with self._lock:
            delta = positive_delta(flat, self._base)
            self._base = flat
            rec = {
                "type": "flight_record",
                "corr": corr or f"{processor.name}-{processor._batch_seq}",
                "seq": int(processor._batch_seq),
                "ts_ms": round(time.time() * 1000.0, 3),
                "records_in": delta.pop("records_in", 0),
                "matches_out": delta.pop("matches_out", 0),
                "phase_seconds": {
                    k[: -len("_seconds")]: round(delta.pop(k), 6)
                    for k in _SECONDS_KEYS
                    if k in delta
                },
                # Only the counters that MOVED this batch — a healthy
                # batch's record stays small.
                "counters": {
                    k: int(v)
                    for k, v in delta.items()
                    if isinstance(v, (int, float))
                },
                "watermark": processor._watermark,
                "slab_live": slab_live,
                "ring_pending": ring_pending,
                "lanes": len(processor._lane_of),
            }
            if guard is not None:
                rec["held"] = int(guard.held)
                rec["dead_letters"] = int(
                    sum(guard.reason_counts.values())
                )
            if len(self.records) == self.records.maxlen:
                self.dropped += 1
            self.records.append(rec)
        return rec

    def note(self, **attrs: Any) -> None:
        """Attach extra context to the newest record (escalation state,
        recovery round, ...) — a no-op on an empty ring."""
        with self._lock:
            if self.records:
                self.records[-1].update(attrs)

    # -- dumping ------------------------------------------------------------

    def dump(
        self, reason: str, corr: Optional[str] = None
    ) -> Optional[str]:
        """Write the ring as JSONL (header line + one line per record,
        oldest first) to ``<path>-<reason>-<n>.jsonl``; returns the path,
        or the record list when the recorder has no path.  The ring is
        NOT cleared — consecutive triggers each ship full context."""
        with self._lock:
            self.dumps += 1
            n = self.dumps
            records = list(self.records)
            header = {
                "type": "flight_dump",
                "reason": reason,
                "corr": corr,
                "ts_ms": round(time.time() * 1000.0, 3),
                "records": len(records),
                "dropped": self.dropped,
                "capacity": self.capacity,
            }
        if self.path is None:
            return [header] + records  # type: ignore[return-value]
        path = f"{self.path}-{reason}-{n}.jsonl"
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(header, default=str) + "\n")
            for rec in records:
                f.write(json.dumps(rec, default=str) + "\n")
        os.replace(tmp, path)  # a torn dump never shadows a complete one
        self.dump_paths.append(path)
        logger.warning(
            "flight recorder dumped %d batch records to %s (reason=%s, "
            "corr=%s)", len(records), path, reason, corr,
        )
        return path


def read_dump(path: str) -> Dict[str, Any]:
    """Parse one dump file into ``{"header": ..., "records": [...]}`` —
    the inverse of :meth:`FlightRecorder.dump` (diagnostic/test helper)."""
    with open(path, "r", encoding="utf-8") as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    if not lines or lines[0].get("type") != "flight_dump":
        raise ValueError(f"{path} is not a flight-recorder dump")
    return {"header": lines[0], "records": lines[1:]}
