"""The stream-processor analog: micro-batched host→device record pump.

Reference: ``CEPProcessor.java:88-163``.  The reference receives one record
at a time from Kafka Streams, steps one NFA, and forwards matches.  Here a
*micro-batch* of records is grouped by key into device lanes (the partition
analog, SURVEY §2.2), padded to a rectangular ``[K, T]`` batch, scanned in
one device dispatch, and the completed matches are decoded and emitted in
exact arrival order — the order the reference would have forwarded them.

Lane ownership mirrors the reference's per-partition state contract
(``CEPProcessor.java:117-134``): each key owns one lane's run queue, slab,
and fold state for the processor's lifetime; checkpoints externalize those
arrays (``runtime/checkpoint.py``).

Time is int32 on device (the TPU-native width).  Epoch-millisecond
timestamps don't fit, so the processor subtracts a fixed ``epoch`` (default:
the first record's timestamp) from every record before transfer; windows
compare time *differences*, which rebasing preserves exactly.  Predicates
therefore observe rebased timestamps — pass ``epoch=0`` if a predicate
matches on absolute time and your timestamps are small.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List, NamedTuple, Optional, Sequence as Seq, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kafkastreams_cep_tpu import native
from kafkastreams_cep_tpu.engine.matcher import (
    OFFSET_LIMIT,
    EngineConfig,
    EventBatch,
)
from kafkastreams_cep_tpu.parallel.batch import BatchMatcher
from kafkastreams_cep_tpu.utils.events import Event, Sequence
from kafkastreams_cep_tpu.utils.metrics import Metrics

from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime")

_I32 = np.iinfo(np.int32)


class Record(NamedTuple):
    """One input record, the host analog of a Kafka ``(key, value, ts)``.

    ``offset`` is the record's log position within its key's lane: pass the
    source offset (Kafka-style) to enable replay dedup, or leave ``None``
    for auto-assignment.  Mixing explicit and auto offsets within one lane
    is allowed but auto always continues past the highest seen.
    """

    key: Hashable
    value: Any
    timestamp: int
    offset: Optional[int] = None


def _bucket(t: int) -> int:
    """Round a batch length up to the next power of two so recompiles are
    bounded (one trace per bucket) instead of one per distinct length."""
    n = 1
    while n < t:
        n *= 2
    return n


class CEPProcessor:
    """Micro-batching processor: records in, :class:`Sequence` matches out.

    ``num_lanes`` bounds the number of distinct keys (the partition count
    analog); a new key claims a free lane and keeps it for the processor's
    lifetime — one more key than lanes raises, like an unassigned Kafka
    partition would.  Values must share one numeric pytree structure
    (scalars or nested dicts of scalars): they are stacked into device
    arrays and handed to predicates as traced pytrees.  The first record
    fixes the schema (leaf structure and int/float dtypes), like a serde; a
    later record with a float where the schema says int is rejected rather
    than silently truncated.

    Predicates receive the record key as a numeric scalar: integer keys
    pass through unchanged; any other key type is represented by its lane
    index (keys must then not be matched on — the reference's lambdas can
    close over arbitrary keys, a device program cannot).

    **At-least-once dedup (deviation — fixes reference README.md:108).**
    The reference corrupts runs when records replay; here each lane keeps a
    high-water mark, and a record whose explicit ``offset`` is below it is
    dropped (counted in ``metrics.duplicates_dropped``).  Pass
    ``dedup=False`` to reproduce the reference's replay behavior.

    ``process(records)`` accepts any number of records, splits them into
    per-lane queues, pads to the max queue length (bucketed to powers of
    two so jit retraces are bounded), scans the whole batch in one jitted
    dispatch, and returns ``(key, Sequence)`` pairs in the exact order the
    reference's per-record loop would have forwarded them
    (``CEPProcessor.java:154-163``): by arrival of the completing record,
    then run-queue order.
    """

    def __init__(
        self,
        pattern,
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        topic: str = "stream",
        epoch: Optional[int] = None,
        gc_events: bool = True,
        dedup: bool = True,
        gc_interval: int = 16,
        gc_events_interval: int = 8,
        mesh=None,
    ):
        # ``mesh``: a ``jax.sharding.Mesh`` shards the lane axis over the
        # devices (state-follows-partition, ``CEPProcessor.java:117-134`` —
        # each lane's run queue/slab/folds live on exactly one device for
        # the processor's lifetime).  The rest of the runtime is identical:
        # checkpoints gather to host arrays (mesh-agnostic, so a restore
        # may re-place onto a different mesh — the rebalance analog).
        self.mesh = mesh
        if mesh is not None:
            from kafkastreams_cep_tpu.parallel.sharding import ShardedMatcher

            self.batch = ShardedMatcher(pattern, num_lanes, mesh, config)
        else:
            self.batch = BatchMatcher(pattern, num_lanes, config)
        self.topic = topic
        self.num_lanes = int(num_lanes)
        # Maintenance sweep every N batches (0 = off; on by default —
        # unbounded streams need it twice over).  Long streams strand
        # walk-bound-truncated paths in the slab (counted in ``trunc``);
        # the sweep frees entries no future buffer op can reach, holding
        # occupancy bounded at fixed slab_entries.  The same sweep also
        # renormalizes Dewey versions (EngineConfig.renorm_versions) so
        # straddling runs' per-event version growth (NFA.java:185-188)
        # doesn't exhaust the fixed dewey_depth.
        self.gc_interval = int(gc_interval)
        # Host-event GC cadence: _gc_events costs a full device_get of slab
        # keys + run state; amortizing it every N batches keeps the host
        # mirror bounded without a per-batch sync (VERDICT round-4 item 9).
        self.gc_events_interval = max(int(gc_events_interval), 1)
        self.state = self.batch.init_state()
        self.epoch = epoch  # None = rebase to the first record's timestamp
        self.gc_events = gc_events
        self.dedup = dedup
        self._lane_of: Dict[Hashable, int] = {}
        self._key_of: Dict[int, Hashable] = {}
        self._next_offset = np.zeros(self.num_lanes, dtype=np.int64)
        # Per-lane offset base: the engine sees offsets rebased to log
        # positions (device offsets must stay < 2^24 for the slab's f32
        # pointer packing, engine.matcher.OFFSET_LIMIT); the first record of
        # a lane fixes its base, like `epoch` does for timestamps.
        self._off_base = np.full(self.num_lanes, -1, dtype=np.int64)
        # Host event mirror, keyed by *device* (rebased) offset per lane.
        self._events: List[Dict[int, Event]] = [dict() for _ in range(self.num_lanes)]
        self._value_proto = None
        self.metrics = Metrics()

    # -- key -> lane assignment (partition-assignment analog) ---------------

    def lane(self, key: Hashable) -> int:
        existing = self._lane_of.get(key)
        if existing is not None:
            return existing
        lane = len(self._lane_of)
        if lane >= self.num_lanes:
            raise ValueError(
                f"more than num_lanes={self.num_lanes} distinct keys; "
                f"size the processor for the key cardinality it serves"
            )
        self._lane_of[key] = lane
        self._key_of[lane] = key
        logger.info("assigned key %r to lane %d", key, lane)
        return lane

    def _key_code(self, key: Hashable, lane: int) -> int:
        if isinstance(key, (int, np.integer)) and _I32.min <= key <= _I32.max:
            return int(key)
        return lane

    def _rebased_ts(self, timestamp: int) -> int:
        rel = int(timestamp) - self.epoch
        if not (_I32.min <= rel <= _I32.max):
            raise ValueError(
                f"timestamp {timestamp} is {rel} ms from the processor epoch "
                f"{self.epoch}, outside int32 device time (~±24.8 days); "
                "construct the processor with an epoch near your stream's "
                "timestamps"
            )
        return rel

    # -- the per-batch hot path --------------------------------------------

    def process(self, records: Seq[Record]) -> List[Tuple[Hashable, Sequence]]:
        if not records:
            return []
        K = self.num_lanes
        if self.epoch is None:
            self.epoch = int(records[0].timestamp)
        if self._value_proto is None:
            # A pytree of dtypes with the records' value structure (kept as
            # plain picklable objects for the checkpoint header).
            leaves0, treedef0 = jax.tree_util.tree_flatten(records[0].value)
            self._value_proto = jax.tree_util.tree_unflatten(
                treedef0,
                [
                    np.dtype(np.float32)
                    if np.issubdtype(np.asarray(l).dtype, np.floating)
                    else np.dtype(np.int32)
                    for l in leaves0
                ],
            )
        dtypes, treedef = jax.tree_util.tree_flatten(self._value_proto)

        # Validate the whole batch BEFORE mutating any lane bookkeeping, so
        # a bad record rejects the batch atomically (nothing half-ingested).
        # Lane assignment is simulated first and committed only after
        # validation — a rejected batch must not consume lane slots.
        # Offsets are simulated the same way: explicit ones below the lane's
        # high-water mark are duplicates (at-least-once replay) and dropped.
        lane_sim = dict(self._lane_of)
        lanes = []
        for rec in records:
            lane = lane_sim.get(rec.key)
            if lane is None:
                lane = len(lane_sim)
                if lane >= self.num_lanes:
                    raise ValueError(
                        f"more than num_lanes={self.num_lanes} distinct "
                        "keys; size the processor for the key cardinality "
                        "it serves"
                    )
                lane_sim[rec.key] = lane
            lanes.append(lane)
        rel_ts = [self._rebased_ts(rec.timestamp) for rec in records]
        next_sim = self._next_offset.copy()
        base_sim = self._off_base.copy()
        offsets: List[Optional[int]] = []
        batch_leaves = []
        for rank, rec in enumerate(records):
            leaves = jax.tree_util.tree_leaves(rec.value)
            if len(leaves) != len(dtypes):
                raise ValueError(
                    f"record {rank}: value structure differs from the "
                    "schema fixed by the first record"
                )
            for leaf, dt in zip(leaves, dtypes):
                if np.issubdtype(np.asarray(leaf).dtype, np.floating) and not np.issubdtype(dt, np.floating):
                    raise ValueError(
                        f"record {rank}: float value {leaf!r} in a field the "
                        "schema (fixed by the first record) typed as int"
                    )
            batch_leaves.append(leaves)
            lane = lanes[rank]
            off = rec.offset if rec.offset is not None else int(next_sim[lane])
            if self.dedup and off < next_sim[lane]:
                offsets.append(None)  # duplicate — high-water mark drop
            else:
                if base_sim[lane] < 0:
                    base_sim[lane] = off  # first record fixes the lane base
                dev = off - int(base_sim[lane])
                if dev < 0:
                    raise ValueError(
                        f"record {rank}: offset {off} is below lane "
                        f"{lane}'s base {int(base_sim[lane])} (out-of-order "
                        "replay below the first seen offset needs dedup=True)"
                    )
                if dev >= OFFSET_LIMIT:
                    raise ValueError(
                        f"record {rank}: offset {off} is {dev} past lane "
                        f"{lane}'s base — per-lane log positions must stay "
                        f"below 2^24 (engine f32 pointer packing)"
                    )
                offsets.append(off)
                next_sim[lane] = max(next_sim[lane], off + 1)

        # Validation passed — commit the simulated lane assignments.
        for key, lane in lane_sim.items():
            if key not in self._lane_of:
                self._lane_of[key] = lane
                self._key_of[lane] = key
                logger.info("assigned key %r to lane %d", key, lane)

        # Host-event bookkeeping (the decode mirror), one pass.  Events keep
        # their true source offsets; the mirror is keyed by device offset.
        self._off_base = base_sim
        dropped = 0
        for rank, rec in enumerate(records):
            off = offsets[rank]
            if off is None:
                dropped += 1
                continue
            lane = lanes[rank]
            self._next_offset[lane] = max(self._next_offset[lane], off + 1)
            event = Event(
                rec.key, rec.value, int(rec.timestamp), self.topic, lane, off
            )
            self._events[lane][off - int(self._off_base[lane])] = event
        self.metrics.duplicates_dropped += dropped
        if dropped:
            logger.info("dropped %d replayed records (high-water mark)", dropped)
        if all(off is None for off in offsets):
            return []

        # Lane-queue positions + columnar [K, T] packing via the native
        # ingest kernels (NumPy fallbacks inside, ``native/``).
        n = len(records)
        lanes_arr = np.asarray(lanes, dtype=np.int32)
        keep = np.fromiter(
            (off is not None for off in offsets), dtype=np.uint8, count=n
        )
        pos, _qlen, max_len = native.queue_positions(lanes_arr, keep, K)
        T = _bucket(max_len)

        key_col = np.fromiter(
            (
                self._key_code(rec.key, lanes[rank])
                for rank, rec in enumerate(records)
            ),
            dtype=np.int32,
            count=n,
        )
        ts_col = np.asarray(rel_ts, dtype=np.int32)
        off_col = np.fromiter(
            (
                off - int(self._off_base[lanes[rank]]) if off is not None else 0
                for rank, off in enumerate(offsets)
            ),
            dtype=np.int32,
            count=n,
        )
        rank_col = np.arange(n, dtype=np.int64)

        # Pad to [K, T]; padding slots carry valid=False and leave lane
        # state untouched (engine contract, matcher.py step()).
        key_arr = np.zeros((K, T), dtype=np.int32)
        ts = np.zeros((K, T), dtype=np.int32)
        off = np.zeros((K, T), dtype=np.int32)
        valid = np.zeros((K, T), dtype=bool)
        rank_of = np.full((K, T), -1, dtype=np.int64)
        native.pack_column(key_arr, key_col, lanes_arr, pos, keep)
        native.pack_column(ts, ts_col, lanes_arr, pos, keep)
        native.pack_column(off, off_col, lanes_arr, pos, keep)
        native.pack_column(rank_of, rank_col, lanes_arr, pos, keep)
        native.pack_valid(valid, lanes_arr, pos, keep)
        val_leaves = [np.zeros((K, T), dtype=dt) for dt in dtypes]
        for i, dt in enumerate(dtypes):
            col = np.asarray([leaves[i] for leaves in batch_leaves], dtype=dt)
            native.pack_column(val_leaves[i], col, lanes_arr, pos, keep)

        events = EventBatch(
            key=jnp.asarray(key_arr),
            value=jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(v) for v in val_leaves]
            ),
            ts=jnp.asarray(ts),
            off=jnp.asarray(off),
            valid=jnp.asarray(valid),
        )
        if self.mesh is not None:
            events = self.batch.shard_events(events)

        with self.metrics.timed("device_seconds"):
            self.state, out = self.batch.scan(self.state, events)
            if self.gc_interval and (self.metrics.batches + 1) % self.gc_interval == 0:
                self.state = self.batch.sweep(self.state)
            jax.block_until_ready(out.count)
        with self.metrics.timed("decode_seconds"):
            matches = self._decode(out, rank_of)
            if self.gc_events and (
                (self.metrics.batches + 1) % self.gc_events_interval == 0
            ):
                self._gc_events()
        self.metrics.records_in += len(records) - dropped
        self.metrics.matches_out += len(matches)
        self.metrics.batches += 1
        return matches

    def _decode(self, out, rank_of) -> List[Tuple[Hashable, Sequence]]:
        """Device walk outputs -> (key, Sequence), in arrival order.

        Vectorized: one device_get, hit discovery and ordering in numpy;
        Python touches only actual match rows (typically a tiny fraction of
        [K, T, R]), not the full grid.
        """
        stage = np.asarray(jax.device_get(out.stage))  # [K, T, R, W]
        off = np.asarray(jax.device_get(out.off))
        count = np.asarray(jax.device_get(out.count))  # [K, T, R]
        names = self.batch.names
        ks, ts, rs = np.nonzero(count)
        if ks.size == 0:
            return []
        # Arrival order (rank of the completing record), then queue order.
        order = np.lexsort((rs, rank_of[ks, ts]))
        ks, ts, rs = ks[order], ts[order], rs[order]
        cnts = count[ks, ts, rs]
        stages = stage[ks, ts, rs]  # [M, W]
        offs = off[ks, ts, rs]
        matches: List[Tuple[Hashable, Sequence]] = []
        for i in range(ks.size):
            k = int(ks[i])
            seq = Sequence()
            ev_store = self._events[k]
            for w in range(int(cnts[i])):
                seq.add(names[int(stages[i, w])], ev_store[int(offs[i, w])])
            matches.append((self._key_of[k], seq))
        return matches

    def _gc_events(self) -> None:
        """Drop host events no longer reachable from device state.

        The device slab GCs entries by refcount exactly like the reference
        buffer (``KVSharedVersionedBuffer.java:147-171``); the host mirror
        only needs events still present in a lane's slab or pointed at by a
        live run, so everything else is released here after each batch.
        """
        slab_stage = np.asarray(jax.device_get(self.state.slab.stage))  # [K, E]
        slab_off = np.asarray(jax.device_get(self.state.slab.off))
        run_alive = np.asarray(jax.device_get(self.state.alive))  # [K, R]
        run_off = np.asarray(jax.device_get(self.state.event_off))
        for k in range(self.num_lanes):
            live = set(slab_off[k][slab_stage[k] >= 0].tolist())
            live.update(run_off[k][run_alive[k]].tolist())
            store = self._events[k]
            dead = [o for o in store if o not in live]
            for o in dead:
                del store[o]

    def place(self, state):
        """Device placement for host-built state (mesh-aware) — used by
        checkpoint restore so snapshots re-place onto whatever mesh this
        processor runs on."""
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            return jax.device_put(
                state,
                NamedSharding(self.mesh, PartitionSpec(self.batch.axis)),
            )
        return jax.device_put(state)

    # -- diagnostics --------------------------------------------------------

    def counters(self) -> Dict[str, int]:
        """Lane-summed overflow/drop counters (all zero in healthy runs)."""
        return self.batch.counters(self.state)

    def metrics_snapshot(self) -> Dict[str, float]:
        """Runtime metrics + engine counters in one flat dict."""
        return self.metrics.snapshot(self.counters())
