"""The stream-processor analog: micro-batched host→device record pump.

Reference: ``CEPProcessor.java:88-163``.  The reference receives one record
at a time from Kafka Streams, steps one NFA, and forwards matches.  Here a
*micro-batch* of records is grouped by key into device lanes (the partition
analog, SURVEY §2.2), padded to a rectangular ``[K, T]`` batch, scanned in
one device dispatch, and the completed matches are decoded and emitted in
exact arrival order — the order the reference would have forwarded them.

Lane ownership mirrors the reference's per-partition state contract
(``CEPProcessor.java:117-134``): each key owns one lane's run queue, slab,
and fold state for the processor's lifetime; checkpoints externalize those
arrays (``runtime/checkpoint.py``).

Time is int32 on device (the TPU-native width).  Epoch-millisecond
timestamps don't fit, so the processor subtracts a fixed ``epoch`` (default:
the first record's timestamp) from every record before transfer; windows
compare time *differences*, which rebasing preserves exactly.  Predicates
therefore observe rebased timestamps — pass ``epoch=0`` if a predicate
matches on absolute time and your timestamps are small.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Hashable, List, NamedTuple, Optional, Sequence as Seq, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kafkastreams_cep_tpu import native
from kafkastreams_cep_tpu.engine.matcher import (
    OFFSET_LIMIT,
    EngineConfig,
    EventBatch,
)
from kafkastreams_cep_tpu.parallel.batch import BatchMatcher
from kafkastreams_cep_tpu.runtime.ingest import (
    REASON_LANE_OVERFLOW,
    REASON_LATE,
    REASON_OVERLOAD_SHED,
    REASON_SCHEMA,
    REASON_TIME_RANGE,
    Defect,
    IngestGuard,
    IngestPolicy,
)
from kafkastreams_cep_tpu.runtime.overload import shed_keep as _shed_keep
from kafkastreams_cep_tpu.utils import tracecache
from kafkastreams_cep_tpu.utils.events import Event, Sequence
from kafkastreams_cep_tpu.utils.failpoints import fire as _failpoint
from kafkastreams_cep_tpu.utils.metrics import Metrics, device_memory_stats
from kafkastreams_cep_tpu.utils.telemetry import TraceSink, maybe_span

from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime")

_I32 = np.iinfo(np.int32)


class InputRejected(ValueError):
    """Deterministic input rejection by processor validation.

    Raised *before* any lane bookkeeping or device state mutates (batch
    validation is atomic), so the batch is bad, not the engine: a
    restore-and-replay recovery cycle cannot help and must not run.  The
    supervisor keys on this exact type — a plain ``ValueError`` out of a
    device dispatch (JAX surfaces some device faults that way) still
    triggers recovery.  Subclasses ``ValueError`` so pre-existing callers'
    except clauses keep working.
    """


class Record(NamedTuple):
    """One input record, the host analog of a Kafka ``(key, value, ts)``.

    ``offset`` is the record's log position within its key's lane: pass the
    source offset (Kafka-style) to enable replay dedup, or leave ``None``
    for auto-assignment.  Mixing explicit and auto offsets within one lane
    is allowed but auto always continues past the highest seen.
    """

    key: Hashable
    value: Any
    timestamp: int
    offset: Optional[int] = None


def _bucket(t: int) -> int:
    """Round a batch length up to the next power of two so recompiles are
    bounded (one trace per bucket) instead of one per distinct length."""
    n = 1
    while n < t:
        n *= 2
    return n


class CEPProcessor:
    """Micro-batching processor: records in, :class:`Sequence` matches out.

    ``num_lanes`` bounds the number of distinct keys (the partition count
    analog); a new key claims a free lane and keeps it for the processor's
    lifetime — one more key than lanes raises, like an unassigned Kafka
    partition would.  Values must share one numeric pytree structure
    (scalars or nested dicts of scalars): they are stacked into device
    arrays and handed to predicates as traced pytrees.  The first record
    fixes the schema (leaf structure and int/float dtypes), like a serde; a
    later record with a float where the schema says int is rejected rather
    than silently truncated.

    Predicates receive the record key as a numeric scalar: integer keys
    pass through unchanged; any other key type is represented by its lane
    index (keys must then not be matched on — the reference's lambdas can
    close over arbitrary keys, a device program cannot).

    **At-least-once dedup (deviation — fixes reference README.md:108).**
    The reference corrupts runs when records replay; here each lane keeps a
    high-water mark, and a record whose explicit ``offset`` is below it is
    dropped (counted in ``metrics.duplicates_dropped``).  Pass
    ``dedup=False`` to reproduce the reference's replay behavior.

    ``process(records)`` accepts any number of records, splits them into
    per-lane queues, pads to the max queue length (bucketed to powers of
    two so jit retraces are bounded), scans the whole batch in one jitted
    dispatch, and returns ``(key, Sequence)`` pairs in the exact order the
    reference's per-record loop would have forwarded them
    (``CEPProcessor.java:154-163``): by arrival of the completing record,
    then run-queue order.
    """

    def __init__(
        self,
        pattern,
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        topic: str = "stream",
        epoch: Optional[int] = None,
        gc_events: bool = True,
        dedup: bool = True,
        gc_interval: int = 16,
        gc_events_interval: int = 8,
        decode_budget: int = 131072,
        pipeline: bool = False,
        mesh=None,
        trace_sink: Optional[TraceSink] = None,
        name: Optional[str] = None,
        drain_interval: int = 1,
        ingest: Optional[IngestPolicy] = None,
        flight=None,
        profile=None,
        clock=None,
        latency=None,
    ):
        # ``profile``: an optional measured ``per_stage`` selectivity
        # snapshot (``stage_counters()`` of an attribution run) handed to
        # the tiered matcher's lazy-chain conjunct ordering; ignored
        # untiered.  The supervisor's adaptive replanner
        # (runtime/supervisor.py AdaptPolicy) rebuilds the processor with
        # a fresh measured profile when observed selectivity drifts.
        # ``mesh``: a ``jax.sharding.Mesh`` shards the lane axis over the
        # devices (state-follows-partition, ``CEPProcessor.java:117-134`` —
        # each lane's run queue/slab/folds live on exactly one device for
        # the processor's lifetime).  The rest of the runtime is identical:
        # checkpoints gather to host arrays (mesh-agnostic, so a restore
        # may re-place onto a different mesh — the rebalance analog).
        self.mesh = mesh
        tiering = config is not None and getattr(config, "tiering", False)
        if mesh is not None:
            from kafkastreams_cep_tpu.parallel.sharding import ShardedMatcher

            if tiering:
                # The tiered matcher's host control flow (per-tier
                # dispatch selection) is not expressible under shard_map
                # today; refusing beats silently restoring a tiered
                # checkpoint into an untiered shape.
                raise ValueError(
                    "EngineConfig.tiering is single-chip: construct the "
                    "processor without a mesh (or without tiering)"
                )
            self.batch = ShardedMatcher(pattern, num_lanes, mesh, config)
        elif tiering:
            from kafkastreams_cep_tpu.parallel.tiered import (
                TieredBatchMatcher,
            )

            self.batch = TieredBatchMatcher(
                pattern, num_lanes, config, profile=profile
            )
        else:
            self.batch = BatchMatcher(pattern, num_lanes, config)
        self.topic = topic
        self.num_lanes = int(num_lanes)
        # Maintenance sweep every N batches (0 = off; on by default —
        # unbounded streams need it twice over).  Long streams strand
        # walk-bound-truncated paths in the slab (counted in ``trunc``);
        # the sweep frees entries no future buffer op can reach, holding
        # occupancy bounded at fixed slab_entries.  The same sweep also
        # renormalizes Dewey versions (EngineConfig.renorm_versions) so
        # straddling runs' per-event version growth (NFA.java:185-188)
        # doesn't exhaust the fixed dewey_depth.
        self.gc_interval = int(gc_interval)
        # Host-event GC cadence: _gc_events costs a full device_get of slab
        # keys + run state; amortizing it every N batches keeps the host
        # mirror bounded without a per-batch sync (VERDICT round-4 item 9).
        self.gc_events_interval = max(int(gc_events_interval), 1)
        # Total compacted match rows the decode pulls per batch (0 =
        # always pull the raw [K, T, R, W] grid); batches with more
        # matches than the budget fall back to the full pull, counted in
        # ``metrics.decode_fallbacks``.  See _decode / ops/decode.py.
        self.decode_budget = int(decode_budget)
        # Pipelined mode (SURVEY §2.2 PP row — the fetch-ahead overlap the
        # reference gets from Kafka Streams' poll loop): process() returns
        # the PREVIOUS batch's matches, so batch N's device scan overlaps
        # batch N+1's host packing and batch N-1's decode.  Call flush()
        # to drain the last batch.  Match content is identical to the
        # serial mode, one call later; the host-event GC cadence drains
        # the pipeline first (its liveness pull must not prune events a
        # pending decode still references).
        self.pipeline = bool(pipeline)
        self._pending: Optional[tuple] = None
        self.state = self.batch.init_state()
        # Lazy extraction (EngineConfig.lazy_extraction): completed matches
        # are compact device handles until the batched drain pass
        # materializes them.  ``drain_interval`` sets the drain cadence in
        # batches (1 = every batch, the default — matches the eager
        # engine's emission latency exactly; larger values trade latency
        # for fewer drain dispatches and need a handle ring sized for the
        # longer interval).  ``flush()`` and checkpoints always drain.
        self.lazy = bool(self.batch.matcher.config.lazy_extraction)
        self.drain_interval = max(int(drain_interval), 1)
        # step_seq value at the start of the current batch's scan — maps a
        # drained handle's absolute completion step back to this batch's
        # t-axis (arrival ordering); restored from device state on resume.
        self._step_base = 0
        self.epoch = epoch  # None = rebase to the first record's timestamp
        self.gc_events = gc_events
        self.dedup = dedup
        self._lane_of: Dict[Hashable, int] = {}
        self._key_of: Dict[int, Hashable] = {}
        self._next_offset = np.zeros(self.num_lanes, dtype=np.int64)
        # Per-lane offset base: the engine sees offsets rebased to log
        # positions (device offsets must stay < 2^24 for the slab's f32
        # pointer packing, engine.matcher.OFFSET_LIMIT); the first record of
        # a lane fixes its base, like `epoch` does for timestamps.
        self._off_base = np.full(self.num_lanes, -1, dtype=np.int64)
        # Host event mirror, keyed by *device* (rebased) offset per lane.
        self._events: List[Dict[int, Event]] = [dict() for _ in range(self.num_lanes)]
        # Columnar-path batches (process_columns): events stay as packed
        # [K, T] columns until a decode or GC touches them — match-sparse
        # streams then never pay per-record Event construction.  Each entry
        # is (start [K], count [K], abs_ts [K, T], value leaves [K, T]...).
        self._col_batches: List[tuple] = []
        self._value_proto = None
        self.metrics = Metrics()
        # Telemetry (utils/telemetry.py): an optional span sink — every
        # process() call emits one "batch" span with nested phase spans
        # (pack -> dispatch -> device -> decode -> gc); None costs one
        # attribute check per phase.  ``name`` labels this processor in
        # per-pattern attribution (bank members pass their query name).
        self.trace = trace_sink
        self.name = name or topic
        self._batch_seq = 0
        # Event-time watermark: the max record timestamp ingested (absolute
        # ms), for the watermark / event-time-lag gauges in
        # ``metrics_snapshot`` — the ``records-lag`` analog.
        self._watermark: Optional[int] = None
        # Ingestion guard (runtime/ingest.py): a watermark-driven reorder
        # buffer + per-record quarantine in front of the engine.  None (the
        # default) keeps the historical batch-atomic front door: any bad
        # record raises InputRejected for the whole batch, and arrival
        # order is the engine order.  With a policy, records are validated
        # per record (defects dead-lettered, or raised under
        # ``on_bad_record="raise"``), held until the watermark passes them,
        # and released to the engine in timestamp order with auto-assigned
        # engine offsets; source offsets drive replay dedup at admission.
        # Injectable wall clock (tests pin a fake): every host-side stamp —
        # the event-time-lag gauge and all latency-ledger boundaries —
        # reads it.  Wall clock (time.time), not perf_counter: stamps must
        # stay comparable across a checkpoint→restore process boundary.
        self._clock = clock if clock is not None else time.time
        # Latency-attribution ledger (utils/latency.py): ``True`` builds a
        # fresh ledger on this processor's clock, an existing ledger is
        # adopted as-is (supervisor restore / bank members sharing one),
        # None/False disarms it — one ``None`` check per call site, zero
        # device work either way.
        if latency is True:
            from kafkastreams_cep_tpu.utils.latency import LatencyLedger

            self.ledger = LatencyLedger(clock=self._clock)
        else:
            self.ledger = latency or None
        self._guard = (
            IngestGuard(ingest, clock=self._clock)
            if ingest is not None
            else None
        )
        # Flight recorder (runtime/flight.py): a bounded ring of per-batch
        # records (phase timings, counter deltas, occupancy) appended at
        # the end of every batch and dumped as JSONL on crash/escalation/
        # quarantine-burst — None costs one check per batch.
        self.flight = flight
        self._dlq_base = 0  # dead-letter total at last batch (burst detect)
        # Brownout actuators (runtime/overload.py, set by the supervisor's
        # OverloadController — never directly by callers):
        # ``overload_admit_fraction`` None = door open; otherwise the
        # fraction of admissible records kept at the ingest door, via a
        # deterministic within-batch Bresenham stride (0.0 = L4, refuse
        # all).  ``telemetry_defer`` skips the per-lane/per-key device
        # gathers in metrics_snapshot while browned out.
        self.overload_admit_fraction: Optional[float] = None
        self.telemetry_defer = False

    def set_clock(self, clock) -> None:
        """Re-inject the host clock everywhere it is read (processor
        stamps, guard admit stamps, ledger commits).  Clocks are not
        durable state — a restored processor runs on wall clock until the
        caller pins one (tests do, for deterministic stamps)."""
        self._clock = clock
        if self._guard is not None:
            self._guard._clock = clock
        if self.ledger is not None:
            self.ledger.clock = clock

    # -- key -> lane assignment (partition-assignment analog) ---------------

    def lane(self, key: Hashable) -> int:
        existing = self._lane_of.get(key)
        if existing is not None:
            return existing
        lane = len(self._lane_of)
        if lane >= self.num_lanes:
            raise InputRejected(
                f"key {key!r}: more than num_lanes={self.num_lanes} "
                "distinct keys; size the processor for the key "
                "cardinality it serves"
            )
        self._lane_of[key] = lane
        self._key_of[lane] = key
        logger.info("assigned key %r to lane %d", key, lane)
        return lane

    def _key_code(self, key: Hashable, lane: int) -> int:
        if isinstance(key, (int, np.integer)) and _I32.min <= key <= _I32.max:
            return int(key)
        return lane

    def _rebased_ts(self, timestamp: int, rank: int = -1, key=None) -> int:
        rel = int(timestamp) - self.epoch
        if not (_I32.min <= rel <= _I32.max):
            where = f"record {rank} (key {key!r}): " if rank >= 0 else ""
            raise InputRejected(
                f"{where}timestamp {timestamp} is {rel} ms from the "
                f"processor epoch {self.epoch}, outside int32 device time "
                "(~±24.8 days); construct the processor with an epoch near "
                "your stream's timestamps"
            )
        return rel

    # -- the per-batch hot path --------------------------------------------

    @contextlib.contextmanager
    def _phase(self, name: str):
        """One batch phase: a nested trace span + the ``{name}_seconds``
        accumulator + the ``phases[name]`` latency histogram, in one."""
        with maybe_span(self.trace, f"phase.{name}"):
            with self.metrics.timed(f"{name}_seconds"):
                yield

    def process(self, records: Seq[Record]) -> List[Tuple[Hashable, Sequence]]:
        if not records:
            return []
        self._batch_seq += 1
        with maybe_span(
            self.trace, "batch", path="records", batch=self._batch_seq,
            records=len(records),
        ) as sp:
            # Release stamp for the latency ledger: batch entry (the guard
            # releases mid-pack; validation time counts as queue).
            lat_t0 = self._clock() if self.ledger is not None else None
            with self._phase("pack"):
                if self._guard is not None:
                    released = self._ingest(
                        list(records), f"{self.name}-{self._batch_seq}"
                    )
                    sp["released"] = len(released)
                    packed = (
                        self._pack_records(released) if released else None
                    )
                else:
                    packed = self._pack_records(records)
            if packed is None:
                # Nothing released/kept this batch — still a flight tick
                # (a quarantine burst can empty a batch entirely).
                self._flight_tick()
                return []
            events, rank_of, n_kept = packed
            sp["lanes"] = len(self._lane_of)
            lat = None
            if self.ledger is not None:
                lat = self.ledger.start_batch(
                    f"{self.name}-{self._batch_seq}", n_kept,
                    admit=(
                        self._guard.last_release_stamps
                        if self._guard is not None
                        else None
                    ),
                    release=lat_t0,
                )
            matches = self._dispatch(events, rank_of, n_kept, lat)
            sp["matches"] = len(matches)
            return matches

    # -- the ingestion guard (runtime/ingest.py) ---------------------------

    def _ingest(self, records: List[Record], corr: str) -> List[Record]:
        """Admit one raw batch through the guard; returns the released
        (watermark-passed, timestamp-ordered) records with engine offsets
        reset to auto — release order IS the engine's log order, and the
        source offsets already did their job (dedup at admission)."""
        guard = self._guard
        # Fault site: before any guard or lane bookkeeping mutates — the
        # batch is rejected wholesale, nothing half-admitted.
        _failpoint("ingest.admit")
        strict = guard.policy.on_bad_record == "raise"
        admit_frac = self.overload_admit_fraction
        n_admissible = 0
        for idx, rec in enumerate(records):
            defect = self._record_defect(rec)
            if defect is None:
                # Brownout shed (runtime/overload.py L3+): AFTER
                # validation and replay dedup — source_hw already
                # advanced, so a re-submitted shed record dedups silently
                # instead of double-counting — and the Bresenham index
                # runs over admissible records only, so replaying the
                # same batch sheds the same records.
                keep = admit_frac is None or _shed_keep(
                    n_admissible, admit_frac
                )
                n_admissible += 1
                if not keep:
                    # Fault site: the shed decision is made but not yet
                    # recorded — recovery replays the batch from the
                    # snapshot + journal and re-sheds deterministically.
                    _failpoint("overload.shed")
                    guard.quarantine(
                        rec, REASON_OVERLOAD_SHED,
                        f"brownout admit fraction {admit_frac}", corr,
                    )
                    # The shed record's event time is still observed:
                    # the watermark keeps advancing so the held backlog
                    # drains while the door is throttled/closed.
                    guard.observe_time(rec.timestamp)
                    continue
                guard.push(rec)
                continue
            if defect.silent:
                self.metrics.duplicates_dropped += 1
                continue
            if strict:
                raise InputRejected(
                    f"record {idx} (key {rec.key!r}): {defect.reason}: "
                    f"{defect.detail}"
                )
            guard.quarantine(rec, defect.reason, defect.detail, corr)
        released = guard.release()
        # Fault site: the adversarial window — the buffer already moved
        # (records admitted, releases popped) but the engine never saw
        # them.  Recovery must restore the buffer from the snapshot and
        # re-admit from the journal (chaos-tested).
        _failpoint("ingest.release")
        return [
            r._replace(offset=None) if r.offset is not None else r
            for r in released
        ]

    def _record_defect(self, rec: Record) -> Optional[Defect]:
        """Validate ONE record against the schema/lane/time contracts the
        batch path enforces atomically; commits schema, epoch, and lane
        assignment on first sight (the guard admits per record, so there
        is no batch to reject).  Returns None when admissible."""
        guard = self._guard
        if self._value_proto is None:
            leaves0, treedef0 = jax.tree_util.tree_flatten(rec.value)
            self._value_proto = jax.tree_util.tree_unflatten(
                treedef0,
                [
                    np.dtype(np.float32)
                    if np.issubdtype(np.asarray(l).dtype, np.floating)
                    else np.dtype(np.int32)
                    for l in leaves0
                ],
            )
        dtypes, treedef = jax.tree_util.tree_flatten(self._value_proto)
        leaves, rec_def = jax.tree_util.tree_flatten(rec.value)
        if rec_def != treedef:
            return Defect(
                REASON_SCHEMA,
                f"value structure {rec_def} differs from the schema "
                f"{treedef} fixed by the first record",
            )
        for field_i, (leaf, dt) in enumerate(zip(leaves, dtypes)):
            if np.issubdtype(np.asarray(leaf).dtype, np.floating) and not (
                np.issubdtype(dt, np.floating)
            ):
                return Defect(
                    REASON_SCHEMA,
                    f"field #{field_i}: float value {leaf!r} in a field "
                    "the schema (fixed by the first record) typed as int",
                )
        lane = self._lane_of.get(rec.key)
        if lane is None:
            if len(self._lane_of) >= self.num_lanes:
                return Defect(
                    REASON_LANE_OVERFLOW,
                    f"key {rec.key!r} would exceed num_lanes="
                    f"{self.num_lanes}; size the processor for the key "
                    "cardinality it serves",
                )
            lane = len(self._lane_of)
            self._lane_of[rec.key] = lane
            self._key_of[lane] = rec.key
            logger.info("assigned key %r to lane %d", rec.key, lane)
        if self.epoch is None:
            self.epoch = int(rec.timestamp)
        rel = int(rec.timestamp) - self.epoch
        if not (_I32.min <= rel <= _I32.max):
            return Defect(
                REASON_TIME_RANGE,
                f"timestamp {rec.timestamp} is {rel} ms from the processor "
                f"epoch {self.epoch}, outside int32 device time "
                "(~±24.8 days)",
            )
        if rec.offset is not None:
            hw = guard.source_hw.get(lane, 0)
            if self.dedup and rec.offset < hw:
                return Defect("duplicate", "", silent=True)
            guard.source_hw[lane] = max(hw, int(rec.offset) + 1)
        behind = guard.late_by(int(rec.timestamp))
        if behind is not None:
            return Defect(
                REASON_LATE,
                f"timestamp {rec.timestamp} is {behind} ms behind the "
                f"watermark {guard.watermark} (grace "
                f"{guard.policy.grace_ms} ms)",
            )
        return None

    def drain_ingest(self) -> List[Tuple[Hashable, Sequence]]:
        """End-of-stream drain of the reorder buffer: release every held
        record regardless of watermark (the stream is declared over, so
        nothing younger can still arrive) and run them through the
        engine.  A no-op without a guard or with an empty buffer.  Call
        :meth:`flush` afterwards for pipelined / lazy processors."""
        if self._guard is None:
            return []
        lat_t0 = self._clock() if self.ledger is not None else None
        released = self._guard.drain()
        if not released:
            return []
        released = [
            r._replace(offset=None) if r.offset is not None else r
            for r in released
        ]
        self._batch_seq += 1
        with maybe_span(
            self.trace, "batch", path="ingest-drain", batch=self._batch_seq,
            records=len(released),
        ) as sp:
            with self._phase("pack"):
                packed = self._pack_records(released)
            if packed is None:
                return []
            lat = None
            if self.ledger is not None:
                lat = self.ledger.start_batch(
                    f"{self.name}-{self._batch_seq}", packed[2],
                    admit=self._guard.last_release_stamps, release=lat_t0,
                )
            matches = self._dispatch(*packed, lat)
            sp["matches"] = len(matches)
            return matches

    def _pack_records(self, records: Seq[Record]):
        """Validate + lane-assign + pad one record batch to ``[K, T]``
        device columns; None when every record was a replay duplicate."""
        K = self.num_lanes
        if self.epoch is None:
            self.epoch = int(records[0].timestamp)
        if self._value_proto is None:
            # A pytree of dtypes with the records' value structure (kept as
            # plain picklable objects for the checkpoint header).
            leaves0, treedef0 = jax.tree_util.tree_flatten(records[0].value)
            self._value_proto = jax.tree_util.tree_unflatten(
                treedef0,
                [
                    np.dtype(np.float32)
                    if np.issubdtype(np.asarray(l).dtype, np.floating)
                    else np.dtype(np.int32)
                    for l in leaves0
                ],
            )
        dtypes, treedef = jax.tree_util.tree_flatten(self._value_proto)

        # Validate the whole batch BEFORE mutating any lane bookkeeping, so
        # a bad record rejects the batch atomically (nothing half-ingested).
        # Lane assignment is simulated first and committed only after
        # validation — a rejected batch must not consume lane slots.
        # Offsets are simulated the same way: explicit ones below the lane's
        # high-water mark are duplicates (at-least-once replay) and dropped.
        lane_sim = dict(self._lane_of)
        lanes = []
        for rank, rec in enumerate(records):
            lane = lane_sim.get(rec.key)
            if lane is None:
                lane = len(lane_sim)
                if lane >= self.num_lanes:
                    raise InputRejected(
                        f"record {rank} (key {rec.key!r}): more than "
                        f"num_lanes={self.num_lanes} distinct keys; size "
                        "the processor for the key cardinality it serves"
                    )
                lane_sim[rec.key] = lane
            lanes.append(lane)
        rel_ts = [
            self._rebased_ts(rec.timestamp, rank, rec.key)
            for rank, rec in enumerate(records)
        ]
        next_sim = self._next_offset.copy()
        base_sim = self._off_base.copy()
        offsets: List[Optional[int]] = []
        batch_leaves = []
        for rank, rec in enumerate(records):
            leaves = jax.tree_util.tree_leaves(rec.value)
            if len(leaves) != len(dtypes):
                raise InputRejected(
                    f"record {rank} (key {rec.key!r}): value structure "
                    f"({len(leaves)} fields) differs from the schema fixed "
                    f"by the first record ({len(dtypes)} fields)"
                )
            for field_i, (leaf, dt) in enumerate(zip(leaves, dtypes)):
                if np.issubdtype(np.asarray(leaf).dtype, np.floating) and not np.issubdtype(dt, np.floating):
                    raise InputRejected(
                        f"record {rank} (key {rec.key!r}): field #{field_i} "
                        f"float value {leaf!r} in a field the schema (fixed "
                        "by the first record) typed as int"
                    )
            batch_leaves.append(leaves)
            lane = lanes[rank]
            off = rec.offset if rec.offset is not None else int(next_sim[lane])
            if self.dedup and off < next_sim[lane]:
                offsets.append(None)  # duplicate — high-water mark drop
            else:
                if base_sim[lane] < 0:
                    base_sim[lane] = off  # first record fixes the lane base
                dev = off - int(base_sim[lane])
                if dev < 0:
                    raise InputRejected(
                        f"record {rank} (key {rec.key!r}): offset {off} is "
                        f"below lane {lane}'s base {int(base_sim[lane])} "
                        "(out-of-order replay below the first seen offset "
                        "needs dedup=True)"
                    )
                if dev >= OFFSET_LIMIT:
                    raise InputRejected(
                        f"record {rank} (key {rec.key!r}): offset {off} is "
                        f"{dev} past lane {lane}'s base — per-lane log "
                        "positions must stay below 2^24 (engine f32 "
                        "pointer packing)"
                    )
                offsets.append(off)
                next_sim[lane] = max(next_sim[lane], off + 1)

        # Validation passed — commit the simulated lane assignments.
        for key, lane in lane_sim.items():
            if key not in self._lane_of:
                self._lane_of[key] = lane
                self._key_of[lane] = key
                logger.info("assigned key %r to lane %d", key, lane)

        # Host-event bookkeeping (the decode mirror), one pass.  Events keep
        # their true source offsets; the mirror is keyed by device offset.
        self._off_base = base_sim
        dropped = 0
        for rank, rec in enumerate(records):
            off = offsets[rank]
            if off is None:
                dropped += 1
                continue
            lane = lanes[rank]
            self._next_offset[lane] = max(self._next_offset[lane], off + 1)
            event = Event(
                rec.key, rec.value, int(rec.timestamp), self.topic, lane, off
            )
            self._events[lane][off - int(self._off_base[lane])] = event
        self.metrics.duplicates_dropped += dropped
        if dropped:
            logger.info("dropped %d replayed records (high-water mark)", dropped)
        wm = max(int(rec.timestamp) for rec in records)
        self._watermark = wm if self._watermark is None else max(self._watermark, wm)
        if all(off is None for off in offsets):
            return None

        # Lane-queue positions + columnar [K, T] packing via the native
        # ingest kernels (NumPy fallbacks inside, ``native/``).
        n = len(records)
        lanes_arr = np.asarray(lanes, dtype=np.int32)
        keep = np.fromiter(
            (off is not None for off in offsets), dtype=np.uint8, count=n
        )
        pos, _qlen, max_len = native.queue_positions(lanes_arr, keep, K)
        T = _bucket(max_len)

        key_col = np.fromiter(
            (
                self._key_code(rec.key, lanes[rank])
                for rank, rec in enumerate(records)
            ),
            dtype=np.int32,
            count=n,
        )
        ts_col = np.asarray(rel_ts, dtype=np.int32)
        off_col = np.fromiter(
            (
                off - int(self._off_base[lanes[rank]]) if off is not None else 0
                for rank, off in enumerate(offsets)
            ),
            dtype=np.int32,
            count=n,
        )
        rank_col = np.arange(n, dtype=np.int64)

        # Pad to [K, T]; padding slots carry valid=False and leave lane
        # state untouched (engine contract, matcher.py step()).
        key_arr = np.zeros((K, T), dtype=np.int32)
        ts = np.zeros((K, T), dtype=np.int32)
        off = np.zeros((K, T), dtype=np.int32)
        valid = np.zeros((K, T), dtype=bool)
        rank_of = np.full((K, T), -1, dtype=np.int64)
        native.pack_column(key_arr, key_col, lanes_arr, pos, keep)
        native.pack_column(ts, ts_col, lanes_arr, pos, keep)
        native.pack_column(off, off_col, lanes_arr, pos, keep)
        native.pack_column(rank_of, rank_col, lanes_arr, pos, keep)
        native.pack_valid(valid, lanes_arr, pos, keep)
        val_leaves = [np.zeros((K, T), dtype=dt) for dt in dtypes]
        for i, dt in enumerate(dtypes):
            col = np.asarray([leaves[i] for leaves in batch_leaves], dtype=dt)
            native.pack_column(val_leaves[i], col, lanes_arr, pos, keep)

        events = EventBatch(
            key=jnp.asarray(key_arr),
            value=jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(v) for v in val_leaves]
            ),
            ts=jnp.asarray(ts),
            off=jnp.asarray(off),
            valid=jnp.asarray(valid),
        )
        return events, rank_of, len(records) - dropped

    def process_columns(
        self, keys, values, timestamps
    ) -> List[Tuple[Hashable, Sequence]]:
        """Columnar ingestion: ``[N]`` arrays instead of Record objects.

        The per-record :meth:`process` spends microseconds of Python per
        record (validation, Event construction) — fine at Kafka-consumer
        rates, the wall at engine rates.  This path validates and packs
        with array ops and defers Event construction until a match (or the
        GC) actually touches an event, so match-sparse streams never pay
        it (the packed columns themselves are the mirror).

        ``keys`` is an ``[N]`` array (numeric keys vectorize; object keys
        fall back to a Python mapping pass), ``values`` a pytree of ``[N]``
        arrays with the schema's structure, ``timestamps`` ``[N]`` ints.
        Offsets are always auto-assigned — explicit-offset replay/dedup
        needs the per-record path.  Emitted Events carry values rebuilt
        from the packed columns (schema dtypes), not the caller's original
        scalars."""
        if self._guard is not None:
            raise ValueError(
                "the ingestion guard runs on the per-record path only; "
                "process_columns bypasses per-record validation and the "
                "reorder buffer (construct the processor without "
                "ingest=... to use the columnar path)"
            )
        self._batch_seq += 1
        with maybe_span(
            self.trace, "batch", path="columns", batch=self._batch_seq,
        ) as sp:
            lat_t0 = self._clock() if self.ledger is not None else None
            with self._phase("pack"):
                packed = self._pack_columns(keys, values, timestamps)
            if packed is None:
                return []
            events, rank_of, n = packed
            sp["records"] = n
            sp["lanes"] = len(self._lane_of)
            lat = None
            if self.ledger is not None:
                lat = self.ledger.start_batch(
                    f"{self.name}-{self._batch_seq}", n, release=lat_t0,
                )
            matches = self._dispatch(events, rank_of, n, lat)
            sp["matches"] = len(matches)
            return matches

    def _pack_columns(self, keys, values, timestamps):
        keys_arr = np.asarray(keys)
        if keys_arr.ndim != 1:
            raise InputRejected(
                f"keys must be a 1-D column, got shape {keys_arr.shape}"
            )
        ts_arr = np.asarray(timestamps, dtype=np.int64)
        n = int(keys_arr.shape[0])
        # One timestamp per record, validated BEFORE the native pack path:
        # pack_column dereferences n column elements by row, so a short
        # timestamps column would be an out-of-bounds read, not an error.
        if ts_arr.shape != (n,):
            raise InputRejected(
                f"timestamps shape {ts_arr.shape} != ({n},); pass exactly "
                "one timestamp per record"
            )
        if n == 0:
            return None
        K = self.num_lanes
        if self.epoch is None:
            self.epoch = int(ts_arr[0])
        leaves_in, treedef_in = jax.tree_util.tree_flatten(values)
        leaves_in = [np.asarray(l) for l in leaves_in]
        if self._value_proto is None:
            self._value_proto = jax.tree_util.tree_unflatten(
                treedef_in,
                [
                    np.dtype(np.float32)
                    if np.issubdtype(l.dtype, np.floating)
                    else np.dtype(np.int32)
                    for l in leaves_in
                ],
            )
        dtypes, treedef = jax.tree_util.tree_flatten(self._value_proto)
        if treedef_in != treedef:
            raise InputRejected(
                "value columns structure differs from the schema fixed by "
                "the first batch"
            )
        for field_i, (l, dt) in enumerate(zip(leaves_in, dtypes)):
            if l.shape != (n,):
                raise InputRejected(
                    f"field #{field_i}: value column shape {l.shape} != "
                    f"({n},)"
                )
            if np.issubdtype(l.dtype, np.floating) and not np.issubdtype(
                dt, np.floating
            ):
                raise InputRejected(
                    f"field #{field_i}: float column in a field the "
                    "schema typed as int"
                )

        # Lane mapping, committed atomically after the overflow check.
        if keys_arr.dtype == object:
            uniq = list(dict.fromkeys(keys_arr.tolist()))
        else:
            vals, first = np.unique(keys_arr, return_index=True)
            uniq = [v.item() for v in vals[np.argsort(first)]]
        new = [k for k in uniq if k not in self._lane_of]
        if len(self._lane_of) + len(new) > K:
            raise InputRejected(
                f"more than num_lanes={K} distinct keys (first overflowing "
                f"key: {new[K - len(self._lane_of)]!r}); size the "
                "processor for the key cardinality it serves"
            )
        for k in new:
            lane = len(self._lane_of)
            self._lane_of[k] = lane
            self._key_of[lane] = k
            logger.info("assigned key %r to lane %d", k, lane)
        if keys_arr.dtype == object:
            lanes_arr = np.fromiter(
                (self._lane_of[k] for k in keys_arr.tolist()),
                dtype=np.int32, count=n,
            )
        else:
            ku = np.fromiter(self._lane_of.keys(), dtype=keys_arr.dtype)
            lv = np.fromiter(self._lane_of.values(), dtype=np.int32)
            order = np.argsort(ku)
            lanes_arr = lv[order][
                np.searchsorted(ku[order], keys_arr)
            ].astype(np.int32)

        rel = ts_arr - self.epoch
        if rel.size and (rel.min() < _I32.min or rel.max() > _I32.max):
            bad = int(
                np.argmax((rel < _I32.min) | (rel > _I32.max))
            )
            raise InputRejected(
                f"record {bad} (key {keys_arr[bad]!r}): timestamp "
                f"{int(ts_arr[bad])} outside int32 device time relative "
                f"to the processor epoch {self.epoch}"
            )
        wm = int(ts_arr.max())
        self._watermark = wm if self._watermark is None else max(self._watermark, wm)

        keep = np.ones(n, dtype=np.uint8)
        pos, qlen, max_len = native.queue_positions(lanes_arr, keep, K)
        # Auto offsets: lane l's batch rows take consecutive log positions
        # from its high-water mark; a fresh lane's base pins to it.
        fresh = (self._off_base < 0) & (qlen > 0)
        self._off_base[fresh] = self._next_offset[fresh]
        start_dev = self._next_offset - self._off_base  # [K] first dev off
        dev_off = (start_dev[lanes_arr] + pos).astype(np.int64)
        if dev_off.size and dev_off.max() >= OFFSET_LIMIT:
            raise InputRejected(
                "per-lane log positions past 2^24 (engine f32 pointer "
                "packing) — rotate the processor via checkpoint/restore"
            )
        self._next_offset += qlen

        T = _bucket(max_len)
        # Per-key decision, exactly like _key_code on the record path: an
        # int32-range integer key passes through, anything else is its
        # lane index (an out-of-range batch-mate must not change another
        # key's code).
        if np.issubdtype(keys_arr.dtype, np.integer):
            in_range = (keys_arr >= _I32.min) & (keys_arr <= _I32.max)
            key_codes = np.where(
                in_range, keys_arr.astype(np.int64),
                lanes_arr.astype(np.int64),
            ).astype(np.int32)
        elif keys_arr.dtype == object:
            # Object columns can mix int and non-int keys; each element
            # must take the code _key_code gives it on the record path (an
            # in-range int keeps its value, anything else its lane index),
            # or record- and column-ingested events of the SAME key would
            # see different ``key`` values in predicates.
            key_codes = np.fromiter(
                (
                    self._key_code(k, int(lanes_arr[i]))
                    for i, k in enumerate(keys_arr.tolist())
                ),
                dtype=np.int32,
                count=n,
            )
        else:
            key_codes = lanes_arr.astype(np.int32)
        key_arr = np.zeros((K, T), dtype=np.int32)
        ts = np.zeros((K, T), dtype=np.int32)
        off = np.zeros((K, T), dtype=np.int32)
        valid = np.zeros((K, T), dtype=bool)
        rank_of = np.full((K, T), -1, dtype=np.int64)
        abs_ts = np.zeros((K, T), dtype=np.int64)
        native.pack_column(key_arr, key_codes, lanes_arr, pos, keep)
        native.pack_column(ts, rel.astype(np.int32), lanes_arr, pos, keep)
        native.pack_column(off, dev_off.astype(np.int32), lanes_arr, pos, keep)
        native.pack_column(rank_of, np.arange(n, dtype=np.int64), lanes_arr, pos, keep)
        native.pack_column(abs_ts, ts_arr, lanes_arr, pos, keep)
        native.pack_valid(valid, lanes_arr, pos, keep)
        val_leaves = [np.zeros((K, T), dtype=dt) for dt in dtypes]
        for i, dt in enumerate(dtypes):
            native.pack_column(
                val_leaves[i], leaves_in[i].astype(dt), lanes_arr, pos, keep
            )

        # Lazy mirror: the packed columns ARE the event store until a
        # match or the GC touches a row.
        col_start = np.where(qlen > 0, start_dev, -1).astype(np.int64)
        self._col_batches.append(
            (col_start, qlen.astype(np.int64), abs_ts, val_leaves)
        )

        events = EventBatch(
            key=jnp.asarray(key_arr),
            value=jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(v) for v in val_leaves]
            ),
            ts=jnp.asarray(ts),
            off=jnp.asarray(off),
            valid=jnp.asarray(valid),
        )
        return events, rank_of, n

    def _dispatch(self, events, rank_of, n_records, lat=None):
        # Fault-injection sites (utils/failpoints.py; no-ops unless a test
        # armed them): ``device.dispatch`` fails before the scan — state
        # untouched; ``device.result`` fails after ``self.state`` advanced
        # but before the batch's matches reach the caller — the adversarial
        # window the supervisor's restore-and-replay must cover.
        _failpoint("device.dispatch")
        if self.mesh is not None:
            # Shard fault site: the host→mesh transfer is where a dead
            # device first surfaces on the sharded path — state untouched,
            # so the supervisor's evacuation can restore-and-replay onto
            # the surviving sub-mesh (arm with ShardLost to drive it).
            _failpoint("shard.dispatch")
            events = self.batch.shard_events(events)

        base = self._step_base
        if lat is not None:
            lat.dispatch = self._clock()
        with self._phase("dispatch"):
            # Enqueue only: the scan (and any due sweep) dispatch async;
            # the wait is attributed to the device phase below.
            self.state, out = self.batch.scan(self.state, events)
            self._step_base += int(events.ts.shape[1])
            if self.gc_interval and (self.metrics.batches + 1) % self.gc_interval == 0:
                # Pending lazy handles survive the sweep by construction:
                # they are mark-sweep liveness roots and renorm rows
                # (parallel/batch.py sweep_lanes).
                self.state = self.batch.sweep(self.state)
        drain_out = None
        if self.lazy and (
            (self.metrics.batches + 1) % self.drain_interval == 0
        ):
            with self._phase("drain"):
                # One batched pass materializes every pending handle —
                # the deferred analog of the eager in-step extraction
                # walks, off the per-step critical path.
                self.state, drain_out = self.batch.drain(self.state)
        with self._phase("device"):
            if not self.pipeline:
                # Serial mode: wait here so device_seconds is the real
                # device wall time.  Pipelined mode never blocks on the
                # fresh dispatch — the wait lands in the next call's
                # decode of THIS batch, overlapped with its device scan.
                jax.block_until_ready(
                    out.count if drain_out is None else drain_out.count
                )
        if lat is not None:
            # Device-completion stamp: rides the existing gates transfer —
            # no extra device_get.  Serial mode just blocked, so this is
            # real completion; pipelined mode observes the enqueue point
            # (the wait lands in the next call's decode, and so does the
            # stamp's tail — host-observed by design).
            lat.complete = self._clock()
        _failpoint("device.result")
        gc_due = self.gc_events and (
            (self.metrics.batches + 1) % self.gc_events_interval == 0
        )
        self.metrics.records_in += n_records
        self.metrics.batches += 1
        with self._phase("decode"):
            if self.pipeline:
                prev, self._pending = (
                    self._pending, (out, rank_of, drain_out, base, lat),
                )
                matches = self._decode(*prev[:4]) if prev is not None else []
                if prev is not None:
                    self._lat_finish(
                        prev[4], (not self.lazy) or prev[2] is not None
                    )
                if gc_due:
                    # The GC liveness pull must not prune events the
                    # still-pending decode references: drain first.
                    pend, self._pending = self._pending, None
                    matches += self._decode(*pend[:4])
                    self._lat_finish(
                        pend[4], (not self.lazy) or pend[2] is not None
                    )
            else:
                matches = self._decode(out, rank_of, drain_out, base)
                self._lat_finish(
                    lat, (not self.lazy) or drain_out is not None
                )
        if gc_due:
            with self._phase("gc"):
                self._gc_events()
        self.metrics.matches_out += len(matches)
        self._flight_tick()
        return matches

    def _lat_finish(self, lat, emitted: bool) -> None:
        """Commit or defer one batch's latency bundle at its decode.

        ``emitted`` means the batch's matches just left the device (eager
        decode, or this batch's drain carried its handles): the bundle —
        plus any parked earlier bundles whose handles rode the same drain
        — commits at one emit stamp.  Otherwise (lazy, drain not due) the
        bundle parks until the drain that emits it; a bundle that never
        commits because its batch failed dies with the rollback and is
        re-observed on replay — exactly-once counts, honest wall clock.
        """
        if lat is None or self.ledger is None:
            return
        if emitted:
            emit = self._clock()
            self.ledger.commit_deferred(emit)
            self.ledger.commit(lat, emit)
        else:
            self.ledger.defer(lat)

    def _flight_tick(self) -> None:
        """Record this batch in the flight ring (runtime/flight.py) and
        trigger a quarantine-burst dump when the guard dead-lettered a
        burst's worth of records in one batch.  One ``None`` check when
        no recorder is attached."""
        if self.flight is None:
            return
        corr = f"{self.name}-{self._batch_seq}"
        self.flight.observe(self, corr=corr)
        if self._guard is not None:
            total = int(sum(self._guard.reason_counts.values()))
            if total - self._dlq_base >= self.flight.quarantine_burst:
                self.flight.dump("quarantine_burst", corr=corr)
            self._dlq_base = total

    def flush(self) -> List[Tuple[Hashable, Sequence]]:
        """Drain the pipelined in-flight batch (no-op in serial mode or
        when nothing is pending), and — under lazy extraction — also
        drain any handles still pending on device (a ``drain_interval``
        > 1 leaves up to interval-1 batches' matches undrained).  Call
        before checkpointing a pipelined processor — a snapshot cannot
        carry undecoded device outputs."""
        matches: List[Tuple[Hashable, Sequence]] = []
        if self._pending is not None:
            pend, self._pending = self._pending, None
            with self._phase("decode"):
                matches = self._decode(*pend[:4])
            self._lat_finish(pend[4], (not self.lazy) or pend[2] is not None)
        if self.lazy:
            with self._phase("drain"):
                self.state, dout = self.batch.drain(self.state)
            with self._phase("decode"):
                # No rank_of: everything pending predates "now", so the
                # order key degrades to (completion step, lane, run row).
                matches += self._decode_drained(dout, None, self._step_base)
            if self.ledger is not None:
                # This drain emitted every parked batch's matches.
                self.ledger.commit_deferred(self._clock())
        self.metrics.matches_out += len(matches)
        return matches

    def _decode(
        self, out, rank_of, drain_out=None, base=0
    ) -> List[Tuple[Hashable, Sequence]]:
        """One batch's matches: the eager ``StepOutput`` grid (empty under
        lazy extraction) plus, when a drain ran, the drained handles."""
        matches = [] if self.lazy else self._decode_eager(out, rank_of)
        if drain_out is not None:
            matches = matches + self._decode_drained(
                drain_out, rank_of, base
            )
        return matches

    def _decode_drained(
        self, dout, rank_of, base
    ) -> List[Tuple[Hashable, Sequence]]:
        """Drained handles -> (key, Sequence) in the eager emission order.

        Handles completed in THIS batch (``seq >= base``) order exactly
        like the eager path — by arrival rank of the completing record,
        then run-queue row; handles deferred from earlier batches (only
        with ``drain_interval > 1`` or after a restore) emit first, by
        (completion step, lane, run row).

        Fast path mirrors the eager decode: the hit rows compact
        on-device (``ops/decode.py: compact_drained``) so the host pulls
        rows proportional to the match count, not ``lanes x ring``.
        """
        if self.decode_budget:
            from kafkastreams_cep_tpu.ops.decode import compact_drained

            K, HB = dout.count.shape
            c_stage, c_off, c_count, c_seq, c_row, c_k, c_n, _ovf = (
                compact_drained(dout, self.decode_budget)
            )
            n = int(c_n)
            if n <= min(self.decode_budget, K * HB):
                if n == 0:
                    return []
                m = 1
                while m < n:
                    m *= 2
                m = min(m, int(c_count.shape[0]))
                cnts, stages, offs, seqs, rows, ks = jax.device_get(
                    (c_count[:m], c_stage[:m], c_off[:m], c_seq[:m],
                     c_row[:m], c_k[:m])
                )
                return self._emit_drained(
                    ks[:n], cnts[:n], stages[:n], offs[:n], seqs[:n],
                    rows[:n], rank_of, base,
                )
            self.metrics.decode_fallbacks += 1
        count = np.asarray(jax.device_get(dout.count))  # [K, HB]
        ks, hs = np.nonzero(count)
        if ks.size == 0:
            return []
        stage, off, seqa, rowa = (
            np.asarray(jax.device_get(x))
            for x in (dout.stage, dout.off, dout.seq, dout.row)
        )
        return self._emit_drained(
            ks, count[ks, hs], stage[ks, hs], off[ks, hs], seqa[ks, hs],
            rowa[ks, hs], rank_of, base,
        )

    def _emit_drained(self, ks, cnts, stages, offs, seqs, rows, rank_of,
                      base):
        if rank_of is not None:
            cur = seqs >= base
            t_idx = np.clip(seqs - base, 0, rank_of.shape[1] - 1)
            key2 = np.where(cur, rank_of[ks, t_idx], seqs)
        else:
            cur = np.zeros(ks.shape, bool)
            key2 = seqs
        order = np.lexsort(
            (rows, np.where(cur, 0, ks), key2, cur.astype(np.int8))
        )
        return self._build_matches(
            ks[order], cnts[order], stages[order], offs[order]
        )

    def _decode_eager(self, out, rank_of) -> List[Tuple[Hashable, Sequence]]:
        """Device walk outputs -> (key, Sequence), in arrival order.

        Fast path: the batch's match rows compact on-device into a GLOBAL
        budget of ``decode_budget`` rows across all lanes
        (``ops/decode.py``), so the host pulls kilobytes-to-megabytes
        proportional to the actual match count instead of the raw
        ``[K, T, R, W]`` grid — gigabytes at production shapes, and the
        processor's former critical-path wall (SURVEY §2.2 PP row).  A
        batch with more total matches than the budget falls back to the
        full pull (counted in ``decode_fallbacks``; correctness never
        depends on the budget).
        """
        if self.decode_budget:
            from kafkastreams_cep_tpu.ops.decode import compact_matches

            K, T, R = out.count.shape
            c_stage, c_off, c_count, c_k, c_t, c_r, c_n, _overflow = (
                compact_matches(out, self.decode_budget)
            )
            # One scalar round-trip; overflow is host-derivable from it
            # (an extra device_get costs a full latency floor on tunneled
            # devices).
            n = int(c_n)
            if n <= min(self.decode_budget, K * T * R):
                if n == 0:
                    return []
                # Second phase pulls only the hit rows — padded up to a
                # power of two so slice shapes (and their compiled
                # executables) are bounded at log2(budget) variants.
                m = 1
                while m < n:
                    m *= 2
                m = min(m, int(c_count.shape[0]))
                count, stage, off, k_arr, t_arr, r_arr = jax.device_get(
                    (c_count[:m], c_stage[:m], c_off[:m], c_k[:m],
                     c_t[:m], c_r[:m])
                )
                return self._emit(
                    k_arr[:n], t_arr[:n], r_arr[:n], count[:n],
                    stage[:n], off[:n], rank_of,
                )
            self.metrics.decode_fallbacks += 1
        stage = np.asarray(jax.device_get(out.stage))  # [K, T, R, W]
        off = np.asarray(jax.device_get(out.off))
        count = np.asarray(jax.device_get(out.count))  # [K, T, R]
        ks, ts, rs = np.nonzero(count)
        if ks.size == 0:
            return []
        return self._emit(
            ks, ts, rs, count[ks, ts, rs], stage[ks, ts, rs],
            off[ks, ts, rs], rank_of,
        )

    def _emit(self, ks, ts, rs, cnts, stages, offs, rank_of):
        """Hit rows -> (key, Sequence) in arrival order (rank of the
        completing record), then run-queue order."""
        order = np.lexsort((rs, rank_of[ks, ts]))
        return self._build_matches(
            ks[order], cnts[order], stages[order], offs[order]
        )

    def _build_matches(self, ks, cnts, stages, offs):
        """Already-ordered hit rows -> (key, Sequence) objects."""
        names = self.batch.names
        matches: List[Tuple[Hashable, Sequence]] = []
        for i in range(ks.size):
            k = int(ks[i])
            seq = Sequence()
            for w in range(int(cnts[i])):
                seq.add(
                    names[int(stages[i, w])],
                    self._event_at(k, int(offs[i, w])),
                )
            matches.append((self._key_of[k], seq))
        return matches

    def _event_at(self, lane: int, off: int) -> Event:
        """Event by (lane, device offset): the materialized mirror first,
        then the lazy column batches (newest first), caching on hit."""
        ev = self._events[lane].get(off)
        if ev is not None:
            return ev
        for start, cnt, abs_ts, leaves in reversed(self._col_batches):
            s = int(start[lane])
            if s >= 0 and s <= off < s + int(cnt[lane]):
                ev = self._materialize(lane, off, s, abs_ts, leaves)
                self._events[lane][off] = ev
                return ev
        raise KeyError(f"lane {lane} has no event at device offset {off}")

    def _materialize(self, lane, off, start, abs_ts, leaves) -> Event:
        t = off - start
        dtypes, treedef = jax.tree_util.tree_flatten(self._value_proto)
        value = jax.tree_util.tree_unflatten(
            treedef, [l[lane, t].item() for l in leaves]
        )
        return Event(
            self._key_of[lane], value, int(abs_ts[lane, t]), self.topic,
            lane, off + int(self._off_base[lane]),
        )

    def _gc_events(self) -> None:
        """Drop host events no longer reachable from device state.

        The device slab GCs entries by refcount exactly like the reference
        buffer (``KVSharedVersionedBuffer.java:147-171``); the host mirror
        only needs events still present in a lane's slab or pointed at by a
        live run, so everything else is released here after each batch.
        """
        # Tiered processors wrap the engine state (engine/tiered.py);
        # liveness lives in the engine half either way.
        eng = getattr(self.state, "engine", self.state)
        slab_stage = np.asarray(jax.device_get(eng.slab.stage))  # [K, E]
        slab_off = np.asarray(jax.device_get(eng.slab.off))
        run_alive = np.asarray(jax.device_get(eng.alive))  # [K, R]
        run_off = np.asarray(jax.device_get(eng.event_off))
        for k in range(self.num_lanes):
            live = set(slab_off[k][slab_stage[k] >= 0].tolist())
            live.update(run_off[k][run_alive[k]].tolist())
            # Live rows still sitting in lazy column batches materialize
            # now (the batches are dropped below); dead rows never do.
            for start, cnt, abs_ts, leaves in self._col_batches:
                s = int(start[k])
                if s < 0:
                    continue
                hi = s + int(cnt[k])
                for o in live:
                    if s <= o < hi and o not in self._events[k]:
                        self._events[k][o] = self._materialize(
                            k, o, s, abs_ts, leaves
                        )
            store = self._events[k]
            dead = [o for o in store if o not in live]
            for o in dead:
                del store[o]
        self._col_batches.clear()

    def lane_shards(self) -> Optional[List[int]]:
        """The live lane→shard assignment (contiguous blocks over the
        mesh's lane axis), or ``None`` unmeshed.  Recorded in checkpoint
        headers so a snapshot states which mesh wrote it and a restore
        onto a different device count is an explicit, logged event
        (``runtime/checkpoint.py``)."""
        if self.mesh is None:
            return None
        per = self.num_lanes // int(self.mesh.devices.size)
        return [k // per for k in range(self.num_lanes)]

    def place(self, state):
        """Device placement for host-built state (mesh-aware) — used by
        checkpoint restore so snapshots re-place onto whatever mesh this
        processor runs on."""
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            return jax.device_put(
                state,
                NamedSharding(self.mesh, PartitionSpec(self.batch.axis)),
            )
        return jax.device_put(state)

    # -- diagnostics --------------------------------------------------------

    def counters(self) -> Dict[str, int]:
        """Lane-summed overflow/drop counters (all zero in healthy runs)."""
        return self.batch.counters(self.state)

    def hot_counters(self) -> Dict[str, int]:
        """Two-tier residency telemetry of the live state (lane-summed;
        all zero when ``slab_hot_entries == 0``)."""
        return self.batch.hot_counters(self.state)

    def walk_counters(self) -> Dict[str, int]:
        """Walk-cost telemetry of the live state (lane-summed hop counts
        by walker class — the reduce-width perf model's observables)."""
        return self.batch.walk_counters(self.state)

    def tier_counters(self) -> Dict[str, int]:
        """Compiler-tiering telemetry (events screened by the stencil
        prefix tier / prefix completions / NFA promotions); structural
        zeros on untiered processors."""
        from kafkastreams_cep_tpu.engine.matcher import TIER_COUNTER_NAMES

        fn = getattr(self.batch, "tier_counters", None)
        if fn is None:
            return {n: 0 for n in TIER_COUNTER_NAMES}
        return fn(self.state)

    def metrics_snapshot(self, per_lane: bool = True) -> Dict[str, Any]:
        """Runtime metrics + engine counters + attribution in one dict.

        Flat lifetime counters keep their historical keys; added on top:
        hot-tier counters (``slab_hot_hits``, ... — previously computed but
        unreachable from the snapshot), per-phase latency histograms under
        ``"phases"`` (count/sum/p50/p99 per batch phase), per-lane and
        per-pattern engine-counter breakdowns, the event-time watermark and
        lag gauges, and HBM byte gauges (``device_memory_stats``).  Pass
        ``per_lane=False`` to skip the per-lane host gather (banks do, to
        keep member snapshots light).
        """
        snap: Dict[str, Any] = self.metrics.snapshot(self.counters())
        hot = self.hot_counters()
        snap.update(hot)
        snap.update(self.walk_counters())
        tier = self.tier_counters()
        snap.update(tier)
        snap["watermark"] = self._watermark
        # Injectable clock (not inline time.time): deterministic under a
        # pinned test clock, and consistent with every latency stamp.
        snap["event_time_lag_ms"] = (
            int(self._clock() * 1000) - self._watermark
            if self._watermark is not None
            else None
        )
        if self.ledger is not None:
            # Latency-attribution ledger (utils/latency.py): segment/stall/
            # per-query histograms, exemplars, and the SLO burn gauge —
            # rendered as cep_latency_seconds{segment=} etc.
            snap["latency"] = self.ledger.snapshot()
        if self._guard is not None:
            # Guard telemetry: the three loss counters (all-zero ⇒
            # loss-free), hold depth/age gauges, and per-reason
            # dead-letter counts (rendered with reason labels by
            # utils/telemetry.render_prometheus).
            snap.update(self._guard.stats())
            snap["dead_letters"] = dict(self._guard.reason_counts)
        snap["per_pattern"] = {
            self.name: {
                **self.counters(),
                **hot,
                **tier,  # labeled cep_prefix_*/cep_tier_* series per query
                "records_in": self.metrics.records_in,
                "matches_out": self.metrics.matches_out,
            }
        }
        plan = getattr(self.batch, "plan", None)
        if plan is not None:
            # The compiler tiering decision (per-query ``tier=`` tag of
            # the profiler CLI; strings are skipped by the Prometheus
            # renderer, the counters above are the scrapeable series).
            snap["tier_plan"] = plan.describe()
        per_stage = self.batch.stage_counters(self.state)
        if per_stage:
            # Per-stage selectivity & cost attribution
            # (EngineConfig.stage_attribution) — the compiler-tiering /
            # lazy-chain-ordering signal, labeled by stage name in the
            # Prometheus rendering.
            snap["per_stage"] = per_stage
        # Brownout L1+ defers the per-lane/per-key device gathers — the
        # one part of the snapshot that costs device round-trips.
        if per_lane and not self.telemetry_defer:
            snap["per_lane"] = self.batch.per_lane_counters(self.state)
            snap["per_key"] = self.per_key_cost(
                per_lane_arrays=snap["per_lane"]
            )
        snap["hbm"] = device_memory_stats()
        # Compiled-program cache health (utils/tracecache.py): entry
        # count vs capacity plus hit/miss/eviction totals — an eviction
        # storm here is recompilation thrash, the first thing to check
        # when adaptive replans or escalations slow a stream down.
        snap["trace_cache"] = tracecache.stats()
        return snap

    def per_key_cost(
        self, top_k: int = 8, per_lane_arrays=None
    ) -> Dict[str, Any]:
        """Top-K heavy-hitter cost attribution by *key* (tentpole part 1,
        the hot-key-rebalancing signal): each lane's total device walk
        work (walk + extract + drain hops — the per-hop cost model's
        observable) mapped back through the key→lane assignment, ranked,
        with each hitter's share of the total.  Rendered as
        ``cep_key_hops{key=...,lane=...}`` gauges by
        ``utils/telemetry.render_prometheus``.  Works with attribution
        off — the per-lane hop counters always exist.
        """
        arrays = (
            per_lane_arrays
            if per_lane_arrays is not None
            else self.batch.per_lane_counters(self.state)
        )
        hops = (
            np.asarray(arrays["walk_hops"], dtype=np.int64)
            + np.asarray(arrays["extract_hops"], dtype=np.int64)
            + np.asarray(arrays["drain_hops"], dtype=np.int64)
        ).reshape(-1)
        total = int(hops.sum())
        order = np.argsort(hops, kind="stable")[::-1][: max(int(top_k), 1)]
        top = []
        for lane in order:
            lane = int(lane)
            if hops[lane] <= 0 or lane not in self._key_of:
                continue
            top.append(
                {
                    "key": str(self._key_of[lane]),
                    "lane": lane,
                    "hops": int(hops[lane]),
                    "share": (
                        round(float(hops[lane]) / total, 4) if total else 0.0
                    ),
                }
            )
        return {"total_hops": total, "top": top}
