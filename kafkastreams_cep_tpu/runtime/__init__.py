"""Host runtime: the stream-processor layer above the device engine.

The reference integrates with Kafka Streams through ``CEPProcessor``
(``CEPProcessor.java:50-163``): per-record processing, per-partition state
ownership, store-backed checkpointing, match forwarding.  Here the same
responsibilities are host-side Python around the batched device matcher:

* :class:`CEPProcessor` — micro-batches records by key lane, pads to the
  device shape, scans, and emits completed :class:`Sequence` matches in
  arrival order (``runtime/processor.py``);
* :mod:`runtime.checkpoint` — snapshot/restore of the device state arrays
  with stages referenced by name only, so code never serializes
  (``ComputationStageSerDe.java:40-123`` contract);
* :mod:`runtime.supervisor` — failure detection and auto-recovery
  (checkpoint + journal replay), the rebalance/changelog-restore analog
  the reference inherits from Kafka Streams (SURVEY §5).
"""

from kafkastreams_cep_tpu.runtime.processor import (
    CEPProcessor,
    InputRejected,
    Record,
)
from kafkastreams_cep_tpu.runtime.bank import CEPBank
from kafkastreams_cep_tpu.runtime.checkpoint import (
    CheckpointCorrupt,
    restore_processor,
    save_checkpoint,
    load_checkpoint,
)
from kafkastreams_cep_tpu.runtime.flight import FlightRecorder
from kafkastreams_cep_tpu.runtime.ingest import (
    DeadLetter,
    IngestGuard,
    IngestPolicy,
)
from kafkastreams_cep_tpu.runtime.overload import (
    OverloadController,
    OverloadPolicy,
)
from kafkastreams_cep_tpu.runtime.migrate import (
    migrate_processor,
    move_lanes,
    plan_rebalance,
    repartition_state,
    widen_state,
)
from kafkastreams_cep_tpu.runtime.supervisor import (
    HealthReport,
    ShardPolicy,
    Supervisor,
    check_health,
)

__all__ = [
    "CEPBank",
    "CEPProcessor",
    "CheckpointCorrupt",
    "DeadLetter",
    "FlightRecorder",
    "HealthReport",
    "IngestGuard",
    "IngestPolicy",
    "InputRejected",
    "OverloadController",
    "OverloadPolicy",
    "Record",
    "ShardPolicy",
    "Supervisor",
    "check_health",
    "migrate_processor",
    "move_lanes",
    "plan_rebalance",
    "repartition_state",
    "save_checkpoint",
    "load_checkpoint",
    "restore_processor",
    "widen_state",
]
