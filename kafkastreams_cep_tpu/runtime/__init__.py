"""Host runtime: the stream-processor layer above the device engine.

The reference integrates with Kafka Streams through ``CEPProcessor``
(``CEPProcessor.java:50-163``): per-record processing, per-partition state
ownership, store-backed checkpointing, match forwarding.  Here the same
responsibilities are host-side Python around the batched device matcher:

* :class:`CEPProcessor` — micro-batches records by key lane, pads to the
  device shape, scans, and emits completed :class:`Sequence` matches in
  arrival order (``runtime/processor.py``);
* :mod:`runtime.checkpoint` — snapshot/restore of the device state arrays
  with stages referenced by name only, so code never serializes
  (``ComputationStageSerDe.java:40-123`` contract).
"""

from kafkastreams_cep_tpu.runtime.processor import CEPProcessor, Record
from kafkastreams_cep_tpu.runtime.bank import CEPBank
from kafkastreams_cep_tpu.runtime.checkpoint import (
    restore_processor,
    save_checkpoint,
    load_checkpoint,
)

__all__ = [
    "CEPBank",
    "CEPProcessor",
    "Record",
    "save_checkpoint",
    "load_checkpoint",
    "restore_processor",
]
