"""Checkpoint / restore of processor state — the changelog-store analog.

The reference persists its entire engine state in Kafka Streams changelog
stores: the run queue re-serialized every record (``CEPProcessor.java:
158-160``) and the buffer/aggregate stores mutated through the store API.
Critically, **code is never serialized** — runs reference stages by *name*
and are rehydrated from the compiled topology on restore
(``ComputationStageSerDe.java:40-46,66-78``).

The TPU analog: all canonical state already lives in fixed-shape device
arrays (:class:`EngineState`), so a checkpoint is a host-side snapshot of
those arrays plus the host bookkeeping (key→lane map, per-lane event store,
offsets).  The manifest records the compiled stage *names*; restore
compiles the pattern fresh from user code and refuses a topology whose
names differ — exactly the reference's lookup-by-name contract.

Format: one ``.npz`` for the arrays + a pickled header for host metadata
(events and keys are user objects — pickle is the Kryo analog; predicates
and fold functions are never written).
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import pickle
from typing import Any, Dict, Optional

import jax
import numpy as np

from kafkastreams_cep_tpu.engine.matcher import EngineConfig, EngineState
from kafkastreams_cep_tpu.runtime.processor import CEPProcessor

from kafkastreams_cep_tpu.utils.failpoints import fire as _failpoint
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime.checkpoint")

# v2: EngineState.agg became typed-encoded int32 (float32 fold states as
# bit patterns) — v1 checkpoints' float32 agg arrays are not translatable
# without the old dtype convention, so they are refused rather than
# silently cast.
# v3: headers record state_dtypes so a fold dtype flip between save and
# restore is refused; v2 files lack the record and cannot be checked, so
# they are refused too (same no-silent-reinterpretation rule).  v3's
# decode_budget header field is the processor's GLOBAL compacted-row
# budget (runtime/processor.py) — no earlier released format carried a
# per-lane meaning.
FORMAT_VERSION = 3


class CheckpointCorrupt(ValueError):
    """The checkpoint file's payload does not match its recorded sha256
    digest (bit rot, torn write, truncation).  The supervisor's resume
    path falls back to the previous-good snapshot + journal-chain replay
    instead of crashing (``runtime/supervisor.py``)."""


def _flatten_state(state: EngineState) -> Dict[str, np.ndarray]:
    """EngineState -> flat ``{path: ndarray}`` with stable names."""
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    out = {}
    for path, leaf in flat:
        name = "/".join(
            p.name if hasattr(p, "name") else str(p.idx) for p in path
        )
        out[name] = np.asarray(jax.device_get(leaf))
    return out


def _unflatten_state(template: EngineState, arrays: Dict[str, np.ndarray]) -> EngineState:
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in flat:
        name = "/".join(
            p.name if hasattr(p, "name") else str(p.idx) for p in path
        )
        if name not in arrays:
            raise ValueError(f"checkpoint missing state array {name!r}")
        arr = arrays[name]
        if arr.shape != leaf.shape:
            raise ValueError(
                f"checkpoint array {name!r} has shape {arr.shape}, "
                f"engine expects {leaf.shape} (EngineConfig mismatch?)"
            )
        # No silent reinterpretation — the array-level twin of the header
        # ``state_dtypes`` rule: agg stores float32 fold states as int32
        # bit patterns, so a cast here could flip bits-as-values without
        # any shape mismatch to catch it.
        if np.dtype(arr.dtype) != np.dtype(leaf.dtype):
            raise ValueError(
                f"checkpoint array {name!r} has dtype {arr.dtype}, engine "
                f"expects {np.dtype(leaf.dtype)} — refusing the silent "
                "cast (dtype changes are not translatable)"
            )
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_checkpoint(
    processor: CEPProcessor, path: str, extra: Optional[Dict[str, Any]] = None
) -> None:
    """Snapshot a processor's full state to ``path`` (a single file).

    ``extra`` rides along in the header for the caller's own bookkeeping
    (e.g. the supervisor's journal sequence number)."""
    _failpoint("checkpoint.save")
    if getattr(processor, "_pending", None) is not None:
        raise ValueError(
            "pipelined processor holds an undecoded batch; call flush() "
            "before checkpointing (a snapshot cannot carry device outputs)"
        )
    if processor._col_batches:
        # Lazy columnar batches (process_columns) materialize their live
        # rows into the picklable mirror; dead rows are dropped.
        processor._gc_events()
    arrays = _flatten_state(processor.state)
    header = {
        "format_version": FORMAT_VERSION,
        "extra": dict(extra or {}),
        # Stage names only — the lookup-by-name restore contract.
        "stage_names": list(processor.batch.names),
        "state_names": list(processor.batch.matcher.tables.state_names),
        # Dtypes travel with the names: agg stores float32 states as int32
        # bit patterns, so a dtype flip between save and restore would
        # silently reinterpret bits — refused like a name mismatch.
        "state_dtypes": list(processor.batch.matcher.tables.state_dtypes),
        "config": dataclasses.asdict(processor.batch.matcher.config),
        "num_lanes": processor.num_lanes,
        "topic": processor.topic,
        "epoch": processor.epoch,
        "gc_events": processor.gc_events,
        "dedup": processor.dedup,
        "gc_interval": processor.gc_interval,
        "gc_events_interval": processor.gc_events_interval,
        "decode_budget": processor.decode_budget,
        "pipeline": processor.pipeline,
        "drain_interval": processor.drain_interval,
        "lane_of": dict(processor._lane_of),
        # Which mesh wrote this snapshot (None/absent: single device).
        # Lane rows are stored in LOGICAL lane order — mesh-agnostic — so
        # these are provenance, not placement: a restore onto a different
        # device count re-places the same rows through repartition_state
        # (see restore_processor) and logs the assignment change.
        "mesh_size": (
            int(processor.mesh.devices.size)
            if processor.mesh is not None
            else None
        ),
        "lane_shards": processor.lane_shards(),
        "next_offset": processor._next_offset.copy(),
        "off_base": processor._off_base.copy(),
        "events": [dict(d) for d in processor._events],
        "value_proto": processor._value_proto,
        # Ingestion-guard state (runtime/ingest.py): records still held in
        # the reorder buffer, watermark/frontier, dead letters, and loss
        # counters — first-class durable state, restored verbatim so a
        # resume releases exactly what the crashed process would have.
        "ingest": (
            processor._guard.to_state()
            if processor._guard is not None
            else None
        ),
        # Latency-ledger state (utils/latency.py): committed segment
        # histograms plus in-flight deferred bundles — additive key
        # (readers default to None when absent, so format_version stays
        # put), same durability discipline as the guard state above.
        "latency": (
            processor.ledger.to_state()
            if getattr(processor, "ledger", None) is not None
            else None
        ),
    }
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    # Payload integrity: a digest over the flattened state arrays, checked
    # on load — a corrupt snapshot must fail loudly (and recoverably, via
    # the supervisor's previous-good fallback), never restore flipped bits.
    header["arrays_sha256"] = hashlib.sha256(buf.getvalue()).hexdigest()
    with open(path, "wb") as f:
        pickle.dump({"header": header, "arrays": buf.getvalue()}, f)
    logger.info(
        "checkpoint saved to %s: %d lanes, stages %s",
        path, header["num_lanes"], header["stage_names"],
    )


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Read a checkpoint file into ``{header, arrays}``.

    Raises :class:`CheckpointCorrupt` when the file cannot be parsed or
    its array payload fails the header's sha256 digest."""
    try:
        with open(path, "rb") as f:
            blob = pickle.load(f)
        header = blob["header"]
    except (OSError, FileNotFoundError):
        raise
    except Exception as e:
        raise CheckpointCorrupt(
            f"checkpoint {path} is unreadable ({type(e).__name__}: {e})"
        ) from e
    if header["format_version"] != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {header['format_version']} unsupported"
        )
    want = header.get("arrays_sha256")
    if want is not None:
        got = hashlib.sha256(blob["arrays"]).hexdigest()
        if got != want:
            raise CheckpointCorrupt(
                f"checkpoint {path} failed integrity check: array payload "
                f"sha256 {got} != header digest {want}"
            )
    try:
        with np.load(io.BytesIO(blob["arrays"])) as z:
            arrays = {k: z[k] for k in z.files}
    except Exception as e:
        raise CheckpointCorrupt(
            f"checkpoint {path} array payload is unreadable "
            f"({type(e).__name__}: {e})"
        ) from e
    return {"header": header, "arrays": arrays}


def restore_processor(
    pattern, path: str, ckpt: Optional[Dict[str, Any]] = None, mesh=None
) -> CEPProcessor:
    """Rebuild a processor from user code + a checkpoint.

    ``pattern`` is compiled fresh (predicates/folds come from code, exactly
    like ``ComputationStageSerDe`` rehydrating stages from the topology);
    the checkpoint supplies only state.  A topology whose stage names don't
    match the checkpoint is refused.  Pass ``ckpt`` (a
    :func:`load_checkpoint` result) to reuse an already-loaded file.

    Checkpoints are mesh-agnostic host arrays, so ``mesh`` may differ from
    the mesh (or single device) that wrote the snapshot — the rebalance
    analog: lanes re-place onto the new device set, exactly like Kafka
    Streams restoring changelogged partitions onto a resized consumer
    group.  The mesh size must divide ``num_lanes`` (refused with a clear
    error, not a shard_map internality); a device-count change routes the
    state through ``runtime.migrate.repartition_state`` — identity, by
    the relabeling invariant below — and is logged as an explicit
    assignment change.
    """
    if ckpt is None:
        ckpt = load_checkpoint(path)
    header = ckpt["header"]
    config = EngineConfig(**header["config"])
    target_devs = int(mesh.devices.size) if mesh is not None else 1
    if int(header["num_lanes"]) % target_devs:
        raise ValueError(
            f"checkpoint holds {header['num_lanes']} lanes, not divisible "
            f"by the {target_devs}-device restore mesh — pick a mesh whose "
            "size divides the lane count (parallel/sharding.py contract)"
        )
    proc = CEPProcessor(
        pattern,
        header["num_lanes"],
        config,
        topic=header["topic"],
        epoch=header["epoch"],
        gc_events=header.get("gc_events", True),
        dedup=header.get("dedup", True),
        gc_interval=header.get("gc_interval", 0),
        gc_events_interval=header.get("gc_events_interval", 8),
        decode_budget=header.get("decode_budget", 131072),
        pipeline=header.get("pipeline", False),
        drain_interval=header.get("drain_interval", 1),
        mesh=mesh,
    )
    if list(proc.batch.names) != list(header["stage_names"]):
        raise ValueError(
            "pattern topology does not match checkpoint: stages "
            f"{proc.batch.names} vs checkpoint {header['stage_names']}"
        )
    if list(proc.batch.matcher.tables.state_names) != list(header["state_names"]):
        raise ValueError("fold-state names do not match checkpoint")
    proc_dtypes = list(proc.batch.matcher.tables.state_dtypes)
    if list(header["state_dtypes"]) != proc_dtypes:
        raise ValueError(
            "fold-state dtypes do not match checkpoint: "
            f"{proc_dtypes} vs checkpoint {header['state_dtypes']} "
            "(typed agg bit patterns are not translatable across dtypes)"
        )
    state = _unflatten_state(proc.state, ckpt["arrays"])
    written_devs = int(header.get("mesh_size") or 1)
    if written_devs != target_devs:
        # Snapshot rows are logical lanes and every lane→shard assignment
        # this runtime produces (evacuation, rebalance — runtime/migrate.py
        # move_lanes) RELABELS lanes so the live assignment is always the
        # contiguous identity.  Restoring onto a different device count is
        # therefore the identity repartition re-placed in new-sized blocks;
        # routing it through repartition_state keeps one audited
        # re-assignment point (shape/permutation validation, host
        # normalization) instead of a silent device_put.
        from kafkastreams_cep_tpu.runtime import migrate as migrate_mod

        state = migrate_mod.repartition_state(
            state, np.arange(int(header["num_lanes"]))
        )
        logger.info(
            "checkpoint written on %d device(s) restored onto %d: lanes "
            "re-placed in %d-lane shard blocks",
            written_devs, target_devs,
            int(header["num_lanes"]) // target_devs,
        )
    proc.state = proc.place(state)
    # The drained-handle ordering base is derivable from device state:
    # step_seq is the per-lane step counter (identical across lanes — all
    # lanes step together), and a restore resumes exactly at it.  Tiered
    # processors nest the engine state (engine/tiered.py: TieredState),
    # so the flattened array name carries the ``engine/`` prefix.
    step_seq = ckpt["arrays"].get(
        "step_seq", ckpt["arrays"].get("engine/step_seq")
    )
    proc._step_base = int(np.max(np.asarray(step_seq)))
    proc._lane_of = dict(header["lane_of"])
    proc._key_of = {v: k for k, v in proc._lane_of.items()}
    proc._next_offset = np.asarray(header["next_offset"]).copy()
    if "off_base" in header:
        proc._off_base = np.asarray(header["off_base"]).copy()
    else:
        # Pre-rebasing checkpoint: lanes that already saw records hold
        # absolute (unrebased) device offsets, so their base must stay 0;
        # untouched lanes stay unset so their first record fixes a base.
        proc._off_base = np.where(proc._next_offset > 0, 0, -1).astype(np.int64)
    proc._events = [dict(d) for d in header["events"]]
    proc._value_proto = header["value_proto"]
    if header.get("ingest") is not None:
        from kafkastreams_cep_tpu.runtime.ingest import IngestGuard

        proc._guard = IngestGuard.from_state(header["ingest"])
        # Flight-recorder burst detection diffs against the cumulative
        # dead-letter total; re-base it so a restore never reads the
        # whole history as one burst.
        proc._dlq_base = int(sum(proc._guard.reason_counts.values()))
    if header.get("latency") is not None:
        from kafkastreams_cep_tpu.utils.latency import LatencyLedger

        # The clock is not durable (pickling a callable would be a lie
        # across hosts): the restored ledger runs on wall clock; callers
        # with a pinned clock re-inject it via ``proc.set_clock(...)``.
        proc.ledger = LatencyLedger.from_state(header["latency"])
    logger.info(
        "restored processor from %s: %d keys assigned, offsets %s",
        path, len(proc._lane_of), proc._next_offset.tolist(),
    )
    return proc
