"""Live-state migration onto a strictly-wider ``EngineConfig``.

The reference never needs this: its run queue, shared buffer, and Dewey
versions are heap-backed and unbounded (``NFA.java:75``,
``CEPProcessor.java:144-149``).  The array engine's fixed shapes make
capacity the design's own failure mode — overflow is counted and dropped
(``ops/slab.py``), never silent, but the dropped branches are gone.  This
module is the escape hatch: widen every state array of a *live* processor
so the supervisor can escalate capacity mid-stream instead of warning
about loss (``runtime/supervisor.py`` ``auto_escalate``).

Why widening is a pure embedding (the proof burden, per dimension)
-------------------------------------------------------------------
A migration must guarantee: stepping the widened state under the wide
engine produces, for as long as the *narrow* engine would not have hit a
capacity limit, bit-identical run queues, slab contents, Dewey versions,
fold state, match emissions, and capacity counters — and past the point
the narrow engine would drop, the wide engine simply retains what the
narrow one lost.  Dimension by dimension:

* **R -> R' (run queue).**  Queue compaction (``engine/matcher.py
  finish``) always leaves live runs in a contiguous prefix in queue
  order, dead slots carrying the compaction fill values.  Appending dead
  slots (the same fill values) preserves the prefix and its order; dead
  slots are fully masked in the chain (``alive`` gates every predicate,
  put, walk, and candidate), so they contribute nothing until a
  compaction writes a live run into them — exactly when the narrow queue
  would have counted a ``run_drops``.
* **E -> E' (slab entries).**  Entries are keyed by ``(stage, off)`` —
  unique across the slab — and every lookup is a full-slab masked match,
  so results are placement-independent; allocation takes the *first*
  free slot (``argmax``), and appended free slots sit at the end, so
  allocation order is unchanged until the narrow slab would have been
  full (a ``slab_full_drops``).  Two-tier layouts add demotion, but the
  victim choice reads only occupied-hot rows (appended slots are free
  overflow rows) and the overflow destination is again first-free —
  unchanged until the narrow overflow tier would have filled.  Refcounts,
  npreds, and the free list ride along untouched.
* **MP -> MP' (predecessor lists).**  Pointers append at ``npreds`` and
  walks take the first version-compatible pointer in insertion order;
  padding null pointers (``pstage == -1``) past ``npreds`` is exactly the
  representation an MP'-wide engine would have built.
* **D -> D' (Dewey width).**  Versions are left-aligned digit vectors
  with an explicit length; every Dewey op masks by length and slots at
  index >= vlen are zero by construction (``ops/dewey_ops.py``), so a
  zero-extended tail is the same version in a wider vector, and
  ``is_compatible``/``add_run``/``add_stage`` answer identically.
* **W, walker_budget (walk/compute bounds).**  Not state-shaped; growing
  them needs no array change (they bound per-step compute, and a longer
  bound only extends walks the narrow engine would have truncated into a
  ``slab_trunc``).
* **Counters.**  Copied verbatim — migration never forgives past loss;
  the supervisor's escalation protocol instead *rolls back* to the last
  pre-loss state and re-processes, which is what makes "finish with all
  loss counters zero" achievable.

The hot-tier split (``slab_hot_entries``) is a perf knob with no capacity
semantics (drops are bit-identical at any E_hot — ``ops/slab.py``
"Two-tier layout"); migration may change it freely, which moves entries'
*tier accounting* (``hot_hits``/``demotions`` telemetry) but never the
match stream or any capacity counter.

Embedding parity — each dim widened alone and combined, jnp and kernel
walk paths — is property-tested in ``tests/test_migrate.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from kafkastreams_cep_tpu.engine.matcher import EngineConfig, EngineState
from kafkastreams_cep_tpu.ops.slab import SlabState
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime.migrate")

# Config fields that are array-shape dims (may only grow) vs semantic
# switches (must not change under a live migration: they alter the match
# stream, not capacity).
_SHAPE_DIMS = (
    "max_runs", "slab_entries", "slab_preds", "dewey_depth", "max_walk",
    "handle_ring",
)
_SEMANTIC_FLAGS = (
    "renorm_versions", "enforce_windows", "sequential_slab", "walker_budget",
    "lazy_extraction",
    # Not semantic for the match stream, but it shapes the attribution
    # arrays ([S] vs [0]) — a live embedding across the flip does not
    # exist, so it rides the no-change list.
    "stage_attribution",
    # Tiering shapes the state itself (TieredState wraps the engine state
    # with the stencil prefix carry, engine/tiered.py): a flip mid-stream
    # would orphan either the carry or the seed run.
    "tiering",
)


def check_widens(old: EngineConfig, new: EngineConfig) -> None:
    """Refuse a migration target that is not a pure widening of ``old``."""
    for f in _SHAPE_DIMS:
        o, n = getattr(old, f), getattr(new, f)
        if n < o:
            raise ValueError(
                f"migration cannot shrink {f}: {o} -> {n} (state embedding "
                "only exists into a strictly-wider config)"
            )
    for f in _SEMANTIC_FLAGS:
        o, n = getattr(old, f), getattr(new, f)
        if o != n:
            raise ValueError(
                f"migration cannot change {f} ({o} -> {n}): it alters match "
                "semantics, not capacity — restart the processor instead"
            )
    if new == old:
        raise ValueError("migration target equals the current config")


def _pad(arr: np.ndarray, axis: int, new_size: int, fill) -> np.ndarray:
    """Grow ``arr`` along ``axis`` (negative, from the end) to
    ``new_size``, new slots holding ``fill``."""
    ax = arr.ndim + axis
    grow = new_size - arr.shape[ax]
    if grow == 0:
        return arr
    shape = list(arr.shape)
    shape[ax] = grow
    pad = np.full(shape, fill, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=ax)


def widen_state(
    state: EngineState, old: EngineConfig, new: EngineConfig
) -> EngineState:
    """Embed ``state`` (host or device arrays, any leading batch axes)
    into the shapes of ``new``.  Returns host numpy arrays; callers
    re-place onto the device (``CEPProcessor.place``).

    A tiered state (``engine/tiered.py: TieredState``) widens by widening
    its engine half; the stencil prefix carry is shaped by the *pattern*
    (prefix length), not by any capacity knob, so it copies verbatim —
    a live partial prefix survives the migration bit-for-bit.
    """
    inner = getattr(state, "engine", None)
    if inner is not None:
        import jax as _jax

        return state._replace(
            engine=widen_state(inner, old, new),
            carry=_jax.tree_util.tree_map(np.asarray, state.carry),
        )
    check_widens(old, new)
    g = lambda x: np.asarray(x)  # device_get + concrete dtype
    R2, E2, MP2, D2 = (
        new.max_runs, new.slab_entries, new.slab_preds, new.dewey_depth,
    )
    # Run-queue axis: dead-slot fill values match the queue compaction's
    # (matcher.py ``compact`` fill args) so the widened state is exactly
    # what a wide compaction of the same live set would have produced.
    ver = _pad(_pad(g(state.ver), -1, D2, 0), -2, R2, 0)
    slab = state.slab
    new_slab = SlabState(
        stage=_pad(g(slab.stage), -1, E2, -1),
        off=_pad(g(slab.off), -1, E2, -1),
        refs=_pad(g(slab.refs), -1, E2, 0),
        npreds=_pad(g(slab.npreds), -1, E2, 0),
        pstage=_pad(_pad(g(slab.pstage), -1, MP2, -1), -2, E2, -1),
        poff=_pad(_pad(g(slab.poff), -1, MP2, -1), -2, E2, -1),
        pver=_pad(
            _pad(_pad(g(slab.pver), -1, D2, 0), -2, MP2, 0), -3, E2, 0
        ),
        pvlen=_pad(_pad(g(slab.pvlen), -1, MP2, 0), -2, E2, 0),
        full_drops=g(slab.full_drops),
        pred_drops=g(slab.pred_drops),
        missing=g(slab.missing),
        trunc=g(slab.trunc),
        collisions=g(slab.collisions),
        hot_hits=g(slab.hot_hits),
        hot_misses=g(slab.hot_misses),
        overflow_walks=g(slab.overflow_walks),
        demotions=g(slab.demotions),
        walk_hops=g(slab.walk_hops),
        extract_hops=g(slab.extract_hops),
        drain_hops=g(slab.drain_hops),
        # Per-stage attribution: [S] is pattern-shaped, not capacity-
        # shaped — copied verbatim like every other counter.
        stage_hops=g(slab.stage_hops),
    )
    # Handle-ring axis (HB -> HB'): pending handles occupy a contiguous
    # prefix in completion order (appends at hr_count, drain clears to 0),
    # so appending empty slots — the same fill values init_state/drain use
    # — is exactly the state a wide ring would hold; a ring slot past
    # hr_count is never read.  The widened ring only retains what the
    # narrow ring would have counted in handle_overflows.
    HB2 = new.handle_ring
    return EngineState(
        alive=_pad(g(state.alive), -1, R2, False),
        id_pos=_pad(g(state.id_pos), -1, R2, -1),
        eval_pos=_pad(g(state.eval_pos), -1, R2, 0),
        ver=ver,
        vlen=_pad(g(state.vlen), -1, R2, 0),
        event_off=_pad(g(state.event_off), -1, R2, -1),
        start_ts=_pad(g(state.start_ts), -1, R2, -1),
        branching=_pad(g(state.branching), -1, R2, False),
        agg=_pad(g(state.agg), -2, R2, 0),
        slab=new_slab,
        run_drops=g(state.run_drops),
        ver_overflows=g(state.ver_overflows),
        hr_stage=_pad(g(state.hr_stage), -1, HB2, -1),
        hr_off=_pad(g(state.hr_off), -1, HB2, -1),
        hr_ver=_pad(_pad(g(state.hr_ver), -1, D2, 0), -2, HB2, 0),
        hr_vlen=_pad(g(state.hr_vlen), -1, HB2, 0),
        hr_ts=_pad(g(state.hr_ts), -1, HB2, 0),
        hr_seq=_pad(g(state.hr_seq), -1, HB2, 0),
        hr_row=_pad(g(state.hr_row), -1, HB2, 0),
        hr_count=g(state.hr_count),
        step_seq=g(state.step_seq),
        handle_overflows=g(state.handle_overflows),
        stage_counts=g(state.stage_counts),
    )


def canonical_state(state: EngineState) -> EngineState:
    """Project ``state`` onto its *observable* content: dead slots take
    canonical fill values.

    The engine never reads a dead run slot (``alive`` gates everything),
    a free slab row (``stage == -1`` never matches a lookup), or a
    pointer slot at index >= ``npreds`` (every pointer scan masks by it)
    — but those slots physically hold whatever the last shift/delete left
    behind, and the leftovers differ between the jnp and kernel walk
    implementations and across a migration (padded null vs stale
    residue).  Two states are behaviorally identical iff their canonical
    projections are bit-equal; the migration parity and chaos-oracle
    suites compare through this.

    Tiered states project their engine half; the stencil carry is already
    canonical (the trailing window is rewritten wholesale every scan, so
    it holds no implementation-dependent residue).
    """
    inner = getattr(state, "engine", None)
    if inner is not None:
        import jax as _jax

        return state._replace(
            engine=canonical_state(inner),
            carry=_jax.tree_util.tree_map(np.asarray, state.carry),
        )
    g = lambda x: np.asarray(x)
    alive = g(state.alive)
    slab = state.slab
    stage = g(slab.stage)
    npreds = g(slab.npreds)
    live_e = stage >= 0
    mp = slab.pstage.shape[-1]
    live_p = live_e[..., None] & (
        np.arange(mp, dtype=np.int32) < npreds[..., None]
    )
    d = lambda m, arr, fill: np.where(m, g(arr), fill)
    dp = live_p[..., None]  # broadcast over the Dewey axis
    # Ring slots past the pending prefix are never read (appends write at
    # hr_count, drain reads [0, hr_count)); their residue differs between
    # the drain implementations, so they canonicalize to the init fills.
    hb = state.hr_stage.shape[-1]
    pend = np.arange(hb, dtype=np.int32) < g(state.hr_count)[..., None]
    return EngineState(
        alive=alive,
        id_pos=d(alive, state.id_pos, -1),
        eval_pos=d(alive, state.eval_pos, 0),
        ver=d(alive[..., None], state.ver, 0),
        vlen=d(alive, state.vlen, 0),
        event_off=d(alive, state.event_off, -1),
        start_ts=d(alive, state.start_ts, -1),
        branching=d(alive, state.branching, False),
        agg=d(alive[..., None], state.agg, 0),
        slab=slab._replace(
            stage=stage,
            off=d(live_e, slab.off, -1),
            refs=d(live_e, slab.refs, 0),
            npreds=d(live_e, npreds, 0),
            pstage=d(live_p, slab.pstage, -1),
            poff=d(live_p, slab.poff, -1),
            pver=d(dp, slab.pver, 0),
            pvlen=d(live_p, slab.pvlen, 0),
        ),
        run_drops=g(state.run_drops),
        ver_overflows=g(state.ver_overflows),
        hr_stage=d(pend, state.hr_stage, -1),
        hr_off=d(pend, state.hr_off, -1),
        hr_ver=d(pend[..., None], state.hr_ver, 0),
        hr_vlen=d(pend, state.hr_vlen, 0),
        hr_ts=d(pend, state.hr_ts, 0),
        hr_seq=d(pend, state.hr_seq, 0),
        hr_row=d(pend, state.hr_row, 0),
        hr_count=g(state.hr_count),
        step_seq=g(state.step_seq),
        handle_overflows=g(state.handle_overflows),
        stage_counts=g(state.stage_counts),
    )


def migrate_processor(pattern, proc, new_config: EngineConfig, mesh=None):
    """Rebuild a live :class:`CEPProcessor` on a strictly-wider config.

    ``pattern`` is re-compiled fresh (the ``ComputationStageSerDe``
    contract: code never migrates, only state); all host bookkeeping —
    lane map, offsets, event mirror, metrics — carries over by reference
    semantics identical to a checkpoint restore, but without touching
    disk.  The processor must hold no undecoded pipelined batch (call
    ``flush()`` first): a device output is shaped by the *old* config and
    cannot survive the migration.
    """
    from kafkastreams_cep_tpu.runtime.processor import CEPProcessor

    if getattr(proc, "_pending", None) is not None:
        raise ValueError(
            "pipelined processor holds an undecoded batch; call flush() "
            "before migrating (device outputs are shaped by the old config)"
        )
    old_config = proc.batch.matcher.config
    check_widens(old_config, new_config)
    new_proc = CEPProcessor(
        pattern,
        proc.num_lanes,
        new_config,
        topic=proc.topic,
        epoch=proc.epoch,
        gc_events=proc.gc_events,
        dedup=proc.dedup,
        gc_interval=proc.gc_interval,
        gc_events_interval=proc.gc_events_interval,
        decode_budget=proc.decode_budget,
        pipeline=proc.pipeline,
        drain_interval=proc.drain_interval,
        mesh=mesh if mesh is not None else proc.mesh,
    )
    if list(new_proc.batch.names) != list(proc.batch.names):
        raise ValueError(
            "pattern topology changed across migration: stages "
            f"{new_proc.batch.names} vs live {proc.batch.names}"
        )
    new_proc.state = new_proc.place(
        widen_state(proc.state, old_config, new_config)
    )
    new_proc._lane_of = dict(proc._lane_of)
    new_proc._key_of = dict(proc._key_of)
    new_proc._next_offset = proc._next_offset.copy()
    new_proc._off_base = proc._off_base.copy()
    new_proc._events = [dict(d) for d in proc._events]
    new_proc._col_batches = list(proc._col_batches)
    new_proc._value_proto = proc._value_proto
    new_proc._step_base = proc._step_base  # pending-handle ordering base
    new_proc.metrics = proc.metrics  # continuity: one stream, one meter
    # Flight recorder continuity: the ring (and its burst baseline) spans
    # the migration like the metrics do.
    new_proc.flight = proc.flight
    new_proc._dlq_base = proc._dlq_base
    # Ingestion guard (runtime/ingest.py): pure host state — held records,
    # watermark, dead letters, and loss counters move with the migration
    # exactly like the event mirror (the engine never saw the held
    # records, so widening cannot perturb them).
    new_proc._guard = proc._guard
    logger.info(
        "migrated processor %s -> %s",
        {f: getattr(old_config, f) for f in _SHAPE_DIMS},
        {f: getattr(new_config, f) for f in _SHAPE_DIMS},
    )
    return new_proc
