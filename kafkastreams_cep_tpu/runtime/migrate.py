"""Live-state migration onto a strictly-wider ``EngineConfig``.

The reference never needs this: its run queue, shared buffer, and Dewey
versions are heap-backed and unbounded (``NFA.java:75``,
``CEPProcessor.java:144-149``).  The array engine's fixed shapes make
capacity the design's own failure mode — overflow is counted and dropped
(``ops/slab.py``), never silent, but the dropped branches are gone.  This
module is the escape hatch: widen every state array of a *live* processor
so the supervisor can escalate capacity mid-stream instead of warning
about loss (``runtime/supervisor.py`` ``auto_escalate``).

Why widening is a pure embedding (the proof burden, per dimension)
-------------------------------------------------------------------
A migration must guarantee: stepping the widened state under the wide
engine produces, for as long as the *narrow* engine would not have hit a
capacity limit, bit-identical run queues, slab contents, Dewey versions,
fold state, match emissions, and capacity counters — and past the point
the narrow engine would drop, the wide engine simply retains what the
narrow one lost.  Dimension by dimension:

* **R -> R' (run queue).**  Queue compaction (``engine/matcher.py
  finish``) always leaves live runs in a contiguous prefix in queue
  order, dead slots carrying the compaction fill values.  Appending dead
  slots (the same fill values) preserves the prefix and its order; dead
  slots are fully masked in the chain (``alive`` gates every predicate,
  put, walk, and candidate), so they contribute nothing until a
  compaction writes a live run into them — exactly when the narrow queue
  would have counted a ``run_drops``.
* **E -> E' (slab entries).**  Entries are keyed by ``(stage, off)`` —
  unique across the slab — and every lookup is a full-slab masked match,
  so results are placement-independent; allocation takes the *first*
  free slot (``argmax``), and appended free slots sit at the end, so
  allocation order is unchanged until the narrow slab would have been
  full (a ``slab_full_drops``).  Two-tier layouts add demotion, but the
  victim choice reads only occupied-hot rows (appended slots are free
  overflow rows) and the overflow destination is again first-free —
  unchanged until the narrow overflow tier would have filled.  Refcounts,
  npreds, and the free list ride along untouched.
* **MP -> MP' (predecessor lists).**  Pointers append at ``npreds`` and
  walks take the first version-compatible pointer in insertion order;
  padding null pointers (``pstage == -1``) past ``npreds`` is exactly the
  representation an MP'-wide engine would have built.
* **D -> D' (Dewey width).**  Versions are left-aligned digit vectors
  with an explicit length; every Dewey op masks by length and slots at
  index >= vlen are zero by construction (``ops/dewey_ops.py``), so a
  zero-extended tail is the same version in a wider vector, and
  ``is_compatible``/``add_run``/``add_stage`` answer identically.
* **W, walker_budget (walk/compute bounds).**  Not state-shaped; growing
  them needs no array change (they bound per-step compute, and a longer
  bound only extends walks the narrow engine would have truncated into a
  ``slab_trunc``).
* **Counters.**  Copied verbatim — migration never forgives past loss;
  the supervisor's escalation protocol instead *rolls back* to the last
  pre-loss state and re-processes, which is what makes "finish with all
  loss counters zero" achievable.

The hot-tier split (``slab_hot_entries``) is a perf knob with no capacity
semantics (drops are bit-identical at any E_hot — ``ops/slab.py``
"Two-tier layout"); migration may change it freely, which moves entries'
*tier accounting* (``hot_hits``/``demotions`` telemetry) but never the
match stream or any capacity counter.

Embedding parity — each dim widened alone and combined, jnp and kernel
walk paths — is property-tested in ``tests/test_migrate.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from kafkastreams_cep_tpu.engine.matcher import EngineConfig, EngineState
from kafkastreams_cep_tpu.ops.slab import SlabState
from kafkastreams_cep_tpu.utils.failpoints import fire as _failpoint
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime.migrate")

#: Sentinel for ``move_lanes(mesh=...)``: "keep the processor's current
#: mesh" must be distinguishable from "explicitly unmeshed" (``None``).
_KEEP_MESH = object()

# Config fields that are array-shape dims (may only grow) vs semantic
# switches (must not change under a live migration: they alter the match
# stream, not capacity).
_SHAPE_DIMS = (
    "max_runs", "slab_entries", "slab_preds", "dewey_depth", "max_walk",
    "handle_ring",
)
_SEMANTIC_FLAGS = (
    "renorm_versions", "enforce_windows", "sequential_slab", "walker_budget",
    "lazy_extraction",
    # Not semantic for the match stream, but it shapes the attribution
    # arrays ([S] vs [0]) — a live embedding across the flip does not
    # exist, so it rides the no-change list.
    "stage_attribution",
    # Tiering shapes the state itself (TieredState wraps the engine state
    # with the stencil prefix carry, engine/tiered.py): a flip mid-stream
    # would orphan either the carry or the seed run.
    "tiering",
)


def check_widens(old: EngineConfig, new: EngineConfig) -> None:
    """Refuse a migration target that is not a pure widening of ``old``."""
    for f in _SHAPE_DIMS:
        o, n = getattr(old, f), getattr(new, f)
        if n < o:
            raise ValueError(
                f"migration cannot shrink {f}: {o} -> {n} (state embedding "
                "only exists into a strictly-wider config)"
            )
    for f in _SEMANTIC_FLAGS:
        o, n = getattr(old, f), getattr(new, f)
        if o != n:
            raise ValueError(
                f"migration cannot change {f} ({o} -> {n}): it alters match "
                "semantics, not capacity — restart the processor instead"
            )
    if new == old:
        raise ValueError("migration target equals the current config")


def _pad(arr: np.ndarray, axis: int, new_size: int, fill) -> np.ndarray:
    """Grow ``arr`` along ``axis`` (negative, from the end) to
    ``new_size``, new slots holding ``fill``."""
    ax = arr.ndim + axis
    grow = new_size - arr.shape[ax]
    if grow == 0:
        return arr
    shape = list(arr.shape)
    shape[ax] = grow
    pad = np.full(shape, fill, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=ax)


def widen_state(
    state: EngineState, old: EngineConfig, new: EngineConfig
) -> EngineState:
    """Embed ``state`` (host or device arrays, any leading batch axes)
    into the shapes of ``new``.  Returns host numpy arrays; callers
    re-place onto the device (``CEPProcessor.place``).

    A tiered state (``engine/tiered.py: TieredState``) widens by widening
    its engine half; the stencil prefix carry is shaped by the *pattern*
    (prefix length), not by any capacity knob, so it copies verbatim —
    a live partial prefix survives the migration bit-for-bit.
    """
    inner = getattr(state, "engine", None)
    if inner is not None:
        import jax as _jax

        if isinstance(inner, (tuple, list)) and not hasattr(
            inner, "_fields"
        ):
            # Multi-tenant bank state (parallel/tenantbank.py): a PLAIN
            # tuple of engines, one stacked engine per residual group
            # (an EngineState itself is a NamedTuple and must NOT take
            # this branch), one carry per prefix group — widen each
            # engine, carries copy verbatim.
            return state._replace(
                engine=tuple(widen_state(e, old, new) for e in inner),
                carry=_jax.tree_util.tree_map(np.asarray, state.carry),
            )
        return state._replace(
            engine=widen_state(inner, old, new),
            carry=_jax.tree_util.tree_map(np.asarray, state.carry),
        )
    check_widens(old, new)
    g = lambda x: np.asarray(x)  # device_get + concrete dtype
    R2, E2, MP2, D2 = (
        new.max_runs, new.slab_entries, new.slab_preds, new.dewey_depth,
    )
    # Run-queue axis: dead-slot fill values match the queue compaction's
    # (matcher.py ``compact`` fill args) so the widened state is exactly
    # what a wide compaction of the same live set would have produced.
    ver = _pad(_pad(g(state.ver), -1, D2, 0), -2, R2, 0)
    slab = state.slab
    new_slab = SlabState(
        stage=_pad(g(slab.stage), -1, E2, -1),
        off=_pad(g(slab.off), -1, E2, -1),
        refs=_pad(g(slab.refs), -1, E2, 0),
        npreds=_pad(g(slab.npreds), -1, E2, 0),
        pstage=_pad(_pad(g(slab.pstage), -1, MP2, -1), -2, E2, -1),
        poff=_pad(_pad(g(slab.poff), -1, MP2, -1), -2, E2, -1),
        pver=_pad(
            _pad(_pad(g(slab.pver), -1, D2, 0), -2, MP2, 0), -3, E2, 0
        ),
        pvlen=_pad(_pad(g(slab.pvlen), -1, MP2, 0), -2, E2, 0),
        full_drops=g(slab.full_drops),
        pred_drops=g(slab.pred_drops),
        missing=g(slab.missing),
        trunc=g(slab.trunc),
        collisions=g(slab.collisions),
        hot_hits=g(slab.hot_hits),
        hot_misses=g(slab.hot_misses),
        overflow_walks=g(slab.overflow_walks),
        demotions=g(slab.demotions),
        walk_hops=g(slab.walk_hops),
        extract_hops=g(slab.extract_hops),
        drain_hops=g(slab.drain_hops),
        # Per-stage attribution: [S] is pattern-shaped, not capacity-
        # shaped — copied verbatim like every other counter.
        stage_hops=g(slab.stage_hops),
    )
    # Handle-ring axis (HB -> HB'): pending handles occupy a contiguous
    # prefix in completion order (appends at hr_count, drain clears to 0),
    # so appending empty slots — the same fill values init_state/drain use
    # — is exactly the state a wide ring would hold; a ring slot past
    # hr_count is never read.  The widened ring only retains what the
    # narrow ring would have counted in handle_overflows.
    HB2 = new.handle_ring
    return EngineState(
        alive=_pad(g(state.alive), -1, R2, False),
        id_pos=_pad(g(state.id_pos), -1, R2, -1),
        eval_pos=_pad(g(state.eval_pos), -1, R2, 0),
        ver=ver,
        vlen=_pad(g(state.vlen), -1, R2, 0),
        event_off=_pad(g(state.event_off), -1, R2, -1),
        start_ts=_pad(g(state.start_ts), -1, R2, -1),
        branching=_pad(g(state.branching), -1, R2, False),
        agg=_pad(g(state.agg), -2, R2, 0),
        slab=new_slab,
        run_drops=g(state.run_drops),
        ver_overflows=g(state.ver_overflows),
        hr_stage=_pad(g(state.hr_stage), -1, HB2, -1),
        hr_off=_pad(g(state.hr_off), -1, HB2, -1),
        hr_ver=_pad(_pad(g(state.hr_ver), -1, D2, 0), -2, HB2, 0),
        hr_vlen=_pad(g(state.hr_vlen), -1, HB2, 0),
        hr_ts=_pad(g(state.hr_ts), -1, HB2, 0),
        hr_seq=_pad(g(state.hr_seq), -1, HB2, 0),
        hr_row=_pad(g(state.hr_row), -1, HB2, 0),
        hr_count=g(state.hr_count),
        step_seq=g(state.step_seq),
        handle_overflows=g(state.handle_overflows),
        stage_counts=g(state.stage_counts),
    )


def canonical_state(state: EngineState) -> EngineState:
    """Project ``state`` onto its *observable* content: dead slots take
    canonical fill values.

    The engine never reads a dead run slot (``alive`` gates everything),
    a free slab row (``stage == -1`` never matches a lookup), or a
    pointer slot at index >= ``npreds`` (every pointer scan masks by it)
    — but those slots physically hold whatever the last shift/delete left
    behind, and the leftovers differ between the jnp and kernel walk
    implementations and across a migration (padded null vs stale
    residue).  Two states are behaviorally identical iff their canonical
    projections are bit-equal; the migration parity and chaos-oracle
    suites compare through this.

    Tiered states project their engine half; the stencil carry is already
    canonical (the trailing window is rewritten wholesale every scan, so
    it holds no implementation-dependent residue).
    """
    inner = getattr(state, "engine", None)
    if inner is not None:
        import jax as _jax

        if isinstance(inner, (tuple, list)) and not hasattr(
            inner, "_fields"
        ):
            # Multi-tenant bank: plain tuple of per-group engines (an
            # EngineState NamedTuple must NOT take this branch).
            return state._replace(
                engine=tuple(canonical_state(e) for e in inner),
                carry=_jax.tree_util.tree_map(np.asarray, state.carry),
            )
        return state._replace(
            engine=canonical_state(inner),
            carry=_jax.tree_util.tree_map(np.asarray, state.carry),
        )
    g = lambda x: np.asarray(x)
    alive = g(state.alive)
    slab = state.slab
    stage = g(slab.stage)
    npreds = g(slab.npreds)
    live_e = stage >= 0
    mp = slab.pstage.shape[-1]
    live_p = live_e[..., None] & (
        np.arange(mp, dtype=np.int32) < npreds[..., None]
    )
    d = lambda m, arr, fill: np.where(m, g(arr), fill)
    dp = live_p[..., None]  # broadcast over the Dewey axis
    # Ring slots past the pending prefix are never read (appends write at
    # hr_count, drain reads [0, hr_count)); their residue differs between
    # the drain implementations, so they canonicalize to the init fills.
    hb = state.hr_stage.shape[-1]
    pend = np.arange(hb, dtype=np.int32) < g(state.hr_count)[..., None]
    return EngineState(
        alive=alive,
        id_pos=d(alive, state.id_pos, -1),
        eval_pos=d(alive, state.eval_pos, 0),
        ver=d(alive[..., None], state.ver, 0),
        vlen=d(alive, state.vlen, 0),
        event_off=d(alive, state.event_off, -1),
        start_ts=d(alive, state.start_ts, -1),
        branching=d(alive, state.branching, False),
        agg=d(alive[..., None], state.agg, 0),
        slab=slab._replace(
            stage=stage,
            off=d(live_e, slab.off, -1),
            refs=d(live_e, slab.refs, 0),
            npreds=d(live_e, npreds, 0),
            pstage=d(live_p, slab.pstage, -1),
            poff=d(live_p, slab.poff, -1),
            pver=d(dp, slab.pver, 0),
            pvlen=d(live_p, slab.pvlen, 0),
        ),
        run_drops=g(state.run_drops),
        ver_overflows=g(state.ver_overflows),
        hr_stage=d(pend, state.hr_stage, -1),
        hr_off=d(pend, state.hr_off, -1),
        hr_ver=d(pend[..., None], state.hr_ver, 0),
        hr_vlen=d(pend, state.hr_vlen, 0),
        hr_ts=d(pend, state.hr_ts, 0),
        hr_seq=d(pend, state.hr_seq, 0),
        hr_row=d(pend, state.hr_row, 0),
        hr_count=g(state.hr_count),
        step_seq=g(state.step_seq),
        handle_overflows=g(state.handle_overflows),
        stage_counts=g(state.stage_counts),
    )


def migrate_processor(pattern, proc, new_config: EngineConfig, mesh=None):
    """Rebuild a live :class:`CEPProcessor` on a strictly-wider config.

    ``pattern`` is re-compiled fresh (the ``ComputationStageSerDe``
    contract: code never migrates, only state); all host bookkeeping —
    lane map, offsets, event mirror, metrics — carries over by reference
    semantics identical to a checkpoint restore, but without touching
    disk.  The processor must hold no undecoded pipelined batch (call
    ``flush()`` first): a device output is shaped by the *old* config and
    cannot survive the migration.
    """
    from kafkastreams_cep_tpu.runtime.processor import CEPProcessor

    if getattr(proc, "_pending", None) is not None:
        raise ValueError(
            "pipelined processor holds an undecoded batch; call flush() "
            "before migrating (device outputs are shaped by the old config)"
        )
    old_config = proc.batch.matcher.config
    check_widens(old_config, new_config)
    new_proc = CEPProcessor(
        pattern,
        proc.num_lanes,
        new_config,
        topic=proc.topic,
        epoch=proc.epoch,
        gc_events=proc.gc_events,
        dedup=proc.dedup,
        gc_interval=proc.gc_interval,
        gc_events_interval=proc.gc_events_interval,
        decode_budget=proc.decode_budget,
        pipeline=proc.pipeline,
        drain_interval=proc.drain_interval,
        mesh=mesh if mesh is not None else proc.mesh,
    )
    if list(new_proc.batch.names) != list(proc.batch.names):
        raise ValueError(
            "pattern topology changed across migration: stages "
            f"{new_proc.batch.names} vs live {proc.batch.names}"
        )
    new_proc.state = new_proc.place(
        widen_state(proc.state, old_config, new_config)
    )
    new_proc._lane_of = dict(proc._lane_of)
    new_proc._key_of = dict(proc._key_of)
    new_proc._next_offset = proc._next_offset.copy()
    new_proc._off_base = proc._off_base.copy()
    new_proc._events = [dict(d) for d in proc._events]
    new_proc._col_batches = list(proc._col_batches)
    new_proc._value_proto = proc._value_proto
    new_proc._step_base = proc._step_base  # pending-handle ordering base
    new_proc.metrics = proc.metrics  # continuity: one stream, one meter
    # Flight recorder continuity: the ring (and its burst baseline) spans
    # the migration like the metrics do.
    new_proc.flight = proc.flight
    new_proc._dlq_base = proc._dlq_base
    # Ingestion guard (runtime/ingest.py): pure host state — held records,
    # watermark, dead letters, and loss counters move with the migration
    # exactly like the event mirror (the engine never saw the held
    # records, so widening cannot perturb them).
    new_proc._guard = proc._guard
    # Latency ledger + clock: continuity by reference, like metrics —
    # committed histograms and in-flight deferred bundles survive the
    # rebuild (deferred handles moved with the engine state above).
    new_proc.ledger = proc.ledger
    new_proc._clock = proc._clock
    logger.info(
        "migrated processor %s -> %s",
        {f: getattr(old_config, f) for f in _SHAPE_DIMS},
        {f: getattr(new_config, f) for f in _SHAPE_DIMS},
    )
    return new_proc


def replan_processor(pattern, proc, profile):
    """Swap a live tiered :class:`CEPProcessor` onto a re-derived
    execution plan (adaptive recompilation, ISSUE 16).

    ``profile`` is a measured ``per_stage`` snapshot (optionally carrying
    per-conjunct rows — ``stage_counters()`` under ``stage_attribution``)
    that re-runs ``apply_lazy_order``/``plan_tiering`` inside the rebuilt
    :class:`TieredBatchMatcher`.  Unlike :func:`migrate_processor` the
    config is *unchanged*: conjunct reordering commutes (property-tested
    in tests/test_tiering.py) and the tier split is a function of pattern
    + config alone, so every state array transfers verbatim — no
    embedding, and matches/emission order/loss counters are invariant to
    the swap point.  Like every live rebuild, the processor must hold no
    undecoded pipelined batch (``flush()`` first).
    """
    from kafkastreams_cep_tpu.runtime.processor import CEPProcessor

    if getattr(proc, "_pending", None) is not None:
        raise ValueError(
            "pipelined processor holds an undecoded batch; call flush() "
            "before replanning (the old plan owns the in-flight dispatch)"
        )
    config = proc.batch.matcher.config
    if not getattr(config, "tiering", False):
        raise ValueError("replan_processor requires a tiered processor")
    # Fault site: a replan that dies here leaves the OLD processor fully
    # intact — the caller keeps the old plan and nothing is lost.
    _failpoint("replan.swap")
    new_proc = CEPProcessor(
        pattern,
        proc.num_lanes,
        config,
        topic=proc.topic,
        epoch=proc.epoch,
        gc_events=proc.gc_events,
        dedup=proc.dedup,
        gc_interval=proc.gc_interval,
        gc_events_interval=proc.gc_events_interval,
        decode_budget=proc.decode_budget,
        pipeline=proc.pipeline,
        drain_interval=proc.drain_interval,
        mesh=proc.mesh,
        profile=profile,
    )
    if list(new_proc.batch.names) != list(proc.batch.names):
        raise ValueError(
            "pattern topology changed across the replan: stages "
            f"{new_proc.batch.names} vs live {proc.batch.names}"
        )
    new_proc.state = new_proc.place(
        _jax_tree_host(proc.state)
    )
    new_proc._lane_of = dict(proc._lane_of)
    new_proc._key_of = dict(proc._key_of)
    new_proc._next_offset = proc._next_offset.copy()
    new_proc._off_base = proc._off_base.copy()
    new_proc._events = [dict(d) for d in proc._events]
    new_proc._col_batches = list(proc._col_batches)
    new_proc._value_proto = proc._value_proto
    new_proc._step_base = proc._step_base  # pending-handle ordering base
    new_proc.metrics = proc.metrics  # continuity: one stream, one meter
    new_proc.flight = proc.flight
    new_proc._dlq_base = proc._dlq_base
    new_proc._guard = proc._guard
    new_proc.ledger = proc.ledger  # continuity by reference, like metrics
    new_proc._clock = proc._clock
    logger.info(
        "replanned processor: tier=%s lazy_order=%s",
        new_proc.batch.plan.tier,
        {
            s: r.get("order")
            for s, r in getattr(new_proc.batch, "lazy_order", {}).items()
            if r.get("reordered")
        },
    )
    return new_proc


def _jax_tree_host(state):
    """Every state leaf as a host numpy array (shape-preserving)."""
    import jax as _jax

    return _jax.tree_util.tree_map(np.asarray, state)


# -- lane repartitioning (shard evacuation / hot-key rebalancing) ------------
#
# Why a lane permutation is a pure relabeling (the proof burden)
# --------------------------------------------------------------
# The mesh shards the leading ``[K]`` lane axis into contiguous blocks
# (``parallel/sharding.py``: ``NamedSharding(mesh, P(axis))``), so moving
# lanes between shards == permuting *logical* lane indices and re-placing.
# That permutation is unobservable, because lane identity is entirely
# internal:
#
# * **Device state.**  Every leaf of ``EngineState``/``SlabState`` (and the
#   ``TieredState`` stencil carry) carries a leading ``[K]`` axis, and the
#   engine is built by lifting a per-lane step with ``vmap``
#   (``parallel/batch.py: lane_step``) — no operation reads across lanes.
#   The only collective on the sharded path is the ``stats`` reduction
#   (``psum`` of per-lane sums), and a sum is permutation-invariant.
# * **External identity is the key, not the lane.**  Records reach a lane
#   only through the host map ``_lane_of`` and matches are emitted keyed
#   by the original key with record-rank ordering (``processor._decode``
#   orders by arrival rank / ``step_seq``, never by lane index).
#   Permuting the state rows and every lane-indexed host structure —
#   ``_lane_of``/``_key_of``, per-lane offsets, the event mirror, queued
#   column batches, the ingest guard's per-lane source high-waters — by
#   the SAME permutation therefore yields a processor whose observable
#   behavior (matches, order, counters) is bit-identical.
# * **Counters.**  Per-lane counters permute with their lanes; every
#   reported total is a lane sum and is unchanged.  A repartition never
#   forgives or invents loss — ``canonical_state`` of the moved state is
#   the lane-permuted ``canonical_state`` of the original, exactly
#   (property-tested in ``tests/test_shard_fault.py``, jnp and kernel
#   walk paths, two-tier slab, live handle ring, tiered carry).


def repartition_state(state, perm: Sequence[int]):
    """Permute the leading ``[K]`` lane axis of every state leaf:
    ``new[i] = old[perm[i]]``.  Returns host numpy arrays; callers
    re-place onto the target mesh (``CEPProcessor.place``).

    ``perm`` must be a permutation of ``range(K)``.  Works on
    ``EngineState`` and ``TieredState`` alike — the stencil prefix carry
    is per-lane ``[K, ...]`` shaped and permutes with its engine half.
    """
    import jax as _jax

    perm = np.asarray(perm, dtype=np.int64).reshape(-1)
    k = perm.shape[0]
    if not np.array_equal(np.sort(perm), np.arange(k)):
        raise ValueError(
            f"perm is not a permutation of range({k}): {perm.tolist()}"
        )

    def take(x):
        arr = np.asarray(x)
        if arr.ndim == 0 or arr.shape[0] != k:
            raise ValueError(
                f"state leaf shape {arr.shape} has no leading [{k}] lane "
                "axis; repartition_state requires lane-batched state"
            )
        return arr[perm]

    return _jax.tree_util.tree_map(take, state)


def plan_rebalance(
    loads: Sequence[int], num_shards: int
) -> Optional[np.ndarray]:
    """A lane permutation that balances per-shard load, or ``None``.

    ``loads`` is a per-lane cost vector (the PR 6 heavy-hitter signal:
    walk + extract + drain hops over the last window).  Shards own
    contiguous blocks of ``K / num_shards`` lanes, so balancing =
    choosing which lanes land in which block: greedy LPT — lanes in
    descending cost order, each to the least-loaded shard with block
    capacity left — then the permutation is the concatenation of the
    blocks.  Deterministic (stable sort, index tie-break).

    Returns ``None`` when the plan would not strictly reduce the maximum
    per-shard load (hysteresis belongs to the caller; this is the
    no-improvement guard so a balanced mesh never thrashes).
    """
    loads = np.asarray(loads, dtype=np.int64).reshape(-1)
    k = loads.shape[0]
    n = int(num_shards)
    if n < 2 or k % n:
        return None
    per = k // n
    old_max = int(loads.reshape(n, per).sum(axis=1).max())
    order = np.argsort(-loads, kind="stable")
    shard_load = np.zeros(n, dtype=np.int64)
    blocks: list = [[] for _ in range(n)]
    for lane in order:
        open_shards = [s for s in range(n) if len(blocks[s]) < per]
        dest = min(open_shards, key=lambda s: (int(shard_load[s]), s))
        blocks[dest].append(int(lane))
        shard_load[dest] += int(loads[lane])
    if int(shard_load.max()) >= old_max:
        return None
    return np.asarray([lane for b in blocks for lane in b], dtype=np.int64)


def move_lanes(pattern, proc, perm=None, mesh=_KEEP_MESH):
    """Rebuild a live :class:`CEPProcessor` under a new lane→shard
    assignment: state rows permuted by ``perm`` (:func:`repartition_state`)
    and re-placed onto ``mesh`` — the same mesh (hot-key rebalancing), a
    shrunk surviving sub-mesh (shard evacuation), or ``None`` (degrade to
    a single device).

    Every lane-indexed host structure moves through the same permutation,
    so key→lane routing, offset dedup, the event mirror, and the ingest
    guard's per-lane high-waters stay consistent with the relabeled state
    (see the module-level pure-relabeling argument).  Like
    :func:`migrate_processor`, the processor must hold no undecoded
    pipelined batch — ``flush()`` first.
    """
    from kafkastreams_cep_tpu.runtime.processor import CEPProcessor

    if getattr(proc, "_pending", None) is not None:
        raise ValueError(
            "pipelined processor holds an undecoded batch; call flush() "
            "before moving lanes (device outputs are lane-ordered by the "
            "old assignment)"
        )
    k = proc.num_lanes
    perm = (
        np.arange(k, dtype=np.int64)
        if perm is None
        else np.asarray(perm, dtype=np.int64).reshape(-1)
    )
    if perm.shape[0] != k or not np.array_equal(np.sort(perm), np.arange(k)):
        raise ValueError(
            f"perm must be a permutation of range({k}): {perm.tolist()}"
        )
    new_mesh = proc.mesh if mesh is _KEEP_MESH else mesh
    # Fault site: a move that dies here leaves the OLD processor fully
    # intact — the caller keeps the old assignment and nothing is lost.
    _failpoint("rebalance.move")
    inv = np.empty(k, dtype=np.int64)
    inv[perm] = np.arange(k, dtype=np.int64)
    config = proc.batch.matcher.config
    new_proc = CEPProcessor(
        pattern,
        k,
        config,
        topic=proc.topic,
        epoch=proc.epoch,
        gc_events=proc.gc_events,
        dedup=proc.dedup,
        gc_interval=proc.gc_interval,
        gc_events_interval=proc.gc_events_interval,
        decode_budget=proc.decode_budget,
        pipeline=proc.pipeline,
        drain_interval=proc.drain_interval,
        mesh=new_mesh,
    )
    if list(new_proc.batch.names) != list(proc.batch.names):
        raise ValueError(
            "pattern topology changed across the move: stages "
            f"{new_proc.batch.names} vs live {proc.batch.names}"
        )
    new_proc.state = new_proc.place(repartition_state(proc.state, perm))
    # Host bookkeeping: old lane ``p`` becomes new lane ``inv[p]``.
    new_proc._lane_of = {key: int(inv[l]) for key, l in proc._lane_of.items()}
    new_proc._key_of = {int(inv[l]): key for l, key in proc._key_of.items()}
    new_proc._next_offset = proc._next_offset[perm].copy()
    new_proc._off_base = proc._off_base[perm].copy()
    new_proc._events = [dict(proc._events[int(p)]) for p in perm]
    new_proc._col_batches = [
        tuple(
            [leaf[perm] for leaf in part] if isinstance(part, list)
            else np.asarray(part)[perm]
            for part in entry
        )
        for entry in proc._col_batches
    ]
    new_proc._value_proto = proc._value_proto
    new_proc._step_base = proc._step_base  # pending-handle ordering base
    new_proc.metrics = proc.metrics  # continuity: one stream, one meter
    new_proc.flight = proc.flight
    new_proc._dlq_base = proc._dlq_base
    new_proc._guard = proc._guard
    new_proc.ledger = proc.ledger  # continuity by reference, like metrics
    new_proc._clock = proc._clock
    if new_proc._guard is not None:
        new_proc._guard.source_hw = {
            int(inv[l]): hw for l, hw in new_proc._guard.source_hw.items()
        }
    moved = int((perm != np.arange(k)).sum())
    logger.info(
        "moved %d/%d lanes onto %s",
        moved, k,
        "no mesh" if new_mesh is None
        else f"{new_mesh.devices.size}-device mesh",
    )
    return new_proc
