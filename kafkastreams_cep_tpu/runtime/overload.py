"""Overload control — SLO-burn-driven brownout ladder (ISSUE 20).

Sustained offered load above device capacity used to have no controlled
failure mode: the reorder buffer and handle ring grew until eviction and
overflow counters tripped, the SLO burn rate rose, and nothing acted on
it.  This module closes the loop between the sensors the runtime already
has (``SLOTracker`` burn rate, reorder hold depth/age, queue-segment
latency, deferred drain backlog) and the actuators it already has (drain
cadence, telemetry depth, per-tenant admission buckets, ingest-door
shedding) through a small deterministic state machine:

=====  =============================================================
level  degradation
=====  =============================================================
L0     healthy — no intervention
L1     widen drain cadence; defer non-essential telemetry reads
       (per-lane/per-key device gathers)
L2     tighten per-tenant admission token buckets proportionally to
       each tenant's measured cost share (heavy hitters squeezed
       hardest, zero-share tenants untouched)
L3     shed admissible records at ingest with the typed
       ``overload_shed`` dead-letter reason — every drop stays in the
       loss ledger, so ``offered == admitted + shed + dead_lettered``
       reconciles exactly
L4     emergency — checkpoint, flush pinned drains, refuse all new
       admissions while the backlog clears
=====  =============================================================

**Determinism.**  The controller itself is pure host state: the pressure
scalar is the max of the normalized signals, levels move one step per
tick, and entry/exit each require a streak of consecutive agreeing ticks
(with ``exit_at < enter_at`` hysteresis so the ladder never flaps on a
boundary).  Shedding at L3+ uses a within-batch Bresenham stride over
the *admissible* records (validation and replay dedup run first), so the
same batch always sheds the same records — a replayed crash admits the
identical subset.

**Durability.**  The supervisor owns every transition: it fires the
``overload.enter`` / ``overload.exit`` failpoints, applies the
actuators, then pins the new level with an immediate checkpoint.  A pin
failure reverts the level and actuators (counted in
``overload_transition_failures``), preserving the invariant that the
in-memory level always equals the last-pinned level — so restore,
migration, and evacuation rewire the actuators from
:meth:`OverloadController.to_state` and a replayed crash lands in the
same level.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime.overload")

#: Number of brownout levels above L0.
MAX_LEVEL = 4


@dataclasses.dataclass(frozen=True)
class OverloadPolicy:
    """Thresholds, hysteresis, and per-level actuator settings.

    Signal references (a raw signal at its reference reads as pressure
    1.0):

    ``burn_ref``      — SLO burn rate (1.0 = burning exactly at budget).
    ``hold_ref``      — reorder-buffer occupancy as a fraction of
                        ``reorder_depth``.
    ``hold_age_ref``  — oldest-held-record event-time age as a multiple
                        of the grace window.
    ``queue_ref``     — ingest-queue segment p99, seconds.
    ``ring_ref``      — deferred drain bundles outstanding (the host
                        proxy for handle-ring occupancy; lazy extraction
                        parks match handles until the drain).  Keep this
                        comfortably above ``max(drain_widen)`` — the
                        widened cadence *creates* deferred bundles, and a
                        tight reference would let the L1 actuator feed
                        its own escalation.

    The ladder: pressure ``>= enter_at[L]`` for ``enter_streak``
    consecutive ticks enters level L+1 from L; pressure ``<=
    exit_at[L-1]`` for ``exit_streak`` ticks drops back to L-1.
    ``exit_at`` sits below ``enter_at`` (hysteresis) and the exit streak
    is longer than the entry streak, so recovery is deliberate and the
    ladder cannot oscillate on a noisy boundary.

    Actuators, indexed by level 0..4:

    ``drain_widen``      — multiplier on the processor's base
                           ``drain_interval``.
    ``admission_scale``  — per-tenant token-bucket squeeze handed to
                           :meth:`AdmissionLimiter.set_pressure` (1.0 =
                           open).
    ``shed_fraction``    — fraction of admissible records shed at the
                           ingest door (1.0 at L4 = refuse everything).
    """

    burn_ref: float = 1.0
    hold_ref: float = 0.5
    hold_age_ref: float = 4.0
    queue_ref: float = 1.0
    ring_ref: float = 16.0
    enter_at: Tuple[float, ...] = (1.0, 2.0, 4.0, 8.0)
    exit_at: Tuple[float, ...] = (0.5, 1.0, 2.0, 4.0)
    enter_streak: int = 2
    exit_streak: int = 4
    drain_widen: Tuple[int, ...] = (1, 4, 4, 8, 8)
    admission_scale: Tuple[float, ...] = (1.0, 1.0, 0.5, 0.25, 0.0)
    shed_fraction: Tuple[float, ...] = (0.0, 0.0, 0.0, 0.5, 1.0)

    def __post_init__(self):
        n = MAX_LEVEL
        if len(self.enter_at) != n or len(self.exit_at) != n:
            raise ValueError(
                f"enter_at/exit_at need {n} thresholds (L1..L{n}), got "
                f"{self.enter_at!r} / {self.exit_at!r}"
            )
        for lvl in range(n):
            if self.exit_at[lvl] >= self.enter_at[lvl]:
                raise ValueError(
                    "hysteresis requires exit_at < enter_at at every "
                    f"level, got exit {self.exit_at[lvl]} >= enter "
                    f"{self.enter_at[lvl]} at L{lvl + 1}"
                )
        for name in ("drain_widen", "admission_scale", "shed_fraction"):
            if len(getattr(self, name)) != n + 1:
                raise ValueError(
                    f"{name} needs {n + 1} entries (L0..L{n}), got "
                    f"{getattr(self, name)!r}"
                )
        if self.enter_streak < 1 or self.exit_streak < 1:
            raise ValueError("streaks must be >= 1")


#: level -> (trigger, action, blast radius, exit condition) — drives the
#: README "Overload & backpressure" ladder table; the README embeds
#: :func:`ladder_table_markdown` output verbatim (pinned by
#: tests/test_overload.py).
LADDER_DOCS: Tuple[Tuple[str, str, str, str, str], ...] = (
    (
        "L0",
        "—",
        "none (healthy)",
        "none",
        "—",
    ),
    (
        "L1",
        "pressure >= `enter_at[0]` for `enter_streak` ticks",
        "widen drain cadence (`drain_widen`); defer per-lane/per-key "
        "telemetry gathers",
        "emit latency only — no record is dropped or reordered",
        "pressure <= `exit_at[0]` for `exit_streak` ticks",
    ),
    (
        "L2",
        "pressure >= `enter_at[1]` for `enter_streak` ticks",
        "tighten per-tenant admission buckets by `admission_scale`, "
        "proportional to measured cost share",
        "heavy-hitter tenants throttled (typed `tenant_quota` sheds); "
        "compliant tenants untouched",
        "pressure <= `exit_at[1]` for `exit_streak` ticks",
    ),
    (
        "L3",
        "pressure >= `enter_at[2]` for `enter_streak` ticks",
        "shed `shed_fraction` of admissible records at ingest "
        "(deterministic within-batch stride), typed `overload_shed`; "
        "flight-recorder dump on entry",
        "all tenants lose a bounded, fully-accounted fraction",
        "pressure <= `exit_at[2]` for `exit_streak` ticks",
    ),
    (
        "L4",
        "pressure >= `enter_at[3]` for `enter_streak` ticks",
        "emergency: checkpoint + flush pinned drains on entry, refuse "
        "all new admissions (typed `overload_shed`)",
        "total admission stop — backlog drains, nothing new enters",
        "pressure <= `exit_at[3]` for `exit_streak` ticks",
    ),
)


def ladder_table_markdown() -> str:
    """Render the brownout ladder table (README "Overload &
    backpressure") from :data:`LADDER_DOCS` — the one place the ladder
    is documented.  The README embeds this output verbatim."""
    rows = [
        ("level", "trigger", "action", "blast radius", "exit condition"),
        ("---", "---", "---", "---", "---"),
    ]
    for level, trigger, action, blast, exit_cond in LADDER_DOCS:
        rows.append((f"**{level}**", trigger, action, blast, exit_cond))
    return "\n".join("| " + " | ".join(r) + " |" for r in rows)


def shed_keep(index: int, admit_fraction: float) -> bool:
    """Whether the ``index``-th admissible record of a batch survives a
    Bresenham stride at ``admit_fraction`` (0.0 = refuse all, 1.0 =
    admit all).  Pure integer-order arithmetic on the within-batch
    index, so replaying the same batch sheds the same records."""
    if admit_fraction >= 1.0:
        return True
    if admit_fraction <= 0.0:
        return False
    return math.floor((index + 1) * admit_fraction) > math.floor(
        index * admit_fraction
    )


class OverloadController:
    """The deterministic ladder state machine.

    The controller never touches the processor: the supervisor gathers
    the signals, calls :meth:`tick` for a proposal, runs the transition
    protocol (failpoints, actuators, pin checkpoint), and then either
    :meth:`commit`\\ s or :meth:`abort`\\ s.  Everything here is plain
    host state that rides the checkpoint header
    (:meth:`to_state`/:meth:`from_state`).
    """

    def __init__(self, policy: Optional[OverloadPolicy] = None):
        self.policy = policy or OverloadPolicy()
        self.level = 0
        self.transitions = 0
        self.transition_failures = 0
        self.shed_total = 0  # records shed while at L3+ (telemetry)
        #: The processor's un-widened drain_interval — ``drain_widen``
        #: multiplies this, and it must be durable: a checkpoint taken
        #: while browned out records the *widened* interval, so a restore
        #: cannot recover the base from the processor.
        self.base_drain = 1
        #: (scale, shares) applied to the admission limiter at the last
        #: L2+ commit — replayed onto the limiter after restore so the
        #: squeeze survives crashes.
        self.admission_pressure: Tuple[float, Dict[str, float]] = (1.0, {})
        self.last_pressure = 0.0
        self._enter_streak = 0
        self._exit_streak = 0
        # In-flight transition: (level, admission_pressure) to restore on
        # abort.  Transient — never serialized (a transition is pinned or
        # it never happened).
        self._prev: Optional[Tuple[int, Tuple[float, Dict[str, float]]]] = (
            None
        )

    # -- pressure -----------------------------------------------------------

    def pressure(self, signals: Dict[str, float]) -> float:
        """Collapse the raw signal dict to the pressure scalar: the max
        of each signal normalized by its policy reference.  Missing
        signals read 0 (a processor without a guard or ledger simply
        contributes no pressure)."""
        p = self.policy

        def norm(key: str, ref: float) -> float:
            v = float(signals.get(key, 0.0) or 0.0)
            return v / ref if ref > 0 else 0.0

        return max(
            norm("burn_rate", p.burn_ref),
            norm("hold_frac", p.hold_ref),
            norm("hold_age_frac", p.hold_age_ref),
            norm("queue_p99_s", p.queue_ref),
            norm("ring_depth", p.ring_ref),
        )

    # -- ladder -------------------------------------------------------------

    def tick(self, signals: Dict[str, float]) -> Optional[Tuple[int, int]]:
        """One observation: update streaks and return a one-step
        transition proposal ``(from_level, to_level)``, or None.  Does
        NOT move the level — the supervisor commits (or reverts) after
        running the transition protocol, so a crash mid-transition
        leaves the previous level authoritative."""
        p = self.policy
        pressure = self.pressure(signals)
        self.last_pressure = pressure
        lvl = self.level
        if lvl < MAX_LEVEL and pressure >= p.enter_at[lvl]:
            self._enter_streak += 1
        else:
            self._enter_streak = 0
        if lvl > 0 and pressure <= p.exit_at[lvl - 1]:
            self._exit_streak += 1
        else:
            self._exit_streak = 0
        if self._enter_streak >= p.enter_streak:
            return (lvl, lvl + 1)
        if self._exit_streak >= p.exit_streak:
            return (lvl, lvl - 1)
        return None

    def begin(self, to_level: int) -> None:
        """Tentatively adopt ``to_level`` so the supervisor's pin
        checkpoint serializes the NEW level (the invariant: the
        in-memory level always equals the last-pinned level).  Must be
        followed by :meth:`commit` (pin succeeded) or :meth:`abort`
        (failpoint or pin failure)."""
        if not 0 <= to_level <= MAX_LEVEL:
            raise ValueError(f"level out of range: {to_level}")
        self._prev = (
            self.level, self.admission_pressure, self._enter_streak,
            self._exit_streak,
        )
        self.level = int(to_level)
        # Streaks reset HERE (not in commit) so the pin checkpoint that
        # runs between begin and commit serializes the post-commit
        # state: a crash right after the pin resumes with the same
        # streaks a crash-free run would carry — the next transition
        # fires on the same tick either way.
        self._enter_streak = 0
        self._exit_streak = 0

    def commit(self) -> None:
        """The transition protocol succeeded (actuators applied, level
        pinned): keep the new level and reset both streaks."""
        frm = self._prev[0] if self._prev is not None else self.level
        logger.info(
            "overload transition L%d -> L%d (pressure %.3f)", frm,
            self.level, self.last_pressure,
        )
        self._prev = None
        self.transitions += 1

    def abort(self) -> None:
        """The transition protocol failed (failpoint or pin-checkpoint
        failure): the previous level stays authoritative.  Streaks are
        restored at threshold, so the next tick re-proposes while the
        pressure condition still holds."""
        if self._prev is not None:
            (
                self.level, self.admission_pressure, self._enter_streak,
                self._exit_streak,
            ) = self._prev
            self._prev = None
        self.transition_failures += 1

    # -- actuator settings --------------------------------------------------

    def drain_widen(self, level: Optional[int] = None) -> int:
        lvl = self.level if level is None else level
        return int(self.policy.drain_widen[lvl])

    def telemetry_defer(self, level: Optional[int] = None) -> bool:
        lvl = self.level if level is None else level
        return lvl >= 1

    def admission_scale(self, level: Optional[int] = None) -> float:
        lvl = self.level if level is None else level
        return float(self.policy.admission_scale[lvl])

    def admit_fraction(self, level: Optional[int] = None) -> Optional[float]:
        """Ingest-door admit fraction, or None when the door is open
        (the processor skips the shed path entirely)."""
        lvl = self.level if level is None else level
        shed = float(self.policy.shed_fraction[lvl])
        return None if shed <= 0.0 else 1.0 - shed

    # -- durability ---------------------------------------------------------

    def to_state(self) -> Dict[str, Any]:
        scale, shares = self.admission_pressure
        return {
            "level": self.level,
            "transitions": self.transitions,
            "transition_failures": self.transition_failures,
            "shed_total": self.shed_total,
            "base_drain": self.base_drain,
            "admission_scale": scale,
            "admission_shares": dict(shares),
            "enter_streak": self._enter_streak,
            "exit_streak": self._exit_streak,
        }

    def load_state(self, state: Dict[str, Any]) -> None:
        self.level = int(state["level"])
        self.transitions = int(state["transitions"])
        self.transition_failures = int(state.get("transition_failures", 0))
        self.shed_total = int(state.get("shed_total", 0))
        self.base_drain = int(state.get("base_drain", 1))
        self.admission_pressure = (
            float(state.get("admission_scale", 1.0)),
            {
                str(k): float(v)
                for k, v in state.get("admission_shares", {}).items()
            },
        )
        self._enter_streak = int(state.get("enter_streak", 0))
        self._exit_streak = int(state.get("exit_streak", 0))

    @classmethod
    def from_state(
        cls, state: Dict[str, Any], policy: Optional[OverloadPolicy] = None
    ) -> "OverloadController":
        ctl = cls(policy)
        ctl.load_state(state)
        return ctl

    # -- telemetry ----------------------------------------------------------

    def metrics(self) -> Dict[str, Any]:
        """Snapshot keys merged into the supervisor's metrics snapshot —
        rendered by utils/telemetry.py as the ``cep_overload_*``
        Prometheus families."""
        return {
            "overload_level": self.level,
            "overload_pressure": round(self.last_pressure, 6),
            "overload_transitions": self.transitions,
            "overload_transition_failures": self.transition_failures,
        }
