"""Multi-query bank: N patterns matched over the same stream.

The reference runs multiple queries by wiring one ``CEPProcessor`` per
pattern into the Kafka Streams topology, all consuming the same topic
(``demo/CEPStockKStreamsDemo.java:55-72`` shows the single-processor
wiring; multiple processors on one source is the documented composition).
The TPU analog keeps that shape: a :class:`CEPBank` owns one
:class:`CEPProcessor` per named query, feeds each the same micro-batch,
and tags emissions with the query name.  Each query's device state is
independent, so a bank's members can also be placed on *different* chips —
the "multi-pattern NFA bank" axis of BASELINE.json config 4, the tensor-
parallel analog from SURVEY §2.2.
"""

from __future__ import annotations

from typing import Dict, Hashable, List, Optional, Sequence as Seq, Tuple

from kafkastreams_cep_tpu.engine.matcher import EngineConfig
from kafkastreams_cep_tpu.runtime.processor import CEPProcessor, Record
from kafkastreams_cep_tpu.utils.events import Sequence
from kafkastreams_cep_tpu.utils.metrics import Metrics
from kafkastreams_cep_tpu.utils.telemetry import merge_counter_dicts

from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime.bank")


class CEPBank:
    """N independent queries over one stream of records.

    ``patterns`` maps query name -> built :class:`Pattern`; every query
    sees every record (same key->lane assignment rules per processor).
    ``process`` returns ``(query_name, key, Sequence)`` triples — per
    query in declaration order, each query's matches in its processor's
    arrival order.
    """

    def __init__(
        self,
        patterns: Dict[str, object],
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        topic: str = "stream",
        epoch: Optional[int] = None,
        trace_sink=None,
    ):
        if not patterns:
            raise ValueError("a bank needs at least one pattern")
        self.processors: Dict[str, CEPProcessor] = {
            name: CEPProcessor(
                pattern, num_lanes, config, topic=topic, epoch=epoch,
                trace_sink=trace_sink, name=name,
            )
            for name, pattern in patterns.items()
        }
        logger.info("bank of %d queries: %s", len(patterns), list(patterns))

    def process(
        self, records: Seq[Record]
    ) -> List[Tuple[str, Hashable, Sequence]]:
        out: List[Tuple[str, Hashable, Sequence]] = []
        for name, proc in self.processors.items():
            out.extend(
                (name, key, seq) for key, seq in proc.process(records)
            )
        return out

    def counters(self) -> Dict[str, Dict[str, int]]:
        return {name: p.counters() for name, p in self.processors.items()}

    def metrics_snapshot(self) -> Dict[str, object]:
        """Bank-wide telemetry: the member registries *merged* (runtime
        counters summed, per-phase latency histograms exactly aggregated —
        the registry ``merge`` is associative, so this equals one registry
        having observed every member's batches), engine drop + hot-tier
        counters summed across members, and the un-merged ``per_pattern``
        breakdown that attributes the totals to individual queries."""
        procs = list(self.processors.values())
        reg = procs[0].metrics.registry
        for p in procs[1:]:
            reg = reg.merge(p.metrics.registry)
        engine = merge_counter_dicts(
            [
                {**p.counters(), **p.hot_counters(), **p.walk_counters()}
                for p in procs
            ]
        )
        snap = Metrics(registry=reg).snapshot(engine)
        # Per-stage attribution merges member-wise by stage-name addition
        # (associative, like every counter merge here); members without
        # attribution contribute nothing.
        per_stage: Dict[str, Dict[str, int]] = {}
        for p in procs:
            for stage, row in p.batch.stage_counters(p.state).items():
                dst = per_stage.setdefault(stage, {})
                for metric, v in row.items():
                    if metric == "selectivity":
                        continue
                    if metric == "conjuncts":
                        # Sub-report keyed by conjunct: evals/accepts add
                        # like every other tally; selectivity re-derives
                        # from the merged totals below.
                        cd = dst.setdefault("conjuncts", {})
                        for key, tallies in v.items():
                            slot = cd.setdefault(
                                key, {"evals": 0, "accepts": 0}
                            )
                            slot["evals"] += tallies["evals"]
                            slot["accepts"] += tallies["accepts"]
                        continue
                    dst[metric] = dst.get(metric, 0) + v
        if per_stage:
            for row in per_stage.values():
                ev = row.get("stage_evals", 0)
                row["selectivity"] = (
                    round(row.get("stage_accepts", 0) / ev, 6) if ev else 0.0
                )
                for slot in row.get("conjuncts", {}).values():
                    slot["selectivity"] = (
                        (slot["accepts"] / slot["evals"])
                        if slot["evals"] else None
                    )
            snap["per_stage"] = per_stage
        snap["per_pattern"] = {
            name: {
                **p.counters(),
                **p.hot_counters(),
                **p.walk_counters(),
                "records_in": p.metrics.records_in,
                "matches_out": p.metrics.matches_out,
            }
            for name, p in self.processors.items()
        }
        return snap
