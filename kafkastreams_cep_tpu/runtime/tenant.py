"""Multi-tenant runtime: N queries, one record stream, one device program.

The serial bank (``runtime/bank.py: CEPBank``) is the reference topology —
one ``CEPProcessor`` per pattern, N dispatches per batch.  This module is
the shared-execution analog over
:class:`~kafkastreams_cep_tpu.parallel.tenantbank.TenantBankMatcher`: one
key→lane routing table, one packed ``[K, T]`` batch, one screened bank
dispatch, and per-query decode with that query's stage names.  Emission
contract per query matches ``CEPProcessor``: by arrival of the completing
record, then run-queue order; queries report in declaration order (the
``CEPBank.process`` contract).

Durability follows ``runtime/checkpoint.py`` exactly: checkpoints carry
arrays + names, never code (the ``ComputationStageSerDe`` contract);
restore recompiles the bank from user patterns and refuses a topology
whose per-query stage names differ.  :class:`TenantSupervisor` adds the
checkpoint-every-N / restore-replay-retry loop of
``runtime/supervisor.py`` scoped to the tenant runtime — replayed
batches' matches are suppressed (already emitted by the pre-fault
incarnation), so a recovered stream is exactly-once.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import os
import pickle
import tempfile
from typing import Any, Dict, Hashable, List, Optional, Sequence as Seq, Tuple

import jax
import numpy as np

from kafkastreams_cep_tpu.engine.matcher import EngineConfig, EventBatch
from kafkastreams_cep_tpu.parallel.tenantbank import (
    TenantBankMatcher,
    TenantState,
)
from kafkastreams_cep_tpu.runtime.checkpoint import (
    CheckpointCorrupt,
    _flatten_state,
    _unflatten_state,
)
from kafkastreams_cep_tpu.runtime.processor import (
    InputRejected,
    Record,
    _bucket,
)
from kafkastreams_cep_tpu.utils.events import Event, Sequence
from kafkastreams_cep_tpu.utils.failpoints import fire as _failpoint
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime.tenant")

TENANT_FORMAT_VERSION = 1

_I32 = np.iinfo(np.int32)


class TenantCEP:
    """N named queries over one stream, one bank dispatch per batch.

    ``patterns`` maps query name -> built pattern (declaration order is
    emission order, like :class:`~kafkastreams_cep_tpu.runtime.bank.
    CEPBank`).  Keys claim lanes first-seen like ``CEPProcessor`` (one
    more key than lanes raises); every query sees every record.  Values
    must share one numeric pytree structure, fixed by the first record.
    """

    def __init__(
        self,
        patterns: Dict[str, object],
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        topic: str = "stream",
        profile: Optional[Dict] = None,
        reorder: bool = True,
    ):
        if not patterns:
            raise ValueError("a tenant bank needs at least one pattern")
        self.query_names = list(patterns)
        self.batch = TenantBankMatcher(
            list(patterns.values()), num_lanes, config,
            profile=profile, reorder=reorder, names=self.query_names,
        )
        self.num_lanes = int(num_lanes)
        self.topic = topic
        self.state: TenantState = self.batch.init_state()
        self._lane_of: Dict[Hashable, int] = {}
        self._key_of: Dict[int, Hashable] = {}
        self._next_offset = np.zeros(self.num_lanes, np.int64)
        self._events: List[Dict[int, Event]] = [
            {} for _ in range(self.num_lanes)
        ]
        self._value_proto: Any = None
        self.batches = 0

    # -- routing --------------------------------------------------------------

    def lane(self, key: Hashable) -> int:
        existing = self._lane_of.get(key)
        if existing is not None:
            return existing
        lane = len(self._lane_of)
        if lane >= self.num_lanes:
            raise InputRejected(
                f"key {key!r}: more than num_lanes={self.num_lanes} "
                "distinct keys; size the tenant runtime for the key "
                "cardinality it serves"
            )
        self._lane_of[key] = lane
        self._key_of[lane] = key
        return lane

    def _key_code(self, key: Hashable, lane: int) -> int:
        if isinstance(key, (int, np.integer)) and _I32.min <= key <= _I32.max:
            return int(key)
        return lane

    # -- the per-batch path ---------------------------------------------------

    def process(
        self, records: Seq[Record]
    ) -> List[Tuple[str, Hashable, Sequence]]:
        """One micro-batch through the whole bank.  Returns
        ``(query_name, key, Sequence)`` triples — queries in declaration
        order, each query's matches in arrival-then-queue order."""
        records = list(records)
        if not records:
            return []
        events, rank_of = self._pack(records)
        _failpoint("device.dispatch")
        self.state, out = self.batch.scan(self.state, events)
        _failpoint("device.result")
        self.batches += 1
        matches: List[Tuple[str, Hashable, Sequence]] = []
        count = np.asarray(jax.device_get(out.count))  # [N, K, T, R]
        stage = np.asarray(jax.device_get(out.stage))
        off = np.asarray(jax.device_get(out.off))
        for q, qname in enumerate(self.query_names):
            names = self.batch.names_of(q)
            ks, ts, rs = np.nonzero(count[q])
            if ks.size == 0:
                continue
            order = np.lexsort((rs, rank_of[ks, ts]))
            ks, ts, rs = ks[order], ts[order], rs[order]
            for i in range(ks.size):
                k = int(ks[i])
                seq = Sequence()
                for w in range(int(count[q, k, ts[i], rs[i]])):
                    seq.add(
                        names[int(stage[q, k, ts[i], rs[i], w])],
                        self._events[k][int(off[q, k, ts[i], rs[i], w])],
                    )
                matches.append((qname, self._key_of[k], seq))
        return matches

    def _pack(self, records: List[Record]):
        """Per-lane queues -> right-padded ``[K, T]`` device batch, plus
        the ``[K, T]`` arrival-rank table the emitter sorts by."""
        per_lane: List[List[Tuple[int, Record]]] = [
            [] for _ in range(self.num_lanes)
        ]
        for rank, rec in enumerate(records):
            if not (_I32.min <= int(rec.timestamp) <= _I32.max):
                raise InputRejected(
                    f"record {rank} (key {rec.key!r}): timestamp "
                    f"{rec.timestamp} outside int32 device time"
                )
            per_lane[self.lane(rec.key)].append((rank, rec))
        if self._value_proto is None:
            self._value_proto = records[0].value
        dtypes, treedef = jax.tree_util.tree_flatten(self._value_proto)
        K = self.num_lanes
        T = _bucket(max(len(q) for q in per_lane))
        key_arr = np.zeros((K, T), np.int32)
        ts_arr = np.zeros((K, T), np.int32)
        off_arr = np.full((K, T), -1, np.int32)
        valid = np.zeros((K, T), bool)
        rank_of = np.full((K, T), np.iinfo(np.int64).max, np.int64)
        leaves = [
            np.zeros(
                (K, T),
                np.float32 if isinstance(p, float) else np.int32,
            )
            for p in dtypes
        ]
        for k, queue in enumerate(per_lane):
            for t, (rank, rec) in enumerate(queue):
                rec_leaves, rec_def = jax.tree_util.tree_flatten(rec.value)
                if rec_def != treedef:
                    raise InputRejected(
                        f"record {rank} (key {rec.key!r}): value structure "
                        f"{rec_def} does not match the stream schema "
                        f"{treedef}"
                    )
                o = int(self._next_offset[k])
                self._next_offset[k] = o + 1
                key_arr[k, t] = self._key_code(rec.key, k)
                ts_arr[k, t] = int(rec.timestamp)
                off_arr[k, t] = o
                valid[k, t] = True
                rank_of[k, t] = rank
                for leaf, v in zip(leaves, rec_leaves):
                    leaf[k, t] = v
                self._events[k][o] = Event(
                    rec.key, rec.value, int(rec.timestamp), self.topic,
                    k, o,
                )
        value = jax.tree_util.tree_unflatten(treedef, leaves)
        return (
            EventBatch(
                key=key_arr, value=value, ts=ts_arr, off=off_arr,
                valid=valid,
            ),
            rank_of,
        )

    # -- telemetry ------------------------------------------------------------

    def counters(self) -> Dict[str, int]:
        return self.batch.counters(self.state)

    def tier_counters(self) -> Dict[str, int]:
        return self.batch.tier_counters(self.state)

    def per_query_counters(self) -> Dict[str, Dict[str, int]]:
        return self.batch.per_query_counters(self.state)

    def metrics_snapshot(self) -> Dict[str, object]:
        return self.batch.metrics_snapshot(self.state)


# ---------------------------------------------------------------------------
# Checkpoint / restore (the changelog-store analog for the whole bank)
# ---------------------------------------------------------------------------


def save_tenant_checkpoint(
    tenant: TenantCEP, path: str, extra: Optional[Dict[str, Any]] = None
) -> None:
    """Snapshot a tenant runtime to one file — arrays + names, no code.

    The array payload is the flattened :class:`TenantState` pytree (per
    residual group engines, per prefix-length group carries); the header
    records every query's stage names so restore can hold the whole bank
    to the lookup-by-name contract at once."""
    _failpoint("checkpoint.save")
    arrays = _flatten_state(tenant.state)
    header = {
        "format_version": TENANT_FORMAT_VERSION,
        "extra": dict(extra or {}),
        "query_names": list(tenant.query_names),
        "stage_names": {
            name: list(tenant.batch.names_of(q))
            for q, name in enumerate(tenant.query_names)
        },
        "config": dataclasses.asdict(tenant.batch.config),
        "num_lanes": tenant.num_lanes,
        "topic": tenant.topic,
        "lane_of": dict(tenant._lane_of),
        "next_offset": tenant._next_offset.copy(),
        "events": [dict(d) for d in tenant._events],
        "value_proto": tenant._value_proto,
        "batches": tenant.batches,
    }
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    header["arrays_sha256"] = hashlib.sha256(buf.getvalue()).hexdigest()
    with open(path, "wb") as f:
        pickle.dump({"header": header, "arrays": buf.getvalue()}, f)
    logger.info(
        "tenant checkpoint saved to %s: %d queries, %d lanes",
        path, len(tenant.query_names), tenant.num_lanes,
    )


def load_tenant_checkpoint(path: str) -> Dict[str, Any]:
    try:
        with open(path, "rb") as f:
            blob = pickle.load(f)
        header = blob["header"]
    except (OSError, FileNotFoundError):
        raise
    except Exception as e:
        raise CheckpointCorrupt(
            f"checkpoint {path} is unreadable ({type(e).__name__}: {e})"
        ) from e
    if header["format_version"] != TENANT_FORMAT_VERSION:
        raise ValueError(
            f"tenant checkpoint format {header['format_version']} "
            "unsupported"
        )
    got = hashlib.sha256(blob["arrays"]).hexdigest()
    if got != header["arrays_sha256"]:
        raise CheckpointCorrupt(
            f"checkpoint {path} failed integrity check: array payload "
            f"sha256 {got} != header digest {header['arrays_sha256']}"
        )
    try:
        with np.load(io.BytesIO(blob["arrays"])) as z:
            arrays = {k: z[k] for k in z.files}
    except Exception as e:
        raise CheckpointCorrupt(
            f"checkpoint {path} array payload is unreadable "
            f"({type(e).__name__}: {e})"
        ) from e
    return {"header": header, "arrays": arrays}


def restore_tenant(
    patterns: Dict[str, object],
    path: str,
    ckpt: Optional[Dict[str, Any]] = None,
) -> TenantCEP:
    """Rebuild a tenant runtime from user code + a checkpoint.

    Patterns are compiled fresh (predicates and folds come from code);
    the checkpoint supplies state only.  A bank whose query names or any
    query's stage names differ from the snapshot is refused."""
    if ckpt is None:
        ckpt = load_tenant_checkpoint(path)
    header = ckpt["header"]
    if list(patterns) != list(header["query_names"]):
        raise ValueError(
            f"query names do not match checkpoint: {list(patterns)} vs "
            f"{header['query_names']}"
        )
    config = EngineConfig(**header["config"])
    tenant = TenantCEP(
        patterns, header["num_lanes"], config, topic=header["topic"]
    )
    for q, name in enumerate(tenant.query_names):
        want = list(header["stage_names"][name])
        got = list(tenant.batch.names_of(q))
        if got != want:
            raise ValueError(
                f"query {name!r} topology does not match checkpoint: "
                f"stages {got} vs checkpoint {want}"
            )
    tenant.state = _unflatten_state(tenant.state, ckpt["arrays"])
    tenant._lane_of = dict(header["lane_of"])
    tenant._key_of = {v: k for k, v in tenant._lane_of.items()}
    tenant._next_offset = np.asarray(header["next_offset"]).copy()
    tenant._events = [dict(d) for d in header["events"]]
    tenant._value_proto = header["value_proto"]
    tenant.batches = int(header["batches"])
    logger.info(
        "restored tenant runtime from %s: %d queries, %d keys assigned",
        path, len(tenant.query_names), len(tenant._lane_of),
    )
    return tenant


# ---------------------------------------------------------------------------
# Supervisor: checkpoint-every-N + restore / replay / retry
# ---------------------------------------------------------------------------


class TenantSupervisor:
    """Auto-recovering wrapper for a tenant runtime.

    Every ``checkpoint_every`` batches the full bank state is snapshot
    (atomic rename — a crash mid-write keeps the previous file).  If a
    batch raises a device fault, the supervisor restores the latest
    snapshot (or a fresh bank before the first one), replays the batches
    journaled since it with their matches *suppressed* (the pre-fault
    incarnation already emitted them — the exactly-once contract), and
    retries the failing batch up to ``max_retries`` times.  Deterministic
    input rejection (:class:`InputRejected`) short-circuits: the batch is
    bad, not the device, and state was untouched."""

    def __init__(
        self,
        patterns: Dict[str, object],
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        checkpoint_path: Optional[str] = None,
        checkpoint_every: int = 16,
        max_retries: int = 1,
        **tenant_kwargs,
    ):
        self._patterns = dict(patterns)
        self._tenant_kwargs = dict(tenant_kwargs)
        self.tenant = TenantCEP(
            patterns, num_lanes, config, **tenant_kwargs
        )
        self.checkpoint_path = checkpoint_path or os.path.join(
            tempfile.gettempdir(),
            f"cep_tenant_{os.getpid()}_{id(self):x}.ckpt",
        )
        self.checkpoint_every = int(checkpoint_every)
        self.max_retries = int(max_retries)
        self._journal: List[List[Record]] = []
        self._has_checkpoint = False
        self.recoveries = 0
        self.checkpoints = 0
        self.checkpoint_failures = 0

    def process(
        self, records: Seq[Record]
    ) -> List[Tuple[str, Hashable, Sequence]]:
        records = list(records)
        last_err: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            try:
                matches = self.tenant.process(records)
                break
            except InputRejected:
                raise
            except Exception as e:  # device fault: recover and retry
                last_err = e
                logger.warning(
                    "batch failed (%s: %s); recovering (attempt %d/%d)",
                    type(e).__name__, e, attempt + 1, self.max_retries,
                )
                self._recover()
        else:
            raise last_err  # retries exhausted
        self._journal.append(records)
        if len(self._journal) >= self.checkpoint_every:
            self.checkpoint()
        return matches

    def checkpoint(self) -> None:
        """Snapshot now (atomic rename) and truncate the journal."""
        tmp = self.checkpoint_path + ".tmp"
        try:
            save_tenant_checkpoint(
                self.tenant, tmp, extra={"batches": self.tenant.batches}
            )
            os.replace(tmp, self.checkpoint_path)
        except Exception as e:
            self.checkpoint_failures += 1
            if os.path.exists(tmp):
                os.remove(tmp)
            logger.warning(
                "checkpoint save failed (%s: %s); journal retained so "
                "recovery replays from the previous snapshot",
                type(e).__name__, e,
            )
            return
        self._has_checkpoint = True
        self.checkpoints += 1
        self._journal = []

    def _recover(self) -> None:
        """Restore the latest good snapshot (or a fresh bank) and replay
        the journaled batches since it, suppressing their matches.

        Replay runs through the same device failure sites as live
        traffic, so recovery itself can fault mid-replay; the recovered
        tenant is only committed once restore + full replay succeed."""
        self.recoveries += 1
        last_err: Optional[BaseException] = None
        for _ in range(32):
            try:
                if self._has_checkpoint:
                    tenant = restore_tenant(
                        self._patterns, self.checkpoint_path
                    )
                else:
                    tenant = TenantCEP(
                        self._patterns, self.tenant.num_lanes,
                        self.tenant.batch.config, **self._tenant_kwargs,
                    )
                for batch in self._journal:
                    # Replay is deterministic; matches were already
                    # emitted by the pre-fault incarnation, so they are
                    # suppressed here (the exactly-once contract).
                    tenant.process(batch)
            except InputRejected:
                raise
            except Exception as e:
                last_err = e
                continue
            self.tenant = tenant
            return
        raise RuntimeError(
            f"tenant recovery failed repeatedly; last error: {last_err}"
        )

    def counters(self) -> Dict[str, int]:
        return self.tenant.counters()

    def metrics_snapshot(self) -> Dict[str, object]:
        out = self.tenant.metrics_snapshot()
        out["recoveries"] = self.recoveries
        out["checkpoints"] = self.checkpoints
        out["checkpoint_failures"] = self.checkpoint_failures
        return out
