"""Multi-tenant runtime: N queries, one record stream, one device program.

The serial bank (``runtime/bank.py: CEPBank``) is the reference topology —
one ``CEPProcessor`` per pattern, N dispatches per batch.  This module is
the shared-execution analog over
:class:`~kafkastreams_cep_tpu.parallel.tenantbank.TenantBankMatcher`: one
key→lane routing table, one packed ``[K, T]`` batch, one screened bank
dispatch, and per-query decode with that query's stage names.  Emission
contract per query matches ``CEPProcessor``: by arrival of the completing
record, then run-queue order; queries report in declaration order (the
``CEPBank.process`` contract).

Durability follows ``runtime/checkpoint.py`` exactly: checkpoints carry
arrays + names, never code (the ``ComputationStageSerDe`` contract);
restore recompiles the bank from user patterns and refuses a topology
whose per-query stage names differ.  :class:`TenantSupervisor` adds the
checkpoint-every-N / restore-replay-retry loop of
``runtime/supervisor.py`` scoped to the tenant runtime — replayed
batches' matches are suppressed (already emitted by the pre-fault
incarnation), so a recovered stream is exactly-once.

Per-tenant isolation (the enforcement stack, outermost first):

* **Admission shedding** — :class:`AdmissionPolicy` puts a per-tenant
  token bucket (``runtime/ingest.py: AdmissionLimiter``) at the front
  door: a flooding tenant's records are shed *before* packing or
  dispatch, dead-lettered under the typed ``tenant_quota`` reason, and
  ledgered so ``offered == admitted + shed + quarantined_dropped``
  reconciles per tenant at any point in the stream.
* **Quota enforcement** — declared :class:`~kafkastreams_cep_tpu.
  compiler.multitenant.TenantQuota` budgets are enforced inside the bank
  (``parallel/tenantbank.py: TenantIsolation``): over-budget tenants'
  prefix fires are masked in the shared screen, counted per tenant in
  ``quota_shed``.
* **Quarantine** — a tenant whose predicate raises, that keeps tripping
  capacity, or that is flagged :class:`TenantMisbehave` is circuit-broken
  out of the bank (columns dark, lanes inert, state frozen for
  :meth:`TenantCEP.reinstate`); the rest of the bank is bit-identical to
  a bank that never contained it.
* **Isolated escalation** — capacity trips are attributed per query;
  :class:`TenantSupervisor` refuses a bank-wide widening whose
  responsible tenant is over its declared share
  (``tenant_escalation_denied``), quarantining repeat offenders instead
  of letting one tenant grow everyone's engine.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import os
import pickle
import tempfile
import time
from typing import (
    Any,
    Callable,
    Dict,
    Hashable,
    List,
    Optional,
    Sequence as Seq,
    Tuple,
)

import jax
import numpy as np

from kafkastreams_cep_tpu.engine.matcher import (
    ArrayStates,
    EngineConfig,
    EventBatch,
)
from kafkastreams_cep_tpu.engine.predmatrix import owner_states
from kafkastreams_cep_tpu.engine.sizing import (
    EscalationPolicy,
    capacity_counters,
    escalate,
)
from kafkastreams_cep_tpu.parallel.tenantbank import (
    TenantBankMatcher,
    TenantState,
)
from kafkastreams_cep_tpu.runtime.checkpoint import (
    CheckpointCorrupt,
    _flatten_state,
    _unflatten_state,
)
from kafkastreams_cep_tpu.runtime.ingest import (
    REASON_TENANT_QUOTA,
    AdmissionLimiter,
    DeadLetter,
)
from kafkastreams_cep_tpu.runtime.migrate import widen_state
from kafkastreams_cep_tpu.runtime.processor import (
    InputRejected,
    Record,
    _bucket,
)
from kafkastreams_cep_tpu.utils.events import Event, Sequence
from kafkastreams_cep_tpu.utils.failpoints import fire as _failpoint
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("runtime.tenant")

TENANT_FORMAT_VERSION = 1

_I32 = np.iinfo(np.int32)


class TenantMisbehave(RuntimeError):
    """A fault attributable to ONE named tenant (query).

    Raised (or injected via the ``tenant.misbehave`` failpoint) when a
    fault can be pinned on a specific tenant; ``query`` carries the
    offender's name so :class:`TenantSupervisor` quarantines exactly that
    tenant and recovers, instead of recovering blind and re-faulting."""

    def __init__(
        self, query: Optional[str] = None, message: Optional[str] = None
    ):
        super().__init__(message or f"tenant {query!r} misbehaving")
        self.query = query


@dataclasses.dataclass(frozen=True)
class AdmissionPolicy:
    """Record-admission rate limiting at the tenant runtime's front door.

    ``rate_per_batch``    — token-bucket refill per processed batch and
                            tenant; a tenant offering more than this
                            sustained is shed before packing/dispatch.
    ``burst``             — bucket capacity (default ``max(1, 2*rate)``);
                            0 sheds a tenant's every record.
    ``key_tenant``        — record key -> tenant id (default ``str(key)``).
                            With one key space per tenant this is also
                            how admission maps to bank queries by name.
    ``shed_quarantined``  — also drop records whose tenant is currently
                            quarantined (``quarantined_dropped`` in the
                            ledger).  Only correct when the key space is
                            partitioned per tenant — a shared key's
                            records feed OTHER tenants' queries too, so
                            the default keeps them flowing and lets the
                            bank's compute masks do the isolation.
    ``dead_letter_cap``   — retained shed records (FIFO), each tagged
                            with the typed ``tenant_quota`` reason.
    """

    rate_per_batch: float
    burst: Optional[float] = None
    key_tenant: Optional[Callable[[Hashable], str]] = None
    shed_quarantined: bool = False
    dead_letter_cap: int = 1024

    def __post_init__(self):
        if self.rate_per_batch < 0:
            raise ValueError(
                f"rate_per_batch must be >= 0, got {self.rate_per_batch}"
            )
        if self.dead_letter_cap < 0:
            raise ValueError("dead_letter_cap must be >= 0")


class TenantAdmission:
    """The admission front door: token buckets + the per-tenant ledger.

    Deterministic host state.  The reconciliation invariant — per tenant,
    ``offered == admitted + shed + quarantined_dropped`` — holds after
    every :meth:`filter`; :meth:`to_state` round-trips through the
    checkpoint header (the *policy* never does — callables come from
    code, exactly like predicates), so the ledger survives crash/restore
    and journal replay reproduces it bit-identically."""

    def __init__(self, policy: AdmissionPolicy):
        self.policy = policy
        self.limiter = AdmissionLimiter(policy.rate_per_batch, policy.burst)
        self.offered: Dict[str, int] = {}
        self.admitted: Dict[str, int] = {}
        self.shed: Dict[str, int] = {}
        self.quarantined_dropped: Dict[str, int] = {}
        self.dead_letters: List[DeadLetter] = []
        self.batch_seq = 0

    def tenant_of(self, key: Hashable) -> str:
        fn = self.policy.key_tenant
        return str(key) if fn is None else str(fn(key))

    def _dead_letter(self, record: Record, detail: str, corr: str) -> None:
        if self.policy.dead_letter_cap <= 0:
            return
        if len(self.dead_letters) >= self.policy.dead_letter_cap:
            self.dead_letters.pop(0)
        self.dead_letters.append(
            DeadLetter(record, REASON_TENANT_QUOTA, detail, corr)
        )

    def filter(
        self, records: Seq[Record], quarantined: frozenset
    ) -> List[Record]:
        """One batch through the front door: returns the admitted
        records in arrival order, ledgering and dead-lettering the rest.
        Refill happens at batch completion (consume-then-refill), so a
        rolled-back batch replays against identical buckets."""
        corr = f"admit-{self.batch_seq}"
        self.batch_seq += 1
        out: List[Record] = []
        for rec in records:
            t = self.tenant_of(rec.key)
            self.offered[t] = self.offered.get(t, 0) + 1
            if self.policy.shed_quarantined and t in quarantined:
                _failpoint("quota.shed")
                self.quarantined_dropped[t] = (
                    self.quarantined_dropped.get(t, 0) + 1
                )
                self._dead_letter(rec, f"tenant {t!r} quarantined", corr)
                continue
            if not self.limiter.admit(t):
                _failpoint("quota.shed")
                self.shed[t] = self.shed.get(t, 0) + 1
                self._dead_letter(
                    rec, f"tenant {t!r} admission bucket empty", corr
                )
                continue
            self.admitted[t] = self.admitted.get(t, 0) + 1
            out.append(rec)
        self.limiter.refill()
        return out

    def ledger(self) -> Dict[str, Dict[str, int]]:
        tenants = sorted(
            set(self.offered)
            | set(self.admitted)
            | set(self.shed)
            | set(self.quarantined_dropped)
        )
        return {
            t: {
                "offered": self.offered.get(t, 0),
                "admitted": self.admitted.get(t, 0),
                "shed": self.shed.get(t, 0),
                "quarantined_dropped": self.quarantined_dropped.get(t, 0),
            }
            for t in tenants
        }

    def to_state(self) -> Dict[str, Any]:
        return {
            "limiter": self.limiter.to_state(),
            "offered": dict(self.offered),
            "admitted": dict(self.admitted),
            "shed": dict(self.shed),
            "quarantined_dropped": dict(self.quarantined_dropped),
            "dead_letters": [tuple(d) for d in self.dead_letters],
            "batch_seq": self.batch_seq,
        }

    def load_state(self, state: Dict[str, Any]) -> None:
        self.limiter = AdmissionLimiter.from_state(state["limiter"])
        self.offered = dict(state["offered"])
        self.admitted = dict(state["admitted"])
        self.shed = dict(state["shed"])
        self.quarantined_dropped = dict(state["quarantined_dropped"])
        self.dead_letters = [DeadLetter(*d) for d in state["dead_letters"]]
        self.batch_seq = int(state["batch_seq"])


class TenantCEP:
    """N named queries over one stream, one bank dispatch per batch.

    ``patterns`` maps query name -> built pattern (declaration order is
    emission order, like :class:`~kafkastreams_cep_tpu.runtime.bank.
    CEPBank`).  Keys claim lanes first-seen like ``CEPProcessor`` (one
    more key than lanes raises); every query sees every record.  Values
    must share one numeric pytree structure, fixed by the first record.

    ``quotas`` (name -> :class:`~kafkastreams_cep_tpu.compiler.
    multitenant.TenantQuota`) declares per-tenant budgets the bank
    enforces; ``admission`` puts an :class:`AdmissionPolicy` token bucket
    ahead of packing.  Both are optional and zero-cost when absent.
    """

    def __init__(
        self,
        patterns: Dict[str, object],
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        topic: str = "stream",
        profile: Optional[Dict] = None,
        reorder: bool = True,
        quotas: Optional[Dict] = None,
        admission: Optional[AdmissionPolicy] = None,
        clock=None,
        latency=None,
    ):
        if not patterns:
            raise ValueError("a tenant bank needs at least one pattern")
        self.query_names = list(patterns)
        self.batch = TenantBankMatcher(
            list(patterns.values()), num_lanes, config,
            profile=profile, reorder=reorder, names=self.query_names,
            quotas=quotas,
        )
        self.num_lanes = int(num_lanes)
        self.topic = topic
        self.admission = (
            TenantAdmission(admission) if admission is not None else None
        )
        self.quarantine_reasons: Dict[str, str] = {}
        self.state: TenantState = self.batch.init_state()
        self._lane_of: Dict[Hashable, int] = {}
        self._key_of: Dict[int, Hashable] = {}
        self._next_offset = np.zeros(self.num_lanes, np.int64)
        self._events: List[Dict[int, Event]] = [
            {} for _ in range(self.num_lanes)
        ]
        self._value_proto: Any = None
        self.batches = 0
        # Injectable clock + latency ledger (utils/latency.py): the tenant
        # path has no reorder buffer, so segments degrade gracefully —
        # reorder_hold is 0, queue is the pack, device is the bank scan +
        # result pull, drain_defer the host emit loop.  Per-query e2e
        # lands in ``observe_query`` (one ``query=`` label per tenant).
        self._clock = clock if clock is not None else time.time
        if latency is True:
            from kafkastreams_cep_tpu.utils.latency import LatencyLedger

            self.ledger = LatencyLedger(clock=self._clock)
        else:
            self.ledger = latency or None
        # Event-time watermark (max packed record timestamp): feeds the
        # same watermark / event-time-lag gauges CEPProcessor surfaces —
        # the tenant wrapper historically omitted them.
        self._watermark: Optional[int] = None

    # -- routing --------------------------------------------------------------

    def lane(self, key: Hashable) -> int:
        existing = self._lane_of.get(key)
        if existing is not None:
            return existing
        lane = len(self._lane_of)
        if lane >= self.num_lanes:
            raise InputRejected(
                f"key {key!r}: more than num_lanes={self.num_lanes} "
                "distinct keys; size the tenant runtime for the key "
                "cardinality it serves"
            )
        self._lane_of[key] = lane
        self._key_of[lane] = key
        return lane

    def _key_code(self, key: Hashable, lane: int) -> int:
        if isinstance(key, (int, np.integer)) and _I32.min <= key <= _I32.max:
            return int(key)
        return lane

    # -- the per-batch path ---------------------------------------------------

    def process(
        self, records: Seq[Record]
    ) -> List[Tuple[str, Hashable, Sequence]]:
        """One micro-batch through the whole bank.  Returns
        ``(query_name, key, Sequence)`` triples — queries in declaration
        order, each query's matches in arrival-then-queue order."""
        _failpoint("tenant.misbehave")
        records = list(records)
        if not records:
            return []
        if self.admission is None:
            return self._process_admitted(records)
        # Admission is atomic per batch: any raise — an injected
        # ``quota.shed``, a trace-time predicate failure inside the scan
        # — rolls the ledger back, so a retried or replayed batch meets
        # identical buckets and the reconciliation invariant never
        # observes a half-counted batch.
        snap = self.admission.to_state()
        try:
            admitted = self.admission.filter(
                records, frozenset(self.quarantined_names())
            )
            if not admitted:
                self.batches += 1
                return []
            return self._process_admitted(admitted)
        except BaseException:
            self.admission.load_state(snap)
            raise

    def _process_admitted(
        self, records: List[Record]
    ) -> List[Tuple[str, Hashable, Sequence]]:
        lat = None
        if self.ledger is not None:
            lat = self.ledger.start_batch(
                f"{self.topic}-{self.batches + 1}", len(records),
            )
        events, rank_of = self._pack(records)
        _failpoint("device.dispatch")
        if lat is not None:
            lat.dispatch = self._clock()
        self.state, out = self.batch.scan(self.state, events)
        _failpoint("device.result")
        self.batches += 1
        matches: List[Tuple[str, Hashable, Sequence]] = []
        count = np.asarray(jax.device_get(out.count))  # [N, K, T, R]
        stage = np.asarray(jax.device_get(out.stage))
        off = np.asarray(jax.device_get(out.off))
        if lat is not None:
            lat.complete = self._clock()  # result pull done = device done
        for q, qname in enumerate(self.query_names):
            names = self.batch.names_of(q)
            ks, ts, rs = np.nonzero(count[q])
            if ks.size == 0:
                continue
            order = np.lexsort((rs, rank_of[ks, ts]))
            ks, ts, rs = ks[order], ts[order], rs[order]
            for i in range(ks.size):
                k = int(ks[i])
                seq = Sequence()
                for w in range(int(count[q, k, ts[i], rs[i]])):
                    seq.add(
                        names[int(stage[q, k, ts[i], rs[i], w])],
                        self._events[k][int(off[q, k, ts[i], rs[i], w])],
                    )
                matches.append((qname, self._key_of[k], seq))
        if lat is not None:
            emit = self._clock()
            self.ledger.commit(lat, emit)
            # Per-query e2e: one observation per emitted match under the
            # query's label (the bank's per-tenant latency attribution).
            e2e = max(emit - lat.release, 0.0)
            for qname, _k, _s in matches:
                self.ledger.observe_query(qname, e2e)
        return matches

    def _pack(self, records: List[Record]):
        """Per-lane queues -> right-padded ``[K, T]`` device batch, plus
        the ``[K, T]`` arrival-rank table the emitter sorts by."""
        per_lane: List[List[Tuple[int, Record]]] = [
            [] for _ in range(self.num_lanes)
        ]
        for rank, rec in enumerate(records):
            if not (_I32.min <= int(rec.timestamp) <= _I32.max):
                raise InputRejected(
                    f"record {rank} (key {rec.key!r}): timestamp "
                    f"{rec.timestamp} outside int32 device time"
                )
            per_lane[self.lane(rec.key)].append((rank, rec))
        if self._value_proto is None:
            self._value_proto = records[0].value
        dtypes, treedef = jax.tree_util.tree_flatten(self._value_proto)
        K = self.num_lanes
        T = _bucket(max(len(q) for q in per_lane))
        key_arr = np.zeros((K, T), np.int32)
        ts_arr = np.zeros((K, T), np.int32)
        off_arr = np.full((K, T), -1, np.int32)
        valid = np.zeros((K, T), bool)
        rank_of = np.full((K, T), np.iinfo(np.int64).max, np.int64)
        leaves = [
            np.zeros(
                (K, T),
                np.float32 if isinstance(p, float) else np.int32,
            )
            for p in dtypes
        ]
        for k, queue in enumerate(per_lane):
            for t, (rank, rec) in enumerate(queue):
                rec_leaves, rec_def = jax.tree_util.tree_flatten(rec.value)
                if rec_def != treedef:
                    raise InputRejected(
                        f"record {rank} (key {rec.key!r}): value structure "
                        f"{rec_def} does not match the stream schema "
                        f"{treedef}"
                    )
                o = int(self._next_offset[k])
                self._next_offset[k] = o + 1
                key_arr[k, t] = self._key_code(rec.key, k)
                ts_arr[k, t] = int(rec.timestamp)
                if self._watermark is None or rec.timestamp > self._watermark:
                    self._watermark = int(rec.timestamp)
                off_arr[k, t] = o
                valid[k, t] = True
                rank_of[k, t] = rank
                for leaf, v in zip(leaves, rec_leaves):
                    leaf[k, t] = v
                self._events[k][o] = Event(
                    rec.key, rec.value, int(rec.timestamp), self.topic,
                    k, o,
                )
        value = jax.tree_util.tree_unflatten(treedef, leaves)
        return (
            EventBatch(
                key=key_arr, value=value, ts=ts_arr, off=off_arr,
                valid=valid,
            ),
            rank_of,
        )

    # -- quarantine / poison probing ------------------------------------------

    def _qid(self, name: str) -> int:
        try:
            return self.query_names.index(name)
        except ValueError:
            raise KeyError(f"no query named {name!r}") from None

    def quarantine(self, name: str, reason: str = "manual") -> None:
        """Circuit-break query ``name`` out of the bank (see
        :meth:`~kafkastreams_cep_tpu.parallel.tenantbank.
        TenantBankMatcher.quarantine`); ``reason`` is recorded for the
        checkpoint header and telemetry."""
        self.batch.quarantine(self._qid(name))
        self.quarantine_reasons[name] = str(reason)

    def reinstate(self, name: str) -> None:
        """Lift ``name``'s quarantine; its frozen state resumes."""
        self.batch.reinstate(self._qid(name))
        self.quarantine_reasons.pop(name, None)

    def quarantined_names(self) -> List[str]:
        return [
            self.query_names[q] for q in self.batch.quarantined_qids
        ]

    def find_poison(self) -> List[str]:
        """Host-probe every live screen column's predicate on a tiny
        synthetic batch; return the names of queries referencing a
        raising column.

        This attributes trace-time predicate failures (the way a
        poisoned tenant predicate actually surfaces — the scan raises
        before any state moves) to tenants, so the supervisor can
        quarantine the offender instead of retrying into the same raise
        forever.  Columns already dark under quarantine are skipped; a
        runtime that has not seen a record yet cannot probe (no value
        schema) and reports nothing."""
        if self._value_proto is None:
            return []
        leaves, treedef = jax.tree_util.tree_flatten(self._value_proto)
        value = jax.tree_util.tree_unflatten(
            treedef,
            [
                np.zeros(
                    (1, 1),
                    np.float32 if isinstance(p, float) else np.int32,
                )
                for p in leaves
            ],
        )
        key = np.zeros((1, 1), np.int32)
        ts = np.zeros((1, 1), np.int32)
        bad: set = set()
        tables = [qp.tables for qp in self.batch.bank.queries]
        for ci, col in enumerate(self.batch.bank.columns):
            if ci in self.batch._disabled_cols:
                continue
            env = (
                ArrayStates({})
                if col.shared
                else owner_states(tables[col.owner])
            )
            try:
                np.asarray(col.pred(key, value, ts, env))
            except Exception:
                bad |= self.batch._col_users.get(ci, set())
        return sorted(self.query_names[q] for q in bad)

    # -- telemetry ------------------------------------------------------------

    def counters(self) -> Dict[str, int]:
        return self.batch.counters(self.state)

    def tier_counters(self) -> Dict[str, int]:
        return self.batch.tier_counters(self.state)

    def per_query_counters(self) -> Dict[str, Dict[str, int]]:
        return self.batch.per_query_counters(self.state)

    def admission_ledger(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant ``offered/admitted/shed/quarantined_dropped``
        (empty without an :class:`AdmissionPolicy`)."""
        return {} if self.admission is None else self.admission.ledger()

    def metrics_snapshot(self) -> Dict[str, object]:
        out = self.batch.metrics_snapshot(self.state)
        # Watermark / event-time-lag gauges — the same ``records-lag``
        # analog CEPProcessor surfaces, through the same injectable clock
        # (the tenant and meshed wrappers historically omitted it).
        out["watermark"] = self._watermark
        out["event_time_lag_ms"] = (
            int(self._clock() * 1000) - self._watermark
            if self._watermark is not None
            else None
        )
        if self.ledger is not None:
            out["latency"] = self.ledger.snapshot()
        if self.admission is not None:
            ledger = self.admission.ledger()
            for name in ("offered", "admitted", "shed",
                         "quarantined_dropped"):
                out[f"admission_{name}_total"] = sum(
                    row[name] for row in ledger.values()
                )
            # Rendered as ``dead_letters_total{reason=...}`` by
            # utils/telemetry.py — same contract as the ingest guard's.
            reasons: Dict[str, int] = {}
            for d in self.admission.dead_letters:
                reasons[d.reason] = reasons.get(d.reason, 0) + 1
            out["dead_letters"] = reasons
            out["dead_letter_depth"] = len(self.admission.dead_letters)
        return out


# ---------------------------------------------------------------------------
# Checkpoint / restore (the changelog-store analog for the whole bank)
# ---------------------------------------------------------------------------


def save_tenant_checkpoint(
    tenant: TenantCEP, path: str, extra: Optional[Dict[str, Any]] = None
) -> None:
    """Snapshot a tenant runtime to one file — arrays + names, no code.

    The array payload is the flattened :class:`TenantState` pytree (per
    residual group engines, per prefix-length group carries); the header
    records every query's stage names so restore can hold the whole bank
    to the lookup-by-name contract at once."""
    _failpoint("checkpoint.save")
    arrays = _flatten_state(tenant.state)
    header = {
        "format_version": TENANT_FORMAT_VERSION,
        "extra": dict(extra or {}),
        "query_names": list(tenant.query_names),
        "stage_names": {
            name: list(tenant.batch.names_of(q))
            for q, name in enumerate(tenant.query_names)
        },
        "config": dataclasses.asdict(tenant.batch.config),
        "num_lanes": tenant.num_lanes,
        "topic": tenant.topic,
        "lane_of": dict(tenant._lane_of),
        "next_offset": tenant._next_offset.copy(),
        "events": [dict(d) for d in tenant._events],
        "value_proto": tenant._value_proto,
        "batches": tenant.batches,
        # Isolation bookkeeping (additive — readers default when absent,
        # so the format version stays 1).  The admission POLICY is never
        # pickled: callables come from code, like predicates; only the
        # deterministic ledger/bucket state rides along.
        "isolation": tenant.batch.iso_state(),
        "quarantine_reasons": dict(tenant.quarantine_reasons),
        # Watermark + latency-ledger state (additive — readers default
        # when absent): same durability discipline as the processor path.
        "watermark": tenant._watermark,
        "latency": (
            tenant.ledger.to_state() if tenant.ledger is not None else None
        ),
        "admission": (
            tenant.admission.to_state()
            if tenant.admission is not None
            else None
        ),
    }
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    header["arrays_sha256"] = hashlib.sha256(buf.getvalue()).hexdigest()
    with open(path, "wb") as f:
        pickle.dump({"header": header, "arrays": buf.getvalue()}, f)
    logger.info(
        "tenant checkpoint saved to %s: %d queries, %d lanes",
        path, len(tenant.query_names), tenant.num_lanes,
    )


def load_tenant_checkpoint(path: str) -> Dict[str, Any]:
    try:
        with open(path, "rb") as f:
            blob = pickle.load(f)
        header = blob["header"]
    except (OSError, FileNotFoundError):
        raise
    except Exception as e:
        raise CheckpointCorrupt(
            f"checkpoint {path} is unreadable ({type(e).__name__}: {e})"
        ) from e
    if header["format_version"] != TENANT_FORMAT_VERSION:
        raise ValueError(
            f"tenant checkpoint format {header['format_version']} "
            "unsupported"
        )
    got = hashlib.sha256(blob["arrays"]).hexdigest()
    if got != header["arrays_sha256"]:
        raise CheckpointCorrupt(
            f"checkpoint {path} failed integrity check: array payload "
            f"sha256 {got} != header digest {header['arrays_sha256']}"
        )
    try:
        with np.load(io.BytesIO(blob["arrays"])) as z:
            arrays = {k: z[k] for k in z.files}
    except Exception as e:
        raise CheckpointCorrupt(
            f"checkpoint {path} array payload is unreadable "
            f"({type(e).__name__}: {e})"
        ) from e
    return {"header": header, "arrays": arrays}


def restore_tenant(
    patterns: Dict[str, object],
    path: str,
    ckpt: Optional[Dict[str, Any]] = None,
    **tenant_kwargs,
) -> TenantCEP:
    """Rebuild a tenant runtime from user code + a checkpoint.

    Patterns are compiled fresh (predicates and folds come from code);
    the checkpoint supplies state only.  A bank whose query names or any
    query's stage names differ from the snapshot is refused.
    ``tenant_kwargs`` (quotas, admission policy, ...) are the code-side
    configuration and forward to :class:`TenantCEP` — the snapshot's
    isolation ledger and admission state are applied on top."""
    if ckpt is None:
        ckpt = load_tenant_checkpoint(path)
    header = ckpt["header"]
    if list(patterns) != list(header["query_names"]):
        raise ValueError(
            f"query names do not match checkpoint: {list(patterns)} vs "
            f"{header['query_names']}"
        )
    config = EngineConfig(**header["config"])
    kwargs = dict(tenant_kwargs)
    kwargs.setdefault("topic", header["topic"])
    tenant = TenantCEP(patterns, header["num_lanes"], config, **kwargs)
    for q, name in enumerate(tenant.query_names):
        want = list(header["stage_names"][name])
        got = list(tenant.batch.names_of(q))
        if got != want:
            raise ValueError(
                f"query {name!r} topology does not match checkpoint: "
                f"stages {got} vs checkpoint {want}"
            )
    tenant.state = _unflatten_state(tenant.state, ckpt["arrays"])
    tenant._lane_of = dict(header["lane_of"])
    tenant._key_of = {v: k for k, v in tenant._lane_of.items()}
    tenant._next_offset = np.asarray(header["next_offset"]).copy()
    tenant._events = [dict(d) for d in header["events"]]
    tenant._value_proto = header["value_proto"]
    tenant.batches = int(header["batches"])
    tenant._watermark = header.get("watermark")
    if header.get("latency") is not None:
        from kafkastreams_cep_tpu.utils.latency import LatencyLedger

        # Clock stays as constructed (clocks are wiring, not state).
        tenant.ledger = LatencyLedger.from_state(
            header["latency"], clock=tenant._clock
        )
    iso = header.get("isolation")
    if iso is not None:
        tenant.batch.load_iso_state(iso)
    tenant.quarantine_reasons = dict(header.get("quarantine_reasons", {}))
    adm = header.get("admission")
    if adm is not None and tenant.admission is not None:
        tenant.admission.load_state(adm)
    logger.info(
        "restored tenant runtime from %s: %d queries, %d keys assigned",
        path, len(tenant.query_names), len(tenant._lane_of),
    )
    return tenant


# ---------------------------------------------------------------------------
# Supervisor: checkpoint-every-N + restore / replay / retry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class QuarantinePolicy:
    """When repeated per-tenant misbehavior hardens into quarantine.

    ``trip_streak`` — consecutive denied escalations (capacity trips by
    a tenant over its declared quota) before that tenant is quarantined
    outright; the streak resets whenever the tenant trips nothing."""

    trip_streak: int = 3

    def __post_init__(self):
        if self.trip_streak < 1:
            raise ValueError("trip_streak must be >= 1")


class TenantSupervisor:
    """Auto-recovering wrapper for a tenant runtime.

    Every ``checkpoint_every`` batches the full bank state is snapshot
    (atomic rename — a crash mid-write keeps the previous file).  If a
    batch raises a device fault, the supervisor restores the latest
    snapshot (or a fresh bank before the first one), replays the batches
    journaled since it with their matches *suppressed* (the pre-fault
    incarnation already emitted them — the exactly-once contract), and
    retries the failing batch up to ``max_retries`` times.  Deterministic
    input rejection (:class:`InputRejected`) short-circuits: the batch is
    bad, not the device, and state was untouched.

    Blast-radius containment: a :class:`TenantMisbehave` fault
    quarantines the named tenant before recovery; any other fault is
    first probed with :meth:`TenantCEP.find_poison` so a raising tenant
    predicate quarantines its owner instead of re-faulting every retry.
    Quarantine decisions live supervisor-side (``quarantines``) and are
    re-applied after every restore, so a decision made after the last
    snapshot survives recovery.  Retries and recovery attempts back off
    exponentially with deterministic jitter — the same discipline (and
    counter, ``retry_backoff_ms_total``) as ``runtime/supervisor.py:
    Supervisor._backoff``; ``retry_backoff_ms=0`` restores the
    historical immediate retry.

    Isolated escalation: with ``auto_escalate`` set, capacity trips are
    attributed per query via counter deltas; a bank-wide widening whose
    every responsible tenant is within quota proceeds (state migrated
    live via ``runtime/migrate.py: widen_state``, then pinned with an
    immediate checkpoint), while a trip driven by an over-quota tenant
    is refused (``tenant_escalation_denied``) and, after
    ``quarantine_policy.trip_streak`` consecutive denials, the offender
    is quarantined — one tenant cannot grow everyone's engine."""

    def __init__(
        self,
        patterns: Dict[str, object],
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        checkpoint_path: Optional[str] = None,
        checkpoint_every: int = 16,
        max_retries: int = 1,
        retry_backoff_ms: float = 50.0,
        retry_backoff_cap_ms: float = 5000.0,
        auto_escalate: Optional[EscalationPolicy] = None,
        quarantine_policy: QuarantinePolicy = QuarantinePolicy(),
        **tenant_kwargs,
    ):
        self._patterns = dict(patterns)
        self._tenant_kwargs = dict(tenant_kwargs)
        self.tenant = TenantCEP(
            patterns, num_lanes, config, **tenant_kwargs
        )
        self.checkpoint_path = checkpoint_path or os.path.join(
            tempfile.gettempdir(),
            f"cep_tenant_{os.getpid()}_{id(self):x}.ckpt",
        )
        self.checkpoint_every = int(checkpoint_every)
        self.max_retries = int(max_retries)
        self.retry_backoff_ms = float(retry_backoff_ms)
        self.retry_backoff_cap_ms = float(retry_backoff_cap_ms)
        self.retry_backoff_ms_total = 0.0
        self._sleep = time.sleep  # tests patch this
        self.auto_escalate = auto_escalate
        self.quarantine_policy = quarantine_policy
        self.quarantines: Dict[str, str] = {}
        self._denial_streak: Dict[str, int] = {}
        self._pq_base: Optional[Dict[str, Dict[str, int]]] = None
        self._journal: List[List[Record]] = []
        self._has_checkpoint = False
        self.recoveries = 0
        self.checkpoints = 0
        self.checkpoint_failures = 0
        self.escalations = 0
        self.tenant_escalation_denied = 0
        self.tenant_quarantines = 0

    def process(
        self, records: Seq[Record]
    ) -> List[Tuple[str, Hashable, Sequence]]:
        records = list(records)
        last_err: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            try:
                matches = self.tenant.process(records)
                break
            except InputRejected:
                raise
            except TenantMisbehave as e:
                # Attributed fault: isolate exactly the offender, then
                # recover — the rest of the bank keeps its state.
                last_err = e
                logger.warning(
                    "tenant misbehaving (%s); quarantining and "
                    "recovering (attempt %d/%d)",
                    e, attempt + 1, self.max_retries,
                )
                self._quarantine_for(e.query, "misbehave")
                if attempt < self.max_retries:
                    self._backoff(attempt)
                self._recover()
            except Exception as e:  # device fault: recover and retry
                last_err = e
                logger.warning(
                    "batch failed (%s: %s); recovering (attempt %d/%d)",
                    type(e).__name__, e, attempt + 1, self.max_retries,
                )
                # A raising tenant predicate would re-fault every retry:
                # probe and quarantine the owner before recovering.
                try:
                    poisoned = self.tenant.find_poison()
                except Exception:
                    poisoned = []
                for name in poisoned:
                    self._quarantine_for(name, "predicate_raise")
                if attempt < self.max_retries:
                    self._backoff(attempt)
                self._recover()
        else:
            raise last_err  # retries exhausted
        self._journal.append(records)
        self._maybe_escalate()
        if len(self._journal) >= self.checkpoint_every:
            self.checkpoint()
        return matches

    def _backoff(self, attempt: int) -> None:
        """Exponential-in-attempt, capped, deterministically jittered —
        ``(batches + 1, attempt)`` seeds the jitter so a replayed chaos
        schedule waits identically (the ``runtime/supervisor.py``
        retry-backoff discipline, scoped to the tenant runtime)."""
        if self.retry_backoff_ms <= 0:
            return
        delay_ms = min(
            self.retry_backoff_cap_ms,
            self.retry_backoff_ms * (2.0 ** attempt),
        )
        rng = np.random.default_rng((self.tenant.batches + 1, attempt))
        delay_ms *= 0.5 + 0.5 * float(rng.random())  # jitter in [0.5, 1.0)
        self.retry_backoff_ms_total += delay_ms
        logger.info(
            "retry backoff: %.1f ms before attempt %d",
            delay_ms, attempt + 2,
        )
        self._sleep(delay_ms / 1000.0)

    # -- quarantine bookkeeping ----------------------------------------------

    def _quarantine_for(self, name: Optional[str], reason: str) -> None:
        """Record a quarantine decision (supervisor-side authoritative —
        re-applied after every restore) and apply it to the live bank.
        An unattributed fault (no tenant name) isolates nothing."""
        if name is None or name not in self._patterns:
            return
        if name in self.quarantines:
            return
        self.quarantines[name] = str(reason)
        self.tenant_quarantines += 1
        try:
            self.tenant.quarantine(name, reason)
        except Exception as e:
            # quarantine.enter contract: a fault here leaves the bank
            # un-quarantined and live; the recorded decision re-applies
            # on the next recovery.
            logger.warning(
                "quarantine of %r deferred (%s: %s); re-applied on "
                "recovery", name, type(e).__name__, e,
            )

    def reinstate(self, name: str) -> None:
        """Lift a quarantine: clears the supervisor-side decision (so
        recovery stops re-applying it) and the bank's enforcement."""
        self.quarantines.pop(name, None)
        self._denial_streak.pop(name, None)
        self.tenant.reinstate(name)

    # -- isolated escalation ---------------------------------------------------

    def _maybe_escalate(self) -> None:
        """Per-tenant-attributed auto-widening after a clean batch.

        Capacity-counter deltas since the last check attribute each trip
        to its query; if every tripping tenant is within its declared
        quota, the whole bank widens (the shared-engine reality: knobs
        are bank-wide) — otherwise the widening is DENIED and charged to
        the over-quota tenants, quarantining streak offenders."""
        if self.auto_escalate is None:
            return
        pq = self.tenant.per_query_counters()
        base = self._pq_base or {}
        self._pq_base = pq
        tripping: Dict[str, Dict[str, int]] = {}
        for name, counters in pq.items():
            prev = base.get(name, {})
            deltas = {
                c: v - prev.get(c, 0)
                for c, v in capacity_counters(counters).items()
                if v - prev.get(c, 0) > 0
            }
            if deltas:
                tripping[name] = deltas
        if not tripping:
            for name in list(self._denial_streak):
                self._denial_streak.pop(name)
            return
        iso = self.tenant.batch.iso
        over = [
            name
            for name in tripping
            if iso.over[self.tenant._qid(name)]
        ]
        for name in list(self._denial_streak):
            if name not in over:
                self._denial_streak.pop(name)
        if over:
            self.tenant_escalation_denied += 1
            logger.warning(
                "escalation denied: capacity trips %s attributed to "
                "over-quota tenants %s",
                {n: d for n, d in tripping.items()}, over,
            )
            for name in over:
                streak = self._denial_streak.get(name, 0) + 1
                self._denial_streak[name] = streak
                if streak >= self.quarantine_policy.trip_streak:
                    self._quarantine_for(name, "capacity")
            return
        merged: Dict[str, int] = {}
        for deltas in tripping.values():
            for c, v in deltas.items():
                merged[c] = merged.get(c, 0) + v
        new_cfg = escalate(
            self.tenant.batch.config, merged, self.auto_escalate
        )
        if new_cfg is None:
            return  # every tripped dimension at its ceiling
        logger.warning(
            "escalating bank config for compliant trips %s", merged
        )
        self._widen(new_cfg)
        self.escalations += 1

    def _widen(self, new_cfg: EngineConfig) -> None:
        """Live-migrate the whole bank into ``new_cfg`` shapes
        (``widen_state`` — counters and live runs survive bit-for-bit)
        and pin the widened incarnation with an immediate checkpoint so
        recovery never narrows back (forward-only)."""
        old = self.tenant
        new = TenantCEP(
            self._patterns, old.num_lanes, new_cfg,
            **self._tenant_kwargs,
        )
        new.state = widen_state(old.state, old.batch.config, new_cfg)
        new._lane_of = dict(old._lane_of)
        new._key_of = dict(old._key_of)
        new._next_offset = old._next_offset.copy()
        new._events = [dict(d) for d in old._events]
        new._value_proto = old._value_proto
        new.batches = old.batches
        new.batch.load_iso_state(old.batch.iso_state())
        new.quarantine_reasons = dict(old.quarantine_reasons)
        if new.admission is not None and old.admission is not None:
            new.admission.load_state(old.admission.to_state())
        self.tenant = new
        self.checkpoint()

    def checkpoint(self) -> None:
        """Snapshot now (atomic rename) and truncate the journal."""
        tmp = self.checkpoint_path + ".tmp"
        try:
            save_tenant_checkpoint(
                self.tenant, tmp, extra={"batches": self.tenant.batches}
            )
            os.replace(tmp, self.checkpoint_path)
        except Exception as e:
            self.checkpoint_failures += 1
            if os.path.exists(tmp):
                os.remove(tmp)
            logger.warning(
                "checkpoint save failed (%s: %s); journal retained so "
                "recovery replays from the previous snapshot",
                type(e).__name__, e,
            )
            return
        self._has_checkpoint = True
        self.checkpoints += 1
        self._journal = []

    def _recover(self) -> None:
        """Restore the latest good snapshot (or a fresh bank) and replay
        the journaled batches since it, suppressing their matches.

        Replay runs through the same device failure sites as live
        traffic, so recovery itself can fault mid-replay; the recovered
        tenant is only committed once restore + full replay succeed.
        Failed attempts back off with the same deterministic exponential
        schedule as batch retries (``runtime/supervisor.py`` discipline
        — the historical immediate-retry loop hammered a faulting device
        32 times back-to-back).  Supervisor-side quarantine decisions
        are re-applied before replay, so a tenant quarantined after the
        last snapshot stays isolated through recovery — and its replay
        traffic is masked exactly as live traffic was."""
        self.recoveries += 1
        last_err: Optional[BaseException] = None
        for attempt in range(32):
            if attempt:
                self._backoff(attempt - 1)
            try:
                if self._has_checkpoint:
                    tenant = restore_tenant(
                        self._patterns, self.checkpoint_path,
                        **self._tenant_kwargs,
                    )
                else:
                    tenant = TenantCEP(
                        self._patterns, self.tenant.num_lanes,
                        self.tenant.batch.config, **self._tenant_kwargs,
                    )
                for name, reason in self.quarantines.items():
                    tenant.quarantine(name, reason)
                for batch in self._journal:
                    # Replay is deterministic; matches were already
                    # emitted by the pre-fault incarnation, so they are
                    # suppressed here (the exactly-once contract).
                    tenant.process(batch)
            except InputRejected:
                raise
            except Exception as e:
                last_err = e
                continue
            self.tenant = tenant
            return
        raise RuntimeError(
            f"tenant recovery failed repeatedly; last error: {last_err}"
        )

    def counters(self) -> Dict[str, int]:
        return self.tenant.counters()

    def per_query_counters(self) -> Dict[str, Dict[str, int]]:
        return self.tenant.per_query_counters()

    def admission_ledger(self) -> Dict[str, Dict[str, int]]:
        return self.tenant.admission_ledger()

    def metrics_snapshot(self) -> Dict[str, object]:
        out = self.tenant.metrics_snapshot()
        out["recoveries"] = self.recoveries
        out["checkpoints"] = self.checkpoints
        out["checkpoint_failures"] = self.checkpoint_failures
        out["escalations"] = self.escalations
        out["tenant_escalation_denied"] = self.tenant_escalation_denied
        out["tenant_quarantines"] = self.tenant_quarantines
        out["retry_backoff_ms_total"] = round(
            self.retry_backoff_ms_total, 3
        )
        return out
