"""kafkastreams_cep_tpu — a TPU-native Complex Event Processing framework.

A ground-up re-design of the capabilities of ``vaquarkhan/kafkastreams-cep``
(the SASE+ NFA pattern-matching library for Kafka Streams) for TPU hardware:

* a fluent pattern DSL (``Query``) mirroring the reference QueryBuilder
  (reference: ``pattern/QueryBuilder.java``),
* a pattern -> NFA compiler producing both a host stage graph and dense
  transition tables (reference: ``pattern/StatesFactory.java``),
* a faithful host *oracle* engine used for conformance
  (reference: ``nfa/NFA.java``),
* a batched JAX/XLA array engine (``engine.TPUMatcher``) that steps the NFA
  over fixed-shape run/buffer state under ``jit``, vmapping over key lanes,
  differentially tested against the oracle (``tests/test_engine_*.py``),
* a vectorized-over-time stencil fast path for strict sequences
  (``engine.StencilMatcher``),
* single-chip key batching and multi-chip mesh sharding
  (``parallel.BatchMatcher`` / ``parallel.ShardedMatcher``),
* a host runtime with micro-batching, checkpoint/restore, multi-query
  banks, and the stock demo (``runtime.CEPProcessor``, ``runtime.CEPBank``,
  ``runtime/checkpoint.py``, ``examples/stock_demo.py``;
  reference: ``CEPProcessor.java``),
* failure detection & recovery: health probes, auto-restore with
  deterministic replay, and a durable CRC-framed record journal with
  process-crash resume (``runtime.supervisor``, ``native/journal.py``,
  ``examples/resilient_pipeline.py``),
* native C++ host kernels behind ctypes with NumPy fallbacks — columnar
  lane packing, JSON-lines parsing, journal IO (``native/``),
* a benchmark harness (``bench.py``) covering the BASELINE.json configs
  and driver entries (``__graft_entry__.py``).
"""

from kafkastreams_cep_tpu.utils.events import Event, Sequence
from kafkastreams_cep_tpu.nfa.dewey import DeweyVersion
from kafkastreams_cep_tpu.pattern.query import Query, QueryBuilder
from kafkastreams_cep_tpu.pattern.pattern import Pattern, Cardinality, SelectStrategy
from kafkastreams_cep_tpu.pattern.predicate import Matcher, and_, or_, not_
from kafkastreams_cep_tpu.compiler.stages import (
    Stage,
    StageType,
    EdgeOperation,
    compile_pattern,
)
from kafkastreams_cep_tpu.nfa.oracle import OracleNFA
from kafkastreams_cep_tpu.engine.matcher import (
    EngineConfig,
    MatcherSession,
    TPUMatcher,
)
from kafkastreams_cep_tpu.engine.stencil import StencilMatcher
from kafkastreams_cep_tpu.parallel import BatchMatcher, ShardedMatcher, key_mesh
from kafkastreams_cep_tpu.runtime import (
    CEPProcessor,
    InputRejected,
    Record,
    restore_processor,
    save_checkpoint,
)
from kafkastreams_cep_tpu.utils.logging import configure_logging
from kafkastreams_cep_tpu.utils.telemetry import (
    InMemoryTraceSink,
    JsonlTraceSink,
    MetricsRegistry,
    Reporter,
    render_prometheus,
)

__version__ = "0.2.0"

__all__ = [
    "Event",
    "Sequence",
    "DeweyVersion",
    "Query",
    "QueryBuilder",
    "Pattern",
    "Cardinality",
    "SelectStrategy",
    "Matcher",
    "and_",
    "or_",
    "not_",
    "Stage",
    "StageType",
    "EdgeOperation",
    "compile_pattern",
    "OracleNFA",
    "EngineConfig",
    "MatcherSession",
    "TPUMatcher",
    "StencilMatcher",
    "BatchMatcher",
    "ShardedMatcher",
    "key_mesh",
    "CEPProcessor",
    "InputRejected",
    "Record",
    "save_checkpoint",
    "restore_processor",
    "configure_logging",
    "InMemoryTraceSink",
    "JsonlTraceSink",
    "MetricsRegistry",
    "Reporter",
    "render_prometheus",
]
