"""Tiered single-chip matcher: stencil prefix tier + NFA suffix tier.

Drop-in for :class:`~kafkastreams_cep_tpu.parallel.batch.BatchMatcher`
(same scan/sweep/drain/counters surface, ``CEPProcessor`` selects it when
``EngineConfig.tiering`` is set) that executes the compiler tiering plan
(``compiler/tiering.py``):

* ``nfa``     — no usable prefix: pure delegation to the inner
  :class:`BatchMatcher`, state still wrapped in :class:`TieredState` so
  every config compiles to one state shape.
* ``stencil`` — the whole pattern is a strict sequence: the prefix tier
  IS the matcher; completions are rendered as the engine's ``StepOutput``
  grid (``engine/tiered.py: stencil_step_output``) and the NFA engine is
  never dispatched (its ``step_seq`` still ticks, keeping drain/handle
  ordering invariants intact).
* ``hybrid``  — the stencil screens the whole ``[K, T]`` batch first
  (fully parallel over keys *and* time), then the NFA tier scans the
  batch with a promotion step fused after every engine step
  (``engine/tiered.py: build_promote``).  When the stencil reports no
  completions **and** no suffix run is alive anywhere, the NFA dispatch
  is skipped outright — on screened (production-monitoring-shaped)
  traffic most batches never pay a single NFA step.  The skip is exact:
  a stepped empty queue changes nothing but ``step_seq``, which the skip
  path advances by ``T`` in one op.

Gating is *chunk-level and fully on device*: the ``[K, T]`` batch is
segmented into ``EngineConfig.gate_chunk``-sized chunks and each chunk's
NFA work runs under a ``lax.cond`` — a chunk with no live suffix run and
no prefix completion advances ``step_seq`` in one op and emits a zero
output block.  The scan issues **zero per-scan host syncs**: dispatch
accounting accumulates on device and reaches the host only at telemetry
reads (:attr:`TieredBatchMatcher.nfa_dispatches`), so pipelined
processors keep full dispatch/decode overlap under tiering (the old
design paid one scalar ``device_get`` per scan to decide the skip on
host).  The skip is exact for any ``gate_chunk``: promotion happens
*after* the completing step — exactly the untiered schedule — so a
completion in chunk ``i`` has its first observable NFA effect inside
chunk ``i`` itself, which the gate (``any(alive) | any(fire)`` over the
chunk) never skips.

Parity: matches, emission order, and loss counters are bit-identical to
the untiered engine on loss-free workloads across the jnp and Pallas
walk-kernel paths (tests/test_tiering.py).  Under ``CEP_SCAN_KERNEL``
the hybrid tier runs a *native tiered whole-scan program*
(``ops/scan_kernel.py: build_scan(..., promotion=p)``): the stencil
feed's per-step promotion inputs join the event stream, and the
promotion's slab writes + run-queue append run as a fused phase after
the engine phases, gated per step on device — no per-step fallback.  A
pattern that cannot lower to Mosaic falls back permanently to the
chunked per-step path (the same failure policy as the untiered kernel,
``parallel/batch.py: guarded_scan_fallback``).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from kafkastreams_cep_tpu.compiler.tables import TransitionTables, lower
from kafkastreams_cep_tpu.compiler.tiering import (
    TIER_HYBRID,
    TIER_NFA,
    TIER_STENCIL,
    TieringPlan,
    apply_lazy_order,
    plan_tiering,
)
from kafkastreams_cep_tpu.engine.matcher import (
    TIER_COUNTER_NAMES,
    EngineConfig,
    EngineState,
    EventBatch,
    StepOutput,
)
from kafkastreams_cep_tpu.engine.stencil import PrefixCarry, StencilPrefix
from kafkastreams_cep_tpu.engine.tiered import (
    TieredState,
    build_promote,
    seedless_init,
    stencil_step_output,
)
from kafkastreams_cep_tpu.parallel.batch import (
    BatchMatcher,
    broadcast_state,
    kernel_lane_step,
    lane_step,
)
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("parallel.tiered")


@functools.lru_cache(maxsize=1)
def _bump_engine_jit():
    """Process-wide singleton (pattern-free: pure pytree surgery)."""
    return jax.jit(lambda eng, t: eng._replace(step_seq=eng.step_seq + t))


class TieredBatchMatcher:
    """``K`` lanes matched under a compiler tiering plan (one chip).

    ``profile`` is an optional measured ``per_stage`` snapshot
    (``metrics_snapshot()["per_stage"]`` from a ``stage_attribution``
    run) consumed by the lazy-chain predicate ordering; without it the
    static cost model orders the conjuncts.  ``reorder=False`` skips the
    ordering pass entirely (differential baseline).
    """

    def __init__(
        self,
        pattern,
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        profile: Optional[Dict] = None,
        reorder: bool = True,
    ):
        tables = (
            pattern
            if isinstance(pattern, TransitionTables)
            else lower(pattern)
        )
        config = config or EngineConfig()
        if reorder:
            tables, self.lazy_order = apply_lazy_order(tables, profile)
        else:
            self.lazy_order = {}
        self.plan: TieringPlan = plan_tiering(tables, config, profile)
        self.tables = tables
        self.num_lanes = int(num_lanes)
        self.inner = BatchMatcher(tables, num_lanes, config)
        self.matcher = self.inner.matcher
        self.uses_walk_kernel = self.inner.uses_walk_kernel
        self.uses_scan_kernel = False
        logger.info(
            "tiered matcher: %s (%s), %d lanes",
            self.plan.tier, self.plan.reason, self.num_lanes,
        )
        # Dispatch accounting.  ``scan_calls`` and ``gate_chunks`` are
        # host integers (pure Python bookkeeping); chunk-level NFA
        # dispatches accumulate *on device* (``_nfa_chunks_dev``) so the
        # gated scan stays sync-free — :attr:`nfa_dispatches` folds them
        # in with a single transfer at telemetry-read time.
        self.scan_calls = 0
        self.gate_chunks = 0  # device-gated chunks offered (bench denom)
        self._nfa_dispatch_host = 0  # whole-batch dispatches (nfa/kernel)
        self._nfa_chunks_dev = None  # [*] i32 — chunks that ran NFA work
        p = self.plan.prefix_len
        if self.plan.tier == TIER_NFA:
            self._prefix = None
        else:
            self._prefix = StencilPrefix(tables, num_lanes, p)
            self._promote = build_promote(tables, config, p)
            if self.plan.tier == TIER_STENCIL:
                self._synth = self._cached(
                    "tiered.synth", (p,),
                    lambda: jax.jit(
                        stencil_step_output(tables, config, p)
                    ),
                )
            if (
                self.plan.tier == TIER_HYBRID
                and self.inner.uses_scan_kernel
            ):
                # Native tiered whole-scan program: the promotion feed
                # joins the event stream and the promotion phase fuses
                # after the engine phases (ops/scan_kernel.py), gated
                # per step on device.  Same guarded-fallback policy as
                # the untiered kernel: only a lowering failure swaps in
                # the chunked per-step path permanently.
                import os as _os

                scan_mode = _os.environ.get("CEP_SCAN_KERNEL", "0")

                def _build_tiered_full(scan_mode=scan_mode, p=p):
                    from kafkastreams_cep_tpu.ops import scan_kernel

                    full = scan_kernel.build_scan(
                        self.tables, self.matcher.config, promotion=p
                    )
                    full.interpret = scan_mode == "interpret"
                    return jax.jit(full)

                self._kernel_scan_jit = self._cached(
                    "tiered.scan_kernel", (p, scan_mode),
                    _build_tiered_full,
                )
                self.uses_scan_kernel = True
                logger.info("tiered matcher: whole-scan kernel enabled")

    # -- state ---------------------------------------------------------------

    @property
    def names(self) -> List[str]:
        return self.inner.names

    def _empty_carry(self) -> PrefixCarry:
        K = self.num_lanes
        i32 = jnp.int32
        z = jnp.zeros((K,), i32)
        return PrefixCarry(
            bools=jnp.zeros((K, 0, 0), bool),
            offs=jnp.zeros((K, 0), i32),
            ts=jnp.zeros((K, 0), i32),
            sver=jnp.zeros((K, 0), i32),
            cnt=z, screened=z, fires=z, promotions=z,
        )

    def init_state(self) -> TieredState:
        if self.plan.tier == TIER_NFA:
            return TieredState(
                engine=self.inner.init_state(), carry=self._empty_carry()
            )
        # The begin stage lives on the stencil tier: the NFA queue starts
        # empty and only promotions populate it.
        eng = broadcast_state(
            seedless_init(self.matcher._init_fn), self.num_lanes
        )
        return TieredState(engine=eng, carry=self._prefix.init_carry())

    # -- the scan ------------------------------------------------------------

    def _cached(self, namespace, tag, build):
        """Trace-cache lookup keyed by this matcher's (tables, config)
        fingerprint plus ``tag`` (utils/tracecache.py)."""
        import dataclasses as _dc

        from kafkastreams_cep_tpu.compiler.multitenant import tables_key
        from kafkastreams_cep_tpu.utils import tracecache

        tkey = tables_key(self.tables)
        key = (
            None
            if tkey is None
            else (tkey, _dc.astuple(self.matcher.config)) + tuple(tag)
        )
        return tracecache.lookup(namespace, key, build)

    @property
    def _bump_jit(self):
        """Advance ``step_seq`` by T without stepping: the exact effect a
        full scan of an empty, promotion-free queue would have had."""
        return _bump_engine_jit()

    @functools.cached_property
    def _hybrid_scan_jit(self):
        """The chunk-gated hybrid scan: ``(eng, events, promo) -> (eng,
        outs, promoted [K], dispatched)`` — ``dispatched`` the i32 count
        of chunks whose NFA work actually ran.  Entirely on device: the
        gate is a ``lax.cond`` per ``gate_chunk``-sized segment, so the
        host never syncs to decide a skip."""
        if self.inner.uses_walk_kernel:
            base_step = kernel_lane_step(
                self.matcher._phases, self.inner._kernel_interpret
            )
        else:
            base_step = lane_step(self.matcher._step_fn)
        promote_b = jax.vmap(self._promote)
        cfg = self.matcher.config
        C = max(int(cfg.gate_chunk), 1)
        K, R, W = self.num_lanes, cfg.max_runs, cfg.max_walk
        i32 = jnp.int32
        tmap = jax.tree_util.tree_map

        def body(s, x):
            ev, pr = x
            # Step first, then promote: the prefix completes *at* event
            # t, and the promoted run first evaluates at t+1 — exactly
            # the untiered run's schedule.
            s, out = base_step(s, ev)
            s, n = promote_b(s, pr.fire, pr.offs, pr.anchor_ts, pr.sver)
            return s, (out, n)

        def run_chunk(args):
            s, ev_t, pr_t = args
            s, (outs, ns) = jax.lax.scan(body, s, (ev_t, pr_t))
            return s, outs, jnp.sum(ns, axis=0)  # ns: [Tc, K] -> [K]

        def skip_chunk(args):
            # Exact: a scanned empty, promotion-free queue changes
            # nothing but step_seq, advanced here in one op.
            s, ev_t, _pr_t = args
            Tc = ev_t.ts.shape[0]
            outs = StepOutput(
                stage=jnp.full((Tc, K, R, W), -1, i32),
                off=jnp.full((Tc, K, R, W), -1, i32),
                count=jnp.zeros((Tc, K, R), i32),
            )
            s = s._replace(step_seq=s.step_seq + i32(Tc))
            return s, outs, jnp.zeros((K,), i32)

        def gated_chunk(s, ev_t, pr_t):
            # The chunk can observe NFA state iff a suffix run is live
            # at entry or the prefix completes inside it (promotion is
            # post-step, so a completion's first effect is in-chunk).
            needed = jnp.any(s.alive) | jnp.any(pr_t.fire)
            s, outs, n = jax.lax.cond(
                needed, run_chunk, skip_chunk, (s, ev_t, pr_t)
            )
            return s, outs, n, needed.astype(i32)

        def scan(eng: EngineState, events: EventBatch, promo):
            swap = lambda x: jnp.swapaxes(x, 0, 1)
            ev_t = tmap(swap, events)  # leaves [T, K, ...]
            pr_t = tmap(swap, promo)
            T = ev_t.ts.shape[0]
            m, r = divmod(T, C)
            promoted = jnp.zeros((K,), i32)
            dispatched = i32(0)
            parts = []
            if m:
                # All full chunks through ONE traced cond body: reshape
                # to [m, C, ...] and scan chunk-at-a-time.
                chunked = tmap(
                    lambda x: x[: m * C].reshape((m, C) + x.shape[1:]),
                    (ev_t, pr_t),
                )

                def outer(s, x):
                    ev, pr = x
                    s, outs, n, d = gated_chunk(s, ev, pr)
                    return s, (outs, n, d)

                eng, (outs_c, ns, ds) = jax.lax.scan(outer, eng, chunked)
                parts.append(
                    tmap(
                        lambda x: x.reshape((m * C,) + x.shape[2:]),
                        outs_c,
                    )
                )
                promoted = promoted + jnp.sum(ns, axis=0)
                dispatched = dispatched + jnp.sum(ds)
            if r:
                # Genuine ragged tail — never padded (padding would tick
                # step_seq past the batch and break bit-parity).
                ev_r, pr_r = tmap(lambda x: x[m * C :], (ev_t, pr_t))
                eng, outs_r, n_r, d_r = gated_chunk(eng, ev_r, pr_r)
                parts.append(outs_r)
                promoted = promoted + n_r
                dispatched = dispatched + d_r
            outs = (
                parts[0]
                if len(parts) == 1
                else tmap(
                    lambda *xs: jnp.concatenate(xs, axis=0), *parts
                )
            )
            outs = tmap(swap, outs)  # back to [K, T, ...]
            return eng, outs, promoted, dispatched

        return self._cached(
            "tiered.hybrid_scan_chunked",
            (
                self.plan.prefix_len, self.inner.uses_walk_kernel,
                self.inner._kernel_interpret,
            ),
            lambda: jax.jit(scan),
        )

    @property
    def nfa_dispatches(self) -> int:
        """NFA-tier dispatch count: whole-batch dispatches (pure-NFA
        plans and the tiered whole-scan kernel) plus device-gated chunks
        that actually ran NFA work.  Reading it is the only host sync in
        the dispatch accounting (telemetry/bench only — never on the
        scan path)."""
        n = self._nfa_dispatch_host
        if self._nfa_chunks_dev is not None:
            n += int(jax.device_get(self._nfa_chunks_dev))
        return n

    def _kernel_scan(self, eng: EngineState, events: EventBatch, promo):
        """The tiered whole-scan kernel with the guarded permanent
        fallback (lowering failures only) onto the chunked path."""
        from kafkastreams_cep_tpu.parallel.batch import is_lowering_error

        try:
            eng, out, promoted = self._kernel_scan_jit(eng, events, promo)
            return eng, out, promoted, None
        except Exception as e:
            if not is_lowering_error(e):
                raise
            logger.warning(
                "tiered whole-scan kernel failed to lower (%s); falling "
                "back to the chunk-gated per-step path", e,
            )
            self.uses_scan_kernel = False
            return self._hybrid_scan_jit(eng, events, promo)

    def scan(self, state: TieredState, events: EventBatch):
        """One ``[K, T]`` batch through the tier plan.  Same output
        contract as :meth:`BatchMatcher.scan`.  Sync-free: every tier
        decision is either host-static (the plan) or a device-side
        ``lax.cond`` (the chunk gate), so pipelined callers keep full
        dispatch/decode overlap."""
        T = int(events.ts.shape[1])
        self.scan_calls += 1
        if self.plan.tier == TIER_NFA:
            self._nfa_dispatch_host += 1
            eng, out = self.inner.scan(state.engine, events)
            return TieredState(eng, state.carry), out
        # Stencil/hybrid tiers never reach inner.scan, so the measured
        # conjunct tally (stage_attribution) accumulates here — same
        # once-per-batch schedule as the untiered matcher.
        self.inner._accumulate_conjuncts(events)
        carry, promo = self._prefix.scan(state.carry, events)
        if self.plan.tier == TIER_STENCIL:
            out = self._synth(promo)
            eng = self._bump_jit(state.engine, jnp.int32(T))
            return TieredState(eng, carry), out
        if self.uses_scan_kernel:
            eng, out, promoted, dispatched = self._kernel_scan(
                state.engine, events, promo
            )
        else:
            eng, out, promoted, dispatched = self._hybrid_scan_jit(
                state.engine, events, promo
            )
        if dispatched is None:
            # Whole-scan kernel: one launch, gated per step in-program.
            self._nfa_dispatch_host += 1
        else:
            C = max(int(self.matcher.config.gate_chunk), 1)
            self.gate_chunks += -(-T // C)
            self._nfa_chunks_dev = (
                dispatched
                if self._nfa_chunks_dev is None
                else self._nfa_chunks_dev + dispatched
            )
        carry = carry._replace(promotions=carry.promotions + promoted)
        return TieredState(eng, carry), out

    # -- maintenance / drains ------------------------------------------------

    def sweep(self, state: TieredState) -> TieredState:
        """Engine-tier maintenance sweep; the stencil carry holds no slab
        references (partial prefixes own no entries) so it rides along
        untouched."""
        return state._replace(engine=self.inner.sweep(state.engine))

    def drain(self, state: TieredState):
        eng, out = self.inner.drain(state.engine)
        return state._replace(engine=eng), out

    # -- telemetry -----------------------------------------------------------

    def counters(self, state: TieredState) -> Dict[str, int]:
        return self.inner.counters(state.engine)

    def hot_counters(self, state: TieredState) -> Dict[str, int]:
        return self.inner.hot_counters(state.engine)

    def walk_counters(self, state: TieredState) -> Dict[str, int]:
        return self.inner.walk_counters(state.engine)

    def per_lane_counters(self, state: TieredState) -> Dict[str, list]:
        return self.inner.per_lane_counters(state.engine)

    def stage_counters(self, state: TieredState):
        return self.inner.stage_counters(state.engine)

    def tier_counters(self, state: TieredState) -> Dict[str, int]:
        """Lane-summed tier telemetry in ``TIER_COUNTER_NAMES`` order:
        events screened by the prefix tier, prefix completions, and runs
        promoted into the NFA tier."""
        c = state.carry
        vals = jax.device_get(
            (jnp.sum(c.screened), jnp.sum(c.fires), jnp.sum(c.promotions))
        )
        return {n: int(v) for n, v in zip(TIER_COUNTER_NAMES, vals)}

    def metrics_snapshot(self, state: TieredState) -> Dict[str, object]:
        out = self.inner.metrics_snapshot(state.engine)
        out.update(self.tier_counters(state))
        # Dispatch-gate telemetry (host + one device read, never on the
        # scan path): how much NFA work the chunk gate actually elided.
        out["tier_scan_calls"] = self.scan_calls
        out["tier_gate_chunks"] = self.gate_chunks
        out["tier_nfa_dispatches"] = self.nfa_dispatches
        return out
