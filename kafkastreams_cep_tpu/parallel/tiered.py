"""Tiered single-chip matcher: stencil prefix tier + NFA suffix tier.

Drop-in for :class:`~kafkastreams_cep_tpu.parallel.batch.BatchMatcher`
(same scan/sweep/drain/counters surface, ``CEPProcessor`` selects it when
``EngineConfig.tiering`` is set) that executes the compiler tiering plan
(``compiler/tiering.py``):

* ``nfa``     — no usable prefix: pure delegation to the inner
  :class:`BatchMatcher`, state still wrapped in :class:`TieredState` so
  every config compiles to one state shape.
* ``stencil`` — the whole pattern is a strict sequence: the prefix tier
  IS the matcher; completions are rendered as the engine's ``StepOutput``
  grid (``engine/tiered.py: stencil_step_output``) and the NFA engine is
  never dispatched (its ``step_seq`` still ticks, keeping drain/handle
  ordering invariants intact).
* ``hybrid``  — the stencil screens the whole ``[K, T]`` batch first
  (fully parallel over keys *and* time), then the NFA tier scans the
  batch with a promotion step fused after every engine step
  (``engine/tiered.py: build_promote``).  When the stencil reports no
  completions **and** no suffix run is alive anywhere, the NFA dispatch
  is skipped outright — on screened (production-monitoring-shaped)
  traffic most batches never pay a single NFA step.  The skip is exact:
  a stepped empty queue changes nothing but ``step_seq``, which the skip
  path advances by ``T`` in one op.

The gating check costs one scalar ``device_get`` per ``scan`` call (the
stencil output must be inspected on host to elide the NFA dispatch);
pipelined processors therefore lose some dispatch/decode overlap under
tiering — throughput on screened workloads gains far more than the sync
costs (bench ``CEP_BENCH_TIER``).

Parity: matches, emission order, and loss counters are bit-identical to
the untiered engine on loss-free workloads across the jnp and Pallas
walk-kernel paths (tests/test_tiering.py).  Under ``CEP_SCAN_KERNEL``
the *hybrid* suffix scan falls back to the per-step kernel path (the
whole-scan Pallas program cannot take per-step promotion inputs); the
untiered scan-kernel output is bit-identical to the per-step path, so
tiered-vs-untiered parity is unaffected.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from kafkastreams_cep_tpu.compiler.tables import TransitionTables, lower
from kafkastreams_cep_tpu.compiler.tiering import (
    TIER_HYBRID,
    TIER_NFA,
    TIER_STENCIL,
    TieringPlan,
    apply_lazy_order,
    plan_tiering,
)
from kafkastreams_cep_tpu.engine.matcher import (
    TIER_COUNTER_NAMES,
    EngineConfig,
    EngineState,
    EventBatch,
    StepOutput,
)
from kafkastreams_cep_tpu.engine.stencil import PrefixCarry, StencilPrefix
from kafkastreams_cep_tpu.engine.tiered import (
    TieredState,
    build_promote,
    seedless_init,
    stencil_step_output,
)
from kafkastreams_cep_tpu.parallel.batch import (
    BatchMatcher,
    broadcast_state,
    kernel_lane_step,
    lane_step,
)
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("parallel.tiered")


@functools.lru_cache(maxsize=1)
def _bump_engine_jit():
    """Process-wide singleton (pattern-free: pure pytree surgery)."""
    return jax.jit(lambda eng, t: eng._replace(step_seq=eng.step_seq + t))


@functools.lru_cache(maxsize=1)
def _gate_engine_jit():
    """Process-wide singleton (pattern-free reduction)."""
    return jax.jit(lambda alive, fire: jnp.any(alive) | jnp.any(fire))


class TieredBatchMatcher:
    """``K`` lanes matched under a compiler tiering plan (one chip).

    ``profile`` is an optional measured ``per_stage`` snapshot
    (``metrics_snapshot()["per_stage"]`` from a ``stage_attribution``
    run) consumed by the lazy-chain predicate ordering; without it the
    static cost model orders the conjuncts.  ``reorder=False`` skips the
    ordering pass entirely (differential baseline).
    """

    def __init__(
        self,
        pattern,
        num_lanes: int,
        config: Optional[EngineConfig] = None,
        profile: Optional[Dict] = None,
        reorder: bool = True,
    ):
        tables = (
            pattern
            if isinstance(pattern, TransitionTables)
            else lower(pattern)
        )
        config = config or EngineConfig()
        if reorder:
            tables, self.lazy_order = apply_lazy_order(tables, profile)
        else:
            self.lazy_order = {}
        self.plan: TieringPlan = plan_tiering(tables, config, profile)
        self.tables = tables
        self.num_lanes = int(num_lanes)
        self.inner = BatchMatcher(tables, num_lanes, config)
        self.matcher = self.inner.matcher
        self.uses_walk_kernel = self.inner.uses_walk_kernel
        self.uses_scan_kernel = False  # the tiered scan is step-driven
        logger.info(
            "tiered matcher: %s (%s), %d lanes",
            self.plan.tier, self.plan.reason, self.num_lanes,
        )
        # Host-side dispatch accounting: how often the NFA tier actually
        # ran (the skip-gate's measurable effect; bench CEP_BENCH_TIER).
        self.scan_calls = 0
        self.nfa_dispatches = 0
        p = self.plan.prefix_len
        if self.plan.tier == TIER_NFA:
            self._prefix = None
        else:
            self._prefix = StencilPrefix(tables, num_lanes, p)
            self._promote = build_promote(tables, config, p)
            if self.plan.tier == TIER_STENCIL:
                self._synth = self._cached(
                    "tiered.synth", (p,),
                    lambda: jax.jit(
                        stencil_step_output(tables, config, p)
                    ),
                )
            if self.inner.uses_scan_kernel:
                # The whole-scan Pallas program has no per-step promotion
                # inputs; the per-step (kernel or jnp) path is bit-
                # identical, so the fallback costs nothing but the fusion.
                logger.warning(
                    "CEP_SCAN_KERNEL requested but the hybrid tier runs "
                    "the per-step path (promotions are per-step inputs)"
                )

    # -- state ---------------------------------------------------------------

    @property
    def names(self) -> List[str]:
        return self.inner.names

    def _empty_carry(self) -> PrefixCarry:
        K = self.num_lanes
        i32 = jnp.int32
        z = jnp.zeros((K,), i32)
        return PrefixCarry(
            bools=jnp.zeros((K, 0, 0), bool),
            offs=jnp.zeros((K, 0), i32),
            ts=jnp.zeros((K, 0), i32),
            sver=jnp.zeros((K, 0), i32),
            cnt=z, screened=z, fires=z, promotions=z,
        )

    def init_state(self) -> TieredState:
        if self.plan.tier == TIER_NFA:
            return TieredState(
                engine=self.inner.init_state(), carry=self._empty_carry()
            )
        # The begin stage lives on the stencil tier: the NFA queue starts
        # empty and only promotions populate it.
        eng = broadcast_state(
            seedless_init(self.matcher._init_fn), self.num_lanes
        )
        return TieredState(engine=eng, carry=self._prefix.init_carry())

    # -- the scan ------------------------------------------------------------

    def _cached(self, namespace, tag, build):
        """Trace-cache lookup keyed by this matcher's (tables, config)
        fingerprint plus ``tag`` (utils/tracecache.py)."""
        import dataclasses as _dc

        from kafkastreams_cep_tpu.compiler.multitenant import tables_key
        from kafkastreams_cep_tpu.utils import tracecache

        tkey = tables_key(self.tables)
        key = (
            None
            if tkey is None
            else (tkey, _dc.astuple(self.matcher.config)) + tuple(tag)
        )
        return tracecache.lookup(namespace, key, build)

    @property
    def _bump_jit(self):
        """Advance ``step_seq`` by T without stepping: the exact effect a
        full scan of an empty, promotion-free queue would have had."""
        return _bump_engine_jit()

    @property
    def _gate_jit(self):
        return _gate_engine_jit()

    @functools.cached_property
    def _hybrid_scan_jit(self):
        if self.inner.uses_walk_kernel:
            base_step = kernel_lane_step(
                self.matcher._phases, self.inner._kernel_interpret
            )
        else:
            base_step = lane_step(self.matcher._step_fn)
        promote_b = jax.vmap(self._promote)

        def scan(eng: EngineState, events: EventBatch, promo):
            swap = lambda x: jnp.swapaxes(x, 0, 1)
            ev_t = jax.tree_util.tree_map(swap, events)
            pr_t = jax.tree_util.tree_map(swap, promo)

            def body(s, x):
                ev, pr = x
                # Step first, then promote: the prefix completes *at*
                # event t, and the promoted run first evaluates at t+1 —
                # exactly the untiered run's schedule.
                s, out = base_step(s, ev)
                s, n = promote_b(s, pr.fire, pr.offs, pr.anchor_ts, pr.sver)
                return s, (out, n)

            eng, (outs, ns) = jax.lax.scan(body, eng, (ev_t, pr_t))
            outs = jax.tree_util.tree_map(swap, outs)
            return eng, outs, jnp.sum(ns, axis=0)  # ns: [T, K] -> [K]

        return self._cached(
            "tiered.hybrid_scan",
            (
                self.plan.prefix_len, self.inner.uses_walk_kernel,
                self.inner._kernel_interpret,
            ),
            lambda: jax.jit(scan),
        )

    def _zero_out(self, T: int) -> StepOutput:
        cfg = self.matcher.config
        K, R, W = self.num_lanes, cfg.max_runs, cfg.max_walk
        i32 = jnp.int32
        return StepOutput(
            stage=jnp.full((K, T, R, W), -1, i32),
            off=jnp.full((K, T, R, W), -1, i32),
            count=jnp.zeros((K, T, R), i32),
        )

    def scan(self, state: TieredState, events: EventBatch):
        """One ``[K, T]`` batch through the tier plan.  Same output
        contract as :meth:`BatchMatcher.scan`; host-gated, so not itself
        jittable (callers that need a pure jitted scan use the untiered
        matcher)."""
        T = int(events.ts.shape[1])
        self.scan_calls += 1
        if self.plan.tier == TIER_NFA:
            self.nfa_dispatches += 1
            eng, out = self.inner.scan(state.engine, events)
            return TieredState(eng, state.carry), out
        carry, promo = self._prefix.scan(state.carry, events)
        if self.plan.tier == TIER_STENCIL:
            out = self._synth(promo)
            eng = self._bump_jit(state.engine, jnp.int32(T))
            return TieredState(eng, carry), out
        # Hybrid: skip the NFA dispatch outright when nothing can happen
        # there — no live suffix run and no promotion this batch.  One
        # scalar sync; the skip is exact (see module docstring).
        needed = bool(
            jax.device_get(
                self._gate_jit(state.engine.alive, promo.fire)
            )
        )
        if not needed:
            eng = self._bump_jit(state.engine, jnp.int32(T))
            return TieredState(eng, carry), self._zero_out(T)
        self.nfa_dispatches += 1
        eng, out, promoted = self._hybrid_scan_jit(
            state.engine, events, promo
        )
        carry = carry._replace(promotions=carry.promotions + promoted)
        return TieredState(eng, carry), out

    # -- maintenance / drains ------------------------------------------------

    def sweep(self, state: TieredState) -> TieredState:
        """Engine-tier maintenance sweep; the stencil carry holds no slab
        references (partial prefixes own no entries) so it rides along
        untouched."""
        return state._replace(engine=self.inner.sweep(state.engine))

    def drain(self, state: TieredState):
        eng, out = self.inner.drain(state.engine)
        return state._replace(engine=eng), out

    # -- telemetry -----------------------------------------------------------

    def counters(self, state: TieredState) -> Dict[str, int]:
        return self.inner.counters(state.engine)

    def hot_counters(self, state: TieredState) -> Dict[str, int]:
        return self.inner.hot_counters(state.engine)

    def walk_counters(self, state: TieredState) -> Dict[str, int]:
        return self.inner.walk_counters(state.engine)

    def per_lane_counters(self, state: TieredState) -> Dict[str, list]:
        return self.inner.per_lane_counters(state.engine)

    def stage_counters(self, state: TieredState):
        return self.inner.stage_counters(state.engine)

    def tier_counters(self, state: TieredState) -> Dict[str, int]:
        """Lane-summed tier telemetry in ``TIER_COUNTER_NAMES`` order:
        events screened by the prefix tier, prefix completions, and runs
        promoted into the NFA tier."""
        c = state.carry
        vals = jax.device_get(
            (jnp.sum(c.screened), jnp.sum(c.fires), jnp.sum(c.promotions))
        )
        return {n: int(v) for n, v in zip(TIER_COUNTER_NAMES, vals)}

    def metrics_snapshot(self, state: TieredState) -> Dict[str, object]:
        out = self.inner.metrics_snapshot(state.engine)
        out.update(self.tier_counters(state))
        return out
