"""Single-chip key-batched matcher: ``vmap`` of the engine over lanes.

The reference runs one independent NFA per Kafka partition
(``CEPProcessor.java:117-134``); here each *lane* of a ``[K]`` leading axis
is one such independent matcher (state + slab), stepped in lockstep by one
compiled dispatch.  This is the unit the mesh layer shards.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from kafkastreams_cep_tpu.engine.matcher import (
    COUNTER_NAMES,
    EngineConfig,
    EngineState,
    EventBatch,
    StepOutput,
    TPUMatcher,
    counter_values,
)


def broadcast_state(state: EngineState, num_lanes: int) -> EngineState:
    """Tile one lane's engine state to a ``[K]`` leading axis."""
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (num_lanes,) + x.shape), state
    )


def lane_step(step_one):
    """Lift a per-lane step to a ``[K]``-batched step (shared by the batch
    and sharded matchers so lane semantics can never diverge)."""

    def step(state: EngineState, ev: EventBatch):
        return jax.vmap(step_one)(state, ev)

    return step


def lane_scan(step_one):
    """Lift a per-lane step to a ``[K, T]`` scanned batch."""

    def scan(state: EngineState, events: EventBatch):
        return jax.vmap(lambda s, e: jax.lax.scan(step_one, s, e))(
            state, events
        )

    return scan


class BatchMatcher:
    """``K`` independent per-key matchers stepped as one array program.

    ``step`` consumes one event per lane (``EventBatch`` leaves shaped
    ``[K, ...]``); ``scan`` consumes a ``[K, T]`` time-stacked batch and runs
    the whole window in a single ``lax.scan`` dispatch — the shape the
    micro-batcher (``runtime/processor.py``) and the benchmarks feed.
    """

    def __init__(
        self,
        pattern,
        num_lanes: int,
        config: Optional[EngineConfig] = None,
    ):
        self.matcher = TPUMatcher(pattern, config)
        self.num_lanes = int(num_lanes)
        self._step_fn = lane_step(self.matcher._step_fn)
        self._scan_fn = lane_scan(self.matcher._step_fn)
        self.step = jax.jit(self._step_fn)
        self.scan = jax.jit(self._scan_fn)

    @property
    def names(self):
        return self.matcher.names

    def init_state(self) -> EngineState:
        return broadcast_state(self.matcher.init_state(), self.num_lanes)

    def counters(self, state: EngineState) -> Dict[str, int]:
        """Aggregate overflow/drop counters summed over all lanes."""
        return {
            n: int(jnp.sum(v))
            for n, v in zip(COUNTER_NAMES, counter_values(state))
        }
