"""Single-chip key-batched matcher: ``vmap`` of the engine over lanes.

The reference runs one independent NFA per Kafka partition
(``CEPProcessor.java:117-134``); here each *lane* of a ``[K]`` leading axis
is one such independent matcher (state + slab), stepped in lockstep by one
compiled dispatch.  This is the unit the mesh layer shards.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from kafkastreams_cep_tpu.engine.matcher import (
    COUNTER_NAMES,
    HOT_COUNTER_NAMES,
    WALK_COUNTER_NAMES,
    DrainOutput,
    EngineConfig,
    EngineState,
    EventBatch,
    StepOutput,
    TPUMatcher,
    counter_values,
    hot_counter_values,
    walk_counter_values,
)
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("parallel.batch")

# Exception-type names and message fragments that identify a Mosaic/Pallas
# lowering or compilation failure — the only failure class that justifies
# permanently abandoning the fused kernel for a pattern.  Everything else
# (RESOURCE_EXHAUSTED on a transient OOM, cancelled/interrupted calls,
# data-dependent runtime faults) must propagate and leave the kernel armed.
_LOWERING_ERROR_TYPES = (NotImplementedError,)
_LOWERING_ERROR_TYPE_NAMES = (
    "LoweringError",
    "LoweringException",
    "MosaicError",
    "VerificationError",
)
_LOWERING_ERROR_MARKERS = (
    "mosaic",
    "pallas",
    "lowering",
    "unsupported",
    "not implemented",
    "cannot lower",
    "vmem",
    "relayout",
    "bitcast_vreg",
)
_TRANSIENT_MARKERS = (
    "resource_exhausted",
    "interrupted",
    "cancelled",
    "deadline",
    "unavailable",
)


def is_lowering_error(e: BaseException) -> bool:
    """Classify an exception from a fused-kernel call: ``True`` for
    Mosaic/Pallas lowering/compilation failures (pattern cannot lower —
    fall back permanently), ``False`` for anything transient or unknown
    (re-raise; the kernel stays enabled for the next call)."""
    if isinstance(e, _LOWERING_ERROR_TYPES):
        return True
    msg = str(e).lower()
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return False
    for cls in type(e).__mro__:
        if cls.__name__ in _LOWERING_ERROR_TYPE_NAMES:
            return True
    return any(m in msg for m in _LOWERING_ERROR_MARKERS)


def broadcast_state(state: EngineState, num_lanes: int) -> EngineState:
    """Tile one lane's engine state to a ``[K]`` leading axis."""
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (num_lanes,) + x.shape), state
    )


def lane_step(step_one):
    """Lift a per-lane step to a ``[K]``-batched step (shared by the batch
    and sharded matchers so lane semantics can never diverge)."""

    def step(state: EngineState, ev: EventBatch):
        return jax.vmap(step_one)(state, ev)

    return step


def lane_scan(step_one):
    """Lift a per-lane step to a ``[K, T]`` scanned batch."""

    def scan(state: EngineState, events: EventBatch):
        return jax.vmap(lambda s, e: jax.lax.scan(step_one, s, e))(
            state, events
        )

    return scan


def guarded_scan_fallback(fast, make_slow, on_fallback=None,
                          what="whole-scan kernel"):
    """Guarded first call of a fused whole-scan kernel, shared by
    :class:`BatchMatcher` and ``parallel/sharding.ShardedMatcher`` so the
    failure-classification policy can never drift between the single-chip
    and sharded paths.

    The kernel traces user predicates into the Pallas program, so a
    pattern that cannot lower to Mosaic fails at the first *compiled*
    call, not at build time — only that class of failure
    (:func:`is_lowering_error`) permanently swaps in ``make_slow()``.
    Anything transient — device OOM, interrupts, preemption, an injected
    device fault — re-raises with the kernel still armed, so the next
    call (e.g. a supervisor recovery retry) runs the fused path again
    instead of silently degrading for the rest of the process.
    ``on_fallback`` (if given) runs once at the permanent swap, for the
    owner's ``uses_scan_kernel`` bookkeeping.
    """
    slow = None

    def scan(state, events):
        nonlocal slow
        if slow is None:
            try:
                return fast(state, events)
            except Exception as e:
                if not is_lowering_error(e):
                    raise
                logger.warning(
                    "%s failed to lower (%s); falling back to the "
                    "per-step path", what, e,
                )
                slow = make_slow()
                if on_fallback is not None:
                    on_fallback()
        return slow(state, events)

    return scan


def kernel_lane_step(phases, interpret: bool = False, qids=None):
    """A ``[K]``-batched step whose walk pass runs the fused Pallas kernel.

    The chain and puts phases stay vmapped jnp; the walk pass — ~90% of the
    step in the all-jnp engine (PROFILE_r04.md) — runs once over the whole
    lane batch with each block's slab resident in VMEM
    (``ops/walk_kernel.py``).  Semantically identical to
    ``lane_step(matcher._step_fn)`` (same phase order, same sequential
    queue-order walk semantics); differentially tested in
    ``tests/test_walk_kernel.py`` and the engine A/B test.
    """
    from kafkastreams_cep_tpu.ops.walk_kernel import walk_pass_kernel

    ph = phases

    def step(state: EngineState, ev: EventBatch):
        if qids is None:
            rec = jax.vmap(ph.eval_chain)(state, ev)
        else:
            # Stacked bank: each lane evaluates its own query's tables.
            rec = jax.vmap(ph.eval_chain)(state, ev, qids)
        ops = jax.vmap(ph.build_puts)(state, rec, ev)
        wk = jax.vmap(ph.build_walkers)(state, rec, ev)
        # Both slab phases (consuming puts, then all walks) run inside one
        # Pallas call: the slab crosses HBM once per step instead of twice.
        # (Lane-load sorting was tried here and measured net-negative: in
        # load-sorted blocks every batch runs the full hop bound, erasing
        # the batch-count win, and the permutation gathers add traffic.)
        slab, out_stage, out_off, out_count = walk_pass_kernel(
            state.slab, *wk,
            max_walk=ph.max_walk, out_base=ph.out_base,
            out_rows=ph.out_rows, interpret=interpret,
            put_ops=ops, ev_off=ev.off,
            hot_entries=ph.hot_entries,
        )
        if qids is None:
            return jax.vmap(ph.finish)(
                state, ev, rec, slab, out_stage, out_off, out_count
            )
        return jax.vmap(ph.finish)(
            state, ev, rec, slab, out_stage, out_off, out_count, qids
        )

    return step


def kernel_lane_scan(step):
    """Scan a kernel-backed batched step over the time axis of ``[K, T]``
    events (time-major under the hood; the public layout is unchanged)."""

    def scan(state: EngineState, events: EventBatch):
        ev_t = jax.tree_util.tree_map(
            lambda x: jnp.swapaxes(x, 0, 1), events
        )
        state, outs = jax.lax.scan(step, state, ev_t)
        return state, jax.tree_util.tree_map(
            lambda x: jnp.swapaxes(x, 0, 1), outs
        )

    return scan


def sweep_lanes(state: EngineState, depth: int, do_renorm: bool) -> EngineState:
    """Per-lane maintenance sweep shared by the batch and sharded matchers
    (single source, like :func:`lane_step`, so their sweep semantics can
    never diverge): slab mark-sweep (frees entries unreachable from live
    run state) then, when enabled, Dewey version renormalization
    (``ops/renorm.py`` — deletes provably-dead zero positions so the fixed
    ``dewey_depth`` stays sufficient on unbounded straddling streams).

    Pending lazy-extraction handles (``EngineState.hr_*``) are first-class
    liveness roots: a pinned-but-undrained match chain must survive the
    mark-sweep, and its walk version must renormalize together with the
    pointer versions it will be compared against at drain time — handles
    ride the renorm as extra non-seed run rows.  Under the eager engine
    ``hr_count`` is always 0 and both extensions are inert.
    """
    from kafkastreams_cep_tpu.ops import renorm as renorm_mod
    from kafkastreams_cep_tpu.ops import slab as slab_mod

    HB = state.hr_stage.shape[-1]
    R = state.alive.shape[-1]
    pending = (
        jnp.arange(HB, dtype=jnp.int32)[None, :]
        < state.hr_count[:, None]
    )
    run_off = jnp.concatenate(
        [
            jnp.where(state.alive, state.event_off, -1),
            jnp.where(pending, state.hr_off, -1),
        ],
        axis=-1,
    )
    slab = jax.vmap(
        lambda s, ro: slab_mod.mark_sweep(s, None, ro, depth)
    )(state.slab, run_off)
    state = state._replace(slab=slab)
    if do_renorm:
        ver_all = jnp.concatenate([state.ver, state.hr_ver], axis=-2)
        vlen_all = jnp.concatenate([state.vlen, state.hr_vlen], axis=-1)
        alive_all = jnp.concatenate([state.alive, pending], axis=-1)
        # Handles are never seed runs (a match consumed events): id 0.
        id_all = jnp.concatenate(
            [state.id_pos, jnp.zeros_like(state.hr_vlen)], axis=-1
        )
        ver2, vlen2, slab, _ = jax.vmap(renorm_mod.renorm_lane)(
            ver_all, vlen_all, alive_all, id_all, state.slab
        )
        state = state._replace(
            ver=ver2[..., :R, :],
            vlen=vlen2[..., :R],
            hr_ver=ver2[..., R:, :],
            hr_vlen=vlen2[..., R:],
            slab=slab,
        )
    return state


def _select_walk_kernel(config: EngineConfig, num_lanes: int):
    """Decide (use_kernel, interpret) for this batch shape.

    ``CEP_WALK_KERNEL``: ``auto`` (default — kernel on TPU backends when the
    lane count allows), ``0`` (never), ``1`` (force compiled), ``interpret``
    (force interpreter mode — CPU-testable).
    """
    from kafkastreams_cep_tpu.ops.walk_kernel import LANE_BLOCK

    mode = os.environ.get("CEP_WALK_KERNEL", "auto")
    feasible = (
        not config.sequential_slab and num_lanes % LANE_BLOCK == 0
    )
    if not feasible and mode in ("1", "interpret"):
        logger.warning(
            "CEP_WALK_KERNEL=%s requested but infeasible for this matcher "
            "(num_lanes=%d %% %d != 0 or sequential_slab) — falling back "
            "to the jnp walk pass",
            mode, num_lanes, LANE_BLOCK,
        )
    if mode == "0" or not feasible:
        return False, False
    if mode == "interpret":
        return True, True
    if mode == "1":
        return True, False
    return jax.default_backend() == "tpu", False


class BatchMatcher:
    """``K`` independent per-key matchers stepped as one array program.

    ``step`` consumes one event per lane (``EventBatch`` leaves shaped
    ``[K, ...]``); ``scan`` consumes a ``[K, T]`` time-stacked batch and runs
    the whole window in a single ``lax.scan`` dispatch — the shape the
    micro-batcher (``runtime/processor.py``) and the benchmarks feed.
    """

    def __init__(
        self,
        pattern,
        num_lanes: int,
        config: Optional[EngineConfig] = None,
    ):
        self.matcher = TPUMatcher(pattern, config)
        self.num_lanes = int(num_lanes)
        use_kernel, interpret = _select_walk_kernel(
            self.matcher.config, self.num_lanes
        )
        self.uses_walk_kernel = use_kernel
        self._kernel_interpret = interpret
        # Like TPUMatcher, the lane-lifted jitted programs are structural
        # functions of (tables, config, kernel mode): share them across
        # instances so re-building a batch matcher for a known pattern
        # skips the vmap/scan re-trace (utils/tracecache.py).  The lane
        # count K is deliberately NOT in the key — vmap programs retrace
        # per input shape inside jit anyway, so one cached callable
        # serves every K in the same kernel-feasibility class.
        import dataclasses as _dc

        from kafkastreams_cep_tpu.compiler.multitenant import tables_key

        _tk = tables_key(self.matcher.tables)
        self._cache_key = (
            None
            if _tk is None
            else (_tk, _dc.astuple(self.matcher.config))
        )
        if use_kernel:
            logger.info(
                "batch matcher: fused walk kernel enabled (%d lanes%s)",
                self.num_lanes, ", interpret" if interpret else "",
            )
            self._step_fn = kernel_lane_step(self.matcher._phases, interpret)
            self._scan_fn = kernel_lane_scan(self._step_fn)
            self._mode_tag = ("kernel", interpret)
        else:
            self._step_fn = lane_step(self.matcher._step_fn)
            self._scan_fn = lane_scan(self.matcher._step_fn)
            self._mode_tag = ("jnp",)
        # Whole-scan fused kernel (ops/scan_kernel.py): the entire event
        # loop in one Pallas program, state resident in VMEM across T.
        # Opt-in (CEP_SCAN_KERNEL=1, or =interpret for CPU testing):
        # differential parity is pinned by tests/test_scan_kernel.py, and
        # measured throughput is at parity with the per-step walk kernel
        # on the headline trace (see PROFILE_r05.md — both are bound by
        # the same lockstep walk-pass vector work, not launch or HBM
        # overheads), so the per-step path stays the default.
        self.uses_scan_kernel = False
        scan_mode = os.environ.get("CEP_SCAN_KERNEL", "0")
        if scan_mode in ("1", "interpret"):
            from kafkastreams_cep_tpu.ops import scan_kernel

            if self.num_lanes % scan_kernel.LANE_BLOCK:
                logger.warning(
                    "CEP_SCAN_KERNEL=%s requested but num_lanes=%d is not "
                    "a multiple of %d — using the per-step path",
                    scan_mode, self.num_lanes, scan_kernel.LANE_BLOCK,
                )
            else:
                def _build_full(scan_mode=scan_mode):
                    full = scan_kernel.build_scan(
                        self.matcher.tables, self.matcher.config
                    )
                    full.interpret = scan_mode == "interpret"
                    return jax.jit(full)

                jitted_full = self._cached(
                    "batch.scan_kernel", ("scan", scan_mode), _build_full
                )
                self._scan_fn = self._with_fallback(jitted_full)
                self.uses_scan_kernel = True
                logger.info("batch matcher: whole-scan kernel enabled")
        self.step = self._cached(
            "batch.step", self._mode_tag, lambda: jax.jit(self._step_fn)
        )
        self.scan = (
            self._scan_fn
            if self.uses_scan_kernel
            else self._cached(
                "batch.scan", self._mode_tag,
                lambda: jax.jit(self._scan_fn),
            )
        )
        # Measured per-conjunct selectivity: under stage_attribution every
        # consuming-edge conjunct is tallied unconditionally over each
        # scanned batch (compiler/tiering.py: build_conjunct_tally) so
        # apply_lazy_order can rank lazy chains on measurement alone.
        # Accumulation is device-side and asynchronous; the counts sync to
        # host only at telemetry reads (conjunct_counters).  The slot-key
        # tuple joins the cache tag because the tally closes over this
        # instance's conjunct order, which lazy reordering permutes.
        self._conjunct_slots: list = []
        self._conjunct_counts = None
        if self.matcher.config.stage_attribution:
            from kafkastreams_cep_tpu.compiler.tiering import (
                build_conjunct_tally,
            )

            slots, tally = build_conjunct_tally(self.matcher.tables)
            if slots:
                self._conjunct_slots = slots
                self._conjunct_tally_jit = self._cached(
                    "batch.conjunct_tally",
                    ("tally",) + tuple(k for _, k, _ in slots),
                    lambda: jax.jit(tally),
                )
                inner_scan = self.scan

                def _scan_tallied(state, events):
                    self._accumulate_conjuncts(events)
                    return inner_scan(state, events)

                self.scan = _scan_tallied

    def _cached(self, namespace: str, tag, build):
        """Jitted-program lookup in the process trace cache, keyed by this
        matcher's (tables fingerprint, config) plus ``tag`` — unkeyable
        patterns build uncached."""
        from kafkastreams_cep_tpu.utils import tracecache

        key = None if self._cache_key is None else self._cache_key + (tag,)
        return tracecache.lookup(namespace, key, build)

    def _with_fallback(self, jitted_full_scan):
        """:func:`guarded_scan_fallback` over this matcher's per-step
        path — see the helper for the failure-classification policy."""

        def make_slow():
            if self.uses_walk_kernel:
                return self._cached(
                    "batch.scan", self._mode_tag,
                    lambda: jax.jit(kernel_lane_scan(self._step_fn)),
                )
            return self._cached(
                "batch.scan", self._mode_tag,
                lambda: jax.jit(lane_scan(self.matcher._step_fn)),
            )

        def on_fallback():
            self.uses_scan_kernel = False

        return guarded_scan_fallback(
            jitted_full_scan, make_slow, on_fallback
        )

    @property
    def names(self):
        return self.matcher.names

    def init_state(self) -> EngineState:
        return broadcast_state(self.matcher.init_state(), self.num_lanes)

    def sweep(self, state: EngineState) -> EngineState:
        """Free slab entries unreachable from live run state (the deferred
        compaction scan, SURVEY §7 step 4) — see ``ops/slab.py:mark_sweep``
        for the observably-equivalent argument.  Call between batches on
        long streams; ``CEPProcessor(gc_interval=N)`` does so automatically.
        """
        return self._sweep_jit(state)

    @functools.cached_property
    def _sweep_jit(self):
        from kafkastreams_cep_tpu.utils import tracecache

        depth = self.matcher.config.max_walk
        do_renorm = self.matcher.config.renorm_versions
        # Table-free: one sweep program serves every pattern at the same
        # (max_walk, renorm) — key on just those, not the pattern.
        return tracecache.lookup(
            "batch.sweep", (depth, do_renorm),
            lambda: jax.jit(
                lambda state: sweep_lanes(state, depth, do_renorm)
            ),
        )

    def drain(self, state: EngineState):
        """Materialize every pending lazy-extraction handle in one batched
        pass (``engine/matcher.py: build_drain``) — the deferred analog of
        the eager in-step extraction walks, off the per-step critical
        path.  Returns ``(state, DrainOutput)`` with ``[K]``-leading
        outputs; a no-op on eager or already-drained state."""
        return self._drain_jit(state)

    @functools.cached_property
    def _drain_jit(self):
        import dataclasses as _dc

        from kafkastreams_cep_tpu.utils import tracecache

        cfg = self.matcher.config
        # The drain program is table-free (build_drain) — key on config
        # plus kernel mode only, shared across all patterns.
        dkey = (_dc.astuple(cfg), self.uses_walk_kernel,
                self._kernel_interpret)
        if not self.uses_walk_kernel:
            drain_fn = self.matcher._drain_fn
            return tracecache.lookup(
                "batch.drain", dkey,
                lambda: jax.jit(jax.vmap(drain_fn)),
            )
        from kafkastreams_cep_tpu.ops.walk_kernel import walk_pass_kernel

        HB, W, EH, D = (
            cfg.handle_ring, cfg.max_walk, cfg.slab_hot_entries,
            cfg.dewey_depth,
        )
        interpret = self._kernel_interpret

        def drain(state: EngineState):
            i32 = jnp.int32
            pending = (
                jnp.arange(HB, dtype=i32)[None, :]
                < state.hr_count[:, None]
            )  # [K, HB]
            slab = state.slab
            unpin = jnp.sum(
                (
                    (slab.stage[:, None, :] == state.hr_stage[:, :, None])
                    & (slab.off[:, None, :] == state.hr_off[:, :, None])
                    & pending[:, :, None]
                ).astype(i32),
                axis=1,
            )  # [K, E]
            slab = slab._replace(refs=jnp.maximum(slab.refs - unpin, 0))
            ones = jnp.ones_like(pending)
            slab, out_stage, out_off, count = walk_pass_kernel(
                slab, pending, state.hr_stage, state.hr_off,
                state.hr_ver, state.hr_vlen, ones, ones,
                max_walk=W, out_base=0, out_rows=HB,
                interpret=interpret, hot_entries=EH, drain=True,
            )
            out = DrainOutput(
                stage=out_stage,
                off=out_off,
                count=jnp.where(pending, count, 0),
                seq=jnp.where(pending, state.hr_seq, -1),
                row=jnp.where(pending, state.hr_row, -1),
                ts=jnp.where(pending, state.hr_ts, -1),
            )
            state = state._replace(
                slab=slab,
                hr_stage=jnp.full_like(state.hr_stage, -1),
                hr_off=jnp.full_like(state.hr_off, -1),
                hr_ver=jnp.zeros_like(state.hr_ver),
                hr_vlen=jnp.zeros_like(state.hr_vlen),
                hr_ts=jnp.zeros_like(state.hr_ts),
                hr_seq=jnp.zeros_like(state.hr_seq),
                hr_row=jnp.zeros_like(state.hr_row),
                hr_count=jnp.zeros_like(state.hr_count),
            )
            return state, out

        return tracecache.lookup(
            "batch.drain", dkey, lambda: jax.jit(drain)
        )

    def counters(self, state: EngineState) -> Dict[str, int]:
        """Aggregate overflow/drop counters summed over all lanes."""
        return {
            n: int(jnp.sum(v))
            for n, v in zip(COUNTER_NAMES, counter_values(state))
        }

    def hot_counters(self, state: EngineState) -> Dict[str, int]:
        """Two-tier residency telemetry summed over all lanes (all zero
        when ``slab_hot_entries == 0``)."""
        return {
            n: int(jnp.sum(v))
            for n, v in zip(HOT_COUNTER_NAMES, hot_counter_values(state))
        }

    def walk_counters(self, state: EngineState) -> Dict[str, int]:
        """Walk-cost telemetry summed over all lanes (hop counts by
        walker class; not loss indicators)."""
        return {
            n: int(jnp.sum(v))
            for n, v in zip(WALK_COUNTER_NAMES, walk_counter_values(state))
        }

    def per_lane_counters(self, state: EngineState) -> Dict[str, list]:
        """Per-lane (un-summed) drop + hot counters: ``{name: [K ints]}``
        — which lane is burning capacity, beside the summed view."""
        from kafkastreams_cep_tpu.engine.matcher import per_lane_counter_arrays

        return {
            n: v.reshape(-1).tolist()
            for n, v in per_lane_counter_arrays(state).items()
        }

    def _accumulate_conjuncts(self, events: EventBatch) -> None:
        """Fold one batch into the device-side conjunct tally.  Pure
        async device work — no host sync (``conjunct_counters`` syncs)."""
        if not self._conjunct_slots:
            return
        if self._conjunct_counts is None:
            self._conjunct_counts = jnp.zeros(
                (2, len(self._conjunct_slots)), jnp.int32
            )
        self._conjunct_counts = self._conjunct_tally_jit(
            self._conjunct_counts, events
        )

    def conjunct_counters(self) -> Dict[str, Dict[str, Dict[str, Any]]]:
        """Measured per-conjunct tallies: ``{stage: {conjunct_key:
        {evals, accepts, selectivity}}}``.  Selectivity is the marginal
        (order-independent) accept fraction — the ranking signal
        ``apply_lazy_order`` consumes; ``None`` before any batch.  Empty
        unless ``stage_attribution`` is on."""
        import numpy as np

        if not self._conjunct_slots:
            return {}
        if self._conjunct_counts is None:
            counts = np.zeros((2, len(self._conjunct_slots)), np.int64)
        else:
            counts = np.asarray(jax.device_get(self._conjunct_counts))
        report: Dict[str, Dict[str, Dict[str, Any]]] = {}
        for i, (stage, key, _m) in enumerate(self._conjunct_slots):
            ev, ac = int(counts[0, i]), int(counts[1, i])
            report.setdefault(stage, {})[key] = {
                "evals": ev,
                "accepts": ac,
                "selectivity": (ac / ev) if ev else None,
            }
        return report

    def stage_counters(self, state: EngineState) -> Dict[str, Dict[str, int]]:
        """Per-stage selectivity/cost attribution summed over all lanes
        (``{stage_name: {tally: total, selectivity}}``, plus a
        ``"conjuncts"`` sub-report of measured per-conjunct tallies);
        empty when ``EngineConfig.stage_attribution`` is off."""
        from kafkastreams_cep_tpu.engine.matcher import (
            stage_counter_arrays,
            stage_report,
        )

        report = stage_report(stage_counter_arrays(state), self.names)
        for stage, rows in self.conjunct_counters().items():
            report.setdefault(stage, {})["conjuncts"] = rows
        return report

    def metrics_snapshot(self, state: EngineState) -> Dict[str, object]:
        """Engine-level telemetry of ``state`` in one dict: summed drop and
        hot-tier counters plus the per-lane breakdown (and the per-stage
        attribution roll-up when enabled)."""
        from kafkastreams_cep_tpu.engine.matcher import TIER_COUNTER_NAMES

        out: Dict[str, object] = {}
        out.update(self.counters(state))
        out.update(self.hot_counters(state))
        out.update(self.walk_counters(state))
        # Untiered: the tier counters are structural zeros so dashboards
        # see one schema (TieredBatchMatcher overrides with real values).
        out.update({n: 0 for n in TIER_COUNTER_NAMES})
        out["per_lane"] = self.per_lane_counters(state)
        per_stage = self.stage_counters(state)
        if per_stage:
            out["per_stage"] = per_stage
        return out
