"""Multi-tenant query bank: ONE shared stencil screen for N queries.

The stacked bank (``parallel/stacked.py``) fuses N same-shape queries
into one dispatch but still pays every query's predicate work on every
lane.  This matcher executes the bank *plan*
(``compiler/multitenant.py: plan_bank``) instead:

* **One predicate matrix.**  Every distinct prefix predicate in the bank
  is one column of a dense ``[K, T, C]`` boolean matrix
  (``engine/predmatrix.py``) evaluated ONCE per batch — a predicate
  shared by 100 queries costs what it costs one query.
* **One stencil frontier.**  Each non-NFA query's strict-contiguity
  prefix is a path of column ids; all prefixes of equal length advance
  as one vmapped stencil recurrence over the matrix gather
  (``predmatrix.bank_prefix_scan``).  Pure-stencil queries are *done*
  there — their match grids are synthesized without ever touching an
  engine (``engine/tiered.py: stencil_step_output_stacked``).
* **Grouped residuals.**  Hybrid queries' NFA suffixes stack into
  same-shape engine groups (``engine/matcher.py: _build_step`` stacked
  mode) fed by a stacked promotion step
  (``engine/tiered.py: build_promote_stacked``); whole-NFA queries stack
  into seeded groups.  Each hybrid group is skip-gated exactly like the
  single-query tiered matcher — one scalar ``device_get`` for ALL
  groups' gates per scan.

Parity: per query, matches, emission order, and loss counters are
bit-identical to that query running alone on its own serial matcher
(tests/test_multitenant.py) — the screen math is ``StencilPrefix._scan``
verbatim under a query vmap, the promotions replay ``build_promote``
with one-hot selected per-query constants, and group skip-gating only
ever elides steps that change nothing but ``step_seq``.

**Per-tenant isolation** (:class:`TenantIsolation`): each query may
declare a :class:`~kafkastreams_cep_tpu.compiler.multitenant.TenantQuota`
and the matcher *enforces* it — over-quota tenants get their prefix
fires masked at the gather level inside the shared screen (a ``[Nq]``
runtime mask, zero cost and bit-zero effect on compliant tenants) with
sheds counted per tenant in ``quota_shed``.  Quarantine goes further:
the query's exclusively-owned matrix columns are gated dark
(``predmatrix.build_matrix(disabled=...)``), its lanes' events are
invalidated inside its engine group (per-group ``active`` mask — lanes
are qid-dispatched and independent, so co-members are untouched), and
its frozen engine/carry state stays in the checkpoint for later
:meth:`TenantBankMatcher.reinstate`.  Usage needed for the quota
verdicts (live lanes, ring occupancy, fires/sheds) is computed
device-side inside the screen and rides the SAME ``device_get`` as the
hybrid gates — enforcement adds no device round-trip, only a one-batch
verdict lag (documented in README "Isolation contract").  A bank with a
tenant quarantined (or continuously shed) is bit-identical, for every
other tenant, to a bank compiled without that tenant
(tests/test_tenant_isolation.py differential proof).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kafkastreams_cep_tpu.compiler.multitenant import (
    BankPlan,
    TenantQuota,
    bank_key,
    plan_bank,
)
from kafkastreams_cep_tpu.compiler.tiering import TIER_HYBRID, TIER_NFA
from kafkastreams_cep_tpu.engine.matcher import (
    COUNTER_NAMES,
    HOT_COUNTER_NAMES,
    TIER_COUNTER_NAMES,
    WALK_COUNTER_NAMES,
    DrainOutput,
    EngineConfig,
    EngineState,
    EventBatch,
    StepOutput,
    _build_step,
    build_drain,
    counter_values,
    hot_counter_values,
    per_lane_counter_arrays,
    walk_counter_values,
)
from kafkastreams_cep_tpu.engine.predmatrix import (
    bank_prefix_scan,
    build_matrix,
    group_bools,
    init_carries,
)
from kafkastreams_cep_tpu.engine.stencil import PrefixCarry
from kafkastreams_cep_tpu.engine.tiered import (
    build_promote_stacked,
    seedless_init,
    stencil_step_output_stacked,
)
from kafkastreams_cep_tpu.parallel.batch import (
    _select_walk_kernel,
    kernel_lane_scan,
    kernel_lane_step,
    sweep_lanes,
)
from kafkastreams_cep_tpu.parallel.tiered import _bump_engine_jit
from kafkastreams_cep_tpu.utils import tracecache
from kafkastreams_cep_tpu.utils.failpoints import fire as _failpoint
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("parallel.tenantbank")


class TenantState(NamedTuple):
    """Whole-bank matcher state: one stacked ``[Qg*K]`` engine state per
    residual group plus one ``[Nq, K]`` stencil carry per prefix-length
    group.  A pytree (tuples of NamedTuples), so checkpointing, device
    placement, and ``runtime/migrate.py: widen_state`` compose."""

    engine: Tuple[EngineState, ...]
    carry: Tuple[PrefixCarry, ...]


@dataclasses.dataclass
class _PrefixGroup:
    """All non-NFA queries whose prefixes have the same length ``p``:
    one ``[Nq, K]`` carry, one vmapped recurrence over the matrix."""

    p: int
    qids: List[int]  # original query ids, member order
    sigs: np.ndarray  # [Nq, p] column ids
    stencil_rows: List[int]  # member rows that are pure-stencil
    stencil_qids: List[int]


@dataclasses.dataclass
class _EngineGroup:
    """One stacked residual dispatch: same-shape queries, one program."""

    kind: str  # "hybrid" | "nfa"
    qids: List[int]
    tlist: list
    p: int  # shared prefix length (0 for nfa)
    pg: Optional[int]  # owning prefix-group index (hybrid only)
    rows: List[int]  # member rows inside the prefix group (hybrid only)
    programs: tuple = ()  # (step, init_fn, phases, scan_jit, drain_jit)

    @property
    def Q(self) -> int:
        return len(self.qids)


def _stack_sig(t) -> tuple:
    """The same-shape key ``compiler/tables.py: stackable`` tests."""
    return (
        t.num_stages, t.max_hops, int(t.begin_pos), int(t.final_pos),
    )


class TenantIsolation:
    """Host-side per-tenant enforcement state: token buckets, throttle
    verdicts, quarantine flags, and the per-tenant ``quota_shed`` loss
    ledger.

    Pure deterministic host bookkeeping — the device sees only the
    per-batch ``[Nq]`` enabled masks it produces and hands back the
    usage bundle :meth:`observe` consumes.  :meth:`to_state` round-trips
    through the tenant checkpoint header, so throttle/quarantine
    verdicts and shed counters survive crash/restore and replay
    bit-identically (exactly-once for compliant tenants).

    Verdict timing: the fires/live-lanes/ring usage a verdict needs is
    read back together with the hybrid gates, so throttling reacts with
    a ONE-BATCH lag (the batch that first exceeds a quota completes; the
    next is masked).  The ``pred_eval_budget`` knob is the exception —
    its usage (``K * T * prefix_len``) is known before dispatch, so it
    masks the offending batch itself.
    """

    def __init__(
        self,
        quotas: Sequence[Optional[TenantQuota]],
        num_lanes: int,
        config: EngineConfig,
    ):
        self.quotas: List[Optional[TenantQuota]] = list(quotas)
        N = len(self.quotas)
        self.K = int(num_lanes)
        self.config = config
        self.quota_shed = np.zeros(N, np.int64)
        self.offered_fires = np.zeros(N, np.int64)
        self.throttled = np.zeros(N, bool)
        self.quarantined = np.zeros(N, bool)
        self.over: List[Tuple[str, ...]] = [() for _ in range(N)]
        self.live_lanes = np.zeros(N, np.int64)
        self.ring_pending = np.zeros(N, np.int64)
        self.tokens = np.full(N, np.inf)
        self.throttle_transitions = 0
        for q, quota in enumerate(self.quotas):
            if quota is None or quota.match_rate_budget is None:
                continue
            self.tokens[q] = quota.burst
            if quota.burst < 1.0:
                # A zero/sub-1 budget sheds from the very first batch —
                # the deterministic "continuously shed" configuration the
                # differential blast-radius proof uses.
                self.throttled[q] = True
                self.over[q] = ("match_rate_budget",)

    # -- per-batch verdicts --------------------------------------------------

    def enabled(self, qids: Sequence[int], p: int, T: int) -> np.ndarray:
        """The ``[Nq]`` fire mask for one prefix group this batch."""
        m = np.ones(len(qids), bool)
        for i, q in enumerate(qids):
            if self.quarantined[q] or self.throttled[q]:
                m[i] = False
                continue
            quota = self.quotas[q]
            if (
                quota is not None
                and quota.pred_eval_budget is not None
                and self.K * T * p > quota.pred_eval_budget
            ):
                m[i] = False
        return m

    def observe(
        self,
        fires: np.ndarray,
        sheds: np.ndarray,
        live: np.ndarray,
        ring: np.ndarray,
    ) -> None:
        """Fold one batch's usage readback into the ledgers and
        recompute every quotaed tenant's verdict for the next batch."""
        fires = fires.astype(np.int64)
        sheds = sheds.astype(np.int64)
        self.offered_fires += fires + sheds
        self.quota_shed += sheds
        self.live_lanes = live.astype(np.int64)
        self.ring_pending = ring.astype(np.int64)
        for q, quota in enumerate(self.quotas):
            if quota is None or self.quarantined[q]:
                continue
            over: List[str] = []
            if quota.match_rate_budget is not None:
                self.tokens[q] = min(
                    quota.burst, self.tokens[q] + quota.match_rate_budget
                ) - float(fires[q])
                if self.tokens[q] < 1.0:
                    over.append("match_rate_budget")
            if (
                quota.max_live_lanes is not None
                and self.live_lanes[q] > quota.max_live_lanes
            ):
                over.append("max_live_lanes")
            if (
                quota.handle_ring_share is not None
                and self.config.handle_ring > 0
            ):
                cap = (
                    quota.handle_ring_share
                    * self.K
                    * self.config.handle_ring
                )
                if self.ring_pending[q] > cap:
                    over.append("handle_ring_share")
            was = bool(self.throttled[q])
            self.throttled[q] = bool(over)
            self.over[q] = tuple(over)
            if was != self.throttled[q]:
                self.throttle_transitions += 1
                logger.warning(
                    "tenant q%d %s (over: %s)",
                    q,
                    "throttled" if over else "unthrottled",
                    over or "-",
                )

    # -- durability ----------------------------------------------------------

    def to_state(self) -> Dict[str, object]:
        return {
            "quota_shed": self.quota_shed.copy(),
            "offered_fires": self.offered_fires.copy(),
            "throttled": self.throttled.copy(),
            "quarantined": self.quarantined.copy(),
            "tokens": self.tokens.copy(),
            "live_lanes": self.live_lanes.copy(),
            "ring_pending": self.ring_pending.copy(),
            "over": [tuple(o) for o in self.over],
            "throttle_transitions": self.throttle_transitions,
        }

    def load_state(self, state: Dict[str, object]) -> None:
        self.quota_shed = np.asarray(state["quota_shed"], np.int64).copy()
        self.offered_fires = np.asarray(
            state["offered_fires"], np.int64
        ).copy()
        self.throttled = np.asarray(state["throttled"], bool).copy()
        self.quarantined = np.asarray(state["quarantined"], bool).copy()
        self.tokens = np.asarray(state["tokens"], np.float64).copy()
        self.live_lanes = np.asarray(state["live_lanes"], np.int64).copy()
        self.ring_pending = np.asarray(
            state["ring_pending"], np.int64
        ).copy()
        self.over = [tuple(o) for o in state["over"]]
        self.throttle_transitions = int(state["throttle_transitions"])


def _build_group_programs(
    group: _EngineGroup, cfg: EngineConfig, K: int
):
    """Step + scan + drain programs for one engine group.

    The hybrid scan replicates the ``[K, T]`` events across members
    inside the jit, gathers the group's promotion rows out of the owning
    prefix group's ``[Np, K, T, ...]`` tensor (static member rows), and
    runs the step-then-promote schedule of the single-query tiered
    matcher per lane — qid-dispatched, so each lane is its own query.

    Both scans take a runtime ``active [Qg]`` member mask (tenant
    quarantine): an inactive member's lanes see their events invalidated
    (and its promotion fires zeroed), freezing its runs in place without
    retracing — lanes are qid-dispatched and independent, so active
    members step bit-identically to an all-active group.
    """
    Qg = group.Q
    L = Qg * K
    step, init_fn, phases = _build_step(group.tlist, cfg)
    qids = jnp.repeat(jnp.arange(Qg, dtype=jnp.int32), K)
    use_kernel, interpret = _select_walk_kernel(cfg, L)

    def rep(events, active):
        ev = jax.tree_util.tree_map(
            lambda x: jnp.concatenate([x] * Qg, axis=0), events
        )
        lane_on = jnp.repeat(jnp.asarray(active, bool), K)[:, None]
        return ev._replace(valid=ev.valid & lane_on)

    def unstack(out):
        return jax.tree_util.tree_map(
            lambda x: x.reshape((Qg, K) + x.shape[1:]), out
        )

    if group.kind == "nfa":
        if use_kernel:
            bstep = kernel_lane_step(phases, interpret, qids=qids)
            inner_scan = kernel_lane_scan(bstep)
        else:

            def inner_scan(state, events):
                return jax.vmap(
                    lambda s, e, q: jax.lax.scan(
                        lambda c, x: step(c, x, q), s, e
                    )
                )(state, events, qids)

        def scan(state: EngineState, events: EventBatch, active):
            state, out = inner_scan(state, rep(events, active))
            return state, unstack(out)

        scan_jit = jax.jit(scan)
    else:
        if use_kernel:
            base_step = kernel_lane_step(phases, interpret, qids=qids)
        else:

            def base_step(s, ev):
                return jax.vmap(step)(s, ev, qids)

        promote_b = jax.vmap(
            build_promote_stacked(group.tlist, cfg, group.p)
        )
        rows_ix = jnp.asarray(group.rows, jnp.int32)

        def scan(eng: EngineState, events: EventBatch, promo_pg, active):
            ev = rep(events, active)
            # [Np, K, T, ...] -> member rows -> flat [Qg*K, T, ...].
            pr = jax.tree_util.tree_map(
                lambda x: x[rows_ix].reshape((L,) + x.shape[2:]),
                promo_pg,
            )
            lane_on = jnp.repeat(jnp.asarray(active, bool), K)[:, None]
            pr = pr._replace(fire=pr.fire & lane_on)
            swap = lambda x: jnp.swapaxes(x, 0, 1)
            ev_t = jax.tree_util.tree_map(swap, ev)
            pr_t = jax.tree_util.tree_map(swap, pr)

            def body(s, x):
                ev1, pr1 = x
                # Step first, then promote: the prefix completes *at*
                # event t and the promoted run first evaluates at t+1 —
                # the untiered run's schedule (parallel/tiered.py).
                s, out = base_step(s, ev1)
                s, n = promote_b(
                    s, pr1.fire, pr1.offs, pr1.anchor_ts, pr1.sver, qids
                )
                return s, (out, n)

            eng, (outs, ns) = jax.lax.scan(body, eng, (ev_t, pr_t))
            outs = unstack(jax.tree_util.tree_map(swap, outs))
            promoted = jnp.sum(ns, axis=0).reshape(Qg, K)
            return eng, outs, promoted

        scan_jit = jax.jit(scan)

    drain_jit = jax.jit(jax.vmap(build_drain(cfg)))
    return (step, init_fn, phases, scan_jit, drain_jit)


class TenantBankMatcher:
    """N queries x ``K`` lanes under one bank plan (one chip).

    Drop-in for :class:`~kafkastreams_cep_tpu.parallel.stacked.
    StackedBankMatcher` (same ``scan``/``init_state``/``drain``/counters
    surface, ``[N, K, T, R, W]`` outputs decoded per query with
    :meth:`names_of`) without its same-shape requirement: queries group
    by shape internally and the whole bank shares one prefix screen.

    ``names`` optionally labels queries for the per-query telemetry
    breakdown (defaults to ``q0..qN-1``).  ``quotas`` optionally
    declares the per-tenant isolation contract — a dict keyed by query
    name (or a sequence aligned with ``patterns``) of
    :class:`~kafkastreams_cep_tpu.compiler.multitenant.TenantQuota`;
    declared quotas are attached to the bank plan and ENFORCED here
    (fires masked, sheds counted in ``quota_shed`` — see
    :class:`TenantIsolation`).
    """

    def __init__(
        self,
        patterns: Sequence,
        lanes_per_query: int,
        config: Optional[EngineConfig] = None,
        profile: Optional[Dict] = None,
        reorder: bool = True,
        names: Optional[Sequence[str]] = None,
        quotas=None,
    ):
        self.config = config or EngineConfig()
        self.K = int(lanes_per_query)
        patterns = list(patterns)
        self.query_names = (
            list(names)
            if names is not None
            else [f"q{q}" for q in range(len(patterns))]
        )
        if len(self.query_names) != len(patterns):
            raise ValueError("names must have one entry per pattern")
        if quotas is None:
            qlist: List[Optional[TenantQuota]] = [None] * len(patterns)
        elif isinstance(quotas, dict):
            unknown = set(quotas) - set(self.query_names)
            if unknown:
                raise ValueError(
                    f"quotas for unknown queries: {sorted(unknown)}"
                )
            qlist = [quotas.get(n) for n in self.query_names]
        else:
            qlist = list(quotas)
            if len(qlist) != len(patterns):
                raise ValueError(
                    "quotas must have one entry per pattern"
                )
        self.bank: BankPlan = plan_bank(
            patterns, self.config, profile, reorder, quotas=qlist
        )
        self.N = len(self.bank.queries)
        self.iso = TenantIsolation(
            [qp.quota for qp in self.bank.queries], self.K, self.config
        )
        self.scan_calls = 0
        self.nfa_dispatches = 0

        # -- prefix-length groups (the shared screen frontier) --------------
        by_p: Dict[int, List[int]] = {}
        for q, qp in enumerate(self.bank.queries):
            if qp.plan.tier != TIER_NFA:
                by_p.setdefault(qp.plan.prefix_len, []).append(q)
        self._pgroups: List[_PrefixGroup] = []
        for p in sorted(by_p):
            qids = by_p[p]
            sigs = np.asarray(
                [self.bank.queries[q].prefix_cols for q in qids],
                np.int32,
            )
            srows = [
                i
                for i, q in enumerate(qids)
                if self.bank.queries[q].plan.tier != TIER_HYBRID
            ]
            self._pgroups.append(
                _PrefixGroup(
                    p=p, qids=qids, sigs=sigs, stencil_rows=srows,
                    stencil_qids=[qids[i] for i in srows],
                )
            )
        member_row = {
            (i, q): r
            for i, pg in enumerate(self._pgroups)
            for r, q in enumerate(pg.qids)
        }

        # -- residual engine groups -----------------------------------------
        groups: Dict[tuple, _EngineGroup] = {}
        for q, qp in enumerate(self.bank.queries):
            if qp.plan.tier == TIER_HYBRID:
                pgi = next(
                    i
                    for i, pg in enumerate(self._pgroups)
                    if q in pg.qids
                )
                key = (
                    "hybrid", qp.plan.prefix_len, _stack_sig(qp.tables),
                )
                g = groups.setdefault(
                    key,
                    _EngineGroup(
                        kind="hybrid", qids=[], tlist=[],
                        p=qp.plan.prefix_len, pg=pgi, rows=[],
                    ),
                )
                g.qids.append(q)
                g.tlist.append(qp.tables)
                g.rows.append(member_row[(pgi, q)])
            elif qp.plan.tier == TIER_NFA:
                key = ("nfa", _stack_sig(qp.tables))
                g = groups.setdefault(
                    key,
                    _EngineGroup(
                        kind="nfa", qids=[], tlist=[], p=0, pg=None,
                        rows=[],
                    ),
                )
                g.qids.append(q)
                g.tlist.append(qp.tables)
        self._groups: List[_EngineGroup] = list(groups.values())
        for g in self._groups:
            g.programs = self._cached_group_programs(g)
        self._hybrid_idx = [
            i for i, g in enumerate(self._groups) if g.kind == "hybrid"
        ]

        logger.info(
            "tenant bank: %d queries -> %d prefix groups (%d columns, "
            "shared hit rate %.2f), %d engine groups (%d hybrid), "
            "predicate dedup %.2fx",
            self.N, len(self._pgroups),
            self.bank.stats["prefix_columns_distinct"],
            self.bank.stats["prefix_shared_hit_rate"],
            len(self._groups), len(self._hybrid_idx),
            self.bank.stats["pred_dedup_ratio"],
        )

        # Column -> referencing queries (quarantine gates a column dark
        # only when EVERY user is quarantined; a column shared with a
        # live tenant keeps evaluating — the live tenant paid for it).
        self._col_users: Dict[int, set] = {}
        for q, qp in enumerate(self.bank.queries):
            for cid in qp.prefix_cols:
                self._col_users.setdefault(int(cid), set()).add(q)
        self._disabled_cols: frozenset = frozenset()
        self._gactive: List[np.ndarray] = [
            np.ones(g.Q, bool) for g in self._groups
        ]
        self._screen_jit = self._cached_screen()

    # -- program construction (trace-cached) ---------------------------------

    def _struct_key(self):
        bkey = bank_key([qp.tables for qp in self.bank.queries])
        if bkey is None:
            return None
        struct = (
            tuple(
                (pg.p, pg.sigs.tobytes(), tuple(pg.stencil_rows))
                for pg in self._pgroups
            ),
            tuple(
                (g.kind, g.p, g.pg, tuple(g.rows), tuple(g.qids))
                for g in self._groups
            ),
        )
        return (bkey, dataclasses.astuple(self.config), struct)

    def _cached_group_programs(self, g: _EngineGroup):
        key = bank_key(g.tlist)
        if key is not None:
            # K is part of the key: the group's per-lane qid table and
            # the walk-kernel feasibility decision are baked into the
            # closure at [Qg*K] lanes.
            key = (
                key, dataclasses.astuple(self.config), g.kind, g.p,
                tuple(g.rows), self.K,
                _select_walk_kernel(self.config, g.Q * self.K),
            )
        return tracecache.lookup(
            "tenant.group_programs", key,
            lambda: _build_group_programs(g, self.config, self.K),
        )

    def _cached_screen(self):
        if not self._pgroups:
            return None
        key = self._struct_key()
        if key is not None:
            # The disabled-column set is baked into the matrix closure
            # (quarantined tenants' private columns are constant False),
            # and K into the gate/usage reshapes, so both must join the
            # structural key.
            key = (key, tuple(sorted(self._disabled_cols)), self.K)
        return tracecache.lookup(
            "tenant.screen", key, lambda: jax.jit(self._build_screen())
        )

    def _build_screen(self):
        """The whole-bank screen: matrix -> per-p-group recurrence ->
        fire-mask enforcement -> stencil synthesis + hybrid gates + the
        usage bundle, one fused program.

        ``masks[i]`` is prefix group ``i``'s ``[Nq]`` enabled mask (a
        runtime arg — no retrace on a throttle flip); a masked member's
        fires are zeroed before synthesis/promotion and counted in the
        shed half of the usage bundle.  ``hactive`` masks a quarantined
        member's frozen alive runs out of its group's gate so it cannot
        force dispatches forever.  Everything the quota verdicts need
        (fires, sheds, live lanes, ring occupancy) is computed here and
        returned with the gates — ONE ``device_get`` per scan, exactly
        as before.
        """
        owner_tables = [qp.tables for qp in self.bank.queries]
        matrix_fn = build_matrix(
            self.bank.columns, owner_tables,
            disabled=self._disabled_cols,
        )
        scans = [bank_prefix_scan(pg.p) for pg in self._pgroups]
        synths = []
        for pg in self._pgroups:
            if pg.stencil_qids:
                synths.append(
                    (
                        jnp.asarray(pg.stencil_rows, jnp.int32),
                        stencil_step_output_stacked(
                            [
                                self.bank.queries[q].tables
                                for q in pg.stencil_qids
                            ],
                            self.config, pg.p,
                        ),
                    )
                )
            else:
                synths.append(None)
        hybrids = [
            (i, self._groups[i].pg,
             jnp.asarray(self._groups[i].rows, jnp.int32))
            for i in self._hybrid_idx
        ]
        sig_tables = [pg.sigs for pg in self._pgroups]
        gQ = [g.Q for g in self._groups]
        K = self.K

        def screen(carries, galive, gring, ev: EventBatch, masks, hactive):
            mat = matrix_fn(ev)
            new_carries, promos, souts = [], [], []
            fires_u, sheds_u = [], []
            for i, (scan, synth) in enumerate(zip(scans, synths)):
                bools_q = group_bools(mat, sig_tables[i])
                c2, promo = scan(carries[i], bools_q, ev)
                m3 = masks[i][:, None, None]
                sheds_u.append(
                    jnp.sum(promo.fire & ~m3, axis=(1, 2))
                )
                promo = promo._replace(fire=promo.fire & m3)
                fires_u.append(jnp.sum(promo.fire, axis=(1, 2)))
                new_carries.append(c2)
                promos.append(promo)
                if synth is None:
                    souts.append(None)
                else:
                    srows, synth_fn = synth
                    souts.append(
                        synth_fn(
                            jax.tree_util.tree_map(
                                lambda x: x[srows], promo
                            )
                        )
                    )
            if hybrids:
                gates = jnp.stack(
                    [
                        jnp.any(
                            galive[gi]
                            & jnp.repeat(hactive[h], K)[:, None]
                        )
                        | jnp.any(promos[pgi].fire[rows])
                        for h, (gi, pgi, rows) in enumerate(hybrids)
                    ]
                )
            else:
                gates = jnp.zeros((0,), bool)
            live_u = tuple(
                jnp.sum(
                    jnp.any(a, axis=-1).reshape(q, K).astype(jnp.int32),
                    axis=1,
                )
                for a, q in zip(galive, gQ)
            )
            ring_u = tuple(
                jnp.sum(r.reshape(q, K), axis=1)
                for r, q in zip(gring, gQ)
            )
            usage = (tuple(fires_u), tuple(sheds_u), live_u, ring_u)
            return (
                tuple(new_carries), tuple(promos), tuple(souts), gates,
                usage,
            )

        return screen

    # -- state ----------------------------------------------------------------

    def names_of(self, q: int) -> List[str]:
        return self.bank.queries[q].tables.names

    def tier_of(self, q: int) -> str:
        return self.bank.queries[q].plan.tier

    def init_state(self) -> TenantState:
        engines = []
        for g in self._groups:
            _, init_fn, _, _, _ = g.programs
            per_q = []
            for lq in range(g.Q):
                s = (
                    init_fn(lq)
                    if g.kind == "nfa"
                    # Hybrid: the begin stage lives on the stencil tier,
                    # so the group queue starts empty (engine/tiered.py).
                    else seedless_init(lambda lq=lq: init_fn(lq))
                )
                per_q.append(s)
            engines.append(
                jax.tree_util.tree_map(
                    lambda *xs: jnp.concatenate(
                        [
                            jnp.broadcast_to(x, (self.K,) + x.shape)
                            for x in xs
                        ]
                    ),
                    *per_q,
                )
            )
        carries = tuple(
            init_carries(len(pg.qids), self.K, pg.p)
            for pg in self._pgroups
        )
        return TenantState(engine=tuple(engines), carry=carries)

    # -- the scan --------------------------------------------------------------

    def _zero_group_out(self, Qg: int, T: int) -> StepOutput:
        cfg = self.config
        K, R, W = self.K, cfg.max_runs, cfg.max_walk
        i32 = jnp.int32
        return StepOutput(
            stage=jnp.full((Qg, K, T, R, W), -1, i32),
            off=jnp.full((Qg, K, T, R, W), -1, i32),
            count=jnp.zeros((Qg, K, T, R), i32),
        )

    def scan(self, state: TenantState, events: EventBatch):
        """One ``[K, T]`` batch through the whole bank.  Every query sees
        every record (the reference's one-processor-per-pattern topology);
        outputs come back ``[N, K, T, R, W]`` in original query order.
        Host-gated like the single-query tiered matcher, so not itself
        jittable."""
        T = int(events.ts.shape[1])
        self.scan_calls += 1
        masks_np = [
            self.iso.enabled(pg.qids, pg.p, T) for pg in self._pgroups
        ]
        if self._screen_jit is not None:
            galive = tuple(e.alive for e in state.engine)
            gring = tuple(e.hr_count for e in state.engine)
            masks = tuple(jnp.asarray(m) for m in masks_np)
            hactive = tuple(
                jnp.asarray(self._gactive[i]) for i in self._hybrid_idx
            )
            carries, promos, souts, gates, usage = self._screen_jit(
                state.carry, galive, gring, events, masks, hactive
            )
            carries = list(carries)
            # ONE transfer: the hybrid gates AND the quota usage bundle
            # ride the same device_get (the zero-extra-sync contract).
            gates_h, usage_h = jax.device_get((gates, usage))
            gates_h = np.asarray(gates_h)
        else:
            carries, promos, souts, gates_h, usage_h = (
                [], (), (), np.zeros(0), None
            )

        blocks: List[Tuple[List[int], StepOutput]] = []
        for pg, so in zip(self._pgroups, souts):
            if so is not None:
                blocks.append((pg.stencil_qids, so))

        engines = list(state.engine)
        hseq = 0
        for i, g in enumerate(self._groups):
            active = jnp.asarray(self._gactive[i])
            if g.kind == "nfa":
                self.nfa_dispatches += 1
                _, _, _, scan_jit, _ = g.programs
                engines[i], out_g = scan_jit(engines[i], events, active)
                blocks.append((g.qids, out_g))
                continue
            gate = bool(gates_h[hseq])
            hseq += 1
            if not gate:
                # Exact skip: stepping an empty, promotion-free group
                # changes nothing but step_seq (parallel/tiered.py).
                engines[i] = _bump_engine_jit()(
                    engines[i], jnp.int32(T)
                )
                blocks.append((g.qids, self._zero_group_out(g.Q, T)))
                continue
            self.nfa_dispatches += 1
            _, _, _, scan_jit, _ = g.programs
            engines[i], out_g, promoted = scan_jit(
                engines[i], events, promos[g.pg], active
            )
            c = carries[g.pg]
            carries[g.pg] = c._replace(
                promotions=c.promotions.at[
                    jnp.asarray(g.rows, jnp.int32)
                ].add(promoted)
            )
            blocks.append((g.qids, out_g))

        self._observe_usage(usage_h)
        out = self._assemble(blocks)
        return (
            TenantState(engine=tuple(engines), carry=tuple(carries)),
            out,
        )

    def _observe_usage(self, usage_h) -> None:
        """Scatter the screen's per-group usage bundle back to global
        query ids and let the isolation controller re-verdict."""
        if usage_h is None:
            return
        fires_u, sheds_u, live_u, ring_u = usage_h
        fires = np.zeros(self.N, np.int64)
        sheds = np.zeros(self.N, np.int64)
        live = np.zeros(self.N, np.int64)
        ring = np.zeros(self.N, np.int64)
        for pg, f, s in zip(self._pgroups, fires_u, sheds_u):
            f = np.asarray(f)
            s = np.asarray(s)
            for r, q in enumerate(pg.qids):
                fires[q] = f[r]
                sheds[q] = s[r]
        for g, lv, rg in zip(self._groups, live_u, ring_u):
            lv = np.asarray(lv)
            rg = np.asarray(rg)
            for r, q in enumerate(g.qids):
                live[q] = lv[r]
                ring[q] = rg[r]
        self.iso.observe(fires, sheds, live, ring)

    # -- quarantine / reinstatement -------------------------------------------

    @property
    def quarantined_qids(self) -> List[int]:
        return [int(q) for q in np.nonzero(self.iso.quarantined)[0]]

    def quarantine(self, q: int) -> None:
        """Circuit-break query ``q`` out of the bank: its exclusively
        owned matrix columns go dark (the predicate is never called
        again — a poisoned predicate cannot raise at trace time), its
        lanes' events are invalidated in its engine group, and its fires
        are masked.  Engine/carry state freezes in place (and stays in
        checkpoints) for later :meth:`reinstate`.  The rest of the bank
        is bit-identical to a bank compiled without ``q``."""
        q = int(q)
        if not 0 <= q < self.N:
            raise ValueError(f"no query {q} in a bank of {self.N}")
        if self.iso.quarantined[q]:
            return
        _failpoint("quarantine.enter")
        self.iso.quarantined[q] = True
        logger.warning(
            "tenant %s (q%d) quarantined", self.query_names[q], q
        )
        self._rebuild_enforcement()

    def reinstate(self, q: int) -> None:
        """Lift query ``q``'s quarantine: columns re-enabled, lanes
        re-activated, frozen state resumes (expired windows prune on the
        first post-reinstatement event, exactly as a live run's would)."""
        q = int(q)
        if not 0 <= q < self.N or not self.iso.quarantined[q]:
            return
        self.iso.quarantined[q] = False
        self.iso.throttled[q] = False  # re-verdicted next batch
        self.iso.over[q] = ()
        logger.info(
            "tenant %s (q%d) reinstated", self.query_names[q], q
        )
        self._rebuild_enforcement()

    def _rebuild_enforcement(self) -> None:
        """Recompute the quarantine-derived structures: the disabled
        column set (columns every user of which is quarantined), the
        per-group member activity masks, and the screen program (the
        disabled set is baked into the matrix closure)."""
        quarantined = set(self.quarantined_qids)
        self._disabled_cols = frozenset(
            cid
            for cid, users in self._col_users.items()
            if users and users <= quarantined
        )
        self._gactive = [
            np.asarray([q not in quarantined for q in g.qids], bool)
            for g in self._groups
        ]
        self._screen_jit = self._cached_screen()

    def iso_state(self) -> Dict[str, object]:
        """The enforcement ledger for the checkpoint header."""
        return self.iso.to_state()

    def load_iso_state(self, state: Dict[str, object]) -> None:
        """Restore the enforcement ledger (checkpoint restore / widen
        migration) and rebuild the derived quarantine structures —
        without firing ``quarantine.enter`` (no NEW quarantine decision
        is being made)."""
        self.iso.load_state(state)
        self._rebuild_enforcement()

    def _assemble(self, blocks):
        """Concatenate per-group ``[n, ...]`` output blocks and permute
        back to original query order along the leading axis."""
        order = np.concatenate(
            [np.asarray(qids, np.int64) for qids, _ in blocks]
        )
        inv = jnp.asarray(np.argsort(order), jnp.int32)
        parts = [out for _, out in blocks]
        if len(parts) == 1:
            return jax.tree_util.tree_map(lambda x: x[inv], parts[0])
        return jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0)[inv], *parts
        )

    # -- maintenance / drains --------------------------------------------------

    def sweep(self, state: TenantState) -> TenantState:
        """Engine-group maintenance sweeps; stencil carries hold no slab
        references (partial prefixes own no entries) so they ride along
        untouched."""
        depth = self.config.max_walk
        do_renorm = self.config.renorm_versions
        swp = tracecache.lookup(
            "batch.sweep", (depth, do_renorm),
            lambda: jax.jit(
                lambda s: sweep_lanes(s, depth, do_renorm)
            ),
        )
        return state._replace(
            engine=tuple(swp(e) for e in state.engine)
        )

    def _zero_drain(self, n: int) -> DrainOutput:
        cfg = self.config
        HB, W = cfg.handle_ring, cfg.max_walk
        i32 = jnp.int32
        full = lambda shape: jnp.full(shape, -1, i32)
        return DrainOutput(
            stage=full((n, self.K, HB, W)),
            off=full((n, self.K, HB, W)),
            count=jnp.zeros((n, self.K, HB), i32),
            seq=full((n, self.K, HB)),
            row=full((n, self.K, HB)),
            ts=full((n, self.K, HB)),
        )

    def drain(self, state: TenantState):
        """Materialize pending lazy-extraction handles for every group;
        returns ``[N, K, ...]`` outputs in query order (pure-stencil
        queries never own handles — their rows are the empty drain)."""
        engines = list(state.engine)
        blocks: List[Tuple[List[int], DrainOutput]] = []
        covered: set = set()
        for i, g in enumerate(self._groups):
            _, _, _, _, drain_jit = g.programs
            engines[i], d = drain_jit(engines[i])
            blocks.append(
                (
                    g.qids,
                    jax.tree_util.tree_map(
                        lambda x: x.reshape(
                            (g.Q, self.K) + x.shape[1:]
                        ),
                        d,
                    ),
                )
            )
            covered.update(g.qids)
        rest = [q for q in range(self.N) if q not in covered]
        if rest:
            blocks.append((rest, self._zero_drain(len(rest))))
        out = self._assemble(blocks)
        return state._replace(engine=tuple(engines)), out

    # -- telemetry -------------------------------------------------------------

    def _summed(self, state: TenantState, names, values_fn):
        tot = dict.fromkeys(names, 0)
        for eng in state.engine:
            for n, v in zip(names, values_fn(eng)):
                tot[n] += int(jnp.sum(v))
        return tot

    def counters(self, state: TenantState) -> Dict[str, int]:
        return self._summed(state, COUNTER_NAMES, counter_values)

    def hot_counters(self, state: TenantState) -> Dict[str, int]:
        return self._summed(
            state, HOT_COUNTER_NAMES, hot_counter_values
        )

    def walk_counters(self, state: TenantState) -> Dict[str, int]:
        return self._summed(
            state, WALK_COUNTER_NAMES, walk_counter_values
        )

    def tier_counters(self, state: TenantState) -> Dict[str, int]:
        vals = [0, 0, 0]
        for c in state.carry:
            got = jax.device_get(
                (
                    jnp.sum(c.screened), jnp.sum(c.fires),
                    jnp.sum(c.promotions),
                )
            )
            vals = [a + int(b) for a, b in zip(vals, got)]
        return dict(zip(TIER_COUNTER_NAMES, vals))

    def per_query_counters(
        self, state: TenantState
    ) -> Dict[str, Dict[str, int]]:
        """Per-query attribution across the whole bank: loss + hot +
        walk counters summed over each query's ``K``-lane block of its
        group, plus that query's stencil-tier telemetry.  Queries with
        no residual engine (pure stencil) report zero engine counters."""
        names = COUNTER_NAMES + HOT_COUNTER_NAMES + WALK_COUNTER_NAMES
        per_q: Dict[int, Dict[str, int]] = {
            q: dict.fromkeys(names, 0) for q in range(self.N)
        }
        for g, eng in zip(self._groups, state.engine):
            arrays = per_lane_counter_arrays(eng)
            for r, q in enumerate(g.qids):
                for n, v in arrays.items():
                    per_q[q][n] = int(
                        v.reshape(g.Q, self.K)[r].sum()
                    )
        tier_zero = dict.fromkeys(TIER_COUNTER_NAMES, 0)
        for q in range(self.N):
            per_q[q].update(tier_zero)
        for pg, c in zip(self._pgroups, state.carry):
            scr, fr, pr = jax.device_get(
                (
                    jnp.sum(c.screened, axis=1),
                    jnp.sum(c.fires, axis=1),
                    jnp.sum(c.promotions, axis=1),
                )
            )
            for r, q in enumerate(pg.qids):
                per_q[q][TIER_COUNTER_NAMES[0]] = int(scr[r])
                per_q[q][TIER_COUNTER_NAMES[1]] = int(fr[r])
                per_q[q][TIER_COUNTER_NAMES[2]] = int(pr[r])
        for q in range(self.N):
            per_q[q]["quota_shed"] = int(self.iso.quota_shed[q])
            per_q[q]["quota_throttled"] = int(self.iso.throttled[q])
            per_q[q]["quarantined"] = int(self.iso.quarantined[q])
        return {
            self.query_names[q]: per_q[q] for q in range(self.N)
        }

    def metrics_snapshot(self, state: TenantState) -> Dict[str, object]:
        """Bank-wide telemetry: merged engine counters, shared-screen
        tier counters, compile-time sharing stats, and the ``per_query``
        breakdown (rendered as ``cep_*{query="..."}`` by
        ``utils/telemetry.py``)."""
        out: Dict[str, object] = {}
        out.update(self.counters(state))
        out.update(self.hot_counters(state))
        out.update(self.walk_counters(state))
        out.update(self.tier_counters(state))
        out["bank_queries"] = self.N
        out["bank_prefix_groups"] = len(self._pgroups)
        out["bank_engine_groups"] = len(self._groups)
        out["bank_pred_dedup_ratio"] = float(
            self.bank.stats["pred_dedup_ratio"]
        )
        out["bank_prefix_shared_hit_rate"] = float(
            self.bank.stats["prefix_shared_hit_rate"]
        )
        out["quota_shed_total"] = int(self.iso.quota_shed.sum())
        out["quota_throttled_queries"] = int(self.iso.throttled.sum())
        out["quarantined_queries"] = int(self.iso.quarantined.sum())
        out["quota_throttle_transitions"] = int(
            self.iso.throttle_transitions
        )
        # Measured dispatch gating (the PR 10 screen→NFA gate, bank form):
        # each scan offers every engine group one dispatch opportunity;
        # the fraction actually dispatched is the headroom number the
        # gate-chunk autotuning roadmap item keys on.
        out["bank_scan_calls"] = int(self.scan_calls)
        out["bank_nfa_dispatches"] = int(self.nfa_dispatches)
        opportunities = int(self.scan_calls) * max(len(self._groups), 1)
        out["bank_nfa_dispatch_fraction"] = (
            round(int(self.nfa_dispatches) / opportunities, 6)
            if opportunities
            else None
        )
        out["per_query"] = self.per_query_counters(state)
        return out
