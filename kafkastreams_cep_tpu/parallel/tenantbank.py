"""Multi-tenant query bank: ONE shared stencil screen for N queries.

The stacked bank (``parallel/stacked.py``) fuses N same-shape queries
into one dispatch but still pays every query's predicate work on every
lane.  This matcher executes the bank *plan*
(``compiler/multitenant.py: plan_bank``) instead:

* **One predicate matrix.**  Every distinct prefix predicate in the bank
  is one column of a dense ``[K, T, C]`` boolean matrix
  (``engine/predmatrix.py``) evaluated ONCE per batch — a predicate
  shared by 100 queries costs what it costs one query.
* **One stencil frontier.**  Each non-NFA query's strict-contiguity
  prefix is a path of column ids; all prefixes of equal length advance
  as one vmapped stencil recurrence over the matrix gather
  (``predmatrix.bank_prefix_scan``).  Pure-stencil queries are *done*
  there — their match grids are synthesized without ever touching an
  engine (``engine/tiered.py: stencil_step_output_stacked``).
* **Grouped residuals.**  Hybrid queries' NFA suffixes stack into
  same-shape engine groups (``engine/matcher.py: _build_step`` stacked
  mode) fed by a stacked promotion step
  (``engine/tiered.py: build_promote_stacked``); whole-NFA queries stack
  into seeded groups.  Each hybrid group is skip-gated exactly like the
  single-query tiered matcher — one scalar ``device_get`` for ALL
  groups' gates per scan.

Parity: per query, matches, emission order, and loss counters are
bit-identical to that query running alone on its own serial matcher
(tests/test_multitenant.py) — the screen math is ``StencilPrefix._scan``
verbatim under a query vmap, the promotions replay ``build_promote``
with one-hot selected per-query constants, and group skip-gating only
ever elides steps that change nothing but ``step_seq``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kafkastreams_cep_tpu.compiler.multitenant import (
    BankPlan,
    bank_key,
    plan_bank,
)
from kafkastreams_cep_tpu.compiler.tiering import TIER_HYBRID, TIER_NFA
from kafkastreams_cep_tpu.engine.matcher import (
    COUNTER_NAMES,
    HOT_COUNTER_NAMES,
    TIER_COUNTER_NAMES,
    WALK_COUNTER_NAMES,
    DrainOutput,
    EngineConfig,
    EngineState,
    EventBatch,
    StepOutput,
    _build_step,
    build_drain,
    counter_values,
    hot_counter_values,
    per_lane_counter_arrays,
    walk_counter_values,
)
from kafkastreams_cep_tpu.engine.predmatrix import (
    bank_prefix_scan,
    build_matrix,
    group_bools,
    init_carries,
)
from kafkastreams_cep_tpu.engine.stencil import PrefixCarry
from kafkastreams_cep_tpu.engine.tiered import (
    build_promote_stacked,
    seedless_init,
    stencil_step_output_stacked,
)
from kafkastreams_cep_tpu.parallel.batch import (
    _select_walk_kernel,
    kernel_lane_scan,
    kernel_lane_step,
    sweep_lanes,
)
from kafkastreams_cep_tpu.parallel.tiered import _bump_engine_jit
from kafkastreams_cep_tpu.utils import tracecache
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("parallel.tenantbank")


class TenantState(NamedTuple):
    """Whole-bank matcher state: one stacked ``[Qg*K]`` engine state per
    residual group plus one ``[Nq, K]`` stencil carry per prefix-length
    group.  A pytree (tuples of NamedTuples), so checkpointing, device
    placement, and ``runtime/migrate.py: widen_state`` compose."""

    engine: Tuple[EngineState, ...]
    carry: Tuple[PrefixCarry, ...]


@dataclasses.dataclass
class _PrefixGroup:
    """All non-NFA queries whose prefixes have the same length ``p``:
    one ``[Nq, K]`` carry, one vmapped recurrence over the matrix."""

    p: int
    qids: List[int]  # original query ids, member order
    sigs: np.ndarray  # [Nq, p] column ids
    stencil_rows: List[int]  # member rows that are pure-stencil
    stencil_qids: List[int]


@dataclasses.dataclass
class _EngineGroup:
    """One stacked residual dispatch: same-shape queries, one program."""

    kind: str  # "hybrid" | "nfa"
    qids: List[int]
    tlist: list
    p: int  # shared prefix length (0 for nfa)
    pg: Optional[int]  # owning prefix-group index (hybrid only)
    rows: List[int]  # member rows inside the prefix group (hybrid only)
    programs: tuple = ()  # (step, init_fn, phases, scan_jit, drain_jit)

    @property
    def Q(self) -> int:
        return len(self.qids)


def _stack_sig(t) -> tuple:
    """The same-shape key ``compiler/tables.py: stackable`` tests."""
    return (
        t.num_stages, t.max_hops, int(t.begin_pos), int(t.final_pos),
    )


def _build_group_programs(
    group: _EngineGroup, cfg: EngineConfig, K: int
):
    """Step + scan + drain programs for one engine group.

    The hybrid scan replicates the ``[K, T]`` events across members
    inside the jit, gathers the group's promotion rows out of the owning
    prefix group's ``[Np, K, T, ...]`` tensor (static member rows), and
    runs the step-then-promote schedule of the single-query tiered
    matcher per lane — qid-dispatched, so each lane is its own query.
    """
    Qg = group.Q
    L = Qg * K
    step, init_fn, phases = _build_step(group.tlist, cfg)
    qids = jnp.repeat(jnp.arange(Qg, dtype=jnp.int32), K)
    use_kernel, interpret = _select_walk_kernel(cfg, L)

    def rep(events):
        return jax.tree_util.tree_map(
            lambda x: jnp.concatenate([x] * Qg, axis=0), events
        )

    def unstack(out):
        return jax.tree_util.tree_map(
            lambda x: x.reshape((Qg, K) + x.shape[1:]), out
        )

    if group.kind == "nfa":
        if use_kernel:
            bstep = kernel_lane_step(phases, interpret, qids=qids)
            inner_scan = kernel_lane_scan(bstep)
        else:

            def inner_scan(state, events):
                return jax.vmap(
                    lambda s, e, q: jax.lax.scan(
                        lambda c, x: step(c, x, q), s, e
                    )
                )(state, events, qids)

        def scan(state: EngineState, events: EventBatch):
            state, out = inner_scan(state, rep(events))
            return state, unstack(out)

        scan_jit = jax.jit(scan)
    else:
        if use_kernel:
            base_step = kernel_lane_step(phases, interpret, qids=qids)
        else:

            def base_step(s, ev):
                return jax.vmap(step)(s, ev, qids)

        promote_b = jax.vmap(
            build_promote_stacked(group.tlist, cfg, group.p)
        )
        rows_ix = jnp.asarray(group.rows, jnp.int32)

        def scan(eng: EngineState, events: EventBatch, promo_pg):
            ev = rep(events)
            # [Np, K, T, ...] -> member rows -> flat [Qg*K, T, ...].
            pr = jax.tree_util.tree_map(
                lambda x: x[rows_ix].reshape((L,) + x.shape[2:]),
                promo_pg,
            )
            swap = lambda x: jnp.swapaxes(x, 0, 1)
            ev_t = jax.tree_util.tree_map(swap, ev)
            pr_t = jax.tree_util.tree_map(swap, pr)

            def body(s, x):
                ev1, pr1 = x
                # Step first, then promote: the prefix completes *at*
                # event t and the promoted run first evaluates at t+1 —
                # the untiered run's schedule (parallel/tiered.py).
                s, out = base_step(s, ev1)
                s, n = promote_b(
                    s, pr1.fire, pr1.offs, pr1.anchor_ts, pr1.sver, qids
                )
                return s, (out, n)

            eng, (outs, ns) = jax.lax.scan(body, eng, (ev_t, pr_t))
            outs = unstack(jax.tree_util.tree_map(swap, outs))
            promoted = jnp.sum(ns, axis=0).reshape(Qg, K)
            return eng, outs, promoted

        scan_jit = jax.jit(scan)

    drain_jit = jax.jit(jax.vmap(build_drain(cfg)))
    return (step, init_fn, phases, scan_jit, drain_jit)


class TenantBankMatcher:
    """N queries x ``K`` lanes under one bank plan (one chip).

    Drop-in for :class:`~kafkastreams_cep_tpu.parallel.stacked.
    StackedBankMatcher` (same ``scan``/``init_state``/``drain``/counters
    surface, ``[N, K, T, R, W]`` outputs decoded per query with
    :meth:`names_of`) without its same-shape requirement: queries group
    by shape internally and the whole bank shares one prefix screen.

    ``names`` optionally labels queries for the per-query telemetry
    breakdown (defaults to ``q0..qN-1``).
    """

    def __init__(
        self,
        patterns: Sequence,
        lanes_per_query: int,
        config: Optional[EngineConfig] = None,
        profile: Optional[Dict] = None,
        reorder: bool = True,
        names: Optional[Sequence[str]] = None,
    ):
        self.config = config or EngineConfig()
        self.K = int(lanes_per_query)
        self.bank: BankPlan = plan_bank(
            patterns, self.config, profile, reorder
        )
        self.N = len(self.bank.queries)
        self.query_names = (
            list(names)
            if names is not None
            else [f"q{q}" for q in range(self.N)]
        )
        if len(self.query_names) != self.N:
            raise ValueError("names must have one entry per pattern")
        self.scan_calls = 0
        self.nfa_dispatches = 0

        # -- prefix-length groups (the shared screen frontier) --------------
        by_p: Dict[int, List[int]] = {}
        for q, qp in enumerate(self.bank.queries):
            if qp.plan.tier != TIER_NFA:
                by_p.setdefault(qp.plan.prefix_len, []).append(q)
        self._pgroups: List[_PrefixGroup] = []
        for p in sorted(by_p):
            qids = by_p[p]
            sigs = np.asarray(
                [self.bank.queries[q].prefix_cols for q in qids],
                np.int32,
            )
            srows = [
                i
                for i, q in enumerate(qids)
                if self.bank.queries[q].plan.tier != TIER_HYBRID
            ]
            self._pgroups.append(
                _PrefixGroup(
                    p=p, qids=qids, sigs=sigs, stencil_rows=srows,
                    stencil_qids=[qids[i] for i in srows],
                )
            )
        member_row = {
            (i, q): r
            for i, pg in enumerate(self._pgroups)
            for r, q in enumerate(pg.qids)
        }

        # -- residual engine groups -----------------------------------------
        groups: Dict[tuple, _EngineGroup] = {}
        for q, qp in enumerate(self.bank.queries):
            if qp.plan.tier == TIER_HYBRID:
                pgi = next(
                    i
                    for i, pg in enumerate(self._pgroups)
                    if q in pg.qids
                )
                key = (
                    "hybrid", qp.plan.prefix_len, _stack_sig(qp.tables),
                )
                g = groups.setdefault(
                    key,
                    _EngineGroup(
                        kind="hybrid", qids=[], tlist=[],
                        p=qp.plan.prefix_len, pg=pgi, rows=[],
                    ),
                )
                g.qids.append(q)
                g.tlist.append(qp.tables)
                g.rows.append(member_row[(pgi, q)])
            elif qp.plan.tier == TIER_NFA:
                key = ("nfa", _stack_sig(qp.tables))
                g = groups.setdefault(
                    key,
                    _EngineGroup(
                        kind="nfa", qids=[], tlist=[], p=0, pg=None,
                        rows=[],
                    ),
                )
                g.qids.append(q)
                g.tlist.append(qp.tables)
        self._groups: List[_EngineGroup] = list(groups.values())
        for g in self._groups:
            g.programs = self._cached_group_programs(g)
        self._hybrid_idx = [
            i for i, g in enumerate(self._groups) if g.kind == "hybrid"
        ]

        logger.info(
            "tenant bank: %d queries -> %d prefix groups (%d columns, "
            "shared hit rate %.2f), %d engine groups (%d hybrid), "
            "predicate dedup %.2fx",
            self.N, len(self._pgroups),
            self.bank.stats["prefix_columns_distinct"],
            self.bank.stats["prefix_shared_hit_rate"],
            len(self._groups), len(self._hybrid_idx),
            self.bank.stats["pred_dedup_ratio"],
        )

        self._screen_jit = self._cached_screen()

    # -- program construction (trace-cached) ---------------------------------

    def _struct_key(self):
        bkey = bank_key([qp.tables for qp in self.bank.queries])
        if bkey is None:
            return None
        struct = (
            tuple(
                (pg.p, pg.sigs.tobytes(), tuple(pg.stencil_rows))
                for pg in self._pgroups
            ),
            tuple(
                (g.kind, g.p, g.pg, tuple(g.rows), tuple(g.qids))
                for g in self._groups
            ),
        )
        return (bkey, dataclasses.astuple(self.config), struct)

    def _cached_group_programs(self, g: _EngineGroup):
        key = bank_key(g.tlist)
        if key is not None:
            # K is part of the key: the group's per-lane qid table and
            # the walk-kernel feasibility decision are baked into the
            # closure at [Qg*K] lanes.
            key = (
                key, dataclasses.astuple(self.config), g.kind, g.p,
                tuple(g.rows), self.K,
                _select_walk_kernel(self.config, g.Q * self.K),
            )
        return tracecache.lookup(
            "tenant.group_programs", key,
            lambda: _build_group_programs(g, self.config, self.K),
        )

    def _cached_screen(self):
        if not self._pgroups:
            return None
        key = self._struct_key()
        return tracecache.lookup(
            "tenant.screen", key, lambda: jax.jit(self._build_screen())
        )

    def _build_screen(self):
        """The whole-bank screen: matrix -> per-p-group recurrence ->
        stencil synthesis + hybrid gates, one fused program."""
        owner_tables = [qp.tables for qp in self.bank.queries]
        matrix_fn = build_matrix(self.bank.columns, owner_tables)
        scans = [bank_prefix_scan(pg.p) for pg in self._pgroups]
        synths = []
        for pg in self._pgroups:
            if pg.stencil_qids:
                synths.append(
                    (
                        jnp.asarray(pg.stencil_rows, jnp.int32),
                        stencil_step_output_stacked(
                            [
                                self.bank.queries[q].tables
                                for q in pg.stencil_qids
                            ],
                            self.config, pg.p,
                        ),
                    )
                )
            else:
                synths.append(None)
        hybrids = [
            (self._groups[i].pg,
             jnp.asarray(self._groups[i].rows, jnp.int32))
            for i in self._hybrid_idx
        ]
        sig_tables = [pg.sigs for pg in self._pgroups]

        def screen(carries, alives, ev: EventBatch):
            mat = matrix_fn(ev)
            new_carries, promos, souts = [], [], []
            for i, (scan, synth) in enumerate(zip(scans, synths)):
                bools_q = group_bools(mat, sig_tables[i])
                c2, promo = scan(carries[i], bools_q, ev)
                new_carries.append(c2)
                promos.append(promo)
                if synth is None:
                    souts.append(None)
                else:
                    srows, synth_fn = synth
                    souts.append(
                        synth_fn(
                            jax.tree_util.tree_map(
                                lambda x: x[srows], promo
                            )
                        )
                    )
            if hybrids:
                gates = jnp.stack(
                    [
                        jnp.any(alives[i])
                        | jnp.any(promos[pgi].fire[rows])
                        for i, (pgi, rows) in enumerate(hybrids)
                    ]
                )
            else:
                gates = jnp.zeros((0,), bool)
            return (
                tuple(new_carries), tuple(promos), tuple(souts), gates,
            )

        return screen

    # -- state ----------------------------------------------------------------

    def names_of(self, q: int) -> List[str]:
        return self.bank.queries[q].tables.names

    def tier_of(self, q: int) -> str:
        return self.bank.queries[q].plan.tier

    def init_state(self) -> TenantState:
        engines = []
        for g in self._groups:
            _, init_fn, _, _, _ = g.programs
            per_q = []
            for lq in range(g.Q):
                s = (
                    init_fn(lq)
                    if g.kind == "nfa"
                    # Hybrid: the begin stage lives on the stencil tier,
                    # so the group queue starts empty (engine/tiered.py).
                    else seedless_init(lambda lq=lq: init_fn(lq))
                )
                per_q.append(s)
            engines.append(
                jax.tree_util.tree_map(
                    lambda *xs: jnp.concatenate(
                        [
                            jnp.broadcast_to(x, (self.K,) + x.shape)
                            for x in xs
                        ]
                    ),
                    *per_q,
                )
            )
        carries = tuple(
            init_carries(len(pg.qids), self.K, pg.p)
            for pg in self._pgroups
        )
        return TenantState(engine=tuple(engines), carry=carries)

    # -- the scan --------------------------------------------------------------

    def _zero_group_out(self, Qg: int, T: int) -> StepOutput:
        cfg = self.config
        K, R, W = self.K, cfg.max_runs, cfg.max_walk
        i32 = jnp.int32
        return StepOutput(
            stage=jnp.full((Qg, K, T, R, W), -1, i32),
            off=jnp.full((Qg, K, T, R, W), -1, i32),
            count=jnp.zeros((Qg, K, T, R), i32),
        )

    def scan(self, state: TenantState, events: EventBatch):
        """One ``[K, T]`` batch through the whole bank.  Every query sees
        every record (the reference's one-processor-per-pattern topology);
        outputs come back ``[N, K, T, R, W]`` in original query order.
        Host-gated like the single-query tiered matcher, so not itself
        jittable."""
        T = int(events.ts.shape[1])
        self.scan_calls += 1
        if self._screen_jit is not None:
            alives = tuple(
                state.engine[i].alive for i in self._hybrid_idx
            )
            carries, promos, souts, gates = self._screen_jit(
                state.carry, alives, events
            )
            carries = list(carries)
            gates_h = np.asarray(jax.device_get(gates))
        else:
            carries, promos, souts, gates_h = [], (), (), np.zeros(0)

        blocks: List[Tuple[List[int], StepOutput]] = []
        for pg, so in zip(self._pgroups, souts):
            if so is not None:
                blocks.append((pg.stencil_qids, so))

        engines = list(state.engine)
        hseq = 0
        for i, g in enumerate(self._groups):
            if g.kind == "nfa":
                self.nfa_dispatches += 1
                _, _, _, scan_jit, _ = g.programs
                engines[i], out_g = scan_jit(engines[i], events)
                blocks.append((g.qids, out_g))
                continue
            gate = bool(gates_h[hseq])
            hseq += 1
            if not gate:
                # Exact skip: stepping an empty, promotion-free group
                # changes nothing but step_seq (parallel/tiered.py).
                engines[i] = _bump_engine_jit()(
                    engines[i], jnp.int32(T)
                )
                blocks.append((g.qids, self._zero_group_out(g.Q, T)))
                continue
            self.nfa_dispatches += 1
            _, _, _, scan_jit, _ = g.programs
            engines[i], out_g, promoted = scan_jit(
                engines[i], events, promos[g.pg]
            )
            c = carries[g.pg]
            carries[g.pg] = c._replace(
                promotions=c.promotions.at[
                    jnp.asarray(g.rows, jnp.int32)
                ].add(promoted)
            )
            blocks.append((g.qids, out_g))

        out = self._assemble(blocks)
        return (
            TenantState(engine=tuple(engines), carry=tuple(carries)),
            out,
        )

    def _assemble(self, blocks):
        """Concatenate per-group ``[n, ...]`` output blocks and permute
        back to original query order along the leading axis."""
        order = np.concatenate(
            [np.asarray(qids, np.int64) for qids, _ in blocks]
        )
        inv = jnp.asarray(np.argsort(order), jnp.int32)
        parts = [out for _, out in blocks]
        if len(parts) == 1:
            return jax.tree_util.tree_map(lambda x: x[inv], parts[0])
        return jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0)[inv], *parts
        )

    # -- maintenance / drains --------------------------------------------------

    def sweep(self, state: TenantState) -> TenantState:
        """Engine-group maintenance sweeps; stencil carries hold no slab
        references (partial prefixes own no entries) so they ride along
        untouched."""
        depth = self.config.max_walk
        do_renorm = self.config.renorm_versions
        swp = tracecache.lookup(
            "batch.sweep", (depth, do_renorm),
            lambda: jax.jit(
                lambda s: sweep_lanes(s, depth, do_renorm)
            ),
        )
        return state._replace(
            engine=tuple(swp(e) for e in state.engine)
        )

    def _zero_drain(self, n: int) -> DrainOutput:
        cfg = self.config
        HB, W = cfg.handle_ring, cfg.max_walk
        i32 = jnp.int32
        full = lambda shape: jnp.full(shape, -1, i32)
        return DrainOutput(
            stage=full((n, self.K, HB, W)),
            off=full((n, self.K, HB, W)),
            count=jnp.zeros((n, self.K, HB), i32),
            seq=full((n, self.K, HB)),
            row=full((n, self.K, HB)),
            ts=full((n, self.K, HB)),
        )

    def drain(self, state: TenantState):
        """Materialize pending lazy-extraction handles for every group;
        returns ``[N, K, ...]`` outputs in query order (pure-stencil
        queries never own handles — their rows are the empty drain)."""
        engines = list(state.engine)
        blocks: List[Tuple[List[int], DrainOutput]] = []
        covered: set = set()
        for i, g in enumerate(self._groups):
            _, _, _, _, drain_jit = g.programs
            engines[i], d = drain_jit(engines[i])
            blocks.append(
                (
                    g.qids,
                    jax.tree_util.tree_map(
                        lambda x: x.reshape(
                            (g.Q, self.K) + x.shape[1:]
                        ),
                        d,
                    ),
                )
            )
            covered.update(g.qids)
        rest = [q for q in range(self.N) if q not in covered]
        if rest:
            blocks.append((rest, self._zero_drain(len(rest))))
        out = self._assemble(blocks)
        return state._replace(engine=tuple(engines)), out

    # -- telemetry -------------------------------------------------------------

    def _summed(self, state: TenantState, names, values_fn):
        tot = dict.fromkeys(names, 0)
        for eng in state.engine:
            for n, v in zip(names, values_fn(eng)):
                tot[n] += int(jnp.sum(v))
        return tot

    def counters(self, state: TenantState) -> Dict[str, int]:
        return self._summed(state, COUNTER_NAMES, counter_values)

    def hot_counters(self, state: TenantState) -> Dict[str, int]:
        return self._summed(
            state, HOT_COUNTER_NAMES, hot_counter_values
        )

    def walk_counters(self, state: TenantState) -> Dict[str, int]:
        return self._summed(
            state, WALK_COUNTER_NAMES, walk_counter_values
        )

    def tier_counters(self, state: TenantState) -> Dict[str, int]:
        vals = [0, 0, 0]
        for c in state.carry:
            got = jax.device_get(
                (
                    jnp.sum(c.screened), jnp.sum(c.fires),
                    jnp.sum(c.promotions),
                )
            )
            vals = [a + int(b) for a, b in zip(vals, got)]
        return dict(zip(TIER_COUNTER_NAMES, vals))

    def per_query_counters(
        self, state: TenantState
    ) -> Dict[str, Dict[str, int]]:
        """Per-query attribution across the whole bank: loss + hot +
        walk counters summed over each query's ``K``-lane block of its
        group, plus that query's stencil-tier telemetry.  Queries with
        no residual engine (pure stencil) report zero engine counters."""
        names = COUNTER_NAMES + HOT_COUNTER_NAMES + WALK_COUNTER_NAMES
        per_q: Dict[int, Dict[str, int]] = {
            q: dict.fromkeys(names, 0) for q in range(self.N)
        }
        for g, eng in zip(self._groups, state.engine):
            arrays = per_lane_counter_arrays(eng)
            for r, q in enumerate(g.qids):
                for n, v in arrays.items():
                    per_q[q][n] = int(
                        v.reshape(g.Q, self.K)[r].sum()
                    )
        tier_zero = dict.fromkeys(TIER_COUNTER_NAMES, 0)
        for q in range(self.N):
            per_q[q].update(tier_zero)
        for pg, c in zip(self._pgroups, state.carry):
            scr, fr, pr = jax.device_get(
                (
                    jnp.sum(c.screened, axis=1),
                    jnp.sum(c.fires, axis=1),
                    jnp.sum(c.promotions, axis=1),
                )
            )
            for r, q in enumerate(pg.qids):
                per_q[q][TIER_COUNTER_NAMES[0]] = int(scr[r])
                per_q[q][TIER_COUNTER_NAMES[1]] = int(fr[r])
                per_q[q][TIER_COUNTER_NAMES[2]] = int(pr[r])
        return {
            self.query_names[q]: per_q[q] for q in range(self.N)
        }

    def metrics_snapshot(self, state: TenantState) -> Dict[str, object]:
        """Bank-wide telemetry: merged engine counters, shared-screen
        tier counters, compile-time sharing stats, and the ``per_query``
        breakdown (rendered as ``cep_*{query="..."}`` by
        ``utils/telemetry.py``)."""
        out: Dict[str, object] = {}
        out.update(self.counters(state))
        out.update(self.hot_counters(state))
        out.update(self.walk_counters(state))
        out.update(self.tier_counters(state))
        out["bank_queries"] = self.N
        out["bank_prefix_groups"] = len(self._pgroups)
        out["bank_engine_groups"] = len(self._groups)
        out["bank_pred_dedup_ratio"] = float(
            self.bank.stats["pred_dedup_ratio"]
        )
        out["bank_prefix_shared_hit_rate"] = float(
            self.bank.stats["prefix_shared_hit_rate"]
        )
        out["per_query"] = self.per_query_counters(state)
        return out
