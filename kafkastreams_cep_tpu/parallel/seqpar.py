"""Sequence parallelism: one long trace split over the mesh's time axis.

The reference handles long streams only by windowed pruning — events are
strictly sequential per partition (``NFA.java:94-109``).  The general NFA
inherits that sequential dependence (run state at event ``t`` depends on
``t-1``), but the strict-SEQ stencil fragment (``engine/stencil.py``) does
not: a match at position ``t`` reads only the ``n`` events ending at ``t``.
That makes the time axis shardable — the CEP analog of
sequence/context parallelism, with a *halo exchange* instead of ring
attention: each device evaluates its chunk's predicate booleans locally and
receives the previous chunk's trailing ``n-1`` columns via one
``lax.ppermute`` hop over ICI.  Communication per step is ``O(K·n)``
booleans, independent of chunk length.

Device 0's halo arrives as ``ppermute`` zeros — exactly "no preceding
events", so a fresh trace needs no special casing.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kafkastreams_cep_tpu.engine.matcher import ArrayStates, EventBatch
from kafkastreams_cep_tpu.engine.stencil import StencilMatcher, StencilOutput


class TimeShardedStencil:
    """Strict-SEQ matching with the time axis sharded over a mesh.

    ``match(events)`` consumes a ``[K, T]`` batch with ``T`` divisible by
    the mesh size (padding slots are masked via ``valid``, exactly like the
    single-device scan); every device stencils its own ``T/n_dev`` chunk
    after one boundary exchange.  Output shapes equal the single-device
    :class:`StencilMatcher` scan on the same batch — verified equal
    element-for-element in ``tests/test_seqpar.py``.
    """

    def __init__(self, pattern, num_lanes: int, mesh: Mesh):
        self.inner = StencilMatcher(pattern, num_lanes)
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_dev = int(mesh.devices.size)
        self.num_lanes = int(num_lanes)
        n = self.inner.n
        preds = self.inner._preds
        axis = self.axis

        def local(key, value, ts, off, valid):
            # [K, Tc] local chunk -> per-stage bools, halo, stencil.
            K = key.shape[0]
            Tc = key.shape[1]
            states = ArrayStates({})
            bools = jnp.stack(
                [
                    jnp.broadcast_to(
                        jnp.asarray(p(key, value, ts, states), bool), (K, Tc)
                    )
                    & valid
                    for p in preds
                ],
                axis=-1,
            )  # [K, Tc, n]
            offs = jnp.asarray(off, jnp.int32)
            if n == 1:
                return bools[..., 0], offs[..., None]

            perm = [(i, i + 1) for i in range(self.n_dev - 1)]
            halo_b = jax.lax.ppermute(bools[:, Tc - (n - 1) :, :], axis, perm)
            halo_o = jax.lax.ppermute(
                offs[:, Tc - (n - 1) :], axis, perm
            )
            ext_b = jnp.concatenate([halo_b, bools], axis=1)  # [K, Tc+n-1, n]
            ext_o = jnp.concatenate([halo_o, offs], axis=1)
            hit = ext_b[:, 0:Tc, 0]
            for i in range(1, n):
                hit = hit & ext_b[:, i : i + Tc, i]
            match_offs = jnp.stack(
                [ext_o[:, i : i + Tc] for i in range(n)], axis=-1
            )
            return hit, match_offs

        spec_in = (
            P(None, axis), P(None, axis), P(None, axis), P(None, axis),
            P(None, axis),
        )
        spec_out = (P(None, axis), P(None, axis, None))
        from kafkastreams_cep_tpu.parallel.sharding import _shard_map

        self._match = jax.jit(
            _shard_map(
                local,
                mesh=mesh,
                in_specs=spec_in,
                out_specs=spec_out,
                check_vma=False,
            )
        )

    def shard_events(self, events: EventBatch) -> EventBatch:
        """Place a host-built fully-valid [K, T] batch, T sharded."""
        sh = NamedSharding(self.mesh, P(None, self.axis))
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), events
        )

    def match(self, events: EventBatch) -> StencilOutput:
        T = events.ts.shape[-1]
        if T % self.n_dev:
            raise ValueError(
                f"time axis {T} not divisible by mesh size {self.n_dev}"
            )
        hit, offs = self._match(
            events.key, events.value, events.ts, events.off, events.valid
        )
        return StencilOutput(hit=hit, offs=offs)
