"""Stacked multi-query bank: N same-shape queries in ONE compiled dispatch.

The reference composes multi-query topologies as one ``CEPProcessor`` per
pattern over the same topic; the serial device analog (``runtime/bank.py``)
pays one dispatch per query.  When queries lower to the same table shape
(stage count, chain depth — typical for banks of parameterized variants of
one query), their tables stack on a leading query axis and a per-lane
``qid`` selects each lane's query inside the engine step
(``engine/matcher.py: _build_step`` stacked mode).  N queries x K lanes run
as ``N*K`` lanes of one program — BASELINE.json config 4's "multi-pattern
NFA bank, batched".

Identical predicates across the stack (shared stages of parameterized
variants) are interned by bytecode structure before tracing
(``compiler/multitenant.py: plan_step_predicates``), so the fused step
evaluates each distinct predicate once per event rather than once per
query — ``StackedBankMatcher.pred_stats`` reports the measured dedup
ratio.  For banks with shared strict-contiguity *prefixes*, the
multi-tenant matcher (``parallel/tenantbank.py``) goes further and
screens the whole bank with one stencil pass.

Use :func:`stackable` to test compatibility and fall back to
``runtime/bank.py: CEPBank``'s per-query loop otherwise.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from kafkastreams_cep_tpu.compiler.tables import (
    TransitionTables,
    lower,
    stackable,
)
from kafkastreams_cep_tpu.engine.matcher import (
    COUNTER_NAMES,
    EngineConfig,
    EngineState,
    EventBatch,
    _build_step,
    counter_values,
)
from kafkastreams_cep_tpu.parallel.batch import (
    _select_walk_kernel,
    kernel_lane_scan,
    kernel_lane_step,
)
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("parallel.stacked")


class StackedBankMatcher:
    """``Q`` same-shape queries x ``K`` lanes each, one compiled program.

    Lane layout: query-major — lane ``q * K + k`` runs query ``q`` over key
    lane ``k``.  ``scan`` takes per-key events shaped ``[K, T]`` and
    replicates them across queries (every query sees every record, like the
    reference's one-processor-per-pattern topology); outputs come back
    ``[Q, K, T, R, W]`` so callers decode per query with that query's stage
    names (``names_of``).
    """

    def __init__(
        self,
        patterns: Sequence,
        lanes_per_query: int,
        config: Optional[EngineConfig] = None,
    ):
        self.tables_list: List[TransitionTables] = [
            p if isinstance(p, TransitionTables) else lower(p)
            for p in patterns
        ]
        if not stackable(self.tables_list):
            raise ValueError(
                "queries do not share a stackable table shape; use "
                "runtime.bank.CEPBank's per-query loop instead"
            )
        self.config = config or EngineConfig()
        self.Q = len(self.tables_list)
        self.K = int(lanes_per_query)
        self.num_lanes = self.Q * self.K
        logger.info(
            "stacked bank: %d queries x %d lanes in one dispatch",
            self.Q, self.K,
        )
        step, init_state, phases = _build_step(self.tables_list, self.config)
        self._step_fn = step
        self._init_fn = init_state
        self._phases = phases
        # _build_step interns predicates by bytecode identity across the
        # whole stack (compiler/multitenant.py: plan_step_predicates):
        # a bank of N parameterized variants of one query evaluates each
        # *distinct* predicate once per event instead of N times per lane.
        self.pred_stats = dict(phases.pred_stats or {})
        if self.pred_stats:
            logger.info(
                "stacked bank predicate dedup: %d -> %d distinct "
                "(%d event-level, %d run-level; ratio %.2f)",
                self.pred_stats.get("total_predicates", 0),
                self.pred_stats.get("distinct_predicates", 0),
                self.pred_stats.get("event_level", 0),
                self.pred_stats.get("run_level", 0),
                self.pred_stats.get("dedup_ratio", 1.0),
            )
        qids = jnp.repeat(
            jnp.arange(self.Q, dtype=jnp.int32), self.K
        )  # [Q*K]
        self._qids = qids

        use_kernel, interpret = _select_walk_kernel(
            self.config, self.num_lanes
        )
        self.uses_walk_kernel = use_kernel
        if use_kernel:
            bstep = kernel_lane_step(phases, interpret, qids=qids)
            scan = kernel_lane_scan(bstep)
        else:

            def scan(state: EngineState, events: EventBatch):
                return jax.vmap(
                    lambda s, e, q: jax.lax.scan(
                        lambda c, x: step(c, x, q), s, e
                    )
                )(state, events, qids)

        def scan_rep(state: EngineState, events: EventBatch):
            # Replicate [K, T] events across queries INSIDE the jit so XLA
            # fuses the broadcast instead of copying Q x [K, T] per call.
            ev = jax.tree_util.tree_map(
                lambda x: jnp.concatenate([x] * self.Q, axis=0), events
            )
            return scan(state, ev)

        self._scan_fn = scan
        self.scan_flat = jax.jit(scan_rep)
        self._drain_jit = None  # built on first drain() (lazy configs)

    def names_of(self, q: int) -> List[str]:
        return self.tables_list[q].names

    def init_state(self) -> EngineState:
        """Per-query initial state tiled to the [Q*K] lane axis."""
        per_q = [self._init_fn(q) for q in range(self.Q)]
        return jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(
                [jnp.broadcast_to(x, (self.K,) + x.shape) for x in xs]
            ),
            *per_q,
        )

    def scan(self, state: EngineState, events: EventBatch):
        """Events ``[K, T]`` -> replicated across queries (inside the
        jit) -> outputs reshaped ``[Q, K, T, ...]``."""
        state, out = self.scan_flat(state, events)
        out = jax.tree_util.tree_map(
            lambda x: x.reshape((self.Q, self.K) + x.shape[1:]), out
        )
        return state, out

    def counters(self, state: EngineState) -> Dict[str, int]:
        return {
            n: int(jnp.sum(v))
            for n, v in zip(COUNTER_NAMES, counter_values(state))
        }

    def hot_counters(self, state: EngineState) -> Dict[str, int]:
        """Two-tier residency telemetry summed over all lanes."""
        from kafkastreams_cep_tpu.engine.matcher import (
            HOT_COUNTER_NAMES,
            hot_counter_values,
        )

        return {
            n: int(jnp.sum(v))
            for n, v in zip(HOT_COUNTER_NAMES, hot_counter_values(state))
        }

    def walk_counters(self, state: EngineState) -> Dict[str, int]:
        """Walk-cost telemetry summed over all lanes."""
        from kafkastreams_cep_tpu.engine.matcher import (
            WALK_COUNTER_NAMES,
            walk_counter_values,
        )

        return {
            n: int(jnp.sum(v))
            for n, v in zip(WALK_COUNTER_NAMES, walk_counter_values(state))
        }

    def drain(self, state: EngineState):
        """Materialize pending lazy-extraction handles for every lane of
        the stacked ``[Q*K]`` axis in one pass (the drain is table-free,
        so one pass serves all bank members)."""
        if self._drain_jit is None:
            from kafkastreams_cep_tpu.engine.matcher import build_drain

            self._drain_jit = jax.jit(jax.vmap(build_drain(self.config)))
        return self._drain_jit(state)

    def stage_counters(self, state: EngineState) -> Dict[str, Dict[str, int]]:
        """Per-stage attribution summed over the whole ``[Q*K]`` lane axis
        (stage *positions* are shared by construction — stackable tables
        have one stage shape — so the roll-up uses query 0's names);
        empty when attribution is off."""
        from kafkastreams_cep_tpu.engine.matcher import (
            stage_counter_arrays,
            stage_report,
        )

        return stage_report(
            stage_counter_arrays(state), self.tables_list[0].names
        )

    def per_query_counters(self, state: EngineState) -> Dict[str, Dict[str, int]]:
        """Per-pattern attribution: drop + hot counters summed over each
        query's ``K``-lane block of the ``[Q*K]`` lane axis (lane layout is
        query-major) — which bank member is burning capacity inside the
        one fused dispatch."""
        from kafkastreams_cep_tpu.engine.matcher import per_lane_counter_arrays

        arrays = per_lane_counter_arrays(state)
        return {
            f"q{q}": {
                n: int(v.reshape(self.Q, self.K)[q].sum())
                for n, v in arrays.items()
            }
            for q in range(self.Q)
        }

    def metrics_snapshot(self, state: EngineState) -> Dict[str, object]:
        """Bank-wide engine telemetry: the per-member registries merged
        (summed drop + hot counters) beside the ``per_pattern`` breakdown
        that attributes them to individual queries."""
        from kafkastreams_cep_tpu.engine.matcher import TIER_COUNTER_NAMES

        out: Dict[str, object] = {}
        out.update(self.counters(state))
        out.update(self.hot_counters(state))
        out.update(self.walk_counters(state))
        # Stacked banks run whole-NFA (same-shape stacking is the point);
        # tier counters are structural zeros for schema uniformity.
        out.update({n: 0 for n in TIER_COUNTER_NAMES})
        out["per_pattern"] = self.per_query_counters(state)
        per_stage = self.stage_counters(state)
        if per_stage:
            out["per_stage"] = per_stage
        return out


def choose_bank(
    patterns: Sequence,
    config: Optional[EngineConfig] = None,
    sample_events: Optional[EventBatch] = None,
    reps: int = 2,
) -> Tuple[str, Dict[str, float]]:
    """Serial vs stacked, decided the way capacity is (engine/sizing.py):
    by measurement, not a cost model.

    The tradeoff is real in both directions: stacking runs the bank in one
    dispatch (one compile, one launch, better utilization at small
    per-query widths) but the stacked step evaluates *every* query's
    predicates on every lane (``engine/matcher.py eval_preds``), so wide
    lane counts with pred-heavy queries can favor the serial loop.  Where
    the crossover falls depends on Q, K, T, the pattern, and the backend —
    so when ``sample_events`` (a ``[K_s, T]`` batch, small ``K_s``) is
    given, both variants are timed on it and the faster wins.  Without a
    sample: non-stackable banks are serial by necessity, stackable ones
    default to stacked (the single-compile saving alone is decisive for
    short streams — a serial bank compiles once per query).

    Measured finding (v5e, BENCH_r05): at production widths (>=6400
    lanes/query) serial wins steady-state at every benched bank width
    (fused at 0.79-0.91x for N=2/8/16) — per-dispatch overhead is
    negligible at those widths while the stacked step pays every query's
    predicate work on every lane; fused wins compile time 2-4x (one
    program vs N).  Size the sample near the deployment's per-query
    width: a 128-lane sample once picked stacked for an N=8 bank whose
    12800-lane-per-query deployment favored serial, because dispatch
    overhead dominates at sample width.

    Returns ``(mode, details)`` with measured rates in ``details`` when a
    sample was timed."""
    import time

    from kafkastreams_cep_tpu.parallel.batch import BatchMatcher

    tlist = [
        p if isinstance(p, TransitionTables) else lower(p) for p in patterns
    ]
    if not stackable(tlist):
        return "serial", {"reason": "not stackable"}
    if sample_events is None:
        return "stacked", {"reason": "no sample; one compile beats Q"}

    K_s = int(sample_events.ts.shape[0])

    def best_of(fn):
        fn()  # compile + warm
        t = float("inf")
        for _ in range(max(reps, 1)):
            t0 = time.perf_counter()
            fn()
            t = min(t, time.perf_counter() - t0)
        return t

    serial_ms = [BatchMatcher(t, K_s, config) for t in tlist]
    serial_states = [m.init_state() for m in serial_ms]

    def run_serial():
        outs = [
            m.scan(s, sample_events)
            for m, s in zip(serial_ms, serial_states)
        ]
        jax.block_until_ready([o[1].count for o in outs])

    t_serial = best_of(run_serial)

    stacked = StackedBankMatcher(tlist, K_s, config)
    st0 = stacked.init_state()

    def run_stacked():
        _, out = stacked.scan(st0, sample_events)
        jax.block_until_ready(out.count)

    t_stacked = best_of(run_stacked)
    details = {
        "serial_s": t_serial,
        "stacked_s": t_stacked,
        "speedup_stacked": t_serial / t_stacked,
    }
    mode = "stacked" if t_stacked <= t_serial else "serial"
    logger.info("choose_bank: %s (%s)", mode, details)
    return mode, details
