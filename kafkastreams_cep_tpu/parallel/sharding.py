"""Multi-chip execution: the key axis sharded over a ``jax.sharding.Mesh``.

This is the distributed backend replacing the reference's Kafka-broker
fabric (SURVEY §2.2): partition assignment becomes a sharded lane axis,
"changelog replication" becomes host-side checkpoint of the sharded state
(``runtime/checkpoint.py``), and cross-partition diagnostics ride XLA
collectives (``psum``) over ICI within a slice and DCN across hosts.  Lanes
never exchange data during matching — exactly like the reference's
partitions (``CEPProcessor.java:160``) — so the hot path is collective-free
by construction and scales linearly by design.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kafkastreams_cep_tpu.engine.matcher import (
    COUNTER_NAMES,
    HOT_COUNTER_NAMES,
    WALK_COUNTER_NAMES,
    EngineConfig,
    EngineState,
    EventBatch,
    TPUMatcher,
    counter_values,
    hot_counter_values,
    walk_counter_values,
)
from kafkastreams_cep_tpu.parallel.batch import (
    _select_walk_kernel,
    broadcast_state,
    guarded_scan_fallback,
    kernel_lane_scan,
    kernel_lane_step,
    lane_scan,
    lane_step,
)
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("parallel.sharding")


class ShardLost(RuntimeError):
    """A mesh shard (device) is dead or unreachable.

    Raised by deployment probes / injected at the ``shard.dispatch``
    failpoint; the supervisor's evacuation path catches it, shrinks the
    mesh to the survivors (:func:`surviving_mesh`), and restores-and-
    replays onto the sub-mesh (``runtime/supervisor.py``).
    ``shard`` is the dead shard's index along the mesh's lane axis.
    """

    def __init__(self, msg: str = "shard lost", shard: int = 0):
        super().__init__(msg)
        self.shard = int(shard)


def surviving_mesh(mesh: Mesh, dead, num_lanes: int) -> Optional[Mesh]:
    """The degraded-mode mesh after losing the shards in ``dead``.

    Keeps the largest prefix of surviving devices whose count divides
    ``num_lanes`` (the ``ShardedMatcher`` contiguous-block constraint) —
    documented degraded-mode policy: capacity may shrink below the
    survivor count to keep lane blocks equal-sized, and a single-device
    mesh (``n=1``) is always reachable since every ``K`` divides by 1.
    Raises when every shard is dead.
    """
    dead = {int(d) for d in dead}
    survivors = [
        d for i, d in enumerate(mesh.devices.flat) if i not in dead
    ]
    if not survivors:
        raise ValueError("no surviving devices: every mesh shard is dead")
    m = len(survivors)
    while num_lanes % m:
        m -= 1
    return key_mesh(survivors[:m], axis=mesh.axis_names[0])


def _shard_map(*args, **kwargs):
    """``jax.shard_map`` with fallback to the pre-0.5 experimental home
    (the engine runs on older jaxlib in CI than on the TPU hosts).
    ``check_vma`` was spelled ``check_rep`` there."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn

        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return fn(*args, **kwargs)


def key_mesh(devices: Optional[Sequence] = None, axis: str = "keys") -> Mesh:
    """A 1-D mesh over ``devices`` (default: all) sharding the key axis.

    Multi-host meshes need no special casing: key lanes are independent, so
    the same spec lays shards over ICI within a slice and DCN across hosts.
    """
    import numpy as np

    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (axis,))


class ShardedMatcher:
    """``K`` key lanes sharded over a device mesh via ``jax.shard_map``.

    ``K`` must be divisible by the mesh size; each device steps ``K/n``
    lanes with the same compiled per-lane program as :class:`BatchMatcher`.
    ``stats`` is the one collective op — a ``psum`` of the overflow counters
    and per-step match counts across shards.
    """

    def __init__(
        self,
        pattern,
        num_lanes: int,
        mesh: Mesh,
        config: Optional[EngineConfig] = None,
    ):
        self.matcher = TPUMatcher(pattern, config)
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        n = mesh.devices.size
        if num_lanes % n:
            raise ValueError(
                f"num_lanes={num_lanes} not divisible by mesh size {n}"
            )
        self.num_lanes = int(num_lanes)
        spec = P(self.axis)
        # Each shard steps K/n lanes with the same code as BatchMatcher —
        # including the fused walk kernel when the per-shard lane count
        # allows it (Pallas composes with shard_map; lanes never cross
        # shards, so the kernel sees an ordinary lane batch).
        use_kernel, interpret = _select_walk_kernel(
            self.matcher.config, self.num_lanes // n
        )
        self.uses_walk_kernel = use_kernel
        if use_kernel:
            local_step = kernel_lane_step(self.matcher._phases, interpret)
            local_scan = kernel_lane_scan(local_step)
        else:
            local_step = lane_step(self.matcher._step_fn)
            local_scan = lane_scan(self.matcher._step_fn)
        # Whole-scan kernel inside shard_map (opt-in, same knob as
        # BatchMatcher): lanes never cross shards, so each shard's block
        # is an ordinary lane batch for the fused program.
        self.uses_scan_kernel = False
        fallback_local_scan = local_scan
        scan_mode = __import__("os").environ.get("CEP_SCAN_KERNEL", "0")
        if scan_mode in ("1", "interpret"):
            from kafkastreams_cep_tpu.ops import scan_kernel

            if (self.num_lanes // n) % scan_kernel.LANE_BLOCK == 0:
                full = scan_kernel.build_scan(
                    self.matcher.tables, self.matcher.config
                )
                full.interpret = scan_mode == "interpret"
                local_scan = full
                self.uses_scan_kernel = True
            else:
                logger.warning(
                    "CEP_SCAN_KERNEL=%s requested but per-shard lane count "
                    "%d is not a multiple of %d — using the per-step path",
                    scan_mode, self.num_lanes // n, scan_kernel.LANE_BLOCK,
                )

        def local_stats(state):
            local = jnp.stack(
                [jnp.sum(v) for v in counter_values(state)]
                + [jnp.sum(state.alive)]
                + [jnp.sum(v) for v in hot_counter_values(state)]
                + [jnp.sum(v) for v in walk_counter_values(state)]
            )
            return jax.lax.psum(local, self.axis)

        # check_vma off: constants born inside fori_loop carries are
        # device-invariant and trip the varying-axes check; the hot path has
        # no collectives, so the replication analysis buys nothing here.
        shard = lambda f, out_specs: _shard_map(
            f, mesh=mesh, in_specs=spec, out_specs=out_specs, check_vma=False
        )
        self.step = jax.jit(shard(local_step, spec))
        if self.uses_scan_kernel:
            # Same guarded first call as BatchMatcher._with_fallback: the
            # kernel traces user predicates, so a pattern that cannot lower
            # to Mosaic fails at the first compiled call — fall back to the
            # per-step sharded path then, and only then (transient runtime
            # errors propagate and leave the kernel armed).
            self.scan = self._scan_with_fallback(
                jax.jit(shard(local_scan, spec)),
                lambda: jax.jit(shard(fallback_local_scan, spec)),
            )
        else:
            self.scan = jax.jit(shard(local_scan, spec))
        self._stats = jax.jit(shard(local_stats, P()))

    def _scan_with_fallback(self, fast, make_slow):
        """:func:`parallel.batch.guarded_scan_fallback` — one shared
        classification policy with the single-chip matcher, so a
        transient device error on the sharded kernel path retries with
        the kernel armed instead of permanently disabling it."""

        def on_fallback():
            self.uses_scan_kernel = False

        return guarded_scan_fallback(
            fast, make_slow, on_fallback, what="sharded whole-scan kernel"
        )

    @property
    def names(self):
        return self.matcher.names

    def init_state(self) -> EngineState:
        state = broadcast_state(self.matcher.init_state(), self.num_lanes)
        return jax.device_put(state, NamedSharding(self.mesh, P(self.axis)))

    def shard_events(self, events: EventBatch) -> EventBatch:
        """Place a host-built ``[K, ...]`` event batch onto the mesh."""
        return jax.device_put(events, NamedSharding(self.mesh, P(self.axis)))

    def stats(self, state: EngineState) -> Dict[str, int]:
        """Mesh-global counter totals (one ``psum`` across all shards)."""
        vals = jax.device_get(self._stats(state))
        keys = (
            COUNTER_NAMES + ("alive_runs",) + HOT_COUNTER_NAMES
            + WALK_COUNTER_NAMES
        )
        return {k: int(v) for k, v in zip(keys, vals)}

    def counters(self, state: EngineState) -> Dict[str, int]:
        """Overflow/drop counters summed over all lanes — the
        :class:`BatchMatcher` interface, so the runtime layer (processor,
        supervisor, checkpoint) is matcher-agnostic."""
        stats = self.stats(state)
        return {k: stats[k] for k in COUNTER_NAMES}

    def hot_counters(self, state: EngineState) -> Dict[str, int]:
        """Two-tier residency telemetry totals (BatchMatcher interface)."""
        stats = self.stats(state)
        return {k: stats[k] for k in HOT_COUNTER_NAMES}

    def walk_counters(self, state: EngineState) -> Dict[str, int]:
        """Walk-cost telemetry totals (BatchMatcher interface)."""
        stats = self.stats(state)
        return {k: stats[k] for k in WALK_COUNTER_NAMES}

    def drain(self, state: EngineState):
        """Materialize pending lazy-extraction handles on every shard
        (lane-elementwise, collective-free — the BatchMatcher interface;
        see ``engine/matcher.py: build_drain``)."""
        return self._drain_jit(state)

    @functools.cached_property
    def _drain_jit(self):
        local = jax.vmap(self.matcher._drain_fn)
        spec = P(self.axis)
        return jax.jit(
            _shard_map(
                local, mesh=self.mesh, in_specs=spec,
                out_specs=(spec, spec), check_vma=False,
            )
        )

    @functools.cached_property
    def _stage_stats(self):
        """Mesh-global per-stage attribution: each shard reduces its lane
        block to ``[5, S]`` (the four selectivity tallies + stage hops)
        and one ``psum`` merges the shards — associative by construction
        (integer addition), exactly like the scalar-counter psum."""
        spec = P(self.axis)

        def local(state: EngineState):
            sc = jnp.sum(state.stage_counts, axis=0)  # [4, S]
            sh = jnp.sum(state.slab.stage_hops, axis=0)[None, :]  # [1, S]
            return jax.lax.psum(
                jnp.concatenate([sc, sh], axis=0), self.axis
            )

        return jax.jit(
            _shard_map(
                local, mesh=self.mesh, in_specs=spec, out_specs=P(),
                check_vma=False,
            )
        )

    def stage_counters(self, state: EngineState) -> Dict[str, Dict[str, int]]:
        """Per-stage attribution totals psum-merged across every shard
        (BatchMatcher interface); empty when attribution is off."""
        from kafkastreams_cep_tpu.engine.matcher import (
            STAGE_TALLY_NAMES,
            stage_report,
        )

        if int(state.stage_counts.shape[-1]) == 0:
            return {}
        import numpy as np

        merged = np.asarray(jax.device_get(self._stage_stats(state)))
        arrays = {
            n: merged[i].astype(np.int64)
            for i, n in enumerate(STAGE_TALLY_NAMES)
        }
        arrays["stage_walk_hops"] = merged[4].astype(np.int64)
        return stage_report(arrays, self.names)

    def per_lane_counters(self, state: EngineState) -> Dict[str, list]:
        """Per-lane drop + hot counters gathered from every shard:
        ``{name: [K ints]}`` with global lane indices (the lane axis is
        sharded, so lane ``k`` lives on device ``k // (K/n)``) — which
        lane, and therefore which shard, is burning capacity."""
        from kafkastreams_cep_tpu.engine.matcher import per_lane_counter_arrays

        return {
            n: v.reshape(-1).tolist()
            for n, v in per_lane_counter_arrays(state).items()
        }

    def metrics_snapshot(
        self,
        state: EngineState,
        watermark=None,
        clock=None,
        ledgers=None,
    ) -> Dict[str, object]:
        """Mesh-global engine telemetry in one dict — the per-shard
        registries merged: the summed view rides the one-``psum`` ``stats``
        collective (each shard's counter block is its local registry; the
        psum IS the merge), the per-lane breakdown a host gather.

        ``watermark`` (absolute ms) adds the watermark / event-time-lag
        gauges the unmeshed processor surfaces — through the caller's
        injectable ``clock`` — which the meshed wrapper historically
        omitted.  ``ledgers`` is an iterable of per-host
        :class:`~kafkastreams_cep_tpu.utils.latency.LatencyLedger` to fold
        into one ``latency`` entry (ledgers are host-side, so the
        multi-host merge is the associative ``merge``, not a psum)."""
        from kafkastreams_cep_tpu.engine.matcher import TIER_COUNTER_NAMES

        out: Dict[str, object] = dict(self.stats(state))
        # Tiering is single-chip today (the hybrid scan host-gates the NFA
        # dispatch, which shard_map cannot): the tier counters ride the
        # merged snapshot as structural zeros so the fleet schema is one.
        out.update({n: 0 for n in TIER_COUNTER_NAMES})
        out["per_lane"] = self.per_lane_counters(state)
        per_stage = self.stage_counters(state)
        if per_stage:
            out["per_stage"] = per_stage
        if watermark is not None:
            now = clock if clock is not None else time.time
            out["watermark"] = int(watermark)
            out["event_time_lag_ms"] = int(now() * 1000) - int(watermark)
        if ledgers:
            merged = None
            for led in ledgers:
                merged = led if merged is None else merged.merge(led)
            out["latency"] = merged.snapshot()
        return out

    def sweep(self, state: EngineState) -> EngineState:
        """Slab mark-sweep over every shard (lane-elementwise — XLA keeps
        the existing sharding; no collectives)."""
        return self._sweep_jit(state)

    @functools.cached_property
    def _sweep_jit(self):
        from kafkastreams_cep_tpu.parallel.batch import sweep_lanes

        depth = self.matcher.config.max_walk
        do_renorm = self.matcher.config.renorm_versions

        def local(state: EngineState) -> EngineState:
            return sweep_lanes(state, depth, do_renorm)

        spec = P(self.axis)
        return jax.jit(
            _shard_map(
                local, mesh=self.mesh, in_specs=spec, out_specs=spec,
                check_vma=False,
            )
        )
