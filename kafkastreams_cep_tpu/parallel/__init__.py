"""Data-parallel and multi-chip execution of the array NFA engine.

The reference scales out with Kafka partition parallelism — one NFA per
(topic, partition), state externalized per partition, partitions spread
across tasks and instances (``CEPProcessor.java:117-134,160``).  The TPU
analog (SURVEY §2.2) is the **key axis**: every key lane owns an independent
fixed-shape engine state, so

* on one chip, lanes batch via ``vmap`` (:class:`BatchMatcher`), and
* across chips, the lane axis shards over a ``jax.sharding.Mesh`` via
  ``jax.shard_map`` (:class:`ShardedMatcher`) — matching itself needs no
  collectives (lanes never communicate, like Kafka partitions), while
  global diagnostics ride ``psum`` over ICI/DCN.
"""

from kafkastreams_cep_tpu.parallel.batch import BatchMatcher
from kafkastreams_cep_tpu.parallel.seqpar import TimeShardedStencil
from kafkastreams_cep_tpu.parallel.sharding import (
    ShardedMatcher,
    ShardLost,
    key_mesh,
    surviving_mesh,
)
from kafkastreams_cep_tpu.parallel.stacked import (
    StackedBankMatcher,
    choose_bank,
)
from kafkastreams_cep_tpu.parallel.tiered import TieredBatchMatcher

__all__ = [
    "BatchMatcher",
    "ShardLost",
    "ShardedMatcher",
    "StackedBankMatcher",
    "TieredBatchMatcher",
    "TimeShardedStencil",
    "choose_bank",
    "key_mesh",
    "surviving_mesh",
]
