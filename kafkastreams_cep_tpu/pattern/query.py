"""Fluent query DSL.

Three-phase builder mirroring the reference
(``pattern/QueryBuilder.java``, ``SelectBuilder.java``,
``PredicateBuilder.java``)::

    query = (
        Query()
        .select("first").where(lambda k, v, ts, st: v == "A")
        .then()
        .select("second").one_or_more().skip_till_next_match()
            .where(lambda k, v, ts, st: v == "B")
            .fold("count", lambda k, v, cur: cur + 1, init=0)
        .then()
        .select("last").where(lambda k, v, ts, st: v == "C")
            .within(1, "h")
        .build()
    )
"""

from __future__ import annotations

from typing import Any, Optional

from kafkastreams_cep_tpu.pattern.aggregator import StateAggregator
from kafkastreams_cep_tpu.pattern.pattern import Cardinality, Pattern, SelectStrategy


class Query:
    """Entry point: ``Query().select([name])`` (QueryBuilder.java:28,37)."""

    def select(self, name: Optional[str] = None) -> "SelectBuilder":
        return SelectBuilder(Pattern(name))


# Alias matching the reference class name.
QueryBuilder = Query


class SelectBuilder:
    """Cardinality + selection strategy phase (SelectBuilder.java:26-59)."""

    def __init__(self, pattern: Pattern):
        self._pattern = pattern

    def optional(self) -> "SelectBuilder":
        self._pattern.cardinality = Cardinality.OPTIONAL
        return self

    def one_or_more(self) -> "SelectBuilder":
        self._pattern.cardinality = Cardinality.ONE_OR_MORE
        return self

    def zero_or_more(self) -> "SelectBuilder":
        self._pattern.cardinality = Cardinality.ZERO_OR_MORE
        return self

    def skip_till_next_match(self) -> "SelectBuilder":
        self._pattern.strategy = SelectStrategy.SKIP_TIL_NEXT_MATCH
        return self

    def skip_till_any_match(self) -> "SelectBuilder":
        self._pattern.strategy = SelectStrategy.SKIP_TIL_ANY_MATCH
        return self

    def strict_contiguity(self) -> "SelectBuilder":
        self._pattern.strategy = SelectStrategy.STRICT_CONTIGUITY
        return self

    def where(self, matcher) -> "PredicateBuilder":
        self._pattern.add_predicate(matcher)
        return PredicateBuilder(self._pattern)


class PredicateBuilder:
    """Predicates / folds / window phase (PredicateBuilder.java:34-55)."""

    def __init__(self, pattern: Pattern):
        self._pattern = pattern

    def and_(self, matcher) -> "PredicateBuilder":
        self._pattern.add_predicate(matcher)
        return self

    def fold(
        self, state: str, aggregator, init: Any = 0, dtype: Any = None
    ) -> "PredicateBuilder":
        self._pattern.add_aggregator(
            StateAggregator(state, aggregator, init, dtype)
        )
        return self

    def within(self, time: float, unit: str = "ms") -> "PredicateBuilder":
        self._pattern.set_window(time, unit)
        return self

    def then(self) -> "Query":
        """Start the next stage, linked to this one (PredicateBuilder.java:49-51)."""
        return _ChainedQuery(self._pattern)

    def build(self) -> Pattern:
        return self._pattern


class _ChainedQuery(Query):
    def __init__(self, ancestor: Pattern):
        self._ancestor = ancestor

    def select(self, name: Optional[str] = None) -> SelectBuilder:
        return SelectBuilder(Pattern(name, ancestor=self._ancestor))
