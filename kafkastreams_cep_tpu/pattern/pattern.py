"""The pattern model: a linked chain of pattern stages.

Mirrors ``pattern/Pattern.java``: each stage holds a (AND-composed) predicate,
a cardinality, an event-selection strategy, an optional time window, and a
list of fold aggregates; stages link child -> ancestor
(``Pattern.java:102-104,176-178``), and unnamed stages default their name to
the level number (``Pattern.java:160-162``).
"""

from __future__ import annotations

import enum
from typing import Any, Callable, List, Optional

from kafkastreams_cep_tpu.pattern.aggregator import StateAggregator
from kafkastreams_cep_tpu.pattern.predicate import Matcher, and_


class Cardinality(enum.Enum):
    # Values as in Pattern.java:27-42.
    ZERO_OR_MORE = -2
    ONE_OR_MORE = -1
    OPTIONAL = 0
    ONE = 1


class SelectStrategy(enum.Enum):
    # Pattern.java:44-57.
    STRICT_CONTIGUITY = "strict_contiguity"
    SKIP_TIL_NEXT_MATCH = "skip_till_next_match"
    SKIP_TIL_ANY_MATCH = "skip_till_any_match"


_UNIT_MS = {
    "ms": 1,
    "milliseconds": 1,
    "s": 1000,
    "seconds": 1000,
    "m": 60_000,
    "minutes": 60_000,
    "h": 3_600_000,
    "hours": 3_600_000,
    "d": 86_400_000,
    "days": 86_400_000,
}


def to_millis(time: float, unit: str) -> int:
    try:
        return int(time * _UNIT_MS[unit.lower()])
    except KeyError:
        raise ValueError(f"unknown time unit {unit!r}; use one of {sorted(_UNIT_MS)}")


class Pattern:
    """One stage of a sequence pattern, linked to its ancestor."""

    def __init__(self, name: Optional[str] = None, ancestor: Optional["Pattern"] = None):
        self.level: int = ancestor.level + 1 if ancestor is not None else 0
        self._name = name
        self.ancestor = ancestor
        self.predicate: Optional[Matcher] = None
        self.window_time_ms: Optional[int] = None
        self.strategy: SelectStrategy = SelectStrategy.STRICT_CONTIGUITY
        self.cardinality: Cardinality = Cardinality.ONE
        self.aggregates: List[StateAggregator] = []

    # -- mutation used by the builders ---------------------------------
    def add_predicate(self, matcher) -> None:
        # AND-composition like Pattern.java:145-150.
        matcher = matcher if isinstance(matcher, Matcher) else Matcher(matcher)
        self.predicate = matcher if self.predicate is None else and_(self.predicate, matcher)

    def add_aggregator(self, agg: StateAggregator) -> None:
        self.aggregates.append(agg)

    def set_window(self, time: float, unit: str = "ms") -> None:
        self.window_time_ms = to_millis(time, unit)

    # -- accessors ------------------------------------------------------
    @property
    def name(self) -> str:
        # Unnamed stages take their level number (Pattern.java:160-162).
        return self._name if self._name is not None else str(self.level)

    def chain(self) -> List["Pattern"]:
        """The full pattern, newest stage first (Pattern.java:187-210)."""
        out, cur = [], self
        while cur is not None:
            out.append(cur)
            cur = cur.ancestor
        return out

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Pattern({self.name}, card={self.cardinality.name}, "
            f"strategy={self.strategy.name}, window={self.window_time_ms})"
        )
