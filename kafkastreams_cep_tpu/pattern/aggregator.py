"""Fold-aggregate state declarations.

The reference lets a stage register named fold functions
``(key, value, current) -> new`` evaluated only when an event is consumed
(``pattern/Aggregator.java:22-25``, ``nfa/NFA.java:248,260-265``), with the
state scoped per run and copied on Kleene branching
(``pattern/ValueStore.java:92-97``).

Deviation from the reference (documented): the Java implementation starts a
fresh run's fold state as ``null``; arrays cannot represent ``null``, so every
fold must declare an ``init`` value (default ``0``).  ``states.get(name)``
returns ``init`` until the first fold runs.  Patterns whose predicates only
read state that an earlier stage's fold always sets (the common case, e.g. the
SASE stock query) behave identically.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np

AggregatorFn = Callable[[Any, Any, Any], Any]


@dataclasses.dataclass(frozen=True)
class StateAggregator:
    """A named fold: ``fn(key, value, current) -> new`` with initial value.

    Mirrors ``pattern/StateAggregator.java:20-37`` plus the explicit ``init``.
    ``dtype`` is the device storage type of the state — the array analog of
    the reference's generic ``Aggregator<K, V, T>`` (``Aggregator.java:
    22-25``): ``"int32"`` folds stay exact past float32's 2^24 integer
    range, ``"float32"`` is IEEE single.  ``None`` infers from ``init``'s
    Python type (float -> float32, int/bool -> int32).  Fold return values
    are cast to the state dtype, like assigning to a typed Java field.
    """

    name: str
    fn: AggregatorFn
    init: Any = 0
    dtype: Any = None

    @property
    def resolved_dtype(self) -> str:
        if self.dtype is not None:
            d = str(self.dtype)
            if d not in ("int32", "float32"):
                raise ValueError(
                    f"fold state {self.name!r}: dtype must be 'int32' or "
                    f"'float32', got {self.dtype!r}"
                )
            return d
        kind = np.asarray(self.init).dtype
        if np.issubdtype(kind, np.floating):
            return "float32"
        if np.issubdtype(kind, np.integer) or np.issubdtype(kind, np.bool_):
            return "int32"
        raise ValueError(
            f"fold state {self.name!r}: cannot infer dtype from init "
            f"{self.init!r} (type {type(self.init).__name__}); pass "
            f"dtype='int32' or 'float32' explicitly"
        )
