"""Fold-aggregate state declarations.

The reference lets a stage register named fold functions
``(key, value, current) -> new`` evaluated only when an event is consumed
(``pattern/Aggregator.java:22-25``, ``nfa/NFA.java:248,260-265``), with the
state scoped per run and copied on Kleene branching
(``pattern/ValueStore.java:92-97``).

Deviation from the reference (documented): the Java implementation starts a
fresh run's fold state as ``null``; arrays cannot represent ``null``, so every
fold must declare an ``init`` value (default ``0``).  ``states.get(name)``
returns ``init`` until the first fold runs.  Patterns whose predicates only
read state that an earlier stage's fold always sets (the common case, e.g. the
SASE stock query) behave identically.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

AggregatorFn = Callable[[Any, Any, Any], Any]


@dataclasses.dataclass(frozen=True)
class StateAggregator:
    """A named fold: ``fn(key, value, current) -> new`` with initial value.

    Mirrors ``pattern/StateAggregator.java:20-37`` plus the explicit ``init``.
    """

    name: str
    fn: AggregatorFn
    init: Any = 0
