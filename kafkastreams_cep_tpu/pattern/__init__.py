from kafkastreams_cep_tpu.pattern.pattern import Pattern, Cardinality, SelectStrategy
from kafkastreams_cep_tpu.pattern.predicate import Matcher, and_, or_, not_, true_
from kafkastreams_cep_tpu.pattern.aggregator import StateAggregator
from kafkastreams_cep_tpu.pattern.query import Query, QueryBuilder

__all__ = [
    "Pattern",
    "Cardinality",
    "SelectStrategy",
    "Matcher",
    "and_",
    "or_",
    "not_",
    "true_",
    "StateAggregator",
    "Query",
    "QueryBuilder",
]
