"""Predicate algebra for pattern guards.

A matcher is a function ``(key, value, timestamp, states) -> bool`` — the same
signature as the reference's ``Matcher.matches`` (``pattern/Matcher.java:22``)
— plus the combinators ``not_``/``and_``/``or_``
(``pattern/Matcher.java:24-70``).

Matchers must be written so they are **JAX-traceable**: the ``bool`` they
return may be a traced ``jnp.bool_`` scalar when evaluated inside the array
engine, and a plain Python bool when evaluated by the host oracle.  ``states``
is a read-only view over the per-run fold state (see
``pattern/aggregator.py``); inside the array engine its values are traced
scalars.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

MatcherFn = Callable[[Any, Any, Any, Any], Any]


class Matcher:
    """A named, composable guard over ``(key, value, timestamp, states)``."""

    __slots__ = ("fn", "label")

    def __init__(self, fn: MatcherFn, label: Optional[str] = None):
        if isinstance(fn, Matcher):
            fn, label = fn.fn, label or fn.label
        if not callable(fn):
            raise TypeError(f"matcher must be callable, got {type(fn)!r}")
        self.fn = fn
        self.label = label or getattr(fn, "__name__", "matcher")

    def __call__(self, key, value, timestamp, states):
        return self.fn(key, value, timestamp, states)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Matcher({self.label})"


def _wrap(m) -> Matcher:
    return m if isinstance(m, Matcher) else Matcher(m)


def _normalize(result):
    """Coerce plain host values to bool; leave traced/array values alone.

    Bitwise ``~``/``&``/``|`` are the only operators traced booleans support,
    but they are wrong for plain truthy ints (``~1 == -2`` is truthy), so host
    scalars are normalized to ``bool`` first.
    """
    if isinstance(result, bool):
        return result
    if not hasattr(result, "shape") and not hasattr(result, "dtype"):
        # Any non-array host value (int, None, '', lists...): Python truth.
        # Only traced/array values pass through to the bitwise path.
        return bool(result)
    return result


def not_(matcher) -> Matcher:
    m = _wrap(matcher)

    def fn(key, value, timestamp, states):
        result = _normalize(m(key, value, timestamp, states))
        return (not result) if isinstance(result, bool) else ~result

    return Matcher(fn, label=f"not({m.label})")


def and_(left, right) -> Matcher:
    l, r = _wrap(left), _wrap(right)

    def fn(key, value, timestamp, states):
        lv = _normalize(l(key, value, timestamp, states))
        rv = _normalize(r(key, value, timestamp, states))
        if isinstance(lv, bool) and isinstance(rv, bool):
            return lv and rv
        return lv & rv

    return Matcher(fn, label=f"and({l.label},{r.label})")


def or_(left, right) -> Matcher:
    l, r = _wrap(left), _wrap(right)

    def fn(key, value, timestamp, states):
        lv = _normalize(l(key, value, timestamp, states))
        rv = _normalize(r(key, value, timestamp, states))
        if isinstance(lv, bool) and isinstance(rv, bool):
            return lv or rv
        return lv | rv

    return Matcher(fn, label=f"or({l.label},{r.label})")


def true_() -> Matcher:
    return Matcher(lambda key, value, timestamp, states: True, label="true")
