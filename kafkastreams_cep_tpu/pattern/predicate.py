"""Predicate algebra for pattern guards.

A matcher is a function ``(key, value, timestamp, states) -> bool`` — the same
signature as the reference's ``Matcher.matches`` (``pattern/Matcher.java:22``)
— plus the combinators ``not_``/``and_``/``or_``
(``pattern/Matcher.java:24-70``).

Matchers must be written so they are **JAX-traceable**: the ``bool`` they
return may be a traced ``jnp.bool_`` scalar when evaluated inside the array
engine, and a plain Python bool when evaluated by the host oracle.  ``states``
is a read-only view over the per-run fold state (see
``pattern/aggregator.py``); inside the array engine its values are traced
scalars.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

MatcherFn = Callable[[Any, Any, Any, Any], Any]


class Matcher:
    """A named, composable guard over ``(key, value, timestamp, states)``.

    Combinator structure is recorded (``op``/``parts``) so compile-time
    passes can see through it: ``and_`` chains are commuting conjunct
    lists the tiering pass (``compiler/tiering.py``) may reorder by
    selectivity/cost without changing semantics.  ``cost_hint`` and
    ``selectivity_hint`` are optional user annotations consumed by that
    pass's static cost model (see :func:`hint`); neither affects what the
    matcher computes.
    """

    __slots__ = ("fn", "label", "op", "parts", "cost_hint", "selectivity_hint")

    def __init__(self, fn: MatcherFn, label: Optional[str] = None):
        if isinstance(fn, Matcher):
            fn, label = fn.fn, label or fn.label
        if not callable(fn):
            raise TypeError(f"matcher must be callable, got {type(fn)!r}")
        self.fn = fn
        self.label = label or getattr(fn, "__name__", "matcher")
        self.op: Optional[str] = None  # "and" | "or" | "not" for combinators
        self.parts: tuple = ()
        self.cost_hint: Optional[float] = None
        self.selectivity_hint: Optional[float] = None

    def __call__(self, key, value, timestamp, states):
        return self.fn(key, value, timestamp, states)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Matcher({self.label})"


def _wrap(m) -> Matcher:
    return m if isinstance(m, Matcher) else Matcher(m)


def _normalize(result):
    """Coerce plain host values to bool; leave traced/array values alone.

    Bitwise ``~``/``&``/``|`` are the only operators traced booleans support,
    but they are wrong for plain truthy ints (``~1 == -2`` is truthy), so host
    scalars are normalized to ``bool`` first.
    """
    if isinstance(result, bool):
        return result
    if not hasattr(result, "shape") and not hasattr(result, "dtype"):
        # Any non-array host value (int, None, '', lists...): Python truth.
        # Only traced/array values pass through to the bitwise path.
        return bool(result)
    return result


def not_(matcher) -> Matcher:
    m = _wrap(matcher)

    def fn(key, value, timestamp, states):
        result = _normalize(m(key, value, timestamp, states))
        return (not result) if isinstance(result, bool) else ~result

    out = Matcher(fn, label=f"not({m.label})")
    out.op, out.parts = "not", (m,)
    return out


def and_(left, right) -> Matcher:
    l, r = _wrap(left), _wrap(right)

    def fn(key, value, timestamp, states):
        lv = _normalize(l(key, value, timestamp, states))
        rv = _normalize(r(key, value, timestamp, states))
        if isinstance(lv, bool) and isinstance(rv, bool):
            return lv and rv
        return lv & rv

    out = Matcher(fn, label=f"and({l.label},{r.label})")
    out.op, out.parts = "and", (l, r)
    return out


def or_(left, right) -> Matcher:
    l, r = _wrap(left), _wrap(right)

    def fn(key, value, timestamp, states):
        lv = _normalize(l(key, value, timestamp, states))
        rv = _normalize(r(key, value, timestamp, states))
        if isinstance(lv, bool) and isinstance(rv, bool):
            return lv or rv
        return lv | rv

    out = Matcher(fn, label=f"or({l.label},{r.label})")
    out.op, out.parts = "or", (l, r)
    return out


def hint(matcher, cost: Optional[float] = None,
         selectivity: Optional[float] = None) -> Matcher:
    """Annotate a matcher with a relative evaluation cost and/or an
    expected accept fraction (0..1).  Pure metadata for the lazy-chain
    ordering pass (``compiler/tiering.py: apply_lazy_order``): cheap,
    selective conjuncts are ordered ahead of expensive ones.  Returns the
    (wrapped) matcher itself."""
    m = _wrap(matcher)
    if cost is not None:
        m.cost_hint = float(cost)
    if selectivity is not None:
        m.selectivity_hint = float(selectivity)
    return m


def true_() -> Matcher:
    return Matcher(lambda key, value, timestamp, states: True, label="true")
