"""Native host-runtime bindings: C++ batch packing + JSON-lines parsing.

The compute path is JAX/XLA (``engine/``); the host runtime around it —
grouping micro-batches into lanes, scattering columns into device-ready
``[K, T]`` grids, and parsing the JSON ingest boundary — is native C++
(``src/ingest.cpp``), the part the reference delegates to the JVM and its
serdes (``CEPProcessor.java:154-163``, ``demo/StockEventSerDe.java:50-89``).

The shared library is built lazily with ``g++`` on first use and cached
under ``~/.cache/kafkastreams_cep_tpu`` keyed by source hash; loading is via
``ctypes`` (no pybind11 in this environment).  Every entry point has a pure
NumPy fallback with identical semantics — ``native.available()`` says which
is active, and ``CEP_NO_NATIVE=1`` forces the fallback (used by the
differential tests in ``tests/test_native.py``).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("native")

_SRC_DIR = Path(__file__).parent / "src"
_SRC = _SRC_DIR / "ingest.cpp"  # ABI anchor; all .cpp files are compiled
_ABI_VERSION = 1

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _cache_dir() -> Path:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    d = Path(base) / "kafkastreams_cep_tpu"
    d.mkdir(parents=True, exist_ok=True)
    return d


def _build() -> Optional[Path]:
    try:
        sources = sorted(_SRC_DIR.glob("*.cpp"))
        if not sources:
            raise OSError(f"no .cpp sources under {_SRC_DIR}")
        blob = b"\0".join(s.read_bytes() for s in sources)
    except OSError as e:
        # e.g. a wheel built without the .cpp in package data.
        logger.warning("native source unavailable (%s); using NumPy fallbacks", e)
        return None
    tag = hashlib.sha256(blob).hexdigest()[:16]
    out = _cache_dir() / f"libcepingest-{tag}.so"
    if out.exists():
        return out
    # Build in the cache dir itself so the atomic-publish rename below never
    # crosses filesystems (tmpfs /tmp vs on-disk home would raise EXDEV).
    with tempfile.TemporaryDirectory(dir=_cache_dir()) as tmp:
        tmp_out = Path(tmp) / out.name
        cmd = [
            "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
            *[str(s) for s in sources], "-o", str(tmp_out),
        ]
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, timeout=120
            )
            # Atomic publish so concurrent builders race benignly.
            os.replace(tmp_out, out)
        except OSError as e:
            logger.warning(
                "native build failed (%s); using NumPy fallbacks", e
            )
            return None
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
            detail = getattr(e, "stderr", b"")
            logger.warning(
                "native build failed (%s); using NumPy fallbacks: %s",
                type(e).__name__,
                detail.decode() if isinstance(detail, bytes) else detail,
            )
            return None
    logger.info("built native ingest library: %s", out)
    return out


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("CEP_NO_NATIVE"):
        logger.info("CEP_NO_NATIVE set; using NumPy fallbacks")
        return None
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError as e:
        logger.warning("native load failed (%s); using NumPy fallbacks", e)
        return None

    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    f64p = ctypes.POINTER(ctypes.c_double)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32, i64 = ctypes.c_int32, ctypes.c_int64

    lib.cep_native_abi_version.restype = i32
    if lib.cep_native_abi_version() != _ABI_VERSION:
        logger.warning("native ABI mismatch; using NumPy fallbacks")
        return None

    lib.cep_queue_positions.restype = i32
    lib.cep_queue_positions.argtypes = [i32p, u8p, i64, i32, i32p, i32p]
    for name, vp in (
        ("cep_pack_i32", i32p),
        ("cep_pack_f32", f32p),
        ("cep_pack_i64", i64p),
    ):
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = [vp, vp, i32p, i32p, u8p, i64, i64]
    lib.cep_pack_valid.restype = None
    lib.cep_pack_valid.argtypes = [u8p, i32p, i32p, u8p, i64, i64]
    lib.cep_parse_json_lines.restype = i64
    lib.cep_parse_json_lines.argtypes = [
        ctypes.c_char_p, i64, ctypes.c_char_p, i32, ctypes.c_char_p,
        f64p, ctypes.c_char_p, i64, u8p, i64, i64p,
    ]
    lib.cep_journal_append.restype = i32
    lib.cep_journal_append.argtypes = [ctypes.c_char_p, u8p, i64, i32]
    lib.cep_journal_scan.restype = i64
    lib.cep_journal_scan.argtypes = [u8p, i64, i64p, i64, i64p]
    _lib = lib
    return _lib


def available() -> bool:
    """True when the C++ library is loaded (False = NumPy fallbacks)."""
    return _load() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


# ---------------------------------------------------------------------------
# Lane-queue positions


def queue_positions(
    lanes: np.ndarray, keep: np.ndarray, num_lanes: int
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Per-record position within its lane queue, queue lengths, and max
    queue length.  ``lanes[i]`` is record ``i``'s lane; ``keep[i]`` masks
    dropped records (position -1)."""
    lanes = np.ascontiguousarray(lanes, dtype=np.int32)
    keep = np.ascontiguousarray(keep, dtype=np.uint8)
    n = lanes.shape[0]
    pos = np.empty(n, dtype=np.int32)
    qlen = np.zeros(num_lanes, dtype=np.int32)
    lib = _load()
    if lib is not None:
        max_len = lib.cep_queue_positions(
            _ptr(lanes, ctypes.c_int32), _ptr(keep, ctypes.c_uint8),
            n, num_lanes, _ptr(pos, ctypes.c_int32),
            _ptr(qlen, ctypes.c_int32),
        )
        return pos, qlen, int(max_len)
    # NumPy fallback: position = rank of the record among kept records of
    # its lane, in arrival order.
    pos.fill(-1)
    kept = keep.astype(bool)
    idx = np.nonzero(kept)[0]
    if idx.size:
        kl = lanes[idx]
        order = np.argsort(kl, kind="stable")
        sor = kl[order]
        starts = np.r_[0, np.nonzero(np.diff(sor))[0] + 1]
        ranks = np.arange(sor.size) - np.repeat(starts, np.diff(np.r_[starts, sor.size]))
        pos[idx[order]] = ranks.astype(np.int32)
        counts = np.bincount(kl, minlength=num_lanes)
        qlen[: counts.size] = counts.astype(np.int32)
    return pos, qlen, int(qlen.max(initial=0))


# ---------------------------------------------------------------------------
# Columnar scatter


def pack_column(
    dst: np.ndarray,
    src: np.ndarray,
    lanes: np.ndarray,
    pos: np.ndarray,
    keep: np.ndarray,
) -> None:
    """``dst[lanes[i], pos[i]] = src[i]`` for every kept record.

    ``dst`` must be C-contiguous ``[K, T]``; dtype must be int32, float32,
    or int64 (the runtime's column types)."""
    lanes = np.ascontiguousarray(lanes, dtype=np.int32)
    pos = np.ascontiguousarray(pos, dtype=np.int32)
    keep_u8 = np.ascontiguousarray(keep, dtype=np.uint8)
    src = np.ascontiguousarray(src, dtype=dst.dtype)
    assert dst.flags.c_contiguous
    lib = _load()
    if lib is not None:
        n, T = lanes.shape[0], dst.shape[1]
        if dst.dtype == np.int32:
            lib.cep_pack_i32(
                _ptr(dst, ctypes.c_int32), _ptr(src, ctypes.c_int32),
                _ptr(lanes, ctypes.c_int32), _ptr(pos, ctypes.c_int32),
                _ptr(keep_u8, ctypes.c_uint8), n, T,
            )
        elif dst.dtype == np.float32:
            lib.cep_pack_f32(
                _ptr(dst, ctypes.c_float), _ptr(src, ctypes.c_float),
                _ptr(lanes, ctypes.c_int32), _ptr(pos, ctypes.c_int32),
                _ptr(keep_u8, ctypes.c_uint8), n, T,
            )
        elif dst.dtype == np.int64:
            lib.cep_pack_i64(
                _ptr(dst, ctypes.c_int64), _ptr(src, ctypes.c_int64),
                _ptr(lanes, ctypes.c_int32), _ptr(pos, ctypes.c_int32),
                _ptr(keep_u8, ctypes.c_uint8), n, T,
            )
        else:  # pragma: no cover - guarded by runtime column types
            raise TypeError(f"unsupported pack dtype {dst.dtype}")
        return
    m = keep.astype(bool)
    dst[lanes[m], pos[m]] = src[m]


def pack_valid(
    dst: np.ndarray, lanes: np.ndarray, pos: np.ndarray, keep: np.ndarray
) -> None:
    """Set ``dst[lanes[i], pos[i]] = True`` for every kept record (``dst``
    is the boolean validity grid)."""
    lanes = np.ascontiguousarray(lanes, dtype=np.int32)
    pos = np.ascontiguousarray(pos, dtype=np.int32)
    keep_u8 = np.ascontiguousarray(keep, dtype=np.uint8)
    lib = _load()
    if lib is not None and dst.dtype == np.bool_ and dst.flags.c_contiguous:
        lib.cep_pack_valid(
            _ptr(dst, ctypes.c_uint8), _ptr(lanes, ctypes.c_int32),
            _ptr(pos, ctypes.c_int32), _ptr(keep_u8, ctypes.c_uint8),
            lanes.shape[0], dst.shape[1],
        )
        return
    m = keep.astype(bool)
    dst[lanes[m], pos[m]] = True


# ---------------------------------------------------------------------------
# JSON-lines parsing


def parse_json_lines(
    text: bytes,
    fields: Sequence[str],
    key_field: str = "",
    key_width: int = 32,
) -> Tuple[np.ndarray, List[Optional[str]], np.ndarray]:
    """Parse newline-separated flat JSON objects into columns.

    Returns ``(values[n, F] float64, keys[n], ok[n] bool)`` where ``keys``
    holds the ``key_field`` string of each line (None when absent, empty, or
    when the line failed the fast parse).  Lines with ``ok=False`` should be
    re-parsed by the caller with a full JSON parser — the fast path rejects
    (rather than interprets) anything outside its fragment: nested
    containers, escapes, booleans/null in numeric fields, keys longer than
    ``key_width``.  Lines are ``\n``-separated (no bare-``\r`` splitting).
    Both paths implement this contract identically.
    """
    if isinstance(text, str):
        text = text.encode("utf-8")
    F = len(fields)
    if not text:
        return (
            np.zeros((0, F), dtype=np.float64),
            [],
            np.zeros(0, dtype=bool),
        )
    n_lines = text.count(b"\n") + (0 if text.endswith(b"\n") else 1)
    values = np.full((max(n_lines, 1), F), np.nan, dtype=np.float64)
    ok = np.zeros(max(n_lines, 1), dtype=np.uint8)
    keys_buf = np.zeros((max(n_lines, 1), key_width), dtype=np.uint8)

    lib = _load()
    if lib is not None and n_lines:
        names_blob = b"".join(f.encode() + b"\0" for f in fields)
        n_bad = ctypes.c_int64(0)
        consumed = lib.cep_parse_json_lines(
            text, len(text), names_blob, F, key_field.encode(),
            _ptr(values, ctypes.c_double),
            keys_buf.ctypes.data_as(ctypes.c_char_p), key_width,
            _ptr(ok, ctypes.c_uint8), n_lines, ctypes.byref(n_bad),
        )
        if consumed >= 0:
            keys: List[Optional[str]] = []
            for i in range(n_lines):
                if ok[i] and key_field:
                    raw = bytes(keys_buf[i]).rstrip(b"\0")
                    keys.append(raw.decode("utf-8", "replace") or None)
                else:
                    keys.append(None)
            return values[:n_lines], keys, ok[:n_lines].astype(bool)

    # Pure-Python fallback — same accept/reject contract as the C++ path.
    import json
    import math

    def _tofloat(v):
        # Match strtod: JSON integer literals beyond float range are ±inf.
        try:
            return float(v)
        except OverflowError:
            return math.inf if v > 0 else -math.inf

    keys = []
    # errors="replace" mirrors the native path: invalid bytes fail a line's
    # JSON parse (outside strings) or survive as U+FFFD inside key strings,
    # never crash.
    lines = text.decode("utf-8", errors="replace").split("\n")
    if lines and lines[-1] == "" and text.endswith(b"\n"):
        lines.pop()
    lines = lines[: values.shape[0]]
    for i, line in enumerate(lines):
        row = None
        key: Optional[str] = None
        # The native path fails any string containing a backslash (no
        # escape handling); match that before handing to the full parser.
        if "\\" in line:
            keys.append(None)
            continue
        try:
            obj = json.loads(line)
            if (
                isinstance(obj, dict)
                and not any(
                    isinstance(v, (bool, dict, list)) or v is None
                    for v in obj.values()
                )
                and all(isinstance(obj.get(f), (int, float)) for f in fields)
            ):
                row = [_tofloat(obj[f]) for f in fields]
                if key_field:
                    raw = obj.get(key_field)
                    if isinstance(raw, str):
                        if len(raw.encode("utf-8")) > key_width:
                            row = None  # native: key too wide fails the line
                        else:
                            key = raw or None
        except (ValueError, KeyError, TypeError):
            row = None
        if row is None:
            keys.append(None)
            continue
        values[i] = row
        ok[i] = 1
        keys.append(key)
    n = len(lines)
    return values[:n], keys, ok[:n].astype(bool)
