"""Durable record journal — the Kafka changelog-segment analog.

The reference's recovery story rests on broker log segments: state stores
are changelog-backed, so a restarted task replays the log to rebuild state
(SURVEY §5, ``CEPProcessor.java:144-149``).  Here the supervisor pairs
array checkpoints with this journal: every processed batch is appended as
one CRC32-framed payload, and after *any* failure — device loss or a full
process crash — the journal's intact prefix replays deterministically on
top of the last checkpoint.

Writes go through the native C++ path (``src/journal.cpp``, one syscall
per batch, optional fsync) when the shared library is available; the pure
Python fallback produces byte-identical files (same framing, same zlib
CRC32), so journals are fully interchangeable between the two.

A torn final frame (crash mid-write) is detected by magic/length/CRC
validation and simply ends the replay — exactly a log truncated at the
last good record.  ``Journal.replay`` also *repairs* the file by
truncating the corrupt tail so subsequent appends never interleave with
garbage.
"""

from __future__ import annotations

import ctypes
import os
import struct
import zlib
from typing import Iterator, List, Optional

import numpy as np

from kafkastreams_cep_tpu import native as _native
from kafkastreams_cep_tpu.utils.failpoints import fire as _failpoint
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("native.journal")

MAGIC = 0x43455031  # "CEP1"
_HEADER = struct.Struct("<III")  # magic, payload_len, crc32


class Journal:
    """Append-only CRC-framed payload log at ``path``.

    ``sync=True`` fsyncs every append (machine-crash durable); the default
    covers process crashes only, like Kafka's default ``flush.messages``.
    """

    def __init__(self, path: str, sync: bool = False):
        self.path = str(path)
        self.sync = bool(sync)

    # -- writing ------------------------------------------------------------

    def append(self, payload: bytes) -> None:
        payload = bytes(payload)
        # Fault site: an append that fails before anything reaches the file
        # (EROFS, ENOSPC at open) — see utils/failpoints.py.
        _failpoint("journal.append")
        # Remember the last good boundary: a failed append may leave a torn
        # frame that would make every LATER (successful) frame unreachable
        # on replay — roll back to this size before reporting the failure.
        try:
            size0 = os.path.getsize(self.path)
        except OSError:
            size0 = 0
        try:
            self._append(payload)
            # Fault site at the durability barrier: the frame bytes reached
            # the OS but the fsync (or the write itself, native path) is
            # reported failed — the except clause below rolls the frame
            # back so the on-disk journal stays a clean frame prefix.
            _failpoint("journal.fsync")
        except Exception:
            self._rollback(size0)
            raise

    def _append(self, payload: bytes) -> None:
        lib = _native._load()
        if lib is not None:
            # Zero-copy borrow: c_char_p points at the bytes object's
            # buffer, which the C side only reads.
            buf = ctypes.cast(
                ctypes.c_char_p(payload or b"\0"),
                ctypes.POINTER(ctypes.c_uint8),
            )
            rc = lib.cep_journal_append(
                self.path.encode(), buf, len(payload), 1 if self.sync else 0
            )
            if rc != 0:
                raise OSError(f"journal append failed (rc={rc}): {self.path}")
            return
        frame = _HEADER.pack(MAGIC, len(payload), zlib.crc32(payload))
        with open(self.path, "ab") as f:
            f.write(frame + payload)
            f.flush()
            if self.sync:
                os.fsync(f.fileno())

    def _rollback(self, size: int) -> None:
        try:
            if os.path.getsize(self.path) > size:
                with open(self.path, "r+b") as f:
                    f.truncate(size)
        except FileNotFoundError:
            return  # nothing was written — nothing to roll back
        except OSError:
            logger.exception(
                "journal %s: rollback after failed append also failed; "
                "later frames may be unreachable until replay repairs",
                self.path,
            )

    # -- reading ------------------------------------------------------------

    def _scan(self, data: bytes) -> tuple:
        """(frame spans, intact-prefix length) of ``data``."""
        lib = _native._load()
        if lib is not None and data:
            max_frames = max(len(data) // _HEADER.size, 1)
            out = np.empty(2 * max_frames, dtype=np.int64)
            valid = ctypes.c_int64(0)
            buf = ctypes.cast(
                ctypes.c_char_p(data), ctypes.POINTER(ctypes.c_uint8)
            )
            n = lib.cep_journal_scan(
                buf, len(data),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                max_frames, ctypes.byref(valid),
            )
            spans = [(int(out[2 * i]), int(out[2 * i + 1])) for i in range(n)]
            return spans, int(valid.value)
        spans: List[tuple] = []
        pos = 0
        while pos + _HEADER.size <= len(data):
            magic, plen, crc = _HEADER.unpack_from(data, pos)
            if magic != MAGIC:
                break
            start = pos + _HEADER.size
            if start + plen > len(data):
                break  # truncated tail
            if zlib.crc32(data[start:start + plen]) != crc:
                break  # corrupt
            spans.append((start, plen))
            pos = start + plen
        return spans, pos

    def replay(self, repair: bool = True) -> Iterator[bytes]:
        """Yield every intact payload in order; optionally truncate a
        corrupt/torn tail so future appends start at a clean boundary."""
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return
        spans, valid = self._scan(data)
        if repair and valid < len(data):
            logger.warning(
                "journal %s: truncating %d corrupt tail bytes after %d "
                "intact frames", self.path, len(data) - valid, len(spans),
            )
            with open(self.path, "r+b") as f:
                f.truncate(valid)
        for start, plen in spans:
            yield data[start:start + plen]

    # -- lifecycle ----------------------------------------------------------

    def truncate(self) -> None:
        """Drop all frames (checkpoint taken; the tail restarts empty)."""
        with open(self.path, "wb"):
            pass

    def delete(self) -> None:
        try:
            os.remove(self.path)
        except FileNotFoundError:
            pass
