// Native host-runtime kernels: columnar batch packing + JSON-lines event
// parsing.
//
// The reference's ingest boundary is Kafka Streams handing one deserialized
// record at a time to CEPProcessor.process() (CEPProcessor.java:154-163),
// with serdes (serde/KryoSerDe.java, demo StockEventSerDe.java:50-89) doing
// byte<->object work in the JVM.  Here the ingest boundary feeds a TPU: the
// host must group a micro-batch of records into per-key lanes and scatter
// them into rectangular [K, T] device-ready arrays.  That packing is pure
// pointer chasing — the part of the runtime that belongs in native code, not
// in Python loops.  Exposed extern "C" and loaded via ctypes
// (kafkastreams_cep_tpu/native/__init__.py), with NumPy fallbacks.
//
// Build: g++ -O3 -march=native -shared -fPIC ingest.cpp -o libcepingest.so

#include <cstdint>
#include <cstring>
#include <cmath>
#include <cstdlib>

extern "C" {

// ---------------------------------------------------------------------------
// Lane-queue positioning: for each kept record (arrival order), its position
// within its lane's queue this batch.  qlen_out[K] receives final queue
// lengths.  Returns the max queue length (the T to pad to), 0 if empty.
int32_t cep_queue_positions(const int32_t* lanes, const uint8_t* keep,
                            int64_t n, int32_t num_lanes, int32_t* pos_out,
                            int32_t* qlen_out) {
  for (int32_t k = 0; k < num_lanes; ++k) qlen_out[k] = 0;
  int32_t max_len = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (!keep[i]) {
      pos_out[i] = -1;
      continue;
    }
    int32_t lane = lanes[i];
    int32_t p = qlen_out[lane]++;
    pos_out[i] = p;
    if (qlen_out[lane] > max_len) max_len = qlen_out[lane];
  }
  return max_len;
}

// Scatter one int32 column into its [K, T] slot grid.
void cep_pack_i32(int32_t* dst, const int32_t* src, const int32_t* lanes,
                  const int32_t* pos, const uint8_t* keep, int64_t n,
                  int64_t T) {
  for (int64_t i = 0; i < n; ++i)
    if (keep[i]) dst[(int64_t)lanes[i] * T + pos[i]] = src[i];
}

// Scatter one float32 column into its [K, T] slot grid.
void cep_pack_f32(float* dst, const float* src, const int32_t* lanes,
                  const int32_t* pos, const uint8_t* keep, int64_t n,
                  int64_t T) {
  for (int64_t i = 0; i < n; ++i)
    if (keep[i]) dst[(int64_t)lanes[i] * T + pos[i]] = src[i];
}

// Scatter one int64 column (arrival ranks) into its [K, T] slot grid.
void cep_pack_i64(int64_t* dst, const int64_t* src, const int32_t* lanes,
                  const int32_t* pos, const uint8_t* keep, int64_t n,
                  int64_t T) {
  for (int64_t i = 0; i < n; ++i)
    if (keep[i]) dst[(int64_t)lanes[i] * T + pos[i]] = src[i];
}

// Mark valid slots in the [K, T] grid.
void cep_pack_valid(uint8_t* dst, const int32_t* lanes, const int32_t* pos,
                    const uint8_t* keep, int64_t n, int64_t T) {
  for (int64_t i = 0; i < n; ++i)
    if (keep[i]) dst[(int64_t)lanes[i] * T + pos[i]] = 1;
}

// ---------------------------------------------------------------------------
// JSON-lines parsing: flat objects with numeric fields and at most one
// string field of interest (the record key), e.g. the demo's
// {"name":"e1","price":100,"volume":1010} (StockEventSerDe.java:50-89).
//
// Restrictions (by design — this is a columnar fast path, not a general
// JSON library): no nested objects/arrays, no escapes inside the key
// string, numbers are doubles.  Lines failing to parse are skipped and
// counted; the caller can fall back to Python json for them.

namespace {

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

// Parse a JSON string token at p (pointing at '"'); returns pointer past the
// closing quote, writes [start, len) of the contents. No escape handling —
// a backslash fails the parse (caller falls back).
inline const char* parse_string(const char* p, const char* end,
                                const char** start, int64_t* len) {
  if (p >= end || *p != '"') return nullptr;
  ++p;
  *start = p;
  while (p < end && *p != '"') {
    if (*p == '\\') return nullptr;
    ++p;
  }
  if (p >= end) return nullptr;
  *len = p - *start;
  return p + 1;
}

}  // namespace

// Parse up to max_lines newline-separated JSON objects from buf.
//
//   field_names: num_fields zero-terminated numeric field names, back to back
//   key_field:   zero-terminated name of the string key field ("" = none)
//   num_out:     [max_lines, num_fields] doubles (NaN = field absent)
//   key_out:     [max_lines, key_width] bytes, zero-padded
//   line_ok:     [max_lines] 1 = parsed, 0 = skipped (caller falls back)
//
// Returns the number of lines consumed (parsed or skipped); *n_bad receives
// the number skipped.
int64_t cep_parse_json_lines(const char* buf, int64_t len,
                             const char* field_names, int32_t num_fields,
                             const char* key_field, double* num_out,
                             char* key_out, int64_t key_width,
                             uint8_t* line_ok, int64_t max_lines,
                             int64_t* n_bad) {
  // Decode the field-name table once.
  const char* names[64];
  int64_t name_lens[64];
  if (num_fields > 64) return -1;
  {
    const char* p = field_names;
    for (int32_t f = 0; f < num_fields; ++f) {
      names[f] = p;
      name_lens[f] = (int64_t)strlen(p);
      p += name_lens[f] + 1;
    }
  }
  const int64_t key_len_name = (int64_t)strlen(key_field);

  const char* p = buf;
  const char* end = buf + len;
  int64_t line = 0;
  *n_bad = 0;

  while (p < end && line < max_lines) {
    const char* line_end = (const char*)memchr(p, '\n', end - p);
    if (!line_end) line_end = end;
    const char* q = skip_ws(p, line_end);

    double* row = num_out + line * num_fields;
    for (int32_t f = 0; f < num_fields; ++f) row[f] = NAN;
    char* krow = key_out + line * key_width;
    if (key_width > 0) memset(krow, 0, key_width);

    bool ok = q < line_end && *q == '{';
    if (ok) {
      ++q;
      for (;;) {
        q = skip_ws(q, line_end);
        if (q < line_end && *q == '}') {
          ++q;
          break;
        }
        const char* fname;
        int64_t fname_len;
        q = parse_string(q, line_end, &fname, &fname_len);
        if (!q) { ok = false; break; }
        q = skip_ws(q, line_end);
        if (q >= line_end || *q != ':') { ok = false; break; }
        q = skip_ws(q + 1, line_end);
        if (q >= line_end) { ok = false; break; }

        if (*q == '"') {  // string value
          const char* vstart;
          int64_t vlen;
          q = parse_string(q, line_end, &vstart, &vlen);
          if (!q) { ok = false; break; }
          if (key_width > 0 && fname_len == key_len_name &&
              memcmp(fname, key_field, fname_len) == 0) {
            if (vlen > key_width) { ok = false; break; }  // key too wide
            memset(krow, 0, key_width);  // duplicated field: last one wins
            memcpy(krow, vstart, vlen);
          }
        } else {  // numeric value (true/false/null fail the grammar check)
          char* numend = nullptr;
          double v = strtod(q, &numend);
          if (numend == q || numend > line_end) { ok = false; break; }
          // The consumed token must match the exact JSON number grammar —
          // strtod alone also accepts inf/nan/hex, leading zeros ("01"),
          // bare trailing dots ("1."), and "1.e3", all of which json.loads
          // (the fallback) rejects.
          const char* c = q;
          if (c < numend && *c == '-') ++c;
          if (c < numend && *c == '0') {
            ++c;  // a leading 0 must be the whole integer part
          } else if (c < numend && *c >= '1' && *c <= '9') {
            while (c < numend && *c >= '0' && *c <= '9') ++c;
          } else {
            ok = false;
            break;
          }
          if (c < numend && *c == '.') {
            ++c;
            if (c >= numend || *c < '0' || *c > '9') { ok = false; break; }
            while (c < numend && *c >= '0' && *c <= '9') ++c;
          }
          if (c < numend && (*c == 'e' || *c == 'E')) {
            ++c;
            if (c < numend && (*c == '+' || *c == '-')) ++c;
            if (c >= numend || *c < '0' || *c > '9') { ok = false; break; }
            while (c < numend && *c >= '0' && *c <= '9') ++c;
          }
          if (c != numend) { ok = false; break; }
          for (int32_t f = 0; f < num_fields; ++f) {
            if (fname_len == name_lens[f] &&
                memcmp(fname, names[f], fname_len) == 0) {
              row[f] = v;
              break;
            }
          }
          q = numend;
        }

        q = skip_ws(q, line_end);
        if (q < line_end && *q == ',') { ++q; continue; }
        if (q < line_end && *q == '}') { ++q; break; }
        ok = false;
        break;
      }
      // Trailing garbage after the closing brace fails the line.
      if (ok && skip_ws(q, line_end) != line_end) ok = false;
      // All requested numeric fields must be present.
      if (ok)
        for (int32_t f = 0; f < num_fields; ++f)
          if (std::isnan(row[f])) { ok = false; break; }
    }

    line_ok[line] = ok ? 1 : 0;
    if (!ok) ++(*n_bad);
    ++line;
    p = (line_end < end) ? line_end + 1 : end;
  }
  return line;
}

// ---------------------------------------------------------------------------
// Version tag so the Python side can verify the loaded library matches the
// source it expects (rebuilds are keyed by source hash; this is a backstop).
int32_t cep_native_abi_version() { return 1; }

}  // extern "C"
