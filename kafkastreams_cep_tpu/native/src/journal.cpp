// Native durable record journal — the changelog-segment analog.
//
// The reference inherits durability from Kafka: every state store is
// changelog-backed, and the broker's log segments make replay possible
// after any failure (SURVEY §5; CEPProcessor.java:144-149).  Here the
// supervisor checkpoints state arrays and journals the record batches
// since the last snapshot; this file gives that journal a crash-safe
// on-disk form: an append-only log of CRC32-framed payloads with
// fsync-on-demand, written natively so the per-batch cost is one write
// syscall, not Python byte shuffling.
//
// Frame layout (little-endian):
//   u32 magic = 0x43455031 ("CEP1")  u32 payload_len  u32 crc32(payload)
//   payload bytes
//
// A reader validates frames in order and stops at the first corrupt or
// truncated frame (a torn write from a crash) — everything before it is
// intact, matching a log truncated at the last good record.  The Python
// fallback (native/journal.py) implements the identical format with
// zlib.crc32; files are interchangeable between the two.
//
// Build: compiled into the same shared library as ingest.cpp.

#include <cstdint>
#include <cstdio>
#include <cstring>

#if defined(__unix__) || defined(__APPLE__)
#include <unistd.h>
#endif

extern "C" {

static const uint32_t kMagic = 0x43455031u;

// CRC-32 (IEEE 802.3, reflected, init/final 0xFFFFFFFF) — the same
// polynomial and conventions as zlib.crc32, table generated on first use.
static uint32_t crc_table[256];
static int crc_table_ready = 0;

static void crc_init() {
  for (uint32_t n = 0; n < 256; ++n) {
    uint32_t c = n;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : (c >> 1);
    crc_table[n] = c;
  }
  crc_table_ready = 1;
}

uint32_t cep_crc32(const uint8_t* buf, int64_t len) {
  if (!crc_table_ready) crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (int64_t i = 0; i < len; ++i)
    c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// Append one framed payload to the file (opened/closed per call — batch
// appends are rare enough that open cost is noise, and no handle state
// must survive across the ctypes boundary).  Returns 0 on success.
int32_t cep_journal_append(const char* path, const uint8_t* payload,
                           int64_t len, int32_t sync) {
  if (len < 0 || len > (int64_t)0xFFFFFFFF) return -3;  // u32 frame length
  FILE* f = fopen(path, "ab");
  if (!f) return -1;
  uint32_t header[3] = {kMagic, (uint32_t)len, cep_crc32(payload, len)};
  int ok = fwrite(header, sizeof(header), 1, f) == 1 &&
           (len == 0 || fwrite(payload, (size_t)len, 1, f) == 1);
  if (ok && fflush(f) != 0) ok = 0;
#if defined(__unix__) || defined(__APPLE__)
  if (ok && sync) {
    // fsync: flush the page cache so a machine crash keeps the frame;
    // plain process crashes are covered by fflush alone.
    if (fsync(fileno(f)) != 0) ok = 0;
  }
#endif
  fclose(f);
  return ok ? 0 : -2;
}

// Validate frames in buf; writes each frame's (payload_offset, payload_len)
// into out (pairs of int64), up to max_frames.  Returns the number of valid
// frames; *valid_bytes receives the byte length of the intact prefix.
int64_t cep_journal_scan(const uint8_t* buf, int64_t len, int64_t* out,
                         int64_t max_frames, int64_t* valid_bytes) {
  int64_t pos = 0, n = 0;
  while (n < max_frames && pos + 12 <= len) {
    uint32_t magic, plen, crc;
    memcpy(&magic, buf + pos, 4);
    memcpy(&plen, buf + pos + 4, 4);
    memcpy(&crc, buf + pos + 8, 4);
    if (magic != kMagic) break;
    if (pos + 12 + (int64_t)plen > len) break;  // truncated tail
    if (cep_crc32(buf + pos + 12, plen) != crc) break;  // corrupt
    out[2 * n] = pos + 12;
    out[2 * n + 1] = plen;
    ++n;
    pos += 12 + plen;
  }
  *valid_bytes = pos;
  return n;
}

}  // extern "C"
