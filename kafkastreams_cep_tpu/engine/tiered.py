"""Tier promotion: seed NFA suffix runs from stencil prefix completions.

The compiler tiering pass (``compiler/tiering.py``) splits a query into a
strict-contiguity prefix (run by ``engine/stencil.py: StencilPrefix``)
and a residual NFA suffix.  This module builds the *promotion* step that
joins the two tiers: at every event where the prefix completes, inject
into the NFA engine exactly the run — and exactly the shared-buffer
chain — the untiered engine would hold at that moment, so everything
downstream (suffix evaluation, branching, extraction, lazy drains,
checkpoints) is bit-identical by construction.

What "exactly the run" means, traced against ``engine/matcher.py``:

* **Dewey root.**  The untiered seed re-adds itself with ``add_run`` on
  every event its begin predicate accepts, so the run rooted at window
  event ``t0`` carries first digit ``v = 1 + accepts-before-t0`` (the
  stencil tier counts those accepts, ``PrefixCarry.cnt/sver``).  Each
  stage crossing inside the prefix appends one ``.0`` digit
  (``NFA.java:185-188``), so at promotion the version is ``[v, 0, ...,
  0]`` with length ``p`` — provided ``p <= dewey_depth``, which the
  tiering pass guarantees, no prefix-internal append can ever have
  overflowed.
* **Window anchor.**  ``getFirstPatternTimestamp`` re-anchors the window
  start while the run's identity stage is BEGIN-typed, so the untiered
  run's ``start_ts`` settles on the *second* window event for ``p >= 2``
  and the root event for ``p == 1`` — the stencil's ``anchor_ts``.
* **Queue position.**  Strict prefixes neither branch nor reorder, so
  suffix runs keep creation order, and creation order equals completion
  order (fixed prefix length); appending each promotion after the live
  queue prefix (compaction leaves live runs contiguous) reproduces the
  untiered queue's relative order — and therefore emission order.
* **Shared buffer.**  The untiered prefix run wrote ``put_first`` at its
  root and one chained ``put`` per later stage, under the versions above;
  the promotion replays those p puts verbatim.  Entries are keyed
  ``(stage, off)`` and prefix chains are private to their run, so writing
  them at promotion time instead of spread over p steps changes nothing
  an op can observe (slot *placement* may differ — never match content).

Partial prefixes — windows that have not completed — exist only as
stencil carry booleans: no run-queue slot, no slab entry, no walk hop.
That is the entire point of the tier split; it also means capacity
counters can only diverge from the untiered engine in regimes where the
untiered engine was already dropping state (its queue/slab held the
partials), i.e. outside the loss-free contract both engines are held to.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kafkastreams_cep_tpu.engine.matcher import (
    EngineConfig,
    EngineState,
    StepOutput,
)
from kafkastreams_cep_tpu.engine.stencil import PrefixCarry, PromoOutput
from kafkastreams_cep_tpu.ops import slab as slab_mod
from kafkastreams_cep_tpu.ops.onehot import get_at, put_at


class TieredState(NamedTuple):
    """Full tiered-matcher state: the NFA engine state plus the stencil
    prefix carry.  A pytree, so checkpoints (``runtime/checkpoint.py``),
    migration (``runtime/migrate.py``), and device placement all compose;
    runtime code that needs the engine half of *either* state shape uses
    :func:`engine_view`."""

    engine: EngineState
    carry: PrefixCarry


def engine_view(state):
    """The :class:`EngineState` inside ``state`` — identity for a bare
    engine state, the ``engine`` field of a :class:`TieredState`.  The
    single accessor runtime-layer probes (GC, flight recorder, health)
    go through so they stay matcher-agnostic."""
    return getattr(state, "engine", state)


def seedless_init(init_state_fn) -> EngineState:
    """An engine initial state with NO seed run: under tiering the begin
    stage lives on the stencil tier, so the NFA queue starts empty and
    only promotions populate it.  Derived from the standard init by
    clearing run 0 (the seed slot) back to compaction fill values."""
    s = init_state_fn()
    i32 = jnp.int32
    R = s.alive.shape[0]
    return s._replace(
        alive=jnp.zeros((R,), bool),
        eval_pos=jnp.zeros((R,), i32),
        ver=jnp.zeros_like(s.ver),
        vlen=jnp.zeros((R,), i32),
    )


def build_promote(tables, cfg: EngineConfig, prefix_len: int):
    """Compile the per-lane promotion step for one tiering plan.

    Returns a pure jittable ``promote(state, fire, offs, anchor_ts, sver)
    -> (state, n_promoted)`` that, when ``fire``:

    1. replays the prefix chain's shared-buffer writes (``put_first`` at
       the root, chained ``put`` per later stage) under the promoted
       Dewey versions;
    2. appends the suffix run — identity ``ident[p-1]``, eval position
       ``consume_target[p-1]``, version ``[v, 0...]``/len ``p``, pointer
       event = the completing prefix event, window start = the anchor —
       after the live queue prefix;
    3. counts a queue-full promotion in ``run_drops`` (the untiered
       analog: the run the narrow queue could not hold).

    vmaps cleanly over a ``[K]`` lane axis.
    """
    p = int(prefix_len)
    R, D = cfg.max_runs, cfg.dewey_depth
    EH = cfg.slab_hot_entries
    if not 0 < p <= D:
        raise ValueError(
            f"prefix_len={p} must be in 1..dewey_depth={D} (the promoted "
            "version carries one digit per prefix stage)"
        )
    idents = [int(tables.ident[j]) for j in range(p)]
    eval_pos = int(tables.consume_target[p - 1])
    id_pos = idents[p - 1]
    NS = max(tables.num_states, 1)

    def _enc(x, dt):
        if dt == "float32":
            return int(np.float32(x).view(np.int32))
        return int(np.int32(x))

    inits_row = jnp.asarray(
        [
            _enc(x, d)
            for x, d in zip(tables.state_inits, tables.state_dtypes)
        ]
        + [0] * (NS - tables.num_states)
        or [0],
        dtype=jnp.int32,
    )

    def promote(
        state: EngineState, fire, offs, anchor_ts, sver
    ) -> Tuple[EngineState, jnp.ndarray]:
        i32 = jnp.int32
        fire = jnp.asarray(fire)
        cnt = jnp.sum(state.alive.astype(i32))
        fit = fire & (cnt < R)

        ver = jnp.zeros((D,), i32).at[0].set(jnp.asarray(sver, i32))
        slab = state.slab
        slab = slab_mod.put_first(
            slab, jnp.int32(idents[0]), offs[..., 0], ver, jnp.int32(1),
            enable=fit, hot_entries=EH,
        )
        for j in range(1, p):
            slab = slab_mod.put(
                slab, jnp.int32(idents[j]), offs[..., j],
                jnp.int32(idents[j - 1]), offs[..., j - 1],
                ver, jnp.int32(j + 1), enable=fit, hot_entries=EH,
            )

        row = cnt  # live runs are a contiguous prefix (queue compaction)
        state = state._replace(
            alive=put_at(state.alive, row, True, enable=fit),
            id_pos=put_at(state.id_pos, row, jnp.int32(id_pos), enable=fit),
            eval_pos=put_at(
                state.eval_pos, row, jnp.int32(eval_pos), enable=fit
            ),
            ver=put_at(state.ver, row, ver[None, :], enable=fit),
            vlen=put_at(state.vlen, row, jnp.int32(p), enable=fit),
            event_off=put_at(
                state.event_off, row, offs[..., p - 1], enable=fit
            ),
            start_ts=put_at(
                state.start_ts, row, jnp.asarray(anchor_ts, i32), enable=fit
            ),
            branching=put_at(state.branching, row, False, enable=fit),
            agg=put_at(state.agg, row, inits_row[None, :], enable=fit),
            slab=slab,
            run_drops=state.run_drops + jnp.where(fire & ~fit, 1, 0),
        )
        return state, jnp.where(fit, 1, 0).astype(i32)

    return promote


def build_promote_stacked(tlist, cfg: EngineConfig, prefix_len: int):
    """The stacked-bank analog of :func:`build_promote`: one promotion
    step shared by a group of same-shape queries with equal prefix
    length, lane-dispatched by ``qid`` exactly like the stacked engine
    step (``engine/matcher.py: _build_step`` stacked mode).

    Per lane, the replayed chain writes and the appended suffix run use
    the lane's *own* query's stage identities, eval position, and fold
    inits (one-hot selected, ``ops/onehot.py: get_at``); everything else
    is the single-query promotion verbatim, so vmapping over a ``[Q*K]``
    lane axis with per-lane ``qid`` promotes each lane bit-identically
    to its query's own :func:`build_promote`.
    """
    p = int(prefix_len)
    R, D = cfg.max_runs, cfg.dewey_depth
    EH = cfg.slab_hot_entries
    if not 0 < p <= D:
        raise ValueError(
            f"prefix_len={p} must be in 1..dewey_depth={D} (the promoted "
            "version carries one digit per prefix stage)"
        )
    idents_q = np.asarray(
        [[int(t.ident[j]) for j in range(p)] for t in tlist], np.int32
    )  # [Q, p]
    eval_pos_q = np.asarray(
        [int(t.consume_target[p - 1]) for t in tlist], np.int32
    )
    NS = max(max(t.num_states for t in tlist), 1)

    def _enc(x, dt):
        if dt == "float32":
            return int(np.float32(x).view(np.int32))
        return int(np.int32(x))

    inits_q = np.asarray(
        [
            [
                _enc(x, d)
                for x, d in zip(t.state_inits, t.state_dtypes)
            ]
            + [0] * (NS - t.num_states)
            for t in tlist
        ],
        np.int32,
    )  # [Q, NS]
    idents_dev = jnp.asarray(idents_q)
    eval_pos_dev = jnp.asarray(eval_pos_q)
    inits_dev = jnp.asarray(inits_q)

    def promote(
        state: EngineState, fire, offs, anchor_ts, sver, qid
    ) -> Tuple[EngineState, jnp.ndarray]:
        i32 = jnp.int32
        fire = jnp.asarray(fire)
        ident_row = get_at(idents_dev, qid)  # [p]
        cnt = jnp.sum(state.alive.astype(i32))
        fit = fire & (cnt < R)

        ver = jnp.zeros((D,), i32).at[0].set(jnp.asarray(sver, i32))
        slab = state.slab
        slab = slab_mod.put_first(
            slab, ident_row[0], offs[..., 0], ver, jnp.int32(1),
            enable=fit, hot_entries=EH,
        )
        for j in range(1, p):
            slab = slab_mod.put(
                slab, ident_row[j], offs[..., j],
                ident_row[j - 1], offs[..., j - 1],
                ver, jnp.int32(j + 1), enable=fit, hot_entries=EH,
            )

        row = cnt  # live runs are a contiguous prefix (queue compaction)
        state = state._replace(
            alive=put_at(state.alive, row, True, enable=fit),
            id_pos=put_at(
                state.id_pos, row, ident_row[p - 1], enable=fit
            ),
            eval_pos=put_at(
                state.eval_pos, row, get_at(eval_pos_dev, qid), enable=fit
            ),
            ver=put_at(state.ver, row, ver[None, :], enable=fit),
            vlen=put_at(state.vlen, row, jnp.int32(p), enable=fit),
            event_off=put_at(
                state.event_off, row, offs[..., p - 1], enable=fit
            ),
            start_ts=put_at(
                state.start_ts, row, jnp.asarray(anchor_ts, i32), enable=fit
            ),
            branching=put_at(state.branching, row, False, enable=fit),
            agg=put_at(
                state.agg, row, get_at(inits_dev, qid)[None, :], enable=fit
            ),
            slab=slab,
            run_drops=state.run_drops + jnp.where(fire & ~fit, 1, 0),
        )
        return state, jnp.where(fit, 1, 0).astype(i32)

    return promote


def stencil_step_output(tables, cfg: EngineConfig, prefix_len: int):
    """Compile the pure-stencil tier's output synthesizer: prefix
    completions rendered as the ``[K, T, R, W]`` :class:`StepOutput` grid
    the untiered engine's extraction walk would emit — identity stages
    final-first, offsets backward, one match (row 0) per completing
    event.  Requires ``p <= max_walk`` (the tiering pass guarantees it:
    a longer pattern would have been truncated by the walk bound, which
    a stencil cannot reproduce)."""
    p = int(prefix_len)
    R, W = cfg.max_runs, cfg.max_walk
    if p > W:
        raise ValueError(
            f"pure-stencil tier needs prefix_len={p} <= max_walk={W}"
        )
    rev_ident = jnp.asarray(
        [int(tables.ident[j]) for j in range(p - 1, -1, -1)], jnp.int32
    )

    def synth(promo: PromoOutput) -> StepOutput:
        i32 = jnp.int32
        K, T = promo.fire.shape
        fire = promo.fire
        stage_rows = jnp.where(
            fire[..., None], rev_ident[None, None, :], -1
        )  # [K, T, p]
        off_rows = jnp.where(fire[..., None], promo.offs[..., ::-1], -1)
        pad = jnp.full((K, T, W - p), -1, i32)
        stage = jnp.full((K, T, R, W), -1, i32)
        off = jnp.full((K, T, R, W), -1, i32)
        stage = stage.at[:, :, 0, :].set(
            jnp.concatenate([stage_rows, pad], axis=-1)
        )
        off = off.at[:, :, 0, :].set(
            jnp.concatenate([off_rows, pad], axis=-1)
        )
        count = jnp.zeros((K, T, R), i32).at[:, :, 0].set(
            jnp.where(fire, p, 0)
        )
        return StepOutput(stage=stage, off=off, count=count)

    return synth


def stencil_step_output_stacked(tlist, cfg: EngineConfig, prefix_len: int):
    """:func:`stencil_step_output` for a group of pure-stencil queries
    with equal prefix length: one synthesizer over ``[N]``-stacked
    :class:`PromoOutput` leaves, vmapped with each member's reversed
    identity row as a per-member input.  The per-member slice is the
    single-query synth verbatim."""
    p = int(prefix_len)
    R, W = cfg.max_runs, cfg.max_walk
    if p > W:
        raise ValueError(
            f"pure-stencil tier needs prefix_len={p} <= max_walk={W}"
        )
    rev_idents = jnp.asarray(
        [
            [int(t.ident[j]) for j in range(p - 1, -1, -1)]
            for t in tlist
        ],
        jnp.int32,
    )  # [N, p]

    def synth_one(promo: PromoOutput, rev_ident) -> StepOutput:
        i32 = jnp.int32
        K, T = promo.fire.shape
        fire = promo.fire
        stage_rows = jnp.where(
            fire[..., None], rev_ident[None, None, :], -1
        )  # [K, T, p]
        off_rows = jnp.where(fire[..., None], promo.offs[..., ::-1], -1)
        pad = jnp.full((K, T, W - p), -1, i32)
        stage = jnp.full((K, T, R, W), -1, i32)
        off = jnp.full((K, T, R, W), -1, i32)
        stage = stage.at[:, :, 0, :].set(
            jnp.concatenate([stage_rows, pad], axis=-1)
        )
        off = off.at[:, :, 0, :].set(
            jnp.concatenate([off_rows, pad], axis=-1)
        )
        count = jnp.zeros((K, T, R), i32).at[:, :, 0].set(
            jnp.where(fire, p, 0)
        )
        return StepOutput(stage=stage, off=off, count=count)

    def synth(promo: PromoOutput) -> StepOutput:
        return jax.vmap(synth_one)(promo, rev_idents)

    return synth
