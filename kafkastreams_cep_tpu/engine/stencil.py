"""Stencil matcher — the vectorized-over-time fast path for strict SEQ.

For a branch-free pattern (every stage cardinality ONE, strict contiguity,
no folds — ``TransitionTables.is_strict_seq``), the reference NFA's
semantics collapse to a stencil: the begin stage re-seeds a run at every
event (``NFA.java:148-157``), strict contiguity kills a run on the first
non-matching event (no IGNORE edges, ``StatesFactory.java:93-96``), so a
match completes at event ``t`` **iff** stage ``i``'s predicate holds on
event ``t-n+1+i`` for all ``i``.  No run queue, no shared buffer, no
versions — just ``n`` boolean arrays ANDed under relative shifts, fully
parallel over keys *and* time (the general engine is sequential over time).

``within()`` windows need no handling here for parity: in the reference all
non-seed runs are epsilon wrappers that never carry ``windowMs``
(``Stage.java:41-46``), so windows never prune (see ``engine/matcher.py``).
That invariant is no longer merely noted: the tiering pass *asserts* it at
compile time (``compiler/tiering.py: check_no_prune``) and refuses to route
a windowed prefix onto this tier when ``EngineConfig.enforce_windows``
breaks the proof.

A carry of the last ``n-1`` events' per-stage booleans and offsets makes
matching exact across micro-batch boundaries.  Conformance: differential
tests against :class:`OracleNFA` in ``tests/test_stencil.py``.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kafkastreams_cep_tpu.compiler.tables import (
    OP_BEGIN,
    TransitionTables,
    lower,
)
from kafkastreams_cep_tpu.engine.matcher import ArrayStates, EventBatch


def _cached_scan_jit(namespace, tables, shape_key, scan_fn):
    """Jit ``scan_fn`` through the process trace cache keyed by the
    pattern fingerprint + lane/prefix shape — stencil matchers for the
    same pattern (tests, tenant banks instantiating per-query screens,
    recovery rebuilds) share one traced program."""
    from kafkastreams_cep_tpu.compiler.multitenant import tables_key
    from kafkastreams_cep_tpu.utils import tracecache

    tkey = tables_key(tables)
    key = None if tkey is None else (tkey,) + tuple(shape_key)
    return tracecache.lookup(namespace, key, lambda: jax.jit(scan_fn))


class StencilState(NamedTuple):
    """Carry across micro-batches: the trailing ``n-1`` valid events."""

    bools: jnp.ndarray  # [K, n-1, n] bool — per-stage predicate values
    offs: jnp.ndarray  # [K, n-1] int32 — event offsets (-1 = none yet)


class StencilOutput(NamedTuple):
    """``hit[k, t]`` = a match completed at batch slot ``t``;
    ``offs[k, t, i]`` = the offset of the stage-``i`` event of that match."""

    hit: jnp.ndarray  # [K, T] bool
    offs: jnp.ndarray  # [K, T, n] int32


class StencilMatcher:
    """Compiled stencil matcher for one strict-SEQ pattern over ``K`` lanes.

    ``scan(state, events)`` consumes a ``[K, T]`` :class:`EventBatch` whose
    valid slots form a per-lane prefix (the processor's padding shape) and
    returns every completed match.  Unlike :class:`TPUMatcher` there is no
    sequential dependence on the time axis, so throughput is bounded by
    memory bandwidth, not step latency.
    """

    def __init__(self, pattern, num_lanes: int):
        self.tables: TransitionTables = (
            pattern if isinstance(pattern, TransitionTables) else lower(pattern)
        )
        if not self.tables.is_strict_seq():
            raise ValueError(
                "pattern is not a branch-free strict sequence; use TPUMatcher"
            )
        self.num_lanes = int(num_lanes)
        # Chain positions 0..n-1 each consume via a BEGIN edge; final is last.
        n = self.tables.num_stages - 1
        assert np.all(self.tables.consume_op[:n] == OP_BEGIN)
        self.n = n
        # Stage names in chain order, for decoding matches.
        self.stage_names: List[str] = self.tables.names[:n]
        self._preds = [
            self.tables.predicates[self.tables.consume_pred[i]] for i in range(n)
        ]
        self.scan = _cached_scan_jit(
            "stencil.scan", self.tables, (self.num_lanes,), self._scan
        )

    def init_state(self) -> StencilState:
        K, n = self.num_lanes, self.n
        return StencilState(
            bools=jnp.zeros((K, max(n - 1, 0), n), bool),
            offs=jnp.full((K, max(n - 1, 0)), -1, jnp.int32),
        )

    def _scan(
        self, state: StencilState, ev: EventBatch
    ) -> Tuple[StencilState, StencilOutput]:
        K, n = self.num_lanes, self.n
        T = ev.ts.shape[-1]
        states = ArrayStates({})
        # [K, T, n]: every stage predicate on every event, one fused pass.
        bools = jnp.stack(
            [
                jnp.broadcast_to(
                    jnp.asarray(p(ev.key, ev.value, ev.ts, states), bool),
                    (K, T),
                )
                & ev.valid
                for p in self._preds
            ],
            axis=-1,
        )
        offs = jnp.asarray(ev.off, jnp.int32)

        if n == 1:
            out = StencilOutput(hit=bools[..., 0], offs=offs[..., None])
            return state, out

        ext_bools = jnp.concatenate([state.bools, bools], axis=1)  # [K, T+n-1, n]
        ext_offs = jnp.concatenate([state.offs, offs], axis=1)  # [K, T+n-1]

        # hit[k, t] = AND_i ext_bools[k, t+i, i]  (stage i saw event t-n+1+i).
        hit = ext_bools[:, 0:T, 0]
        for i in range(1, n):
            hit = hit & ext_bools[:, i : i + T, i]
        match_offs = jnp.stack(
            [ext_offs[:, i : i + T] for i in range(n)], axis=-1
        )

        # New carry: the last n-1 *valid* columns.  Valid slots are a prefix
        # of each lane's row, so they occupy ext columns [c, c+n-2] where c
        # is the lane's valid count.
        c = jnp.sum(ev.valid, axis=1).astype(jnp.int32)  # [K]
        carry_bools = jax.vmap(
            lambda row, start: jax.lax.dynamic_slice(
                row, (start, 0), (n - 1, n)
            )
        )(ext_bools, c)
        carry_offs = jax.vmap(
            lambda row, start: jax.lax.dynamic_slice(row, (start,), (n - 1,))
        )(ext_offs, c)

        return StencilState(carry_bools, carry_offs), StencilOutput(hit, match_offs)

    def decode(self, out: StencilOutput, events_by_offset, lane_keys=None):
        """Host-side: materialize matches as ``Sequence`` objects per lane.

        ``events_by_offset`` is a list (per lane) of ``{offset: Event}``.
        Stages are inserted final-first, matching the reference's backward
        buffer walk (``KVSharedVersionedBuffer.java:161``).
        """
        from kafkastreams_cep_tpu.utils.events import Sequence

        hit = np.asarray(jax.device_get(out.hit))
        offs = np.asarray(jax.device_get(out.offs))
        matches = []
        for k, t in zip(*np.nonzero(hit)):
            seq = Sequence()
            for i in range(self.n - 1, -1, -1):
                seq.add(
                    self.stage_names[i],
                    events_by_offset[k][int(offs[k, t, i])],
                )
            matches.append((int(k), int(t), seq))
        return matches


# ---------------------------------------------------------------------------
# Prefix mode — the stencil as the first tier of a hybrid matcher
# ---------------------------------------------------------------------------


class PrefixCarry(NamedTuple):
    """Cross-batch carry of the stencil *prefix* tier (compiler tiering).

    Beyond :class:`StencilState`'s trailing-window booleans/offsets, the
    prefix tier must be able to *promote* a completing window into the
    NFA tier with exactly the state an untiered run would carry, so the
    carry also tracks per-event timestamps (window anchors), the seed
    Dewey version each window root was born under, and the running
    begin-accept count that generates those versions.  The three trailing
    fields are the tier telemetry counters — device state so they
    checkpoint/migrate/merge like every engine counter.
    """

    bools: jnp.ndarray  # [K, p-1, p] bool — per-stage predicate values
    offs: jnp.ndarray  # [K, p-1] int32 — event offsets (-1 = none yet)
    ts: jnp.ndarray  # [K, p-1] int32 — rebased event timestamps
    sver: jnp.ndarray  # [K, p-1] int32 — seed version at each event
    cnt: jnp.ndarray  # [K] int32 — begin-accepts seen (seed ver - 1)
    screened: jnp.ndarray  # [K] int32 — valid events the prefix screened
    fires: jnp.ndarray  # [K] int32 — prefix completions
    promotions: jnp.ndarray  # [K] int32 — runs injected into the NFA tier


class PromoOutput(NamedTuple):
    """Per-step promotion feed for the NFA tier: at every batch slot where
    the prefix completed (``fire``), the p prefix-event offsets, the
    window-anchor timestamp, and the first Dewey digit the promoted run
    must carry (the seed version at the window root)."""

    fire: jnp.ndarray  # [K, T] bool
    offs: jnp.ndarray  # [K, T, p] int32
    anchor_ts: jnp.ndarray  # [K, T] int32
    sver: jnp.ndarray  # [K, T] int32


class StencilPrefix:
    """Stencil evaluation of a query's strict-contiguity *prefix*.

    Generalizes :class:`StencilMatcher` from whole patterns to the leading
    ``prefix_len`` stages chosen by ``compiler/tiering.py``: ``scan``
    consumes a ``[K, T]`` :class:`EventBatch` fully parallel over keys and
    time and emits, per step, whether the prefix completed there plus
    everything the NFA tier needs to seed the suffix run — the exact
    Dewey root (``1 + begin-accepts before the window root``, the version
    the untiered seed would have handed that run), the window anchor
    (the reference resets the window start while a run's identity stage
    is BEGIN-typed, so the anchor is the window's second event for
    ``p >= 2`` and its only event for ``p == 1``), and the p event
    offsets whose shared-buffer chain the promotion writes.

    Predicates are evaluated against the declared fold-state *inits*:
    prefix stages carry no folds (by definition of the split), so every
    untiered prefix run evaluates against exactly those values.
    """

    def __init__(self, tables, num_lanes: int, prefix_len: int):
        self.tables: TransitionTables = (
            tables if isinstance(tables, TransitionTables) else lower(tables)
        )
        p = int(prefix_len)
        n = self.tables.num_stages - 1
        if not 0 < p <= n:
            raise ValueError(f"prefix_len={p} outside 1..{n}")
        if np.any(self.tables.consume_op[:p] != OP_BEGIN) or np.any(
            self.tables.ignore_pred[:p] >= 0
        ) or np.any(self.tables.proceed_pred[:p] >= 0) or any(
            slot.stage < p for slot in self.tables.aggs
        ):
            raise ValueError(
                f"stages [0, {p}) are not a strict-contiguity prefix; run "
                "compiler.tiering.plan_tiering first"
            )
        self.num_lanes = int(num_lanes)
        self.p = p
        self._preds = [
            self.tables.predicates[self.tables.consume_pred[j]]
            for j in range(p)
        ]
        # Fold-state inits (decoded to each state's declared dtype): the
        # exact ArrayStates view an untiered prefix run evaluates against.
        self._states = ArrayStates(
            {
                name: (
                    jnp.asarray(init, jnp.float32)
                    if dt == "float32"
                    else jnp.asarray(init, jnp.int32)
                )
                for name, init, dt in zip(
                    self.tables.state_names,
                    self.tables.state_inits,
                    self.tables.state_dtypes,
                )
            }
        )
        self.scan = _cached_scan_jit(
            "stencil.prefix_scan", self.tables,
            (self.num_lanes, self.p), self._scan,
        )

    def init_carry(self) -> PrefixCarry:
        K, p = self.num_lanes, self.p
        i32 = jnp.int32
        z = jnp.zeros((K,), i32)
        return PrefixCarry(
            bools=jnp.zeros((K, p - 1, p), bool),
            offs=jnp.full((K, p - 1), -1, i32),
            ts=jnp.zeros((K, p - 1), i32),
            sver=jnp.ones((K, p - 1), i32),
            cnt=z,
            screened=z,
            fires=z,
            promotions=z,
        )

    def _scan(
        self, carry: PrefixCarry, ev: EventBatch
    ) -> Tuple[PrefixCarry, PromoOutput]:
        K, p = self.num_lanes, self.p
        i32 = jnp.int32
        T = ev.ts.shape[-1]
        bools = jnp.stack(
            [
                jnp.broadcast_to(
                    jnp.asarray(
                        pr(ev.key, ev.value, ev.ts, self._states), bool
                    ),
                    (K, T),
                )
                & ev.valid
                for pr in self._preds
            ],
            axis=-1,
        )  # [K, T, p]
        offs = jnp.asarray(ev.off, i32)
        ts = jnp.asarray(ev.ts, i32)
        b0 = bools[..., 0]
        # Seed version at each batch slot: 1 + begin-accepts strictly
        # before it (the version the untiered seed hands the run it
        # creates there — the seed bumps on every accept, not only on
        # completed prefixes).
        sver = 1 + carry.cnt[:, None] + (
            jnp.cumsum(b0.astype(i32), axis=1) - b0.astype(i32)
        )

        ext_b = jnp.concatenate([carry.bools, bools], axis=1)
        ext_off = jnp.concatenate([carry.offs, offs], axis=1)
        ext_ts = jnp.concatenate([carry.ts, ts], axis=1)
        ext_sver = jnp.concatenate([carry.sver, sver], axis=1)

        # fire[k, t] = AND_j ext_b[k, t+j, j]: stage j saw event t-p+1+j.
        fire = ext_b[:, 0:T, 0]
        for j in range(1, p):
            fire = fire & ext_b[:, j : j + T, j]
        offs_out = jnp.stack(
            [ext_off[:, j : j + T] for j in range(p)], axis=-1
        )
        # Window anchor: the event the untiered run's start_ts settles on
        # (the second window event for p >= 2 — re-anchored while the run
        # identity is the BEGIN-typed stage — else the root itself).
        a = min(1, p - 1)
        anchor = ext_ts[:, a : a + T]
        sver_out = ext_sver[:, 0:T]

        # New carry: the trailing p-1 *valid* columns (valid slots form a
        # per-lane prefix, so they end at column c = carry + valid count).
        c = jnp.sum(ev.valid, axis=1).astype(i32)
        carry_b = jax.vmap(
            lambda row, start: jax.lax.dynamic_slice(
                row, (start, 0), (p - 1, p)
            )
        )(ext_b, c)
        slice1 = lambda row, start: jax.lax.dynamic_slice(
            row, (start,), (p - 1,)
        )
        new_carry = PrefixCarry(
            bools=carry_b,
            offs=jax.vmap(slice1)(ext_off, c),
            ts=jax.vmap(slice1)(ext_ts, c),
            sver=jax.vmap(slice1)(ext_sver, c),
            cnt=carry.cnt + jnp.sum(b0.astype(i32), axis=1),
            screened=carry.screened
            + jnp.sum(ev.valid.astype(i32), axis=1),
            fires=carry.fires + jnp.sum(fire.astype(i32), axis=1),
            promotions=carry.promotions,
        )
        return new_carry, PromoOutput(fire, offs_out, anchor, sver_out)
