"""Stencil matcher — the vectorized-over-time fast path for strict SEQ.

For a branch-free pattern (every stage cardinality ONE, strict contiguity,
no folds — ``TransitionTables.is_strict_seq``), the reference NFA's
semantics collapse to a stencil: the begin stage re-seeds a run at every
event (``NFA.java:148-157``), strict contiguity kills a run on the first
non-matching event (no IGNORE edges, ``StatesFactory.java:93-96``), so a
match completes at event ``t`` **iff** stage ``i``'s predicate holds on
event ``t-n+1+i`` for all ``i``.  No run queue, no shared buffer, no
versions — just ``n`` boolean arrays ANDed under relative shifts, fully
parallel over keys *and* time (the general engine is sequential over time).

``within()`` windows need no handling here for parity: in the reference all
non-seed runs are epsilon wrappers that never carry ``windowMs``
(``Stage.java:41-46``), so windows never prune (see ``engine/matcher.py``).

A carry of the last ``n-1`` events' per-stage booleans and offsets makes
matching exact across micro-batch boundaries.  Conformance: differential
tests against :class:`OracleNFA` in ``tests/test_stencil.py``.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kafkastreams_cep_tpu.compiler.tables import (
    OP_BEGIN,
    TransitionTables,
    lower,
)
from kafkastreams_cep_tpu.engine.matcher import ArrayStates, EventBatch


class StencilState(NamedTuple):
    """Carry across micro-batches: the trailing ``n-1`` valid events."""

    bools: jnp.ndarray  # [K, n-1, n] bool — per-stage predicate values
    offs: jnp.ndarray  # [K, n-1] int32 — event offsets (-1 = none yet)


class StencilOutput(NamedTuple):
    """``hit[k, t]`` = a match completed at batch slot ``t``;
    ``offs[k, t, i]`` = the offset of the stage-``i`` event of that match."""

    hit: jnp.ndarray  # [K, T] bool
    offs: jnp.ndarray  # [K, T, n] int32


class StencilMatcher:
    """Compiled stencil matcher for one strict-SEQ pattern over ``K`` lanes.

    ``scan(state, events)`` consumes a ``[K, T]`` :class:`EventBatch` whose
    valid slots form a per-lane prefix (the processor's padding shape) and
    returns every completed match.  Unlike :class:`TPUMatcher` there is no
    sequential dependence on the time axis, so throughput is bounded by
    memory bandwidth, not step latency.
    """

    def __init__(self, pattern, num_lanes: int):
        self.tables: TransitionTables = (
            pattern if isinstance(pattern, TransitionTables) else lower(pattern)
        )
        if not self.tables.is_strict_seq():
            raise ValueError(
                "pattern is not a branch-free strict sequence; use TPUMatcher"
            )
        self.num_lanes = int(num_lanes)
        # Chain positions 0..n-1 each consume via a BEGIN edge; final is last.
        n = self.tables.num_stages - 1
        assert np.all(self.tables.consume_op[:n] == OP_BEGIN)
        self.n = n
        # Stage names in chain order, for decoding matches.
        self.stage_names: List[str] = self.tables.names[:n]
        self._preds = [
            self.tables.predicates[self.tables.consume_pred[i]] for i in range(n)
        ]
        self.scan = jax.jit(self._scan)

    def init_state(self) -> StencilState:
        K, n = self.num_lanes, self.n
        return StencilState(
            bools=jnp.zeros((K, max(n - 1, 0), n), bool),
            offs=jnp.full((K, max(n - 1, 0)), -1, jnp.int32),
        )

    def _scan(
        self, state: StencilState, ev: EventBatch
    ) -> Tuple[StencilState, StencilOutput]:
        K, n = self.num_lanes, self.n
        T = ev.ts.shape[-1]
        states = ArrayStates({})
        # [K, T, n]: every stage predicate on every event, one fused pass.
        bools = jnp.stack(
            [
                jnp.broadcast_to(
                    jnp.asarray(p(ev.key, ev.value, ev.ts, states), bool),
                    (K, T),
                )
                & ev.valid
                for p in self._preds
            ],
            axis=-1,
        )
        offs = jnp.asarray(ev.off, jnp.int32)

        if n == 1:
            out = StencilOutput(hit=bools[..., 0], offs=offs[..., None])
            return state, out

        ext_bools = jnp.concatenate([state.bools, bools], axis=1)  # [K, T+n-1, n]
        ext_offs = jnp.concatenate([state.offs, offs], axis=1)  # [K, T+n-1]

        # hit[k, t] = AND_i ext_bools[k, t+i, i]  (stage i saw event t-n+1+i).
        hit = ext_bools[:, 0:T, 0]
        for i in range(1, n):
            hit = hit & ext_bools[:, i : i + T, i]
        match_offs = jnp.stack(
            [ext_offs[:, i : i + T] for i in range(n)], axis=-1
        )

        # New carry: the last n-1 *valid* columns.  Valid slots are a prefix
        # of each lane's row, so they occupy ext columns [c, c+n-2] where c
        # is the lane's valid count.
        c = jnp.sum(ev.valid, axis=1).astype(jnp.int32)  # [K]
        carry_bools = jax.vmap(
            lambda row, start: jax.lax.dynamic_slice(
                row, (start, 0), (n - 1, n)
            )
        )(ext_bools, c)
        carry_offs = jax.vmap(
            lambda row, start: jax.lax.dynamic_slice(row, (start,), (n - 1,))
        )(ext_offs, c)

        return StencilState(carry_bools, carry_offs), StencilOutput(hit, match_offs)

    def decode(self, out: StencilOutput, events_by_offset, lane_keys=None):
        """Host-side: materialize matches as ``Sequence`` objects per lane.

        ``events_by_offset`` is a list (per lane) of ``{offset: Event}``.
        Stages are inserted final-first, matching the reference's backward
        buffer walk (``KVSharedVersionedBuffer.java:161``).
        """
        from kafkastreams_cep_tpu.utils.events import Sequence

        hit = np.asarray(jax.device_get(out.hit))
        offs = np.asarray(jax.device_get(out.offs))
        matches = []
        for k, t in zip(*np.nonzero(hit)):
            seq = Sequence()
            for i in range(self.n - 1, -1, -1):
                seq.add(
                    self.stage_names[i],
                    events_by_offset[k][int(offs[k, t, i])],
                )
            matches.append((int(k), int(t), seq))
        return matches
