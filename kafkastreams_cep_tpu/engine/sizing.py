"""Capacity estimation — derive an ``EngineConfig`` instead of hand-tuning.

The reference needs no sizing: its run queue, shared buffer, and versions
are heap-backed and unbounded (``NFA.java:75``, ``CEPProcessor.java:
144-149``).  The array engine's shapes are static, so every dimension is a
capacity knob with an overflow counter.  This module closes the gap the
way a profiler would: run the real pattern over a *sample* of the real
traffic with instrumented occupancy maxima, then derive a config with
headroom — growing any dimension whose counter fires and tightening the
rest.

``probe``    — one instrumented run: counters + occupancy maxima.
``suggest``  — a config from a probe report (structural floors from the
               compiled tables + measured maxima x margin).
``autosize`` — the closed loop: probe, grow what overflowed, re-probe,
               then tighten.  The returned config is verified loss-free
               on the sample (capacity counters zero; ``slab_missing``
               is excluded — with every capacity counter zero it marks
               reference-NPE trace states, a pattern property the
               reference would crash on, not a sizing defect).
``escalate`` — the *online* analog of one autosize growth step: given
               the capacity counters a live batch tripped, the next
               strictly-wider config under an :class:`EscalationPolicy`
               (growth factor, per-dim ceiling).  The supervisor pairs
               it with live-state migration (``runtime/migrate.py``) so
               a production overflow becomes a transparent capacity
               escalation instead of a loss warning.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kafkastreams_cep_tpu.engine.matcher import (
    EngineConfig,
    EventBatch,
)
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("engine.sizing")

# Counters that indicate a capacity knob is too small, with the knob they
# grow.  slab_missing is deliberately absent (see module docstring);
# walk_collisions is a semantics flag, not a capacity.
_COUNTER_KNOB = {
    "run_drops": "max_runs",
    "ver_overflows": "dewey_depth",
    "slab_full_drops": "slab_entries",
    "slab_pred_drops": "slab_preds",
    "slab_trunc": "max_walk",
    "handle_overflows": "handle_ring",
}

# Ingestion-guard loss counters and the IngestPolicy knob each one grows
# (runtime/ingest.py) — the host-side twin of _COUNTER_KNOB: a late drop
# means the grace window under-covered the stream's skew, an eviction
# means the reorder buffer was too shallow for the in-flight disorder.
# ``quarantined`` is deliberately absent: a schema/lane defect is a data
# defect, not a capacity defect — no knob makes a malformed record valid.
_INGEST_COUNTER_KNOB = {
    "late_dropped": "grace_ms",
    "reorder_evictions": "reorder_depth",
}

# Additive growth floors for knobs whose current value may be 0 (a pure
# multiplier would never move grace_ms off zero).
_INGEST_KNOB_FLOOR = {"grace_ms": 1000, "reorder_depth": 64}


class ProbeReport(NamedTuple):
    """What one instrumented sample run observed."""

    counters: Dict[str, int]
    max_alive_runs: int  # per lane, max over chunk boundaries
    max_live_entries: int  # slab entries in use, per lane
    max_npreds: int  # pointer-list width in use
    max_vlen: int  # deepest Dewey version (runs and pointers)
    max_match_len: int  # longest extracted match
    max_matches_chunk: int  # matches completed per lane per chunk — the
    #   handle-ring working set under lazy extraction (drain runs at scan
    #   cadence, so one chunk's completions must fit the ring)
    config: EngineConfig


def _chunked(events: EventBatch, chunk: int):
    T = int(events.ts.shape[1])
    for t0 in range(0, T, chunk):
        yield jax.tree_util.tree_map(
            lambda x: x[:, t0:t0 + chunk], events
        )


def probe(
    pattern,
    events: EventBatch,
    config: EngineConfig,
    sweep_every: int = 16,
) -> ProbeReport:
    """Run ``pattern`` over ``events [K, T]`` under ``config``, sweeping
    every ``sweep_every`` events (match the deployment's cadence: the
    processor sweeps every ``gc_interval`` micro-batches), and record
    occupancy maxima.

    Maxima are sampled at chunk boundaries; within-chunk peaks are covered
    by the growth loop in :func:`autosize` (a dimension that only peaks
    intra-chunk still fires its counter and grows).
    """
    from kafkastreams_cep_tpu.parallel.batch import BatchMatcher

    K = int(events.ts.shape[0])
    batch = BatchMatcher(pattern, K, config)
    state = batch.init_state()
    chunk = max(int(sweep_every), 1)
    mx = dict(alive=0, entries=0, npreds=0, vlen=0, mlen=0, mchunk=0)
    for ev in _chunked(events, chunk):
        state, out = batch.scan(state, ev)
        mx["alive"] = max(mx["alive"], int(jnp.max(jnp.sum(state.alive, -1))))
        mx["entries"] = max(
            mx["entries"], int(jnp.max(jnp.sum(state.slab.stage >= 0, -1)))
        )
        mx["npreds"] = max(mx["npreds"], int(jnp.max(state.slab.npreds)))
        mx["vlen"] = max(
            mx["vlen"],
            int(jnp.max(state.vlen)),
            int(jnp.max(state.slab.pvlen)),
        )
        if config.lazy_extraction:
            # Lazy configs emit through the drain pass: drain at chunk
            # cadence (the processor's) and measure there instead.
            state, dout = batch.drain(state)
            mx["mlen"] = max(mx["mlen"], int(jnp.max(dout.count)))
            mx["mchunk"] = max(
                mx["mchunk"],
                int(jnp.max(jnp.sum(dout.count > 0, axis=-1))),
            )
        else:
            mx["mlen"] = max(mx["mlen"], int(jnp.max(out.count)))
            # Completions per lane over this chunk — sum of completed
            # match slots across the chunk's (t, r) grid, max over lanes:
            # the lazy handle ring must hold one drain interval's worth.
            mx["mchunk"] = max(
                mx["mchunk"],
                int(jnp.max(jnp.sum(out.count > 0, axis=(-2, -1)))),
            )
        state = batch.sweep(state)
    return ProbeReport(
        counters=batch.counters(state),
        max_alive_runs=mx["alive"],
        max_live_entries=mx["entries"],
        max_npreds=mx["npreds"],
        max_vlen=mx["vlen"],
        max_match_len=mx["mlen"],
        max_matches_chunk=mx["mchunk"],
        config=config,
    )


def _round8(x: int) -> int:
    return max(8, int(math.ceil(x / 8)) * 8)


def suggest(tables, report: ProbeReport, margin: float = 1.5) -> EngineConfig:
    """An ``EngineConfig`` from a probe report.

    Structural floors come from the compiled tables: a run chain can hold
    ``max_hops`` frames, every stage can hold a run; measured maxima get
    ``margin`` on top.  Shapes round to multiples of 8 (TPU sublane tile)
    except the walk bound, which is exact work, not storage.  Intra-chunk
    peaks the boundary sampling missed are handled by :func:`autosize`'s
    verify step, not by padding every dimension here — a 2x "branchy"
    multiplier on runs was measured costing the loss-free bench 4.5x
    throughput for capacity the verify pass proves unnecessary.
    """
    S = tables.num_stages
    floor_runs = S + 2
    cfg = report.config
    slab_entries = _round8(max(8, int(report.max_live_entries * margin)))
    return dataclasses.replace(
        cfg,
        max_runs=_round8(
            max(floor_runs, int(report.max_alive_runs * margin))
        ),
        slab_entries=slab_entries,
        slab_hot_entries=suggest_hot_entries(
            slab_entries, report.max_alive_runs
        ),
        slab_preds=_round8(max(2, int(report.max_npreds * margin))),
        dewey_depth=_round8(
            max(tables.max_hops + 2, int(report.max_vlen * margin))
        ),
        max_walk=max(
            tables.max_hops + 2, int(report.max_match_len * margin) + 2
        ),
        handle_ring=suggest_handle_ring(report.max_matches_chunk, margin),
    )


def suggest_hot_entries(slab_entries: int, max_alive_runs: int) -> int:
    """E_hot for a derived ``slab_entries``.

    The hot tier is a perf knob, not a capacity knob (drops are identical
    at any E_hot — ops/slab.py "Two-tier layout"), so sizing targets the
    walk access pattern: walks start at run pointer events and the current
    event, so the per-step *fresh* working set is bounded by the live run
    count, and PROFILE_r05's E-sweep puts the sweet spot for the hot
    window at ~16-24 rows.  Below E=32 a two-tier split buys nothing (the
    full reduce is already hot-sized) and 0 keeps the legacy single tier.
    """
    if slab_entries < 32:
        return 0
    e_hot = _round8(max(8, min(24, 2 * max_alive_runs)))
    return min(e_hot, slab_entries - 8)


def suggest_handle_ring(max_matches_chunk: int, margin: float = 1.5) -> int:
    """HB for a probed per-chunk completion maximum.

    The ring holds every match completed between drains; the probe's
    chunk cadence matches the processor's scan cadence (drain runs after
    every scan), so the measured per-lane per-chunk completion maximum x
    margin, rounded to the sublane tile, is the loss-free capacity.
    Derived even for eager configs — the knob is inert there and a later
    ``lazy_extraction=True`` flip inherits a sized ring.
    """
    return _round8(max(8, int(max_matches_chunk * margin)))


def capacity_counters(counters: Dict[str, int]) -> Dict[str, int]:
    """The capacity-relevant subset of an engine counters dict."""
    return {k: counters[k] for k in _COUNTER_KNOB if k in counters}


def ingest_capacity_counters(stats: Dict[str, int]) -> Dict[str, int]:
    """The knob-growable subset of an ingestion-guard stats dict."""
    return {k: stats[k] for k in _INGEST_COUNTER_KNOB if k in stats}


def escalate_ingest(
    policy,
    tripped: Dict[str, int],
    growth: float = 2.0,
    max_policy=None,
):
    """The next wider :class:`~kafkastreams_cep_tpu.runtime.ingest.
    IngestPolicy` for the loss counters in ``tripped`` (counter-name ->
    positive-delta, names per ``_INGEST_COUNTER_KNOB``).

    Unlike engine escalation this is *forward-only*: the supervisor does
    not roll back and re-process (the dropped records are already in the
    dead-letter queue, recoverable by the caller) — widening stops the
    bleeding for the rest of the stream.  Returns None when nothing can
    grow (at the ``max_policy`` ceiling, or no knob-mapped counter
    tripped).
    """
    grown = {}
    for counter, delta in tripped.items():
        knob = _INGEST_COUNTER_KNOB.get(counter)
        if knob is None or not delta:
            continue
        cur = getattr(policy, knob)
        new = max(int(math.ceil(cur * growth)), cur + _INGEST_KNOB_FLOOR[knob])
        if max_policy is not None:
            new = min(new, getattr(max_policy, knob))
        if new > cur:
            grown[knob] = new
    if not grown:
        return None
    return dataclasses.replace(policy, **grown)


class EscalationPolicy(NamedTuple):
    """How a live supervisor grows capacity when a batch trips a loss
    counter (``Supervisor(auto_escalate=...)``).

    ``growth``      — multiplier applied to each tripped dimension (shape
                      dims re-round to the TPU sublane tile of 8).
    ``hysteresis``  — consecutive tripping batches required before an
                      escalation actually fires.  1 (default) escalates
                      on the first trip, which is the only setting under
                      which *nothing is ever lost* (the tripping batch is
                      rolled back and re-processed wide); >1 tolerates
                      transient spikes at the cost of warned-not-recovered
                      loss on the tolerated batches — the classic
                      stability-vs-loss hysteresis tradeoff, made
                      explicit.
    ``max_config``  — per-dimension ceiling; a dimension at its ceiling
                      stops growing (None = unbounded).  When *every*
                      tripped dimension is at its ceiling, escalation is
                      exhausted and the supervisor degrades to the
                      warn-and-count behavior.
    ``max_rounds``  — growth rounds attempted per batch (a batch whose
                      re-run still trips grows again, up to this bound).
    """

    growth: float = 2.0
    hysteresis: int = 1
    max_config: Optional[EngineConfig] = None
    max_rounds: int = 4


def escalate(
    config: EngineConfig,
    tripped: Dict[str, int],
    policy: EscalationPolicy = EscalationPolicy(),
) -> Optional[EngineConfig]:
    """The next strictly-wider config for the counters in ``tripped``
    (a counter-name -> positive-delta dict; names map to dims via the
    same ``_COUNTER_KNOB`` table autosize uses).  Returns None when every
    tripped dimension is already at its ceiling — escalation exhausted.
    """
    grown = {}
    for counter, delta in tripped.items():
        knob = _COUNTER_KNOB.get(counter)
        if knob is None or not delta:
            continue
        cur = getattr(config, knob)
        new = int(math.ceil(cur * policy.growth))
        if knob != "max_walk":  # walk bound is exact work, not storage
            new = _round8(new)
        if policy.max_config is not None:
            new = min(new, getattr(policy.max_config, knob))
        if new > cur:
            grown[knob] = new
    if not grown:
        return None
    new_cfg = dataclasses.replace(config, **grown)
    # Keep the hot-tier split valid (a perf knob — ops/slab.py proves
    # drops identical at any E_hot, so deriving it fresh is safe) and
    # sized for the grown run count.
    if new_cfg.slab_hot_entries:
        new_cfg = dataclasses.replace(
            new_cfg,
            slab_hot_entries=suggest_hot_entries(
                new_cfg.slab_entries, new_cfg.max_runs // 2
            ),
        )
    return new_cfg


def autosize(
    pattern,
    events: EventBatch,
    start: Optional[EngineConfig] = None,
    margin: float = 1.5,
    sweep_every: int = 16,
    max_iters: int = 6,
) -> EngineConfig:
    """Probe -> grow what overflowed -> re-probe -> tighten -> verify.

    Returns a config whose capacity counters are all zero on ``events``
    (the sample); raises if ``max_iters`` doublings cannot get there.
    The sample should be representative traffic — like sizing a JVM heap
    from a load test, a heavier production trace can still overflow, and
    the counters remain the runtime signal for that.
    """
    from kafkastreams_cep_tpu.compiler.tables import lower

    cfg = start or EngineConfig(
        max_runs=16, slab_entries=64, slab_preds=8, dewey_depth=16,
        max_walk=16,
    )
    tables = lower(pattern)
    report = probe(pattern, events, cfg, sweep_every)
    for it in range(max_iters):
        hot = {
            k: v for k, v in capacity_counters(report.counters).items() if v
        }
        if not hot:
            break
        grown = {}
        for counter in hot:
            knob = _COUNTER_KNOB[counter]
            grown[knob] = getattr(cfg, knob) * 2
        logger.info("autosize iter %d: grew %s (counters %s)", it, grown, hot)
        cfg = dataclasses.replace(cfg, **grown)
        report = probe(pattern, events, cfg, sweep_every)
    hot = {k: v for k, v in capacity_counters(report.counters).items() if v}
    if hot:
        raise RuntimeError(
            f"autosize: counters still nonzero after {max_iters} growth "
            f"iterations: {hot}"
        )

    tight = suggest(tables, report, margin)
    verify = probe(pattern, events, tight, sweep_every)
    if any(capacity_counters(verify.counters).values()):
        # The margin under-covered an intra-chunk peak; keep the loose
        # (verified-clean) config rather than iterate forever.
        logger.info(
            "autosize: tightened config overflowed (%s); keeping probe "
            "config", capacity_counters(verify.counters),
        )
        return report.config
    return tight
