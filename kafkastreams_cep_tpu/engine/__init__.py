"""Device NFA engine: the batched array matcher and its session wrapper."""

from kafkastreams_cep_tpu.engine.matcher import (
    ArrayStates,
    EngineConfig,
    EngineState,
    EventBatch,
    MatcherSession,
    StepOutput,
    TPUMatcher,
)

__all__ = [
    "ArrayStates",
    "EngineConfig",
    "EngineState",
    "EventBatch",
    "MatcherSession",
    "StepOutput",
    "TPUMatcher",
]
