"""Device NFA engine: the batched array matcher, the strict-SEQ stencil
fast path, and the session wrapper."""

from kafkastreams_cep_tpu.engine.matcher import (
    ArrayStates,
    DrainOutput,
    EngineConfig,
    EngineState,
    EventBatch,
    MatcherSession,
    StepOutput,
    TPUMatcher,
)
from kafkastreams_cep_tpu.engine.sizing import (
    EscalationPolicy,
    ProbeReport,
    autosize,
    capacity_counters,
    escalate,
    probe,
    suggest,
)
from kafkastreams_cep_tpu.engine.stencil import (
    PrefixCarry,
    PromoOutput,
    StencilMatcher,
    StencilOutput,
    StencilPrefix,
    StencilState,
)
from kafkastreams_cep_tpu.engine.tiered import TieredState, engine_view

__all__ = [
    "ArrayStates",
    "DrainOutput",
    "EngineConfig",
    "EngineState",
    "EscalationPolicy",
    "EventBatch",
    "MatcherSession",
    "PrefixCarry",
    "ProbeReport",
    "PromoOutput",
    "StencilMatcher",
    "StencilOutput",
    "StencilPrefix",
    "StencilState",
    "StepOutput",
    "TPUMatcher",
    "TieredState",
    "engine_view",
    "autosize",
    "capacity_counters",
    "escalate",
    "probe",
    "suggest",
]
