"""Device NFA engine: the batched array matcher, the strict-SEQ stencil
fast path, and the session wrapper."""

from kafkastreams_cep_tpu.engine.matcher import (
    ArrayStates,
    DrainOutput,
    EngineConfig,
    EngineState,
    EventBatch,
    MatcherSession,
    StepOutput,
    TPUMatcher,
)
from kafkastreams_cep_tpu.engine.sizing import (
    EscalationPolicy,
    ProbeReport,
    autosize,
    capacity_counters,
    escalate,
    probe,
    suggest,
)
from kafkastreams_cep_tpu.engine.stencil import (
    StencilMatcher,
    StencilOutput,
    StencilState,
)

__all__ = [
    "ArrayStates",
    "DrainOutput",
    "EngineConfig",
    "EngineState",
    "EscalationPolicy",
    "EventBatch",
    "MatcherSession",
    "ProbeReport",
    "StencilMatcher",
    "StencilOutput",
    "StencilState",
    "StepOutput",
    "TPUMatcher",
    "autosize",
    "capacity_counters",
    "escalate",
    "probe",
    "suggest",
]
