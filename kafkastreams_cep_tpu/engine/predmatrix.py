"""Dense predicate matrix: every distinct bank predicate once per batch.

The multi-tenant bank (``parallel/tenantbank.py``) screens N queries'
strict-contiguity prefixes over one shared ``[K, T]`` batch.  Naively that
is ``sum_q prefix_len(q)`` predicate evaluations per event; after the
bank compile pass (``compiler/multitenant.py: plan_bank``) the distinct
prefix predicates form a *column table*, and this module evaluates that
table as one dense ``[K, T, C]`` boolean matrix in a single fused pass —
each distinct predicate touches the batch exactly once, no matter how
many queries reference it.  Every query's prefix is then a gather of
``p`` columns (``group_bools``), and the whole frontier advances with
one vmapped stencil recurrence (``bank_prefix_scan``).

Bit-identity contract: ``single_prefix_scan`` is the post-predicate math
of ``engine/stencil.py: StencilPrefix._scan``, verbatim — integer and
boolean ops only, so vmapping it over a query axis is exact, and a
tenant bank's per-query promotions equal the promotions ``StencilPrefix``
would have produced for that query alone.  Column values are exact too:
a *shared* column is provably state-independent (``reads_states``), so
evaluating it under an empty states env equals evaluating it under any
owner's fold-state inits; a *private* (stateful or unkeyable) column is
evaluated under its owning query's decoded init env — exactly
``StencilPrefix._states``.

The residual (NFA-tier) analog of this matrix lives inside the engine
step itself: ``engine/matcher.py: _build_step`` splits the merged
dispatch table into event-level entries (evaluated once per event and
broadcast across runs — the per-step rows of the same conceptual matrix)
and run-level entries, on the jnp path and both Pallas kernels.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kafkastreams_cep_tpu.compiler.multitenant import PrefixColumn
from kafkastreams_cep_tpu.compiler.tables import TransitionTables
from kafkastreams_cep_tpu.engine.matcher import ArrayStates, EventBatch
from kafkastreams_cep_tpu.engine.stencil import PrefixCarry, PromoOutput


def owner_states(tables: TransitionTables) -> ArrayStates:
    """The fold-state *init* environment a prefix predicate evaluates
    against (``engine/stencil.py: StencilPrefix`` builds the same view):
    prefix stages precede every fold update, so an untiered run still in
    its prefix always sees exactly these values."""
    return ArrayStates(
        {
            name: (
                jnp.asarray(init, jnp.float32)
                if dt == "float32"
                else jnp.asarray(init, jnp.int32)
            )
            for name, init, dt in zip(
                tables.state_names, tables.state_inits, tables.state_dtypes
            )
        }
    )


def build_matrix(
    columns: Sequence[PrefixColumn],
    owner_tables: Sequence[TransitionTables],
    disabled: Sequence[int] = (),
):
    """A fused evaluator ``matrix(ev) -> [K, T, C]`` for the bank's
    prefix column table.

    Each column is one distinct predicate; shared columns get an empty
    states env (state-independence is proven, so the env is
    unobservable), private ones their owner's init env.  Values are
    ANDed with ``ev.valid`` so padded slots never fire — the same
    masking ``StencilPrefix._scan`` applies per stage.

    ``disabled`` columns (tenant quarantine — ``parallel/tenantbank.py``
    gates out every column used *only* by quarantined queries) are
    emitted as constant ``False`` without calling the predicate at all:
    a quarantined tenant's poisoned predicate can neither raise at trace
    time nor consume screen work, and its users gather only ``False`` —
    bit-identical to the screen of a bank that never contained them.
    """
    dis = frozenset(int(c) for c in disabled)
    envs = [
        ArrayStates({}) if col.shared else owner_states(
            owner_tables[col.owner]
        )
        for col in columns
    ]

    def matrix(ev: EventBatch) -> jnp.ndarray:
        K, T = ev.valid.shape
        dark = jnp.zeros((K, T), bool)
        return jnp.stack(
            [
                dark
                if ci in dis
                else (
                    jnp.broadcast_to(
                        jnp.asarray(
                            col.pred(ev.key, ev.value, ev.ts, env), bool
                        ),
                        (K, T),
                    )
                    & ev.valid
                )
                for ci, (col, env) in enumerate(zip(columns, envs))
            ],
            axis=-1,
        )

    return matrix


def group_bools(matrix: jnp.ndarray, sigs: np.ndarray) -> jnp.ndarray:
    """Gather one prefix group's stage booleans from the dense matrix.

    ``sigs`` is the group's ``[Nq, p]`` column-id table (every member has
    the same prefix length); returns ``[Nq, K, T, p]`` — query-major so
    the leading axis vmaps straight into :func:`bank_prefix_scan`.
    """
    cols = jnp.asarray(np.asarray(sigs, dtype=np.int32))
    return jnp.transpose(matrix[:, :, cols], (2, 0, 1, 3))


def single_prefix_scan(p: int):
    """The prefix recurrence for one query, predicates already evaluated.

    ``scan(carry, bools, offs, ts, valid) -> (carry, PromoOutput)`` is
    ``StencilPrefix._scan`` from its ``bools`` line down, verbatim — see
    the module docstring for why that equivalence is the whole
    correctness argument.
    """
    i32 = jnp.int32

    def scan(
        carry: PrefixCarry,
        bools: jnp.ndarray,  # [K, T, p], valid-masked
        offs: jnp.ndarray,  # [K, T] int32
        ts: jnp.ndarray,  # [K, T] int32
        valid: jnp.ndarray,  # [K, T] bool
    ) -> Tuple[PrefixCarry, PromoOutput]:
        T = ts.shape[-1]
        b0 = bools[..., 0]
        # Seed version at each batch slot: 1 + begin-accepts strictly
        # before it (the version the untiered seed hands the run it
        # creates there — the seed bumps on every accept, not only on
        # completed prefixes).
        sver = 1 + carry.cnt[:, None] + (
            jnp.cumsum(b0.astype(i32), axis=1) - b0.astype(i32)
        )

        ext_b = jnp.concatenate([carry.bools, bools], axis=1)
        ext_off = jnp.concatenate([carry.offs, offs], axis=1)
        ext_ts = jnp.concatenate([carry.ts, ts], axis=1)
        ext_sver = jnp.concatenate([carry.sver, sver], axis=1)

        # fire[k, t] = AND_j ext_b[k, t+j, j]: stage j saw event t-p+1+j.
        fire = ext_b[:, 0:T, 0]
        for j in range(1, p):
            fire = fire & ext_b[:, j : j + T, j]
        offs_out = jnp.stack(
            [ext_off[:, j : j + T] for j in range(p)], axis=-1
        )
        # Window anchor: the event the untiered run's start_ts settles on
        # (the second window event for p >= 2 — re-anchored while the run
        # identity is the BEGIN-typed stage — else the root itself).
        a = min(1, p - 1)
        anchor = ext_ts[:, a : a + T]
        sver_out = ext_sver[:, 0:T]

        # New carry: the trailing p-1 *valid* columns (valid slots form a
        # per-lane prefix, so they end at column c = carry + valid count).
        c = jnp.sum(valid, axis=1).astype(i32)
        carry_b = jax.vmap(
            lambda row, start: jax.lax.dynamic_slice(
                row, (start, 0), (p - 1, p)
            )
        )(ext_b, c)
        slice1 = lambda row, start: jax.lax.dynamic_slice(
            row, (start,), (p - 1,)
        )
        new_carry = PrefixCarry(
            bools=carry_b,
            offs=jax.vmap(slice1)(ext_off, c),
            ts=jax.vmap(slice1)(ext_ts, c),
            sver=jax.vmap(slice1)(ext_sver, c),
            cnt=carry.cnt + jnp.sum(b0.astype(i32), axis=1),
            screened=carry.screened + jnp.sum(valid.astype(i32), axis=1),
            fires=carry.fires + jnp.sum(fire.astype(i32), axis=1),
            promotions=carry.promotions,
        )
        return new_carry, PromoOutput(fire, offs_out, anchor, sver_out)

    return scan


def bank_prefix_scan(p: int):
    """The recurrence for a whole prefix group: ``scan(carries, bools_q,
    ev) -> (carries, PromoOutput)`` with carries/bools/outputs carrying a
    leading ``[Nq]`` query axis and the event batch shared.  One fused
    dispatch advances every member query's screen.
    """
    one = single_prefix_scan(p)

    def scan(carries: PrefixCarry, bools_q: jnp.ndarray, ev: EventBatch):
        offs = jnp.asarray(ev.off, jnp.int32)
        ts = jnp.asarray(ev.ts, jnp.int32)
        return jax.vmap(one, in_axes=(0, 0, None, None, None))(
            carries, bools_q, offs, ts, ev.valid
        )

    return scan


def init_carries(num_queries: int, num_lanes: int, p: int) -> PrefixCarry:
    """``[Nq]``-stacked :class:`PrefixCarry` — per query, exactly
    ``StencilPrefix.init_carry`` (fresh-screen seed version 1)."""
    Nq, K = int(num_queries), int(num_lanes)
    i32 = jnp.int32
    z = jnp.zeros((Nq, K), i32)
    return PrefixCarry(
        bools=jnp.zeros((Nq, K, p - 1, p), bool),
        offs=jnp.full((Nq, K, p - 1), -1, i32),
        ts=jnp.zeros((Nq, K, p - 1), i32),
        sver=jnp.ones((Nq, K, p - 1), i32),
        cnt=z,
        screened=z,
        fires=z,
        promotions=z,
    )
