"""The TPU array NFA engine — batched, jittable SASE+ matching.

This is the device counterpart of the host oracle (``nfa/oracle.py``) and the
reason this project exists: the per-event evaluator of the reference
(``nfa/NFA.java:94-289``) re-expressed as fixed-shape masked array programs so
it jits, vmaps over keys, and shards over a TPU mesh.

Representation
--------------
The run queue (``NFA.java:75``, a ``LinkedBlockingQueue``) becomes ``R`` fixed
run slots.  Every queued run in the reference is either the *seed* run (the
non-epsilon BEGIN stage re-added every event, ``NFA.java:148-157``) or an
epsilon wrapper ``eps(identity, target)`` (``Stage.java:42-46``), so a run slot
stores:

* ``id_pos``    — canonical identity position of the wrapper (``-1`` = seed,
  i.e. ``previous == null`` in ``NFA.evaluate``);
* ``eval_pos``  — the wrapper's PROCEED target, where edge evaluation happens;
* ``ver/vlen``  — fixed-width Dewey version (``ops/dewey_ops.py``);
* ``event_off`` — pointer-event offset (``ComputationStage.getEvent``);
* ``start_ts``  — window start; ``branching`` — the branch flag
  (``ComputationStage.java:91-97``);
* ``agg``       — per-run fold state.  Fold state can live *per slot* because
  at any time each live run has a distinct sequence id: branch runs and
  re-seeds always take fresh ids, and one run yields at most one same-id
  successor per event (a frame either recurses on PROCEED or emits its one
  local successor).

Per-event step (semantics matched to ``NFA.java:162-250``)
----------------------------------------------------------
1. all predicates are evaluated for every run against its pre-event fold
   state — exact because within one event all predicate evaluations happen
   before all folds (folds run on recursion unwind, ``NFA.java:248``), and
   runs never share fold state;
2. each run walks its PROCEED chain, statically unrolled to the pattern's
   ``max_hops``: masked BEGIN/TAKE/PROCEED/IGNORE dispatch, the 4-pair
   branching rule (``NFA.java:280-289``), stage-digit appends on non-branching
   stage crossings (``NFA.java:185-188``), producing at most one survivor,
   one branch run per frame, and the seed re-add;
3. folds apply innermost-frame-first (the unwind order), with branch-time
   fold-state copies capturing exactly the reference's
   copy-before-current-frame's-fold semantics (``NFA.java:243,248``);
4. shared-buffer mutations (``ops/slab.py``) run sequentially in the
   reference's op order: per run in queue order — consuming puts in frame
   order, then branch walks deepest-first, then dead-run removal — and match
   extraction for final states after all runs (``NFA.java:102-123``);
5. survivors/branches/re-seeds are compacted into the next queue in exactly
   the order the reference appends them; overflow beyond ``R`` is counted,
   never silent.

Windows: the reference's epsilon wrappers never carry ``windowMs``
(``Stage.newEpsilonState``, ``Stage.java:41-46``), and every non-seed run is
an epsilon wrapper, so ``isOutOfWindow`` (``ComputationStage.java:98-100``)
can never fire — ``within()`` does not prune in the reference.  The engine
reproduces that faithfully by default; ``EngineConfig.enforce_windows=True``
opts into functional pruning using the evaluation stage's window.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kafkastreams_cep_tpu.compiler.tables import (
    OP_BEGIN,
    OP_TAKE,
    TYPE_BEGIN,
    TransitionTables,
    lower,
    stackable,
)
from kafkastreams_cep_tpu.ops import dewey_ops
from kafkastreams_cep_tpu.ops import slab as slab_mod
from kafkastreams_cep_tpu.ops.onehot import get_at, get_at2, put_at
from kafkastreams_cep_tpu.pattern.pattern import Pattern
from kafkastreams_cep_tpu.utils.events import Event, Sequence

from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("engine")


class ArrayStates:
    """Read-only fold-state view handed to predicates on device.

    Mirrors ``pattern/States.java:46-68``; values are traced scalars.  Unlike
    the host view, state is always "present" (initialized to the declared
    ``init``), so ``get_or_else`` only falls back for unknown names.
    """

    __slots__ = ("_values",)

    def __init__(self, values: Dict[str, Any]):
        self._values = values

    def get(self, name: str):
        return self._values[name]

    def get_or_else(self, name: str, default):
        if name in self._values:
            return self._values[name]
        return default

    def __getitem__(self, name: str):
        return self.get(name)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static shape/feature knobs for one compiled matcher."""

    max_runs: int = 16  # R — run-queue slots (overflow counted in run_drops)
    slab_entries: int = 64  # E — shared-buffer slots per key
    # E_hot — hot-tier slots of the two-tier slab layout (0 = legacy single
    # tier).  Slots [0, E_hot) hold the most recent entries (new entries
    # always allocate hot; the least-recent hot entry demotes to the
    # overflow tier when the hot tier fills), and the walk passes resolve
    # each hop against the hot rows first, touching the overflow rows only
    # on a miss — in the Pallas kernels the common hop pays an E_hot-sized
    # reduce instead of an E-sized one (PROFILE_r05.md finding 2).  Capacity
    # semantics are unchanged: every drop counter is bit-identical to the
    # single-tier engine, and matches/slab contents agree modulo which slot
    # (tier) an entry occupies.  Must be a multiple of 8 (TPU sublane tile)
    # strictly below slab_entries.  Residency telemetry rides the
    # slab_hot_hits / slab_hot_misses / slab_overflow_walks /
    # slab_demotions counters (HOT_COUNTER_NAMES).
    slab_hot_entries: int = 0
    slab_preds: int = 8  # MP — predecessor pointers per buffer entry
    dewey_depth: int = 12  # D — fixed Dewey width (overflow counted)
    max_walk: int = 16  # W — buffer walk bound = max match length
    # Width of the compacted walker pool the jnp walk pass runs over.
    # Typically only ~1-2 of the step's 3R+ candidate walkers are enabled;
    # the pass drains enabled walkers in queue-order batches of this width.
    # 1 (default) = exactly the reference's sequential per-walker order.
    # Wider batches run walkers of a batch in lockstep — near-sequential and
    # faster when many walkers fire, but when two removal walkers meet at
    # one entry in the same hop, prune/delete attribution can deviate from
    # sequential (a refs==0 entry may survive with a stale pointer).  That
    # trigger is counted per occurrence in the ``walk_collisions`` counter:
    # a run whose walk_collisions stays 0 matched sequential order exactly;
    # nonzero means the match set may have diverged.  The fused Pallas
    # kernel path is always sequential-exact (and collision-free) regardless.
    walker_budget: int = 1
    # Delete provably-dead zero positions from all versions in a lane at
    # sweep time (ops/renorm.py) — keeps the fixed dewey_depth sufficient
    # on unbounded streams whose straddling runs append a digit per event
    # (NFA.java:185-188).  Semantics-preserving by construction; the switch
    # exists for differential testing.  Only effective when sweeps actually
    # run: BatchMatcher/ShardedMatcher ``sweep()`` between scans, which
    # ``CEPProcessor`` schedules every ``gc_interval`` batches (on by
    # default there); bare ``MatcherSession`` never sweeps.
    renorm_versions: bool = True
    enforce_windows: bool = False  # deviation: functional within() pruning
    # Apply slab ops one run at a time (the reference's literal op order)
    # instead of the batched per-step passes.  The batched path reproduces
    # the same per-entry op order (see ops/slab.py) and is ~2 orders of
    # magnitude faster on TPU; this switch exists for differential testing.
    sequential_slab: bool = False
    # Lazy match extraction (PROFILE_r06 "next leverage" item 1): when True,
    # a run reaching the final stage no longer dispatches its W-hop
    # extraction walk inside the per-step walk pass — the dominant walker
    # class and the main source of two-tier hot misses on match-dense
    # traces (PROFILE_r05 finding 2).  Instead the step emits a fixed-width
    # *handle* (root stage, root offset, Dewey version, completion step +
    # run row + timestamp) into a per-lane handle ring and *pins* the
    # referenced chain (refcount +1 at the root, so no removal walk can
    # delete it before drain; the maintenance sweep additionally roots
    # pending handles).  Materialization moves to the batched drain pass
    # (``TPUMatcher.drain`` / ``BatchMatcher.drain``) that unpins and walks
    # all pending handles together, off the per-step critical path.  The
    # drained match set is identical to the eager engine's
    # (tests/test_lazy_extraction.py); eager mode remains the differential
    # oracle.
    lazy_extraction: bool = False
    # HB — per-lane handle-ring slots (multiple of 8, TPU sublane tile).
    # Must hold every match completed between drains; a full ring drops the
    # match and counts ``handle_overflows`` (a loss counter: all-zero means
    # loss-free, like every other capacity knob — sizing.suggest derives it
    # from the probe's per-chunk match maxima).
    handle_ring: int = 16
    # Continuous profiling (PROFILE/ISSUE 6): per-stage selectivity and
    # cost attribution.  When True the engine carries per-stage tallies —
    # frames evaluated / accepted (TAKE|BEGIN fired) / ignored / rejected
    # per stage (``EngineState.stage_counts``, the lazy-chain stage-
    # ordering signal of arxiv 1612.05110) plus per-stage walk-hop costs
    # (``SlabState.stage_hops``, keyed by the walker's current stage) —
    # threaded identically through the jnp path and both Pallas kernels,
    # so the three paths agree bit-exactly.  Off (the default) every
    # attribution array has zero size and every tally is skipped at trace
    # time: zero new device work.  Not a capacity knob; migration must
    # not flip it (runtime/migrate.py _SEMANTIC_FLAGS).
    stage_attribution: bool = False
    # Compiler tiering (ROADMAP "route pattern prefixes onto the stencil
    # path"): when True the runtime builds a TieredBatchMatcher
    # (parallel/tiered.py) that runs each query's maximal strict-
    # contiguity prefix on the branch-free stencil tier over the whole
    # [K, T] batch and promotes runs into this NFA+slab engine only at
    # events where the prefix completes (compiler/tiering.py).  Matches,
    # emission order, and loss counters are bit-identical to the untiered
    # engine on loss-free workloads (tests/test_tiering.py); patterns
    # with no usable prefix fall back to whole-NFA execution unchanged.
    # Semantic for state *shape* (the tiered state carries the stencil
    # carry), so migration must not flip it (runtime/migrate.py).
    tiering: bool = False
    # Hybrid-tier gating granularity (events per device-gated segment of
    # the chunked hybrid scan, parallel/tiered.py): the [K, T] batch is
    # segmented at promotion boundaries and each segment's NFA work runs
    # under a device-side ``lax.cond`` — a segment with no live suffix
    # run and no prefix completion is skipped on device (step_seq += C in
    # one op), so the scan issues zero host syncs.  Pure performance
    # knob: any value yields bit-identical results (the skip is exact),
    # so migration/replanning may change it freely (NOT in
    # _SEMANTIC_FLAGS).  Smaller chunks skip more NFA work on screened
    # traffic; larger chunks amortize the per-segment gate.
    gate_chunk: int = 32


class EventBatch(NamedTuple):
    """One event (or a [T]-stacked batch) for a single key lane.

    ``value`` is an arbitrary pytree of numeric scalars — the same object the
    predicates receive.  ``valid`` masks padding steps.
    """

    key: jnp.ndarray
    value: Any
    ts: jnp.ndarray
    off: jnp.ndarray
    valid: jnp.ndarray


class EngineState(NamedTuple):
    """Full per-key engine state (run queue + slab + counters)."""

    alive: jnp.ndarray  # [R] bool
    id_pos: jnp.ndarray  # [R] int32 — -1 = seed run
    eval_pos: jnp.ndarray  # [R] int32
    ver: jnp.ndarray  # [R, D] int32
    vlen: jnp.ndarray  # [R] int32
    event_off: jnp.ndarray  # [R] int32 — -1 = none
    start_ts: jnp.ndarray  # [R] int32
    branching: jnp.ndarray  # [R] bool
    agg: jnp.ndarray  # [R, NS] int32 — typed-encoded fold state (float32
    #   states stored as their bit pattern; see _build_step)
    slab: slab_mod.SlabState
    run_drops: jnp.ndarray  # scalar int32 — queue-overflow drops
    ver_overflows: jnp.ndarray  # scalar int32 — Dewey add_stage overflows
    # --- lazy-extraction handle ring (EngineConfig.lazy_extraction; all
    #     fields inert under the eager engine).  Slots [0, hr_count) hold
    #     pending match handles in completion order; drain clears them.
    hr_stage: jnp.ndarray  # [HB] int32 — root identity stage (-1 free)
    hr_off: jnp.ndarray  # [HB] int32 — root event offset (walk origin)
    hr_ver: jnp.ndarray  # [HB, D] int32 — walk version at completion
    hr_vlen: jnp.ndarray  # [HB] int32
    hr_ts: jnp.ndarray  # [HB] int32 — completing event's (rebased) ts
    hr_seq: jnp.ndarray  # [HB] int32 — step_seq at completion (ordering)
    hr_row: jnp.ndarray  # [HB] int32 — completing run row (queue order)
    hr_count: jnp.ndarray  # scalar int32 — pending handles
    step_seq: jnp.ndarray  # scalar int32 — monotone per-lane step counter
    handle_overflows: jnp.ndarray  # scalar int32 — ring-full match drops
    # --- per-stage selectivity tallies (EngineConfig.stage_attribution;
    #     shape [4, 0] when off — inert).  Row order is STAGE_TALLY_NAMES:
    #     frames evaluated / accepted / ignored / rejected per stage.
    stage_counts: jnp.ndarray  # [4, S] int32


class StepOutput(NamedTuple):
    """Matches completed by one event, in emission order.

    ``stage[r, w]``/``off[r, w]`` hold the backward buffer walk of run slot
    ``r``'s match (final stage first, like ``Sequence`` insertion order);
    ``count[r]`` is 0 for slots that completed nothing.
    """

    stage: jnp.ndarray  # [R, W] int32 — identity positions
    off: jnp.ndarray  # [R, W] int32 — event offsets
    count: jnp.ndarray  # [R] int32


class DrainOutput(NamedTuple):
    """One drain pass's materialized matches, in ring (completion) order.

    Row ``h`` is handle ``h`` of the ring at drain time: ``count[h] == 0``
    past the pending prefix.  ``seq``/``row`` recover the eager engine's
    emission order ((completing step, run-queue row) — the processor sorts
    drained matches by them), ``ts`` the completing event's timestamp.
    All leading axes batch ([K] under the lane-batched matchers).
    """

    stage: jnp.ndarray  # [HB, W] int32
    off: jnp.ndarray  # [HB, W] int32
    count: jnp.ndarray  # [HB] int32
    seq: jnp.ndarray  # [HB] int32
    row: jnp.ndarray  # [HB] int32
    ts: jnp.ndarray  # [HB] int32


def _as_bool(x) -> jnp.ndarray:
    return jnp.asarray(x, dtype=bool).reshape(())


# The batched slab walks extract pointer rows with f32 matmuls (ops/slab.py
# ``_pack_ptrs``), so event offsets must stay exactly representable in
# float32.  Host entry points enforce this; the runtime's per-lane offsets
# are log positions, so the bound is 16.7M events per lane.
OFFSET_LIMIT = 1 << 24


def check_offset(offset: int) -> int:
    if offset < 0:
        raise ValueError(
            f"event offset {offset} is negative; -1 is the engine's "
            "null-pointer sentinel, so offsets must be >= 0"
        )
    if offset >= OFFSET_LIMIT:
        raise ValueError(
            f"event offset {offset} >= 2^24; the engine's f32 pointer packing "
            "requires per-lane offsets below 16,777,216 — rebase source "
            "offsets to per-lane log positions (the runtime's auto-assignment "
            "does this) before feeding the engine"
        )
    return int(offset)


# Single source of truth for the engine's overflow/drop diagnostics; every
# aggregator (matcher, batch, sharded) derives its reporting from this pair
# so names and order can never drift.
COUNTER_NAMES = (
    "run_drops",
    "ver_overflows",
    "slab_full_drops",
    "slab_pred_drops",
    "slab_missing",
    "slab_trunc",
    "walk_collisions",
    "handle_overflows",
)

# Two-tier residency telemetry (EngineConfig.slab_hot_entries) — kept OUT of
# COUNTER_NAMES on purpose: those are overflow/drop counters whose all-zero
# state means "loss-free" (bench.py, sizing.py rely on that), while these
# only describe where walk hops resolved and are nonzero on any two-tier
# run.  Same single-source discipline: every reporter derives from this
# pair.
HOT_COUNTER_NAMES = (
    "slab_hot_hits",
    "slab_hot_misses",
    "slab_overflow_walks",
    "slab_demotions",
)

# Walk-cost telemetry (PROFILE_r05/r06: the walk pass is compute-bound on
# per-hop reduces x lockstep trip counts) — like HOT_COUNTER_NAMES these are
# NOT loss indicators and live outside COUNTER_NAMES; they make the
# reduce-width perf model measurable on CPU CI.  ``extract_hops`` counts
# eager in-step extraction walk hops; ``drain_hops`` the deferred drain
# pass's (lazy_extraction); ``walk_hops`` everything else (branch refcount
# walks, dead-run removals).
WALK_COUNTER_NAMES = (
    "walk_hops",
    "extract_hops",
    "drain_hops",
)

# Per-stage selectivity tallies (EngineConfig.stage_attribution), in the
# row order of ``EngineState.stage_counts``.  Like the walk counters these
# are NOT loss indicators; they exist so the compiler-tiering and
# lazy-chain stage-ordering work (ROADMAP) can read per-stage selectivity
# (accepts / evals) and cost without hand-run scripts.  The per-stage
# walk-hop cost rides ``SlabState.stage_hops`` and reports beside these
# as ``stage_walk_hops``.
STAGE_TALLY_NAMES = (
    "stage_evals",
    "stage_accepts",
    "stage_ignores",
    "stage_rejects",
)

# Compiler-tiering telemetry (EngineConfig.tiering): how much traffic the
# stencil prefix tier absorbed before the NFA tier saw anything.  NOT loss
# indicators (like the hot/walk counters) — ``prefix_events_screened``
# counts every valid event the prefix evaluated, ``prefix_fires`` the
# prefix completions, ``tier_promotions`` the runs actually injected into
# the NFA tier (fires minus queue-overflow drops).  Untiered matchers
# report them as structural zeros so dashboards need no per-tier schema.
TIER_COUNTER_NAMES = (
    "prefix_events_screened",
    "prefix_fires",
    "tier_promotions",
)


def counter_values(state: "EngineState") -> Tuple[jnp.ndarray, ...]:
    """The counters of ``state`` in ``COUNTER_NAMES`` order."""
    return (
        state.run_drops,
        state.ver_overflows,
        state.slab.full_drops,
        state.slab.pred_drops,
        state.slab.missing,
        state.slab.trunc,
        state.slab.collisions,
        state.handle_overflows,
    )


def hot_counter_values(state: "EngineState") -> Tuple[jnp.ndarray, ...]:
    """The two-tier counters of ``state`` in ``HOT_COUNTER_NAMES`` order."""
    return (
        state.slab.hot_hits,
        state.slab.hot_misses,
        state.slab.overflow_walks,
        state.slab.demotions,
    )


def walk_counter_values(state: "EngineState") -> Tuple[jnp.ndarray, ...]:
    """The walk-cost counters of ``state`` in ``WALK_COUNTER_NAMES``
    order."""
    return (
        state.slab.walk_hops,
        state.slab.extract_hops,
        state.slab.drain_hops,
    )


def per_lane_counter_arrays(state: "EngineState") -> Dict[str, Any]:
    """Un-summed counter arrays (drop + hot + walk-cost), one host int64
    array per name, for per-lane attribution (telemetry pillar 3): a
    ``[K]``-batched state yields ``[K]`` arrays — which lane is burning
    capacity — while a single-lane state yields scalars.  One
    ``device_get`` for all of them.
    """
    names = COUNTER_NAMES + HOT_COUNTER_NAMES + WALK_COUNTER_NAMES
    vals = jax.device_get(
        counter_values(state)
        + hot_counter_values(state)
        + walk_counter_values(state)
    )
    return {
        n: np.asarray(v).astype(np.int64) for n, v in zip(names, vals)
    }


def stage_counter_arrays(state: "EngineState") -> Dict[str, Any]:
    """Per-stage attribution arrays as host int64 ndarrays: the four
    selectivity tallies (``STAGE_TALLY_NAMES``, each ``[..., S]``) plus
    ``stage_walk_hops`` from the slab.  Leading batch axes (lanes) are
    preserved so callers can attribute per lane *and* per stage; empty
    dict when attribution is off (zero-size arrays).  One ``device_get``
    for all of them."""
    if int(state.stage_counts.shape[-1]) == 0:
        return {}
    sc, sh = jax.device_get((state.stage_counts, state.slab.stage_hops))
    sc = np.asarray(sc).astype(np.int64)
    out = {
        n: sc[..., i, :] for i, n in enumerate(STAGE_TALLY_NAMES)
    }
    out["stage_walk_hops"] = np.asarray(sh).astype(np.int64)
    return out


def stage_report(
    arrays: Dict[str, Any], names: Sequence[str]
) -> Dict[str, Dict[str, int]]:
    """``stage_counter_arrays`` output -> ``{stage_name: {metric: total}}``
    with leading (lane) axes summed away and a derived ``selectivity``
    (accepts / evals) per stage — the roll-up ``metrics_snapshot``
    publishes under ``per_stage``."""
    if not arrays:
        return {}
    S = next(iter(arrays.values())).shape[-1]
    out: Dict[str, Dict[str, int]] = {}
    for s in range(S):
        name = names[s] if s < len(names) else f"stage{s}"
        row = {
            metric: int(np.asarray(arr).reshape(-1, S)[:, s].sum())
            for metric, arr in arrays.items()
        }
        ev = row.get("stage_evals", 0)
        row["selectivity"] = (
            round(row.get("stage_accepts", 0) / ev, 6) if ev else 0.0
        )
        out[name] = row
    return out


class StepPhases(NamedTuple):
    """The step's per-lane phase functions, exposed so batched callers can
    run the walk pass over the full lane batch (the fused Pallas kernel
    operates on ``[K]``-batched slabs and cannot live under ``vmap``)."""

    eval_chain: Any
    build_puts: Any
    build_walkers: Any
    finish: Any
    out_base: int
    out_rows: int
    max_walk: int
    hot_entries: int
    pred_stats: Any = None  # merged-dispatch dedup stats (multitenant)


class _ChainRecord(NamedTuple):
    """Everything one run's chain produced, consumed by the slab pass."""

    surv_alive: jnp.ndarray
    surv_final: jnp.ndarray
    surv_id: jnp.ndarray
    surv_eval: jnp.ndarray
    surv_ver: jnp.ndarray
    surv_vlen: jnp.ndarray
    surv_event: jnp.ndarray
    surv_start: jnp.ndarray
    surv_branching: jnp.ndarray
    put_en: jnp.ndarray  # [H]
    put_cur: jnp.ndarray  # [H]
    put_prev: jnp.ndarray  # [H] — -1 = put_first
    put_ver: jnp.ndarray  # [H, D]
    put_vlen: jnp.ndarray  # [H]
    br_en: jnp.ndarray  # [H]
    br_prev: jnp.ndarray  # [H] — walk origin stage
    br_ver: jnp.ndarray  # [H, D] — walk version (pre-add_run)
    br_vlen: jnp.ndarray  # [H]
    br_run_ver: jnp.ndarray  # [H, D] — branch-run version (add_run)
    br_run_vlen: jnp.ndarray  # [H]
    br_id: jnp.ndarray  # [H] — branch-run identity (= prev)
    br_eval: jnp.ndarray  # [H] — branch-run eval (= frame stage)
    br_event: jnp.ndarray  # [H]
    br_start: jnp.ndarray  # [H]
    br_agg: jnp.ndarray  # [H, NS] — typed-encoded
    final_agg: jnp.ndarray  # [NS] — survivor fold state (all folds applied)
    has_succ: jnp.ndarray
    dead: jnp.ndarray
    ovf: jnp.ndarray  # int32 — Dewey overflows in this chain
    stage_tally: jnp.ndarray  # [4, S] int32 — per-stage selectivity tallies
    #   in STAGE_TALLY_NAMES row order ([4, 0] when attribution is off)


def _build_step(tables, cfg: EngineConfig):
    """Compile the per-event step — a pure jittable fn.

    ``tables`` is one :class:`TransitionTables` or a LIST of them sharing
    the compiled table shape: a *stacked bank* (BASELINE.json config 4).
    Stacked tables ride a leading query axis selected per lane by a traced
    ``qid``; per-query predicates and folds are statically merged, so N
    same-shape queries run as one compiled program over ``N x K`` lanes
    instead of N dispatches.
    """
    tlist = list(tables) if isinstance(tables, (list, tuple)) else [tables]
    tables = tlist[0]
    Q = len(tlist)
    if not stackable(tlist):
        raise ValueError(
            "stacked patterns must share the compiled table shape "
            "(stage count, chain depth, begin/final positions); "
            "fall back to one matcher per query otherwise"
        )
    R, D, W = cfg.max_runs, cfg.dewey_depth, cfg.max_walk
    EH = cfg.slab_hot_entries
    if EH:
        if EH % 8 or not 0 < EH < cfg.slab_entries:
            raise ValueError(
                f"slab_hot_entries={EH} must be a multiple of 8 strictly "
                f"below slab_entries={cfg.slab_entries} (0 disables the "
                "two-tier layout)"
            )
    HB = cfg.handle_ring
    if HB <= 0 or HB % 8:
        raise ValueError(
            f"handle_ring={HB} must be a positive multiple of 8 (TPU "
            "sublane tile; the ring is engine state even under the eager "
            "engine)"
        )
    H = tables.max_hops
    NS = max(max(t.num_states for t in tlist), 1)
    S_CAND = 1 + H + 1  # survivor, branch per hop, re-seed
    # Per-stage attribution width: the pattern's stage count when enabled,
    # 0 (zero-size arrays, zero device work) when not.
    S_AT = tables.num_stages if cfg.stage_attribution else 0

    # Merged predicate dispatch table: the union of all queries'
    # predicates deduplicated and split into an event-level half (proven
    # independent of per-run fold state — evaluated once per event, the
    # dense predicate-matrix rows) and a run-level half (evaluated per
    # run under the owner query's decode).  compiler/multitenant.py owns
    # the proofs; per-query table entries remap into the merged ids.
    from kafkastreams_cep_tpu.compiler.multitenant import (
        plan_step_predicates,
    )

    pred_plan = plan_step_predicates(tlist)
    _remaps = pred_plan.remaps

    def stk(get, offset=False):
        rows = []
        for q, t in enumerate(tlist):
            a = np.asarray(get(t))
            if offset and len(_remaps[q]):
                a = np.where(a >= 0, _remaps[q][np.maximum(a, 0)], a)
            rows.append(a)
        return jnp.asarray(np.stack(rows))  # [Q, S]

    ident = stk(lambda t: t.ident)
    types = stk(lambda t: t.types)
    consume_op = stk(lambda t: t.consume_op)
    consume_pred = stk(lambda t: t.consume_pred, offset=True)
    consume_target = stk(lambda t: t.consume_target)
    ignore_pred = stk(lambda t: t.ignore_pred, offset=True)
    proceed_pred = stk(lambda t: t.proceed_pred, offset=True)
    proceed_target = stk(lambda t: t.proceed_target)
    # Device time is int32 (TPU-native width; callers rebase epoch-ms via
    # the runtime's `epoch`, runtime/processor.py).  Windows must fit too.
    for t in tlist:
        if t.window_ms.max(initial=-1) > np.iinfo(np.int32).max:
            raise ValueError(
                f"window of {int(t.window_ms.max())} ms exceeds int32 device "
                "time; windows up to ~24.8 days are supported"
            )
    window_ms = stk(lambda t: t.window_ms.astype(np.int32))
    final_pos = int(tables.final_pos)
    begin_pos = int(tables.begin_pos)
    # Typed fold state (the array analog of the reference's generic
    # ``Aggregator<K, V, T>``, ``Aggregator.java:22-25``): every state is
    # STORED as int32 — float32 states as their bit pattern — so the
    # structural machinery (branch copies, queue compaction, checkpoints)
    # is dtype-blind and bit-exact, and int32 folds stay exact past
    # float32's 2^24 integer range.  Values are decoded/encoded only at
    # the fold and predicate boundaries.  Per query when stacked.
    is_float_q = [
        [d == "float32" for d in t.state_dtypes]
        + [False] * (NS - t.num_states)
        for t in tlist
    ]

    def _enc_host(x, flt):
        if flt:
            return int(np.float32(x).view(np.int32))
        return int(np.int32(x))

    inits = jnp.asarray(
        [
            [
                _enc_host(x, f)
                for x, f in zip(
                    list(t.state_inits) + [0] * (NS - t.num_states),
                    is_float_q[q],
                )
            ]
            or [0]
            for q, t in enumerate(tlist)
        ],
        dtype=jnp.int32,
    )  # [Q, NS]

    def dec(v, flt):
        return jax.lax.bitcast_convert_type(v, jnp.float32) if flt else v

    def enc(v, flt):
        if flt:
            return jax.lax.bitcast_convert_type(
                jnp.asarray(v, jnp.float32), jnp.int32
            )
        return jnp.asarray(v, jnp.int32)

    def inits_of(qid):
        return inits[0] if Q == 1 else get_at(inits, qid)

    G0, G1 = pred_plan.num_event, pred_plan.num_run

    def eval_preds_event(key, value, ts):
        """The event-level half of the merged dispatch table: predicates
        proven independent of per-run fold state (``compiler/multitenant.
        reads_states``), deduplicated across stacked queries, evaluated
        ONCE per event instead of once per run per query.  The ``states``
        argument is provably never observed; an empty view is passed."""
        empty = ArrayStates({})
        return jnp.stack(
            [
                _as_bool(e.pred(key, value, ts, empty))
                for e in pred_plan.event_entries
            ]
        )

    def eval_preds_run(key, value, ts, agg_row):
        """The run-level half: each fold-state-reading predicate against
        the lane's agg row decoded through its OWNER query's
        names/dtypes.

        Stacked-bank contract: a lane's agg row is also decoded under
        *other* queries' dtype conventions (every run-level predicate
        evaluates on every lane); those values are never selected — the
        per-query remap keeps each lane on its own query's predicate ids
        — but the evaluation itself happens.  Predicates must therefore
        be pure array functions — no side effects, no host callbacks,
        total over garbage inputs.  jit tracing already enforces the
        first two; NaN- or overflow-sensitive user code must tolerate
        off-query rows."""
        env: Dict[int, ArrayStates] = {}
        vals = []
        for e in pred_plan.run_entries:
            states = env.get(e.owner)
            if states is None:
                t = tlist[e.owner]
                states = ArrayStates(
                    {
                        n: dec(agg_row[i], is_float_q[e.owner][i])
                        for i, n in enumerate(t.state_names)
                    }
                )
                env[e.owner] = states
            vals.append(_as_bool(e.pred(key, value, ts, states)))
        return jnp.stack(vals)

    # All traced-index reads below go through one-hot selects (ops/onehot)
    # instead of gathers/scatters so the whole chain fuses on TPU — see the
    # implementation note in ops/slab.py.  Tables carry a leading query
    # axis; Q == 1 resolves it statically.
    def tbl(table, idx, qid):
        """``table[qid][idx]`` for a static table and traced indices."""
        if Q == 1:
            return get_at(table[0], idx)
        return get_at2(table, qid, idx)

    def pv(preds, pid):
        """Predicate value by id; ``-1`` (absent edge) is False."""
        return jnp.where(pid >= 0, get_at(preds, jnp.maximum(pid, 0)), False)

    def chain_one(
        alive, id_pos, eval_pos, ver, vlen, event_off, start_ts0, branching, agg,
        preds, key, value, ts, off, qid,
    ) -> _ChainRecord:
        """One run's full evaluation chain (``NFA.evaluate``, recursion
        unrolled to the pattern depth)."""
        i32 = jnp.int32
        seed = id_pos < 0
        idc = jnp.maximum(id_pos, 0)
        # getFirstPatternTimestamp (NFA.java:347-349): BEGIN-typed runs reset
        # the window start to the current event's timestamp.
        id_type_begin = seed | (tbl(types, idc, qid) == TYPE_BEGIN)
        start = jnp.where(id_type_begin, ts, start_ts0)

        if cfg.enforce_windows:
            w = tbl(window_ms, eval_pos, qid)
            out_w = (~id_type_begin) & (w != -1) & (ts - start_ts0 > w)
        else:
            # Faithful: epsilon wrappers carry windowMs == -1
            # (Stage.java:41-46), so no run is ever out of window.
            out_w = jnp.bool_(False)
        active = alive & ~out_w

        # Epsilon-hop stage digit (NFA.java:185-188): crossing into a new
        # stage off a non-branching run appends ".0".  A branching run never
        # appends (its flag survives the whole chain because setVersion — the
        # only thing that clears it — is itself gated on not-branching).
        cross0 = tbl(ident, eval_pos, qid) != idc
        do_add0 = active & ~seed & cross0 & ~branching
        _, vlen_a, ovf0 = dewey_ops.add_stage(ver, vlen)
        vl = jnp.where(do_add0, vlen_a, vlen)
        vv = ver
        ovf = jnp.where(do_add0 & ovf0, 1, 0).astype(i32)

        cur = eval_pos
        prev = jnp.where(seed, i32(-1), id_pos)

        zero_ver = jnp.zeros((D,), i32)
        surv_alive = jnp.bool_(False)
        surv_final = jnp.bool_(False)
        surv_id = i32(0)
        surv_eval = i32(0)
        surv_ver = zero_ver
        surv_vlen = i32(0)
        surv_event = i32(0)
        surv_start = i32(0)
        surv_branching = jnp.bool_(False)

        put_en, put_cur, put_prev, put_ver, put_vlen = [], [], [], [], []
        br_en, br_prev, br_ver, br_vlen = [], [], [], []
        br_run_ver, br_run_vlen, br_id, br_eval, br_event, br_start = [], [], [], [], [], []
        consumed_h, frame_pos = [], []
        tally = jnp.zeros((4, S_AT), i32)

        for _h in range(H):
            cs = jnp.maximum(cur, 0)
            cop = tbl(consume_op, cs, qid)
            cp = pv(preds, tbl(consume_pred, cs, qid))
            take_m = active & (cop == OP_TAKE) & cp
            begin_m = active & (cop == OP_BEGIN) & cp
            ig_m = active & pv(preds, tbl(ignore_pred, cs, qid))
            pr_m = active & pv(preds, tbl(proceed_pred, cs, qid))
            # The 4-pair nondeterministic branching rule (NFA.java:280-289).
            branch_m = (pr_m & take_m) | (ig_m & take_m) | (ig_m & begin_m) | (ig_m & pr_m)
            branch_m = branch_m & (prev >= 0)  # unreachable for seeds; guard
            consumed = take_m | begin_m
            if S_AT:
                # Per-stage selectivity: every frame that ran predicate
                # dispatch at stage ``cs`` tallies one eval, plus one
                # accept (consumed), ignore, or reject (nothing fired —
                # the run dead-ends here) as applicable.
                rejected = active & ~consumed & ~ig_m & ~pr_m
                oh_s = jnp.arange(S_AT, dtype=i32) == cs
                tally = tally + (
                    oh_s[None, :]
                    & jnp.stack([active, consumed, ig_m, rejected])[:, None]
                ).astype(i32)

            # Survivor: at most one across the chain — a frame either
            # recurses on PROCEED or emits its single local successor.
            st = take_m & ~branch_m  # self-loop re-add (NFA.java:196-205)
            sb = begin_m  # advance (NFA.java:210-222), kept even when branching
            si = ig_m & ~branch_m  # unchanged re-add (NFA.java:223-227)
            fire = st | sb | si
            tgt = tbl(consume_target, cs, qid)
            surv_id = jnp.where(fire, jnp.where(si, id_pos, tbl(ident, cs, qid)), surv_id)
            surv_eval = jnp.where(
                fire, jnp.where(st, cs, jnp.where(sb, tgt, eval_pos)), surv_eval
            )
            surv_ver = jnp.where(fire, vv, surv_ver)
            surv_vlen = jnp.where(fire, vl, surv_vlen)
            surv_event = jnp.where(fire, jnp.where(si, event_off, off), surv_event)
            surv_start = jnp.where(fire, jnp.where(si, start_ts0, start), surv_start)
            surv_branching = jnp.where(fire, si & branching, surv_branching)
            surv_final = jnp.where(fire, sb & (tgt == final_pos), surv_final)
            surv_alive = surv_alive | fire

            # Consuming put; on a branching TAKE the event is recorded under
            # the bumped version and no successor is emitted (NFA.java:206-208).
            put_en.append(consumed)
            put_cur.append(tbl(ident, cs, qid))
            put_prev.append(jnp.where(prev >= 0, tbl(ident, jnp.maximum(prev, 0), qid), i32(-1)))
            put_ver.append(jnp.where(take_m & branch_m, dewey_ops.add_run(vv, vl), vv))
            put_vlen.append(vl)

            # Branch run (NFA.java:231-246): eps(previous, current), version
            # addRun, pointer event = previous when the frame also ignored.
            br_en.append(branch_m)
            br_prev.append(tbl(ident, jnp.maximum(prev, 0), qid))
            br_ver.append(vv)
            br_vlen.append(vl)
            br_run_ver.append(dewey_ops.add_run(vv, vl))
            br_run_vlen.append(vl)
            br_id.append(tbl(ident, jnp.maximum(prev, 0), qid))
            br_eval.append(cs)
            br_event.append(jnp.where(ig_m, event_off, off))
            br_start.append(start)
            consumed_h.append(consumed)
            frame_pos.append(cs)

            # PROCEED recursion (NFA.java:182-190).
            ptgt = tbl(proceed_target, cs, qid)
            ptc = jnp.maximum(ptgt, 0)
            do_add = pr_m & (tbl(ident, ptc, qid) != tbl(ident, cs, qid)) & ~branching
            _, vlen_b, ovf_b = dewey_ops.add_stage(vv, vl)
            vl = jnp.where(do_add, vlen_b, vl)
            ovf = ovf + jnp.where(do_add & ovf_b, 1, 0).astype(i32)
            prev = jnp.where(pr_m, cs, prev)
            cur = jnp.where(pr_m, ptc, cur)
            active = pr_m

        # Fold pass, innermost frame first (folds run on recursion unwind,
        # NFA.java:248); branch-time copies capture the state *before* the
        # branching frame's own fold but *after* deeper frames'
        # (NFA.java:243 runs before :248), restricted to the states declared
        # at the branching stage (ValueStore.branch copies only those).
        s = agg
        inits_l = inits_of(qid)
        br_agg: List[Any] = [None] * H
        for h in range(H - 1, -1, -1):
            copy_mask = jnp.zeros((NS,), bool)
            for q, t in enumerate(tlist):
                qm = True if Q == 1 else (qid == q)
                for slot in t.aggs:
                    copy_mask = copy_mask.at[slot.state].set(
                        copy_mask[slot.state]
                        | ((frame_pos[h] == slot.stage) & qm)
                    )
            br_agg[h] = jnp.where(copy_mask, s, inits_l)
            for q, t in enumerate(tlist):
                qm = True if Q == 1 else (qid == q)
                for slot in t.aggs:
                    cond = consumed_h[h] & (frame_pos[h] == slot.stage) & qm
                    flt = is_float_q[q][slot.state]
                    val = enc(
                        slot.fn(key, value, dec(s[slot.state], flt)), flt
                    )
                    s = s.at[slot.state].set(
                        jnp.where(cond, val, s[slot.state])
                    )
        final_agg = s

        any_br = jnp.any(jnp.stack(br_en)) if H else jnp.bool_(False)
        has_succ = surv_alive | any_br
        dead = alive & ~seed & ~has_succ

        stk = jnp.stack
        return _ChainRecord(
            surv_alive, surv_final, surv_id, surv_eval, surv_ver, surv_vlen,
            surv_event, surv_start, surv_branching,
            stk(put_en), stk(put_cur), stk(put_prev), stk(put_ver), stk(put_vlen),
            stk(br_en), stk(br_prev), stk(br_ver), stk(br_vlen),
            stk(br_run_ver), stk(br_run_vlen), stk(br_id), stk(br_eval),
            stk(br_event), stk(br_start),
            stk(br_agg), final_agg, has_succ, dead, ovf, tally,
        )

    RH = R * H

    def eval_chain(
        state: EngineState, ev: EventBatch, qid=None
    ) -> _ChainRecord:
        """Predicate evaluation + every run's unrolled chain (per lane).
        ``qid`` selects the lane's query in a stacked bank (None = 0)."""
        i32 = jnp.int32
        if qid is None:
            qid = jnp.zeros((), i32)
        key, value = ev.key, ev.value
        ts, off = jnp.asarray(ev.ts, i32), jnp.asarray(ev.off, i32)
        # The merged [R, G] predicate frame: the event-level block is one
        # evaluation broadcast over runs; only state-reading predicates
        # pay the per-run vmap.
        parts = []
        if G0:
            parts.append(
                jnp.broadcast_to(
                    eval_preds_event(key, value, ts), (R, G0)
                )
            )
        if G1:
            parts.append(
                jax.vmap(lambda a: eval_preds_run(key, value, ts, a))(
                    state.agg
                )
            )
        if len(parts) == 2:
            preds = jnp.concatenate(parts, axis=-1)
        elif parts:
            preds = parts[0]
        else:
            preds = jnp.zeros((R, 0), jnp.bool_)
        return jax.vmap(
            chain_one,
            in_axes=(0, 0, 0, 0, 0, 0, 0, 0, 0, 0, None, None, None, None,
                     None),
        )(
            state.alive, state.id_pos, state.eval_pos, state.ver, state.vlen,
            state.event_off, state.start_ts, state.branching, state.agg,
            preds, key, value, ts, off, qid,
        )

    def build_puts(state: EngineState, rec: _ChainRecord, ev: EventBatch):
        """The step's consuming-put ops (per lane), in reference order:
        run-major, frame-ascending (``NFA.java`` queue order)."""
        prev_off_rep = jnp.repeat(state.event_off, H)
        return slab_mod.PutOps(
            en=rec.put_en.reshape(RH),
            first=rec.put_prev.reshape(RH) < 0,
            cur_stage=rec.put_cur.reshape(RH),
            prev_stage=rec.put_prev.reshape(RH),
            prev_off=prev_off_rep,
            ver=rec.put_ver.reshape(RH, D),
            vlen=rec.put_vlen.reshape(RH),
        )

    def build_walkers(state: EngineState, rec: _ChainRecord, ev: EventBatch):
        """The step's walker-candidate queue (per lane; no slab mutation).

        Queue layout (reference op order): branch frames deepest-first per
        run ([RH]), dead-run removals ([R]), final extractions ([R]) —
        ``out_base = RH + R``, ``out_rows = R``.
        """
        i32 = jnp.int32
        off = jnp.asarray(ev.off, i32)
        valid = _as_bool(ev.valid)
        final_en = rec.surv_alive & rec.surv_final & valid
        if cfg.lazy_extraction:
            # Lazy extraction: completed matches become ring handles
            # (finish()) instead of W-hop extraction walkers — the final
            # segment keeps its rows (layout is static) but never enables.
            final_en = jnp.zeros_like(final_en)

        prev_off_rep = jnp.repeat(state.event_off, H)

        def rev(f):
            return f[:, ::-1].reshape((RH,) + f.shape[2:])

        dead_en = rec.dead & (state.event_off >= 0)
        w_en = jnp.concatenate([rev(rec.br_en), dead_en, final_en])
        w_stage = jnp.concatenate(
            [rev(rec.br_prev), jnp.maximum(state.id_pos, 0), rec.surv_id]
        )
        w_off = jnp.concatenate(
            [prev_off_rep, state.event_off, jnp.broadcast_to(off, (R,))]
        )
        w_ver = jnp.concatenate([rev(rec.br_ver), state.ver, rec.surv_ver])
        w_vlen = jnp.concatenate(
            [rev(rec.br_vlen), state.vlen, rec.surv_vlen]
        )
        w_remove = jnp.concatenate(
            [jnp.zeros((RH,), bool), jnp.ones((2 * R,), bool)]
        )
        w_out = jnp.concatenate(
            [jnp.zeros((RH + R,), bool), jnp.ones((R,), bool)]
        )
        return (w_en, w_stage, w_off, w_ver, w_vlen, w_remove, w_out)

    def step(
        state: EngineState, ev: EventBatch, qid=None
    ) -> Tuple[EngineState, StepOutput]:
        i32 = jnp.int32
        off = jnp.asarray(ev.off, i32)
        valid = _as_bool(ev.valid)

        rec = eval_chain(state, ev, qid)

        # --- Shared-buffer mutations, in the reference's exact op order:
        # per run (queue order): consuming puts frame-by-frame, branch walks
        # deepest-first (they run on recursion unwind), then dead-run path
        # removal (NFA.java:102-103,117-123).  The batched path applies the
        # same ops phase-by-phase with identical per-entry ordering
        # (ops/slab.py batched kernels); the sequential path below executes
        # them literally one run at a time.
        final_en = rec.surv_alive & rec.surv_final & valid

        def run_body(r, slab):
            # Row extraction by one-hot (r is a traced loop index); the ``h``
            # indexing below is static.
            prev_off = get_at(state.event_off, r)
            put_en = get_at(rec.put_en, r)
            put_cur = get_at(rec.put_cur, r)
            put_prev = get_at(rec.put_prev, r)
            put_ver = get_at(rec.put_ver, r)
            put_vlen = get_at(rec.put_vlen, r)
            for h in range(H):
                en = put_en[h]
                first = en & (put_prev[h] < 0)
                chained = en & (put_prev[h] >= 0)
                slab = slab_mod.put_first(
                    slab, put_cur[h], off,
                    put_ver[h], put_vlen[h], enable=first, hot_entries=EH,
                )
                slab = slab_mod.put(
                    slab, put_cur[h], off, put_prev[h], prev_off,
                    put_ver[h], put_vlen[h], enable=chained, hot_entries=EH,
                )
            br_en = get_at(rec.br_en, r)
            br_prev = get_at(rec.br_prev, r)
            br_ver = get_at(rec.br_ver, r)
            br_vlen = get_at(rec.br_vlen, r)
            for h in range(H - 1, -1, -1):
                slab = slab_mod.branch(
                    slab, br_prev[h], prev_off,
                    br_ver[h], br_vlen[h], W,
                    enable=br_en[h], hot_entries=EH,
                )
            dead_en = get_at(rec.dead, r) & (prev_off >= 0)
            slab, _, _, _ = slab_mod.peek(
                slab, jnp.maximum(get_at(state.id_pos, r), 0), prev_off,
                get_at(state.ver, r), get_at(state.vlen, r), W,
                remove=True, enable=dead_en, hot_entries=EH,
                hop_kind="walk",
            )
            return slab

        def fin_body(r, carry):
            slab, out_stage, out_off, out_count = carry
            fe = get_at(final_en, r)
            slab, st_row, off_row, cnt = slab_mod.peek(
                slab, get_at(rec.surv_id, r), off, get_at(rec.surv_ver, r),
                get_at(rec.surv_vlen, r), W, remove=True, enable=fe,
                hot_entries=EH,
            )
            out_stage = put_at(out_stage, r, st_row[None, :], enable=fe)
            out_off = put_at(out_off, r, off_row[None, :], enable=fe)
            out_count = put_at(out_count, r, cnt, enable=fe)
            return slab, out_stage, out_off, out_count

        if cfg.sequential_slab:
            slab = jax.lax.fori_loop(0, R, run_body, state.slab)
            if cfg.lazy_extraction:
                # Lazy: finish() appends handles instead; no in-step
                # extraction walks at all.
                out_stage = jnp.full((R, W), -1, i32)
                out_off = jnp.full((R, W), -1, i32)
                out_count = jnp.zeros((R,), i32)
            else:
                # Match construction for final states, after all runs
                # (NFA.java:111-115), in queue order.
                slab, out_stage, out_off, out_count = jax.lax.fori_loop(
                    0, R, fin_body,
                    (
                        slab,
                        jnp.full((R, W), -1, i32),
                        jnp.full((R, W), -1, i32),
                        jnp.zeros((R,), i32),
                    ),
                )
        else:
            # One walk pass serves every walker of the step — branch
            # refcount walks (deepest-first per run, NFA.java:231-246),
            # dead-run removals (NFA.java:102-103,117-123), and final-match
            # extraction (NFA.java:111-115) — compacted in queue-order rank
            # into a small pool (PROFILE_r04.md: carrying all 3R+ slots
            # through every hop was ~90% of the step).
            # (Rank-compacting the puts like the walk pass was measured
            # net-negative in jnp: the vmapped batch loop costs every lane
            # the busiest lane's batch count.  puts_batched's O(RH^2)
            # masks fuse well under XLA; the fused kernel path applies
            # puts in-kernel instead.)
            slab = slab_mod.puts_batched(
                state.slab, build_puts(state, rec, ev), off, hot_entries=EH
            )
            wk = build_walkers(state, rec, ev)
            slab, out_stage, out_off, out_count = slab_mod.walks_compacted(
                slab, *wk, W,
                budget=cfg.walker_budget, out_base=RH + R, out_rows=R,
                hot_entries=EH,
            )

        return finish(state, ev, rec, slab, out_stage, out_off, out_count,
                      qid)

    def finish(
        state: EngineState,
        ev: EventBatch,
        rec: _ChainRecord,
        slab,
        out_stage,
        out_off,
        out_count,
        qid=None,
    ) -> Tuple[EngineState, StepOutput]:
        """Queue compaction + padding masking (per lane)."""
        i32 = jnp.int32
        if qid is None:
            qid = jnp.zeros((), i32)
        valid = _as_bool(ev.valid)
        inits_l = inits_of(qid)

        # --- Next queue: per run [survivor, branches deepest-first, re-seed],
        # flattened in queue order, compacted into R slots (overflow counted).
        seed_mask = state.alive & (state.id_pos < 0)
        reseed_ver = jnp.where(
            rec.has_succ[:, None],
            jax.vmap(dewey_ops.add_run)(state.ver, state.vlen),
            state.ver,
        )

        def cand(field_surv, field_br, field_seed):
            # [R] / [R, H] / [R] -> [R, S_CAND]; branches deepest-first.
            parts = [field_surv[:, None]]
            if H:
                parts.append(field_br[:, ::-1])
            parts.append(field_seed[:, None])
            return jnp.concatenate(parts, axis=1)

        c_alive = cand(
            rec.surv_alive & ~rec.surv_final,
            rec.br_en,
            seed_mask,
        )
        c_id = cand(rec.surv_id, rec.br_id, jnp.full((R,), -1, i32))
        c_eval = cand(rec.surv_eval, rec.br_eval, jnp.full((R,), begin_pos, i32))
        c_ver = jnp.concatenate(
            [rec.surv_ver[:, None, :]]
            + ([rec.br_run_ver[:, ::-1, :]] if H else [])
            + [reseed_ver[:, None, :]],
            axis=1,
        )
        c_vlen = cand(rec.surv_vlen, rec.br_run_vlen, state.vlen)
        c_event = cand(rec.surv_event, rec.br_event, jnp.full((R,), -1, i32))
        c_start = cand(rec.surv_start, rec.br_start, jnp.full((R,), -1, i32))
        c_branching = cand(
            rec.surv_branching,
            jnp.ones((R, H), bool) if H else jnp.zeros((R, 0), bool),
            jnp.zeros((R,), bool),
        )
        c_agg = jnp.concatenate(
            [rec.final_agg[:, None, :]]
            + ([rec.br_agg[:, ::-1, :]] if H else [])
            + [jnp.broadcast_to(inits_l, (R, NS))[:, None, :]],
            axis=1,
        )

        RS = R * S_CAND
        flat_alive = c_alive.reshape(RS)
        idx = jnp.cumsum(flat_alive.astype(i32)) - 1
        keep = flat_alive & (idx < R)
        dropped = jnp.sum((flat_alive & (idx >= R)).astype(i32))

        # Scatter-free compaction: each kept candidate's one-hot destination
        # row, reduced over the candidate axis (at most one source per slot).
        ohm = keep[:, None] & (idx[:, None] == jnp.arange(R, dtype=i32)[None, :])

        def compact(field, fill=0):
            flat = field.reshape((RS,) + field.shape[2:])
            m = ohm.reshape((RS, R) + (1,) * (flat.ndim - 1))
            if flat.dtype == jnp.bool_:
                return jnp.any(m & flat[:, None], axis=0)
            vals = jnp.sum(jnp.where(m, flat[:, None], 0), axis=0).astype(flat.dtype)
            got = jnp.any(m, axis=0).reshape((R,) + (1,) * (flat.ndim - 1))
            return jnp.where(got, vals, jnp.asarray(fill, flat.dtype))

        new_alive = jnp.any(ohm & flat_alive[:, None], axis=0)

        # --- Lazy extraction: append completed matches to the handle ring
        # and pin each root (refs +1) so no removal walk can delete the
        # chain's root entry before the drain pass unpins and walks it.
        hr = dict(
            hr_stage=state.hr_stage, hr_off=state.hr_off,
            hr_ver=state.hr_ver, hr_vlen=state.hr_vlen,
            hr_ts=state.hr_ts, hr_seq=state.hr_seq, hr_row=state.hr_row,
            hr_count=state.hr_count,
            handle_overflows=state.handle_overflows,
        )
        if cfg.lazy_extraction:
            off = jnp.asarray(ev.off, i32)
            ts = jnp.asarray(ev.ts, i32)
            final_en = rec.surv_alive & rec.surv_final & valid
            rank = jnp.cumsum(final_en.astype(i32)) - 1
            dst = state.hr_count + rank
            fit = final_en & (dst < HB)
            m = fit[:, None] & (
                jnp.arange(HB, dtype=i32)[None, :] == dst[:, None]
            )  # [R, HB] — at most one True per row and per column
            got = jnp.any(m, axis=0)

            def ring_set(cur, val):
                if val.ndim == 1:
                    upd = jnp.sum(jnp.where(m, val[:, None], 0), axis=0)
                    return jnp.where(got, upd.astype(cur.dtype), cur)
                upd = jnp.sum(
                    jnp.where(m[:, :, None], val[:, None, :], 0), axis=0
                )
                return jnp.where(got[:, None], upd.astype(cur.dtype), cur)

            pin = jnp.sum(
                (
                    (slab.stage[None, :] == rec.surv_id[:, None])
                    & (slab.off[None, :] == off)
                    & fit[:, None]
                ).astype(i32),
                axis=0,
            )
            slab = slab._replace(refs=slab.refs + pin)
            hr = dict(
                hr_stage=ring_set(state.hr_stage, rec.surv_id),
                hr_off=ring_set(
                    state.hr_off, jnp.broadcast_to(off, (R,))
                ),
                hr_ver=ring_set(state.hr_ver, rec.surv_ver),
                hr_vlen=ring_set(state.hr_vlen, rec.surv_vlen),
                hr_ts=ring_set(state.hr_ts, jnp.broadcast_to(ts, (R,))),
                hr_seq=ring_set(
                    state.hr_seq, jnp.broadcast_to(state.step_seq, (R,))
                ),
                hr_row=ring_set(state.hr_row, jnp.arange(R, dtype=i32)),
                hr_count=state.hr_count + jnp.sum(fit.astype(i32)),
                handle_overflows=state.handle_overflows
                + jnp.sum((final_en & ~fit).astype(i32)),
            )

        new_state = EngineState(
            alive=new_alive,
            id_pos=compact(c_id, -1),
            eval_pos=compact(c_eval),
            ver=compact(c_ver),
            vlen=compact(c_vlen),
            event_off=compact(c_event, -1),
            start_ts=compact(c_start, -1),
            branching=compact(c_branching, False),
            agg=compact(c_agg),
            slab=slab,
            run_drops=state.run_drops + dropped,
            ver_overflows=state.ver_overflows + jnp.sum(rec.ovf),
            step_seq=state.step_seq,
            stage_counts=state.stage_counts
            + jnp.sum(rec.stage_tally, axis=0),
            **hr,
        )

        # Padding steps leave the state untouched and emit nothing.
        new_state = jax.tree_util.tree_map(
            lambda n, o: jnp.where(
                jnp.reshape(valid, (1,) * n.ndim), n, o
            ) if n.ndim else jnp.where(valid, n, o),
            new_state, state,
        )
        # The step counter ticks on every step, padding included — it is
        # the StepOutput ``t`` index (handle ordering), not match state.
        new_state = new_state._replace(step_seq=state.step_seq + 1)
        out = StepOutput(
            stage=jnp.where(valid, out_stage, -1),
            off=jnp.where(valid, out_off, -1),
            count=jnp.where(valid, out_count, 0),
        )
        return new_state, out

    def init_state(q: int = 0) -> EngineState:
        i32 = jnp.int32
        ver = jnp.zeros((R, D), i32).at[0, 0].set(1)
        return EngineState(
            alive=jnp.zeros((R,), bool).at[0].set(True),
            id_pos=jnp.full((R,), -1, i32),
            eval_pos=jnp.full((R,), begin_pos, i32),
            ver=ver,
            vlen=jnp.zeros((R,), i32).at[0].set(1),
            event_off=jnp.full((R,), -1, i32),
            start_ts=jnp.full((R,), -1, i32),
            branching=jnp.zeros((R,), bool),
            agg=jnp.broadcast_to(inits[q], (R, NS)),
            slab=slab_mod.make(
                cfg.slab_entries, cfg.slab_preds, D, num_stages=S_AT
            ),
            run_drops=jnp.zeros((), i32),
            ver_overflows=jnp.zeros((), i32),
            hr_stage=jnp.full((HB,), -1, i32),
            hr_off=jnp.full((HB,), -1, i32),
            hr_ver=jnp.zeros((HB, D), i32),
            hr_vlen=jnp.zeros((HB,), i32),
            hr_ts=jnp.zeros((HB,), i32),
            hr_seq=jnp.zeros((HB,), i32),
            hr_row=jnp.zeros((HB,), i32),
            hr_count=jnp.zeros((), i32),
            step_seq=jnp.zeros((), i32),
            handle_overflows=jnp.zeros((), i32),
            stage_counts=jnp.zeros((4, S_AT), i32),
        )

    phases = StepPhases(
        eval_chain=eval_chain,
        build_puts=build_puts,
        build_walkers=build_walkers,
        finish=finish,
        out_base=RH + R,
        out_rows=R,
        max_walk=W,
        hot_entries=EH,
        pred_stats=dict(pred_plan.stats),
    )
    return step, init_state, phases


def build_drain(cfg: EngineConfig):
    """The per-lane batched drain pass for ``cfg`` — a pure jittable
    ``drain(state) -> (state, DrainOutput)``.

    Unpins every pending handle's root (the emission-time refcount +1,
    ``finish``), then walks all handles together through the step walk
    machinery (``ops/slab.py: walks_compacted`` with ``drain=True`` hop
    accounting) with full removal semantics — exactly the walks the eager
    engine would have run in-step, in the same per-handle order (ring
    order = completion order; ``budget=1`` default runs each alone).  The
    ring is cleared.  A no-op on an empty ring (and under the eager
    engine), so callers may drain unconditionally.  Table-free: one drain
    works for any pattern compiled at the same shapes, stacked banks
    included.
    """
    HB, W, EH, D = (
        cfg.handle_ring, cfg.max_walk, cfg.slab_hot_entries,
        cfg.dewey_depth,
    )
    i32 = jnp.int32

    def drain(state: EngineState) -> Tuple[EngineState, DrainOutput]:
        pending = jnp.arange(HB, dtype=i32) < state.hr_count
        slab = state.slab
        unpin = jnp.sum(
            (
                (slab.stage[None, :] == state.hr_stage[:, None])
                & (slab.off[None, :] == state.hr_off[:, None])
                & pending[:, None]
            ).astype(i32),
            axis=0,
        )
        slab = slab._replace(refs=jnp.maximum(slab.refs - unpin, 0))
        ones = jnp.ones((HB,), bool)
        slab, out_stage, out_off, count = slab_mod.walks_compacted(
            slab, pending, state.hr_stage, state.hr_off, state.hr_ver,
            state.hr_vlen, ones, ones, W,
            budget=cfg.walker_budget, out_base=0, out_rows=HB,
            hot_entries=EH, drain=True,
        )
        out = DrainOutput(
            stage=out_stage,
            off=out_off,
            count=jnp.where(pending, count, 0),
            seq=jnp.where(pending, state.hr_seq, -1),
            row=jnp.where(pending, state.hr_row, -1),
            ts=jnp.where(pending, state.hr_ts, -1),
        )
        state = state._replace(
            slab=slab,
            hr_stage=jnp.full((HB,), -1, i32),
            hr_off=jnp.full((HB,), -1, i32),
            hr_ver=jnp.zeros((HB, D), i32),
            hr_vlen=jnp.zeros((HB,), i32),
            hr_ts=jnp.zeros((HB,), i32),
            hr_seq=jnp.zeros((HB,), i32),
            hr_row=jnp.zeros((HB,), i32),
            hr_count=jnp.zeros((), i32),
        )
        return state, out

    return drain


def _build_programs(tables: TransitionTables, cfg: EngineConfig):
    """Build the full program bundle one :class:`TPUMatcher` needs.

    Returned as a tuple so :mod:`utils.tracecache` can share it across
    matcher instances with structurally identical (tables, config): the
    jitted callables carry their trace/compile caches with them, so a
    cache hit skips both the Python re-trace and the XLA compile.
    """
    step, init_state, phases = _build_step(tables, cfg)

    def scan(state: EngineState, events: EventBatch):
        """Run a [T]-stacked batch of events; returns [T]-stacked outputs."""
        return jax.lax.scan(step, state, events)

    drain_fn = build_drain(cfg)
    return (
        step, init_state, phases, jax.jit(step), jax.jit(scan), drain_fn,
        jax.jit(drain_fn),
    )


class TPUMatcher:
    """A compiled array matcher for one pattern.

    The core object is a pure jitted ``step(state, event) -> (state, output)``
    over a single key lane; ``scan`` runs a [T]-batch of events under
    ``lax.scan``, and both vmap cleanly over a leading key axis (see
    ``parallel/``).  Differential conformance against :class:`OracleNFA` is
    enforced by ``tests/test_engine*.py``.
    """

    def __init__(
        self,
        pattern,
        config: Optional[EngineConfig] = None,
    ):
        self.tables: TransitionTables = (
            pattern if isinstance(pattern, TransitionTables) else lower(pattern)
        )
        self.config = config or EngineConfig()
        logger.info(
            "building matcher: %d stages %s, max_hops=%d, %s",
            self.tables.num_stages, self.tables.names,
            self.tables.max_hops, self.config,
        )
        # The traced/jitted programs are structural functions of
        # (tables, config): identical fingerprints share one build —
        # including the jit caches behind ``step``/``scan``/``drain`` —
        # so re-instantiating a matcher for an already-compiled pattern
        # (tests, evacuation restores, supervisor recovery) costs a dict
        # lookup instead of a 2-5s re-trace.
        from kafkastreams_cep_tpu.compiler.multitenant import tables_key
        from kafkastreams_cep_tpu.utils import tracecache

        tkey = tables_key(self.tables)
        cache_key = (
            None
            if tkey is None
            else (tkey, dataclasses.astuple(self.config))
        )
        (
            self._step_fn, self._init_fn, self._phases, self.step,
            self.scan, self._drain_fn, self.drain,
        ) = tracecache.lookup(
            "engine.programs",
            cache_key,
            lambda: _build_programs(self.tables, self.config),
        )

    @property
    def names(self) -> List[str]:
        return self.tables.names

    def init_state(self) -> EngineState:
        return self._init_fn()

    def _scan(self, state: EngineState, events: EventBatch):
        """Run a [T]-stacked batch of events; returns [T]-stacked outputs."""
        return jax.lax.scan(self._step_fn, state, events)

    def counters(self, state: EngineState) -> Dict[str, int]:
        """Host-side diagnostic snapshot of all overflow/drop counters."""
        return {
            n: int(v) for n, v in zip(COUNTER_NAMES, counter_values(state))
        }

    def hot_counters(self, state: EngineState) -> Dict[str, int]:
        """Two-tier residency telemetry (all zero when
        ``slab_hot_entries == 0``) — reported separately from
        :meth:`counters` because these are not loss indicators."""
        return {
            n: int(v)
            for n, v in zip(HOT_COUNTER_NAMES, hot_counter_values(state))
        }

    def walk_counters(self, state: EngineState) -> Dict[str, int]:
        """Walk-cost telemetry (per-hop device work by walker class) —
        like :meth:`hot_counters`, not loss indicators."""
        return {
            n: int(v)
            for n, v in zip(WALK_COUNTER_NAMES, walk_counter_values(state))
        }

    def stage_counters(self, state: EngineState) -> Dict[str, Dict[str, int]]:
        """Per-stage selectivity/cost attribution
        (``EngineConfig.stage_attribution``): ``{stage_name: {tally:
        total, ..., selectivity}}`` summed over any leading lane axes;
        empty dict when attribution is off."""
        return stage_report(stage_counter_arrays(state), self.names)


class MatcherSession:
    """Stateful single-partition wrapper with the oracle's ``match()`` API.

    Feeds events one at a time through the jitted step, keeps the raw
    :class:`Event` objects host-side keyed by offset, and decodes completed
    matches back into :class:`Sequence` objects — the engine analog of
    ``OracleNFA.match`` for conformance tests and small-scale use.  Event
    values must be numeric pytrees (scalars or dicts of scalars).
    """

    def __init__(self, matcher: TPUMatcher):
        self.matcher = matcher
        self.state = matcher.init_state()
        self._events: Dict[int, Event] = {}
        self._offset = 0

    def match(
        self,
        key,
        value,
        timestamp: int,
        topic: str = "test",
        partition: int = 0,
        offset: Optional[int] = None,
    ) -> List[Sequence]:
        if offset is None:
            offset = self._offset
        check_offset(offset)
        self._offset = max(self._offset, offset + 1)
        event = Event(key, value, timestamp, topic, partition, offset)
        self._events[offset] = event
        ev = EventBatch(
            key=jnp.asarray(0 if key is None else key),
            value=value,
            ts=jnp.asarray(timestamp, jnp.int32),
            off=jnp.asarray(offset, jnp.int32),
            valid=jnp.asarray(True),
        )
        self.state, out = self.matcher.step(self.state, ev)
        if self.matcher.config.lazy_extraction:
            # Per-event sessions drain immediately so the oracle-style
            # match() contract (matches returned by the completing event)
            # holds; batch callers drain at scan cadence instead.
            self.state, drained = self.matcher.drain(self.state)
            return self.decode_drained(drained)
        return self.decode(out)

    def decode(self, out: StepOutput) -> List[Sequence]:
        """Materialize one step's matches as :class:`Sequence` objects."""
        stage, off, count = (np.asarray(jax.device_get(x)) for x in out)
        names = self.matcher.names
        matches: List[Sequence] = []
        for r in range(count.shape[0]):
            n = int(count[r])
            if n == 0:
                continue
            seq = Sequence()
            for w in range(n):
                seq.add(names[int(stage[r, w])], self._events[int(off[r, w])])
            matches.append(seq)
        return matches

    def decode_drained(self, out: DrainOutput) -> List[Sequence]:
        """Materialize a drain pass's matches (already in completion
        order — ring order)."""
        stage, off, count = (
            np.asarray(jax.device_get(x))
            for x in (out.stage, out.off, out.count)
        )
        names = self.matcher.names
        matches: List[Sequence] = []
        for h in range(count.shape[0]):
            n = int(count[h])
            if n == 0:
                continue
            seq = Sequence()
            for w in range(n):
                seq.add(names[int(stage[h, w])], self._events[int(off[h, w])])
            matches.append(seq)
        return matches

    def counters(self) -> Dict[str, int]:
        return self.matcher.counters(self.state)
