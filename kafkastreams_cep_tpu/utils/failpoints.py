"""Deterministic fault injection — named failure sites, seed-schedulable.

The supervisor's whole value is code that never runs in a happy path:
checkpoint-save failures, journal append/fsync failures, device faults
mid-stream, crashes between a snapshot and the journal truncation.  This
module makes those paths *drivable*: production code declares a named
**failpoint site** (``fire("journal.append")``) at each place a real
fault could surface, and a test arms a deterministic schedule of which
hit indices of which sites raise which exception.  Disarmed sites cost
one attribute read — no schedule, no counting, no overhead in
production.

Design rules:

* **Sites are named, not positional.**  A schedule written against
  ``device.dispatch`` keeps meaning across refactors; adding a site never
  perturbs existing schedules.
* **Determinism.**  Hit counters start at the moment a session is
  activated, so ``{"journal.append": [2]}`` always means "the third
  append after arming" — and :func:`random_schedule` derives a full
  schedule from one integer seed, making every chaos run exactly
  reproducible.
* **Faults are exceptions**, matching how every real fault in this stack
  surfaces (device loss, ENOSPC, EIO).  Crash simulation — abandoning the
  process mid-write — cannot be an exception (the crashed process runs no
  ``except`` clause); the torn-write helpers below forge the on-disk
  aftermath instead, and the chaos harness abandons the live objects.

Sites currently threaded through the runtime:

=====================  ====================================================
``device.dispatch``    entry of ``CEPProcessor._dispatch`` — the fault hits
                       *before* the scan, device state untouched
``device.result``      after the scan replaced ``self.state``, before the
                       decode — the adversarial case: state advanced, the
                       batch's matches never reached the caller
``journal.append``     entry of ``Journal.append`` — nothing written
``journal.fsync``      after the frame bytes reached the OS, at the
                       durability barrier — ``append`` rolls the frame back
                       so the journal stays a clean prefix
``checkpoint.save``    entry of ``save_checkpoint`` — snapshot never forms
``checkpoint.rename``  between the tmp-file write and the atomic
                       ``os.replace`` — the crash window the ``.tmp``
                       protocol exists for
``ingest.admit``       entry of ``CEPProcessor._ingest`` — before any
                       guard or lane bookkeeping mutates; the batch is
                       rejected wholesale, nothing half-admitted
``ingest.release``     after the reorder buffer moved (records admitted,
                       releases popped) but before the engine dispatch —
                       the adversarial window: the held set advanced
                       while device state did not, so recovery must
                       restore the buffer from the snapshot + journal
``shard.dispatch``     meshed branch of ``CEPProcessor._dispatch``, at the
                       host→mesh transfer — where a lost device first
                       surfaces on the sharded path; arm with
                       ``parallel.sharding.ShardLost`` to drive the
                       supervisor's shard-evacuation path
``rebalance.move``     entry of ``runtime.migrate.move_lanes``, before any
                       state moves — a fault here must leave the old
                       processor (and lane assignment) fully intact
``tenant.misbehave``   entry of ``runtime.tenant.TenantCEP.process``,
                       before admission, packing, or any state mutation —
                       arm with ``runtime.tenant.TenantMisbehave`` to flag
                       a tenant for supervisor quarantine
``quota.shed``         the admission shed path of ``runtime.tenant.
                       TenantAdmission`` (token bucket empty or traffic
                       for a quarantined tenant), before the dead letter
                       and shed ledger entries are recorded
``quarantine.enter``   entry of ``parallel.tenantbank.TenantBankMatcher.
                       quarantine``, before any enforcement state flips —
                       a fault here must leave the bank un-quarantined
                       and fully live
``overload.enter``     the brownout ladder's level-up protocol
                       (``runtime/supervisor.py _overload_transition``),
                       before actuators apply or the level pins — a fault
                       here must leave the previous level authoritative
``overload.exit``      the same protocol stepping down — identical
                       contract on the recovery direction
``overload.shed``      the ingest-door shed path at L3+
                       (``CEPProcessor._ingest``), after the Bresenham
                       keep/shed decision but before the dead letter is
                       recorded — recovery replays the batch and re-sheds
                       deterministically
=====================  ====================================================
"""

from __future__ import annotations

import contextlib
import os
import struct
import zlib
from typing import Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np


class InjectedFault(RuntimeError):
    """Default exception for device-ish sites (supervisor recovery path)."""


class InjectedIOError(OSError):
    """Default exception for disk-ish sites (journal/checkpoint paths)."""


# Which exception a site raises when the arming does not say otherwise:
# device sites surface like a device loss (generic Exception -> recovery),
# disk sites like an errno failure (the counters/suspension paths).
_DEFAULT_EXC: Dict[str, Callable[[str], BaseException]] = {}


def _default_exc(site: str) -> BaseException:
    # ``shard.*`` models a lost mesh device — device family, not disk.
    if site.startswith(("device.", "shard.")):
        return InjectedFault(f"injected fault at {site}")
    return InjectedIOError(f"injected I/O failure at {site}")


class _Plan:
    """Armed behavior of one site: which hit indices raise what."""

    __slots__ = ("hits", "times", "exc")

    def __init__(
        self,
        hits: Optional[Iterable[int]] = None,
        times: int = 0,
        exc: Optional[Callable[[], BaseException]] = None,
    ):
        self.hits = frozenset(int(h) for h in hits) if hits is not None else None
        self.times = int(times)  # fire on the first N hits (hits is None)
        self.exc = exc

    def should(self, n: int) -> bool:
        if self.hits is not None:
            return n in self.hits
        return n < self.times


class Failpoints:
    """A registry of armed failure sites; one global instance drives all
    production sites (module-level :func:`fire`)."""

    def __init__(self):
        self._plans: Dict[str, _Plan] = {}
        self._hits: Dict[str, int] = {}
        self._enabled = False

    # -- arming (test side) -------------------------------------------------

    def arm(
        self,
        site: str,
        hits: Optional[Iterable[int]] = None,
        times: int = 1,
        exc: Optional[Callable[[], BaseException]] = None,
    ) -> None:
        """Arm ``site``: raise on the hit indices in ``hits`` (0-based,
        counted from activation), or on the first ``times`` hits when
        ``hits`` is None.  ``exc`` builds the exception to raise (default
        per site family)."""
        self._plans[site] = _Plan(hits=hits, times=times, exc=exc)
        self._enabled = True

    def arm_schedule(
        self,
        schedule: Dict[str, Sequence[int]],
        exc: Optional[Callable[[], BaseException]] = None,
    ) -> None:
        for site, hit_list in schedule.items():
            self.arm(site, hits=hit_list, exc=exc)

    def clear(self) -> None:
        """Disarm everything and reset all hit counters."""
        self._plans.clear()
        self._hits.clear()
        self._enabled = False

    @contextlib.contextmanager
    def session(
        self,
        schedule: Optional[Dict[str, Sequence[int]]] = None,
        exc: Optional[Callable[[], BaseException]] = None,
    ):
        """Context manager: arm ``schedule``, always clear on exit."""
        self.clear()
        if schedule:
            self.arm_schedule(schedule, exc=exc)
        else:
            self._enabled = True  # count hits even with nothing armed
        try:
            yield self
        finally:
            self.clear()

    def hits(self, site: str) -> int:
        """How many times ``site`` fired since activation."""
        return self._hits.get(site, 0)

    # -- firing (production side) -------------------------------------------

    def fire(self, site: str) -> None:
        """Called by production code at a failure site.  No-op (one
        attribute read) unless a session is active."""
        if not self._enabled:
            return
        n = self._hits.get(site, 0)
        self._hits[site] = n + 1
        plan = self._plans.get(site)
        if plan is None:
            return
        raising = plan.should(n)
        # Armed-site hits land in the trace stream (utils/telemetry.py)
        # when a default sink is installed, so a chaos JSONL shows the
        # injected fault right next to the recovery span it provoked.
        # Only armed sites pay the lookup; disarmed cost is unchanged.
        from kafkastreams_cep_tpu.utils.telemetry import get_default_sink

        sink = get_default_sink()
        if sink is not None:
            sink.event("failpoint", site=site, hit=n, raised=raising)
        if raising:
            raise (plan.exc() if plan.exc is not None else _default_exc(site))


#: The process-wide registry every production site reports to.
FAILPOINTS = Failpoints()


def fire(site: str) -> None:
    """Module-level convenience for production call sites."""
    FAILPOINTS.fire(site)


# -- seeded schedules --------------------------------------------------------

#: All sites threaded through the runtime, in a stable order (schedules
#: index into this; keep append-only so seeds stay meaningful).
SITES = (
    "device.dispatch",
    "device.result",
    "journal.append",
    "journal.fsync",
    "checkpoint.save",
    "checkpoint.rename",
    # Ingestion-guard sites (append-only: schedules index by site name,
    # and random_schedule seeds by position — see the docstring table).
    "ingest.admit",
    "ingest.release",
    # Reporter cadence write: between serializing the metrics JSONL
    # record and its single-write append — a crash here must leave the
    # stream without any partial line (utils/telemetry.py Reporter.flush).
    "report.write",
    # Mesh fault-tolerance sites (runtime/supervisor.py shard evacuation
    # and hot-key rebalancing; see the docstring table).
    "shard.dispatch",
    "rebalance.move",
    # Adaptive replan swap (runtime/supervisor.py _maybe_replan): between
    # deriving the new plan and committing the rebuilt processor — a
    # crash here must leave the old plan fully live (replan_failures).
    "replan.swap",
    # Per-tenant isolation sites (runtime/tenant.py admission shedding +
    # supervisor quarantine, parallel/tenantbank.py enforcement; see the
    # docstring table).
    "tenant.misbehave",
    "quota.shed",
    "quarantine.enter",
    # Brownout ladder sites (runtime/supervisor.py transition protocol +
    # the processor's ingest-door shed; see the docstring table).
    "overload.enter",
    "overload.exit",
    "overload.shed",
)


def random_schedule(
    seed: int,
    horizon: int,
    rate: float = 0.15,
    sites: Sequence[str] = SITES,
) -> Dict[str, List[int]]:
    """A reproducible fault schedule from one integer seed.

    Each site independently fires on each of its first ``horizon`` hits
    with probability ``rate``.  The same seed always produces the same
    schedule; distinct seeds decorrelate quickly (``default_rng`` is
    seeded with ``(seed, site_index)``).
    """
    out: Dict[str, List[int]] = {}
    for i, site in enumerate(sites):
        rng = np.random.default_rng((int(seed), i))
        picks = np.nonzero(rng.random(int(horizon)) < rate)[0]
        if picks.size:
            out[site] = [int(p) for p in picks]
    return out


# -- crash-aftermath forgery -------------------------------------------------

_MAGIC = 0x43455031  # keep in sync with native/journal.py
_HEADER = struct.Struct("<III")


def tear_journal_tail(path: str, payload: bytes = b"torn-frame-payload",
                      keep: int = 6) -> None:
    """Forge the on-disk aftermath of a process dying mid-append: a frame
    whose header promises more bytes than follow.  ``Journal.replay``
    must treat everything before it as intact and truncate the rest."""
    frame = _HEADER.pack(_MAGIC, len(payload), zlib.crc32(payload)) + payload
    with open(path, "ab") as f:
        f.write(frame[: max(int(keep), 1)])


def corrupt_journal_tail(path: str, nbytes: int = 16, seed: int = 0) -> None:
    """Forge a tail of non-frame garbage (a crash after the filesystem
    wrote metadata but garbage data, or a partial overwrite)."""
    rng = np.random.default_rng(seed)
    junk = rng.integers(0, 256, size=int(nbytes), dtype=np.uint8).tobytes()
    # Avoid accidentally forging a valid magic at the boundary.
    if junk[:4] == struct.pack("<I", _MAGIC):
        junk = b"\x00" + junk[1:]
    with open(path, "ab") as f:
        f.write(junk)


def drop_checkpoint_rename(checkpoint_path: str) -> None:
    """Forge a crash between ``save_checkpoint(tmp)`` and ``os.replace``:
    the ``.tmp`` file exists, the real path still holds the old snapshot
    (or nothing).  Callers that already produced a tmp file can simply
    leave it; this helper removes a completed rename's destination to
    re-create the pre-rename world in tests that need it explicitly."""
    tmp = checkpoint_path + ".tmp"
    if os.path.exists(checkpoint_path) and not os.path.exists(tmp):
        os.replace(checkpoint_path, tmp)
