"""Process-level trace cache: share jitted step programs across matchers.

Instantiating a matcher re-traces the engine step (~2-5s of Python/jax
tracing per ``BatchMatcher``) even when an identical program was just
built — the persistent XLA compilation cache absorbs only the backend
compile, not the trace.  Tests, evacuation/rebalance restores, and
supervisor recoveries all rebuild matchers for patterns the process has
already compiled, so the suite's wall clock (ROADMAP PR 8 budget note)
and production recovery latency were paying pure re-trace.

This module is the cache: builders register their result under a
*structural* key — the pattern tables' fingerprint
(``compiler/multitenant.py: tables_key``), the engine config, and
whatever mode flags select the program variant (kernel on/off,
interpret, lane-count feasibility).  Equal keys guarantee equal traced
programs, so the cached jitted callables (whose jit cache carries the
trace *and* the compiled executable) are shared verbatim.  Unkeyable
patterns (``tables_key`` returns None) bypass the cache and behave
exactly as before.

``CEP_TRACE_CACHE`` controls it: unset/``1`` = on (default capacity
4096 entries, LRU), ``0``/``off`` = disabled, any integer = capacity.

The default capacity must comfortably exceed the process's *working
set* of distinct programs, not just bound memory: an LRU swept
sequentially by a working set even slightly over capacity degrades to
a 0% hit rate (every entry is evicted just before its next use), which
here means re-paying full trace cost on nearly every matcher build —
measured as a 2-3x wall-clock regression across the test suite when
the set first outgrew the old 256-entry default.  4096 keeps eviction
a true safety bound (adaptive-replan thrash, pathological pattern
churn) instead of a steady-state behavior; entries are jitted
callables, small on host until executed.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Hashable, Optional

_DEFAULT_CAPACITY = 4096

_lock = threading.Lock()
_store: "OrderedDict[Hashable, Any]" = OrderedDict()
_hits = 0
_misses = 0
_evictions = 0


def capacity() -> int:
    """Configured entry capacity; 0 disables the cache entirely."""
    raw = os.environ.get("CEP_TRACE_CACHE", "").strip().lower()
    if raw in ("", "1", "on", "true"):
        return _DEFAULT_CAPACITY
    if raw in ("0", "off", "false"):
        return 0
    try:
        return max(int(raw), 0)
    except ValueError:
        return _DEFAULT_CAPACITY


def lookup(
    namespace: str, key: Optional[Hashable], build: Callable[[], Any]
) -> Any:
    """``build()``'s result cached under ``(namespace, key)``.

    ``key=None`` (an unkeyable pattern) or a disabled cache calls
    ``build()`` uncached.  LRU eviction keeps at most :func:`capacity`
    entries alive; evicted entries simply fall back to garbage
    collection like any un-cached matcher's programs.
    """
    global _hits, _misses, _evictions
    cap = capacity()
    if key is None or cap == 0:
        return build()
    full = (namespace, key)
    with _lock:
        if full in _store:
            _store.move_to_end(full)
            _hits += 1
            return _store[full]
    value = build()  # outside the lock: builds may be seconds long
    with _lock:
        if full not in _store:
            _misses += 1
            _store[full] = value
            while len(_store) > cap:
                _store.popitem(last=False)
                _evictions += 1
        _store.move_to_end(full)
        return _store[full]


def stats() -> dict:
    with _lock:
        return {
            "entries": len(_store),
            "hits": _hits,
            "misses": _misses,
            "evictions": _evictions,
            "capacity": capacity(),
        }


def clear() -> None:
    """Drop every cached program (tests; never needed in production)."""
    global _hits, _misses, _evictions
    with _lock:
        _store.clear()
        _hits = 0
        _misses = 0
        _evictions = 0
