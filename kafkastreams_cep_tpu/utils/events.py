"""Event and match-sequence domain types.

Semantics follow the reference types ``cep/Event.java`` and
``cep/Sequence.java``: an event is uniquely identified by its stream position
``(topic, partition, offset)``; a sequence is an ordered mapping of stage name
to the list of events matched at that stage, with order-insensitive per-stage
equality (``Sequence.java:57-73``).
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Any, Dict, Iterable, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Event:
    """A uniquely identifiable stream record.

    Identity (equality/hash) is the stream position ``(topic, partition,
    offset)`` only, matching ``Event.java:56-69`` — key/value/timestamp do not
    participate.
    """

    key: Any
    value: Any
    timestamp: int
    topic: str = "test"
    partition: int = 0
    offset: int = 0

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Event):
            return NotImplemented
        return (
            self.topic == other.topic
            and self.partition == other.partition
            and self.offset == other.offset
        )

    def __hash__(self) -> int:
        return hash((self.topic, self.partition, self.offset))

    @property
    def position(self) -> Tuple[str, int, int]:
        return (self.topic, self.partition, self.offset)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Event(key={self.key!r}, value={self.value!r}, ts={self.timestamp}, "
            f"{self.topic}/{self.partition}@{self.offset})"
        )


class Sequence:
    """A completed pattern match: stage name -> matched events.

    Events are inserted in buffer-walk order, i.e. *final stage first*
    (the reference's backward pointer walk,
    ``nfa/buffer/impl/KVSharedVersionedBuffer.java:147-171``); use
    :meth:`reversed` for presentation order, as the reference demo does
    (``demo/CEPStockKStreamsDemo.java:66``).
    """

    def __init__(self, items: Optional[Iterable[Tuple[str, Event]]] = None):
        self._stages: Dict[str, List[Event]] = {}
        if items:
            for stage, event in items:
                self.add(stage, event)

    def add(self, stage: str, event: Event) -> "Sequence":
        self._stages.setdefault(stage, []).append(event)
        return self

    def get(self, stage: str) -> Optional[List[Event]]:
        return self._stages.get(stage)

    def as_map(self) -> Dict[str, List[Event]]:
        return self._stages

    def stages(self) -> List[str]:
        return list(self._stages)

    def size(self) -> int:
        return sum(len(v) for v in self._stages.values())

    def __len__(self) -> int:
        return self.size()

    def reversed(self) -> "Sequence":
        """Presentation order: first stage first, events in arrival order."""
        out = Sequence()
        for stage in reversed(list(self._stages)):
            for event in reversed(self._stages[stage]):
                out.add(stage, event)
        return out

    def __eq__(self, other: object) -> bool:
        # Per-stage equality is order-insensitive (Sequence.java:57-73).
        if not isinstance(other, Sequence):
            return NotImplemented
        if set(self._stages) != set(other._stages):
            return False
        for stage, events in self._stages.items():
            if Counter(events) != Counter(other._stages[stage]):
                return False
        return True

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        parts = ", ".join(
            f"{stage}=[{', '.join(repr(e.value) for e in events)}]"
            for stage, events in self._stages.items()
        )
        return f"Sequence({parts})"
